"""quick_start data providers (ref: demo/quick_start/dataprovider_bow.py and
dataprovider_emb.py — Amazon review sentiment).

Two provider objects over the same synthetic two-class text task:
`process_bow` yields sparse bag-of-words vectors, `process` yields word-id
sequences.
"""

import numpy as np

from paddle_tpu.data.provider import (
    integer_value, integer_value_sequence, provider, sparse_binary_vector,
)

VOCAB = 1024


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        label = int(rng.integers(0, 2))
        L = int(rng.integers(4, 30))
        lo = 0 if label == 0 else VOCAB // 3
        hi = 2 * VOCAB // 3 if label == 0 else VOCAB
        words = rng.integers(lo, hi, L).tolist()
        yield words, label


@provider(input_types={"word": sparse_binary_vector(VOCAB),
                       "label": integer_value(2)})
def process_bow(settings, filename):
    seed = 0 if "train" in filename else 1
    for words, label in _synthetic(2048 if "train" in filename else 256, seed):
        yield sorted(set(words)), label


@provider(input_types={"word": integer_value_sequence(VOCAB),
                       "label": integer_value(2)})
def process(settings, filename):
    seed = 0 if "train" in filename else 1
    yield from _synthetic(2048 if "train" in filename else 256, seed)
