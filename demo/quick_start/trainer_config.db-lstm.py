"""Deep bidirectional LSTM stack: 8 alternating-direction lstmemory layers
(ref: demo/quick_start/trainer_config.db-lstm.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from qs_provider import VOCAB  # noqa: E402

is_predict = get_config_arg("is_predict", bool, False)
# the reference stacks 8; depth is an arg so tests can use a shallow stack
depth = get_config_arg("depth", int, 8)

define_py_data_sources2(
    train_list="demo/quick_start/train.list",
    test_list="demo/quick_start/test.list",
    module="demo.quick_start.qs_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 128) if not is_predict else 1,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25)

bias_attr = ParamAttr(initial_std=0.0, l2_rate=0.0)

data = data_layer(name="word", size=VOCAB)
emb = embedding_layer(input=data, size=128)

hidden_0 = mixed_layer(size=128, input=[full_matrix_projection(input=emb)])
lstm_0 = lstmemory(input=hidden_0, layer_attr=ExtraAttr(drop_rate=0.1))

input_layers = [hidden_0, lstm_0]

lstm = lstm_0
for i in range(1, depth):
    fc = fc_layer(input=input_layers, size=128)
    lstm = lstmemory(input=fc, layer_attr=ExtraAttr(drop_rate=0.1),
                     reverse=(i % 2) == 1)
    input_layers = [fc, lstm]

lstm_last = pooling_layer(input=lstm, pooling_type=MaxPooling())

output = fc_layer(input=lstm_last, size=2, bias_attr=bias_attr,
                  act=SoftmaxActivation())

if is_predict:
    maxid = maxid_layer(output)
    outputs(maxid, output)
else:
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
