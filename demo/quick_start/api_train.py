"""Programmatic training via the paddle_tpu.api layer — no DataProvider
config, the script owns the data and the training loop
(ref: demo/quick_start/api_train.py using swig_paddle + DataProviderConverter).

Run: python demo/quick_start/api_train.py [--num_passes N]
"""

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np  # noqa: E402

from paddle_tpu import api  # noqa: E402
from paddle_tpu.config.parser import parse_config  # noqa: E402
from paddle_tpu.data.provider import (  # noqa: E402
    integer_value, integer_value_sequence,
)
from qs_provider import VOCAB, _synthetic  # noqa: E402


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num_passes", default=3, type=int)
    parser.add_argument("--batch_size", default=64, type=int)
    options = parser.parse_args()

    api.initPaddle()

    config_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "trainer_config.lstm.py")
    trainer_config = parse_config(config_path, "")
    # no data provider needed — this script feeds batches itself
    trainer_config.data_config = None
    trainer_config.test_data_config = None

    model = api.GradientMachine.createFromConfigProto(
        trainer_config.model_config)
    trainer = api.Trainer.create(trainer_config, model)

    converter = api.DataProviderConverter(
        [integer_value_sequence(VOCAB), integer_value(2)],
        names=["word", "label"])

    train_dataset = list(_synthetic(2048, seed=0))
    test_dataset = list(_synthetic(256, seed=1))
    bs = options.batch_size

    trainer.startTrain()
    for pass_id in range(options.num_passes):
        trainer.startTrainPass()
        random.Random(pass_id).shuffle(train_dataset)
        for pos in range(0, len(train_dataset) - bs + 1, bs):
            batch = train_dataset[pos:pos + bs]
            trainer.trainOneDataBatch(len(batch), converter(batch))
        trainer.finishTrainPass()

        trainer.startTestPeriod()
        for pos in range(0, len(test_dataset) - bs + 1, bs):
            batch = test_dataset[pos:pos + bs]
            trainer.testOneDataBatch(len(batch), converter(batch))
        test_cost = trainer.finishTestPeriod()
        print(f"pass {pass_id}: train cost {trainer.getPassCost():.4f} "
              f"test cost {test_cost:.4f}")
    trainer.finishTrain()


if __name__ == "__main__":
    main()
