"""Residual-connection LSTM stack: each layer's input is the sum of the
previous layer's input and hidden state
(ref: demo/quick_start/trainer_config.resnet-lstm.py — a stacked
single-direction variant of the ResNet-LSTM architecture)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from qs_provider import VOCAB  # noqa: E402

is_predict = get_config_arg("is_predict", bool, False)
depth = get_config_arg("depth", int, 3)

define_py_data_sources2(
    train_list="demo/quick_start/train.list",
    test_list="demo/quick_start/test.list",
    module="demo.quick_start.qs_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 128) if not is_predict else 1,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25)

bias_attr = ParamAttr(initial_std=0.0, l2_rate=0.0)

data = data_layer(name="word", size=VOCAB)
emb = embedding_layer(input=data, size=128)
lstm = simple_lstm(input=emb, size=128,
                   lstm_cell_attr=ExtraAttr(drop_rate=0.1))

previous_input, previous_hidden_state = emb, lstm

for i in range(depth):
    # current layer's input = previous layer's input + its hidden state
    current_input = addto_layer(input=[previous_input, previous_hidden_state])
    hidden_state = simple_lstm(input=current_input, size=128,
                               lstm_cell_attr=ExtraAttr(drop_rate=0.1))
    previous_input, previous_hidden_state = current_input, hidden_state

lstm = previous_hidden_state

lstm_last = pooling_layer(input=lstm, pooling_type=MaxPooling())
output = fc_layer(input=lstm_last, size=2, bias_attr=bias_attr,
                  act=SoftmaxActivation())

if is_predict:
    maxid = maxid_layer(output)
    outputs(maxid, output)
else:
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
