"""Bidirectional LSTM text classifier
(ref: demo/quick_start/trainer_config.bidi-lstm.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from qs_provider import VOCAB  # noqa: E402

is_predict = get_config_arg("is_predict", bool, False)

define_py_data_sources2(
    train_list="demo/quick_start/train.list",
    test_list="demo/quick_start/test.list",
    module="demo.quick_start.qs_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 128) if not is_predict else 1,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25)

bias_attr = ParamAttr(initial_std=0.0, l2_rate=0.0)
data = data_layer(name="word", size=VOCAB)
emb = embedding_layer(input=data, size=128)

bi_lstm = bidirectional_lstm(input=emb, size=128)
dropout = dropout_layer(input=bi_lstm, dropout_rate=0.5)

output = fc_layer(input=dropout, size=2, bias_attr=bias_attr,
                  act=SoftmaxActivation())

if is_predict:
    maxid = maxid_layer(output)
    outputs(maxid, output)
else:
    label = data_layer(name="label", size=2)
    outputs(classification_cost(input=output, label=label))
