"""SRL data provider (ref: demo/semantic_role_labeling/dataprovider.py —
CoNLL-05 style: word / predicate / context words / predicate-mark token
sequences plus a target role-label sequence).

Synthetic fallback: role labels are a deterministic function of word, mark
and distance-to-predicate, so the net can learn them; same 7 slots as the
reference.
"""

import os

import numpy as np

from paddle_tpu.data.provider import integer_value_sequence, provider

WORD_DIM = 1000
LABEL_DIM = 19        # IOB over 9 role types + O
MARK_DIM = 2


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        L = int(rng.integers(5, 30))
        words = rng.integers(0, WORD_DIM, L).tolist()
        pred_pos = int(rng.integers(0, L))
        predicate = [words[pred_pos]] * L
        ctx_n1 = [words[max(0, i - 1)] for i in range(L)]
        ctx_0 = list(words)
        ctx_p1 = [words[min(L - 1, i + 1)] for i in range(L)]
        mark = [1 if i == pred_pos else 0 for i in range(L)]
        labels = [((w + abs(i - pred_pos)) % (LABEL_DIM - 1)) if abs(i - pred_pos) < 3
                  else LABEL_DIM - 1
                  for i, w in enumerate(words)]
        yield words, predicate, ctx_n1, ctx_0, ctx_p1, mark, labels


@provider(input_types={
    "word_data": integer_value_sequence(WORD_DIM),
    "verb_data": integer_value_sequence(WORD_DIM),
    "ctx_n1_data": integer_value_sequence(WORD_DIM),
    "ctx_0_data": integer_value_sequence(WORD_DIM),
    "ctx_p1_data": integer_value_sequence(WORD_DIM),
    "mark_data": integer_value_sequence(MARK_DIM),
    "target": integer_value_sequence(LABEL_DIM),
})
def process(settings, filename):
    seed = 0 if "train" in filename else 1
    yield from _synthetic(1024 if "train" in filename else 128, seed)
