"""Deep bidirectional LSTM for semantic role labeling (ref:
demo/semantic_role_labeling/db_lstm.py — 6 feature embeddings with a shared
word table, mixed fusion, depth-8 alternating-direction LSTM stack, CRF
output)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from srl_provider import LABEL_DIM, MARK_DIM, WORD_DIM  # noqa: E402

is_predict = get_config_arg("is_predict", bool, False)
depth = get_config_arg("depth", int, 8)
hidden_dim = get_config_arg("hidden_dim", int, 128)

word_dim = 32
mark_dim = 5
emb_lr = 1e-2
fc_lr = 1e-2
lstm_lr = 2e-2

define_py_data_sources2(
    train_list="demo/semantic_role_labeling/train.list",
    test_list="demo/semantic_role_labeling/test.list",
    module="demo.semantic_role_labeling.srl_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 150),
    learning_method=AdamOptimizer(),
    learning_rate=1e-3,
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25)

word = data_layer(name="word_data", size=WORD_DIM)
predicate = data_layer(name="verb_data", size=WORD_DIM)
ctx_n1 = data_layer(name="ctx_n1_data", size=WORD_DIM)
ctx_0 = data_layer(name="ctx_0_data", size=WORD_DIM)
ctx_p1 = data_layer(name="ctx_p1_data", size=WORD_DIM)
mark = data_layer(name="mark_data", size=MARK_DIM)
target = data_layer(name="target", size=LABEL_DIM)

# shared word-embedding table across the 5 word-feature inputs
ptt = ParameterAttribute(name="src_emb", learning_rate=emb_lr)
layer_attr = ExtraLayerAttribute(drop_rate=0.5)
fc_para_attr = ParameterAttribute(learning_rate=fc_lr)
lstm_para_attr = ParameterAttribute(initial_std=0., learning_rate=lstm_lr)
para_attr = [fc_para_attr, lstm_para_attr]

word_embedding = embedding_layer(size=word_dim, input=word, param_attr=ptt)
predicate_embedding = embedding_layer(size=word_dim, input=predicate, param_attr=ptt)
ctx_n1_embedding = embedding_layer(size=word_dim, input=ctx_n1, param_attr=ptt)
ctx_0_embedding = embedding_layer(size=word_dim, input=ctx_0, param_attr=ptt)
ctx_p1_embedding = embedding_layer(size=word_dim, input=ctx_p1, param_attr=ptt)
mark_embedding = embedding_layer(size=mark_dim, input=mark)

hidden_0 = mixed_layer(
    size=hidden_dim,
    input=[
        full_matrix_projection(word_embedding, size=hidden_dim),
        full_matrix_projection(predicate_embedding, size=hidden_dim),
        full_matrix_projection(ctx_n1_embedding, size=hidden_dim),
        full_matrix_projection(ctx_0_embedding, size=hidden_dim),
        full_matrix_projection(ctx_p1_embedding, size=hidden_dim),
        full_matrix_projection(mark_embedding, size=hidden_dim),
    ])

lstm_0 = lstmemory(input=hidden_0, layer_attr=layer_attr)

# stack L-LSTM and R-LSTM with direct edges (ref: db_lstm.py depth loop)
input_tmp = [hidden_0, lstm_0]
for i in range(1, depth):
    fc = fc_layer(input=input_tmp, size=hidden_dim, act=LinearActivation(),
                  param_attr=para_attr)
    lstm = lstmemory(input=fc, act=ReluActivation(), reverse=(i % 2) == 1,
                     layer_attr=layer_attr)
    input_tmp = [fc, lstm]

feature_out = fc_layer(input=input_tmp, size=LABEL_DIM, act=LinearActivation(),
                       param_attr=para_attr)

if not is_predict:
    crf = crf_layer(input=feature_out, label=target,
                    param_attr=ParameterAttribute(name="crfw"))
    crf_dec = crf_decoding_layer(size=LABEL_DIM, input=feature_out, label=target,
                                 param_attr=ParameterAttribute(name="crfw"))
    chunk_evaluator(name="role_f1", input=crf_dec, label=target,
                    chunk_scheme="IOB", num_chunk_types=(LABEL_DIM - 1) // 2)
    outputs(crf)
else:
    crf_dec = crf_decoding_layer(size=LABEL_DIM, input=feature_out,
                                 param_attr=ParameterAttribute(name="crfw"))
    outputs(crf_dec)
