"""Attention seq2seq network (ref: demo/seqToseq/seqToseq_net.py:70-120 —
bi-GRU encoder, additive-attention GRU decoder, beam-search generation).
North-star benchmark #2 (BASELINE.md)."""

from paddle_tpu.dsl import *

dict_size = get_config_arg("dict_size", int, 32)
is_generating = get_config_arg("is_generating", bool, False)
beam_size = get_config_arg("beam_size", int, 3)
max_length = get_config_arg("max_length", int, 12)
batch_size = get_config_arg("batch_size", int, 0)
compute_dtype = get_config_arg("compute_dtype", str, "")

# reference-scale dims are 512 (ref: seqToseq_net.py:72-74); the default here
# is small for fast tests — the bench passes hidden_dim=512
word_vector_dim = get_config_arg("hidden_dim", int, 64)
encoder_size = word_vector_dim
decoder_size = word_vector_dim

define_py_data_sources2(
    train_list=None if is_generating else "demo/seqToseq/train.list",
    test_list="demo/seqToseq/test.list",
    module="demo.seqToseq.seq_provider",
    obj="process")

settings(
    batch_size=batch_size or (32 if not is_generating else 8),
    learning_rate=5e-4,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(1e-4 * 32),
    gradient_clipping_threshold=25,
    compute_dtype=compute_dtype)

# ---------------- encoder ----------------
src_word = data_layer(name="source_language_word", size=dict_size)
src_emb = embedding_layer(input=src_word, size=word_vector_dim,
                          param_attr=ParameterAttribute(name="_source_language_embedding"))
src_fwd = simple_gru(input=src_emb, size=encoder_size)
src_bwd = simple_gru(input=src_emb, size=encoder_size, reverse=True)
encoded_vector = concat_layer(input=[src_fwd, src_bwd])

with mixed_layer(size=decoder_size) as encoded_proj:
    encoded_proj += full_matrix_projection(input=encoded_vector, size=decoder_size)

backward_first = first_seq(input=src_bwd)
with mixed_layer(size=decoder_size, act=TanhActivation()) as decoder_boot:
    decoder_boot += full_matrix_projection(input=backward_first, size=decoder_size)


def gru_decoder_with_attention(enc_vec, enc_proj, current_word):
    # layers carrying parameters are explicitly named so the training and
    # generation configs produce identical parameter names (the reference's
    # demo does the same — shared params are matched by name)
    decoder_mem = memory(name="gru_decoder", size=decoder_size,
                         boot_layer=decoder_boot)
    context = simple_attention(
        name="attention", encoded_sequence=enc_vec, encoded_proj=enc_proj,
        decoder_state=decoder_mem)
    with mixed_layer(size=decoder_size * 3, name="decoder_inputs") as decoder_inputs:
        decoder_inputs += full_matrix_projection(input=context,
                                                 size=decoder_size * 3)
        decoder_inputs += full_matrix_projection(input=current_word,
                                                 size=decoder_size * 3)
    gru_step = gru_step_layer(
        name="gru_decoder", input=decoder_inputs, output_mem=decoder_mem,
        size=decoder_size)
    with mixed_layer(size=dict_size, act=SoftmaxActivation(),
                     bias_attr=True, name="decoder_prob") as out:
        out += full_matrix_projection(input=gru_step, size=dict_size)
    return out


if not is_generating:
    trg_word = data_layer(name="target_language_word", size=dict_size)
    trg_emb = embedding_layer(
        input=trg_word, size=word_vector_dim,
        param_attr=ParameterAttribute(name="_target_language_embedding"))
    decoder = recurrent_group(
        name="decoder_group", step=gru_decoder_with_attention,
        input=[StaticInput(input=encoded_vector, is_seq=True),
               StaticInput(input=encoded_proj, is_seq=True),
               trg_emb])
    lbl = data_layer(name="target_language_next_word", size=dict_size)
    classification_cost(input=decoder, label=lbl)
else:
    gen_input = GeneratedInput(
        size=dict_size, embedding_name="_target_language_embedding",
        embedding_size=word_vector_dim)
    beam_gen = beam_search(
        name="decoder_group", step=gru_decoder_with_attention,
        input=[StaticInput(input=encoded_vector, is_seq=True),
               StaticInput(input=encoded_proj, is_seq=True),
               gen_input],
        bos_id=0, eos_id=1, beam_size=beam_size, max_length=max_length)
    outputs(beam_gen)
