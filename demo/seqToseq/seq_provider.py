"""seq2seq data provider (ref: demo/seqToseq/dataprovider.py).

Reads tokenized parallel corpora if present under data/ (the reference's
WMT14 download layout); otherwise a synthetic sequence-reversal task with a
small vocabulary — an exact, learnable stand-in that exercises the same
machinery (variable lengths, attention, beam decode).

Slots: src ids, trg ids (<s> + target), trg_next ids (target + <e>),
matching the reference's three data fields.
"""

import os

import numpy as np

from paddle_tpu.data.provider import integer_value_sequence, provider

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

BOS = 0   # <s>
EOS = 1   # <e>
UNK = 2


def make_settings_args(dict_size):
    return {"src_dict_dim": dict_size, "trg_dict_dim": dict_size}


def _synthetic(n, seed, vocab):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        L = int(rng.integers(3, 9))
        src = rng.integers(3, vocab, L).tolist()
        trg = src[::-1]
        yield src, [BOS] + trg, trg + [EOS]


def _file_pairs(split):
    src_f = os.path.join(DATA_DIR, f"{split}.src")
    trg_f = os.path.join(DATA_DIR, f"{split}.trg")
    if not (os.path.exists(src_f) and os.path.exists(trg_f)):
        return None

    def gen():
        with open(src_f) as fs, open(trg_f) as ft:
            for ls, lt in zip(fs, ft):
                src = [int(t) for t in ls.split()]
                trg = [int(t) for t in lt.split()]
                yield src, [BOS] + trg, trg + [EOS]
    return gen()


def _make(vocab):
    @provider(input_types={
        "source_language_word": integer_value_sequence(vocab),
        "target_language_word": integer_value_sequence(vocab),
        "target_language_next_word": integer_value_sequence(vocab)})
    def process(settings, filename):
        split = "train" if "train" in filename else "test"
        pairs = _file_pairs(split)
        if pairs is None:
            pairs = _synthetic(4096 if split == "train" else 256,
                               seed=0 if split == "train" else 1, vocab=vocab)
        for src, trg, trg_next in pairs:
            yield [src, trg, trg_next]
    return process


process = _make(int(os.environ.get("SEQ2SEQ_DICT_SIZE", "32")))
