"""IMDB sentiment config (ref: demo/sentiment/trainer_config.py —
settings + stacked_lstm_net)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from sentiment_net import stacked_lstm_net, bidirectional_lstm_net  # noqa: E402
from sentiment_provider import VOCAB  # noqa: E402

is_predict = get_config_arg("is_predict", bool, False)
net_type = get_config_arg("net", str, "stacked")
batch_size = get_config_arg("batch_size", int, 128)
hid_dim = get_config_arg("hid_dim", int, 512)
# bench override: the real pre-IMDB dictionary is ~100k+ words; the
# synthetic provider's is VOCAB
dict_dim = get_config_arg("dict_dim", int, VOCAB)

define_py_data_sources2(
    train_list="demo/sentiment/train.list",
    test_list="demo/sentiment/test.list",
    module="demo.sentiment.sentiment_provider",
    obj="process")

settings(
    batch_size=batch_size,
    learning_rate=2e-3,
    learning_method=AdamOptimizer(),
    regularization=L2Regularization(8e-4),
    gradient_clipping_threshold=25,
    compute_dtype=get_config_arg("compute_dtype", str, ""))

if net_type == "stacked":
    stacked_lstm_net(dict_dim, class_dim=2, stacked_num=3, hid_dim=hid_dim,
                     is_predict=is_predict)
else:
    bidirectional_lstm_net(dict_dim, class_dim=2, is_predict=is_predict)
