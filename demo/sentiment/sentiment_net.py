"""Sentiment network definitions (ref: demo/sentiment/sentiment_net.py —
bidirectional_lstm_net and stacked_lstm_net on IMDB)."""

from paddle_tpu.dsl import *


def bidirectional_lstm_net(input_dim, class_dim=2, emb_dim=128, lstm_dim=128,
                           is_predict=False):
    """(ref: sentiment_net.py bidirectional_lstm_net:60)."""
    data = data_layer("word", input_dim)
    emb = embedding_layer(input=data, size=emb_dim)
    bi_lstm = bidirectional_lstm(input=emb, size=lstm_dim)
    dropout = dropout_layer(input=bi_lstm, dropout_rate=0.5)
    output = fc_layer(input=dropout, size=class_dim, act=SoftmaxActivation())
    if not is_predict:
        lbl = data_layer("label", class_dim)
        outputs(classification_cost(input=output, label=lbl))
    else:
        outputs(output)
    return output


def stacked_lstm_net(input_dim, class_dim=2, emb_dim=128, hid_dim=512,
                     stacked_num=3, is_predict=False):
    """Stacked bidirectional LSTM per Zhou et al. 2015
    (ref: sentiment_net.py stacked_lstm_net:77 — alternating-direction
    lstmemory stack with parallel fc path, max-pooled)."""
    assert stacked_num % 2 == 1
    hid_lr = 1e-3
    layer_attr = ExtraLayerAttribute(drop_rate=0.5)
    fc_para_attr = ParameterAttribute(learning_rate=hid_lr)
    lstm_para_attr = ParameterAttribute(initial_std=0., learning_rate=1.)
    para_attr = [fc_para_attr, lstm_para_attr]
    bias_attr = ParameterAttribute(initial_std=0., l2_rate=0.)
    relu = ReluActivation()
    linear = LinearActivation()

    data = data_layer("word", input_dim)
    emb = embedding_layer(input=data, size=emb_dim)

    fc1 = fc_layer(input=emb, size=hid_dim, act=linear, bias_attr=bias_attr)
    lstm1 = lstmemory(input=fc1, act=relu, bias_attr=bias_attr,
                      layer_attr=layer_attr)

    inputs_ = [fc1, lstm1]
    for i in range(2, stacked_num + 1):
        fc = fc_layer(input=inputs_, size=hid_dim, act=linear,
                      param_attr=para_attr, bias_attr=bias_attr)
        lstm = lstmemory(input=fc, reverse=(i % 2) == 0, act=relu,
                         bias_attr=bias_attr, layer_attr=layer_attr)
        inputs_ = [fc, lstm]

    fc_last = pooling_layer(input=inputs_[0], pooling_type=MaxPooling())
    lstm_last = pooling_layer(input=inputs_[1], pooling_type=MaxPooling())
    output = fc_layer(input=[fc_last, lstm_last], size=class_dim,
                      act=SoftmaxActivation(), bias_attr=bias_attr,
                      param_attr=para_attr)
    if not is_predict:
        lbl = data_layer("label", class_dim)
        outputs(classification_cost(input=output, label=lbl))
    else:
        outputs(output)
    return output
