"""Sentiment data provider (ref: demo/sentiment/dataprovider.py).

Reads `<label>\t<space-separated words>` text shards if present under data/
(the reference's IMDB preprocess layout); with no dataset on disk, falls back
to a synthetic two-class task: each class draws its words from a distinct
half of the vocabulary with some overlap — learnable by an LSTM pooled over
time, hermetic for tests/benchmarks.
"""

import os

import numpy as np

from paddle_tpu.data.provider import integer_value, integer_value_sequence, provider

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")

VOCAB = 2000


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        label = int(rng.integers(0, 2))
        L = int(rng.integers(5, 40))
        # class 0 words ~ [0, .6*V), class 1 words ~ [.4*V, V)
        lo = 0 if label == 0 else int(0.4 * VOCAB)
        hi = int(0.6 * VOCAB) if label == 0 else VOCAB
        words = rng.integers(lo, hi, L).tolist()
        yield words, label


def _file_samples(filename, dictionary):
    with open(filename) as f:
        for line in f:
            lab, _, text = line.partition("\t")
            words = [dictionary.get(w, 0) for w in text.split()]
            if words:
                yield words, int(lab)


@provider(input_types={"word": integer_value_sequence(VOCAB),
                       "label": integer_value(2)})
def process(settings, filename):
    path = os.path.join(DATA_DIR, os.path.basename(filename))
    if os.path.exists(path):
        dictionary = getattr(settings, "dictionary", None)
        if not dictionary:
            raise ValueError(
                "real data shards found under data/ but no 'dictionary' arg "
                "was passed to the provider (load_data_args)")
        yield from _file_samples(path, dictionary)
    else:
        seed = 0 if "train" in filename else 1
        yield from _synthetic(2048 if "train" in filename else 256, seed)
