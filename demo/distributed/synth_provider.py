"""Deterministic synthetic classification data for the distributed-
training demo/bench (docs/distributed_training.md): every process that
constructs this provider with the same args sees the IDENTICAL sample
stream, which is what the pserver exactness oracle and the rank-strided
data sharding of tools/train_dist.py assume."""

import numpy as np

from paddle_tpu.data.provider import (dense_vector, integer_value,
                                      provider)


def _init(settings, file_list, dim=32, classes=8, n=1024, seed=7, **_kw):
    settings.dim = int(dim)
    settings.classes = int(classes)
    settings.n = int(n)
    settings.seed = int(seed)
    settings.slots = {"x": dense_vector(settings.dim),
                      "y": integer_value(settings.classes)}


@provider(init_hook=_init, should_shuffle=False)
def process(settings, _file):
    rng = np.random.default_rng(settings.seed)
    w = rng.standard_normal((settings.dim, settings.classes))
    for _ in range(settings.n):
        x = rng.standard_normal(settings.dim).astype(np.float32)
        # a learnable rule so the demo's cost actually falls
        y = int(np.argmax(x @ w + 0.1 * rng.standard_normal(
            settings.classes)))
        yield [x, y]
