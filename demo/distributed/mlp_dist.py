"""MLP config for the parameter-server training demo/bench
(docs/distributed_training.md).  Deterministic synthetic data; shapes
ride --config_args so the bench can scale the wire traffic:

  python tools/train_dist.py --config demo/distributed/mlp_dist.py \
      --config-args "dim=64,hidden=256,batch_size=32" \
      --pserver 127.0.0.1:8571 --rank 0 --trainers 2
"""

from paddle_tpu.dsl import *  # noqa: F401,F403

dim = get_config_arg("dim", int, 32)          # noqa: F821
hidden = get_config_arg("hidden", int, 64)    # noqa: F821
classes = get_config_arg("classes", int, 8)   # noqa: F821
batch_size = get_config_arg("batch_size", int, 16)   # noqa: F821
samples = get_config_arg("samples", int, 1024)       # noqa: F821
compute_dtype = get_config_arg("compute_dtype", str, "")  # noqa: F821
# the full update-rule surface the sync exactness contract covers:
# L2 weight decay + model averaging ride config args so the oracle
# tests (and curious operators) can flip them on
l2 = get_config_arg("l2", float, 0.0)                # noqa: F821
avg_window = get_config_arg("avg_window", float, 0.0)  # noqa: F821
# trainer-side pre-accumulation: sum N batches locally, ONE send_grad
# per window (N× less gradient wire traffic, bit-exact vs N=1 with
# grad_accum — docs/distributed_training.md)
batches_per_send = get_config_arg("batches_per_send", int, 1)  # noqa: F821

define_py_data_sources2(
    train_list="none", test_list=None,
    module="demo.distributed.synth_provider", obj="process",
    args={"dim": dim, "classes": classes, "n": samples})

settings(batch_size=batch_size, learning_rate=0.05,
         learning_method=MomentumOptimizer(momentum=0.9),  # noqa: F405
         regularization=(L2Regularization(l2)      # noqa: F405
                         if l2 else None),
         learning_rate_schedule="poly",
         learning_rate_decay_a=0.001, learning_rate_decay_b=0.5,
         average_window=avg_window, max_average_window=3,
         num_batches_per_send_parameter=batches_per_send,
         compute_dtype=compute_dtype)

x = data_layer(name="x", size=dim)            # noqa: F405
h1 = fc_layer(input=x, size=hidden, act=TanhActivation())   # noqa: F405
h2 = fc_layer(input=h1, size=hidden, act=TanhActivation())  # noqa: F405
out = fc_layer(input=h2, size=classes,        # noqa: F405
               act=SoftmaxActivation())       # noqa: F405
classification_cost(input=out,                # noqa: F405
                    label=data_layer(name="y", size=classes))  # noqa: F405
