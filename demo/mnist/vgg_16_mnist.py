"""MNIST small-VGG config (ref: demo/mnist/vgg_16_mnist.py — small_vgg over
28x28x1 images, 10 classes)."""

from paddle_tpu.dsl import *

define_py_data_sources2(
    train_list="demo/mnist/train.list",
    test_list="demo/mnist/test.list",
    module="demo.mnist.mnist_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 128),
    learning_rate=0.1 / 128.0,
    learning_method=MomentumOptimizer(momentum=0.9),
    regularization=L2Regularization(5e-4 * 128),
    compute_dtype=get_config_arg("compute_dtype", str, ""))

img = data_layer(name="pixel", size=784, height=28, width=28)
predict = small_vgg(input_image=img, num_channels=1, num_classes=10)
label = data_layer(name="label", size=10)
classification_cost(input=predict, label=label)
