"""MNIST MLP config (ref: demo/mnist/mlp_trainer_config-style; the simplest
end-to-end demo)."""

from paddle_tpu.dsl import *

is_test = get_config_arg("is_test", bool, False)

define_py_data_sources2(
    train_list="demo/mnist/train.list",
    test_list="demo/mnist/test.list",
    module="demo.mnist.mnist_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 128),
    learning_rate=0.1 / 128.0,
    learning_method=MomentumOptimizer(momentum=0.9),
    regularization=L2Regularization(5e-4 * 128))

img = data_layer(name="pixel", size=784)
h1 = fc_layer(input=img, size=128, act=TanhActivation())
h2 = fc_layer(input=h1, size=128, act=TanhActivation())
predict = fc_layer(input=h2, size=10, act=SoftmaxActivation())
label = data_layer(name="label", size=10)
classification_cost(input=predict, label=label)
