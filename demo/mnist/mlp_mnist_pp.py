"""MNIST MLP with config-driven pipeline parallelism.

The same model as mlp_mnist.py, annotated with per-layer pipeline stages
the way the reference places layers on devices (ref: ParallelNeuralNetwork
`device=N`; trainer_config_helpers device attr).  Train it on a mesh with
a `pipe` axis:

    from paddle_tpu.parallel.mesh import make_mesh
    tr = Trainer(parse_config("demo/mnist/mlp_mnist_pp.py", ""),
                 mesh=make_mesh(data=4, pipe=2))

or via the CLI: --mesh_shape=data:4,pipe:2.  Training is EXACT vs the
un-pipelined config (tests/test_pipeline_config.py).
"""

from paddle_tpu.dsl import *

define_py_data_sources2(
    train_list="demo/mnist/train.list",
    test_list="demo/mnist/test.list",
    module="demo.mnist.mnist_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 128),
    learning_rate=0.1 / 128.0,
    learning_method=MomentumOptimizer(momentum=0.9),
    regularization=L2Regularization(5e-4 * 128),
    pipeline_micro_batches=get_config_arg("micro_batches", int, 4))

img = data_layer(name="pixel", size=784)
h1 = fc_layer(input=img, size=128, act=TanhActivation(),
              layer_attr=ExtraLayerAttribute(device=0))
h2 = fc_layer(input=h1, size=128, act=TanhActivation(),
              layer_attr=ExtraLayerAttribute(device=1))
predict = fc_layer(input=h2, size=10, act=SoftmaxActivation(),
                   layer_attr=ExtraLayerAttribute(device=1))
label = data_layer(name="label", size=10)
classification_cost(input=predict, label=label)
