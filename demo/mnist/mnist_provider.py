"""MNIST data provider (ref: demo/mnist/mnist_provider.py).

Reads the standard IDX-format files if present in demo/mnist/data/ (the
reference's get_mnist_data.sh downloads them); with no dataset on disk it
falls back to a deterministic synthetic digit-like dataset so the demo and
benchmarks run hermetically.
"""

import os
import struct

import numpy as np

from paddle_tpu.data.provider import dense_vector, integer_value, provider

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


def _read_idx_images(path):
    with open(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    return data.astype(np.float32) / 255.0


def _read_idx_labels(path):
    with open(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)


def _synthetic(n, seed):
    """Digit-like blobs: each class is a fixed random 28x28 template plus
    noise — linearly separable enough to show convergence.  Templates are
    seeded independently of the split so train and test share classes."""
    templates = np.random.default_rng(42).random((10, 784)).astype(np.float32)
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n).astype(np.int32)
    x = 0.7 * templates[y] + 0.3 * rng.random((n, 784)).astype(np.float32)
    return x, y


def _load(split):
    img = os.path.join(DATA_DIR, f"{split}-images-idx3-ubyte")
    lbl = os.path.join(DATA_DIR, f"{split}-labels-idx1-ubyte")
    if os.path.exists(img) and os.path.exists(lbl):
        return _read_idx_images(img), _read_idx_labels(lbl)
    return _synthetic(8192 if split == "train" else 1024,
                      seed=0 if split == "train" else 1)


@provider(input_types={"pixel": dense_vector(784), "label": integer_value(10)})
def process(settings, filename):
    split = "train" if "train" in filename else "t10k"
    x, y = _load(split)
    for i in range(len(y)):
        yield [x[i], int(y[i])]
