"""Decoder-only transformer language model — the beyond-reference model
family built from this framework's long-context stack: multi-head
attention (rotary positions, grouped-query heads, sliding window, and the
dense/flash/blockwise/ring auto-selection), pre-norm residual blocks with
layer_norm + GELU, all through the classic config DSL.

Train (causal next-token loss on token sequences):
    python -m paddle_tpu train --config=demo/model_zoo/transformer_lm.py \
        --config_args=vocab=32000,dim=512,layers=8,heads=8

Real data: put text-file paths in demo/model_zoo/lm_train.list and the
provider trains BYTE-LEVEL on their contents (vocab >= 258); the stock
placeholder list keeps the hermetic synthetic motif stream.

Long sequences scale over a mesh `seq` axis (ring attention) and the
batch over `data`:  tr = Trainer(cfg, mesh=make_mesh(data=2, seq=4)).
"""

from paddle_tpu.dsl import *

vocab = get_config_arg("vocab", int, 256)
dim = get_config_arg("dim", int, 64)
n_layers = get_config_arg("layers", int, 2)
n_heads = get_config_arg("heads", int, 4)
n_kv_heads = get_config_arg("kv_heads", int, 0)       # 0 = full MHA
window = get_config_arg("window", int, 0)             # 0 = full attention
ffn_mult = get_config_arg("ffn_mult", int, 4)
batch_size = get_config_arg("batch_size", int, 16)
compute_dtype = get_config_arg("compute_dtype", str, "")
attn_impl = get_config_arg("attn_impl", str, "auto")  # auto/dense/flash/blockwise/ring/ulysses
block_k_min = get_config_arg("block_k_min", int, 0)   # 0 = default crossover

define_py_data_sources2(
    train_list="demo/model_zoo/lm_train.list", test_list=None,
    module="demo.model_zoo.lm_provider", obj="process",
    args={"vocab": vocab})

settings(
    batch_size=batch_size,
    learning_rate=3e-4,
    learning_method=AdamOptimizer(),
    gradient_clipping_threshold=1.0,
    compute_dtype=compute_dtype)

tokens = data_layer(name="tokens", size=vocab)
h = embedding_layer(input=tokens, size=dim,
                    param_attr=ParamAttr(name="_tok_embedding",
                                         initial_std=0.02))

for i in range(n_layers):
    # pre-norm attention block: h = h + MHA(LN(h)) — rotary positions
    # instead of learned absolute embeddings
    attn_in = layer_norm_layer(input=h, name=f"blk{i}_ln1")
    attn = multi_head_attention_layer(
        attn_in, size=dim, num_heads=n_heads, causal=True, use_rope=True,
        num_kv_heads=n_kv_heads or None, window=window or None,
        attn_impl=attn_impl if attn_impl != "auto" else None,
        block_k_min=block_k_min or None,
        name=f"blk{i}_attn")
    h = addto_layer(input=[h, attn], act=LinearActivation(),
                    name=f"blk{i}_res1", bias_attr=False)
    # pre-norm GELU MLP block: h = h + W2 gelu(W1 LN(h))
    ffn_in = layer_norm_layer(input=h, name=f"blk{i}_ln2")
    ffn_h = fc_layer(input=ffn_in, size=dim * ffn_mult, act=GeluActivation(),
                     name=f"blk{i}_ffn1",
                     param_attr=ParamAttr(initial_std=0.02), bias_attr=True)
    ffn_o = fc_layer(input=ffn_h, size=dim, act=LinearActivation(),
                     name=f"blk{i}_ffn2",
                     param_attr=ParamAttr(initial_std=0.02), bias_attr=True)
    h = addto_layer(input=[h, ffn_o], act=LinearActivation(),
                    name=f"blk{i}_res2", bias_attr=False)

final = layer_norm_layer(input=h, name="final_ln")
logits = fc_layer(input=final, size=vocab, act=SoftmaxActivation(),
                  name="lm_head", param_attr=ParamAttr(initial_std=0.02),
                  bias_attr=False)
labels = data_layer(name="next_tokens", size=vocab)
classification_cost(input=logits, label=labels)
