"""Classify images / extract features with a trained ResNet
(ref: demo/model_zoo/resnet/classify.py — swig_paddle prediction +
per-layer feature dumps).  Runs the jitted forward graph in TEST mode and
prints top-1 predictions, or dumps any named layer's activations."""

import argparse
import sys

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="demo/model_zoo/resnet.py")
    ap.add_argument("--config_args",
                    default="layer_num=50,image_size=32,num_classes=4,use_data=0")
    ap.add_argument("--checkpoint", default="", help="checkpoint dir to load")
    ap.add_argument("--feature_layer", default="",
                    help="dump this layer's activations instead of predicting")
    ap.add_argument("--npy", default="", help="input images .npy [N, 3*H*W]")
    args = ap.parse_args(argv)

    import jax
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.graph.context import TEST
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config(args.config, args.config_args + ",is_predict=1")
    tr = Trainer(cfg, seed=1)
    if args.checkpoint:
        tr.load(args.checkpoint)

    if args.npy:
        x = np.load(args.npy).astype(np.float32)
    else:  # demo input
        x = np.random.default_rng(0).random((4, cfg.model_config.layers[0].size),
                                            np.float32).astype(np.float32) - 0.5

    outputs, _, _ = tr.executor.forward(
        tr.params, {"image": Argument(value=x)}, None, TEST,
        jax.random.PRNGKey(0))
    if args.feature_layer:
        # features are saved in the reference's flat C-major row layout
        feats = np.asarray(outputs[args.feature_layer].flatten_image().value)
        print(f"{args.feature_layer}: shape={feats.shape}")
        np.save("features.npy", feats)
    else:
        probs = np.asarray(outputs["output"].value)
        for i, p in enumerate(probs):
            print(f"sample {i}: label={int(p.argmax())} prob={float(p.max()):.4f}")


if __name__ == "__main__":
    main()
