"""Image provider for the model-zoo ResNet (ref: demo/model_zoo/resnet/
example/image_list_provider.py).  Reads `<path> <label>` image-list files
when real decoded data is available; otherwise serves a deterministic
synthetic dataset (class-template images + noise) so the config trains
hermetically at any image_size/num_classes."""

import numpy as np

from paddle_tpu.data.provider import dense_vector, integer_value, provider


def _init(settings, file_list=None, image_size=224, num_classes=1000, **kw):
    settings.slots = {
        "image": dense_vector(3 * image_size * image_size),
        "label": integer_value(num_classes),
    }
    settings.geom = (image_size, num_classes)


@provider(init_hook=_init)
def process(settings, filename):
    image_size, num_classes = getattr(settings, "geom", (224, 1000))
    dim = 3 * image_size * image_size
    n = 256 if "train" in filename else 64
    templates = np.random.default_rng(11).random((num_classes, dim)) \
        .astype(np.float32)
    rng = np.random.default_rng(0 if "train" in filename else 1)
    for _ in range(n):
        y = int(rng.integers(0, num_classes))
        x = 0.7 * templates[y] + 0.3 * rng.random(dim).astype(np.float32)
        yield [x - 0.5, y]
