"""Synthetic token-stream provider for the transformer LM demo: sequences
from a repeated-motif language so next-token prediction is learnable."""

import numpy as np

from paddle_tpu.data.provider import integer_value_sequence, provider


def _init(settings, file_list, **kw):
    """Resize the declared slot dims to the config-driven vocab (ref:
    PyDataProvider2 init_hook pattern — providers that depend on a
    dictionary size learn it at initialize() time)."""
    vocab = int(kw.get("vocab", 256))
    settings.args = vocab
    settings.slots = {"tokens": integer_value_sequence(vocab),
                      "next_tokens": integer_value_sequence(vocab)}


@provider(input_types={"tokens": integer_value_sequence(256),
                       "next_tokens": integer_value_sequence(256)},
          should_shuffle=True, init_hook=_init)
def process(settings, filename):
    vocab = settings.args if isinstance(settings.args, int) else 256
    rng = np.random.default_rng(7)
    motifs = [rng.integers(2, vocab, rng.integers(3, 8)).tolist()
              for _ in range(8)]
    for _ in range(256):
        seq = [1]                                    # BOS
        while len(seq) < 33:
            seq += motifs[int(rng.integers(0, len(motifs)))]
        seq = seq[:33]
        yield {"tokens": seq[:-1], "next_tokens": seq[1:]}
