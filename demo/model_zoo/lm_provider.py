"""Token-stream provider for the transformer LM demo.

Two modes per file-list entry:
  * an existing file -> BYTE-LEVEL language modeling over its contents
    (byte b maps to id b+2; 0=pad, 1=BOS — the zero-dependency tokenizer
    every byte-LM demo uses, wants vocab >= 258) — point lm_train.list
    at any text corpus and train for real;
  * a missing path (the stock `lm_train.list` placeholder) -> the
    synthetic repeated-motif language, so the demo and its tests run
    hermetically with no data download.
"""

import os

import numpy as np

from paddle_tpu.data.provider import integer_value_sequence, provider

_BOS = 1
_BYTE_OFF = 2


def _init(settings, file_list, **kw):
    """Resize the declared slot dims to the config-driven vocab (ref:
    PyDataProvider2 init_hook pattern — providers that depend on a
    dictionary size learn it at initialize() time)."""
    vocab = int(kw.get("vocab", 256))
    settings.args = {"vocab": vocab,
                     "seq_len": int(kw.get("seq_len", 33))}
    settings.slots = {"tokens": integer_value_sequence(vocab),
                      "next_tokens": integer_value_sequence(vocab)}


def _synthetic(vocab, seq_len):
    rng = np.random.default_rng(7)
    motifs = [rng.integers(2, vocab, rng.integers(3, 8)).tolist()
              for _ in range(8)]
    for _ in range(256):
        seq = [_BOS]
        while len(seq) < seq_len:
            seq += motifs[int(rng.integers(0, len(motifs)))]
        seq = seq[:seq_len]
        yield {"tokens": seq[:-1], "next_tokens": seq[1:]}


def _byte_stream(filename, vocab, seq_len):
    data = np.fromfile(filename, np.uint8)
    # clip into the table so a small-vocab config still runs (ids beyond
    # vocab-1 collapse onto the last row rather than crashing the gather)
    ids = np.minimum(data.astype(np.int64) + _BYTE_OFF, vocab - 1)
    clipped = int((data.astype(np.int64) + _BYTE_OFF >= vocab).sum())
    if clipped:
        import logging
        logging.getLogger("paddle_tpu").warning(
            "lm_provider: %d bytes of %s clip onto token id %d — byte "
            "mode wants vocab >= 258 (config arg vocab=)",
            clipped, filename, vocab - 1)
    stride = seq_len - 1
    for start in range(0, max(len(ids) - 1, 1), stride):
        body = ids[start:start + stride].tolist()
        if not body:
            break
        seq = [_BOS] + body
        yield {"tokens": seq[:-1], "next_tokens": seq[1:]}


@provider(input_types={"tokens": integer_value_sequence(256),
                       "next_tokens": integer_value_sequence(256)},
          should_shuffle=True, init_hook=_init)
def process(settings, filename):
    args = settings.args if isinstance(settings.args, dict) else {}
    vocab = int(args.get("vocab", 256))
    seq_len = int(args.get("seq_len", 33))
    if filename and os.path.exists(filename):
        yield from _byte_stream(filename, vocab, seq_len)
    elif filename == "dummy":
        # the stock lm_train.list placeholder: hermetic synthetic stream
        yield from _synthetic(vocab, seq_len)
    else:
        # any OTHER missing path is a typo'd corpus, not a request for
        # toy data — silently training on motifs would mask it
        raise FileNotFoundError(
            f"lm_provider: {filename!r} does not exist (use the stock "
            f"'dummy' entry for the synthetic stream)")
