"""ResNet 50/101/152 model-zoo config (ref: demo/model_zoo/resnet/resnet.py) —
bottleneck blocks with conv+bn branches and addto shortcuts, rebuilt in the
TPU DSL.  `layer_num` picks the depth; `image_size`/`num_classes` are
config_args so the same config serves ImageNet-scale feature extraction and
small smoke runs (the reference fixes 224x224/1000)."""

from paddle_tpu.dsl import *

is_predict = get_config_arg("is_predict", bool, False)
layer_num = get_config_arg("layer_num", int, 50)
image_size = get_config_arg("image_size", int, 224)
num_classes = get_config_arg("num_classes", int, 1000)
batch_size = get_config_arg("batch_size", int, 64)
use_data = get_config_arg("use_data", bool, True)

if use_data:
    define_py_data_sources2(
        train_list=None if is_predict else "demo/model_zoo/train.list",
        test_list="demo/model_zoo/test.list",
        module="demo.model_zoo.imagenet_provider",
        obj="process",
        args={"image_size": image_size, "num_classes": num_classes})

settings(
    batch_size=batch_size,
    learning_rate=0.1 / batch_size,
    learning_method=MomentumOptimizer(momentum=0.9),
    regularization=L2Regularization(0.0001 * batch_size),
    learning_rate_decay_a=0.5,
    learning_rate_decay_b=1200000 * 10,
    learning_rate_schedule="discexp")


def conv_bn_layer(name, input, filter_size, num_filters, stride, padding,
                  channels=None, active_type=None):
    """conv (no act, no bias) + batch-norm carrying the activation
    (ref: resnet.py conv_bn_layer)."""
    tmp = img_conv_layer(
        name=name + "_conv", input=input, filter_size=filter_size,
        num_channels=channels, num_filters=num_filters, stride=stride,
        padding=padding, act=LinearActivation(), bias_attr=False)
    return batch_norm_layer(
        name=name + "_bn", input=tmp,
        act=active_type if active_type is not None else ReluActivation())


def bottleneck_block(name, input, num_filters1, num_filters2):
    """1x1 -> 3x3 -> 1x1 bottleneck; identity shortcut; relu after the add
    (ref: resnet.py bottleneck_block)."""
    last = conv_bn_layer(name + "_branch2a", input, 1, num_filters1, 1, 0)
    last = conv_bn_layer(name + "_branch2b", last, 3, num_filters1, 1, 1)
    last = conv_bn_layer(name + "_branch2c", last, 1, num_filters2, 1, 0,
                         active_type=LinearActivation())
    return addto_layer(name=name + "_addto", input=[input, last],
                       act=ReluActivation())


def mid_projection(name, input, num_filters1, num_filters2, stride=2):
    """Stage-entry block: strided branch1 projection shortcut + bottleneck
    branch2 (ref: resnet.py mid_projection)."""
    branch1 = conv_bn_layer(name + "_branch1", input, 1, num_filters2,
                            stride, 0, active_type=LinearActivation())
    last = conv_bn_layer(name + "_branch2a", input, 1, num_filters1, stride, 0)
    last = conv_bn_layer(name + "_branch2b", last, 3, num_filters1, 1, 1)
    last = conv_bn_layer(name + "_branch2c", last, 1, num_filters2, 1, 0,
                         active_type=LinearActivation())
    return addto_layer(name=name + "_addto", input=[branch1, last],
                       act=ReluActivation())


def deep_res_net(res2_num=3, res3_num=4, res4_num=6, res5_num=3):
    """(ref: resnet.py deep_res_net) — res{2..5}_num pick 50/101/152."""
    img = data_layer(name="image", size=image_size * image_size * 3,
                     height=image_size, width=image_size)
    tmp = conv_bn_layer("res_conv1", img, 7, 64, 2, 3, channels=3)
    tmp = img_pool_layer(name="pool1", input=tmp, pool_size=3, stride=2,
                         pool_type=MaxPooling())

    tmp = mid_projection("res2_1", tmp, 64, 256, stride=1)
    for i in range(2, res2_num + 1):
        tmp = bottleneck_block(f"res2_{i}", tmp, 64, 256)

    tmp = mid_projection("res3_1", tmp, 128, 512)
    for i in range(2, res3_num + 1):
        tmp = bottleneck_block(f"res3_{i}", tmp, 128, 512)

    tmp = mid_projection("res4_1", tmp, 256, 1024)
    for i in range(2, res4_num + 1):
        tmp = bottleneck_block(f"res4_{i}", tmp, 256, 1024)

    tmp = mid_projection("res5_1", tmp, 512, 2048)
    for i in range(2, res5_num + 1):
        tmp = bottleneck_block(f"res5_{i}", tmp, 512, 2048)

    tmp = img_pool_layer(name="pool5", input=tmp,
                         pool_size=tmp.img_size, stride=1,
                         pool_type=AvgPooling())
    return fc_layer(name="output", input=tmp, size=num_classes,
                    act=SoftmaxActivation())


depth_cfg = {
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}
assert layer_num in depth_cfg, f"layer_num must be one of {sorted(depth_cfg)}"
predict = deep_res_net(*depth_cfg[layer_num])

if not is_predict:
    lbl = data_layer(name="label", size=num_classes)
    classification_cost(input=predict, label=lbl)
else:
    outputs(predict)
