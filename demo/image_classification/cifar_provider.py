"""CIFAR-10 data provider (ref: demo/image_classification/image_provider.py).

Loads the python-pickle CIFAR batches if present under data/cifar-10-batches-py
(the reference's download script fetches them); otherwise falls back to a
deterministic synthetic 32x32x3 dataset so demos/benchmarks run hermetically.
Mean subtraction mirrors the reference's ImageTransformer preprocessing.
"""

import os
import pickle

import numpy as np

from paddle_tpu.data.provider import dense_vector, integer_value, provider

DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "data", "cifar-10-batches-py")
DIM = 3 * 32 * 32


def _synthetic(n, seed):
    templates = np.random.default_rng(7).random((10, DIM)).astype(np.float32)
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, n).astype(np.int32)
    x = 0.6 * templates[y] + 0.4 * rng.random((n, DIM)).astype(np.float32)
    return x - 0.5, y


def _load(split):
    if os.path.isdir(DATA_DIR):
        xs, ys = [], []
        names = [f"data_batch_{i}" for i in range(1, 6)] if split == "train" \
            else ["test_batch"]
        for nm in names:
            with open(os.path.join(DATA_DIR, nm), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            xs.append(np.asarray(d[b"data"], np.float32) / 255.0 - 0.5)
            ys.append(np.asarray(d[b"labels"], np.int32))
        return np.concatenate(xs), np.concatenate(ys)
    return _synthetic(10240 if split == "train" else 1024,
                      seed=0 if split == "train" else 1)


@provider(input_types={"image": dense_vector(DIM), "label": integer_value(10)})
def process(settings, filename):
    split = "train" if "train" in filename else "test"
    x, y = _load(split)
    for i in range(len(y)):
        yield [x[i], int(y[i])]
