"""CIFAR-10 small-VGG config (ref: demo/image_classification/vgg_16_cifar.py)
— north-star benchmark #1 (BASELINE.md)."""

from paddle_tpu.dsl import *

is_predict = get_config_arg("is_predict", bool, False)
batch_size = get_config_arg("batch_size", int, 128)
# '' = fp32; 'bfloat16' = mixed precision (fp32 params, bf16 MXU matmuls)
compute_dtype = get_config_arg("compute_dtype", str, "")

define_py_data_sources2(
    train_list=None if is_predict else "demo/image_classification/train.list",
    test_list="demo/image_classification/test.list",
    module="demo.image_classification.cifar_provider",
    obj="process")

settings(
    batch_size=batch_size,
    learning_rate=0.1 / 128.0,
    learning_method=MomentumOptimizer(momentum=0.9),
    regularization=L2Regularization(0.0005 * 128),
    compute_dtype=compute_dtype)

img = data_layer(name="image", size=3 * 32 * 32, height=32, width=32)
predict = small_vgg(input_image=img, num_channels=3, num_classes=10)
if not is_predict:
    lbl = data_layer(name="label", size=10)
    classification_cost(input=predict, label=lbl)
else:
    outputs(predict)
