"""Print the learned w/b from a saved checkpoint
(ref: demo/introduction/evaluate_model.py, which reads the raw pass-00029
parameter files; here checkpoints are the framework's npz format)."""

import sys

from paddle_tpu.trainer import checkpoint as ckpt


def main(path="output"):
    data = ckpt.load_checkpoint(path)
    w = float(data["params"]["w"].reshape(-1)[0])
    b = float(data["params"]["b"].reshape(-1)[0])
    print(f"w={w:.6f}, b={b:.6f}")


if __name__ == "__main__":
    main(*sys.argv[1:])
