"""Linear-regression introduction demo (ref: demo/introduction/
trainer_config.py): learn y = 2x + 0.3 with a single fc layer — the
smallest possible end-to-end config."""

from paddle_tpu.dsl import *

define_py_data_sources2(
    train_list="demo/introduction/train.list",
    test_list=None,
    module="demo.introduction.dataprovider",
    obj="process")

# lr rescaled from the reference's 1e-3: this framework's loss is the
# per-sample MEAN (builder.py GraphExecutor.loss) where the reference
# divides the summed gradient by batch size at the updater with lr tuned
# for that pipeline — 1e-2 reproduces the reference's convergence in 30
# passes (w->2, b->0.3)
settings(batch_size=12, learning_rate=1e-2,
         learning_method=MomentumOptimizer())

x = data_layer(name="x", size=1)
y = data_layer(name="y", size=1)
y_predict = fc_layer(
    input=x,
    param_attr=ParameterAttribute(name="w"),
    size=1,
    act=LinearActivation(),
    bias_attr=ParameterAttribute(name="b"))
regression_cost(input=y_predict, label=y)
