"""(ref: demo/introduction/dataprovider.py): 2000 samples of y = 2x + 0.3."""

import numpy as np

from paddle_tpu.data.provider import dense_vector, provider


@provider(input_types={"x": dense_vector(1), "y": dense_vector(1)})
def process(settings, input_file):
    rng = np.random.default_rng(42)
    for _ in range(2000):
        x = float(rng.random())
        yield [np.array([x], np.float32),
               np.array([2 * x + 0.3], np.float32)]
