"""MovieLens-style data provider (ref: demo/recommendation/dataprovider.py —
movie {id, title word sequence, genres multi-hot} + user {id, gender, age,
occupation} slots and a scaled rating regression target).

Synthetic fallback: ratings come from hidden low-rank user/movie factors, so
the embedding-fusion model can actually fit them.
"""

import numpy as np

from paddle_tpu.data.provider import (
    dense_vector, integer_value, integer_value_sequence, provider,
    sparse_binary_vector,
)

MOVIE_DIM = 512
USER_DIM = 512
TITLE_VOCAB = 256
GENRE_DIM = 18
GENDER_DIM = 2
AGE_DIM = 7
OCCUPATION_DIM = 21

_K = 8
_RNG = np.random.default_rng(7)
_MOVIE_F = _RNG.normal(size=(MOVIE_DIM, _K)).astype(np.float32)
_USER_F = _RNG.normal(size=(USER_DIM, _K)).astype(np.float32)


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        m = int(rng.integers(0, MOVIE_DIM))
        u = int(rng.integers(0, USER_DIM))
        title = rng.integers(0, TITLE_VOCAB, int(rng.integers(2, 8))).tolist()
        genres = sorted(set(rng.integers(0, GENRE_DIM, 3).tolist()))
        gender = u % GENDER_DIM
        age = u % AGE_DIM
        occupation = u % OCCUPATION_DIM
        # rating in [-1, 1] from the latent factors (scaled like the
        # reference's (rating - 3) / 2 five-star normalization)
        r = float(np.tanh(_MOVIE_F[m] @ _USER_F[u] / np.sqrt(_K)))
        yield m, title, genres, u, gender, age, occupation, [r]


@provider(input_types={
    "movie_id": integer_value(MOVIE_DIM),
    "title": integer_value_sequence(TITLE_VOCAB),
    "genres": sparse_binary_vector(GENRE_DIM),
    "user_id": integer_value(USER_DIM),
    "gender": integer_value(GENDER_DIM),
    "age": integer_value(AGE_DIM),
    "occupation": integer_value(OCCUPATION_DIM),
    "rating": dense_vector(1),
})
def process(settings, filename):
    seed = 0 if "train" in filename else 1
    yield from _synthetic(4096 if "train" in filename else 512, seed)
