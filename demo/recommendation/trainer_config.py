"""MovieLens regression config (ref: demo/recommendation/trainer_config.py —
per-feature embedding/fc fusion for movie and user, cosine similarity,
regression cost).  Embedding tables are marked sparse_update: under a mesh
they shard vocab-wise like pserver sparse tables (parallel/sparse.py)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from ml_provider import (  # noqa: E402
    AGE_DIM, GENDER_DIM, GENRE_DIM, MOVIE_DIM, OCCUPATION_DIM, TITLE_VOCAB,
    USER_DIM,
)

is_predict = get_config_arg("is_predict", bool, False)
emb_size = get_config_arg("emb_size", int, 256)
# bench overrides: real MovieLens-1M dims are larger than the synthetic
# provider's (movie 3952, user 6040, title vocab ~5100)
MOVIE_DIM = get_config_arg("movie_dim", int, MOVIE_DIM)
USER_DIM = get_config_arg("user_dim", int, USER_DIM)
TITLE_VOCAB = get_config_arg("title_vocab", int, TITLE_VOCAB)

define_py_data_sources2(
    train_list="demo/recommendation/train.list",
    test_list="demo/recommendation/test.list",
    module="demo.recommendation.ml_provider",
    obj="process")

settings(
    batch_size=get_config_arg("batch_size", int, 1600),
    learning_rate=get_config_arg("learning_rate", float, 1e-3),
    learning_method=RMSPropOptimizer(),
    compute_dtype=get_config_arg("compute_dtype", str, ""))

def id_feature(name, dim):
    emb = embedding_layer(input=data_layer(name, size=dim), size=emb_size,
                          param_attr=ParamAttr(sparse_update=True))
    return fc_layer(input=emb, size=emb_size)


# movie features (ref: construct_feature("movie"))
movie_id_f = id_feature("movie_id", MOVIE_DIM)
title_emb = embedding_layer(input=data_layer("title", size=TITLE_VOCAB),
                            size=emb_size,
                            param_attr=ParamAttr(sparse_update=True))
title_f = sequence_conv_pool(input=title_emb, context_len=5,
                             hidden_size=emb_size)
genre_f = fc_layer(input=fc_layer(input=data_layer("genres", size=GENRE_DIM),
                                  size=emb_size), size=emb_size)
movie_feature = fc_layer(name="movie_fusion",
                         input=[movie_id_f, title_f, genre_f], size=emb_size)

# user features (ref: construct_feature("user"))
user_id_f = id_feature("user_id", USER_DIM)
gender_f = id_feature("gender", GENDER_DIM)
age_f = id_feature("age", AGE_DIM)
occupation_f = id_feature("occupation", OCCUPATION_DIM)
user_feature = fc_layer(name="user_fusion",
                        input=[user_id_f, gender_f, age_f, occupation_f],
                        size=emb_size)

similarity = cos_sim(a=movie_feature, b=user_feature)

if not is_predict:
    outputs(regression_cost(input=similarity,
                            label=data_layer("rating", size=1)))
else:
    outputs(similarity)
