"""RNN-CRF sequence tagging config (ref: demo/sequence_tagging/rnn_crf.py —
embedding + mixed + bidirectional recurrent layers into a CRF, with
crf_decoding + chunk F1 evaluation)."""

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from tagging_provider import (  # noqa: E402
    FEAT_DIM, NUM_CHUNK_TYPES, NUM_LABELS, POS_DIM, WORD_DIM,
)

batch_size = get_config_arg("batch_size", int, 16)

define_py_data_sources2(
    train_list="demo/sequence_tagging/train.list",
    test_list="demo/sequence_tagging/test.list",
    module="demo.sequence_tagging.tagging_provider",
    obj="process")

settings(
    learning_method=MomentumOptimizer(),
    batch_size=batch_size,
    regularization=L2Regularization(batch_size * 1e-5),
    average_window=0.5,
    learning_rate=2e-3,
    learning_rate_decay_a=5e-7,
    learning_rate_decay_b=0.5)

word_dim = 128
hidden_dim = 128
with_rnn = True

initial_std = 1 / math.sqrt(hidden_dim)
param_attr = ParamAttr(initial_std=initial_std)

features = data_layer(name="features", size=FEAT_DIM)
word = data_layer(name="word", size=WORD_DIM)
pos = data_layer(name="pos", size=POS_DIM)
chunk = data_layer(name="chunk", size=NUM_LABELS)

emb = embedding_layer(input=word, size=word_dim,
                      param_attr=ParamAttr(initial_std=0))

hidden1 = mixed_layer(
    size=hidden_dim,
    act=STanhActivation(),
    bias_attr=True,
    input=[full_matrix_projection(emb, size=hidden_dim),
           table_projection(pos, size=hidden_dim, param_attr=param_attr)])

if with_rnn:
    rnn1 = recurrent_layer(act=ReluActivation(), bias_attr=True, input=hidden1,
                           param_attr=ParamAttr(initial_std=0))

hidden2 = mixed_layer(
    size=hidden_dim,
    act=STanhActivation(),
    bias_attr=True,
    input=[full_matrix_projection(hidden1, size=hidden_dim)] +
    ([full_matrix_projection(rnn1, size=hidden_dim,
                             param_attr=ParamAttr(initial_std=0))]
     if with_rnn else []))

if with_rnn:
    rnn2 = recurrent_layer(reverse=True, act=ReluActivation(), bias_attr=True,
                           input=hidden2, param_attr=ParamAttr(initial_std=0))

crf_input = mixed_layer(
    size=NUM_LABELS,
    bias_attr=False,
    input=[full_matrix_projection(hidden2, size=NUM_LABELS)] +
    ([full_matrix_projection(rnn2, size=NUM_LABELS,
                             param_attr=ParamAttr(initial_std=0))]
     if with_rnn else []))

crf = crf_layer(input=crf_input, label=chunk,
                param_attr=ParamAttr(name="crfw", initial_std=0))

crf_dec = crf_decoding_layer(size=NUM_LABELS, input=crf_input, label=chunk,
                             param_attr=ParamAttr(name="crfw"))

sum_evaluator(name="error", input=crf_dec)
chunk_evaluator(name="chunk_f1", input=crf_dec, label=chunk,
                chunk_scheme="IOB", num_chunk_types=NUM_CHUNK_TYPES)

inputs(word, pos, chunk, features)
outputs(crf)
