"""Sequence tagging data provider (ref: demo/sequence_tagging/dataprovider.py
— CoNLL-2000 text chunking: per-token features/word/pos and an IOB chunk
label).

Generates a synthetic chunking task with the reference's slot layout: a
hidden segment process emits IOB labels (11 chunk types, 23 label values)
and token features correlated with the labels — hermetic and learnable.
"""

import numpy as np

from paddle_tpu.data.provider import (
    integer_value_sequence, provider, sparse_binary_vector_sequence,
)

NUM_CHUNK_TYPES = 11
NUM_LABELS = NUM_CHUNK_TYPES * 2 + 1      # IOB: B-x, I-x per type + O = 23
WORD_DIM = 2000
POS_DIM = 44
FEAT_DIM = 1024


def _synthetic(n, seed):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        L = int(rng.integers(4, 24))
        labels, words, poss, feats = [], [], [], []
        i = 0
        while i < L:
            if rng.random() < 0.5:        # O run
                run = int(rng.integers(1, 4))
                for _ in range(min(run, L - i)):
                    labels.append(NUM_LABELS - 1)
                    i += 1
            else:                          # chunk of some type
                t = int(rng.integers(0, NUM_CHUNK_TYPES))
                run = int(rng.integers(1, 4))
                for k in range(min(run, L - i)):
                    labels.append(t * 2 + (0 if k == 0 else 1))
                    i += 1
        for lab in labels:
            # word/pos/features correlated with the label
            words.append(int(rng.integers(0, 80)) + (lab * 80) % WORD_DIM)
            poss.append(lab % POS_DIM)
            feats.append([(lab * 37 + j) % FEAT_DIM for j in range(4)])
        yield feats, words, poss, labels


@provider(input_types={
    "features": sparse_binary_vector_sequence(FEAT_DIM),
    "word": integer_value_sequence(WORD_DIM),
    "pos": integer_value_sequence(POS_DIM),
    "chunk": integer_value_sequence(NUM_LABELS),
})
def process(settings, filename):
    seed = 0 if "train" in filename else 1
    yield from _synthetic(1024 if "train" in filename else 128, seed)
