"""Linear-chain CRF over sparse features (ref:
demo/sequence_tagging/linear_crf.py — single sparse fc into a CRF)."""

import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from paddle_tpu.dsl import *  # noqa: E402
from tagging_provider import FEAT_DIM, NUM_CHUNK_TYPES, NUM_LABELS, POS_DIM, WORD_DIM  # noqa: E402

batch_size = get_config_arg("batch_size", int, 16)

define_py_data_sources2(
    train_list="demo/sequence_tagging/train.list",
    test_list="demo/sequence_tagging/test.list",
    module="demo.sequence_tagging.tagging_provider",
    obj="process")

settings(
    learning_method=MomentumOptimizer(),
    batch_size=batch_size,
    regularization=L2Regularization(batch_size * 1e-4),
    average_window=0.5,
    learning_rate=1e-1,
    learning_rate_decay_a=1e-5,
    learning_rate_decay_b=0.25)


def get_simd_size(size):
    # (ref: linear_crf.py — label count padded for sparse_update alignment)
    return int(math.ceil(float(size) / 8)) * 8


num_label_types = get_simd_size(NUM_LABELS)

features = data_layer(name="features", size=FEAT_DIM)
word = data_layer(name="word", size=WORD_DIM)
pos = data_layer(name="pos", size=POS_DIM)
chunk = data_layer(name="chunk", size=num_label_types)

crf_input = fc_layer(
    input=features, size=num_label_types, act=LinearActivation(),
    bias_attr=False, param_attr=ParamAttr(initial_std=0, sparse_update=True))

crf = crf_layer(input=crf_input, label=chunk,
                param_attr=ParamAttr(name="crfw", initial_std=0))

crf_dec = crf_decoding_layer(size=num_label_types, input=crf_input, label=chunk,
                             param_attr=ParamAttr(name="crfw"))

sum_evaluator(name="error", input=crf_dec)
chunk_evaluator(name="chunk_f1", input=crf_dec, label=chunk,
                chunk_scheme="IOB", num_chunk_types=NUM_CHUNK_TYPES)

inputs(word, pos, chunk, features)
outputs(crf)
