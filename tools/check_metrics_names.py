"""Lint: obs.metrics.CATALOG and docs/observability.md must agree.

The metric catalog (paddle_tpu/obs/metrics.py CATALOG) is the single
source of truth for every metric name this repo emits — the strict
registries (serving server, trainer) refuse names outside it at runtime,
so any metric that actually renders is catalogued.  This lint closes the
other half of the loop against the documentation:

  * every CATALOG name must appear as a `` `name` `` row in the
    "## Metric reference" section of docs/observability.md (a metric
    cannot ship undocumented);
  * every metric row in that section must name a CATALOG entry (the doc
    cannot advertise metrics the code no longer emits).

Wired as a tier-1 test in tests/test_tools.py.  Exit 0 = in sync,
1 = drift (both directions printed), 2 = doc/section missing.
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.obs.metrics import CATALOG  # noqa: E402

DOC = os.path.join(REPO, "docs", "observability.md")
SECTION = "## Metric reference"
#: a metric row: a table line whose FIRST cell is a backticked name
_ROW = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`")


def doc_metric_names(doc_path: str = DOC) -> set[str]:
    """Names documented in the metric-reference tables of the doc."""
    with open(doc_path) as f:
        text = f.read()
    if SECTION not in text:
        raise ValueError(f"{doc_path} has no '{SECTION}' section — the "
                         f"lint anchors to it")
    section = text.split(SECTION, 1)[1]
    # the section runs to the next same-level heading (or EOF)
    section = re.split(r"\n## ", section, maxsplit=1)[0]
    names = set()
    for line in section.splitlines():
        m = _ROW.match(line.strip())
        if m:
            names.add(m.group(1))
    return names


def check(doc_path: str = DOC) -> tuple[set, set]:
    """(undocumented, stale) name sets — both empty when in sync."""
    documented = doc_metric_names(doc_path)
    code = set(CATALOG)
    return code - documented, documented - code


def main(argv=None) -> int:
    try:
        undocumented, stale = check()
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    ok = True
    for name in sorted(undocumented):
        ok = False
        print(f"UNDOCUMENTED: {name!r} is in obs.metrics.CATALOG but has "
              f"no row in {DOC} '{SECTION}'")
    for name in sorted(stale):
        ok = False
        print(f"STALE DOC: {DOC} documents {name!r} but it is not in "
              f"obs.metrics.CATALOG")
    if ok:
        print(f"ok: {len(CATALOG)} metric names in sync with "
              f"docs/observability.md")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
