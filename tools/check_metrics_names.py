"""Lint: obs.metrics.CATALOG, docs/observability.md, and the code agree.

The metric catalog (paddle_tpu/obs/metrics.py CATALOG) is the single
source of truth for every metric name this repo emits — the strict
registries (serving server, trainer) refuse names outside it at runtime,
so any metric that actually renders is catalogued.  This lint closes the
loop in FOUR directions:

  * every CATALOG name must appear as a `` `name` `` row in the
    "## Metric reference" section of docs/observability.md (a metric
    cannot ship undocumented);
  * every metric row in that section must name a CATALOG entry (the doc
    cannot advertise metrics the code no longer emits);
  * every CATALOG name must be REFERENCED as a literal somewhere under
    `paddle_tpu/` outside the CATALOG block itself (a dead catalog row —
    a metric nothing declares or collects — cannot linger and mislead
    dashboards; the CATALOG assignment in obs/metrics.py is excluded via
    ast so a row cannot vouch for itself);
  * every flight-recorder event `kind` emitted under `paddle_tpu/` must
    have a row in the doc's "## Flight event reference" table, and every
    row there must name an emitted kind — the metric lint's sibling:
    before this, event names had no lockstep check at all.  Emission
    sites are found by AST (a Call on a receiver named `flight`, e.g.
    `self.flight.record(...)` / `flight.record(...)`, whose first
    argument must be a STRING LITERAL — a computed kind is itself a lint
    error, because it could ship undocumented).

Wired as a tier-1 test in tests/test_tools.py.  Exit 0 = in sync,
1 = drift (all directions printed), 2 = doc/section missing.
"""

from __future__ import annotations

import ast
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.obs.metrics import CATALOG  # noqa: E402

DOC = os.path.join(REPO, "docs", "observability.md")
SECTION = "## Metric reference"
EVENT_SECTION = "## Flight event reference"
#: a metric/event row: a table line whose FIRST cell is a backticked name
_ROW = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`")


def doc_metric_names(doc_path: str = DOC) -> set[str]:
    """Names documented in the metric-reference tables of the doc."""
    with open(doc_path) as f:
        text = f.read()
    if SECTION not in text:
        raise ValueError(f"{doc_path} has no '{SECTION}' section — the "
                         f"lint anchors to it")
    section = text.split(SECTION, 1)[1]
    # the section runs to the next same-level heading (or EOF)
    section = re.split(r"\n## ", section, maxsplit=1)[0]
    names = set()
    for line in section.splitlines():
        m = _ROW.match(line.strip())
        if m:
            names.add(m.group(1))
    return names


def check(doc_path: str = DOC) -> tuple[set, set]:
    """(undocumented, stale) name sets — both empty when in sync."""
    documented = doc_metric_names(doc_path)
    code = set(CATALOG)
    return code - documented, documented - code


def doc_event_kinds(doc_path: str = DOC) -> set[str]:
    """Event kinds documented in the doc's flight-event table."""
    with open(doc_path) as f:
        text = f.read()
    if EVENT_SECTION not in text:
        raise ValueError(f"{doc_path} has no '{EVENT_SECTION}' section — "
                         f"the event lint anchors to it")
    section = text.split(EVENT_SECTION, 1)[1]
    section = re.split(r"\n## ", section, maxsplit=1)[0]
    kinds = set()
    for line in section.splitlines():
        m = _ROW.match(line.strip())
        if m:
            kinds.add(m.group(1))
    return kinds


def emitted_event_kinds(root: str = None) -> tuple[set[str], list[str]]:
    """(kinds, problems): every first-arg string literal of a
    `*.flight.record(...)` / `flight.record(...)` call under `root`,
    plus a problem line per call whose kind is NOT a literal (those
    could ship undocumented, so they fail the lint)."""
    root = root or os.path.join(REPO, "paddle_tpu")
    kinds: set[str] = set()
    problems: list[str] = []
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path) as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "record"):
                    continue
                recv = node.func.value
                is_flight = (isinstance(recv, ast.Name)
                             and recv.id == "flight") or \
                            (isinstance(recv, ast.Attribute)
                             and recv.attr == "flight")
                if not is_flight:
                    continue          # e.g. CompileWatch.record(self, ...)
                rel = os.path.relpath(path, REPO)
                if not node.args:
                    problems.append(f"{rel}:{node.lineno}: flight.record "
                                    f"with no kind argument")
                elif isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    kinds.add(node.args[0].value)
                else:
                    problems.append(
                        f"{rel}:{node.lineno}: flight.record kind is not "
                        f"a string literal — the event lint cannot see "
                        f"it, so it could ship undocumented")
    return kinds, problems


def check_events(doc_path: str = DOC,
                 root: str = None) -> tuple[set, set, list]:
    """(undocumented, stale, problems) — all empty when in sync."""
    documented = doc_event_kinds(doc_path)
    emitted, problems = emitted_event_kinds(root)
    return emitted - documented, documented - emitted, problems


def _source_without_catalog(path: str) -> str:
    """File source with the CATALOG assignment blanked (ast-located), so
    the catalog's own rows cannot count as references to themselves."""
    import ast

    with open(path) as f:
        src = f.read()
    if os.path.abspath(path) != os.path.abspath(
            os.path.join(REPO, "paddle_tpu", "obs", "metrics.py")):
        return src
    tree = ast.parse(src)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        if any(getattr(t, "id", "") == "CATALOG" for t in targets):
            lines = src.splitlines(True)
            return "".join(lines[:node.lineno - 1]) \
                + "".join(lines[node.end_lineno:])
    return src


def unreferenced_names(names=None, root: str = None) -> set[str]:
    """CATALOG names never referenced as a literal in any .py under
    paddle_tpu/ (outside the CATALOG block) — dead rows the registry
    would happily accept but nothing emits."""
    names = set(CATALOG if names is None else names)
    root = root or os.path.join(REPO, "paddle_tpu")
    sources = []
    for dirpath, _dirnames, filenames in os.walk(root):
        if "__pycache__" in dirpath:
            continue
        for fn in filenames:
            if fn.endswith(".py"):
                sources.append(
                    _source_without_catalog(os.path.join(dirpath, fn)))
    blob = "\n".join(sources)
    return {name for name in names if name not in blob}


def main(argv=None) -> int:
    try:
        undocumented, stale = check()
        dead = unreferenced_names()
        ev_undoc, ev_stale, ev_problems = check_events()
    except (OSError, ValueError, SyntaxError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    ok = True
    for name in sorted(undocumented):
        ok = False
        print(f"UNDOCUMENTED: {name!r} is in obs.metrics.CATALOG but has "
              f"no row in {DOC} '{SECTION}'")
    for name in sorted(stale):
        ok = False
        print(f"STALE DOC: {DOC} documents {name!r} but it is not in "
              f"obs.metrics.CATALOG")
    for name in sorted(dead):
        ok = False
        print(f"DEAD CATALOG ROW: {name!r} is in obs.metrics.CATALOG but "
              f"nothing under paddle_tpu/ references it — delete the row "
              f"or wire the metric")
    for kind in sorted(ev_undoc):
        ok = False
        print(f"UNDOCUMENTED EVENT: flight kind {kind!r} is emitted under "
              f"paddle_tpu/ but has no row in {DOC} '{EVENT_SECTION}'")
    for kind in sorted(ev_stale):
        ok = False
        print(f"STALE EVENT DOC: {DOC} documents flight kind {kind!r} "
              f"but nothing under paddle_tpu/ emits it")
    for line in ev_problems:
        ok = False
        print(f"UNLINTABLE EVENT: {line}")
    if ok:
        emitted, _ = emitted_event_kinds()
        print(f"ok: {len(CATALOG)} metric names and {len(emitted)} flight "
              f"event kinds in sync with docs/observability.md, all "
              f"referenced in code")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
