"""Adjudicate the round-4 fp32 flash parity failure with an f64 oracle.

MEASURE/parity.out (v5e, round 4): `flash B2_T512_H4_D64_float32` had
46/262144 elements outside rtol/atol=2e-3 against an fp32 dense reference
(max abs diff 5e-3, max REL diff 0.49 — i.e. tiny-magnitude outputs).
Question (VERDICT r4 item 2): kernel bug (masking/accumulation) or
tolerance artifact of the MXU's fp32 emulation?

Method: compute the same case three ways on CPU (true-fp32 matmuls,
no MXU) — f64 dense oracle, f32 dense, interpret-mode pallas kernel —
and compare each f32 path's error against the f64 truth.  If the kernel's
error distribution matches dense-f32's, the kernel math is sound and the
on-device miss was MXU precision (adjudication: tolerance); a kernel bug
would show as outliers far beyond dense-f32's rounding envelope.

Run: PYTHONPATH= JAX_PLATFORMS=cpu python tools/adjudicate_flash_fp32.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from paddle_tpu.ops import pallas_attention  # noqa: E402
from paddle_tpu.ops.attention import dot_product_attention  # noqa: E402


def main() -> int:
    B, T, H, D, causal = 2, 512, 4, 64, True
    rng = np.random.default_rng(102)  # the failing case's seed
    q64 = rng.normal(size=(B, T, H, D))
    k64 = rng.normal(size=(B, T, H, D))
    v64 = rng.normal(size=(B, T, H, D))

    with jax.default_matmul_precision("highest"):
        want64 = np.asarray(dot_product_attention(
            jnp.asarray(q64), jnp.asarray(k64), jnp.asarray(v64),
            causal=causal))

    q = jnp.asarray(q64, jnp.float32)
    k = jnp.asarray(k64, jnp.float32)
    v = jnp.asarray(v64, jnp.float32)
    with jax.default_matmul_precision("highest"):
        dense32 = np.asarray(dot_product_attention(q, k, v, causal=causal),
                             np.float64)
    flash32 = np.asarray(pallas_attention.flash_attention(q, k, v,
                                                          causal=causal),
                         np.float64)

    def stats(name, got):
        err = np.abs(got - want64)
        rel = err / np.maximum(np.abs(want64), 1e-30)
        bad = np.sum((err > 2e-3) & (rel > 2e-3))
        out = {"path": name, "max_abs_err": float(err.max()),
               "max_rel_err": float(rel.max()),
               "p99.9_abs_err": float(np.quantile(err, 0.999)),
               "n_beyond_2e-3": int(bad)}
        print(json.dumps(out), flush=True)
        return err.max()

    e_dense = stats("dense_f32_vs_f64", dense32)
    e_flash = stats("flash_interpret_f32_vs_f64", flash32)
    # also: flash-vs-dense in f32 (what the on-device parity actually bars)
    d = np.abs(flash32 - dense32)
    print(json.dumps({"path": "flash_vs_dense_f32",
                      "max_abs_diff": float(d.max())}), flush=True)

    # kernel math is sound iff its f64-truth error is within a small factor
    # of dense-f32's own rounding (both are f32 pipelines of ~T=512 sums)
    verdict = "tolerance" if e_flash < 10 * max(e_dense, 1e-7) else "bug"
    print(json.dumps({"verdict": verdict,
                      "dense_f32_err": float(e_dense),
                      "flash_f32_err": float(e_flash)}), flush=True)
    return 0 if verdict == "tolerance" else 1


if __name__ == "__main__":
    sys.exit(main())
