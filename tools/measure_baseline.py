"""Measure the reference-framework baseline for BASELINE.json.

Why a torch-CPU proxy: the reference (PaddlePaddle v0.9.0, C++/CUDA) hard-
requires Python 2.7 + SWIG + period libraries at build time
(ref: CMakeLists.txt:14-18 `find_package(PythonLibs 2.7 REQUIRED)`), none of
which exist in this image and none of which can be installed (zero egress).
No GPU is present either, so the "Paddle-GPU" target cannot be measured
directly.  What CAN be measured on this host is the same training math —
layer-for-layer reimplementations of the two north-star configs
(ref: demo/image_classification/vgg_16_cifar.py — small_vgg;
demo/seqToseq/seqToseq_net.py:70-120 — bi-GRU + attention GRU decoder) in
torch CPU, whose MKL/oneDNN kernels are a generous stand-in for the
reference's CPU path (SSE/AVX hand kernels + CBLAS, README.md:30-47).

Writes the measured numbers + full provenance into BASELINE.json
`published`.  Usage: python tools/measure_baseline.py [--steps N]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import time

import torch
import torch.nn as nn
import torch.nn.functional as F

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_name() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.startswith("model name"):
                    return ln.split(":", 1)[1].strip()
    except OSError:
        pass
    return platform.processor() or "unknown"


class SmallVGG(nn.Module):
    """small_vgg of the reference demos (ref: trainer_config_helpers/
    networks.py:418 — conv groups [64x2,128x2,256x3,512x3] with BN, 8x8 pool,
    dropout, fc 512 + BN, softmax 10)."""

    def __init__(self, num_classes: int = 10, in_ch: int = 3):
        super().__init__()
        chans = [(in_ch, 64), (64, 64), (64, 128), (128, 128), (128, 256),
                 (256, 256), (256, 256), (256, 512), (512, 512), (512, 512)]
        pool_after = {1, 3, 6, 9}
        layers: list[nn.Module] = []
        for i, (ci, co) in enumerate(chans):
            layers += [nn.Conv2d(ci, co, 3, padding=1),
                       nn.BatchNorm2d(co), nn.ReLU()]
            if i in pool_after:
                # ceil_mode matches the framework's caffe_mode=False pooling
                # geometry on non-divisible sizes (MNIST 28x28)
                layers.append(nn.MaxPool2d(2, 2, ceil_mode=True))
        # img_pool 8x8/8: global over whatever spatial size remains
        # (2x2 from CIFAR 32x32, 2x2 from MNIST 28x28 under ceil pooling)
        layers.append(nn.AdaptiveMaxPool2d(1))
        self.features = nn.Sequential(*layers)
        self.drop = nn.Dropout(0.5)
        self.fc1 = nn.Linear(512, 512)
        self.bn1 = nn.BatchNorm1d(512)
        self.drop1 = nn.Dropout(0.5)
        self.fc2 = nn.Linear(512, num_classes)

    def forward(self, x):
        h = self.features(x).flatten(1)
        h = self.bn1(self.fc1(self.drop(h))).relu()
        return self.fc2(self.drop1(h))


def _throughput(model, opt, loss_fn, steps: int, batch: int,
                clip_norm: float = 0.0) -> float:
    """Shared measurement scaffold: 1 warmup step, then `steps` timed
    full train steps (loss+backward+optimizer), samples/sec."""
    def one():
        opt.zero_grad()
        loss_fn().backward()
        if clip_norm:
            torch.nn.utils.clip_grad_norm_(model.parameters(), clip_norm)
        opt.step()

    one()                                   # warmup
    t0 = time.perf_counter()
    for _ in range(steps):
        one()
    return steps * batch / (time.perf_counter() - t0)


def bench_vgg(steps: int, batch: int = 128) -> float:
    torch.manual_seed(0)
    model = SmallVGG()
    opt = torch.optim.SGD(model.parameters(), lr=0.1 / 128,
                          momentum=0.9, weight_decay=0.0005 * 128)
    x = torch.randn(batch, 3, 32, 32)
    y = torch.randint(0, 10, (batch,))
    return _throughput(model, opt,
                       lambda: F.cross_entropy(model(x), y), steps, batch)


class AttnSeq2Seq(nn.Module):
    """The reference's gru_encoder_decoder (ref: demo/seqToseq/
    seqToseq_net.py:70-120): embedding 512, bi-GRU encoder 512/dir,
    additive attention, GRU decoder 512, softmax over the target dict."""

    def __init__(self, vocab: int = 30000, dim: int = 512):
        super().__init__()
        self.src_emb = nn.Embedding(vocab, dim)
        self.trg_emb = nn.Embedding(vocab, dim)
        self.enc_f = nn.GRU(dim, dim, batch_first=True)
        self.enc_b = nn.GRU(dim, dim, batch_first=True)
        self.enc_proj = nn.Linear(2 * dim, dim, bias=False)
        self.boot = nn.Linear(dim, dim)
        self.att_dec = nn.Linear(dim, dim, bias=False)
        self.att_v = nn.Linear(dim, 1, bias=False)
        self.dec_in = nn.Linear(2 * dim + dim, 3 * dim, bias=False)
        self.cell = nn.GRUCell(3 * dim, dim)
        self.out = nn.Linear(dim, vocab)

    def forward(self, src, trg_in):
        es = self.src_emb(src)
        hf, _ = self.enc_f(es)
        hb, _ = self.enc_b(es.flip(1))
        hb = hb.flip(1)
        enc = torch.cat([hf, hb], -1)            # [B,T,2D]
        proj = self.enc_proj(enc)                # [B,T,D]
        state = torch.tanh(self.boot(hb[:, 0]))  # [B,D]
        et = self.trg_emb(trg_in)
        logits = []
        for t in range(trg_in.shape[1]):
            scores = self.att_v(torch.tanh(proj + self.att_dec(state)[:, None]))
            alpha = scores.softmax(1)            # [B,T,1]
            ctx = (alpha * enc).sum(1)           # [B,2D]
            inp = self.dec_in(torch.cat([ctx, et[:, t]], -1))
            state = self.cell(inp, state)
            logits.append(self.out(state))
        return torch.stack(logits, 1)


def bench_seq2seq(steps: int, batch: int = 64, srclen: int = 30,
                  trglen: int = 30, vocab: int = 30000) -> float:
    torch.manual_seed(0)
    model = AttnSeq2Seq(vocab=vocab)
    opt = torch.optim.Adam(model.parameters(), lr=5e-4, weight_decay=1e-4)
    src = torch.randint(0, vocab, (batch, srclen))
    trg_in = torch.randint(0, vocab, (batch, trglen))
    trg_out = torch.randint(0, vocab, (batch, trglen))
    return _throughput(
        model, opt,
        lambda: F.cross_entropy(model(src, trg_in).flatten(0, 1),
                                trg_out.flatten()),
        steps, batch)


def bench_mnist(steps: int, batch: int = 128) -> float:
    """MNIST small_vgg (ref: demo/mnist/vgg_16_mnist.py — same net as the
    CIFAR config, 1x28x28 input)."""
    torch.manual_seed(0)
    model = SmallVGG(in_ch=1)
    opt = torch.optim.SGD(model.parameters(), lr=0.1 / 128,
                          momentum=0.9, weight_decay=0.0005 * 128)
    x = torch.randn(batch, 1, 28, 28)
    y = torch.randint(0, 10, (batch,))
    return _throughput(model, opt,
                       lambda: F.cross_entropy(model(x), y), steps, batch)


class StackedLSTM(nn.Module):
    """The sentiment demo's stacked_lstm_net (ref: demo/sentiment/
    sentiment_net.py stacked_lstm_net:77 — emb 128, alternating-direction
    fc+lstm pairs at hid 512, max-pool over time of the last pair, fc 2)."""

    def __init__(self, vocab: int, emb: int = 128, hid: int = 512,
                 stacked: int = 3):
        super().__init__()
        self.emb = nn.Embedding(vocab, emb)
        self.fc = nn.ModuleList()
        self.lstm = nn.ModuleList()
        self.reverse = []
        in_dim = emb
        for i in range(1, stacked + 1):
            self.fc.append(nn.Linear(in_dim, hid))
            self.lstm.append(nn.LSTM(hid, hid, batch_first=True))
            self.reverse.append(i % 2 == 0)
            in_dim = 2 * hid
        self.out = nn.Linear(2 * hid, 2)

    def forward(self, w):
        h = self.emb(w)
        fc_o = lstm_o = None
        for fc, lstm, rev in zip(self.fc, self.lstm, self.reverse):
            fc_o = fc(h)
            x = fc_o.flip(1) if rev else fc_o
            lstm_o, _ = lstm(x)
            if rev:
                lstm_o = lstm_o.flip(1)
            lstm_o = lstm_o.relu()
            h = torch.cat([fc_o, lstm_o], -1)
        pooled = torch.cat([fc_o.max(1).values, lstm_o.max(1).values], -1)
        return self.out(pooled)


def bench_sentiment(steps: int, batch: int = 128, seqlen: int = 100,
                    vocab: int = 30000) -> float:
    torch.manual_seed(0)
    model = StackedLSTM(vocab)
    opt = torch.optim.Adam(model.parameters(), lr=2e-3, weight_decay=8e-4)
    w = torch.randint(0, vocab, (batch, seqlen))
    y = torch.randint(0, 2, (batch,))
    # the reference config clips grads at 25 — the compared framework pays
    # for that per step, so the baseline must too
    return _throughput(model, opt, lambda: F.cross_entropy(model(w), y),
                       steps, batch, clip_norm=25.0)


class Recommender(nn.Module):
    """The MovieLens demo (ref: demo/recommendation/trainer_config.py —
    per-feature embedding/fc 256 fusion for movie and user, title text
    conv-pool context 5, cosine similarity regression)."""

    def __init__(self, movie: int = 3952, user: int = 6040,
                 title_vocab: int = 5100, genre: int = 18, emb: int = 256):
        super().__init__()
        def id_feat(n):
            return nn.ModuleDict({"emb": nn.Embedding(n, emb),
                                  "fc": nn.Linear(emb, emb)})
        self.movie_id = id_feat(movie)
        self.title_emb = nn.Embedding(title_vocab, emb)
        self.title_conv = nn.Conv1d(emb, emb, 5, padding=2)
        self.genre_fc1 = nn.Linear(genre, emb)
        self.genre_fc2 = nn.Linear(emb, emb)
        self.movie_fusion = nn.Linear(3 * emb, emb)
        self.user_id = id_feat(user)
        self.gender = id_feat(2)
        self.age = id_feat(7)
        self.occupation = id_feat(21)
        self.user_fusion = nn.Linear(4 * emb, emb)

    @staticmethod
    def _id(f, ids):
        return f["fc"](f["emb"](ids))

    def forward(self, movie_id, title, genres, user_id, gender, age, occ):
        t = self.title_emb(title).transpose(1, 2)          # [B, E, T]
        title_f = self.title_conv(t).max(-1).values        # [B, E]
        m = self.movie_fusion(torch.cat(
            [self._id(self.movie_id, movie_id), title_f,
             self.genre_fc2(self.genre_fc1(genres))], -1))
        u = self.user_fusion(torch.cat(
            [self._id(self.user_id, user_id), self._id(self.gender, gender),
             self._id(self.age, age), self._id(self.occupation, occ)], -1))
        return F.cosine_similarity(m, u, dim=-1)


def bench_recommendation(steps: int, batch: int = 1600,
                         title_len: int = 15) -> float:
    torch.manual_seed(0)
    model = Recommender()
    opt = torch.optim.RMSprop(model.parameters(), lr=1e-3)
    feed = (torch.randint(0, 3952, (batch,)),
            torch.randint(0, 5100, (batch, title_len)),
            torch.rand(batch, 18),
            torch.randint(0, 6040, (batch,)),
            torch.randint(0, 2, (batch,)),
            torch.randint(0, 7, (batch,)),
            torch.randint(0, 21, (batch,)))
    rating = torch.rand(batch)
    return _throughput(model, opt,
                       lambda: F.mse_loss(model(*feed), rating),
                       steps, batch)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(ROOT, "BASELINE.json"))
    args = ap.parse_args()

    hw = {"cpu": _cpu_name(), "cores": os.cpu_count(),
          "torch": torch.__version__, "threads": torch.get_num_threads()}
    print(f"host: {hw}")

    vgg = bench_vgg(args.steps)
    print(f"vgg16_cifar10 (torch-CPU, batch 128): {vgg:.2f} samples/sec")
    s2s = bench_seq2seq(args.steps)
    print(f"wmt14_seq2seq (torch-CPU, batch 64, T=30, vocab 30k): "
          f"{s2s:.2f} samples/sec")
    mnist = bench_mnist(args.steps)
    print(f"mnist_vgg (torch-CPU, batch 128): {mnist:.2f} samples/sec")
    sent = bench_sentiment(args.steps)
    print(f"imdb_sentiment_lstm (torch-CPU, batch 128, T=100, vocab 30k): "
          f"{sent:.2f} samples/sec")
    rec = bench_recommendation(args.steps)
    print(f"movielens_recsys (torch-CPU, batch 1600): {rec:.2f} samples/sec")

    caveat = ("torch-CPU reimplementation of the reference model "
              "(see tools/measure_baseline.py docstring: the v0.9.0 "
              "C++ build requires Python 2.7 — unbuildable here; no "
              "GPU present for the Paddle-GPU target)")
    with open(args.out) as f:
        base = json.load(f)
    base["published"] = {
        "vgg16_cifar10": {
            "samples_per_sec": round(vgg, 2),
            "config": "small_vgg CIFAR-10, batch 128, SGD momentum 0.9 + L2",
            "how": caveat,
            "hardware": hw,
        },
        "wmt14_seq2seq": {
            "samples_per_sec": round(s2s, 2),
            "config": "bi-GRU 512 encoder + attention GRU 512 decoder, "
                      "vocab 30000, batch 64, src/trg len 30, Adam",
            "how": "torch-CPU reimplementation (same caveats)",
            "hardware": hw,
        },
        "mnist_vgg": {
            "samples_per_sec": round(mnist, 2),
            "config": "small_vgg MNIST 1x28x28, batch 128, SGD momentum",
            "how": "torch-CPU reimplementation (same caveats)",
            "hardware": hw,
        },
        "imdb_sentiment_lstm": {
            "samples_per_sec": round(sent, 2),
            "config": "stacked_lstm_net: emb 128, 3 alternating fc+lstm "
                      "pairs hid 512, vocab 30000, batch 128, len 100, Adam",
            "how": "torch-CPU reimplementation (same caveats)",
            "hardware": hw,
        },
        "movielens_recsys": {
            "samples_per_sec": round(rec, 2),
            "config": "embedding/fc 256 fusion, title conv-pool ctx 5, "
                      "cos-sim regression, MovieLens-1M dims, batch 1600, "
                      "RMSProp",
            "how": "torch-CPU reimplementation (same caveats)",
            "hardware": hw,
        },
    }
    with open(args.out, "w") as f:
        json.dump(base, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
