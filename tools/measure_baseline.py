"""Measure the reference-framework baseline for BASELINE.json.

Why a torch-CPU proxy: the reference (PaddlePaddle v0.9.0, C++/CUDA) hard-
requires Python 2.7 + SWIG + period libraries at build time
(ref: CMakeLists.txt:14-18 `find_package(PythonLibs 2.7 REQUIRED)`), none of
which exist in this image and none of which can be installed (zero egress).
No GPU is present either, so the "Paddle-GPU" target cannot be measured
directly.  What CAN be measured on this host is the same training math —
layer-for-layer reimplementations of the two north-star configs
(ref: demo/image_classification/vgg_16_cifar.py — small_vgg;
demo/seqToseq/seqToseq_net.py:70-120 — bi-GRU + attention GRU decoder) in
torch CPU, whose MKL/oneDNN kernels are a generous stand-in for the
reference's CPU path (SSE/AVX hand kernels + CBLAS, README.md:30-47).

Writes the measured numbers + full provenance into BASELINE.json
`published`.  Usage: python tools/measure_baseline.py [--steps N]
"""

from __future__ import annotations

import argparse
import json
import os
import platform
import time

import torch
import torch.nn as nn
import torch.nn.functional as F

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_name() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for ln in f:
                if ln.startswith("model name"):
                    return ln.split(":", 1)[1].strip()
    except OSError:
        pass
    return platform.processor() or "unknown"


class SmallVGG(nn.Module):
    """small_vgg of the reference demos (ref: trainer_config_helpers/
    networks.py:418 — conv groups [64x2,128x2,256x3,512x3] with BN, 8x8 pool,
    dropout, fc 512 + BN, softmax 10)."""

    def __init__(self, num_classes: int = 10):
        super().__init__()
        chans = [(3, 64), (64, 64), (64, 128), (128, 128), (128, 256),
                 (256, 256), (256, 256), (256, 512), (512, 512), (512, 512)]
        pool_after = {1, 3, 6, 9}
        layers: list[nn.Module] = []
        for i, (ci, co) in enumerate(chans):
            layers += [nn.Conv2d(ci, co, 3, padding=1),
                       nn.BatchNorm2d(co), nn.ReLU()]
            if i in pool_after:
                layers.append(nn.MaxPool2d(2, 2))
        layers.append(nn.MaxPool2d(2, 2))  # img_pool 8x8/8 on the 2x2 map -> 1x1
        self.features = nn.Sequential(*layers)
        self.drop = nn.Dropout(0.5)
        self.fc1 = nn.Linear(512, 512)
        self.bn1 = nn.BatchNorm1d(512)
        self.drop1 = nn.Dropout(0.5)
        self.fc2 = nn.Linear(512, num_classes)

    def forward(self, x):
        h = self.features(x).flatten(1)
        h = self.bn1(self.fc1(self.drop(h))).relu()
        return self.fc2(self.drop1(h))


def bench_vgg(steps: int, batch: int = 128) -> float:
    torch.manual_seed(0)
    model = SmallVGG()
    opt = torch.optim.SGD(model.parameters(), lr=0.1 / 128,
                          momentum=0.9, weight_decay=0.0005 * 128)
    x = torch.randn(batch, 3, 32, 32)
    y = torch.randint(0, 10, (batch,))
    # warmup
    loss = F.cross_entropy(model(x), y)
    loss.backward()
    opt.step()
    t0 = time.perf_counter()
    for _ in range(steps):
        opt.zero_grad()
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
    dt = time.perf_counter() - t0
    return steps * batch / dt


class AttnSeq2Seq(nn.Module):
    """The reference's gru_encoder_decoder (ref: demo/seqToseq/
    seqToseq_net.py:70-120): embedding 512, bi-GRU encoder 512/dir,
    additive attention, GRU decoder 512, softmax over the target dict."""

    def __init__(self, vocab: int = 30000, dim: int = 512):
        super().__init__()
        self.src_emb = nn.Embedding(vocab, dim)
        self.trg_emb = nn.Embedding(vocab, dim)
        self.enc_f = nn.GRU(dim, dim, batch_first=True)
        self.enc_b = nn.GRU(dim, dim, batch_first=True)
        self.enc_proj = nn.Linear(2 * dim, dim, bias=False)
        self.boot = nn.Linear(dim, dim)
        self.att_dec = nn.Linear(dim, dim, bias=False)
        self.att_v = nn.Linear(dim, 1, bias=False)
        self.dec_in = nn.Linear(2 * dim + dim, 3 * dim, bias=False)
        self.cell = nn.GRUCell(3 * dim, dim)
        self.out = nn.Linear(dim, vocab)

    def forward(self, src, trg_in):
        es = self.src_emb(src)
        hf, _ = self.enc_f(es)
        hb, _ = self.enc_b(es.flip(1))
        hb = hb.flip(1)
        enc = torch.cat([hf, hb], -1)            # [B,T,2D]
        proj = self.enc_proj(enc)                # [B,T,D]
        state = torch.tanh(self.boot(hb[:, 0]))  # [B,D]
        et = self.trg_emb(trg_in)
        logits = []
        for t in range(trg_in.shape[1]):
            scores = self.att_v(torch.tanh(proj + self.att_dec(state)[:, None]))
            alpha = scores.softmax(1)            # [B,T,1]
            ctx = (alpha * enc).sum(1)           # [B,2D]
            inp = self.dec_in(torch.cat([ctx, et[:, t]], -1))
            state = self.cell(inp, state)
            logits.append(self.out(state))
        return torch.stack(logits, 1)


def bench_seq2seq(steps: int, batch: int = 64, srclen: int = 30,
                  trglen: int = 30, vocab: int = 30000) -> float:
    torch.manual_seed(0)
    model = AttnSeq2Seq(vocab=vocab)
    opt = torch.optim.Adam(model.parameters(), lr=5e-4, weight_decay=1e-4)
    src = torch.randint(0, vocab, (batch, srclen))
    trg_in = torch.randint(0, vocab, (batch, trglen))
    trg_out = torch.randint(0, vocab, (batch, trglen))
    loss = F.cross_entropy(model(src, trg_in).flatten(0, 1), trg_out.flatten())
    loss.backward()
    opt.step()
    t0 = time.perf_counter()
    for _ in range(steps):
        opt.zero_grad()
        loss = F.cross_entropy(model(src, trg_in).flatten(0, 1),
                               trg_out.flatten())
        loss.backward()
        opt.step()
    dt = time.perf_counter() - t0
    return steps * batch / dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(ROOT, "BASELINE.json"))
    args = ap.parse_args()

    hw = {"cpu": _cpu_name(), "cores": os.cpu_count(),
          "torch": torch.__version__, "threads": torch.get_num_threads()}
    print(f"host: {hw}")

    vgg = bench_vgg(args.steps)
    print(f"vgg16_cifar10 (torch-CPU, batch 128): {vgg:.2f} samples/sec")
    s2s = bench_seq2seq(args.steps)
    print(f"wmt14_seq2seq (torch-CPU, batch 64, T=30, vocab 30k): "
          f"{s2s:.2f} samples/sec")

    with open(args.out) as f:
        base = json.load(f)
    base["published"] = {
        "vgg16_cifar10": {
            "samples_per_sec": round(vgg, 2),
            "config": "small_vgg CIFAR-10, batch 128, SGD momentum 0.9 + L2",
            "how": "torch-CPU reimplementation of the reference model "
                   "(see tools/measure_baseline.py docstring: the v0.9.0 "
                   "C++ build requires Python 2.7 — unbuildable here; no "
                   "GPU present for the Paddle-GPU target)",
            "hardware": hw,
        },
        "wmt14_seq2seq": {
            "samples_per_sec": round(s2s, 2),
            "config": "bi-GRU 512 encoder + attention GRU 512 decoder, "
                      "vocab 30000, batch 64, src/trg len 30, Adam",
            "how": "torch-CPU reimplementation (same caveats)",
            "hardware": hw,
        },
    }
    with open(args.out, "w") as f:
        json.dump(base, f, indent=2)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
