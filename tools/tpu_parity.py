"""On-device pallas parity checks — run on a REAL TPU.

The interpret-mode oracles (tests/test_pallas_attention.py,
tests/test_additive_attention.py) validate the math; this validates
mosaic compilation/tiling on hardware for the shapes ADVICE flagged
(bf16 sublane minimums, short/unaligned sequences).  Prints one JSON
line per case; exit 0 iff all pass.

Round-5 duty-cycle hardening (VERDICT r4 item 1 — the r4 run was killed
at its 900s budget after 2 of 10 cases):

- every result is APPENDED to a ledger (MEASURE/parity_ledger.jsonl) with
  a timestamp and a hash of the kernel+oracle sources; `--skip-passed`
  then skips cases already green under the CURRENT code, so each healthy
  tunnel window continues where the last one died instead of redoing it;
- the dense/scan reference side runs on the HOST CPU backend — only the
  pallas kernel under test compiles through the tunnel's remote-compile
  helper (~75s/program observed r4), halving the per-case cost;
- `--list` prints the case names + code hash without touching the
  backend, so the queue orchestrator can see what is pending cheaply.
"""

from __future__ import annotations

import contextlib
import datetime
import hashlib
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax
import jax.numpy as jnp
import numpy as np

_LEDGER = os.path.join(REPO, "MEASURE", "parity_ledger.jsonl")
_HASHED_SOURCES = [
    "paddle_tpu/ops/pallas_attention.py",
    "paddle_tpu/ops/pallas_additive.py",
    "paddle_tpu/ops/pallas_rnn.py",
    "paddle_tpu/ops/attention.py",
    "paddle_tpu/ops/rnn.py",
    "tools/tpu_parity.py",
]


def _code_hash() -> str:
    h = hashlib.sha256()
    for rel in _HASHED_SOURCES:
        try:
            with open(os.path.join(REPO, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(rel.encode())
    return h.hexdigest()[:12]


_ORACLE_DEV = None   # host-CPU device for references; set in main()


def _oracle(fn, *args):
    """Run the reference side on the host CPU backend (true-fp32 matmuls,
    no tunnel remote-compile) when available; HIGHEST precision keeps the
    on-device fallback honest too."""
    ctx = jax.default_device(_ORACLE_DEV) if _ORACLE_DEV is not None \
        else contextlib.nullcontext()
    with ctx, jax.default_matmul_precision("highest"):
        out = fn(*args)
        return jax.tree.map(np.asarray, out)


def _oracle_scan(fn, *args):
    """_oracle + forced lax.scan path: lstm_scan/gru_scan self-route to the
    pallas kernels (ops/rnn.py:_use_fused), which would compare the kernel
    against itself — PADDLE_TPU_PALLAS=0 pins the reference to the scan."""
    prev = os.environ.get("PADDLE_TPU_PALLAS")
    os.environ["PADDLE_TPU_PALLAS"] = "0"
    try:
        return _oracle(fn, *args)
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_PALLAS", None)
        else:
            os.environ["PADDLE_TPU_PALLAS"] = prev


def _case(name, fn, ledger_path, extra):
    rec = {"case": name, "hash": _code_hash(), **extra,
           "ts": datetime.datetime.now(datetime.timezone.utc)
           .isoformat(timespec="seconds")}
    try:
        fn()
        rec["ok"] = True
    except Exception as e:
        rec["ok"] = False
        rec["error"] = f"{type(e).__name__}: {str(e)[:200]}"
    print(json.dumps({k: rec[k] for k in ("case", "ok", "error") if k in rec}),
          flush=True)
    try:
        os.makedirs(os.path.dirname(ledger_path), exist_ok=True)
        with open(ledger_path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return rec["ok"]


def _ledger_passed(ledger_path) -> set:
    """Cases green in the ledger under the CURRENT code hash."""
    cur = _code_hash()
    passed = set()
    try:
        with open(ledger_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("hash") == cur:
                    if rec.get("ok"):
                        passed.add(rec.get("case"))
                    else:
                        passed.discard(rec.get("case"))
    except OSError:
        pass
    return passed


def _seed(name: str) -> int:
    """Stable per-case data seed derived from the case NAME (shape+dtype),
    not its list position — inserting/reordering cases must not silently
    change what data an already-validated case reruns on."""
    import zlib
    return zlib.crc32(name.encode()) % 100000


def flash_cases():
    from paddle_tpu.ops import pallas_attention
    from paddle_tpu.ops.attention import dot_product_attention

    cases = []
    # ordered by information value: the Mosaic-risk shapes (short /
    # unaligned) first — remote compiles are slow enough (~5 min/case
    # through the tunnel) that a mid-run tunnel death keeps only a prefix
    #       B, T,    H, D,  dtype,        causal, tol
    shapes = [
        (1, 7, 2, 64, jnp.bfloat16, False, 3e-2),     # T < 16 (bf16 min)
        (2, 300, 4, 80, jnp.float32, True, 2e-3),     # T,D unaligned
        (2, 256, 2, 256, jnp.bfloat16, True, 3e-2),   # head dim > one lane
        #                                               tile (Mosaic-risk:
        #                                               never lowered on hw)
        (2, 512, 4, 64, jnp.float32, True, 2e-3),
        (2, 1024, 8, 64, jnp.bfloat16, True, 3e-2),   # passed on v5e r4
    ]
    for B, T, H, D, dt, causal, tol in shapes:
        name = (f"flash_B{B}_T{T}_H{H}_D{D}_{jnp.dtype(dt).name}"
                f"{'_causal' if causal else ''}")

        def run(name=name, B=B, T=T, H=H, D=D, dt=dt, causal=causal,
                tol=tol):
            # per-case seed from the NAME: a --only-filtered rerun or a
            # reordered matrix must see the same data as the full suite
            # (tolerance-marginal cases otherwise pass in isolation and
            # fail in sequence, or vice versa)
            rng = np.random.default_rng(_seed(name))
            q = jnp.asarray(rng.normal(size=(B, T, H, D)), dt)
            k = jnp.asarray(rng.normal(size=(B, T, H, D)), dt)
            v = jnp.asarray(rng.normal(size=(B, T, H, D)), dt)
            got = jax.jit(lambda q, k, v: pallas_attention.flash_attention(
                q, k, v, causal=causal))(q, k, v)
            # fp32 reference at true-fp32 matmul precision ON THE HOST CPU:
            # the kernel runs its fp32 dots at HIGHEST, so the dense bar
            # must not carry the MXU's default single-bf16-pass rounding
            # (it alone exceeds the 2e-3 tolerance — v5e round-4 parity);
            # CPU also skips the tunnel's ~75s/program remote compile
            want = _oracle(lambda q, k, v: dot_product_attention(
                q, k, v, causal=causal), q, k, v)
            np.testing.assert_allclose(
                np.asarray(got, np.float32), want.astype(np.float32),
                rtol=tol, atol=tol)
            # backward compiles + matches
            g1 = jax.grad(lambda q: jnp.sum(pallas_attention.flash_attention(
                q, k, v, causal=causal).astype(jnp.float32)))(q)
            g2 = _oracle(lambda q: jax.grad(
                lambda q: jnp.sum(dot_product_attention(
                    q, k, v, causal=causal).astype(jnp.float32)))(q), q)
            np.testing.assert_allclose(np.asarray(g1, np.float32),
                                       g2.astype(np.float32),
                                       rtol=tol * 5, atol=tol * 5)
        cases.append((name, run))
    return cases


def additive_cases():
    from paddle_tpu.ops import pallas_additive
    from paddle_tpu.ops.attention import additive_attention_step as ref

    cases = []
    shapes = [
        (64, 30, 512, 512, 512, jnp.bfloat16, 8e-2),  # the seq2seq shape
        (5, 7, 11, 19, 13, jnp.float32, 2e-4),        # everything unaligned
        (3, 5, 8, 16, 16, jnp.bfloat16, 8e-2),        # T < 16 bf16
    ]
    for B, T, Ds, D, Dv, dt, tol in shapes:
        name = f"additive_B{B}_T{T}_D{Ds}x{D}x{Dv}_{jnp.dtype(dt).name}"

        def run(name=name, B=B, T=T, Ds=Ds, D=D, Dv=Dv, dt=dt, tol=tol):
            rng = np.random.default_rng(_seed(name))
            dec = jnp.asarray(rng.normal(size=(B, Ds)), dt)
            w = jnp.asarray(rng.normal(size=(Ds, D)) * 0.2, dt)
            v = jnp.asarray(rng.normal(size=(D,)), dt)
            proj = jnp.asarray(rng.normal(size=(B, T, D)), dt)
            seq = jnp.asarray(rng.normal(size=(B, T, Dv)), dt)
            lens = rng.integers(1, T + 1, B).astype(np.int32)
            mask = jnp.arange(T)[None, :] < jnp.asarray(lens)[:, None]
            got = jax.jit(pallas_additive.additive_attention_step)(
                dec, w, v, proj, seq, mask)
            # oracle in fp32 on the host CPU: the kernel keeps everything
            # fp32 internally, so bf16 cases compare against the fp32 math
            # with a bf16-rounding tolerance (the bf16-throughout jnp path
            # is the NOISIER of the two)
            want = _oracle(lambda *a: ref(*a, mask),
                           *(x.astype(jnp.float32)
                             for x in (dec, w, v, proj, seq)))
            np.testing.assert_allclose(
                np.asarray(got, np.float32), want.astype(np.float32),
                rtol=tol, atol=tol)
        cases.append((name, run))
    return cases


def rnn_cases():
    """Pallas LSTM/GRU vs the lax.scan reference, fwd + grads, on device —
    these kernels have never run on real TPU either (VERDICT r3 item 1).
    Both paths compute fp32 internally; tolerance covers MXU pass-order
    differences between the kernel's per-step matmul and the scan's.

    Recurrent weights are 1/sqrt(D)-scaled (standard init): a fixed 0.2
    std at D=512 puts the backward recurrence in an exploding-gradient
    regime (per-step gain > 1) where fp32 op-ordering differences amplify
    exponentially and NO two fp32 implementations agree — adjudicated r5
    with an f64 oracle: at std 0.2 the fp32 SCAN itself missed the f64
    truth by the same margin as the kernel (7.2 vs 9.1 abs), while at
    1/sqrt(D) kernel-vs-scan agree to 5e-6."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_rnn, rnn

    cases = []
    shapes = [
        (4, 6, 8),        # tiny/unaligned
        (64, 30, 512),    # the sentiment-bench shape
        (5, 7, 24),       # everything unaligned
    ]
    for B, T, D in shapes:
        lstm_name = f"lstm_B{B}_T{T}_D{D}"
        gru_name = f"gru_B{B}_T{T}_D{D}"

        def run_lstm(name=lstm_name, B=B, T=T, D=D):
            rng = np.random.default_rng(_seed(name))
            x4 = jnp.asarray(rng.standard_normal((B, T, 4 * D)) * 0.5,
                             jnp.float32)
            w = jnp.asarray(rng.standard_normal((D, 4 * D)) * D ** -0.5,
                            jnp.float32)
            lens = jnp.asarray(rng.integers(1, T + 1, B), jnp.int32)
            z = jnp.zeros((B, D), jnp.float32)
            peeps = jnp.zeros((3, D), jnp.float32)

            def fused(x4, w):
                hs, hl, cl = pallas_rnn.lstm_fused(
                    x4, lens, w, peeps, z, z, active_type="tanh",
                    gate_active_type="sigmoid", state_active_type="tanh",
                    reverse=False)
                return jnp.sum(hs * hs) + jnp.sum(hl) + jnp.sum(cl * cl)

            def ref(x4, w):
                hs, hl, cl = rnn.lstm_scan(x4, lens, w, None, reverse=False)
                return jnp.sum(hs * hs) + jnp.sum(hl) + jnp.sum(cl * cl)

            lf, gf = jax.value_and_grad(fused, argnums=(0, 1))(x4, w)
            lr, gr = _oracle_scan(jax.value_and_grad(ref, argnums=(0, 1)),
                                  x4, w)
            np.testing.assert_allclose(float(lf), float(lr), rtol=2e-2)
            for a, b in zip(gf, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=5e-2, atol=5e-2)

        def run_gru(name=gru_name, B=B, T=T, D=D):
            rng = np.random.default_rng(_seed(name))
            x3 = jnp.asarray(rng.standard_normal((B, T, 3 * D)) * 0.5,
                             jnp.float32)
            wg = jnp.asarray(rng.standard_normal((D, 2 * D)) * D ** -0.5,
                             jnp.float32)
            wc = jnp.asarray(rng.standard_normal((D, D)) * D ** -0.5,
                             jnp.float32)
            lens = jnp.asarray(rng.integers(1, T + 1, B), jnp.int32)
            z = jnp.zeros((B, D), jnp.float32)

            def fused(x3, wg, wc):
                hs, hl = pallas_rnn.gru_fused(
                    x3, lens, wg, wc, z, active_type="tanh",
                    gate_active_type="sigmoid", reverse=False)
                return jnp.sum(hs * hs) + jnp.sum(hl)

            def ref(x3, wg, wc):
                hs, hl = rnn.gru_scan(x3, lens, wg, wc, None, reverse=False)
                return jnp.sum(hs * hs) + jnp.sum(hl)

            lf, gf = jax.value_and_grad(fused, argnums=(0, 1, 2))(x3, wg, wc)
            lr, gr = _oracle_scan(
                jax.value_and_grad(ref, argnums=(0, 1, 2)), x3, wg, wc)
            np.testing.assert_allclose(float(lf), float(lr), rtol=2e-2)
            for a, b in zip(gf, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=5e-2, atol=5e-2)

        cases.append((lstm_name, run_lstm))
        cases.append((gru_name, run_gru))
    return cases


def _build_selected(only):
    # build only the selected families: the parity / parity_rnn queue split
    # exists so one family's import failure can't take down the other's step
    families = [(("flash",), flash_cases),
                (("additive",), additive_cases),
                (("lstm", "gru"), rnn_cases)]
    selected = []
    for prefixes, build in families:
        if only and not any(o.startswith(p) or p.startswith(o)
                            for o in only for p in prefixes):
            continue
        selected += [(name, fn) for name, fn in build()
                     if not only or any(name.startswith(o) for o in only)]
    names = [n for n, _ in selected]
    assert len(names) == len(set(names)), (
        f"duplicate parity case names {sorted(set(n for n in names if names.count(n) > 1))} "
        f"— names are the ledger identity and the data seed, so every case "
        f"must encode its full distinguishing shape in its name")
    return selected


def main() -> int:
    global _ORACLE_DEV
    only: list[str] = []
    list_only = skip_passed = False
    ledger = _LEDGER
    for a in sys.argv[1:]:
        if a.startswith("--only="):
            only = [p for p in a.split("=", 1)[1].split(",") if p]
        elif a == "--list":
            list_only = True
        elif a == "--skip-passed":
            skip_passed = True
        elif a.startswith("--ledger="):
            ledger = a.split("=", 1)[1]

    selected = _build_selected(only)
    if not selected:   # a typo'd --only must not produce a vacuous green
        print(json.dumps({"all_ok": False,
                          "error": f"--only={only} matched no cases"}))
        return 1
    if list_only:
        # no backend touched: the queue orchestrator calls this to see what
        # is pending before paying a tunnel backend init.  `pending` uses
        # the SAME _ledger_passed replay as --skip-passed, so the skip
        # decision and the actual skipping can never disagree.
        passed = _ledger_passed(ledger)
        print(json.dumps({"hash": _code_hash(),
                          "cases": [n for n, _ in selected],
                          "pending": [n for n, _ in selected
                                      if n not in passed]}))
        return 0

    passed = _ledger_passed(ledger) if skip_passed else set()
    pending = [(n, fn) for n, fn in selected if n not in passed]
    if not pending:
        print(json.dumps({"all_ok": True, "n_cases": 0,
                          "n_skipped_passed": len(selected)}), flush=True)
        return 0

    # widen jax_platforms so the host CPU backend coexists with the tunnel
    # TPU — the reference side of every case then compiles/runs locally
    # (the image latches JAX_PLATFORMS to the tpu plugin; see
    # tests/conftest.py for the same dance)
    try:
        cur = jax.config.jax_platforms
        if cur and "cpu" not in cur.split(","):
            jax.config.update("jax_platforms", cur + ",cpu")
    except Exception:
        pass
    dev = jax.devices()[0]
    try:
        _ORACLE_DEV = jax.devices("cpu")[0]
    except Exception:
        _ORACLE_DEV = None   # references fall back to the device under test
    print(json.dumps({"platform": dev.platform,
                      "device_kind": dev.device_kind,
                      "oracle": "host-cpu" if _ORACLE_DEV is not None
                      else "on-device",
                      "n_skipped_passed": len(selected) - len(pending)}),
          flush=True)

    extra = {"device_kind": dev.device_kind}
    ok = True
    for name, fn in pending:
        ok &= _case(name, fn, ledger, extra)
    print(json.dumps({"all_ok": bool(ok), "n_cases": len(pending)}),
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
