"""One distributed trainer over the parameter-server tier.

Runs the standard Trainer with a RemoteParameterUpdater: the jitted step
computes gradients on-device, the optimizer applies on the pserver fleet
(tools/pserver.py), and with K sync trainers on disjoint stride shards
the result is BIT-IDENTICAL to one process training with grad_accum=K
(docs/distributed_training.md "Exactness contract").

  # shard 0 of 2 trainers against a single-shard pserver:
  python tools/train_dist.py --config demo/mnist/mlp_mnist.py \
      --pserver 127.0.0.1:8571 --rank 0 --trainers 2 --passes 2

Data sharding: each trainer takes every K-th batch of the config's data
stream (`--rank`-strided — the disjoint-shard convention the exactness
oracle assumes).  SIGTERM/SIGINT drains: the current batch finishes, the
trainer announces ps_drain + ps_leave (the barrier re-sizes, the fleet
continues), exit 0.  On completion prints one machine-readable line:

  TRAIN_JSON:{"rank": 0, "passes": 2, "samples": 4096, ...}
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_addrs(spec: str) -> list:
    out = []
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    if not out:
        raise SystemExit("--pserver needs HOST:PORT[,HOST:PORT...] "
                         "(shard-index order, shard 0 first)")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", required=True)
    ap.add_argument("--config-args", default="")
    ap.add_argument("--pserver", required=True,
                    help="HOST:PORT[,HOST:PORT...] — every shard, shard "
                         "0 (the membership coordinator) first")
    ap.add_argument("--rank", type=int, default=None,
                    help="data-shard index = reduction rank (default: "
                         "server-assigned smallest free)")
    ap.add_argument("--trainers", type=int, default=1,
                    help="fleet size K for the stride data shard (this "
                         "trainer takes batches rank, rank+K, ...)")
    ap.add_argument("--passes", type=int, default=1)
    ap.add_argument("--log-period", type=int, default=0)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="pserver RPC timeout (a sync barrier waits at "
                         "most this long for straggler trainers)")
    args = ap.parse_args(argv)

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.optim.remote_updater import RemoteParameterUpdater
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config(args.config, args.config_args)
    updater = RemoteParameterUpdater(
        cfg.model_config, cfg.opt_config, parse_addrs(args.pserver),
        rank=args.rank, timeout=args.timeout_s)
    tr = Trainer(cfg, seed=args.seed, updater=updater)
    rank = updater.rank
    print(f"joined as rank {rank} (tid {updater.client.tid}), "
          f"mode {updater.mode}", file=sys.stderr, flush=True)

    draining = {"flag": False}

    def on_term(_sig, _frm):
        print("SIGTERM: draining after the current batch",
              file=sys.stderr, flush=True)
        draining["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    def shard(batches):
        """rank-strided disjoint shard, halting cleanly on drain."""
        for b in itertools.islice(batches, rank, None,
                                  max(args.trainers, 1)):
            if draining["flag"]:
                return
            yield b

    t0 = time.time()
    samples = passes = 0
    stats: dict = {}
    try:
        for _ in range(args.passes):
            if draining["flag"]:
                break
            stats = tr.train_one_pass(batches=shard(tr.train_batches()),
                                      log_period=args.log_period)
            samples += int(stats.get("samples", 0))
            passes += 1
    finally:
        updater.drain_and_leave()
    dt = time.time() - t0
    print("TRAIN_JSON:" + json.dumps({
        "rank": rank, "passes": passes, "samples": samples,
        "seconds": round(dt, 3),
        "samples_per_sec": round(samples / dt, 3) if dt > 0 else 0.0,
        "cost": stats.get("cost"),
        "drained": draining["flag"]}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
