"""One distributed trainer over the parameter-server tier.

Runs the standard Trainer with a RemoteParameterUpdater: the jitted step
computes gradients on-device, the optimizer applies on the pserver fleet
(tools/pserver.py), and with K sync trainers on disjoint stride shards
the result is BIT-IDENTICAL to one process training with grad_accum=K
(docs/distributed_training.md "Exactness contract").

  # shard 0 of 2 trainers against a single-shard pserver:
  python tools/train_dist.py --config demo/mnist/mlp_mnist.py \
      --pserver 127.0.0.1:8571 --rank 0 --trainers 2 --passes 2

Data sharding: each trainer takes every K-th batch of the config's data
stream (`--rank`-strided — the disjoint-shard convention the exactness
oracle assumes).  SIGTERM/SIGINT drains: the current batch finishes, the
trainer announces ps_drain + ps_leave (the barrier re-sizes, the fleet
continues), exit 0.  On completion prints one machine-readable line
(sync runs include the last pass's per-window attribution sums —
push/barrier_wait/pull ms):

  TRAIN_JSON:{"rank": 0, "passes": 2, "samples": 4096, ...}

Observability (docs/distributed_training.md "Observability"):
`--trace-out spans.jsonl` enables the span tracer for the run and writes
the retained ring on EVERY exit path (clean, drained, or crashed — the
spans up to a failure are exactly what a postmortem wants), led by a
`{"meta": {"process"}}` identity line so `tools/trace_dump.py --merge`
labels this trainer's track in a stitched fleet trace; `--save-dir`
appends one metrics.jsonl row per pass (the remote-updater timing fields
ride next to the throughput gauges).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_addrs(spec: str) -> list:
    out = []
    for part in spec.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        host, _, port = part.rpartition(":")
        out.append((host or "127.0.0.1", int(port)))
    if not out:
        raise SystemExit("--pserver needs HOST:PORT[,HOST:PORT...] "
                         "(shard-index order, shard 0 first)")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", required=True)
    ap.add_argument("--config-args", default="")
    ap.add_argument("--pserver", required=True,
                    help="HOST:PORT[,HOST:PORT...] — every shard, shard "
                         "0 (the membership coordinator) first")
    ap.add_argument("--rank", type=int, default=None,
                    help="data-shard index = reduction rank (default: "
                         "server-assigned smallest free)")
    ap.add_argument("--trainers", type=int, default=1,
                    help="fleet size K for the stride data shard (this "
                         "trainer takes batches rank, rank+K, ...)")
    ap.add_argument("--passes", type=int, default=1)
    ap.add_argument("--log-period", type=int, default=0)
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--timeout-s", type=float, default=300.0,
                    help="pserver RPC timeout (a sync barrier waits at "
                         "most this long for straggler trainers)")
    ap.add_argument("--trace-out", default="",
                    help="enable the span tracer and write this "
                         "trainer's spans as JSONL here on every exit "
                         "path (trace_dump --merge food)")
    ap.add_argument("--save-dir", default="",
                    help="append one metrics.jsonl row per pass here "
                         "(remote-updater timing fields included)")
    args = ap.parse_args(argv)

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.optim.remote_updater import RemoteParameterUpdater
    from paddle_tpu.trainer.trainer import Trainer

    tracer = None
    if args.trace_out:
        from paddle_tpu.obs import get_tracer

        tracer = get_tracer()
        tracer.enabled = True

    cfg = parse_config(args.config, args.config_args)
    updater = RemoteParameterUpdater(
        cfg.model_config, cfg.opt_config, parse_addrs(args.pserver),
        rank=args.rank, timeout=args.timeout_s)
    tr = Trainer(cfg, seed=args.seed, updater=updater)
    rank = updater.rank
    print(f"joined as rank {rank} (tid {updater.client.tid}), "
          f"mode {updater.mode}", file=sys.stderr, flush=True)

    draining = {"flag": False}

    def on_term(_sig, _frm):
        print("SIGTERM: draining after the current batch",
              file=sys.stderr, flush=True)
        draining["flag"] = True

    signal.signal(signal.SIGTERM, on_term)
    signal.signal(signal.SIGINT, on_term)

    def shard(batches):
        """rank-strided disjoint shard, halting cleanly on drain."""
        for b in itertools.islice(batches, rank, None,
                                  max(args.trainers, 1)):
            if draining["flag"]:
                return
            yield b

    def flush_trace():
        # EVERY exit path flushes (serve.py's finally discipline): a
        # SIGTERM-drained or crashed trainer must still leave a
        # stitchable trace file with its identity line
        if tracer is not None:
            from paddle_tpu.obs import flush_trace_file

            flush_trace_file(tracer, args.trace_out, "trainer", rank=rank)

    t0 = time.time()
    samples = passes = 0
    stats: dict = {}
    try:
        for _ in range(args.passes):
            if draining["flag"]:
                break
            stats = tr.train_one_pass(batches=shard(tr.train_batches()),
                                      log_period=args.log_period)
            samples += int(stats.get("samples", 0))
            passes += 1
            if args.save_dir:
                tr.append_metrics(args.save_dir, extra=stats)
    finally:
        try:
            updater.drain_and_leave()
        finally:
            flush_trace()
    dt = time.time() - t0
    timing = {k: stats[k] for k in
              ("push_ms", "barrier_wait_ms", "pull_ms", "apply_ms",
               "compute_ms", "remote_windows", "async_stale_rejects")
              if k in stats}
    print("TRAIN_JSON:" + json.dumps({
        "rank": rank, "passes": passes, "samples": samples,
        "seconds": round(dt, 3),
        "samples_per_sec": round(samples / dt, 3) if dt > 0 else 0.0,
        "cost": stats.get("cost"),
        "drained": draining["flag"], **timing}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
