"""Convert a span-tracer JSONL dump to Chrome trace_event JSON.

The span tracer (paddle_tpu/obs/trace.py) archives spans as JSON-lines —
one span per line: {"seq", "name", "track", "ts", "dur", "attrs"?,
"instant"?}.  This tool turns that into the Chrome trace_event format
that Perfetto (https://ui.perfetto.dev) and chrome://tracing load
directly: every track becomes a named thread lane, complete spans render
as bars, instants (preempt/done/cancelled/deadline) as markers.

  # server side: record a serving run's request lifecycles
  python tools/serve.py ... --trace-out spans.jsonl     # drain writes it
  # convert + eyeball
  python tools/trace_dump.py spans.jsonl -o trace.json
  python tools/trace_dump.py spans.jsonl --summary      # per-name table

Exit codes: 0 ok, 2 on unreadable/empty input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.obs.trace import spans_to_chrome  # noqa: E402


def load_spans(path: str) -> list[dict]:
    """Read a JSONL span file; skips blank lines, raises on garbage."""
    spans = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}") from e
            if not isinstance(rec, dict) or "name" not in rec \
                    or "ts" not in rec:
                raise ValueError(f"{path}:{i}: not a span record "
                                 f"(need name/ts fields): {rec!r}")
            if not rec.get("instant") and "dur" not in rec:
                raise ValueError(f"{path}:{i}: complete span without a "
                                 f"dur field: {rec!r}")
            spans.append(rec)
    return spans


def summarize(spans: list[dict]) -> str:
    """Per-name span table: count, total duration, max — the quick look
    before opening Perfetto."""
    agg: dict[str, list] = {}
    for s in spans:
        a = agg.setdefault(s["name"], [0, 0.0, 0.0])
        a[0] += 1
        a[1] += float(s.get("dur", 0.0))
        a[2] = max(a[2], float(s.get("dur", 0.0)))
    lines = [f"{'span':<16} {'count':>7} {'total_ms':>10} {'max_ms':>9}"]
    for name in sorted(agg, key=lambda n: -agg[n][1]):
        c, tot, mx = agg[name]
        lines.append(f"{name:<16} {c:>7} {tot * 1e3:>10.2f} {mx * 1e3:>9.2f}")
    tracks = sorted({s.get("track", "main") for s in spans})
    lines.append(f"{len(spans)} spans on {len(tracks)} tracks")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", help="span JSONL (tools/serve.py --trace-out, "
                                  "or Tracer.export_jsonl)")
    ap.add_argument("-o", "--out", default="",
                    help="write Chrome trace_event JSON here "
                         "(default: <input>.trace.json)")
    ap.add_argument("--summary", action="store_true",
                    help="print a per-span-name table instead of writing")
    args = ap.parse_args(argv)

    try:
        spans = load_spans(args.jsonl)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not spans:
        print(f"error: {args.jsonl} holds no spans (tracing never "
              f"enabled, or the ring was cleared)", file=sys.stderr)
        return 2

    if args.summary:
        print(summarize(spans))
        return 0

    out = args.out or args.jsonl + ".trace.json"
    with open(out, "w") as f:
        json.dump(spans_to_chrome(spans), f)
    print(f"wrote {out}: {len(spans)} spans — load in "
          f"https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
