"""Convert span-tracer JSONL dumps to Chrome trace_event JSON.

The span tracer (paddle_tpu/obs/trace.py) archives spans as JSON-lines —
one span per line: {"seq", "name", "track", "ts", "dur", "attrs"?,
"instant"?}.  This tool turns that into the Chrome trace_event format
that Perfetto (https://ui.perfetto.dev) and chrome://tracing load
directly: every track becomes a named thread lane, complete spans render
as bars, instants (preempt/done/cancelled/deadline) as markers.

  # server side: record a serving run's request lifecycles
  python tools/serve.py ... --trace-out spans.jsonl     # drain writes it
  # convert + eyeball
  python tools/trace_dump.py spans.jsonl -o trace.json
  python tools/trace_dump.py spans.jsonl --summary      # per-name table,
                                  # per-lane counts, compile-lane breakdown

Distributed traces (docs/observability.md "Distributed tracing"): a
fleet request crosses router and replica processes — and a training
window crosses trainer and pserver-shard processes — each with its own
span ring and its own perf_counter epoch.  `--merge` stitches several
span FILES into ONE Chrome trace with a named process track group per
file (a file's first line may be a `{"meta": {"process": ..., an
"offset_s"}}` identity record — serve.py/fleet_router.py/pserver.py/
train_dist.py --trace-out all write one); `--pull HOST:PORT`
(repeatable) collects spans LIVE over the `trace` RPC instead —
replica, router, or pserver shard — measuring each process's clock
offset by ping-RTT midpointing so the tracks align:

  python tools/trace_dump.py --pull 127.0.0.1:8440 \\
      --pull 127.0.0.1:8431 --pull 127.0.0.1:8432 -o fleet.trace.json

  # training fleet: pull both pserver shards live, merge the trainers'
  # --trace-out files — one Perfetto trace, role-named tracks
  python tools/trace_dump.py --pull 127.0.0.1:8571 \\
      --pull 127.0.0.1:8572 --merge t0.jsonl t1.jsonl -o dist.trace.json

Exit codes: 0 ok, 2 on unreadable/empty input or an unreachable --pull.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.obs.trace import merge_chrome, spans_to_chrome  # noqa: E402


def load_trace_file(path: str) -> tuple[dict, list[dict]]:
    """Read a JSONL span file as (meta, spans).  `meta` is the optional
    leading identity record ({"process": ..., "offset_s": ...}; {} when
    the file has none — plain Tracer.export_jsonl output)."""
    meta: dict = {}
    spans = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                raise ValueError(f"{path}:{i}: not JSON: {e}") from e
            if isinstance(rec, dict) and "meta" in rec and \
                    "name" not in rec:
                meta = rec["meta"] if isinstance(rec["meta"], dict) else {}
                continue
            if not isinstance(rec, dict) or "name" not in rec \
                    or "ts" not in rec:
                raise ValueError(f"{path}:{i}: not a span record "
                                 f"(need name/ts fields): {rec!r}")
            if not rec.get("instant") and "dur" not in rec:
                raise ValueError(f"{path}:{i}: complete span without a "
                                 f"dur field: {rec!r}")
            spans.append(rec)
    return meta, spans


def load_spans(path: str) -> list[dict]:
    """Read a JSONL span file; skips blank lines (and a meta identity
    line), raises on garbage."""
    return load_trace_file(path)[1]


def pull_source(addr: str, timeout: float = 60.0) -> dict:
    """One live `trace` RPC pull -> a merge_chrome() source: spans +
    process identity + the ping-RTT-measured clock offset mapping that
    process's perf_counter timebase onto this tool's."""
    from paddle_tpu.serving.client import ServingClient

    host, _, port = addr.rpartition(":")
    with ServingClient(host or "127.0.0.1", int(port),
                       timeout=timeout) as c:
        msg = c.trace()
    return {"spans": msg.get("spans") or [],
            "process": msg.get("process"),
            "offset_s": msg.get("offset_s", 0.0),
            "recorded": msg.get("recorded"),
            "dropped": msg.get("dropped")}


def summarize(spans: list[dict]) -> str:
    """Per-name span table, per-lane counts, and a compile-lane
    breakdown (signatures × compile time) — a recompile storm is visible
    from the trace file alone, no Perfetto needed."""
    agg: dict[str, list] = {}
    for s in spans:
        a = agg.setdefault(s["name"], [0, 0.0, 0.0])
        a[0] += 1
        a[1] += float(s.get("dur", 0.0))
        a[2] = max(a[2], float(s.get("dur", 0.0)))
    lines = [f"{'span':<16} {'count':>7} {'total_ms':>10} {'max_ms':>9}"]
    for name in sorted(agg, key=lambda n: -agg[n][1]):
        c, tot, mx = agg[name]
        lines.append(f"{name:<16} {c:>7} {tot * 1e3:>10.2f} {mx * 1e3:>9.2f}")

    # per-lane counts: request lanes collapse to one `req:*` row so a
    # thousand-request trace still summarizes in a screenful
    lanes: dict[str, int] = {}
    for s in spans:
        track = s.get("track", "main")
        if track.startswith("req:"):
            track = "req:*"
        lanes[track] = lanes.get(track, 0) + 1
    lines.append("")
    lines.append(f"{'lane':<16} {'spans':>7}")
    for track in sorted(lanes, key=lambda t: -lanes[t]):
        lines.append(f"{track:<16} {lanes[track]:>7}")

    lines.append(f"{len(spans)} spans on {len(lanes)} lanes")
    comp = compile_breakdown(spans)
    if comp:
        lines.append("")
        lines.append(comp)
    return "\n".join(lines)


def compile_breakdown(spans: list[dict]) -> str:
    """The compile lane, by site: compiles × distinct signatures × wall
    time, plus any recompile-storm markers.  Empty string when the trace
    holds no compile-lane spans (tracing predates the compile watcher,
    or nothing compiled while the ring retained)."""
    sites: dict[str, list] = {}      # site -> [compiles, sigs, seconds]
    storms: dict[str, int] = {}
    for s in spans:
        if s.get("track") != "compile":
            continue
        attrs = s.get("attrs") or {}
        if s.get("instant"):
            if s["name"] == "recompile_storm":
                site = str(attrs.get("site", "?"))
                storms[site] = storms.get(site, 0) + 1
            continue
        a = sites.setdefault(s["name"], [0, set(), 0.0])
        a[0] += 1
        a[1].add(attrs.get("sig", a[0]))   # no sig recorded: count as new
        a[2] += float(s.get("dur", 0.0))
    if not sites and not storms:
        return ""
    lines = [f"compile lane ({sum(a[0] for a in sites.values())} compiles):",
             f"  {'site':<24} {'compiles':>8} {'sigs':>5} {'total_ms':>10}"]
    for site in sorted(sites, key=lambda n: -sites[n][2]):
        c, sigs, tot = sites[site]
        storm = (f"  STORMS={storms.pop(site)}" if site in storms else "")
        lines.append(f"  {site:<24} {c:>8} {len(sigs):>5} "
                     f"{tot * 1e3:>10.2f}{storm}")
    for site, n in sorted(storms.items()):  # storm without retained spans
        lines.append(f"  {site:<24} {'?':>8} {'?':>5} {'?':>10}  STORMS={n}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", nargs="*",
                    help="span JSONL file(s) (tools/serve.py --trace-out, "
                         "or Tracer.export_jsonl); several need --merge")
    ap.add_argument("-o", "--out", default="",
                    help="write Chrome trace_event JSON here "
                         "(default: <input>.trace.json)")
    ap.add_argument("--summary", action="store_true",
                    help="print per-span-name and per-lane tables (plus a "
                         "compile-lane breakdown when present) instead of "
                         "writing")
    ap.add_argument("--merge", action="store_true",
                    help="stitch several span files (and any --pull "
                         "sources) into ONE Chrome trace with a process "
                         "track group per source, applying each file's "
                         "meta offset_s")
    ap.add_argument("--pull", action="append", default=[],
                    metavar="HOST:PORT",
                    help="collect spans live over the `trace` RPC from a "
                         "replica server or fleet router (repeatable; "
                         "clock offset measured per pull via ping RTT); "
                         "implies --merge")
    args = ap.parse_args(argv)

    if len(args.jsonl) > 1 and not (args.merge or args.pull):
        print("error: several input files need --merge (one Chrome trace "
              "with a process group per file)", file=sys.stderr)
        return 2
    if not args.jsonl and not args.pull:
        ap.error("need a span JSONL file or --pull HOST:PORT")

    sources = []
    try:
        for path in args.jsonl:
            meta, spans = load_trace_file(path)
            sources.append({"spans": spans,
                            "process": meta.get("process"),
                            "offset_s": float(meta.get("offset_s", 0.0)),
                            "label": os.path.basename(path)
                            if not meta.get("process") else None})
        for addr in args.pull:
            sources.append(pull_source(addr))
    except (OSError, ValueError, ConnectionError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    all_spans = [s for src in sources for s in src["spans"]]
    if not all_spans:
        print(f"error: {', '.join(args.jsonl + args.pull)} holds no spans "
              f"(tracing never enabled, or the ring was cleared)",
              file=sys.stderr)
        return 2

    if args.summary:
        print(summarize(all_spans))
        return 0

    if args.merge or args.pull or len(sources) > 1:
        out = args.out or ((args.jsonl[0] if args.jsonl
                            else "fleet") + ".trace.json")
        with open(out, "w") as f:
            json.dump(merge_chrome(sources), f)
        names = [(src.get("process") or {}).get("role") or
                 src.get("label") or "?" for src in sources]
        print(f"wrote {out}: {len(all_spans)} spans across "
              f"{len(sources)} processes ({', '.join(names)}) — load in "
              f"https://ui.perfetto.dev or chrome://tracing")
        return 0

    out = args.out or args.jsonl[0] + ".trace.json"
    with open(out, "w") as f:
        json.dump(spans_to_chrome(all_spans), f)
    print(f"wrote {out}: {len(all_spans)} spans — load in "
          f"https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
