"""Settle the sparse-table GSPMD question with banked HLO evidence.

`parallel/sparse.py:20-25` documents the failure mode the explicit
shard_map path exists for: GSPMD servicing a vocab-sharded embedding
lookup by ALL-GATHERING the table to every device (the opposite of the
reference's touched-rows-only economics, ref: math/SparseRowMatrix.h:211).
Whether XLA actually does that for the movielens step had never been
recorded (VERDICT r3 item 8, r4 item 6).

This tool compiles the full recommendation train step over an 8-device
mesh, inventories every collective in the optimized HLO, specifically
greps for all-gathers whose operand/result shape matches a table's row
space, and prints a JSON verdict.  Run under the virtual CPU mesh
(JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8):
the sharding propagation + SPMD partitioning passes that make this
decision run before backend-specific lowering, so the partitioned
program's collective structure is the same evidence the single real
tunnel chip cannot provide (a 1-device mesh partitions nothing).

Usage: [env above] python tools/hlo_sparse_check.py [--save PATH.hlo]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_SHAPE_RE = re.compile(r"[a-z0-9]+\[([0-9,]+)\]")


def gather_spans_table(line: str, tables) -> bool:
    """True iff an all-gather HLO line MATERIALIZES a sharded table: some
    operand/result tensor shape equals the table's full shape, gathered
    along the table's sharded axis.

    Substring-matching a row count anywhere in the line false-positives on
    unrelated collectives that merely carry the number — a logits/feature-
    dimension activation gather, a replica_groups entry, a channel id
    (ADVICE r5).  So: parse the `dtype[d0,d1,...]` shape tokens BEFORE the
    attribute tail (replica_groups=... onward contains bracketed iota
    lists that are not shapes), and flag only when a token's FULL dim
    tuple equals a table shape — the signature of GSPMD reassembling the
    whole table — and the `dimensions={d}` gather axis is that table's
    sharded axis (a coincidentally table-shaped tensor gathered along an
    unsharded dim stays clean).

    GSPMD's grouped lowering may gather into an UNMERGED form — e.g.
    [rows/8, 8, D] (shard axis inserted next to the sharded dim, bitcast
    to [rows, D] afterwards) — so each token is also tried with the gather
    dim merged into either neighbor.

    `tables`: iterable of (shape tuple, sharded-axis index or None)."""
    m = re.search(r"dimensions=\{(\d+)", line)
    gdim = int(m.group(1)) if m else None
    head = line.split("replica_groups=")[0].split("metadata=")[0]
    toks = [tuple(int(x) for x in sm.group(1).split(",") if x)
            for sm in _SHAPE_RE.finditer(head)]

    def candidates(dims):
        """(shape, effective gathered-axis) readings of one token."""
        out = [(dims, gdim)]
        if gdim is not None and gdim < len(dims):
            if gdim > 0:               # merge into the left neighbor
                out.append((dims[:gdim - 1]
                            + (dims[gdim - 1] * dims[gdim],)
                            + dims[gdim + 1:], gdim - 1))
            if gdim < len(dims) - 1:   # merge into the right neighbor
                out.append((dims[:gdim]
                            + (dims[gdim] * dims[gdim + 1],)
                            + dims[gdim + 2:], gdim))
        return out

    for shape, axis in tables:
        shape = tuple(shape)
        for dims in toks:
            for cand, cdim in candidates(dims):
                if cand != shape:
                    continue
                if cdim is not None and axis is not None and cdim != axis:
                    continue
                return True
    return False


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", default=os.path.join(REPO, "MEASURE",
                                                   "recsys_step.hlo"))
    ap.add_argument("--data", type=int, default=8)
    ap.add_argument("--model", type=int, default=1)
    args = ap.parse_args()

    import jax

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.trainer.trainer import Trainer

    n = args.data * args.model
    if len(jax.devices()) < n:
        print(json.dumps({"error": f"need {n} devices, have "
                          f"{len(jax.devices())} — run with JAX_PLATFORMS="
                          f"cpu XLA_FLAGS=--xla_force_host_platform_device_"
                          f"count={n}"}))
        return 1

    mesh = make_mesh(data=args.data, model=args.model)
    # the BASELINE bench dims (MovieLens-1M): title_vocab 5100 % 8 != 0 so
    # that one table legitimately stays replicated — the check covers the
    # two big sharded ones (movie 3952, user 6040)
    cfg = parse_config("demo/recommendation/trainer_config.py",
                       "batch_size=64,movie_dim=3952,user_dim=6040,"
                       "title_vocab=5100")
    tr = Trainer(cfg, seed=1, mesh=mesh)

    # which params came out vocab-sharded, their shapes + sharded axis
    sharded = {}
    tables = []
    for k, v in tr.params.items():
        spec = list(getattr(v.sharding, "spec", []) or [])
        if any(s is not None for s in spec):
            sharded[k] = {"shape": list(v.shape), "spec": [str(s) for s in spec]}
            axis = next((i for i, s in enumerate(spec) if s is not None), None)
            tables.append((tuple(v.shape), axis))
    if not sharded:
        print(json.dumps({"error": "no sharded tables under the mesh"}))
        return 1

    batch = next(tr.train_batches())
    hlo = tr._train_step.lower(tr.params, tr.opt_state, tr.net_state, batch,
                               jax.random.PRNGKey(0)).compile().as_text()
    try:
        os.makedirs(os.path.dirname(args.save), exist_ok=True)
        with open(args.save, "w") as f:
            f.write(hlo)
    except OSError:
        pass

    # inventory every collective op in the optimized module, including the
    # async forms (all-gather-start/-done — the standard TPU lowering);
    # -done lines are skipped so async pairs count once
    colls: dict[str, int] = {}
    gathers = []          # full lines — the shape/dimension parse needs
    for ln in hlo.splitlines():   # the attribute tail; truncate on output
        m = re.search(r"(all-gather|all-reduce|reduce-scatter|"
                      r"all-to-all|collective-permute)(-start|-done)?\(", ln)
        if not m or m.group(2) == "-done":
            continue
        op = m.group(1)
        colls[op] = colls.get(op, 0) + 1
        if op == "all-gather":
            gathers.append(ln.strip())

    # does any all-gather materialize a table — full table shape gathered
    # along its sharded axis?  (shape-anchored — see gather_spans_table)
    table_gathers = [ln[:200] for ln in gathers
                     if gather_spans_table(ln, tables)]

    verdict = {
        "mesh": {"data": args.data, "model": args.model},
        "sharded_tables": sharded,
        "collectives": colls,
        "n_all_gathers": len(gathers),
        "table_all_gathers": table_gathers,
        "verdict": ("GSPMD all-gathers a vocab-sharded table — switch the "
                    "config to parallel/sparse.py:sharded_embedding_lookup"
                    if table_gathers else
                    "no table all-gather: GSPMD services the lookup with "
                    "local gather + reduction (touched-rows economics hold)"),
        "hlo_saved": args.save,
    }
    print(json.dumps(verdict), flush=True)
    return 0 if not table_gathers else 2


if __name__ == "__main__":
    sys.exit(main())
