"""Settle the sparse-table GSPMD question with banked HLO evidence.

`parallel/sparse.py:20-25` documents the failure mode the explicit
shard_map path exists for: GSPMD servicing a vocab-sharded embedding
lookup by ALL-GATHERING the table to every device (the opposite of the
reference's touched-rows-only economics, ref: math/SparseRowMatrix.h:211).
Whether XLA actually does that for the movielens step had never been
recorded (VERDICT r3 item 8, r4 item 6).

This tool compiles the full recommendation train step over an 8-device
mesh, inventories every collective in the optimized HLO, specifically
greps for all-gathers whose operand/result shape matches a table's row
space, and prints a JSON verdict.  Run under the virtual CPU mesh
(JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8):
the sharding propagation + SPMD partitioning passes that make this
decision run before backend-specific lowering, so the partitioned
program's collective structure is the same evidence the single real
tunnel chip cannot provide (a 1-device mesh partitions nothing).

Usage: [env above] python tools/hlo_sparse_check.py [--save PATH.hlo]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", default=os.path.join(REPO, "MEASURE",
                                                   "recsys_step.hlo"))
    ap.add_argument("--data", type=int, default=8)
    ap.add_argument("--model", type=int, default=1)
    args = ap.parse_args()

    import jax

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.trainer.trainer import Trainer

    n = args.data * args.model
    if len(jax.devices()) < n:
        print(json.dumps({"error": f"need {n} devices, have "
                          f"{len(jax.devices())} — run with JAX_PLATFORMS="
                          f"cpu XLA_FLAGS=--xla_force_host_platform_device_"
                          f"count={n}"}))
        return 1

    mesh = make_mesh(data=args.data, model=args.model)
    # the BASELINE bench dims (MovieLens-1M): title_vocab 5100 % 8 != 0 so
    # that one table legitimately stays replicated — the check covers the
    # two big sharded ones (movie 3952, user 6040)
    cfg = parse_config("demo/recommendation/trainer_config.py",
                       "batch_size=64,movie_dim=3952,user_dim=6040,"
                       "title_vocab=5100")
    tr = Trainer(cfg, seed=1, mesh=mesh)

    # which params came out vocab-sharded, and their row counts
    sharded = {}
    for k, v in tr.params.items():
        spec = list(getattr(v.sharding, "spec", []) or [])
        if any(s is not None for s in spec):
            sharded[k] = {"shape": list(v.shape), "spec": [str(s) for s in spec]}
    if not sharded:
        print(json.dumps({"error": "no sharded tables under the mesh"}))
        return 1

    batch = next(tr.train_batches())
    hlo = tr._train_step.lower(tr.params, tr.opt_state, tr.net_state, batch,
                               jax.random.PRNGKey(0)).compile().as_text()
    try:
        os.makedirs(os.path.dirname(args.save), exist_ok=True)
        with open(args.save, "w") as f:
            f.write(hlo)
    except OSError:
        pass

    # inventory every collective op in the optimized module, including the
    # async forms (all-gather-start/-done — the standard TPU lowering);
    # -done lines are skipped so async pairs count once
    colls: dict[str, int] = {}
    gathers = []
    for ln in hlo.splitlines():
        m = re.search(r"(all-gather|all-reduce|reduce-scatter|"
                      r"all-to-all|collective-permute)(-start|-done)?\(", ln)
        if not m or m.group(2) == "-done":
            continue
        op = m.group(1)
        colls[op] = colls.get(op, 0) + 1
        if op == "all-gather":
            gathers.append(ln.strip()[:200])

    # does any all-gather's result shape span a table's full row space?
    table_rows = {v["shape"][0] for v in sharded.values()}
    table_gathers = []
    for ln in gathers:
        for rows in table_rows:
            if re.search(rf"\b{rows},", ln) or re.search(rf"\[{rows},", ln):
                table_gathers.append(ln)
                break

    verdict = {
        "mesh": {"data": args.data, "model": args.model},
        "sharded_tables": sharded,
        "collectives": colls,
        "n_all_gathers": len(gathers),
        "table_all_gathers": table_gathers,
        "verdict": ("GSPMD all-gathers a vocab-sharded table — switch the "
                    "config to parallel/sparse.py:sharded_embedding_lookup"
                    if table_gathers else
                    "no table all-gather: GSPMD services the lookup with "
                    "local gather + reduction (touched-rows economics hold)"),
        "hlo_saved": args.save,
    }
    print(json.dumps(verdict), flush=True)
    return 0 if not table_gathers else 2


if __name__ == "__main__":
    sys.exit(main())
