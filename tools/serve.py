"""Serve a transformer-LM config over TCP (serving/server.py front end).

Server (foreground; SIGTERM or SIGINT drains — finish in-flight requests,
refuse new ones, exit 0):

  python tools/serve.py --config demo/model_zoo/transformer_lm.py \
      --config-args "vocab=256,dim=64,layers=2,heads=4,batch_size=8" \
      --slots 8 --page-size 16 --max-context 256 --port 8431
      [--checkpoint runs/lm/  # newest committed pass dir, .tmp skipped]

On bind it prints one machine-readable line (the scripting contract —
tests/test_server.py's SIGTERM smoke parses it):

  SERVE_JSON:{"host": "127.0.0.1", "port": 8431, "pid": 12345}

Client one-shot (no jax needed beyond the shared package import):

  python tools/serve.py --client 127.0.0.1:8431 --prompt 2,7,9 \
      --max-new 16 --stream
  python tools/serve.py --client 127.0.0.1:8431 --stats
  python tools/serve.py --client 127.0.0.1:8431 --metrics   # Prometheus text

Request-lifecycle tracing: `--trace-out spans.jsonl` enables the span
tracer for the server's lifetime and writes the retained spans (bounded
ring) as JSONL on EVERY exit path — clean drain, engine-pump crash
(exit 1), or an unexpected error — never an empty file; `python
tools/trace_dump.py spans.jsonl -o trace.json` converts to
Perfetto-loadable Chrome trace_event JSON.

Postmortem bundles: `--postmortem-dir DIR` arms the flight recorder's
dump paths — a pump crash, a watchdog wedge (`--wedge-threshold-s`), or
a client `--dump` each freeze an atomic `DIR/postmortem-<ts>-<pid>/`
bundle (events, spans, engine snapshot, metrics, config).  Inspect with
`python tools/postmortem.py DIR/postmortem-.../`.  See
docs/observability.md.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def render_history(reply: dict) -> str:
    """Compact per-series text for --history --watch: one line per
    series with the newest value (the full JSON stays available without
    --watch; tools/obs_top.py is the real dashboard)."""
    lines = [f"samples={reply.get('samples_taken')} "
             f"resolution={reply.get('resolution_s')}s "
             f"series={len(reply.get('series') or {})}"]
    for key, ser in sorted((reply.get("series") or {}).items()):
        pts = ser.get("points") or []
        last = pts[-1][1] if pts else "?"
        lines.append(f"  {ser.get('kind', '?'):7s} {key}  "
                     f"last={last} n={len(pts)}")
    return "\n".join(lines)


def run_client(args) -> int:
    from paddle_tpu.serving.client import ServingClient

    host, _, port = args.client.rpartition(":")
    with ServingClient(host or "127.0.0.1", int(port)) as c:
        if args.metrics:
            print(c.metrics(aggregate=args.aggregate), end="")
            return 0
        if args.history:
            while True:
                reply = c.history(last_s=args.last_s or None,
                                  aggregate=args.aggregate)
                if not args.watch:
                    print(json.dumps(reply, indent=2))
                    return 0
                # \x1b[H\x1b[J = home + clear: a cheap live view
                print("\x1b[H\x1b[J" + render_history(reply), flush=True)
                time.sleep(args.watch)
        if args.dump:
            print(json.dumps(c.dump(), indent=2))
            return 0
        if args.stats:
            print(json.dumps(c.stats(stale_ok=args.stale_ok), indent=2))
            return 0
        prompt = [int(t) for t in str(args.prompt).split(",") if t != ""]
        if not prompt:
            print("need --prompt id,id,... (or --stats)", file=sys.stderr)
            return 2

        def on_token(rid, tok, idx):
            if args.stream:
                print(f"token[{idx}] = {tok}", flush=True)

        toks, reason = c.generate(
            prompt, max_new=args.max_new, temperature=args.temperature,
            top_k=args.top_k, top_p=args.top_p, eos_id=args.eos_id,
            seed=args.seed, timeout_s=args.timeout_s, on_token=on_token)
        print(json.dumps({"tokens": toks, "reason": reason}))
    return 0


def build_engine(args):
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config(args.config, args.config_args)
    tr = Trainer(cfg, seed=args.seed or 0)
    if args.checkpoint:
        from paddle_tpu.trainer.checkpoint import latest_checkpoint

        path = latest_checkpoint(args.checkpoint) or args.checkpoint
        print(f"loading checkpoint {path}", file=sys.stderr)
        tr.load(path)
    if args.prefill_chunk < 0:
        chunk = None                 # chunking off: legacy prefill
    else:
        chunk = args.prefill_chunk or -1   # 0 = engine default
    mesh = None
    if args.mesh:
        # tensor-parallel serving: '--mesh model=N' shards attention heads
        # and the KV page pools over the first N devices (docs/serving.md
        # "Sharded decode"); only the model axis is meaningful here
        from paddle_tpu.parallel.mesh import model_mesh

        name, _, num = args.mesh.replace(":", "=").partition("=")
        if name.strip() != "model" or not num.strip().isdigit():
            raise SystemExit(
                f"--mesh expects 'model=N' (serving shards over the model "
                f"axis only), got {args.mesh!r}")
        mesh = model_mesh(int(num))
        if mesh is not None:
            print(f"sharded decode: model={int(num)} "
                  f"(attention heads + KV pools partitioned)",
                  file=sys.stderr)
    drafter = None
    if args.spec_k > 0:
        if args.drafter == "model":
            # self-speculation: the target drafts for itself over a
            # truncated window, batched across all slots in one
            # dispatch — zero extra weights to load or train
            from paddle_tpu.serving.drafter import ModelDrafter
            drafter = ModelDrafter.from_target(tr.executor, tr.params)
        dyn = " (dynamic per-slot k)" if args.spec_dynamic else ""
        print(f"speculative decoding: up to {args.spec_k} drafts/slot/"
              f"step ({args.drafter} drafter{dyn}; emitted tokens "
              f"unchanged)", file=sys.stderr)
    if args.decode_steps > 1:
        print(f"multi-step decode: {args.decode_steps} scanned decode "
              f"bodies per dispatch when pure-decode (emitted tokens "
              f"unchanged; tokens stream in bursts)", file=sys.stderr)
    if args.spill_budget > 0:
        print(f"KV spill tier: cold cached pages spill to host RAM "
              f"(budget {args.spill_budget} bytes) and restore on "
              f"prefix hits", file=sys.stderr)
    return ServingEngine(tr.executor, tr.params, num_slots=args.slots,
                         page_size=args.page_size,
                         max_context=args.max_context,
                         num_pages=args.num_pages,
                         prefill_chunk=chunk,
                         max_step_tokens=args.max_step_tokens or None,
                         spec_k=args.spec_k,
                         drafter=drafter,
                         spec_dynamic=args.spec_dynamic,
                         decode_steps=args.decode_steps,
                         decode_mode=args.decode_mode,
                         spill_bytes_budget=args.spill_budget,
                         mesh=mesh)


async def amain(args) -> int:
    from paddle_tpu.serving.server import ServingServer

    tracer = None
    if args.trace_out:
        from paddle_tpu.obs import get_tracer

        tracer = get_tracer()
        tracer.enabled = True

    def flush_trace(srv=None):
        # EVERY exit path flushes — a crashed or wedged server must never
        # leave an empty trace file behind (the spans up to the failure
        # are exactly the ones a postmortem wants).  The leading meta
        # line stamps process identity so trace_dump --merge can label
        # this file's track group in a stitched fleet trace.
        if tracer is not None:
            from paddle_tpu.obs import flush_trace_file

            flush_trace_file(tracer, args.trace_out, "replica", args.host,
                             srv.port if srv is not None else args.port)

    engine = build_engine(args)
    srv = ServingServer(engine, host=args.host, port=args.port,
                        max_queue=args.max_queue,
                        postmortem_dir=args.postmortem_dir or None,
                        wedge_threshold_s=args.wedge_threshold_s,
                        role=args.role)
    try:
        host, port = await srv.start()
        print("SERVE_JSON:" + json.dumps(
            {"host": host, "port": port, "pid": os.getpid()}), flush=True)

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        # a dead engine pump must take the PROCESS down (nonzero, trace
        # flushed, bundle already frozen by the server) instead of leaving
        # a zombie listener that answers every generate with an error
        stop_w = asyncio.ensure_future(stop.wait())
        crash_w = asyncio.ensure_future(srv.wait_crashed())
        done, pending = await asyncio.wait(
            [stop_w, crash_w], return_when=asyncio.FIRST_COMPLETED)
        for fut in pending:
            fut.cancel()
        if crash_w in done:
            print("engine pump died; shutting down", file=sys.stderr,
                  flush=True)
            await srv.stop()
            return 1
        print("draining: refusing new requests, finishing in-flight...",
              file=sys.stderr, flush=True)
        await srv.drain()
        print("drained; bye", file=sys.stderr, flush=True)
        return 0
    finally:
        flush_trace(srv)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="demo/model_zoo/transformer_lm.py")
    ap.add_argument("--config-args",
                    default="vocab=256,dim=64,layers=2,heads=4,batch_size=8")
    ap.add_argument("--checkpoint", default="",
                    help="save_dir (newest committed pass used) or pass dir")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (see the SERVE_JSON line)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--max-context", type=int, default=256)
    ap.add_argument("--num-pages", type=int, default=None,
                    help="overcommit the page pool (default: worst case)")
    ap.add_argument("--spill-budget", type=int, default=0,
                    help="host-RAM bytes for the KV spill tier (0 = off): "
                         "cold cached pages spill instead of evicting and "
                         "restore on prefix hits (docs/serving.md)")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunked-prefill chunk size in tokens "
                         "(0 = engine default 4*page_size, negative = "
                         "disable chunking: legacy whole-prompt prefill)")
    ap.add_argument("--max-step-tokens", type=int, default=0,
                    help="per-step token budget for mixed prefill/decode "
                         "steps (0 = prefill_chunk + slots)")
    ap.add_argument("--mesh", default="",
                    help="tensor-parallel serving mesh, e.g. 'model=2': "
                         "shard attention heads + KV pools over the first "
                         "N devices — one replica serves a model bigger "
                         "than a chip (docs/serving.md 'Sharded decode')")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative decoding: up to K drafted tokens "
                         "per decoding slot per step, verified exactly "
                         "in one ragged dispatch (0 = off; emitted "
                         "tokens are identical either way — "
                         "docs/serving.md 'Speculative decoding')")
    ap.add_argument("--drafter", choices=["ngram", "model"],
                    default="ngram",
                    help="with --spec-k: the draft proposer — 'ngram' "
                         "(host prompt lookup) or 'model' "
                         "(self-speculation: the target drafts for "
                         "itself over a truncated window, one batched "
                         "dispatch for all slots)")
    ap.add_argument("--spec-dynamic", action="store_true",
                    help="with --spec-k: per-slot dynamic draft depth — "
                         "an accept-rate EWMA picks k in 0..K per slot "
                         "per flush window; low-accept slots degrade to "
                         "plain decode (emitted tokens unchanged)")
    ap.add_argument("--decode-steps", type=int, default=1,
                    help="multi-step decode: run K decode bodies per "
                         "dispatch in ONE jitted lax.scan whenever every "
                         "live slot is pure-decode (1 = off; emitted "
                         "tokens are identical either way, streaming "
                         "arrives in <=K bursts — docs/serving.md "
                         "'Multi-step decode')")
    ap.add_argument("--decode-mode", choices=["auto", "static"],
                    default="auto",
                    help="step dispatch policy: 'auto' composes "
                         "speculation and multi-step per flush window "
                         "(draft-free pure-decode windows ride the "
                         "scan); 'static' keeps the legacy exclusivity "
                         "(spec disables the scan)")
    ap.add_argument("--role", choices=["prefill", "decode", "both"],
                    default="both",
                    help="disaggregated prefill/decode placement role, "
                         "advertised to the fleet router via hello: "
                         "'prefill' replicas run long prompts and "
                         "kv_push the committed pages to 'decode' "
                         "replicas, which own the token streams; 'both' "
                         "(default) serves everything colocated "
                         "(docs/serving.md 'Disaggregated "
                         "prefill/decode')")
    ap.add_argument("--max-queue", type=int, default=32,
                    help="admission bound beyond the slots; one more "
                         "request gets an overload response")
    ap.add_argument("--postmortem-dir", default="",
                    help="arm the flight recorder: pump crash / watchdog "
                         "wedge / a client --dump each freeze an atomic "
                         "postmortem bundle here (tools/postmortem.py "
                         "pretty-prints one)")
    ap.add_argument("--wedge-threshold-s", type=float, default=30.0,
                    help="pump beat age past which the watchdog declares "
                         "a wedge and dumps a bundle")
    ap.add_argument("--seed", type=int, default=0)
    # client mode
    ap.add_argument("--client", default="",
                    help="HOST:PORT — run as a one-shot client instead")
    ap.add_argument("--prompt", default="", help="comma-separated token ids")
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=0.0)
    ap.add_argument("--eos-id", type=int, default=-1)
    ap.add_argument("--timeout-s", type=float, default=None)
    ap.add_argument("--stream", action="store_true",
                    help="print token frames as they arrive")
    ap.add_argument("--stats", action="store_true",
                    help="with --client: print the stats RPC and exit")
    ap.add_argument("--stale-ok", action="store_true",
                    help="with --stats: loop-thread fast path that never "
                         "waits on the engine pump (the watchdog poll — "
                         "works against a wedged engine)")
    ap.add_argument("--metrics", action="store_true",
                    help="with --client: print the Prometheus-style "
                         "metrics frame and exit")
    ap.add_argument("--aggregate", action="store_true",
                    help="with --client --metrics against a fleet "
                         "router: the fleet-wide view — router fleet_* "
                         "rows + every replica's families under a "
                         "replica=\"rN\" label")
    ap.add_argument("--dump", action="store_true",
                    help="with --client: ask the server to freeze a "
                         "postmortem bundle and print its path (works "
                         "against a wedged engine)")
    ap.add_argument("--history", action="store_true",
                    help="with --client: print the metric time-series "
                         "ring (the `history` RPC — loop thread, "
                         "answers against a wedged engine); against a "
                         "router --aggregate merges every replica's "
                         "series under replica=\"rN\" labels")
    ap.add_argument("--last-s", type=float, default=0.0,
                    help="with --history: only the trailing window, in "
                         "seconds (0 = full retention)")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="with --history: re-poll every N seconds and "
                         "render a compact live view (0 = print JSON "
                         "once); tools/obs_top.py is the full dashboard")
    # server-side tracing
    ap.add_argument("--trace-out", default="",
                    help="enable request-lifecycle tracing; write spans "
                         "as JSONL here on drain (tools/trace_dump.py "
                         "converts to Perfetto-loadable Chrome JSON)")
    args = ap.parse_args(argv)

    if args.client:
        return run_client(args)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
