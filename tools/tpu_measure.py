"""One-shot TPU measurement session — run the moment the tunnel is up.

The axon TPU tunnel dies unpredictably (it killed the round-2 bench
record), so every pending on-hardware measurement is queued here in
priority order, each in its OWN subprocess under a hard timeout with its
output persisted immediately — a mid-session tunnel death keeps
everything already measured.  Priorities (VERDICT round 2):

  1. backend health probe
  2. flash + additive on-device parity (tools/tpu_parity.py
     --only=flash,additive) — VERDICT priority 1, the only unproven
     kernels; per-case output persists if the window dies mid-run
  3. quick bench (vgg + lm + seq2seq-last) -> PERF_LOG.jsonl snapshot —
     the north-star record, early because healthy windows are short
  4. additive-attention kernel vs jnp (tools/bench_additive.py) —
     evidence for the decoder-step routing default
  5. pallas LSTM/GRU kernels vs lax.scan (tools/bench_rnn.py) — the
     RNN routing evidence
  6. transformer-LM train MFU + decode tokens/s per context length
     (tools/bench_lm.py)
  7. attention micro-bench across lengths, bf16 (tools/bench_attention.py)
     — evidence for the layer auto-selection crossover
  8. pallas LSTM/GRU on-device parity (--only=lstm,gru)
  9. attention micro-bench fp32 pass
  10. full 6-config bench -> PERF_LOG.jsonl snapshot (seq2seq last inside)

Results land under MEASURE/<step>.out (+ PERF_LOG.jsonl via bench.py).
The parent process never imports jax (a wedged tunnel blocks any backend
init forever).

Usage: python tools/tpu_measure.py [--skip=parity,attn_bench_f32]
(step names: parity, parity_rnn, attn_bench, attn_bench_f32,
additive_bench, rnn_bench, bench_lm, bench_quick, bench_full)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "MEASURE")


def run_step(name: str, argv: list[str], timeout_s: float,
             env_extra: dict | None = None) -> bool:
    os.makedirs(OUT, exist_ok=True)
    path = os.path.join(OUT, f"{name}.out")
    env = dict(os.environ)
    env.update(env_extra or {})
    t0 = time.time()
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env,
                            cwd=REPO, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            out, _ = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out = ""
        rc = -9
    dt = time.time() - t0
    with open(path, "w") as f:
        f.write(f"# rc={rc} seconds={dt:.1f} argv={argv}\n")
        f.write(out or "")
    print(json.dumps({"step": name, "rc": rc, "seconds": round(dt, 1),
                      "out": path}), flush=True)
    return rc == 0


def health(timeout_s: float = 90) -> bool:
    code = ("import jax; d = jax.devices()[0]; "
            "print('HEALTH', d.platform, d.device_kind)")
    try:
        p = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return False
    ok = "HEALTH tpu" in (p.stdout or "")
    print(json.dumps({"step": "health", "ok": ok,
                      "detail": (p.stdout or p.stderr or "")[-200:].strip()}),
          flush=True)
    return ok


def main() -> int:
    skip: set[str] = set()
    args = list(sys.argv[1:])
    while args:
        a = args.pop(0)
        if a.startswith("--skip="):
            skip |= set(a.split("=", 1)[1].split(","))
        elif a == "--skip" and args:
            skip |= set(args.pop(0).split(","))
    if not health():
        print(json.dumps({"fatal": "TPU not healthy; nothing run"}))
        return 1

    py = sys.executable
    # Ordered by marginal value per healthy-tunnel minute.  Healthy windows
    # have been SHORT (r4: ~22 min), and the tunnel wedged DURING the
    # seq2seq bench in both r2 and r4 — so: flash parity first (VERDICT
    # priority 1, the only unproven kernels; partial output persists if
    # the window dies mid-case), then the full bench record with seq2seq
    # ordered last inside bench.py, then the sweeps.
    steps = [
        ("parity", [py, "tools/tpu_parity.py", "--only=flash,additive"],
         2700, {}),
        ("bench_quick", [py, "bench.py"], 1500,
         {"BENCH_EXTENDED": "0", "BENCH_TIME_BUDGET_S": "1200"}),
        ("additive_bench", [py, "tools/bench_additive.py"], 900, {}),
        ("rnn_bench", [py, "tools/bench_rnn.py"], 1200, {}),
        ("bench_lm", [py, "tools/bench_lm.py"], 2400, {}),
        ("attn_bench",
         [py, "tools/bench_attention.py", "--lens", "512,1024,2048,4096,16384",
          "--iters", "10"], 1500, {}),
        ("parity_rnn", [py, "tools/tpu_parity.py", "--only=lstm,gru"],
         1800, {}),
        ("attn_bench_f32",
         [py, "tools/bench_attention.py", "--lens", "512,1024,4096",
          "--iters", "10", "--dtype", "float32"], 900, {}),
        ("bench_full", [py, "bench.py"], 2400,
         {"BENCH_TIME_BUDGET_S": "2100"}),
    ]
    for name, argv, to, env in steps:
        if name in skip:
            continue
        ok = run_step(name, argv, to, env)
        if not ok and not health(90):
            # a failed step + dead tunnel: stop burning the remaining
            # steps' timeouts against a wedged backend (everything
            # measured so far is already persisted under MEASURE/)
            print(json.dumps({"fatal": f"tunnel died during {name}"}))
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
