"""One-shot TPU measurement session — run the moment the tunnel is up.

The axon TPU tunnel dies unpredictably and healthy windows are SHORT
(~20 min observed r4), so every pending on-hardware measurement is queued
here in priority order, each in its OWN subprocess under a hard timeout
with its output persisted immediately — a mid-session tunnel death keeps
everything already measured.  Round-5 refit (VERDICT r4 item 1):

- ONE CONFIG PER STEP: each BASELINE config is its own `bench.py`
  invocation (BENCH_ONLY=...) that banks its own PERF_LOG.jsonl record;
  bench.py's assembler stitches them into a complete record at driver
  time, so a window only ever needs to afford the next step, not the
  whole matrix.
- SKIP WHAT'S BANKED: parity cases already green in the ledger under the
  current code hash are skipped (tools/tpu_parity.py --skip-passed);
  bench steps whose metric has a PERF_LOG record fresher than
  --fresh-hours (default 6) and micro-bench steps whose MEASURE/*.out is
  rc=0 and fresher are skipped — so the poller's repeated reruns are
  incremental across windows.
- seq2seq is LAST and phase-split (train / decode-only / full): the
  tunnel wedged inside this bench in rounds 2 AND 4 and nobody knows
  which half — the step that wedges IS the bisect evidence.

Results land under MEASURE/<step>.out (+ PERF_LOG.jsonl via bench.py).
The parent process never imports jax (a wedged tunnel blocks any backend
init forever).

Usage: python tools/tpu_measure.py [--skip=step1,step2] [--fresh-hours=6]
"""

from __future__ import annotations

import datetime
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "MEASURE")
sys.path.insert(0, REPO)

from bench import _METRIC_OF  # noqa: E402  (stdlib-only import)


def run_step(name: str, argv: list[str], timeout_s: float,
             env_extra: dict | None = None) -> bool:
    os.makedirs(OUT, exist_ok=True)
    path = os.path.join(OUT, f"{name}.out")
    env = dict(os.environ)
    env.update(env_extra or {})
    t0 = time.time()
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env,
                            cwd=REPO, start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            out, _ = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out = ""
        rc = -9
    dt = time.time() - t0
    with open(path, "w") as f:
        f.write(f"# rc={rc} seconds={dt:.1f} argv={argv}\n")
        f.write(out or "")
    print(json.dumps({"step": name, "rc": rc, "seconds": round(dt, 1),
                      "out": path}), flush=True)
    return rc == 0


_REHEARSE = False   # --rehearse: CPU dry-run of the whole queue (tiny shapes)

#: the backend-init probe every preflight runs — one import + device list,
#: the exact call a wedged axon tunnel blocks forever
_PROBE_CODE = ("import jax; d = jax.devices()[0]; "
               "print('HEALTH', d.platform, d.device_kind)")


def _probe_backend(timeout_s: float, code: str = _PROBE_CODE) -> dict:
    """Run the backend-init probe in its OWN process group under a HARD
    timeout, SIGKILLing the whole group on expiry.  subprocess.run's
    timeout kills only the direct child — a wedged jax init can leave a
    helper process holding the pipe, so the post-kill communicate()
    blocks forever and the 'health check' itself wedges the queue (the
    r04/r05 degraded-window shape).  Returns {ok, detail}."""
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            start_new_session=True)
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            out, _ = proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            out = ""
        return {"ok": False,
                "detail": f"backend init hung > {timeout_s:.0f}s "
                          f"(SIGKILLed probe group)"}
    ok = "HEALTH tpu" in (out or "") or \
        (_REHEARSE and "HEALTH cpu" in (out or ""))
    return {"ok": ok, "detail": (out or "")[-200:].strip()}


def stamp_degraded(reason: str) -> str:
    """Mark THIS measurement window degraded — an atomic `window.json`
    under OUT carrying the reason and timestamp, written the moment the
    preflight (or a mid-queue health recheck) finds the backend
    unusable.  The driver and the next session read it instead of
    inferring a dead window from a pile of per-step timeouts, and the
    queue stops burning its remaining steps' timeouts against a wedged
    backend — the fast-fail half of the PERF.md wedge-avoidance
    design."""
    os.makedirs(OUT, exist_ok=True)
    path = os.path.join(OUT, "window.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"degraded": True, "reason": reason,
                   "ts": datetime.datetime.now(datetime.timezone.utc)
                   .isoformat(timespec="seconds")}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    print(json.dumps({"window": "degraded", "reason": reason,
                      "stamp": path}), flush=True)
    return path


def health(timeout_s: float = 90) -> bool:
    r = _probe_backend(timeout_s)
    print(json.dumps({"step": "health", "ok": r["ok"],
                      "detail": r["detail"]}), flush=True)
    return r["ok"]


# ---------------------------------------------------------------------------
# freshness checks (all stdlib; never import jax here)
# ---------------------------------------------------------------------------

def _age_hours(ts_iso: str) -> float:
    try:
        ts = datetime.datetime.fromisoformat(ts_iso)
        now = datetime.datetime.now(ts.tzinfo or datetime.timezone.utc)
        return (now - ts).total_seconds() / 3600.0
    except ValueError:
        return 1e9


def _metric_fresh(metric: str, hours: float, need_field: str = "") -> str:
    """Non-empty reason iff PERF_LOG has a fresh enough record carrying
    `metric` (top-level or nested part), optionally requiring a field."""
    try:
        path = os.environ.get("BENCH_PERF_LOG") or \
            os.path.join(REPO, "PERF_LOG.jsonl")
        with open(path) as f:
            lines = f.readlines()
    except OSError:
        return ""
    for line in reversed(lines):
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        r = rec.get("record")
        if not isinstance(r, dict):
            continue
        parts = [r] + [v for v in r.values() if isinstance(v, dict)]
        for p in parts:
            if p.get("metric") == metric and p.get("value") and \
                    "error" not in p and \
                    (not need_field or need_field in p):
                age = _age_hours(p.get("measured_at") or rec.get("ts", ""))
                if age < hours:
                    return f"fresh PERF_LOG record (age {age:.1f}h)"
    return ""


def _out_fresh(step: str, hours: float) -> str:
    path = os.path.join(OUT, f"{step}.out")
    try:
        with open(path) as f:
            first = f.readline()
        if not first.startswith("# rc=0"):
            return ""
        age = (time.time() - os.path.getmtime(path)) / 3600.0
        return f"fresh rc=0 output (age {age:.1f}h)" if age < hours else ""
    except OSError:
        return ""


def _parity_pending(only: str, ledger: str) -> int:
    """How many parity cases are NOT yet green under the current code hash —
    computed by tpu_parity --list itself (the same _ledger_passed replay
    that --skip-passed uses, so this can never disagree with the actual
    skipping).  -1 when the listing fails (then just run the step)."""
    try:
        p = subprocess.run(
            [sys.executable, "tools/tpu_parity.py", "--list",
             f"--only={only}", f"--ledger={ledger}"],
            timeout=120, capture_output=True, text=True, cwd=REPO)
        listing = json.loads(p.stdout.strip().splitlines()[-1])
        return len(listing["pending"])
    except Exception:
        return -1


# tiny-shape overrides for --rehearse: the whole queue runs end-to-end on
# the host CPU in minutes, validating orchestration (spawning, ledger,
# freshness skips, output layout) so a real tunnel window is never the
# first time the pipeline executes
_REHEARSE_ENV = {
    "JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
    "PADDLE_TPU_PALLAS_INTERPRET": "1", "BENCH_DTYPE": "float32",
    "BENCH_BATCH_SIZE": "16", "BENCH_ITERS": "2",
    "BENCH_S2S_VOCAB": "200", "BENCH_S2S_HIDDEN": "32",
    "BENCH_S2S_BATCH": "4", "BENCH_S2S_LEN": "6", "BENCH_S2S_ITERS": "2",
    "BENCH_S2S_MAXLEN": "6", "BENCH_S2S_DECODE_REPS": "2",
    "BENCH_MNIST_BATCH": "16", "BENCH_MNIST_ITERS": "2",
    "BENCH_SENT_VOCAB": "500", "BENCH_SENT_BATCH": "8",
    "BENCH_SENT_LEN": "12", "BENCH_SENT_ITERS": "2",
    "BENCH_REC_BATCH": "32", "BENCH_REC_ITERS": "2",
    "BENCH_LM_VOCAB": "500", "BENCH_LM_DIM": "32", "BENCH_LM_LAYERS": "2",
    "BENCH_LM_HEADS": "2", "BENCH_LM_LEN": "32", "BENCH_LM_BATCH": "4",
    "BENCH_LM_ITERS": "2", "BENCH_LM_DECODE_BATCH": "2",
    "BENCH_LM_MAX_NEW": "8", "BENCH_LM_DECODE_REPS": "2",
    "BENCH_SERVE_SLOTS": "2", "BENCH_SERVE_PAGE": "8",
    "BENCH_SERVE_CONTEXT": "48", "BENCH_SERVE_REQS": "6",
    "BENCH_SERVE_PROMPT_LO": "3", "BENCH_SERVE_PROMPT_HI": "12",
    "BENCH_SERVE_MAX_NEW": "4", "BENCH_SERVE_REPS": "2",
    "BENCH_SERVE_PREFIX_POOL": "2", "BENCH_SERVE_PREFIX_LEN": "16",
    "BENCH_SERVE_SUFFIX_LO": "3", "BENCH_SERVE_SUFFIX_HI": "8",
    "BENCH_SERVE_FLEET": "2", "BENCH_SERVE_FLEET_CONC": "2",
    "BENCH_SERVE_SPEC_K": "3",
    "BENCH_SERVE_DECODE_STEPS": "3",
    "BENCH_SERVE_SPILL_SLOTS": "2", "BENCH_SERVE_SPILL_PAGES": "10",
    "BENCH_SERVE_SPILL_BUDGET": "1000000",
}


def main() -> int:
    global OUT, _REHEARSE
    skip: set[str] = set()
    fresh_hours = 6.0
    args = list(sys.argv[1:])
    while args:
        a = args.pop(0)
        if a.startswith("--skip="):
            skip |= set(a.split("=", 1)[1].split(","))
        elif a == "--skip" and args:
            skip |= set(args.pop(0).split(","))
        elif a.startswith("--fresh-hours="):
            fresh_hours = float(a.split("=", 1)[1])
        elif a == "--rehearse":
            _REHEARSE = True
    if _REHEARSE:
        OUT = os.path.join(REPO, "MEASURE_REHEARSAL")
        os.environ.update(_REHEARSE_ENV)
        os.environ["BENCH_PERF_LOG"] = os.path.join(OUT, "PERF_LOG.jsonl")
        os.makedirs(OUT, exist_ok=True)
    if not health():
        # PREFLIGHT: the probe just proved backend init hangs or fails —
        # stamp the window degraded NOW and exit fast, instead of
        # spawning bench children that would each burn a full hard
        # timeout against the same wedged backend (the r04/r05 cause)
        stamp_degraded("preflight: backend init probe failed or hung")
        print(json.dumps({"fatal": "TPU not healthy; nothing run"}))
        return 1
    try:
        # a healthy preflight supersedes any stale degraded stamp
        os.remove(os.path.join(OUT, "window.json"))
    except OSError:
        pass

    py = sys.executable
    fh = fresh_hours
    ledger = os.path.join(OUT, "parity_ledger.jsonl")

    def bench_env(only, budget, extra=None):
        env = {"BENCH_ONLY": only, "BENCH_TIME_BUDGET_S": str(budget)}
        env.update(extra or {})
        return env

    # sweep-tool argvs: tiny shapes under --rehearse, the real matrix on
    # hardware
    if _REHEARSE:
        attn_args = ["--lens", "128", "--batch", "1", "--heads", "2",
                     "--target-ms", "5", "--reps", "1"]
        attn_f32_args = attn_args + ["--dtype", "float32"]
        lm_args = ["--lens", "32", "--impls", "auto", "--vocab", "300",
                   "--dim", "32", "--layers", "2", "--heads", "2",
                   "--dtype", "float32", "--iters", "2",
                   "--tokens-per-batch", "128", "--decode-batch", "2",
                   "--max-new", "8", "--decode-reps", "2"]
        serving_args = ["--num-requests", "6", "--slots", "2",
                        "--page-size", "8", "--max-context", "32",
                        "--prompt-lo", "3", "--prompt-hi", "10",
                        "--max-new", "4", "--vocab", "64", "--dim", "32",
                        "--layers", "1", "--heads", "2",
                        "--dtype", "float32", "--reps", "1",
                        "--rate", "0,20"]
        serving_prefix_args = ["--prefix-skew", "1.0",
                               "--num-requests", "6", "--slots", "2",
                               "--page-size", "8", "--max-context", "48",
                               "--prefix-pool", "2", "--prefix-len", "16",
                               "--suffix-lo", "3", "--suffix-hi", "8",
                               "--max-new", "4", "--vocab", "64",
                               "--dim", "32", "--layers", "1",
                               "--heads", "2", "--dtype", "float32",
                               "--reps", "1"]
        serving_chunked_args = ["--prompt-dist", "heavy-tail",
                                "--num-requests", "6", "--slots", "2",
                                "--page-size", "8", "--max-context", "48",
                                "--prompt-lo", "3", "--prompt-hi", "40",
                                "--prefill-chunk", "8",
                                "--max-new", "4", "--vocab", "64",
                                "--dim", "32", "--layers", "1",
                                "--heads", "2", "--dtype", "float32",
                                "--reps", "1"]
        serving_fleet_args = ["--fleet", "2", "--concurrency", "2",
                              "--num-requests", "8", "--slots", "2",
                              "--page-size", "8", "--max-context", "48",
                              "--prefix-pool", "2", "--prefix-len", "16",
                              "--suffix-lo", "3", "--suffix-hi", "8",
                              "--max-new", "4", "--vocab", "64",
                              "--dim", "32", "--layers", "1",
                              "--heads", "2", "--dtype", "float32"]
        # disaggregated prefill/decode A/B at tiny shapes: 24-token
        # prefixes (three 8-token pages) clear the disagg floor, so the
        # role-split arm genuinely ships pages on the CPU rehearse
        serving_disagg_args = ["--disagg", "--concurrency", "2",
                               "--num-requests", "8", "--slots", "2",
                               "--page-size", "8", "--max-context", "96",
                               "--prefix-pool", "2", "--prefix-len", "24",
                               "--suffix-lo", "4", "--suffix-hi", "8",
                               "--max-new", "8", "--vocab", "64",
                               "--dim", "16", "--layers", "1",
                               "--heads", "2", "--dtype", "float32"]
        serving_tp_args = ["--mesh-model", "2", "--num-requests", "6",
                           "--slots", "2", "--page-size", "8",
                           "--max-context", "48", "--prompt-lo", "3",
                           "--prompt-hi", "10", "--max-new", "4",
                           "--vocab", "64", "--dim", "32",
                           "--layers", "1", "--heads", "2",
                           "--dtype", "float32", "--reps", "1"]
        serving_spec_args = ["--spec-k", "3", "--num-requests", "6",
                             "--slots", "2", "--page-size", "8",
                             "--max-context", "48", "--prompt-lo", "6",
                             "--prompt-hi", "16", "--max-new", "8",
                             "--vocab", "64", "--dim", "32",
                             "--layers", "1", "--heads", "2",
                             "--dtype", "float32", "--reps", "1"]
        # adaptive-speculation matrix (ngram vs batched draft model vs
        # decode_mode=auto, repetitive + heavy-tail workloads) at the
        # same tiny shapes — the accept-rate and auto-vs-static gates
        # run end-to-end on the CPU rehearse
        serving_spec_modes_args = serving_spec_args + [
            "--drafter", "model", "--spec-dynamic"]
        serving_scan_args = ["--decode-steps", "3", "--num-requests", "6",
                             "--slots", "2", "--page-size", "8",
                             "--max-context", "48", "--prompt-lo", "6",
                             "--prompt-hi", "16", "--max-new", "8",
                             "--vocab", "64", "--dim", "32",
                             "--layers", "1", "--heads", "2",
                             "--dtype", "float32", "--reps", "1"]
        # pool (14 pages) deliberately below the 6x3-page prefix working
        # set so the off arm destroys cold prefixes and the on arm spills
        serving_spill_args = ["--spill-budget", "1000000",
                              "--num-pages", "14", "--num-requests", "8",
                              "--slots", "2", "--page-size", "8",
                              "--max-context", "64", "--prefix-pool", "6",
                              "--prefix-len", "24", "--suffix-lo", "4",
                              "--suffix-hi", "8", "--max-new", "8",
                              "--vocab", "64", "--dim", "32",
                              "--layers", "1", "--heads", "2",
                              "--dtype", "float32", "--reps", "1"]
        # the CPU rehearse has one host device by default — the sharded
        # arm needs a virtual 2-device mesh (harmless on real TPU steps,
        # which never see this env)
        tp_env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2"}
        # parameter-server training record: tiny shapes; the trainers run
        # the CPU backend on hardware too (the tier under test is the
        # wire/barrier/update machinery — see bench.py bench_train_dist)
        dist_env = {"BENCH_DIST_SAMPLES": "128", "BENCH_DIST_BATCH": "16",
                    "BENCH_DIST_DIM": "16", "BENCH_DIST_HIDDEN": "32",
                    "BENCH_DIST_PASSES": "1"}
        rnn_args = ["--shapes", "8,16,64", "--iters", "1"]
        tune_args = ["--lens", "256", "--blocks", "128,256", "--batch", "1",
                     "--heads", "2", "--target-ms", "5", "--reps", "1"]
        additive_args = ["--batch", "8", "--enc-len", "8", "--dec-len", "4",
                         "--dim", "32", "--reps", "1", "--dtype", "float32"]
        profile_args = ["--iters", "2", "--batch", "16",
                        "--outdir", os.path.join(OUT, "xplane_vgg")]
    else:
        attn_args = ["--lens", "512,1024,2048,4096,8192,16384"]
        attn_f32_args = ["--lens", "512,1024,4096", "--dtype", "float32"]
        lm_args = []
        # closed-loop peak + the offered-load curve PERF.md's serving
        # section reads (tokens/s + occupancy vs arrival rate)
        serving_args = ["--rate", "0,4,16,64"]
        # TPU-sized prefix-skew A/B (defaults: pool 8 x 128-token
        # prefixes, Zipf 1.0, 16-64-token suffixes)
        serving_prefix_args = ["--prefix-skew", "1.0"]
        # heavy-tail chunked-prefill A/B: Pareto tail up to near slot
        # capacity (768-context default clamps to ~700-token prompts)
        serving_chunked_args = ["--prompt-dist", "heavy-tail",
                                "--prompt-hi", "700"]
        # fleet A/B at TPU size: one router + 2 serve.py subprocesses vs
        # one replica, on the prefix-skew defaults (each arm spawns fresh
        # replicas, so this is the longest serving step)
        serving_fleet_args = ["--fleet", "2"]
        # disaggregated prefill/decode A/B at TPU size: router + 2
        # colocated replicas vs 1 prefill + 1 decode over kv_push, on
        # the prefix-skew defaults (two fresh-replica arms — another
        # long serving step)
        serving_disagg_args = ["--disagg"]
        # tensor-parallel A/B: needs >= 2 real chips; a 1-chip tunnel
        # records the actionable device-count error instead of wedging
        serving_tp_args = ["--mesh-model", "2"]
        # speculative-decoding A/B at TPU size: spec-off vs spec-on k=4
        # on the locally-repetitive workload (defaults)
        serving_spec_args = ["--spec-k", "4"]
        # adaptive-speculation matrix at TPU size: the self-speculation
        # drafter's batched dispatch vs ngram, dynamic k, and the auto
        # dispatch policy — the hardware numbers the ROADMAP owes
        serving_spec_modes_args = ["--spec-k", "4", "--drafter", "model",
                                   "--spec-dynamic"]
        # multi-step decode A/B at TPU size: decode_steps 1 vs 4 on the
        # mixed-length workload (this is where the dispatch-amortization
        # win actually shows — PERF.md "Reading the multi-step bench")
        serving_scan_args = ["--decode-steps", "4"]
        # host-spill A/B at TPU size: 96-page pool (below the default
        # 8x8-page prefix working set plus in-flight demand at 4 slots)
        # with a 64 MiB host budget — PERF.md "Reading the spill bench"
        serving_spill_args = ["--spill-budget", str(64 << 20),
                              "--num-pages", "96", "--slots", "4"]
        tp_env = {}
        dist_env = {}
        rnn_args = []
        additive_args = []
        profile_args = []
        tune_args = []

    # Ordered by marginal value per healthy-tunnel minute (VERDICT r4
    # items 1-7).  done() returning a non-empty reason skips the step.
    #  (name, argv, timeout_s, env, done)
    steps = [
        # (a) flash+additive parity — the fp32 precision fix and the
        # remaining Mosaic-risk shapes have never been verified on device
        ("parity",
         [py, "tools/tpu_parity.py", "--only=flash,additive",
          "--skip-passed", f"--ledger={ledger}"], 1500, {},
         lambda: "all cases green in ledger"
         if _parity_pending("flash,additive", ledger) == 0 else ""),
        # (b) headline + the three never-benched BASELINE configs + LM
        ("bench_vgg", [py, "bench.py"], 760, bench_env("vgg", 700),
         lambda: _metric_fresh(_METRIC_OF["vgg"], fh)),
        ("bench_sentiment", [py, "bench.py"], 660,
         bench_env("sentiment", 600),
         lambda: _metric_fresh(_METRIC_OF["sentiment"], fh)),
        ("bench_mnist", [py, "bench.py"], 560, bench_env("mnist", 500),
         lambda: _metric_fresh(_METRIC_OF["mnist"], fh)),
        ("bench_recommendation", [py, "bench.py"], 660,
         bench_env("recommendation", 600),
         lambda: _metric_fresh(_METRIC_OF["recommendation"], fh)),
        ("bench_lm_record", [py, "bench.py"], 900, bench_env("lm", 840),
         lambda: _metric_fresh(_METRIC_OF["lm"], fh)),
        # the continuous-batching serving record (lm_serving_tok_per_sec +
        # the p99 per-token latency companion): a record from before the
        # latency fields existed must NOT satisfy freshness — require the
        # new field so the queue re-measures once per code era
        ("bench_serving_record", [py, "bench.py"], 900,
         bench_env("serving", 840),
         lambda: _metric_fresh(_METRIC_OF["serving"], fh,
                               need_field="lm_serving_p99_tok_latency_ms")),
        # prefix-cache effectiveness record (hit rate headline + prefill
        # tokens saved + first-token p50 vs the no-cache baseline): the
        # A/B runs the workload twice, so it gets the serving budget too
        ("bench_serving_prefix_record", [py, "bench.py"], 900,
         bench_env("serving_prefix", 840),
         lambda: _metric_fresh(_METRIC_OF["serving_prefix"], fh)),
        # chunked-prefill effectiveness record (p99 inter-token latency
        # with chunking on, vs the whole-prompt-prefill baseline, over
        # the heavy-tail prompt mix): another two-pass A/B, same budget
        ("bench_serving_chunked_record", [py, "bench.py"], 900,
         bench_env("serving_chunked", 840),
         lambda: _metric_fresh(_METRIC_OF["serving_chunked"], fh)),
        # fleet-router record (affinity-arm tok/s + the affinity-vs-
        # random hit-rate comparison): three arms, each spawning fresh
        # replica subprocesses — the largest serving budget in the queue
        ("bench_serving_fleet_record", [py, "bench.py"], 1500,
         bench_env("serving_fleet", 1440),
         lambda: _metric_fresh(_METRIC_OF["serving_fleet"], fh)),
        # disaggregated prefill/decode record (role-split tok/s vs the
        # 2x colocated-replica arm, first-token p50/p99 both arms, and
        # the kv_push/pages-shipped reconciliation): two fresh-replica
        # arms behind routers, same budget as the fleet record
        ("bench_serving_disagg_record", [py, "bench.py"], 1500,
         bench_env("serving_disagg", 1440),
         lambda: _metric_fresh(_METRIC_OF["serving_disagg"], fh)),
        # tensor-parallel sharded-decode record (tokens/s 1 vs 2 shards +
        # KV pool bytes per shard): another two-engine A/B, same budget;
        # the rehearse env injects the 2-virtual-device XLA flag
        ("bench_serving_tp_record", [py, "bench.py"], 900,
         bench_env("serving_tp", 840, tp_env),
         lambda: _metric_fresh(_METRIC_OF["serving_tp"], fh)),
        # speculative-decoding record (spec-on tokens/s + accept rate +
        # the drafted/accepted/emitted reconciliation): another two-arm
        # A/B on one engine, same budget as the other serving A/Bs
        ("bench_serving_spec_record", [py, "bench.py"], 900,
         bench_env("serving_spec", 840),
         lambda: _metric_fresh(_METRIC_OF["serving_spec"], fh)),
        # multi-step decode record (scan-arm tokens/s + baseline arm +
        # the scan_steps == k * scan_flushes dispatch reconciliation):
        # another two-arm A/B on one engine, same budget
        ("bench_serving_scan_record", [py, "bench.py"], 900,
         bench_env("serving_scan", 840),
         lambda: _metric_fresh(_METRIC_OF["serving_scan"], fh)),
        # host-spill record (spill-on hit rate + both arms' tokens saved /
        # first-token p50 + the restored-pages reconciliation): another
        # two-arm A/B on one engine, same budget
        ("bench_serving_spill_record", [py, "bench.py"], 900,
         bench_env("serving_spill", 840),
         lambda: _metric_fresh(_METRIC_OF["serving_spill"], fh)),
        # parameter-server training record (K-trainer aggregate samples/s
        # + the 1-trainer arm + scaling efficiency + the live-flip
        # trace-overhead probe): all subprocesses on the CPU backend, so
        # it never contends for the chip and runs the same on rehearse
        # and hardware windows; freshness requires the probe field so a
        # pre-probe record never masks the measurement (the step pins
        # BENCH_DIST_TRACE=1 — an operator-exported =0 would otherwise
        # write records that can never satisfy the gate)
        ("bench_train_dist_record", [py, "bench.py"], 900,
         bench_env("train_dist", 840,
                   {**dist_env, "BENCH_DIST_TRACE": "1"}),
         lambda: _metric_fresh(_METRIC_OF["train_dist"], fh,
                               "train_dist_trace_overhead_pct")),
        # (c) the VGG regression evidence: xplane profile banked on disk
        ("profile_vgg", [py, "tools/profile_vgg.py"] + profile_args,
         700, {},
         lambda: _out_fresh("profile_vgg", fh)),
        # (d) RNN kernels: zero hardware executions before this round
        ("parity_rnn",
         [py, "tools/tpu_parity.py", "--only=lstm,gru", "--skip-passed",
          f"--ledger={ledger}"], 1500, {},
         lambda: "all cases green in ledger"
         if _parity_pending("lstm,gru", ledger) == 0 else ""),
        ("rnn_bench", [py, "tools/bench_rnn.py"] + rnn_args, 900, {},
         lambda: _out_fresh("rnn_bench", fh)),
        # (e) sweeps: attention crossover (dispatch-proof timing), LM
        # context sweep, additive kernel re-check
        ("attn_bench",
         [py, "tools/bench_attention.py"] + attn_args, 1200, {},
         lambda: _out_fresh("attn_bench", fh)),
        ("bench_lm", [py, "tools/bench_lm.py"] + lm_args, 1500, {},
         lambda: _out_fresh("bench_lm", fh)),
        # serving sweep: closed-loop peak + the tokens/s-vs-arrival-rate
        # occupancy curve (PERF.md "reading the serving bench")
        ("bench_serving", [py, "tools/bench_serving.py"] + serving_args,
         1200, {},
         lambda: _out_fresh("bench_serving", fh)),
        # prefix-skew sweep: the full-size A/B with the per-run breakdown
        # (evictions, COW copies, suffix signatures) banked to OUT
        ("bench_serving_prefix",
         [py, "tools/bench_serving.py"] + serving_prefix_args, 1200, {},
         lambda: _out_fresh("bench_serving_prefix", fh)),
        # heavy-tail chunked-prefill sweep: the full-size off/on A/B with
        # the first-token + inter-token p50/p99 breakdown banked to OUT
        ("bench_serving_chunked",
         [py, "tools/bench_serving.py"] + serving_chunked_args, 1200, {},
         lambda: _out_fresh("bench_serving_chunked", fh)),
        # fleet sweep: the full three-arm A/B banked to OUT (per-arm
        # tok/s, hit rates, router shed/retry counters)
        ("bench_serving_fleet",
         [py, "tools/bench_serving.py"] + serving_fleet_args, 1800, {},
         lambda: _out_fresh("bench_serving_fleet", fh)),
        # disagg sweep: the full colocated-vs-role-split A/B banked to
        # OUT (tok/s + first-token latency both arms, kv_push counters)
        ("bench_serving_disagg",
         [py, "tools/bench_serving.py"] + serving_disagg_args, 1800, {},
         lambda: _out_fresh("bench_serving_disagg", fh)),
        # tensor-parallel sweep: the full-size 1-vs-N-shard A/B banked to
        # OUT (tok/s both arms, per-shard pool bytes, sig stability)
        ("bench_serving_tp",
         [py, "tools/bench_serving.py"] + serving_tp_args, 1200, tp_env,
         lambda: _out_fresh("bench_serving_tp", fh)),
        # speculative-decoding sweep: the full-size spec-off/on A/B with
        # the per-arm step counts and counter reconciliation banked
        ("bench_serving_spec",
         [py, "tools/bench_serving.py"] + serving_spec_args, 1200, {},
         lambda: _out_fresh("bench_serving_spec", fh)),
        # adaptive-speculation matrix sweep: drafter ngram-vs-model
        # accept A/B + dynamic-k + decode_mode=auto arms on both
        # workloads, with the auto-vs-static and accept gates banked
        ("bench_serving_spec_modes",
         [py, "tools/bench_serving.py"] + serving_spec_modes_args,
         1800, {},
         lambda: _out_fresh("bench_serving_spec_modes", fh)),
        # multi-step decode sweep: the full-size k=1 vs k A/B with the
        # flush/step counters and dispatch reconciliation banked
        ("bench_serving_scan",
         [py, "tools/bench_serving.py"] + serving_scan_args, 1200, {},
         lambda: _out_fresh("bench_serving_scan", fh)),
        # host-spill sweep: the full-size off/on A/B with the spill/
        # restore page counters and the hit-rate comparison banked
        ("bench_serving_spill",
         [py, "tools/bench_serving.py"] + serving_spill_args, 1200, {},
         lambda: _out_fresh("bench_serving_spill", fh)),
        ("additive_bench", [py, "tools/bench_additive.py"] + additive_args,
         400, {},
         lambda: _out_fresh("additive_bench", fh)),
        ("tune_flash", [py, "tools/tune_flash.py"] + tune_args, 1200, {},
         lambda: _out_fresh("tune_flash", fh)),
        ("attn_bench_f32",
         [py, "tools/bench_attention.py"] + attn_f32_args, 700, {},
         lambda: _out_fresh("attn_bench_f32", fh)),
        # (f) seq2seq LAST, phase-split: whichever step wedges bisects the
        # r2/r4 tunnel wedge (train scan vs beam program)
        ("s2s_train", [py, "bench.py"], 760,
         bench_env("seq2seq", 700, {"BENCH_S2S_PHASE": "train"}),
         lambda: _metric_fresh(_METRIC_OF["seq2seq"], fh)),
        ("s2s_decode", [py, "bench.py"], 760,
         bench_env("seq2seq", 700, {"BENCH_S2S_PHASE": "decode"}),
         lambda: _metric_fresh("wmt14_seq2seq_beam_decode_tokens_per_sec",
                               fh)),
        # satisfied EITHER by one combined record OR by both phase-split
        # records being fresh (bench.py's _assemble_lkg merges the decode
        # part into the train part) — the wedge-prone full bench must not
        # re-run when its halves just banked
        ("s2s_full", [py, "bench.py"], 1000,
         bench_env("seq2seq", 940),
         lambda: _metric_fresh(_METRIC_OF["seq2seq"], fh,
                               need_field="beam_decode_tokens_per_sec")
         or (_metric_fresh(_METRIC_OF["seq2seq"], fh)
             and _metric_fresh("wmt14_seq2seq_beam_decode_tokens_per_sec",
                               fh)
             and "train+decode phase records both fresh")),
        # (g) one complete single-record run, only if something above
        # left a config stale
        ("bench_full", [py, "bench.py"], 2400,
         {"BENCH_TIME_BUDGET_S": "2100"},
         lambda: "all six metrics fresh"
         if all(_metric_fresh(m, fh) for m in _METRIC_OF.values()) else ""),
    ]
    for name, argv, to, env, done in steps:
        if name in skip:
            continue
        reason = done()
        if reason:
            print(json.dumps({"step": name, "skipped": reason}), flush=True)
            continue
        ok = run_step(name, argv, to, env)
        if not ok and not health(90):
            # a failed step + dead tunnel: stamp the window degraded and
            # stop burning the remaining steps' timeouts against a
            # wedged backend (everything measured so far is already
            # persisted under MEASURE/)
            stamp_degraded(f"tunnel died during step {name!r}")
            print(json.dumps({"fatal": f"tunnel died during {name}"}))
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
