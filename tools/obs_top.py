"""obs_top: a top-style live terminal dashboard for the fleet health plane.

Polls the `history` RPC (obs/timeseries.py — loop-thread, stale-ok, so
rows keep updating while a replica's pump is wedged) plus the `stats`
frame, and renders one row per process: role, token rate, slot/page
occupancy, prefix hit rate, speculative accept rate, firing SLOs
(obs/slo.py), and sparkline trends — the 2016 `watch nvidia-smi` habit,
rebuilt for an engine-pump fleet.

Against a fleet router the single aggregate `history` reply carries
every replica's series under `replica="rN"` labels:

  python tools/obs_top.py --router 127.0.0.1:8440

Or poll an explicit host list (replicas, routers, pservers — any mix;
each answers its own ring):

  python tools/obs_top.py --hosts 127.0.0.1:8431,127.0.0.1:8432

`--once` renders a single frame and exits; `--once --json` prints the
computed rows as machine-readable JSON (what tests/CI consume).
Stdlib-only, like every client-side tool: serving/client.py + wire.py.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.serving.client import ServingClient  # noqa: E402

#: eight-level sparkline ramp (min..max over the series window)
SPARK = "▁▂▃▄▅▆▇█"

_LABEL_RE = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_key(key: str) -> tuple[str, dict]:
    """Split a history series key into (metric name, labels dict)."""
    name, _, inner = key.partition("{")
    labels = {m.group(1): re.sub(r"\\(.)", r"\1", m.group(2))
              for m in _LABEL_RE.finditer(inner)}
    return name, labels


def sparkline(values, width: int = 12) -> str:
    """Newest-right sparkline over the last `width` values."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK[0] * len(vals)
    return "".join(SPARK[min(len(SPARK) - 1,
                             int((v - lo) / (hi - lo) * (len(SPARK) - 1)))]
                   for v in vals)


def _last(points) -> float:
    return float(points[-1][1]) if points else 0.0


def _sum(points) -> float:
    return float(sum(p[1] for p in points))


class _Bucket:
    """One process's series, keyed by bare metric name."""

    def __init__(self):
        self.series: dict[str, dict] = {}    # name -> {"points", "kind"}

    def add(self, name: str, ser: dict) -> None:
        # a labeled family (e.g. latency quantiles) keeps its labels in
        # the bucket key so specific quantiles stay addressable
        self.series[name] = ser

    def points(self, name: str):
        ser = self.series.get(name)
        return (ser or {}).get("points") or []


def bucket_series(series: dict) -> dict[str, _Bucket]:
    """Group an aggregate (or single-process) series dict by the
    `replica` label; unlabeled series land under "" (the polled process
    itself — the router's own rows in aggregate mode)."""
    out: dict[str, _Bucket] = {}
    for key, ser in (series or {}).items():
        name, labels = parse_key(key)
        rid = labels.pop("replica", "")
        bkey = name if not labels else \
            name + "{" + ",".join(f'{k}="{v}"'
                                  for k, v in sorted(labels.items())) + "}"
        out.setdefault(rid, _Bucket()).add(bkey, ser)
    return out


def row_from_bucket(b: _Bucket, resolution_s: float) -> dict:
    """The computed per-process row: rates from counter deltas, ratios
    over the visible window, firing SLOs from the obs_slo_firing series."""
    res = max(1e-9, float(resolution_s))
    tok = b.points("serving_tokens_generated_total")
    hits = _sum(b.points("serving_prefix_hits_total"))
    misses = _sum(b.points("serving_prefix_misses_total"))
    drafted = _sum(b.points("serving_spec_drafted_total"))
    accepted = _sum(b.points("serving_spec_accepted_total"))
    slots = _last(b.points("serving_num_slots"))
    row = {
        "tok_s": round(_last(tok) / res, 2),
        "tok_spark": sparkline([v for _t, v in tok]),
        "occupancy": round(_last(b.points("serving_slots_in_use"))
                           / slots, 3) if slots else None,
        "hit_rate": round(hits / (hits + misses), 3)
        if hits + misses else None,
        "accept_rate": round(accepted / drafted, 3) if drafted else None,
        "slos_firing": sorted(
            parse_key(k)[1].get("slo", "?")
            for k, ser in b.series.items()
            if k.startswith("obs_slo_firing")
            and _last(ser.get("points") or []) >= 1.0),
    }
    # non-serving processes (router/pserver) still get their trend column
    if not tok:
        for name in ("fleet_requests_accepted_total",
                     "pserver_updates_total"):
            pts = b.points(name)
            if pts:
                row["tok_s"] = None
                row["rate_s"] = round(_last(pts) / res, 2)
                row["tok_spark"] = sparkline([v for _t, v in pts])
                break
    return row


def poll_router(addr: str, last_s: float) -> dict:
    host, _, port = addr.rpartition(":")
    with ServingClient(host or "127.0.0.1", int(port)) as c:
        hist = c.history(last_s=last_s or None, aggregate=True)
        stats = c.stats()
    res = float(hist.get("resolution_s") or 5.0)
    roles = {}
    for r in stats.get("replicas") or []:
        roles[r.get("replica")] = {"role": r.get("role"),
                                   "state": r.get("state"),
                                   "addr": r.get("addr")}
    rows = {}
    for rid, b in sorted(bucket_series(hist.get("series")).items()):
        row = row_from_bucket(b, res)
        if rid == "":
            row.update(role="router", state="-", addr=addr)
            rows["router"] = row
        else:
            row.update(roles.get(rid) or {"role": "?", "state": "?"})
            rows[rid] = row
    return {"mode": "router", "resolution_s": res,
            "last_sample_unix": hist.get("last_sample_unix"),
            "replicas": sorted(hist.get("replicas") or []), "rows": rows}


def poll_hosts(addrs: list[str], last_s: float) -> dict:
    rows = {}
    res = 5.0
    for addr in addrs:
        host, _, port = addr.rpartition(":")
        try:
            with ServingClient(host or "127.0.0.1", int(port),
                               connect_attempts=1) as c:
                hello = c.hello()
                hist = c.history(last_s=last_s or None)
                stats = c.stats(stale_ok=hello.get("role") == "replica")
        except (OSError, ConnectionError) as e:
            rows[addr] = {"role": "?", "state": "unreachable",
                          "error": f"{type(e).__name__}: {e}"}
            continue
        res = float(hist.get("resolution_s") or res)
        b = bucket_series(hist.get("series")).get("") or _Bucket()
        row = row_from_bucket(b, res)
        row.update(role=stats.get("role") or hello.get("role") or "?",
                   state="draining" if stats.get("draining") else "up",
                   addr=addr)
        rows[addr] = row
    return {"mode": "hosts", "resolution_s": res, "rows": rows}


def _fmt(v, pct: bool = False) -> str:
    if v is None:
        return "-"
    return f"{v * 100:.1f}%" if pct else f"{v:g}"


def render(frame: dict) -> str:
    head = (f"obs_top  {time.strftime('%H:%M:%S')}  "
            f"resolution={frame.get('resolution_s')}s  "
            f"rows={len(frame['rows'])}")
    cols = (f"{'id':14s} {'role':8s} {'state':10s} {'tok/s':>8s} "
            f"{'occ':>6s} {'hit':>6s} {'acc':>6s}  {'trend':12s} slo")
    lines = [head, cols]
    for rid, row in frame["rows"].items():
        if row.get("state") == "unreachable":
            lines.append(f"{rid:14s} {'?':8s} unreachable  "
                         f"({row.get('error', '')})")
            continue
        rate = row.get("tok_s")
        if rate is None:
            rate = row.get("rate_s")
        firing = ",".join(row.get("slos_firing") or []) or "-"
        lines.append(
            f"{rid:14.14s} {str(row.get('role') or '-'):8.8s} "
            f"{str(row.get('state') or '-'):10.10s} "
            f"{_fmt(rate):>8s} {_fmt(row.get('occupancy'), True):>6s} "
            f"{_fmt(row.get('hit_rate'), True):>6s} "
            f"{_fmt(row.get('accept_rate'), True):>6s}  "
            f"{row.get('tok_spark', ''):12s} {firing}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--router", default="",
                    help="HOST:PORT of a fleet router — one aggregate "
                         "history pull covers every replica")
    ap.add_argument("--hosts", default="",
                    help="comma-separated HOST:PORT list to poll "
                         "directly (replicas/routers/pservers, any mix)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period, seconds")
    ap.add_argument("--window", type=float, default=300.0,
                    help="trailing history window per pull, seconds")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the computed rows as JSON "
                         "(the tests/CI contract)")
    args = ap.parse_args(argv)
    if bool(args.router) == bool(args.hosts):
        print("need exactly one of --router HOST:PORT or --hosts ...",
              file=sys.stderr)
        return 2
    addrs = [a for a in args.hosts.split(",") if a.strip()]
    while True:
        frame = poll_router(args.router, args.window) if args.router \
            else poll_hosts(addrs, args.window)
        if args.once:
            print(json.dumps(frame, indent=2) if args.json
                  else render(frame))
            return 0
        print("\x1b[H\x1b[J" + render(frame), flush=True)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
