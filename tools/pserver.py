"""Serve one parameter-server shard (paddle_tpu/pserver/server.py).

The pserver holds the authoritative parameter + optimizer-state blocks
for multi-process data-parallel training; trainers connect with
`tools/train_dist.py`.  Foreground; SIGTERM or SIGINT drains — open
barriers are failed honestly, one FINAL streaming checkpoint is written
(with --snapshot-dir), exit 0.

  python tools/pserver.py --port 8571 --snapshot-dir runs/dist \
      --snapshot-every 50            # checkpoint every 50 commits, live

Multi-shard fleet (blocks dealt round-robin by the deterministic map;
shard 0 is the membership coordinator):

  python tools/pserver.py --shard-index 0 --n-shards 2 --port 8571
  python tools/pserver.py --shard-index 1 --n-shards 2 --port 8572

On bind it prints one machine-readable line (the scripting contract):

  PSERVER_JSON:{"host": "127.0.0.1", "port": 8571, "pid": 123, ...}

One-shot client ops (stats / Prometheus metrics / commit log / dump):

  python tools/pserver.py --client 127.0.0.1:8571 --stats
  python tools/pserver.py --client 127.0.0.1:8571 --metrics

Observability (docs/distributed_training.md "Observability"):
`--trace-out` writes this shard's span ring on every exit path; the
`trace` RPC (tools/trace_dump.py --pull HOST:PORT) pulls it live with a
no-restart enable flip; `--straggler-ms` tunes the per-window
barrier-skew event and `--wedge-threshold-s` the update-thread watchdog
that freezes one postmortem bundle per wedge episode.

The server is model-agnostic: the FIRST trainer's `ps_init` seeds the
blocks and the optimizer configuration; later trainers must match its
config hash.  Design doc: docs/distributed_training.md.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_client(args) -> int:
    from paddle_tpu.serving import wire
    from paddle_tpu.serving.client import connect_with_backoff

    host, _, port = args.client.rpartition(":")
    sock, hello = connect_with_backoff(host or "127.0.0.1", int(port),
                                       timeout=30.0, expect_role="pserver")
    try:
        if args.metrics:
            wire.write_frame_sync(sock, {"type": "metrics"})
            print(wire.read_frame_sync(sock)["text"], end="")
        elif args.log:
            wire.write_frame_sync(sock, {"type": "ps_log"})
            print(json.dumps(wire.read_frame_sync(sock), indent=2))
        elif args.dump:
            wire.write_frame_sync(sock, {"type": "dump", "id": "cli"})
            reply = wire.read_frame_sync(sock)
            if reply.get("type") == "error":
                print(reply["error"], file=sys.stderr)
                return 1
            print(json.dumps(reply, indent=2))
        elif args.history:
            # the health-plane ring (loop thread, stale-ok — answers
            # against a wedged update thread; docs/observability.md)
            wire.write_frame_sync(sock, {"type": "history", "id": "cli"})
            print(json.dumps(wire.read_frame_sync(sock), indent=2))
        else:
            wire.write_frame_sync(sock, {"type": "stats"})
            print(json.dumps(wire.read_frame_sync(sock), indent=2))
    finally:
        sock.close()
    return 0


async def amain(args) -> int:
    from paddle_tpu.pserver.server import ParameterServer

    tracer = None
    if args.trace_out:
        from paddle_tpu.obs import get_tracer

        tracer = get_tracer()
        tracer.enabled = True

    srv = ParameterServer(
        host=args.host, port=args.port, shard_index=args.shard_index,
        n_shards=args.n_shards, mode=args.mode,
        max_staleness=args.max_staleness,
        beat_timeout_s=args.beat_timeout_s,
        snapshot_dir=args.snapshot_dir or None,
        snapshot_every=args.snapshot_every, keep_last=args.keep_last,
        block_size=args.block_size,
        wedge_threshold_s=args.wedge_threshold_s,
        straggler_ms=args.straggler_ms)
    srv.flight.enabled = True

    def flush_trace():
        # EVERY exit path flushes (serve.py's finally discipline): the
        # meta line stamps role/shard identity so trace_dump --merge
        # labels this shard's track group
        if tracer is not None:
            from paddle_tpu.obs import flush_trace_file

            flush_trace_file(tracer, args.trace_out, "pserver",
                             args.host, srv.port, shard=args.shard_index)

    try:
        host, port = await srv.start()
        print("PSERVER_JSON:" + json.dumps(
            {"host": host, "port": port, "pid": os.getpid(),
             "shard": args.shard_index, "n_shards": args.n_shards,
             "mode": args.mode}), flush=True)

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        print("draining: failing open barriers, writing the final "
              "checkpoint...", file=sys.stderr, flush=True)
        await srv.drain()          # final snapshot with --snapshot-dir
        if srv.last_snapshot_path:
            print(f"final checkpoint: {srv.last_snapshot_path}",
                  file=sys.stderr, flush=True)
        print("drained; bye", file=sys.stderr, flush=True)
        return 0
    finally:
        flush_trace()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (see the PSERVER_JSON line)")
    ap.add_argument("--shard-index", type=int, default=0)
    ap.add_argument("--n-shards", type=int, default=1)
    ap.add_argument("--mode", choices=("sync", "async"), default="sync",
                    help="sync: barrier per batch, bit-exact vs "
                         "grad_accum=K; async: bounded staleness")
    ap.add_argument("--max-staleness", type=int, default=4,
                    help="async mode: versions behind past which a "
                         "gradient is rejected (trainer must re-pull)")
    ap.add_argument("--beat-timeout-s", type=float, default=10.0,
                    help="heartbeat age past which a trainer is dropped "
                         "and its in-flight contribution discarded")
    ap.add_argument("--block-size", type=int, default=0,
                    help="elements per parameter block (0 = default)")
    ap.add_argument("--snapshot-dir", default="",
                    help="streaming-checkpoint target (atomic pass-dir "
                         "format; also the postmortem-bundle dir)")
    ap.add_argument("--snapshot-every", type=int, default=0,
                    help="checkpoint every N commits WITHOUT pausing "
                         "send_grad traffic (0 = only the final one)")
    ap.add_argument("--keep-last", type=int, default=2)
    ap.add_argument("--trace-out", default="",
                    help="enable the span tracer and write this shard's "
                         "spans as JSONL here on every exit path; the "
                         "`trace` RPC (trace_dump --pull) also works "
                         "without this, flipped live")
    ap.add_argument("--wedge-threshold-s", type=float, default=30.0,
                    help="update-thread job lag past which the watchdog "
                         "freezes one postmortem bundle per episode")
    ap.add_argument("--straggler-ms", type=float, default=250.0,
                    help="per-window barrier-arrival skew past which a "
                         "`straggler` flight event names the late rank")
    # client mode
    ap.add_argument("--client", default="",
                    help="HOST:PORT — run as a one-shot client instead")
    ap.add_argument("--stats", action="store_true")
    ap.add_argument("--metrics", action="store_true")
    ap.add_argument("--log", action="store_true",
                    help="with --client: print the commit log")
    ap.add_argument("--dump", action="store_true",
                    help="with --client: freeze a postmortem bundle")
    ap.add_argument("--history", action="store_true",
                    help="with --client: print the health-plane metric "
                         "time-series ring (the `history` RPC)")
    args = ap.parse_args(argv)
    if args.client:
        return run_client(args)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
