"""Serve a fleet router over N serving replicas (paddle_tpu/fleet/).

Router (foreground; SIGTERM or SIGINT drains — finish routed requests,
refuse new ones, exit 0).  Replicas are ordinary `tools/serve.py`
processes; list them up front and/or join them live with the ctl:

  # replicas (each prints SERVE_JSON:{"port": ...})
  python tools/serve.py --config ... --port 8431 &
  python tools/serve.py --config ... --port 8432 &

  # the router (stdlib-only: runs fine on a box with no accelerator)
  python tools/fleet_router.py --port 8440 \
      --replica 127.0.0.1:8431 --replica 127.0.0.1:8432 \
      [--policy affinity] [--postmortem-dir runs/postmortems]

On bind it prints one machine-readable line (same contract as serve.py):

  FLEET_JSON:{"host": "127.0.0.1", "port": 8440, "pid": 12345}

Clients connect to the router exactly as to one replica — serving/client.py,
`tools/serve.py --client HOST:PORT`, same frames, streaming preserved.
Operate the fleet with `python -m paddle_tpu.fleet.ctl --router HOST:PORT
join|leave|drain|undrain|list|wait-drained` (the rolling-restart runbook
lives in docs/serving.md "Fleet").

One-shot client ops (stats / fleet-aggregated metrics / health-plane
history — `python tools/obs_top.py --router HOST:PORT` is the live view):

  python tools/fleet_router.py --client 127.0.0.1:8440 --stats
  python tools/fleet_router.py --client 127.0.0.1:8440 --metrics --aggregate
  python tools/fleet_router.py --client 127.0.0.1:8440 --history --aggregate
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return host or "127.0.0.1", int(port)


def _render_history(reply: dict) -> str:
    # compact one-line-per-series view for --watch (tools/serve.py has
    # the same shape; tools/obs_top.py is the real dashboard)
    lines = [f"samples={reply.get('samples_taken')} "
             f"resolution={reply.get('resolution_s')}s "
             f"replicas={reply.get('replicas')} "
             f"series={len(reply.get('series') or {})}"]
    for key, ser in sorted((reply.get("series") or {}).items()):
        pts = ser.get("points") or []
        last = pts[-1][1] if pts else "?"
        lines.append(f"  {ser.get('kind', '?'):7s} {key}  "
                     f"last={last} n={len(pts)}")
    return "\n".join(lines)


def run_client(args) -> int:
    import time

    from paddle_tpu.serving.client import ServingClient

    host, port = parse_addr(args.client)
    with ServingClient(host, port) as c:
        if args.metrics:
            print(c.metrics(aggregate=args.aggregate), end="")
        elif args.history:
            while True:
                reply = c.history(last_s=args.last_s or None,
                                  aggregate=args.aggregate)
                if not args.watch:
                    print(json.dumps(reply, indent=2))
                    break
                print("\x1b[H\x1b[J" + _render_history(reply), flush=True)
                time.sleep(args.watch)
        elif args.dump:
            print(json.dumps(c.dump(), indent=2))
        else:
            print(json.dumps(c.stats(), indent=2))
    return 0


async def amain(args) -> int:
    from paddle_tpu.fleet import FleetRouter

    tracer = None
    if args.trace_out:
        from paddle_tpu.obs import get_tracer

        tracer = get_tracer()
        tracer.enabled = True

    rt = FleetRouter(host=args.host, port=args.port,
                     replicas=[parse_addr(s) for s in args.replica],
                     policy=args.policy,
                     poll_interval_s=args.poll_interval_s,
                     heartbeat_misses=args.heartbeat_misses,
                     wedge_age_s=args.wedge_age_s,
                     retry_limit=args.retry_limit,
                     disagg_min_prompt=args.disagg_min_prompt,
                     postmortem_dir=args.postmortem_dir or None)

    def flush_trace():
        # EVERY exit path flushes (the serve.py discipline, PR 6): a
        # crashed router must never leave an empty trace file — the
        # placement/relay spans up to the failure are exactly what a
        # postmortem wants.  The meta line stamps process identity so
        # trace_dump --merge labels this file's track group.
        if tracer is not None:
            from paddle_tpu.obs import flush_trace_file

            flush_trace_file(tracer, args.trace_out, "router", args.host,
                             rt.port)

    try:
        host, port = await rt.start()
        print("FLEET_JSON:" + json.dumps(
            {"host": host, "port": port, "pid": os.getpid()}), flush=True)

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        await stop.wait()
        print("draining: refusing new requests, finishing routed ones...",
              file=sys.stderr, flush=True)
        await rt.drain()
        print("drained; bye", file=sys.stderr, flush=True)
        return 0
    finally:
        flush_trace()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (see the FLEET_JSON line)")
    ap.add_argument("--replica", action="append", default=[],
                    metavar="HOST:PORT",
                    help="replica to join at start (repeatable); one not "
                         "up yet is retried until it is — or join live "
                         "via `python -m paddle_tpu.fleet.ctl`")
    ap.add_argument("--policy", default="affinity",
                    choices=["affinity", "least_loaded", "random"],
                    help="placement policy (random exists for the "
                         "fleet bench's hit-rate A/B baseline)")
    ap.add_argument("--poll-interval-s", type=float, default=0.5,
                    help="stats-poll (= heartbeat) period per replica")
    ap.add_argument("--heartbeat-misses", type=int, default=10,
                    help="consecutive missed polls before a replica is "
                         "declared dead and leaves the fleet")
    ap.add_argument("--wedge-age-s", type=float, default=30.0,
                    help="polled pump_last_step_age_s past which the "
                         "replica's circuit opens (placement stops)")
    ap.add_argument("--retry-limit", type=int, default=2,
                    help="max transparent re-placements of a "
                         "never-streamed request after replica failures")
    ap.add_argument("--disagg-min-prompt", type=int, default=0,
                    help="disaggregated prefill/decode: prompts at least "
                         "this long place on a prefill-role replica and "
                         "kv_push to a decode-role one (0 = auto: one KV "
                         "page; negative = never; only fires while both "
                         "role tiers are placeable — docs/serving.md)")
    ap.add_argument("--postmortem-dir", default="",
                    help="arm the flight recorder: total-fleet-unhealthy "
                         "or a client dump frame freezes an atomic "
                         "bundle here")
    ap.add_argument("--trace-out", default="",
                    help="enable router-side distributed tracing "
                         "(ingress/place/relay/retry spans carrying "
                         "trace ids); spans written as JSONL here on "
                         "EVERY exit path — clean drain, crash, SIGTERM "
                         "— ready for tools/trace_dump.py --merge")
    # client mode
    ap.add_argument("--client", default="",
                    help="HOST:PORT — run as a one-shot client instead")
    ap.add_argument("--stats", action="store_true",
                    help="with --client: print the fleet stats frame "
                         "(the default op)")
    ap.add_argument("--metrics", action="store_true",
                    help="with --client: print the Prometheus text")
    ap.add_argument("--history", action="store_true",
                    help="with --client: print the health-plane "
                         "time-series ring (the `history` RPC)")
    ap.add_argument("--aggregate", action="store_true",
                    help="with --metrics/--history: the FLEET view — "
                         "router series plus every replica's under "
                         "replica=\"rN\" labels")
    ap.add_argument("--last-s", type=float, default=0.0,
                    help="with --history: trailing window in seconds "
                         "(0 = full retention)")
    ap.add_argument("--watch", type=float, default=0.0,
                    help="with --history: re-poll every N seconds as a "
                         "compact live view (tools/obs_top.py is the "
                         "full dashboard)")
    ap.add_argument("--dump", action="store_true",
                    help="with --client: freeze a fleet postmortem "
                         "bundle and print its path")
    args = ap.parse_args(argv)
    if args.client:
        return run_client(args)
    return asyncio.run(amain(args))


if __name__ == "__main__":
    sys.exit(main())
