"""Prove the tensor-parallel decode step never all-gathers the KV pools.

Sibling of tools/hlo_sparse_check.py, for the serving engine's sharded
decode (docs/serving.md "Sharded decode"): with the mesh `model` axis
partitioning attention heads and the per-layer KV page pools, the ONLY
acceptable cross-device traffic in a decode step is the post-attention
all-reduce (the Megatron out-projection meeting its row-sharded partial
sums) — GSPMD deciding instead to all-gather a pool (reassembling every
head's pages on every chip) or an attention projection would silently
forfeit both the HBM win (a model bigger than one chip) and the FLOPs win
(decode faster than one chip) that sharding exists for.

This tool compiles the REAL engine's decode, mixed, speculative-verify,
multi-step scan AND batched draft programs (the lax.scan of k decode
bodies — its body appears ONCE in the HLO, as a while loop, so the
all-reduce count must match a single body, not k of them; the
ModelDrafter's draft step must lower with ZERO collectives — its params
are replicated by contract, so any cross-device op means the
replication boundary broke) over an N-device mesh,
inventories every collective in the optimized HLO, flags
any all-gather whose shape+gather-dim matches a KV pool (kv-head axis),
an attention projection, a Megatron-split FFN weight, or the row-sharded
LM head (each on its sharded axis) — the same shape-anchored detector
hlo_sparse_check uses — and prints a JSON verdict.  The expected
all-reduce count is derived from what the engine actually sharded: one
per attention layer (w_o row split) + one per FFN pair (down-projection
row split) + one for the LM head's partial logits.  Run under the
virtual CPU mesh (the SPMD partitioning decision is backend-agnostic):

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/hlo_shard_check.py [--model 2] [--save PATH.hlo]

Exit 0 = clean (no pool/param all-gather), 2 = violation.  Wired into
tier-1 via tests/test_tools.py.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.hlo_sparse_check import gather_spans_table  # noqa: E402


def _collectives(hlo: str):
    """Inventory collective ops (async -start/-done pairs count once);
    returns ({op: count}, [all-gather lines], [all-reduce lines])."""
    colls: dict[str, int] = {}
    gathers, reduces = [], []
    for ln in hlo.splitlines():
        m = re.search(r"(all-gather|all-reduce|reduce-scatter|"
                      r"all-to-all|collective-permute)(-start|-done)?\(", ln)
        if not m or m.group(2) == "-done":
            continue
        op = m.group(1)
        colls[op] = colls.get(op, 0) + 1
        if op == "all-gather":
            gathers.append(ln.strip())
        elif op == "all-reduce":
            reduces.append(ln.strip())
    return colls, gathers, reduces


def run_check(model: int = 2, config_args: str = "vocab=61,dim=32,"
              "layers=2,heads=4,batch_size=4", save: str = "") -> dict:
    """Compile the sharded decode + mixed steps and return the verdict
    dict (see module docstring).  Needs >= `model` local devices."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parallel.mesh import model_mesh
    from paddle_tpu.serving import Request, ServingEngine
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config("demo/model_zoo/transformer_lm.py", config_args)
    tr = Trainer(cfg, seed=1)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64, spec_k=2, mesh=model_mesh(model))

    # the shapes the tool is anchored to: every KV pool sharded on its
    # kv-head axis (2), every attention projection on its sharded axis,
    # the Megatron FFN pairs (up-projection column 1, down-projection
    # row 0), and the row-sharded LM head — reassembling ANY of them on
    # every chip would forfeit the sharding's HBM/FLOPs split
    tables = []
    pool_shapes = {}
    for name, pool in eng.kv.pools.items():
        pool_shapes[name] = list(pool["k"].shape)
        tables.append((tuple(pool["k"].shape), 2))
    params_sharded = {}

    def _anchor(pn: str, axis: int) -> None:
        tables.append((tuple(eng.params[pn].shape), axis))
        params_sharded[pn] = {"shape": list(eng.params[pn].shape),
                              "sharded_axis": axis}

    for l in tr.executor.model.layers:
        if l.type != "multi_head_attention":
            continue
        names = [l.inputs[i].input_parameter_name for i in range(4)]
        for pn, axis in zip(names, (1, 1, 1, 0)):       # wq wk wv | wo
            _anchor(pn, axis)
    for w1, w2 in eng._tp_ffn_pairs:                    # ffn up | down
        _anchor(w1, 1)
        _anchor(w2, 0)
    if eng._tp_lm_head:
        _anchor(eng._tp_lm_head, 0)                     # vocab projection

    # drive one real request so both compiled paths exist with live state,
    # then lower them exactly as the pump dispatches them
    rng = np.random.default_rng(0)
    eng.add_request(Request("probe", rng.integers(2, 61, 5)
                            .astype(np.int32), max_new=4))
    eng.step()
    eng._sync_run_mask([s for s in range(len(eng.slots))
                        if eng.slots[s] is not None])
    eng._sync_device_state()
    st = eng._build_state()
    hlo_decode = eng._decode_step.lower(
        eng.params, st, eng._d_run).compile().as_text()
    T = eng.max_step_tokens
    S = len(eng.slots)
    z = np.zeros(T, np.int32)
    hlo_mixed = eng._mixed_step.lower(
        eng.params, eng._build_state(), eng._stage(z),
        eng._stage(np.full(T, S, np.int32)), eng._stage(z),
        eng._stage(np.zeros(S, np.int32)),
        eng._stage(np.zeros(S, np.int32)),
        eng._stage(np.zeros(S, bool))).compile().as_text()
    # the speculative VERIFY step is a third sharded program — the one
    # nearly every dispatch runs when --spec-k is on, so its layout
    # discipline needs the same proof as decode/mixed (the chain gather
    # over replicated logits must not tempt GSPMD into anything new)
    hlo_spec = eng._spec_step.lower(
        eng.params, eng._build_state(), eng._stage(z),
        eng._stage(np.full(T, S, np.int32)), eng._stage(z),
        eng._stage(np.zeros(S, np.int32)),
        eng._stage(np.zeros(S, np.int32)),
        eng._stage(np.zeros((S, eng.spec_k), np.int32)),
        eng._stage(np.zeros(S, bool)), eng._stage(np.zeros(S, bool)),
        eng._stage(np.zeros(S, np.int32))).compile().as_text()
    # the multi-step SCAN program (decode_steps=k): k decode bodies in
    # ONE lax.scan, which lowers to a while loop whose body appears ONCE
    # in the HLO — so the proof obligation is identical to decode's
    # (zero pool/param all-gathers, exactly the per-body all-reduce
    # set), NOT k copies of it.  k is a static argument of the jit, so
    # the program lowers without flipping the engine's dispatch mode.
    scan_k = 3
    hlo_scan = eng._scan_step_fn().lower(
        scan_k, eng.params, eng._build_state(), eng._d_run,
        eng._d_eos, eng._d_maxnew).compile().as_text()
    # the batched DRAFT step (ModelDrafter): the drafter's replication
    # contract says it holds host/replicated params and compiles with
    # ZERO collectives under any mesh — drafting must never add
    # cross-device traffic to the verify step it feeds.  Self-spec
    # (from_target) is the strongest case: the TARGET's weights, which
    # the engine DID shard — proving its draft program still lowers
    # collective-free shows the replication boundary holds.
    from paddle_tpu.serving.drafter import ModelDrafter
    drafter = ModelDrafter.from_target(tr.executor, tr.params, window=16)
    draft_k = 2
    hlo_draft = drafter._step.lower(
        drafter.params,
        np.zeros((len(eng.slots), drafter.window + draft_k), np.int32),
        np.ones(len(eng.slots), np.int32), draft_k).compile().as_text()

    # the ONLY acceptable collectives: one post-attention all-reduce per
    # attention layer (Megatron w_o row split), one per sharded FFN pair
    # (down-projection row split), and one for the row-sharded LM head's
    # partial logits — derived from what the engine ACTUALLY sharded, so
    # a divisibility skip can never desynchronize tool and engine
    n_expected = (len(eng.kv.pools) + len(eng._tp_ffn_pairs)
                  + (1 if eng._tp_lm_head else 0))
    out = {"mesh": {"model": model}, "pool_shapes": pool_shapes,
           "sharded_params": params_sharded,
           "ffn_pairs_sharded": len(eng._tp_ffn_pairs),
           "lm_head_sharded": bool(eng._tp_lm_head),
           "scan_decode_steps": scan_k,
           "draft": {"window": drafter.window, "k": draft_k,
                     "kind": drafter.kind}, "steps": {}}
    bad = []
    for step, hlo in (("decode", hlo_decode), ("mixed", hlo_mixed),
                      ("spec", hlo_spec), ("scan", hlo_scan),
                      ("draft", hlo_draft)):
        colls, gathers, reduces = _collectives(hlo)
        table_gathers = [ln[:200] for ln in gathers
                        if gather_spans_table(ln, tables)]
        bad += table_gathers
        if step == "draft" and colls:
            # the draft program's bar is stricter than shape-anchoring:
            # ANY collective means the replicated-drafter contract broke
            bad += [f"draft-step collective: {op} x{n}"
                    for op, n in colls.items()]
        out["steps"][step] = {
            "collectives": colls,
            "n_all_gathers": len(gathers),
            "n_all_reduces": len(reduces),
            "expected_all_reduces": 0 if step == "draft" else n_expected,
            "table_all_gathers": table_gathers,
        }
        if save:
            path = save if step == "decode" else \
                re.sub(r"(\.[^.]*)?$", rf".{step}\1", save, count=1)
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                with open(path, "w") as f:
                    f.write(hlo)
                out["steps"][step]["hlo_saved"] = path
            except OSError:
                pass
    out["verdict"] = (
        "GSPMD all-gathers a sharded KV pool or attention projection — "
        "the tensor-parallel decode forfeits its HBM/FLOPs split" if bad
        else "clean: no KV-pool or attention-param all-gather; only the "
             "post-attention all-reduce crosses devices")
    out["ok"] = not bad
    return out


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", type=int, default=2,
                    help="mesh model-axis size (tensor-parallel shards)")
    ap.add_argument("--config-args",
                    default="vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    ap.add_argument("--save", default=os.path.join(REPO, "MEASURE",
                                                   "serving_tp_step.hlo"))
    args = ap.parse_args()

    import jax

    if len(jax.devices()) < args.model:
        print(json.dumps({"error": f"need {args.model} devices, have "
                          f"{len(jax.devices())} — run with JAX_PLATFORMS="
                          f"cpu XLA_FLAGS=--xla_force_host_platform_"
                          f"device_count={args.model}"}))
        return 1
    out = run_check(args.model, args.config_args, args.save)
    print(json.dumps(out), flush=True)
    return 0 if out["ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
