"""Dispatch-proof micro-bench timing shared by the tools/ benches.

The r4 sweeps timed a Python loop of jitted calls with one
`block_until_ready` at the end; through the axon tunnel that reported
times far beyond the chip's peak FLOP rate (tools/bench_attention.py
docstring has the numbers) — the loop measured dispatch, not compute.
The fix, shared here: run N iterations inside ONE jitted `lax.scan`
whose carry feeds iteration i+1 from iteration i's outputs (gradients
folded back with an eps-scaled add), so a single dispatch covers all N
and XLA cannot elide, dedup, or memoize the repeats; completion is
forced by a host read (float()) of a scalar reduced from the final
carry — the only barrier the tunnel has been observed to honor.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp


def fold(carry, grads, eps: float = 1e-30):
    """carry' = carry + eps*grads, leafwise — the dependency chain that
    keeps every scan iteration live without changing the measured math
    (eps is representable in bf16; the add is elementwise noise)."""
    return jax.tree.map(
        lambda c, g: c + jnp.asarray(eps, c.dtype) * g.astype(c.dtype),
        carry, grads)


def timed_chain(step, carry0, n_steps: int, reps: int = 3) -> float:
    """step: carry -> (carry', scalar).  Returns min seconds per step over
    `reps` single-dispatch runs of an n_steps-long scan (compile excluded:
    the warmup dispatch uses the same static n_steps program)."""
    @functools.partial(jax.jit, static_argnums=1)
    def many(carry, n):
        cf, ss = jax.lax.scan(lambda c, _: step(c), carry, None, length=n)
        leaves = [jnp.sum(x.astype(jnp.float32)) for x in jax.tree.leaves(cf)]
        return jnp.sum(ss) + sum(leaves)

    float(many(carry0, n_steps))           # compile + warmup, same program
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        float(many(carry0, n_steps))
        times.append(time.perf_counter() - t0)
    return min(times) / n_steps


def attn_step_flops(B: int, T: int, H: int, D: int) -> float:
    """fwd (QK^T + PV = 4*B*H*T^2*D) + bwd (~2.5x fwd) — shared by the
    attention bench and the flash tuner so their scan regions are sized
    identically; coarse on purpose (it only sizes the region)."""
    return 3.5 * 4 * B * H * T * T * D


def scan_length(est_step_flops: float, target_ms: float = 250.0,
                assumed_flops: float = 80e12,
                lo: int = 4, hi: int = 1024) -> int:
    """Size the scan so one timed region is >= ~target_ms of device work
    (assumed_flops only sizes the region; it is not reported)."""
    n = int(target_ms / 1e3 * assumed_flops / max(est_step_flops, 1.0))
    return max(lo, min(hi, n))
