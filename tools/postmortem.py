"""Pretty-print a flight-recorder postmortem bundle.

A bundle is the atomic directory the serving front end freezes on pump
death, on the watchdog-wedge threshold, or on an operator `dump` frame
(obs/flight.py; armed via `tools/serve.py --postmortem-dir`) — or the
parameter server freezes on an update-thread wedge / `dump` frame
(tools/pserver.py --snapshot-dir).  The renderer is role-aware: a
pserver bundle (engine.json role "pserver") shows the membership table,
update-thread state and window/commit counters instead of the serving
slots/queue layout:

  python tools/postmortem.py runs/postmortems/postmortem-20260803-101500-123/
  python tools/postmortem.py ... --events 50      # more of the event tail
  python tools/postmortem.py ... --json           # machine-readable dump

Prints: the meta header (reason, when, versions, the error if one was
captured), the engine snapshot (slots, queue, page occupancy), compile
and HBM accounting, headline metrics, and the tail of the structured
event ring.  The bundle's spans.jsonl is tools/trace_dump.py food:

  python tools/trace_dump.py <bundle>/spans.jsonl --summary

Exit codes: 0 ok, 2 on a missing/incomplete bundle (e.g. a `.tmp`
straggler from a dump that crashed mid-write).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.obs.flight import load_bundle  # noqa: E402


def _fmt_bytes(n) -> str:
    if not isinstance(n, (int, float)):
        return str(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


#: eight-level sparkline ramp (tools/obs_top.py's, newest-right)
_SPARK = "▁▂▃▄▅▆▇█"


def _sparkline(values, width: int = 24) -> str:
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[0] * len(vals)
    return "".join(_SPARK[min(len(_SPARK) - 1,
                              int((v - lo) / (hi - lo)
                                  * (len(_SPARK) - 1)))] for v in vals)


def _render_history(bundle: dict) -> list:
    """The health-plane section: ring accounting from history.json plus —
    for an SLO-triggered bundle — each firing objective's offending
    series as a sparkline (the slo_fire flight events name their series
    keys, frozen BEFORE anything died)."""
    hist = bundle.get("history") or {}
    series = hist.get("series") or {}
    if not series:
        return []
    out = [f"history: {len(series)} series, "
           f"{hist.get('samples_taken')} samples at "
           f"{hist.get('resolution_s')}s resolution — history.json"]
    for ev in bundle.get("events") or []:
        if ev.get("kind") != "slo_fire":
            continue
        d = ev.get("data") or {}
        out.append(f"  SLO {d.get('slo', '?')}: value={d.get('value')} "
                   f"{d.get('op', '?')} objective={d.get('objective')}  "
                   f"burn short={d.get('short_burn')} "
                   f"long={d.get('long_burn')}")
        for key in str(d.get("series") or "").split(","):
            ser = series.get(key)
            pts = (ser or {}).get("points") or []
            if not pts:
                continue
            vals = [v for _t, v in pts]
            out.append(f"    {key}")
            out.append(f"      {_sparkline(vals)}  "
                       f"min={min(vals):g} max={max(vals):g} "
                       f"last={vals[-1]:g} n={len(vals)}")
    return out


def _render_pserver(eng: dict) -> list:
    """The pserver half of render(): membership table, update-thread
    state, window/commit/snapshot counters — the engine.json a
    parameter-server bundle carries is its stats frame."""
    out = [f"pserver: shard {eng.get('shard')}/{eng.get('n_shards')} "
           f"mode={eng.get('mode')} "
           f"{'initialized' if eng.get('initialized') else 'UNINITIALIZED'}",
           f"  window={eng.get('window')} version={eng.get('version')} "
           f"pass={eng.get('pass_id')}  blocks={eng.get('blocks')} "
           f"({_fmt_bytes(eng.get('block_bytes'))})"]
    lag = eng.get("update_lag_s")
    alive = eng.get("update_alive")
    state = "alive" if alive else "DEAD"
    if alive and isinstance(lag, (int, float)) and \
            isinstance(eng.get("wedge_threshold_s"), (int, float)) and \
            lag > eng["wedge_threshold_s"]:
        state = "WEDGED"
    out.append(f"  update thread: {state} lag={lag}s "
               f"(wedge threshold {eng.get('wedge_threshold_s')}s)")
    if eng.get("update_error"):
        out.append(f"    error: {eng['update_error']}")
    out.append(f"  pending: {eng.get('pending_grads')} grads, "
               f"{eng.get('pending_barriers')} barriers, "
               f"{eng.get('pending_pass_barriers')} pass barriers"
               + ("  DRAINING" if eng.get("draining") else ""))
    out.append(f"  last window skew: {eng.get('last_skew_ms')}ms "
               f"(straggler threshold {eng.get('straggler_ms')}ms)")
    trainers = eng.get("trainers") or []
    out.append(f"  trainers: {eng.get('trainers_active')} active, "
               f"{eng.get('trainers_draining')} draining")
    for t in trainers:
        out.append(f"    rank {t.get('rank')}  {t.get('tid'):<6} "
                   f"{t.get('state'):<9} grads={t.get('grads_sent')} "
                   f"windows={t.get('windows_joined')}")
    snap = eng.get("snapshot") or {}
    if snap.get("dir"):
        out.append(f"  snapshots: {snap.get('written')} written "
                   f"(every {snap.get('every')} commits) "
                   f"last={snap.get('last_path')}"
                   + ("  IN PROGRESS" if snap.get("in_progress") else ""))
    return out


def render(bundle: dict, n_events: int = 20) -> str:
    meta = bundle["meta"]
    out = [f"postmortem bundle: {bundle['path']}",
           f"  reason:   {meta.get('reason', '?')}",
           f"  when:     {meta.get('ts_iso', '?')} "
           f"(pid {meta.get('pid', '?')} on {meta.get('host', '?')})",
           f"  versions: " + " ".join(
               f"{k}={v}" for k, v in meta.get("versions", {}).items())]
    if meta.get("error"):
        first = str(meta["error"]).strip().splitlines()
        out.append(f"  error:    {first[0]}")
        for line in first[1:6]:
            out.append(f"            {line}")
        if len(first) > 6:
            out.append(f"            ... ({len(first) - 6} more lines)")

    eng = bundle.get("engine") or {}
    if eng.get("role") == "pserver" and "snapshot_error" not in eng:
        out.extend(_render_pserver(eng))
    elif eng and "snapshot_error" not in eng:
        slots = eng.get("slots") or []
        live = [s for s in slots if isinstance(slots, list) and s]
        out.append("engine:")
        out.append(f"  steps={eng.get('n_decode_steps')} "
                   f"tokens={eng.get('tokens_generated')} "
                   f"preempts={eng.get('n_preemptions')} "
                   f"cancelled={eng.get('n_cancelled')} "
                   f"expired={eng.get('n_expired')}")
        if isinstance(slots, list):
            out.append(f"  slots: {len(live)}/{len(slots)} occupied")
            for s in live:
                out.append(f"    [{s['slot']}] {s['req_id']} "
                           f"pos={s['pos']} gen={s['generated']}"
                           f"/{s['max_new']}")
        q = eng.get("queued")
        if isinstance(q, list):
            out.append(f"  queued ({len(q)}): "
                       + (", ".join(map(str, q[:8]))
                          + (" …" if len(q) > 8 else "") if q else "-"))
        out.append(f"  pages: {eng.get('pages_in_use')} in use, "
                   f"{eng.get('free_pages')} free of "
                   f"{eng.get('num_pages')} (page_size "
                   f"{eng.get('page_size')})")
        cw = eng.get("compile_watch") or {}
        if cw:
            out.append("  compile watch:")
            for site, st in cw.items():
                storm = (f"  STORMS={st['storms']}" if st.get("storms")
                         else "")
                out.append(f"    {site:<24} {st['compiles']:>3} compiles "
                           f"{st['signatures']:>3} sigs "
                           f"{st['seconds'] * 1e3:>9.1f}ms{storm}")
        hbm = eng.get("hbm") or {}
        if hbm:
            parts = []
            for k in ("kv_pool_bytes", "param_bytes", "live_array_bytes"):
                if k in hbm:
                    parts.append(f"{k.replace('_bytes', '')}="
                                 f"{_fmt_bytes(hbm[k])}")
            dm = hbm.get("device_memory_stats") or {}
            if "bytes_in_use" in dm:
                parts.append(f"device={_fmt_bytes(dm['bytes_in_use'])}"
                             + (f"/{_fmt_bytes(dm['bytes_limit'])}"
                                if "bytes_limit" in dm else ""))
            if parts:
                out.append("  hbm: " + " ".join(parts))

    metrics = bundle.get("metrics") or {}
    if metrics and "snapshot_error" not in metrics:
        heads = [k for k in ("serving_requests_accepted_total",
                             "serving_overload_total", "pump_alive",
                             "pump_last_step_age_s",
                             "trace_spans_recorded_total",
                             "flight_events_recorded_total")
                 if k in metrics]
        if heads:
            out.append("metrics: " + "  ".join(
                f"{k}={metrics[k]:g}" for k in heads)
                + f"  ({len(metrics)} total — metrics.json)")

    out.extend(_render_history(bundle))

    events = bundle.get("events") or []
    out.append(f"events: {len(events)} retained "
               f"({meta.get('events_dropped', 0)} dropped); last "
               f"{min(n_events, len(events))}:")
    t_ref = meta.get("ts", time.time())
    for ev in events[-n_events:]:
        dt = ev.get("ts", t_ref) - t_ref
        data = ev.get("data") or {}
        kv = " ".join(f"{k}={v}" for k, v in data.items())
        out.append(f"  {dt:>8.3f}s  {ev.get('kind', '?'):<16} {kv}")
    spans = bundle.get("spans") or []
    out.append(f"spans: {len(spans)} in spans.jsonl — "
               f"`python tools/trace_dump.py {bundle['path']}/spans.jsonl "
               f"--summary`")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="postmortem-<ts>-<pid> directory")
    ap.add_argument("--events", type=int, default=20,
                    help="how many tail events to print (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="print the whole bundle as one JSON object")
    args = ap.parse_args(argv)

    try:
        bundle = load_bundle(args.bundle)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(bundle, indent=2, default=str))
        return 0
    print(render(bundle, n_events=args.events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
