"""Capture an xplane profile of the VGG/CIFAR-10 train step on TPU.

Evidence tool for the round-4 regression (VERDICT r5 item 3: 51.4k
samples/s @ 37.1% MFU measured r4 vs 56.7k @ ~41% claimed r2 — same code
paths).  Runs the exact bench_vgg step under `jax.profiler.trace`, banks
the raw xplane under MEASURE/xplane_vgg/, and prints an op-level
breakdown (top self-time HLO ops) so a dead tunnel later cannot lose the
evidence.  The r2 profile's signature to compare against (PERF.md): BN
fusions ~25%, max-pool select-and-scatter ~9%, no single op >4.4%.

Usage: python tools/profile_vgg.py [--iters 30] [--batch 128]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")


def capture(iters: int, batch_size: int, outdir: str) -> dict:
    import jax
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    dtype = os.environ.get("BENCH_DTYPE", "bfloat16")
    cfg = parse_config("demo/image_classification/vgg_16_cifar.py",
                       f"batch_size={batch_size},compute_dtype={dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    batches = []
    for _ in range(2 + iters):
        x = rng.random((batch_size, 3 * 32 * 32), np.float32) - 0.5
        y = rng.integers(0, 10, batch_size).astype(np.int32)
        batches.append({"image": Argument(value=x.astype(np.float32)),
                        "label": Argument(ids=y)})

    # compile + warmup OUTSIDE the trace (same shape as the bench's step)
    stats = tr.benchmark(iter(batches[:4]), warmup=2, iters=2, scan=False)
    t0 = time.perf_counter()
    with jax.profiler.trace(outdir):
        s = tr.benchmark(iter(batches), warmup=0, iters=iters, scan=False)
    wall = time.perf_counter() - t0
    return {"samples_per_sec_unscanned": round(s["samples_per_sec"], 1),
            "trace_wall_s": round(wall, 2), "iters": iters,
            "warmup_samples_per_sec": round(stats["samples_per_sec"], 1)}


def analyze(outdir: str, top: int = 25) -> None:
    """Op-level self-time breakdown straight from the xplane protos — the
    tool-data converters (op_profile etc.) are version-fragile, so walk the
    device plane's events directly."""
    paths = sorted(glob.glob(os.path.join(outdir, "**", "*.xplane.pb"),
                             recursive=True))
    if not paths:
        print(json.dumps({"analyze_error": f"no xplane.pb under {outdir}"}))
        return
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except Exception as e:
        print(json.dumps({"analyze_error": f"xplane_pb2 unavailable "
                          f"({type(e).__name__}); raw profile kept at "
                          + outdir}))
        return

    def collect(plane_pred, line_pred):
        agg: dict[str, float] = {}
        total = 0.0
        for path in paths:
            xspace = xplane_pb2.XSpace()
            with open(path, "rb") as f:
                xspace.ParseFromString(f.read())
            for plane in xspace.planes:
                if not plane_pred(plane.name):
                    continue
                names = {mid: m.name
                         for mid, m in plane.event_metadata.items()}
                for line in plane.lines:
                    if not line_pred(line.name):
                        continue
                    for ev in line.events:
                        dur = ev.duration_ps / 1e12
                        nm = names.get(ev.metadata_id, "?")
                        agg[nm] = agg.get(nm, 0.0) + dur
                        total += dur
        return agg, total

    # TPU: per-op events ride the device plane's "XLA Ops" line; on CPU
    # (smoke-test path) they ride tf_XLA* host thread lines instead
    agg, total = collect(
        lambda p: "TPU" in p or "/device:" in p,
        lambda ln: ln == "XLA Ops")
    if total == 0.0:
        agg, total = collect(lambda p: p == "/host:CPU",
                             lambda ln: ln.startswith("tf_XLA"))
    rows = sorted(agg.items(), key=lambda kv: -kv[1])[:top]
    print(json.dumps({"op_total_s": round(total, 4), "source": paths}))
    for name, sec in rows:
        print(json.dumps({"op": name[:120], "self_s": round(sec, 4),
                          "pct": round(100 * sec / total, 2) if total else 0}),
              flush=True)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--analyze-only", default="")
    ap.add_argument("--outdir",
                    default=os.path.join(REPO, "MEASURE", "xplane_vgg"))
    args = ap.parse_args()
    if args.analyze_only:
        analyze(args.analyze_only)
        return 0
    os.makedirs(args.outdir, exist_ok=True)
    info = capture(args.iters, args.batch, args.outdir)
    print(json.dumps(info), flush=True)
    analyze(args.outdir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
