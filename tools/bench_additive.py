"""Micro-benchmark: fused additive-attention step — pallas kernel vs the
single-expression jnp formulation, inside a scan like the real decoder.

Decides whether graph/layers_attn.py should route simple_attention's
additive_attention_step layer to ops/pallas_additive.py (current default
on TPU) or let XLA fuse the jnp expression.  Mirrors the seq2seq training
shape: the step runs T_dec times inside lax.scan with a dummy carry, fwd
+ bwd, bf16 by default.

Usage: python tools/bench_additive.py [--batch 64] [--enc-len 30]
       [--dec-len 30] [--dim 512] [--reps 3] [--dtype bfloat16]
Prints one JSON line per implementation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def bench_impl(name, step_fn, args, dec_len, reps):
    """Times the full fwd+bwd decoder-scan step with the dispatch-proof
    chained-scan harness (tools/_scan_bench.py) — the r4 numbers from the
    old block_until_ready loop were physically impossible (0.028 ms for
    ~10 GFLOP of work) and are superseded."""
    from _scan_bench import fold, scan_length, timed_chain

    dec0, w, v, proj, seq, mask = args
    B, T, D = proj.shape

    def train_step(carry):
        w, v, proj, seq = carry

        # grads w.r.t. proj/seq too: in real training the encoder states
        # are computed from trained params, and their per-step [B, T, D]
        # cotangent accumulation is the bandwidth-heavy half of backward —
        # eliding it would bias the kernel-routing decision
        def loss(w, v, proj, seq):
            def body(c, _):
                ctxv = step_fn(c, w, v, proj, seq, mask)
                # small mixing matmul stands in for the GRU: the carry must
                # depend on the context so the scan is sequential like the
                # real decoder
                new = jnp.tanh(ctxv @ w[: ctxv.shape[-1], : c.shape[-1]]
                               + c)
                return new, jnp.sum(ctxv.astype(jnp.float32))
            _, outs = jax.lax.scan(body, dec0, None, length=dec_len)
            return jnp.sum(outs)
        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2, 3))(w, v, proj, seq)
        return fold(carry, g), l

    # fwd ~ dec_len * (two [B,D]x[D,D] matmuls + score/context reads);
    # bwd ~2.5x — coarse, only sizes the scan
    est = 3.5 * dec_len * (4 * B * D * D + 6 * B * T * D)
    n_steps = scan_length(est)
    dt = timed_chain(train_step, (w, v, proj, seq), n_steps, reps)
    return {"impl": name, "n_steps": n_steps,
            "ms_per_step": round(dt * 1e3, 3),
            "samples_per_sec": round(dec0.shape[0] / dt, 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--enc-len", type=int, default=30)
    ap.add_argument("--dec-len", type=int, default=30)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    from paddle_tpu.ops import pallas_additive
    from paddle_tpu.ops.attention import additive_attention_step as jnp_step

    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    B, T, D = args.batch, args.enc_len, args.dim
    dec0 = jnp.asarray(rng.normal(size=(B, D)), dt)
    w = jnp.asarray(rng.normal(size=(D, D)) * 0.05, dt)
    v = jnp.asarray(rng.normal(size=(D,)), dt)
    proj = jnp.asarray(rng.normal(size=(B, T, D)), dt)
    seq = jnp.asarray(rng.normal(size=(B, T, D)), dt)
    lens = rng.integers(T // 2, T + 1, B).astype(np.int32)
    mask = jnp.arange(T)[None, :] < jnp.asarray(lens)[:, None]

    impls = {"jnp_fused": jnp_step}
    if pallas_additive.supported():
        impls["pallas"] = pallas_additive.additive_attention_step

    for name, fn in impls.items():
        try:
            res = bench_impl(name, fn, (dec0, w, v, proj, seq, mask),
                             args.dec_len, args.reps)
            print(json.dumps(res))
        except Exception as e:
            print(json.dumps({"impl": name,
                              "error": f"{type(e).__name__}: {e}"}))


if __name__ == "__main__":
    main()
