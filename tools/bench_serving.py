"""Continuous-batching serving benchmark — mixed-length Poisson-arrival
workload through serving/engine.py.

Timing discipline (tools/_scan_bench.py's lessons applied to a host-driven
engine): the scheduler IS a host loop and every decode step already ends in
a host read of the sampled tokens — that read is the only completion
barrier the axon tunnel has been observed to honor, so per-step timing can
never report beyond-hardware numbers the way an unsynced dispatch loop
does.  What DOES need guarding is compile time: a full warmup pass drives
the same request mix through the engine first, so every prefill bucket and
the ONE decode signature are compiled before the timed region (asserted:
the decode jit cache must not grow during measurement).

Two modes per row:
  * --rate 0 (default): all requests arrive at t=0 — closed loop, peak
    tokens/sec at full slot pressure;
  * --rate R: open-loop Poisson arrivals at R requests/sec — tokens/sec at
    that offered load plus the mean slot occupancy (the capacity-planning
    curve PERF.md's serving section reads).

One JSON line per measurement, MEASURE/-compatible.

Usage:
  python tools/bench_serving.py                       # TPU-sized defaults
  python tools/bench_serving.py --rate 2,8,32         # occupancy curve
  python tools/bench_serving.py --num-requests 6 --slots 2 ... (rehearse)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_requests(n: int, prompt_lo: int, prompt_hi: int, max_new: int,
                  vocab: int, seed: int = 0, eos_id: int = -1):
    """Mixed-length request set: prompt lengths uniform in
    [prompt_lo, prompt_hi] (spanning several feeder buckets), greedy
    decode (throughput does not depend on token values)."""
    import numpy as np

    from paddle_tpu.serving import Request

    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        p = int(rng.integers(prompt_lo, prompt_hi + 1))
        prompt = rng.integers(2, vocab, p).astype(np.int32)
        reqs.append(Request(f"r{seed}_{i}", prompt, max_new=max_new,
                            eos_id=eos_id))
    return reqs


def make_prefix_prompts(n: int, prefix_pool: int, prefix_len: int,
                        prefix_skew: float, suffix_lo: int, suffix_hi: int,
                        vocab: int, pool_seed: int = 0, seed: int = 0):
    """Raw prompts for the prefix-skew workload: each draws one of
    `prefix_pool` shared system-prompt prefixes (Zipf-distributed
    popularity, exponent `prefix_skew` — rank k with probability
    ∝ 1/(k+1)^skew) and appends a per-request unique suffix.  The POOL is
    seeded by `pool_seed` alone so every rep shares the same prefixes
    (that sharing IS the workload); draws and suffixes vary with `seed`.
    Shared by the engine-level A/B (Request objects) and the fleet bench
    (client prompts over the wire)."""
    import numpy as np

    pool_rng = np.random.default_rng(pool_seed)
    prefixes = [pool_rng.integers(2, vocab, prefix_len).astype(np.int32)
                for _ in range(prefix_pool)]
    rng = np.random.default_rng(seed)
    w = 1.0 / np.arange(1, prefix_pool + 1, dtype=np.float64) ** prefix_skew
    w /= w.sum()
    prompts = []
    for _ in range(n):
        k = int(rng.choice(prefix_pool, p=w))
        s = int(rng.integers(suffix_lo, suffix_hi + 1))
        prompts.append(np.concatenate(
            [prefixes[k], rng.integers(2, vocab, s).astype(np.int32)]))
    return prompts


def make_prefix_requests(n: int, prefix_pool: int, prefix_len: int,
                         prefix_skew: float, suffix_lo: int, suffix_hi: int,
                         max_new: int, vocab: int, pool_seed: int = 0,
                         seed: int = 0, eos_id: int = -1):
    """make_prefix_prompts wrapped as engine Request objects."""
    from paddle_tpu.serving import Request

    prompts = make_prefix_prompts(n, prefix_pool, prefix_len, prefix_skew,
                                  suffix_lo, suffix_hi, vocab,
                                  pool_seed=pool_seed, seed=seed)
    return [Request(f"p{seed}_{i}", prompt, max_new=max_new, eos_id=eos_id)
            for i, prompt in enumerate(prompts)]


def make_heavytail_requests(n: int, prompt_lo: int, prompt_hi: int,
                            max_new: int, vocab: int, seed: int = 0,
                            eos_id: int = -1, tail_frac: float = 0.1):
    """Heavy-tail prompt-length workload (the head-of-line-blocking
    adversary chunked prefill exists for): most prompts are short —
    lognormal body around `prompt_lo` — but `tail_frac` of them draw a
    Pareto tail reaching `prompt_hi` (a few multi-thousand-token prompts
    amid short ones at production shapes).  Greedy decode; lengths clamp
    to [2, prompt_hi] so every request fits the configured pool."""
    import numpy as np

    from paddle_tpu.serving import Request

    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        if rng.random() < tail_frac:
            p = prompt_lo * (1.0 + rng.pareto(1.1))      # heavy tail
        else:
            p = rng.lognormal(np.log(max(prompt_lo, 2)), 0.5)
        p = int(np.clip(p, 2, prompt_hi))
        prompt = rng.integers(2, vocab, p).astype(np.int32)
        reqs.append(Request(f"h{seed}_{i}", prompt, max_new=max_new,
                            eos_id=eos_id))
    return reqs


def make_repetitive_requests(n: int, prompt_lo: int, prompt_hi: int,
                             max_new: int, vocab: int, seed: int = 0,
                             motif_lo: int = 4, motif_hi: int = 12,
                             eos_id: int = -1):
    """Locally-repetitive prompts — the workload speculative decoding
    targets: each prompt tiles a short random motif to a mixed length
    (the structure of templated text, code, and retrieval contexts,
    where the next tokens often repeat an earlier span).  Greedy decode
    (spec changes steps-per-token, never the tokens), eos off so every
    request emits exactly max_new and the drafted/accepted/emitted
    reconciliation is exact."""
    import numpy as np

    from paddle_tpu.serving import Request

    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(n):
        m = int(rng.integers(motif_lo, motif_hi + 1))
        p = int(rng.integers(prompt_lo, prompt_hi + 1))
        motif = rng.integers(2, vocab, m).astype(np.int32)
        prompt = np.tile(motif, -(-p // m))[:p]
        reqs.append(Request(f"s{seed}_{i}", prompt, max_new=max_new,
                            eos_id=eos_id))
    return reqs


def poisson_arrivals(n: int, rate: float, seed: int = 0):
    """Arrival offsets (seconds from t0): exponential gaps at `rate`
    req/s; rate <= 0 -> everything at t=0 (closed loop)."""
    import numpy as np

    if rate <= 0:
        return np.zeros(n)
    rng = np.random.default_rng(seed)
    return np.cumsum(rng.exponential(1.0 / rate, n))


def run_workload(engine, requests, arrivals=None) -> dict:
    """Drive one workload to completion; returns wall seconds, generated
    tokens, mean occupancy over the steps of THIS run, decode steps,
    preemptions — plus the raw latency samples: `step_seconds` (duration
    of every busy decode step = the inter-token latency each live request
    observed on it) and `req_seconds` (admission -> finish per request,
    via the engine's on_finish hook).  The per-step host token read is the
    sync barrier."""
    import numpy as np

    arrivals = np.zeros(len(requests)) if arrivals is None else arrivals
    order = np.argsort(arrivals, kind="stable")
    requests = [requests[i] for i in order]
    arrivals = arrivals[order]
    tok0 = engine.tokens_generated
    step0 = engine.n_decode_steps
    occ0 = engine.occupancy_sum
    pre0 = engine.n_preemptions
    hit0, miss0 = engine.n_prefix_hits, engine.n_prefix_misses
    saved0 = engine.prefill_tokens_saved
    evict0 = engine.prefix.n_evictions if engine.prefix else 0
    cow0 = engine.kv.n_cow
    t_add: dict = {}
    req_seconds: list = []
    step_seconds: list = []
    first_tok_seconds: list = []
    prev_finish = engine.on_finish
    prev_token = engine.on_token

    def _on_finish(rid, toks, reason):
        if rid in t_add:
            req_seconds.append(time.perf_counter() - t_add.pop(rid))
        if prev_finish is not None:
            prev_finish(rid, toks, reason)

    seen_first: set = set()
    itl_seconds: list = []
    last_t: dict = {}
    last_idx: dict = {}
    bleft: dict = {}
    bshare: dict = {}

    def _on_token(rid, tok, idx):
        now = time.perf_counter()
        # index 0 = the prefill-sampled token: admission -> first token is
        # the latency prefix caching exists to cut.  A preempted request's
        # re-admission REPLAYS idx 0 (the engine re-fires on_token for the
        # deterministic restart) — only the first occurrence is the
        # request's real first-token latency, so dedup by rid.
        if idx == 0 and rid in t_add and rid not in seen_first:
            seen_first.add(rid)
            first_tok_seconds.append(now - t_add[rid])
        # burst bookkeeping counts EVERY banked token (replays included —
        # within one burst replayed indexes precede fresh ones), the same
        # discipline the server's token_latency stat uses: at
        # decode_steps=k one scan flush banks up to k tokens per slot in
        # one on_token volley, so each fresh token in the burst owns an
        # equal 1/burst share of the gap since the request's previous
        # fresh token — without it the ITL percentiles of a k>1 run
        # would read k-times bursty against a k=1 run
        if bleft.get(rid, 0) > 0:
            bleft[rid] -= 1
        else:                                  # first token of a new burst
            bleft[rid] = max(1, int(getattr(engine, "cur_burst", 1))) - 1
            bshare[rid] = -1.0
        # inter-token latency as the CLIENT sees it: the gap between a
        # request's consecutive FRESH tokens — the p99 of this is what
        # chunked prefill bounds.  Replayed tokens (idx <= last seen) are
        # dropped and do not advance the clock, so a preempt+replay stall
        # charges one honest big gap at the first fresh token (the same
        # t_last discipline the server's stats use).
        prev = last_idx.get(rid, -1)
        if idx > prev:
            if prev >= 0:
                if bshare[rid] < 0.0:
                    # first FRESH token since last_t: the gap covers this
                    # token plus the bleft still to come (all fresh —
                    # replays sort first within a burst)
                    bshare[rid] = (now - last_t[rid]) / (bleft[rid] + 1)
                itl_seconds.append(bshare[rid])
            last_t[rid] = now
            last_idx[rid] = idx
        if prev_token is not None:
            prev_token(rid, tok, idx)

    engine.on_finish = _on_finish
    engine.on_token = _on_token
    i, n = 0, len(requests)
    t0 = time.perf_counter()
    try:
        while True:
            now = time.perf_counter() - t0
            while i < n and arrivals[i] <= now:
                t_add[requests[i].req_id] = time.perf_counter()
                engine.add_request(requests[i])
                i += 1
            ts = time.perf_counter()
            busy = engine.step()
            if busy:
                step_seconds.append(time.perf_counter() - ts)
            else:
                if i >= n:
                    break
                time.sleep(min(max(arrivals[i] - (time.perf_counter() - t0),
                                   0.0), 0.05))
    finally:
        engine.on_finish = prev_finish
        engine.on_token = prev_token
    dt = time.perf_counter() - t0
    steps = engine.n_decode_steps - step0
    hits = engine.n_prefix_hits - hit0
    misses = engine.n_prefix_misses - miss0
    return {
        "seconds": dt,
        "tokens": engine.tokens_generated - tok0,
        "decode_steps": steps,
        "occupancy": (engine.occupancy_sum - occ0) / steps if steps else 0.0,
        "preemptions": engine.n_preemptions - pre0,
        "step_seconds": step_seconds,
        "req_seconds": req_seconds,
        "first_tok_seconds": first_tok_seconds,
        "itl_seconds": itl_seconds,
        "prefix_hits": hits,
        "prefix_misses": misses,
        "prefix_hit_rate": hits / (hits + misses) if hits + misses else 0.0,
        "prefill_tokens_saved": engine.prefill_tokens_saved - saved0,
        "prefix_evictions": (engine.prefix.n_evictions if engine.prefix
                             else 0) - evict0,
        "prefix_cow": engine.kv.n_cow - cow0,
    }


def warm_workload(engine, request_sets) -> None:
    """Compile everything the measured reps will touch BEFORE the timed
    region: run the first set end-to-end (decode signature + its buckets),
    then prefill one 1-token request per bucket any OTHER set needs —
    otherwise a rep whose seed draws a bucket the warmup seed missed pays
    a multi-second jit compile inside its timing window."""
    import numpy as np

    from paddle_tpu.serving import Request

    engine.run(request_sets[0])
    if engine.prefill_chunk is not None:
        # chunked mode has NO length-dependent prefill programs: one
        # workload compiles both signatures (the mixed step while chunks
        # are in flight, the [S,1] decode step once prefill drains)
        return
    seen = set(engine._prefill_cache)
    for reqs in request_sets[1:]:
        for r in reqs:
            b = engine.bucket_for(r.prompt_ids.size)
            if b not in seen:
                seen.add(b)
                engine.run([Request(f"_warm{b}",
                                    np.full(min(b, r.prompt_ids.size), 2,
                                            np.int32), max_new=1)])


def measure_prefix_skew(eng, wl: dict, reps: int, seed: int) -> dict:
    """A/B prefix-cache measurement on ONE engine: the identical
    prefix-skew workload (fresh Request objects each pass, same seeds)
    with the cache OFF, then ON — the off pass is the no-cache baseline
    the acceptance comparison reads.  Closed loop (all requests at t=0):
    arrival jitter would blur the first-token delta the cache exists to
    cut.

    Warmup discipline: the baseline side compiles the cold prefill
    buckets (warm_workload); the cached side then runs every rep set once
    against a warming tree BEFORE its timed reps — that pass compiles the
    suffix-prefill/pack signatures a warm-tree run touches and leaves the
    tree in the steady state production sees.  The decode step must stay
    at ONE signature throughout (reported as `decode_sig_stable`);
    suffix-prefill signature counts are reported, not asserted — which
    (pages, bucket) pairs occur is tree-state dependent by design."""
    import numpy as np

    def sets():
        return [make_prefix_requests(seed=seed + 1 + r, **wl)
                for r in range(reps)]

    eng.set_prefix_cache(False)
    warm_workload(eng, [make_prefix_requests(seed=seed, **wl)] + sets())
    sig0 = eng._decode_step._cache_size()
    base_vals, base_ftok = [], []
    for reqs in sets():
        rec = run_workload(eng, reqs)
        base_vals.append(rec["tokens"] / rec["seconds"])
        base_ftok += rec["first_tok_seconds"]

    eng.set_prefix_cache(True)
    # two warming passes (not timed): the first runs every rep set from a
    # cold tree (mostly misses — donations build the tree), the second
    # runs them again at steady state, compiling the suffix-prefill/pack
    # and COW-copy signatures a WARM-tree rep actually touches — without
    # it the first timed rep pays those compiles inside its window (a
    # cold-start warmup sees misses where the timed rep sees hits)
    for _ in range(2):
        for reqs in sets():
            eng.run(reqs)
    vals, ftok = [], []
    hits = misses = saved = evs = cows = 0
    for reqs in sets():
        rec = run_workload(eng, reqs)
        vals.append(rec["tokens"] / rec["seconds"])
        ftok += rec["first_tok_seconds"]
        hits += rec["prefix_hits"]
        misses += rec["prefix_misses"]
        saved += rec["prefill_tokens_saved"]
        evs += rec["prefix_evictions"]
        cows += rec["prefix_cow"]
    eng.kv.check()
    pct = lambda xs: float(np.percentile(xs, 50)) * 1e3 if xs else 0.0
    return {
        "decode_sig_stable": eng._decode_step._cache_size() == sig0,
        "baseline_tok_per_sec": float(np.median(base_vals)),
        "cached_tok_per_sec": float(np.median(vals)),
        "baseline_first_tok_ms_p50": round(pct(base_ftok), 3),
        "first_tok_ms_p50": round(pct(ftok), 3),
        "hits": hits, "misses": misses,
        "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
        "tokens_saved": saved, "evictions": evs, "cow": cows,
        "suffix_prefill_sigs": len(eng._prefix_prefill_cache),
    }


def measure_spill(eng, wl: dict, reps: int, seed: int,
                  budget: int) -> dict:
    """Host-spill A/B on ONE engine: the identical prefix-skew workload
    (fresh Request objects each pass, same seeds) with the prefix cache ON
    both arms and the spill tier OFF, then ON at `budget` bytes.  The
    caller sizes the page pool BELOW the Zipf working set (--num-pages),
    so the off arm destroys cold prefixes under pressure and re-pays
    their prefill, while the on arm parks them in host RAM and restores
    on the next hit — the hit-rate delta is the number the tier exists
    for.  reset_prefix_cache between arms (drains the host tier too) so
    the on arm starts from the same cold allocator state.

    Warmup discipline matches measure_prefix_skew: warm_workload compiles
    the prefill buckets, then each arm runs every rep set twice untimed —
    the on arm's warming passes populate the host tier and compile the
    per-bucket restore scatter before the timed region.  The decode and
    mixed steps must hold their signatures across BOTH arms (spill work
    is admission-boundary host code, never a new jit) — reported as
    `sig_stable`, the bench's pass/fail verdict together with the
    restored-pages-vs-tokens-saved reconciliation."""
    import numpy as np

    def sets():
        return [make_prefix_requests(seed=seed + 1 + r, **wl)
                for r in range(reps)]

    pct = lambda xs: float(np.percentile(xs, 50)) * 1e3 if xs else 0.0

    eng.set_spill_budget(0)
    warm_workload(eng, [make_prefix_requests(seed=seed, **wl)] + sets())
    sig0 = eng._decode_step._cache_size()
    mixed0 = eng._mixed_step._cache_size()

    arms = {}
    for label, bytes_budget in (("off", 0), ("on", int(budget))):
        eng.reset_prefix_cache()
        eng.set_spill_budget(bytes_budget)
        for _ in range(2):                     # untimed steady-state warmup
            for reqs in sets():
                eng.run(reqs)
        spilled0 = eng.kv.n_spilled
        restored0 = eng.kv.n_restored
        rhit0 = eng.n_restore_hits
        rsaved0 = eng.restore_tokens_saved
        vals, ftok = [], []
        hits = misses = saved = evs = 0
        for reqs in sets():
            rec = run_workload(eng, reqs)
            vals.append(rec["tokens"] / rec["seconds"])
            ftok += rec["first_tok_seconds"]
            hits += rec["prefix_hits"]
            misses += rec["prefix_misses"]
            saved += rec["prefill_tokens_saved"]
            evs += rec["prefix_evictions"]
        eng.kv.check()
        arms[label] = {
            "tok_per_sec": float(np.median(vals)),
            "first_tok_ms_p50": round(pct(ftok), 3),
            "hits": hits, "misses": misses,
            "hit_rate": hits / (hits + misses) if hits + misses else 0.0,
            "tokens_saved": saved, "evictions": evs,
            "spilled_pages": eng.kv.n_spilled - spilled0,
            "restored_pages": eng.kv.n_restored - restored0,
            "restore_hits": eng.n_restore_hits - rhit0,
            "restore_tokens_saved": eng.restore_tokens_saved - rsaved0,
        }
    off, on = arms["off"], arms["on"]
    # every token a restore saved must be backed by a restored page (a
    # restored hit can save at most page_size tokens per restored page)
    reconcile_ok = (on["restored_pages"] > 0
                    and 0 < on["restore_tokens_saved"]
                    <= on["restored_pages"] * eng.kv.page_size)
    return {
        "spill_budget": int(budget),
        "num_pages": int(eng.kv.num_pages),
        "host_pages": int(eng.kv.host_page_count),
        "host_bytes": int(eng.kv.host_bytes),
        "page_nbytes": int(eng.kv.page_nbytes),
        "tok_per_sec": on["tok_per_sec"],
        "off_tok_per_sec": off["tok_per_sec"],
        "first_tok_ms_p50": on["first_tok_ms_p50"],
        "off_first_tok_ms_p50": off["first_tok_ms_p50"],
        "hit_rate": on["hit_rate"], "off_hit_rate": off["hit_rate"],
        "hit_rate_improved": on["hit_rate"] > off["hit_rate"],
        "tokens_saved": on["tokens_saved"],
        "off_tokens_saved": off["tokens_saved"],
        "evictions": on["evictions"], "off_evictions": off["evictions"],
        "spilled_pages": on["spilled_pages"],
        "restored_pages": on["restored_pages"],
        "restore_hits": on["restore_hits"],
        "restore_tokens_saved": on["restore_tokens_saved"],
        "off_spilled_pages": off["spilled_pages"],
        "restore_fn_sigs": len(eng.kv._restore_fns),
        "reconcile_ok": reconcile_ok,
        "sig_stable": (eng._decode_step._cache_size() == sig0
                       and eng._mixed_step._cache_size() == mixed0),
    }


def measure_chunked(eng, wl: dict, reps: int, seed: int,
                    prefill_chunk: int, max_step_tokens=None) -> dict:
    """Chunked-prefill A/B on ONE engine: the identical heavy-tail
    workload (fresh Request objects each pass, same seeds) with chunking
    OFF — legacy whole-prompt bucketed prefill, the head-of-line-blocking
    baseline — then ON.  Closed loop: arrival jitter would blur the
    inter-token tail the chunking exists to bound.

    Reports first-token AND inter-token p50/p99 for both sides (the
    acceptance comparison reads the p99s: a long cold prompt's prefill
    stalls every decoding slot's inter-token latency on the baseline,
    and the budgeted mixed step bounds it), plus tokens/s and the
    signature-stability verdict (the mixed step must hold ONE signature
    and the decode step its one across the timed region)."""
    import numpy as np

    def sets():
        return [make_heavytail_requests(seed=seed + 1 + r, **wl)
                for r in range(reps)]

    def run_reps():
        vals, ftok, itl = [], [], []
        for reqs in sets():
            rec = run_workload(eng, reqs)
            vals.append(rec["tokens"] / rec["seconds"])
            ftok += rec["first_tok_seconds"]
            itl += rec["itl_seconds"]
        return vals, ftok, itl

    def pcts(xs):
        return ([round(float(v) * 1e3, 3)
                 for v in np.percentile(xs, [50, 99])]
                if xs else [0.0, 0.0])

    eng.set_chunking(None)
    warm_workload(eng, [make_heavytail_requests(seed=seed, **wl)] + sets())
    base_vals, base_ftok, base_itl = run_reps()

    eng.set_chunking(prefill_chunk, max_step_tokens)
    warm_workload(eng, [make_heavytail_requests(seed=seed, **wl)])
    decode_sigs = eng._decode_step._cache_size()
    mixed_sigs = eng._mixed_step._cache_size()
    chunks0 = eng.n_prefill_chunks
    vals, ftok, itl = run_reps()
    eng.kv.check()
    b_ft, b_itl = pcts(base_ftok), pcts(base_itl)
    c_ft, c_itl = pcts(ftok), pcts(itl)
    return {
        "sig_stable": (eng._decode_step._cache_size() == decode_sigs
                       and eng._mixed_step._cache_size() == mixed_sigs
                       and mixed_sigs == 1),
        "prefill_chunk": int(eng.prefill_chunk),
        "max_step_tokens": int(eng.max_step_tokens),
        "prefill_chunks": eng.n_prefill_chunks - chunks0,
        "baseline_tok_per_sec": float(np.median(base_vals)),
        "chunked_tok_per_sec": float(np.median(vals)),
        "baseline_first_tok_ms_p50": b_ft[0],
        "baseline_first_tok_ms_p99": b_ft[1],
        "first_tok_ms_p50": c_ft[0], "first_tok_ms_p99": c_ft[1],
        "baseline_itl_ms_p50": b_itl[0], "baseline_itl_ms_p99": b_itl[1],
        "itl_ms_p50": c_itl[0], "itl_ms_p99": c_itl[1],
        "p99_itl_improved": c_itl[1] < b_itl[1],
        "p99_first_tok_improved": c_ft[1] < b_ft[1],
    }


def measure_spec(eng, wl: dict, reps: int, seed: int, spec_k: int) -> dict:
    """Speculative-decoding A/B on ONE engine: the identical
    locally-repetitive workload (fresh Request objects each pass, same
    seeds) with speculation OFF (the sequential baseline) then ON at
    `--spec-k` via set_speculation — emitted tokens are identical by
    construction (tests/test_spec_decode.py's oracle), so the ONLY
    deltas are steps-per-token and wall time.  Closed loop: spec's win
    is raw decode throughput, arrival jitter would only blur it.

    The token budget is pinned ONCE before both arms (chunk + one full
    chain per slot) so the signature sets stay fixed across the A/B.
    Reports tok/s both sides, the accept rate, the raw drafted/accepted
    counters, compiled steps both sides, and `reconcile_ok` — the
    counters must reconcile exactly to tokens emitted: with eos off no
    chain ever truncates, so every chain banks its accepted drafts plus
    one sampled token — `spec_tokens == accepted + chains` — and both
    arms emit the identical n * max_new total."""
    import numpy as np

    def sets():
        return [make_repetitive_requests(seed=seed + 1 + r, **wl)
                for r in range(reps)]

    S = len(eng.slots)
    if eng.prefill_chunk is not None:
        eng.set_chunking(eng.prefill_chunk,
                         eng.prefill_chunk + S * (spec_k + 1))
    eng.set_speculation(0)
    warm_workload(eng, [make_repetitive_requests(seed=seed, **wl)]
                  + sets())
    base_vals, base_steps = [], 0
    for reqs in sets():
        rec = run_workload(eng, reqs)
        base_vals.append(rec["tokens"] / rec["seconds"])
        base_steps += rec["decode_steps"]

    eng.set_speculation(spec_k)
    eng.run(make_repetitive_requests(seed=seed, **wl))  # verify-sig warm
    decode_sigs = eng._decode_step._cache_size()
    spec_sigs = eng._spec_step._cache_size()
    d0, a0 = eng.n_spec_drafted, eng.n_spec_accepted
    c0, t0 = eng.n_spec_chains, eng.n_spec_tokens
    vals, toks, steps = [], 0, 0
    for reqs in sets():
        rec = run_workload(eng, reqs)
        vals.append(rec["tokens"] / rec["seconds"])
        toks += rec["tokens"]
        steps += rec["decode_steps"]
    eng.kv.check()
    drafted = eng.n_spec_drafted - d0
    accepted = eng.n_spec_accepted - a0
    chains = eng.n_spec_chains - c0
    spec_tokens = eng.n_spec_tokens - t0
    base_med, spec_med = float(np.median(base_vals)), float(np.median(vals))
    return {
        "sig_stable": (eng._decode_step._cache_size() == decode_sigs
                       and eng._spec_step._cache_size() == spec_sigs
                       and spec_sigs == 1),
        "spec_k": int(spec_k),
        "max_step_tokens": int(eng.max_step_tokens),
        "baseline_tok_per_sec": base_med,
        "spec_tok_per_sec": spec_med,
        "speedup_vs_baseline": spec_med / base_med if base_med else 0.0,
        "accept_rate": accepted / drafted if drafted else 0.0,
        "drafted": int(drafted),
        "accepted": int(accepted),
        "chains": int(chains),
        "spec_tokens": int(spec_tokens),
        "tokens": int(toks),
        "baseline_decode_steps": int(base_steps),
        "spec_decode_steps": int(steps),
        "reconcile_ok": (spec_tokens == accepted + chains
                         and toks == reps * wl["n"] * wl["max_new"]),
    }


def measure_spec_modes(eng, wl: dict, hwl: dict, reps: int, seed: int,
                       spec_k: int, scan_k: int = 2,
                       tol: float = 0.85) -> dict:
    """Adaptive-speculation A/B on ONE engine: every drafter/depth/mode
    configuration over the SAME two workloads (fresh Request objects per
    pass, same seeds), all through idle-engine knob flips so the
    signature sets stay fixed.  Emitted tokens are identical in every
    arm by construction (greedy, exact verification), so the deltas are
    accept rate, steps-per-token and wall time.

    Workloads: `wl` is the locally-repetitive motif workload speculation
    targets; `hwl` is the heavy-tail NON-repetitive workload where a
    prompt-lookup drafter finds nothing — the separation the
    model-vs-ngram accept A/B exists to show (a draft MODEL still agrees
    with the target there; self-speculation maximally so).

    Arms (median tok/s over `reps` passes each):
      off_rep     spec 0, steps 1        — sequential baseline
      ngram_rep   spec K, ngram, static  — the PR-12 configuration
      model_rep   spec K, model, static  — batched draft-model dispatch
      scan_heavy  spec 0, steps scan_k   — multi-step baseline
      ngram_heavy / model_heavy          — the accept-rate A/B
      auto_rep / auto_heavy              — spec K model + dynamic k +
                                           decode_steps scan_k, mode auto

    Gates: `accept_model_gt_ngram` (strict, heavy-tail — the drafter
    upgrade's existence proof), `auto_ok_rep` / `auto_ok_heavy` (auto >=
    `tol` x `decode_mode=static` with the SAME spec/scan knobs — the
    pre-choice auto removes must never have been the better choice; tol
    absorbs CPU-host timing noise — at small rehearse scales the
    same-knob ratio sits near 0.9 with several-percent jitter, so the
    default leaves real margin), `sig_stable` (ONE draft signature
    across every model arm, verify/scan signatures unmoved by
    dynamic/auto) and `reconcile_ok` (every arm emitted exactly
    reps * n * max_new tokens).  The spec-OFF medians ride along
    unguarded: on a CPU host the draft rollout costs as much as the
    target step it saves, so spec-on wall time trails spec-off there —
    the same dispatch-bound caveat as the multi-step bench (PERF.md
    'Reading the multi-step bench'); the hardware queue carries the
    real comparison."""
    import numpy as np

    from paddle_tpu.serving.drafter import ModelDrafter, NgramDrafter

    def rep_sets():
        return [make_repetitive_requests(seed=seed + 1 + r, **wl)
                for r in range(reps)]

    def heavy_sets():
        return [make_heavytail_requests(seed=seed + 101 + r, **hwl)
                for r in range(reps)]

    S = len(eng.slots)
    if eng.prefill_chunk is not None:
        eng.set_chunking(eng.prefill_chunk,
                         eng.prefill_chunk + S * (spec_k + 1))
    # self-speculation from the ENGINE's own executor/params: the
    # strongest drafter available without a training run, and exactly
    # what `--drafter model` deploys
    model = ModelDrafter.from_target(eng.executor, eng.params)
    ngram = NgramDrafter()

    def arm(sets_fn, k, drafter, dynamic, steps, mode):
        eng.set_speculation(k, drafter=drafter, dynamic=dynamic)
        eng.set_decode_steps(steps)
        eng.set_decode_mode(mode)
        warm_workload(eng, sets_fn()[:1])
        d0, a0 = eng.n_spec_drafted, eng.n_spec_accepted
        c0 = eng.n_spec_chains
        vals, toks = [], 0
        for reqs in sets_fn():
            rec = run_workload(eng, reqs)
            vals.append(rec["tokens"] / rec["seconds"])
            toks += rec["tokens"]
        drafted = eng.n_spec_drafted - d0
        chains = eng.n_spec_chains - c0
        return {
            "tok_per_sec": float(np.median(vals)),
            "accept_rate": ((eng.n_spec_accepted - a0) / drafted
                            if drafted else 0.0),
            # mean drafted per chain = the depth the policy actually
            # ran at (k=0 windows draft nothing and open no chain)
            "effective_k": drafted / chains if chains else 0.0,
            "tokens": int(toks),
        }

    arms = {
        "off_rep": arm(rep_sets, 0, None, False, 1, "static"),
        "ngram_rep": arm(rep_sets, spec_k, ngram, False, 1, "static"),
        "model_rep": arm(rep_sets, spec_k, model, False, 1, "static"),
        "scan_heavy": arm(heavy_sets, 0, None, False, scan_k, "static"),
        "ngram_heavy": arm(heavy_sets, spec_k, ngram, False, 1, "static"),
        "model_heavy": arm(heavy_sets, spec_k, model, False, 1, "static"),
        "static_rep": arm(rep_sets, spec_k, model, True, scan_k,
                          "static"),
        "static_heavy": arm(heavy_sets, spec_k, model, True, scan_k,
                            "static"),
        "auto_rep": arm(rep_sets, spec_k, model, True, scan_k, "auto"),
        "auto_heavy": arm(heavy_sets, spec_k, model, True, scan_k,
                          "auto"),
    }
    eng.kv.check()
    from paddle_tpu.obs.compile_watch import get_compile_watch
    draft_sigs = get_compile_watch().signature_count("serving.draft_step")
    best_rep = max(arms[a]["tok_per_sec"]
                   for a in ("off_rep", "ngram_rep", "model_rep"))
    best_heavy = max(arms[a]["tok_per_sec"]
                     for a in ("scan_heavy", "ngram_heavy",
                               "model_heavy"))
    out = {
        "spec_k": int(spec_k), "scan_k": int(scan_k),
        "max_step_tokens": int(eng.max_step_tokens),
        "accept_model_gt_ngram": (arms["model_heavy"]["accept_rate"]
                                  > arms["ngram_heavy"]["accept_rate"]),
        "auto_ok_rep": (arms["auto_rep"]["tok_per_sec"]
                        >= tol * arms["static_rep"]["tok_per_sec"]),
        "auto_ok_heavy": (arms["auto_heavy"]["tok_per_sec"]
                          >= tol * arms["static_heavy"]["tok_per_sec"]),
        "best_static_rep_tok_per_sec": best_rep,
        "best_static_heavy_tok_per_sec": best_heavy,
        # ONE batched draft program serves every model arm — dynamic k
        # and auto mode slice host-side, they never re-lower
        "sig_stable": (draft_sigs == 1
                       and eng._spec_step._cache_size() == 1
                       and eng._decode_step._cache_size() == 1),
        "reconcile_ok": all(
            a["tokens"] == reps * w["n"] * w["max_new"]
            for a, w in ((arms[n], wl) for n in
                         ("off_rep", "ngram_rep", "model_rep",
                          "auto_rep"))) and all(
            arms[n]["tokens"] == reps * hwl["n"] * hwl["max_new"]
            for n in ("scan_heavy", "ngram_heavy", "model_heavy",
                      "auto_heavy")),
    }
    for name, a in arms.items():
        out[f"{name}_tok_per_sec"] = a["tok_per_sec"]
        out[f"{name}_accept_rate"] = round(a["accept_rate"], 4)
        out[f"{name}_effective_k"] = round(a["effective_k"], 3)
    out["ok"] = (out["accept_model_gt_ngram"] and out["auto_ok_rep"]
                 and out["auto_ok_heavy"] and out["sig_stable"]
                 and out["reconcile_ok"])
    return out


def measure_scan(eng, wl: dict, reps: int, seed: int, k: int) -> dict:
    """Multi-step decode A/B on ONE engine: the identical mixed-length
    workload (fresh Request objects each pass, same seeds) at
    decode_steps=1 (one dispatch per token) then decode_steps=k (ONE
    jitted lax.scan of k decode bodies per dispatch whenever every live
    slot is pure-decode) — emitted tokens are identical by construction
    (tests/test_multi_step.py's oracle), so the only deltas are
    dispatches-per-token and wall time.  Closed loop: the scan's win is
    host-dispatch amortization, arrival jitter would only blur it.

    set_decode_steps requires an idle engine — both flips happen between
    run_workload calls, when every slot has drained.  sig_stable pins
    the compiled-program story: the k=1 decode step stays at ONE
    signature across both arms and the scan arm compiles exactly ONE
    scanned program (the body appears ONCE in its HLO, as a while loop).
    reconcile_ok is the ceil(n/k) dispatch evidence: greedy with eos off
    means both arms emit exactly n * max_new tokens, and every scan
    flush advances its slots k steps — `scan_steps == k * scan_flushes`
    with `scan_flushes > 0` (steps where admission/prefill interleaves
    fall back to k=1 and touch neither counter)."""
    import numpy as np

    def sets():
        return [make_requests(seed=seed + 1 + r, **wl)
                for r in range(reps)]

    eng.set_decode_steps(1)
    warm_workload(eng, [make_requests(seed=seed, **wl)] + sets())
    base_vals, base_disp = [], 0
    for reqs in sets():
        rec = run_workload(eng, reqs)
        base_vals.append(rec["tokens"] / rec["seconds"])
        base_disp += rec["decode_steps"]

    eng.set_decode_steps(k)
    eng.run(make_requests(seed=seed, **wl))      # scan-signature warm
    decode_sigs = eng._decode_step._cache_size()
    scan_sigs = eng._scan_step._cache_size() if eng._scan_step else 0
    f0, s0 = eng.n_scan_flushes, eng.n_scan_steps
    vals, toks, disp = [], 0, 0
    for reqs in sets():
        rec = run_workload(eng, reqs)
        vals.append(rec["tokens"] / rec["seconds"])
        toks += rec["tokens"]
        disp += rec["decode_steps"]
    eng.kv.check()
    flushes = eng.n_scan_flushes - f0
    steps = eng.n_scan_steps - s0
    base_med, scan_med = float(np.median(base_vals)), float(np.median(vals))
    return {
        "sig_stable": (eng._decode_step._cache_size() == decode_sigs
                       and eng._scan_step is not None
                       and eng._scan_step._cache_size() == scan_sigs
                       and scan_sigs == 1),
        "decode_steps": int(k),
        "baseline_tok_per_sec": base_med,
        "scan_tok_per_sec": scan_med,
        "speedup_vs_baseline": scan_med / base_med if base_med else 0.0,
        "scan_flushes": int(flushes),
        "scan_steps": int(steps),
        "tokens": int(toks),
        "baseline_decode_steps": int(base_disp),
        "scan_decode_steps": int(disp),
        "reconcile_ok": (flushes > 0 and steps == k * flushes
                         and toks == reps * wl["n"] * wl["max_new"]),
    }


# ---------------------------------------------------------------------------
# fleet bench: one router + N replica SUBPROCESSES (tools/serve.py) vs one
# replica, on the prefix-skew workload, affinity vs random placement
# ---------------------------------------------------------------------------

def _spawn_replica(args, seed: int = 1, role: str = None):
    """One tools/serve.py subprocess built from the SAME model recipe as
    build_engine (identical params across replicas: same config, same
    seed); returns (proc, host, port) once its SERVE_JSON line prints."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    argv = [sys.executable, os.path.join(repo, "tools", "serve.py"),
            "--config", "demo/model_zoo/transformer_lm.py",
            "--config-args",
            f"vocab={args.vocab},dim={args.dim},layers={args.layers},"
            f"heads={args.heads},batch_size={args.slots},"
            f"compute_dtype={args.dtype}",
            "--slots", str(args.slots), "--page-size", str(args.page_size),
            "--max-context", str(args.max_context),
            "--max-queue", "64", "--seed", str(seed), "--port", "0"]
    if role:
        argv += ["--role", role]
    env = dict(os.environ, PYTHONPATH=repo)
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True, cwd=repo,
                            env=env)
    import select

    t0 = time.time()
    while time.time() - t0 < 600:
        # select-gate the pipe: a replica wedged BEFORE printing its bind
        # line (stuck compile, hung backend init) must trip this watchdog,
        # not block readline() until the caller's outer timeout kills the
        # whole bench with no diagnosis
        ready, _, _ = select.select([proc.stdout], [], [], 5.0)
        if not ready:
            if proc.poll() is not None:
                raise RuntimeError(f"replica died before binding (rc="
                                   f"{proc.returncode})")
            continue
        line = proc.stdout.readline()
        if not line and proc.poll() is not None:
            raise RuntimeError(f"replica died before binding (rc="
                               f"{proc.returncode})")
        if line.startswith("SERVE_JSON:"):
            addr = json.loads(line[len("SERVE_JSON:"):])
            return proc, addr["host"], addr["port"]
    proc.kill()
    raise RuntimeError("replica never printed SERVE_JSON within 600s")


def _stop_procs(procs) -> None:
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()           # serve.py's SIGTERM drain path
    for proc in procs:
        try:
            proc.wait(timeout=60)
        except Exception:              # noqa: BLE001 — wedged child
            proc.kill()
            proc.wait(timeout=10)


def run_client_workload(host: str, port: int, prompts, max_new: int,
                        concurrency: int) -> dict:
    """Closed-loop client-side drive: `concurrency` threads, each with
    its own ServingClient connection, pulling prompts off one shared
    list.  Returns wall seconds, generated tokens, first-token p50 (ms),
    and the failure list (must be empty for a valid measurement)."""
    import queue as _queue
    import threading

    from paddle_tpu.serving.client import ServingClient

    work: _queue.Queue = _queue.Queue()
    for i, p in enumerate(prompts):
        work.put((i, [int(t) for t in p]))
    tokens = [0] * max(1, concurrency)
    first_tok: list = []
    failures: list = []
    lock = threading.Lock()

    def worker(wid: int):
        try:
            with ServingClient(host, port, timeout=600) as c:
                while True:
                    try:
                        i, p = work.get_nowait()
                    except _queue.Empty:
                        return
                    t0 = time.perf_counter()
                    seen = []

                    def on_tok(rid, tok, idx, _t0=t0, _seen=seen):
                        if idx == 0:
                            _seen.append(time.perf_counter() - _t0)

                    toks, reason = c.generate(p, max_new=max_new,
                                              on_token=on_tok)
                    tokens[wid] += len(toks) - len(p)
                    with lock:
                        first_tok.extend(seen)
                        if reason not in ("length", "stop"):
                            failures.append(f"req {i}: reason={reason}")
        except Exception as e:             # noqa: BLE001 — a failed
            with lock:                     # worker is a failed bench
                failures.append(f"worker {wid}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=worker, args=(w,))
               for w in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    import numpy as np

    return {"seconds": dt, "tokens": int(sum(tokens)),
            "tok_per_sec": sum(tokens) / dt if dt else 0.0,
            "first_tok_ms_p50": round(float(
                np.percentile(first_tok, 50)) * 1e3, 3) if first_tok
            else 0.0,
            "first_tok_ms_p99": round(float(
                np.percentile(first_tok, 99)) * 1e3, 3) if first_tok
            else 0.0,
            "failures": failures}


def _replica_prefix_counts(addrs) -> tuple[int, int]:
    """Aggregate (prefix_hits, prefix_misses) polled DIRECTLY from each
    replica (the router's stats are fleet-shaped)."""
    from paddle_tpu.serving.client import ServingClient

    hits = misses = 0
    for host, port in addrs:
        with ServingClient(host, port, timeout=60) as c:
            s = c.stats(stale_ok=True)
        hits += int(s.get("prefix_hits") or 0)
        misses += int(s.get("prefix_misses") or 0)
    return hits, misses


def measure_fleet(args) -> dict:
    """The fleet A/B (ISSUE 10): the SAME prefix-skew workload through
    (a) ONE replica, connected directly — the no-router baseline;
    (b) a router + N replica subprocesses, policy=random — fan-out with
        the prefix cache sharded blindly (the placement strawman);
    (c) a router + N replicas, policy=affinity — the KV-aware placement.

    Every arm gets FRESH replica processes (a warm prefix tree from the
    previous arm would corrupt the hit-rate comparison) and an untimed
    warmup pass over a DIFFERENT prefix pool (same shapes: compiles the
    mixed/decode signatures and settles the engines without pre-seeding
    the measured prefixes).  Reported: tokens/s per arm, aggregate
    prefix-cache hit rate per arm (polled from the replicas directly),
    and `affinity_hit_gt_random` — the acceptance comparison: affinity
    routing must beat random routing's hit rate on the same workload."""
    wl = dict(n=args.num_requests, prefix_pool=args.prefix_pool,
              prefix_len=args.prefix_len, prefix_skew=args.prefix_skew,
              suffix_lo=args.suffix_lo, suffix_hi=args.suffix_hi,
              vocab=args.vocab)
    timed_prompts = make_prefix_prompts(pool_seed=args.seed,
                                        seed=args.seed + 1, **wl)
    warm_prompts = make_prefix_prompts(pool_seed=args.seed + 1000,
                                       seed=args.seed + 1001, **wl)

    def one_arm(n_replicas: int, policy, trace_probe: bool = False):
        from paddle_tpu.fleet import FleetRouter

        procs, addrs = [], []
        rt = None
        try:
            for _ in range(n_replicas):
                proc, host, port = _spawn_replica(args)
                procs.append(proc)
                addrs.append((host, port))
            if policy is None:
                host, port = addrs[0]
            else:
                rkw = {}
                if trace_probe:
                    # the probe arm gets a PRIVATE router tracer ring so
                    # flipping it cannot touch the bench process's
                    # global tracer state
                    from paddle_tpu.obs import Tracer

                    rkw["tracer"] = Tracer()
                rt = FleetRouter(port=0, replicas=addrs, policy=policy,
                                 **rkw)
                host, port = rt.start_background()
            warm = run_client_workload(host, port, warm_prompts,
                                       args.max_new, args.concurrency)
            if warm["failures"]:
                raise RuntimeError(f"warmup failed: {warm['failures'][:3]}")
            h0, m0 = _replica_prefix_counts(addrs)
            rec = run_client_workload(host, port, timed_prompts,
                                      args.max_new, args.concurrency)
            h1, m1 = _replica_prefix_counts(addrs)
            dh, dm = h1 - h0, m1 - m0
            rec["prefix_hits"] = dh
            rec["prefix_misses"] = dm
            rec["hit_rate"] = dh / (dh + dm) if dh + dm else 0.0
            if rt is not None:
                from paddle_tpu.serving.client import ServingClient

                with ServingClient(host, port, timeout=60) as c:
                    s = c.stats()
                rec["sheds"] = s["sheds"]
                rec["retries"] = s["retries"]
            if trace_probe and rt is not None:
                # the fleet trace-overhead probe, through the ROUTER
                # path on the SAME fleet (fresh replicas per pass would
                # drown the signal in process jitter — the lesson of
                # bench.py's single-engine probe, which reuses one
                # engine): an off pass and an on pass back to back on
                # the warmed fleet, tracing flipped LIVE between them —
                # the trace RPC's `enable` switch on every replica plus
                # the router's private ring.  Each pass draws a FRESH
                # prefix pool so both see cold measured prefixes.
                # Budget: <= 2% tok/s cost (negative = noise).
                import numpy as np

                from paddle_tpu.serving.client import ServingClient

                def set_tracing(on: bool):
                    for h_, p_ in addrs:
                        with ServingClient(h_, p_, timeout=60) as c:
                            c.trace(pings=1, enable=on)
                    rt.tracer.enabled = on

                # interleaved cycles with ALTERNATING order (off,on then
                # on,off): the fleet keeps warming monotonically across
                # passes (allocator, trees, host JIT), so a fixed order
                # reads the warming trend as tracing cost — alternation
                # cancels a linear drift exactly out of the means
                offs, ons, cycle_pcts = [], [], []
                # probe passes are sized UP from the arm workload (4x,
                # floor 128): the off/on delta is a couple percent at
                # most, so each pass must be long enough that client/
                # thread setup jitter sits well under it
                pwl = dict(wl, n=max(int(wl["n"]) * 4, 128))
                # probe passes SATURATE the fleet (closed loop, enough
                # client threads to keep every slot busy): an
                # underutilized fleet measures OS thread scheduling, not
                # serving throughput — saturation is where a tracing
                # cost would show and where the rate is stable
                pconc = max(args.concurrency, 8)
                # one DISCARDED pass at probe scale first: the arm's
                # warmup ran at workload scale, and the first probe-
                # scale pass is itself a warmup (fuller pools, new
                # allocation pattern) — its transient would otherwise
                # land entirely on whichever side runs first
                run_client_workload(
                    host, port, make_prefix_prompts(
                        pool_seed=args.seed + 1900,
                        seed=args.seed + 1901, **pwl),
                    args.max_new, pconc)
                for cyc in range(max(1, int(getattr(
                        args, "trace_overhead_cycles", 5)))):
                    order = (False, True) if cyc % 2 == 0 \
                        else (True, False)
                    pair = {}
                    for on_pass in order:
                        prompts = make_prefix_prompts(
                            pool_seed=args.seed + 2000 + 10 * cyc
                            + int(on_pass),
                            seed=args.seed + 2500 + 10 * cyc
                            + int(on_pass), **pwl)
                        set_tracing(on_pass)
                        r = run_client_workload(host, port, prompts,
                                                args.max_new, pconc)
                        rec["failures"] = rec["failures"] + r["failures"]
                        pair[on_pass] = r["tok_per_sec"]
                        (ons if on_pass else offs).append(
                            r["tok_per_sec"])
                    if pair.get(False):
                        # per-cycle pairwise overhead: the two passes of
                        # a cycle are adjacent in time, so slow machine
                        # drift cancels within each pair; the MEDIAN
                        # over cycles then discards a contended outlier
                        cycle_pcts.append(
                            100.0 * (pair[False] - pair[True])
                            / pair[False])
                set_tracing(False)
                rec["trace_off_tok_per_sec"] = round(
                    float(np.mean(offs)), 1)
                rec["trace_on_tok_per_sec"] = round(
                    float(np.mean(ons)), 1)
                rec["trace_overhead_pct"] = round(
                    float(np.median(cycle_pcts)), 2) \
                    if cycle_pcts else 0.0
                # per-cycle spread, so a reader can tell a real cost
                # from machine noise (the CPU-rehearse caveat PERF.md
                # applies to every serving number)
                rec["trace_overhead_spread_pct"] = round(
                    float(np.max(cycle_pcts) - np.min(cycle_pcts)), 2) \
                    if cycle_pcts else 0.0
            return rec
        finally:
            if rt is not None:
                rt.stop_background(drain=True)
            _stop_procs(procs)

    single = one_arm(1, None)
    random_arm = one_arm(args.fleet, "random")
    affinity = one_arm(args.fleet, "affinity",
                       trace_probe=getattr(args, "trace_overhead", True))
    ok = not (single["failures"] or random_arm["failures"]
              or affinity["failures"])
    return {
        "fleet": args.fleet,
        "concurrency": args.concurrency,
        "ok": ok,
        "failures": (single["failures"] + random_arm["failures"]
                     + affinity["failures"])[:5],
        "trace_off_tok_per_sec": affinity.get("trace_off_tok_per_sec"),
        "trace_on_tok_per_sec": affinity.get("trace_on_tok_per_sec"),
        "trace_overhead_pct": affinity.get("trace_overhead_pct"),
        "trace_overhead_spread_pct":
            affinity.get("trace_overhead_spread_pct"),
        "tok_per_sec": round(affinity["tok_per_sec"], 1),
        "single_tok_per_sec": round(single["tok_per_sec"], 1),
        "random_tok_per_sec": round(random_arm["tok_per_sec"], 1),
        "speedup_vs_single": round(
            affinity["tok_per_sec"] / single["tok_per_sec"], 3)
        if single["tok_per_sec"] else 0.0,
        "hit_rate_affinity": round(affinity["hit_rate"], 4),
        "hit_rate_random": round(random_arm["hit_rate"], 4),
        "hit_rate_single": round(single["hit_rate"], 4),
        "affinity_hit_gt_random":
            affinity["hit_rate"] > random_arm["hit_rate"],
        "first_tok_ms_p50": affinity["first_tok_ms_p50"],
        "random_first_tok_ms_p50": random_arm["first_tok_ms_p50"],
        "router_sheds": affinity.get("sheds", 0.0),
        "router_retries": affinity.get("retries", 0.0),
    }


# ---------------------------------------------------------------------------
# disaggregated prefill/decode bench: router + 2 colocated role=both
# replicas vs router + 1 prefill-role + 1 decode-role replica, the SAME
# long-prompt workload (docs/serving.md "Disaggregated prefill/decode")
# ---------------------------------------------------------------------------

def measure_disagg(args) -> dict:
    """The disaggregation A/B (ISSUE 19): the SAME prefix-skew workload
    (same seeds, same request budget) through
      (a) colocated — a router over 2 role=both replicas (each request
          prefills AND decodes where it lands);
      (b) disagg — a router over 1 prefill-role + 1 decode-role replica:
          long prompts prefill on one, kv_push their committed pages,
          and decode on the other.
    Every arm gets FRESH replica subprocesses and an untimed warmup over
    a different prefix pool.  Reported: tokens/s + first-token p50/p99
    per arm, and the transfer ledger polled from the disagg router.
    Reconcile gate: zero failed requests in either arm, and the disagg
    arm genuinely shipped pages with zero push failures (a fallback-only
    run would silently measure colocated serving twice)."""
    wl = dict(n=args.num_requests, prefix_pool=args.prefix_pool,
              prefix_len=args.prefix_len, prefix_skew=args.prefix_skew,
              suffix_lo=args.suffix_lo, suffix_hi=args.suffix_hi,
              vocab=args.vocab)
    timed_prompts = make_prefix_prompts(pool_seed=args.seed,
                                        seed=args.seed + 1, **wl)
    warm_prompts = make_prefix_prompts(pool_seed=args.seed + 1000,
                                       seed=args.seed + 1001, **wl)

    def one_arm(roles):
        from paddle_tpu.fleet import FleetRouter
        from paddle_tpu.serving.client import ServingClient

        procs, addrs = [], []
        rt = None
        try:
            for role in roles:
                proc, host, port = _spawn_replica(args, role=role)
                procs.append(proc)
                addrs.append((host, port))
            rt = FleetRouter(port=0, replicas=addrs, policy="affinity")
            host, port = rt.start_background()
            warm = run_client_workload(host, port, warm_prompts,
                                       args.max_new, args.concurrency)
            if warm["failures"]:
                raise RuntimeError(f"warmup failed: {warm['failures'][:3]}")
            rec = run_client_workload(host, port, timed_prompts,
                                      args.max_new, args.concurrency)
            with ServingClient(host, port, timeout=60) as c:
                s = c.stats()
            for k in ("kv_pushes", "kv_push_failures", "kv_fallbacks",
                      "kv_pages_shipped", "sheds", "retries"):
                rec[k] = s[k]
            return rec
        finally:
            if rt is not None:
                rt.stop_background(drain=True)
            _stop_procs(procs)

    coloc = one_arm(["both", "both"])
    disagg = one_arm(["prefill", "decode"])
    ok = (not coloc["failures"] and not disagg["failures"]
          and disagg["kv_pages_shipped"] > 0
          and disagg["kv_push_failures"] == 0
          and disagg["kv_fallbacks"] == 0)
    return {
        "concurrency": args.concurrency,
        "ok": ok,
        "failures": (coloc["failures"] + disagg["failures"])[:5],
        "tok_per_sec": round(disagg["tok_per_sec"], 1),
        "coloc_tok_per_sec": round(coloc["tok_per_sec"], 1),
        "speedup_vs_coloc": round(
            disagg["tok_per_sec"] / coloc["tok_per_sec"], 3)
        if coloc["tok_per_sec"] else 0.0,
        "first_tok_ms_p50": disagg["first_tok_ms_p50"],
        "first_tok_ms_p99": disagg["first_tok_ms_p99"],
        "coloc_first_tok_ms_p50": coloc["first_tok_ms_p50"],
        "coloc_first_tok_ms_p99": coloc["first_tok_ms_p99"],
        "kv_pushes": disagg["kv_pushes"],
        "kv_push_failures": disagg["kv_push_failures"],
        "kv_fallbacks": disagg["kv_fallbacks"],
        "pages_shipped": disagg["kv_pages_shipped"],
        "router_sheds": disagg["sheds"],
        "router_retries": disagg["retries"],
    }


def build_engine(args, mesh=None):
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config(
        "demo/model_zoo/transformer_lm.py",
        f"vocab={args.vocab},dim={args.dim},layers={args.layers},"
        f"heads={args.heads},batch_size={args.slots},"
        f"compute_dtype={args.dtype}")
    tr = Trainer(cfg, seed=1)
    eng = ServingEngine(
        tr.executor, tr.params, num_slots=args.slots,
        page_size=args.page_size, max_context=args.max_context,
        num_pages=(getattr(args, "num_pages", 0) or None),
        spill_bytes_budget=(getattr(args, "spill_budget", 0) or 0),
        prefill_chunk=(getattr(args, "prefill_chunk", 0) or -1),
        max_step_tokens=(getattr(args, "max_step_tokens", 0) or None),
        mesh=mesh)
    return eng


# ---------------------------------------------------------------------------
# tensor-parallel bench: the SAME closed-loop workload on a single-device
# engine vs a mesh model=N sharded engine (docs/serving.md "Sharded decode")
# ---------------------------------------------------------------------------

def measure_tp(args) -> dict:
    """1-vs-N-shard A/B: identical request sets (same seeds) through a
    single-device engine and a tensor-parallel engine over `--mesh-model`
    devices, closed loop.  Reports tokens/s both arms plus the number
    sharding exists for — KV pool bytes resident PER SHARD (the sharded
    arm's per-chip HBM is 1/N of the single-chip pool) — and the
    signature-stability verdict (ONE decode + ONE mixed signature on the
    sharded engine too).  Token exactness across shard counts is
    tests/test_serving_tp.py's job.  On a CPU host run under
    XLA_FLAGS=--xla_force_host_platform_device_count=N (rehearse mode
    sets it); real speedups need real chips."""
    import jax
    import numpy as np

    from paddle_tpu.parallel.mesh import model_mesh

    n = int(args.mesh_model)
    if len(jax.devices()) < n:
        raise RuntimeError(
            f"--mesh-model {n} needs {n} devices, have "
            f"{len(jax.devices())} — on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n}")
    base = dict(n=args.num_requests, prompt_lo=args.prompt_lo,
                prompt_hi=min(args.prompt_hi,
                              args.max_context - args.max_new - 1),
                max_new=args.max_new, vocab=args.vocab)

    def rep_sets():
        return [make_requests(seed=args.seed + 1 + r, **base)
                for r in range(args.reps)]

    arms = {}
    for label, shards in (("single", 1), ("tp", n)):
        eng = build_engine(args,
                           mesh=model_mesh(n) if shards > 1 else None)
        warm_workload(eng, [make_requests(seed=args.seed, **base)]
                      + rep_sets())
        sigs = eng._decode_step._cache_size()
        mixed = eng._mixed_step._cache_size()
        vals = []
        for reqs in rep_sets():
            rec = run_workload(eng, reqs)
            vals.append(rec["tokens"] / rec["seconds"])
        arms[label] = {
            "tok_per_sec": float(np.median(vals)),
            "pool_bytes_per_shard": int(eng.kv.pool_bytes_per_shard),
            "sig_stable": (eng._decode_step._cache_size() == sigs == 1
                           and eng._mixed_step._cache_size() == mixed),
            "tp_shards": eng.tp,
        }
        eng.executor.mesh = None       # arms must not inherit the mesh
    single, tp = arms["single"], arms["tp"]
    return {
        "mesh_model": n,
        "tok_per_sec": tp["tok_per_sec"],
        "single_tok_per_sec": single["tok_per_sec"],
        "speedup_vs_single": (tp["tok_per_sec"] / single["tok_per_sec"]
                              if single["tok_per_sec"] else 0.0),
        "pool_bytes_per_shard": tp["pool_bytes_per_shard"],
        "single_pool_bytes": single["pool_bytes_per_shard"],
        "pool_shrink_vs_single": (
            single["pool_bytes_per_shard"] / tp["pool_bytes_per_shard"]
            if tp["pool_bytes_per_shard"] else 0.0),
        "sig_stable": single["sig_stable"] and tp["sig_stable"],
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-requests", type=int, default=64)
    ap.add_argument("--rate", default="0",
                    help="comma list of offered req/s (0 = closed loop)")
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--max-context", type=int, default=768)
    ap.add_argument("--prompt-lo", type=int, default=32)
    ap.add_argument("--prompt-hi", type=int, default=256)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--seed", type=int, default=0)
    # prefix-skew workload (docs/serving.md "Prefix caching"): Zipf draws
    # over a pool of shared system-prompt prefixes + unique suffixes,
    # measured cache-off then cache-on (closed loop; --rate is ignored)
    ap.add_argument("--prefix-skew", type=float, default=None,
                    metavar="EXP",
                    help="run the prefix-skew A/B workload with this Zipf "
                         "exponent (reports hit rate, prefill tokens "
                         "saved, first-token p50 vs no-cache baseline)")
    ap.add_argument("--prefix-pool", type=int, default=8,
                    help="number of distinct shared prefixes")
    ap.add_argument("--prefix-len", type=int, default=128,
                    help="shared prefix length in tokens")
    ap.add_argument("--suffix-lo", type=int, default=16)
    ap.add_argument("--suffix-hi", type=int, default=64)
    # host KV spill tier (docs/serving.md "KV spill tier"): A/B the same
    # prefix-skew workload with the spill tier off then on — pair with
    # --num-pages sized BELOW the Zipf working set so the off arm is
    # forced to destroy cold prefixes under pool pressure
    ap.add_argument("--spill-budget", type=int, default=0, metavar="BYTES",
                    help="run the host-spill A/B: prefix cache on both "
                         "arms, spill tier off then on at BYTES of host "
                         "RAM (reports hit rate, restored pages, prefill "
                         "tokens saved, first-token p50 both arms)")
    ap.add_argument("--num-pages", type=int, default=0,
                    help="KV page-pool size override incl. trash page "
                         "(0 = engine default; the spill A/B wants this "
                         "below the workload's working set)")
    # chunked prefill (docs/serving.md "Chunked prefill"): --prompt-dist
    # heavy-tail runs the A/B (legacy whole-prompt prefill vs budgeted
    # mixed steps) on a Pareto/lognormal prompt-length workload
    # fleet (docs/serving.md "Fleet"): --fleet N runs the router A/B —
    # one replica direct vs router+N replica subprocesses, prefix-skew
    # workload, affinity vs random placement hit rates
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="run the fleet A/B with N replica subprocesses "
                         "(reports tok/s vs one replica and affinity-vs-"
                         "random prefix hit rates)")
    ap.add_argument("--concurrency", type=int, default=8,
                    help="client threads driving the fleet workload")
    # disaggregated prefill/decode (docs/serving.md "Disaggregated
    # prefill/decode"): --disagg runs the role-split A/B — router + 2
    # colocated role=both replicas vs router + 1 prefill + 1 decode
    # replica with the kv_push page-transfer plane, same seeds/budget
    ap.add_argument("--disagg", action="store_true",
                    help="run the disaggregated prefill/decode A/B "
                         "(reports tok/s + first-token p50/p99 per arm "
                         "and the kv_xfer ledger: pushes, pages shipped, "
                         "failures, fallbacks)")
    ap.add_argument("--no-trace-overhead", dest="trace_overhead",
                    action="store_false", default=True,
                    help="skip the fleet trace-overhead arm (a fourth "
                         "affinity arm with router + replica tracing ON "
                         "through the router path; <= 2%% tok/s budget)")
    ap.add_argument("--prompt-dist", choices=["uniform", "heavy-tail"],
                    default="uniform",
                    help="heavy-tail: lognormal body + Pareto tail prompt "
                         "lengths, measured chunking off vs on (first-"
                         "token and inter-token p50/p99 both sides)")
    ap.add_argument("--prefill-chunk", type=int, default=0,
                    help="chunk size in tokens (0 = engine default, "
                         "4*page_size)")
    ap.add_argument("--max-step-tokens", type=int, default=0,
                    help="per-step token budget (0 = engine default, "
                         "prefill_chunk + slots)")
    # tensor-parallel A/B (docs/serving.md "Sharded decode"): the same
    # closed-loop workload on one device vs a mesh model=N sharded engine
    ap.add_argument("--mesh-model", type=int, default=0, metavar="N",
                    help="run the 1-vs-N-shard A/B: tokens/s + KV pool "
                         "bytes per shard, single-device engine vs "
                         "attention-head/KV-pool sharding over N devices")
    # speculative decoding A/B (docs/serving.md "Speculative decoding"):
    # spec-off vs spec-on at k on ONE engine, locally-repetitive prompts
    ap.add_argument("--spec-k", type=int, default=0, metavar="K",
                    help="run the speculative-decoding A/B: the same "
                         "locally-repetitive workload with speculation "
                         "off then on at K drafts/slot/step (reports "
                         "tok/s both arms, accept rate, drafted/"
                         "accepted counters reconciled to tokens)")
    ap.add_argument("--drafter", choices=["ngram", "model"],
                    default="ngram",
                    help="with --spec-k: 'model' runs the adaptive-"
                         "speculation matrix instead of the plain A/B — "
                         "ngram vs batched draft-model (self-speculation)"
                         " vs decode_mode=auto arms on repetitive AND "
                         "heavy-tail workloads, with the model-vs-ngram "
                         "accept-rate gate")
    ap.add_argument("--spec-dynamic", action="store_true",
                    help="with --spec-k: enable the per-slot dynamic-k "
                         "policy in the auto arms (implies the adaptive "
                         "matrix, like --drafter model)")
    # multi-step decode A/B (docs/serving.md "Multi-step decode"):
    # decode_steps=1 vs ONE scanned dispatch of K decode bodies
    ap.add_argument("--decode-steps", type=int, default=0, metavar="K",
                    help="run the multi-step decode A/B: the same "
                         "closed-loop workload at decode_steps=1 then "
                         "with K scanned decode bodies per dispatch "
                         "(reports tok/s both arms, scan flush/step "
                         "counters reconciled to tokens; on CPU expect "
                         "<=1x — PERF.md 'Reading the multi-step bench')")
    args = ap.parse_args()

    import numpy as np

    if args.mesh_model > 1:
        m = measure_tp(args)
        print(json.dumps({
            "bench": "serving_tp",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prompt_lens": [args.prompt_lo, args.prompt_hi],
            "max_new": args.max_new, "dim": args.dim,
            "layers": args.layers, "heads": args.heads,
            "dtype": args.dtype, "reps": args.reps,
            "lm_serving_tp_tok_per_sec": m["tok_per_sec"],
            **{k: m[k] for k in (
                "mesh_model", "single_tok_per_sec", "speedup_vs_single",
                "pool_bytes_per_shard", "single_pool_bytes",
                "pool_shrink_vs_single", "sig_stable")},
        }), flush=True)
        return 0 if m["sig_stable"] else 1

    if args.disagg:
        if args.prefix_skew is None:
            args.prefix_skew = 1.0     # the disagg A/B rides the prefix-
                                       # skew workload too (long shared
                                       # prompts are what disagg splits)
        m = measure_disagg(args)
        print(json.dumps({
            "bench": "serving_disagg",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prefix_pool": args.prefix_pool, "prefix_len": args.prefix_len,
            "prefix_skew": args.prefix_skew,
            "suffix_lens": [args.suffix_lo, args.suffix_hi],
            "max_new": args.max_new, "dim": args.dim,
            "layers": args.layers, "dtype": args.dtype,
            "lm_serving_disagg_tok_per_sec": m["tok_per_sec"],
            **{k: m[k] for k in (
                "concurrency", "coloc_tok_per_sec", "speedup_vs_coloc",
                "first_tok_ms_p50", "first_tok_ms_p99",
                "coloc_first_tok_ms_p50", "coloc_first_tok_ms_p99",
                "kv_pushes", "kv_push_failures", "kv_fallbacks",
                "pages_shipped", "router_sheds", "router_retries",
                "ok", "failures")},
        }), flush=True)
        return 0 if m["ok"] else 1

    if args.fleet > 0:
        if args.prefix_skew is None:
            args.prefix_skew = 1.0     # --prefix-skew doubles as the
        m = measure_fleet(args)        # engine-A/B trigger; fleet mode
                                       # just needs a Zipf exponent
        print(json.dumps({
            "bench": "serving_fleet",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prefix_pool": args.prefix_pool, "prefix_len": args.prefix_len,
            "prefix_skew": args.prefix_skew,
            "suffix_lens": [args.suffix_lo, args.suffix_hi],
            "max_new": args.max_new, "dim": args.dim,
            "layers": args.layers, "dtype": args.dtype,
            "lm_serving_fleet_tok_per_sec": m["tok_per_sec"],
            "lm_serving_fleet_trace_overhead_pct": m["trace_overhead_pct"],
            **{k: m[k] for k in (
                "fleet", "concurrency", "single_tok_per_sec",
                "random_tok_per_sec", "speedup_vs_single",
                "hit_rate_affinity", "hit_rate_random", "hit_rate_single",
                "affinity_hit_gt_random", "first_tok_ms_p50",
                "random_first_tok_ms_p50", "router_sheds",
                "router_retries", "trace_off_tok_per_sec",
                "trace_on_tok_per_sec", "trace_overhead_spread_pct",
                "ok", "failures")},
        }), flush=True)
        return 0 if m["ok"] else 1

    if args.spec_k > 0 and (args.drafter == "model" or args.spec_dynamic):
        eng = build_engine(args)
        hi = min(args.prompt_hi, args.max_context - args.max_new - 1)
        wl = dict(n=args.num_requests, prompt_lo=args.prompt_lo,
                  prompt_hi=hi, max_new=args.max_new, vocab=args.vocab)
        hwl = dict(wl)
        m = measure_spec_modes(eng, wl, hwl, args.reps, args.seed,
                               args.spec_k)
        print(json.dumps({
            "bench": "serving_spec_modes",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prompt_lens": [args.prompt_lo, hi], "max_new": args.max_new,
            "dim": args.dim, "layers": args.layers, "dtype": args.dtype,
            "reps": args.reps, "drafter": "model",
            "spec_dynamic": True,
            "lm_serving_spec_model_tok_per_sec":
                round(m["model_rep_tok_per_sec"], 1),
            "lm_serving_spec_auto_tok_per_sec":
                round(m["auto_rep_tok_per_sec"], 1),
            "lm_serving_spec_effective_k":
                round(m["auto_rep_effective_k"], 3),
            "lm_serving_spec_model_accept_rate_heavy":
                m["model_heavy_accept_rate"],
            "lm_serving_spec_ngram_accept_rate_heavy":
                m["ngram_heavy_accept_rate"],
            **{k: m[k] for k in sorted(m)},
        }), flush=True)
        return 0 if m["ok"] else 1

    if args.spec_k > 0:
        eng = build_engine(args)
        hi = min(args.prompt_hi, args.max_context - args.max_new - 1)
        wl = dict(n=args.num_requests, prompt_lo=args.prompt_lo,
                  prompt_hi=hi, max_new=args.max_new, vocab=args.vocab)
        m = measure_spec(eng, wl, args.reps, args.seed, args.spec_k)
        print(json.dumps({
            "bench": "serving_spec",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prompt_lens": [args.prompt_lo, hi], "max_new": args.max_new,
            "dim": args.dim, "layers": args.layers, "dtype": args.dtype,
            "reps": args.reps,
            "lm_serving_spec_tok_per_sec": round(m["spec_tok_per_sec"], 1),
            "lm_serving_spec_accept_rate": round(m["accept_rate"], 4),
            **{k: m[k] for k in (
                "spec_k", "max_step_tokens", "baseline_tok_per_sec",
                "speedup_vs_baseline", "drafted", "accepted", "chains",
                "spec_tokens", "tokens", "baseline_decode_steps",
                "spec_decode_steps", "reconcile_ok", "sig_stable")},
        }), flush=True)
        return 0 if m["sig_stable"] and m["reconcile_ok"] else 1

    if args.decode_steps > 1:
        eng = build_engine(args)
        hi = min(args.prompt_hi, args.max_context - args.max_new - 1)
        wl = dict(n=args.num_requests, prompt_lo=args.prompt_lo,
                  prompt_hi=hi, max_new=args.max_new, vocab=args.vocab)
        m = measure_scan(eng, wl, args.reps, args.seed, args.decode_steps)
        print(json.dumps({
            "bench": "serving_scan",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prompt_lens": [args.prompt_lo, hi], "max_new": args.max_new,
            "dim": args.dim, "layers": args.layers, "dtype": args.dtype,
            "reps": args.reps,
            "lm_serving_scan_tok_per_sec": round(m["scan_tok_per_sec"], 1),
            **{k: m[k] for k in (
                "decode_steps", "baseline_tok_per_sec",
                "speedup_vs_baseline", "scan_flushes", "scan_steps",
                "tokens", "baseline_decode_steps", "scan_decode_steps",
                "reconcile_ok", "sig_stable")},
        }), flush=True)
        return 0 if m["sig_stable"] and m["reconcile_ok"] else 1

    if args.spill_budget > 0:
        if args.prefix_skew is None:
            args.prefix_skew = 1.0     # the spill A/B rides the prefix-
                                       # skew workload; default the Zipf
                                       # exponent when only --spill-budget
                                       # is given
        eng = build_engine(args)
        wl = dict(n=args.num_requests, prefix_pool=args.prefix_pool,
                  prefix_len=args.prefix_len, prefix_skew=args.prefix_skew,
                  suffix_lo=args.suffix_lo, suffix_hi=args.suffix_hi,
                  max_new=args.max_new, vocab=args.vocab)
        m = measure_spill(eng, wl, args.reps, args.seed, args.spill_budget)
        print(json.dumps({
            "bench": "serving_spill",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prefix_pool": args.prefix_pool, "prefix_len": args.prefix_len,
            "prefix_skew": args.prefix_skew,
            "suffix_lens": [args.suffix_lo, args.suffix_hi],
            "max_new": args.max_new, "dim": args.dim,
            "layers": args.layers, "dtype": args.dtype, "reps": args.reps,
            "lm_serving_spill_hit_rate": round(m["hit_rate"], 4),
            "lm_serving_spill_tok_per_sec": round(m["tok_per_sec"], 1),
            **{k: m[k] for k in (
                "spill_budget", "num_pages", "host_pages", "host_bytes",
                "page_nbytes", "off_tok_per_sec", "first_tok_ms_p50",
                "off_first_tok_ms_p50", "off_hit_rate",
                "hit_rate_improved", "tokens_saved", "off_tokens_saved",
                "evictions", "off_evictions", "spilled_pages",
                "restored_pages", "restore_hits", "restore_tokens_saved",
                "off_spilled_pages", "restore_fn_sigs", "reconcile_ok",
                "sig_stable")},
        }), flush=True)
        return 0 if (m["sig_stable"] and m["reconcile_ok"]
                     and m["hit_rate_improved"]) else 1

    eng = build_engine(args)
    if args.prompt_dist == "heavy-tail":
        # the tail must FIT the pool: clamp at slot capacity minus the
        # decode budget (validate() would reject anything bigger anyway)
        hi = min(args.prompt_hi, args.max_context - args.max_new - 1)
        wl = dict(n=args.num_requests, prompt_lo=args.prompt_lo,
                  prompt_hi=hi, max_new=args.max_new, vocab=args.vocab)
        m = measure_chunked(eng, wl, args.reps, args.seed,
                            args.prefill_chunk or 4 * args.page_size,
                            args.max_step_tokens or None)
        print(json.dumps({
            "bench": "serving_chunked",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prompt_lens": [args.prompt_lo, hi], "max_new": args.max_new,
            "dim": args.dim, "layers": args.layers, "dtype": args.dtype,
            "reps": args.reps,
            "lm_serving_p99_itl_chunked_ms": m["itl_ms_p99"],
            **{k: m[k] for k in (
                "prefill_chunk", "max_step_tokens", "prefill_chunks",
                "baseline_itl_ms_p50", "baseline_itl_ms_p99",
                "itl_ms_p50",
                "baseline_first_tok_ms_p50", "baseline_first_tok_ms_p99",
                "first_tok_ms_p50", "first_tok_ms_p99",
                "baseline_tok_per_sec", "chunked_tok_per_sec",
                "p99_itl_improved", "p99_first_tok_improved",
                "sig_stable")},
        }), flush=True)
        return 0 if m["sig_stable"] else 1
    if args.prefix_skew is not None:
        wl = dict(n=args.num_requests, prefix_pool=args.prefix_pool,
                  prefix_len=args.prefix_len, prefix_skew=args.prefix_skew,
                  suffix_lo=args.suffix_lo, suffix_hi=args.suffix_hi,
                  max_new=args.max_new, vocab=args.vocab)
        m = measure_prefix_skew(eng, wl, args.reps, args.seed)
        # configured prefix share of the prompt tokens — the number the
        # tokens-saved rate should track (PERF.md "reading the hit rate")
        share = args.prefix_len / (
            args.prefix_len + (args.suffix_lo + args.suffix_hi) / 2.0)
        print(json.dumps({
            "bench": "serving_prefix",
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prefix_pool": args.prefix_pool, "prefix_len": args.prefix_len,
            "prefix_skew": args.prefix_skew,
            "suffix_lens": [args.suffix_lo, args.suffix_hi],
            "max_new": args.max_new, "dim": args.dim,
            "layers": args.layers, "dtype": args.dtype, "reps": args.reps,
            "prefix_share_configured": round(share, 3),
            "lm_serving_prefix_hit_rate": round(m["hit_rate"], 4),
            "lm_serving_prefill_tokens_saved_total": m["tokens_saved"],
            "first_tok_ms_p50": m["first_tok_ms_p50"],
            "baseline_first_tok_ms_p50": m["baseline_first_tok_ms_p50"],
            "tokens_per_sec_median": round(m["cached_tok_per_sec"], 1),
            "baseline_tokens_per_sec_median":
                round(m["baseline_tok_per_sec"], 1),
            "prefix_evictions": m["evictions"], "prefix_cow": m["cow"],
            "suffix_prefill_sigs": m["suffix_prefill_sigs"],
            "decode_sig_stable": m["decode_sig_stable"],
        }), flush=True)
        return 0 if m["decode_sig_stable"] else 1
    base = dict(n=args.num_requests, prompt_lo=args.prompt_lo,
                prompt_hi=args.prompt_hi, max_new=args.max_new,
                vocab=args.vocab)

    # every measured workload, generated up front so warmup can compile
    # exactly the buckets the timed reps will touch
    rep_sets = [make_requests(seed=args.seed + 1 + rep, **base)
                for rep in range(args.reps)]
    warm_workload(eng, [make_requests(seed=args.seed, **base)] + rep_sets)
    sigs = eng._decode_step._cache_size()
    mixed = eng._mixed_step._cache_size()
    buckets = len(eng._prefill_cache)

    ok = True
    for rate in [float(r) for r in str(args.rate).split(",") if r != ""]:
        vals, occs, pres = [], [], 0
        step_s, req_s = [], []
        rec = {}
        for rep in range(args.reps):
            reqs = make_requests(seed=args.seed + 1 + rep, **base)
            arr = poisson_arrivals(len(reqs), rate, seed=args.seed + rep)
            rec = run_workload(eng, reqs, arr)
            vals.append(rec["tokens"] / rec["seconds"])
            occs.append(rec["occupancy"])
            pres += rec["preemptions"]
            step_s += rec["step_seconds"]
            req_s += rec["req_seconds"]
        if eng._decode_step._cache_size() != sigs or \
                eng._mixed_step._cache_size() != mixed or \
                len(eng._prefill_cache) != buckets:
            ok = False
            print(json.dumps({"bench": "serving",
                              "error": "decode/mixed step or prefill "
                                       "bucket recompiled during the "
                                       "timed region"}), flush=True)
        q1, med, q3 = np.percentile(vals, [25, 50, 75])
        # per-token latency = busy decode-step duration (each live request
        # advances one token per step); per-request = admit -> finish.
        # p99 over all reps at this rate — the tail the capacity curve is
        # actually planned around, not the mean the throughput row shows.
        tok_p50, tok_p99 = (np.percentile(step_s, [50, 99]) * 1e3
                            if step_s else (0.0, 0.0))
        req_p50, req_p99 = (np.percentile(req_s, [50, 99]) * 1e3
                            if req_s else (0.0, 0.0))
        print(json.dumps({
            "bench": "serving", "rate_req_per_sec": rate,
            "num_requests": args.num_requests, "slots": args.slots,
            "page_size": args.page_size, "max_context": args.max_context,
            "prompt_lens": [args.prompt_lo, args.prompt_hi],
            "max_new": args.max_new,
            "dim": args.dim, "layers": args.layers, "dtype": args.dtype,
            "tokens_per_sec_median": round(float(med), 1),
            "tokens_per_sec_iqr": [round(float(q1), 1), round(float(q3), 1)],
            "occupancy": round(float(np.mean(occs)), 3),   # mean over reps —
            # stays consistent with the median throughput it sits next to
            "tok_latency_ms_p50": round(float(tok_p50), 3),
            "lm_serving_p99_tok_latency_ms": round(float(tok_p99), 3),
            "req_latency_ms_p50": round(float(req_p50), 3),
            "req_latency_ms_p99": round(float(req_p99), 3),
            "decode_steps": rec["decode_steps"],
            "preemptions": pres,
            "decode_signatures": eng._decode_step._cache_size(),
            "prefill_buckets": len(eng._prefill_cache),
            "reps": args.reps,
        }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
