"""Micro-benchmark: attention implementations across sequence lengths.

Compares dense (fused XLA), blockwise (lax.scan online-softmax), and flash
(pallas kernel, TPU) on forward+backward wall time — the evidence behind
the layer's auto-selection thresholds (graph/layers_attn.py).

Dispatch-proof timing (VERDICT r4 weak #6: the old per-call loop reported
~0.03 ms/step at T=1024 AND T=4096 — 4x the work in the same time, i.e.
it measured dispatch, not compute; at T=4096 the reported number exceeded
the chip's peak FLOP rate ~35x, so even `block_until_ready` through the
axon tunnel wasn't a real completion barrier):

- N steps run inside ONE jitted `lax.scan` whose carry feeds each
  iteration's q/k/v from the previous iteration's gradients — a single
  dispatch per timed region, with a data dependency that stops XLA from
  eliding or deduplicating the repeats, and the full fwd+bwd (dq, dk, dv
  all consumed) kept live;
- N is sized from an analytic FLOP estimate so one region is >=~250 ms
  of device work — dispatch latency is then noise, not signal;
- completion is forced by a host read (float()) of a scalar reduced from
  the final carry, not by block_until_ready.

Usage: python tools/bench_attention.py [--lens 512,1024,4096] [--batch 8]
       [--heads 8] [--dim 64] [--target-ms 250] [--reps 3]
       [--dtype bfloat16]
Prints one JSON line per (impl, seq_len).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def bench_impl(fn, q, k, v, n_steps, reps):
    """One fwd+bwd attention step, timed with the shared dispatch-proof
    chained-scan harness (tools/_scan_bench.py) — all micro-benches use
    the same methodology so a harness fix can't leave one diverged."""
    from _scan_bench import fold, timed_chain

    def step(carry):
        q, k, v = carry

        def loss(q, k, v):
            return jnp.sum(fn(q, k, v, causal=True).astype(jnp.float32))
        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return fold(carry, g), l

    return timed_chain(step, (q, k, v), n_steps, reps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lens", default="512,1024,2048,4096")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--target-ms", type=float, default=250.0)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    from paddle_tpu.ops import pallas_attention
    from paddle_tpu.ops.attention import (
        blockwise_attention, dot_product_attention)

    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    impls = {
        "dense": dot_product_attention,
        "blockwise": functools.partial(blockwise_attention, block_k=512),
    }
    if pallas_attention.supported():
        impls["flash"] = pallas_attention.flash_attention

    rng = np.random.default_rng(0)
    from _scan_bench import attn_step_flops as _est_step_flops
    from _scan_bench import scan_length
    try:
        from bench import _chip_peak_tflops
        peak = _chip_peak_tflops(args.dtype) * 1e12   # dtype + device aware
    except Exception:
        peak = 197e12 if args.dtype == "bfloat16" else 98.5e12
    for T in [int(x) for x in args.lens.split(",")]:
        shape = (args.batch, T, args.heads, args.dim)
        q = jnp.asarray(rng.normal(size=shape), dt)
        k = jnp.asarray(rng.normal(size=shape), dt)
        v = jnp.asarray(rng.normal(size=shape), dt)
        est = _est_step_flops(args.batch, T, args.heads, args.dim)
        n_steps = scan_length(est, target_ms=args.target_ms)
        for name, fn in impls.items():
            try:
                sec = bench_impl(fn, q, k, v, n_steps, args.reps)
                print(json.dumps({
                    "impl": name, "seq_len": T, "n_steps": n_steps,
                    "ms_per_step": round(sec * 1e3, 3),
                    "tokens_per_sec": round(args.batch * T / sec, 1),
                    "est_mfu": round(est / sec / peak, 3)}), flush=True)
            except Exception as e:
                print(json.dumps({"impl": name, "seq_len": T,
                                  "error": f"{type(e).__name__}: "
                                           f"{str(e)[:300]}"}), flush=True)


if __name__ == "__main__":
    main()
