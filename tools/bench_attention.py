"""Micro-benchmark: attention implementations across sequence lengths.

Compares dense (fused XLA), blockwise (lax.scan online-softmax), and flash
(pallas kernel, TPU) on forward+backward wall time — the evidence behind
the layer's auto-selection thresholds (graph/layers_attn.py).

Usage: python tools/bench_attention.py [--lens 512,1024,4096] [--batch 4]
       [--heads 8] [--dim 64] [--iters 20] [--dtype bfloat16]
Prints one JSON line per (impl, seq_len).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def bench_impl(name, fn, q, k, v, iters):
    @jax.jit
    def step(q, k, v):
        def loss(q, k, v):
            return jnp.sum(fn(q, k, v, causal=True).astype(jnp.float32))
        l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
        return l, g

    l, g = step(q, k, v)                       # compile + warmup
    jax.block_until_ready((l, g))
    t0 = time.perf_counter()
    for _ in range(iters):
        l, g = step(q, k, v)
    jax.block_until_ready((l, g))
    dt = (time.perf_counter() - t0) / iters
    return dt


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lens", default="512,1024,2048")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    from paddle_tpu.ops import pallas_attention
    from paddle_tpu.ops.attention import (
        blockwise_attention, dot_product_attention)

    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    impls = {
        "dense": dot_product_attention,
        "blockwise": functools.partial(blockwise_attention, block_k=512),
    }
    if pallas_attention.supported():
        impls["flash"] = pallas_attention.flash_attention

    rng = np.random.default_rng(0)
    for T in [int(x) for x in args.lens.split(",")]:
        shape = (args.batch, T, args.heads, args.dim)
        q = jnp.asarray(rng.normal(size=shape), dt)
        k = jnp.asarray(rng.normal(size=shape), dt)
        v = jnp.asarray(rng.normal(size=shape), dt)
        for name, fn in impls.items():
            try:
                sec = bench_impl(name, fn, q, k, v, args.iters)
                print(json.dumps({
                    "impl": name, "seq_len": T, "ms_per_step": round(sec * 1e3, 3),
                    "tokens_per_sec": round(args.batch * T / sec, 1)}))
            except Exception as e:
                print(json.dumps({"impl": name, "seq_len": T,
                                  "error": f"{type(e).__name__}: {e}"}))


if __name__ == "__main__":
    main()
