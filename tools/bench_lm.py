"""Transformer-LM performance sweep on TPU (VERDICT r3 item 3).

The LM family (demo/model_zoo/transformer_lm.py) exercises every round-3
kernel: rotary attention with the dense/flash/blockwise auto-selection,
layer_norm, GELU, the compiled decode loop.  This tool measures, per
sequence length:

  * train tokens/sec + MFU (scan-staged batches, same measurement shape
    as bench.py) for each requested attn_impl — the dense-vs-flash
    crossover table PERF.md needs,
  * greedy decode tokens/sec via graph/lm_decode (fixed-iteration,
    median +- IQR across reps — the variance-controlled decode
    measurement VERDICT r3 item 2 asks for).

One JSON line per measurement.  Token budget per batch is held constant
across lengths (batch = tokens_per_batch / seq_len) so every row saturates
the chip with the same work.

Usage:
  python tools/bench_lm.py --lens 512,1024,4096 --impls auto,dense
  python tools/bench_lm.py --dim 512 --layers 8 --heads 8 --vocab 32000
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _mfu(tr, batch, tokens_per_sec: float, tokens_per_batch: int,
         dtype: str) -> float:
    # bench.py's MFU is per-(samples/sec, batch) but the ratio is identical
    # for (tokens/sec, tokens/batch) — share one implementation
    from bench import _step_mfu
    return _step_mfu(tr, batch, tokens_per_sec, tokens_per_batch, dtype)


def bench_train(args, seq_len: int, impl: str) -> dict:
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    batch = max(1, args.tokens_per_batch // seq_len)
    cfg = parse_config(
        "demo/model_zoo/transformer_lm.py",
        f"vocab={args.vocab},dim={args.dim},layers={args.layers},"
        f"heads={args.heads},batch_size={batch},"
        f"compute_dtype={args.dtype},attn_impl={impl}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    full = np.full((batch,), seq_len, np.int32)
    batches = []
    for _ in range(2 + args.iters):
        tok = rng.integers(2, args.vocab, (batch, seq_len)).astype(np.int32)
        nxt = rng.integers(2, args.vocab, (batch, seq_len)).astype(np.int32)
        batches.append({"tokens": Argument(ids=tok, lengths=full),
                        "next_tokens": Argument(ids=nxt, lengths=full)})
    stats = tr.benchmark(iter(batches), warmup=2, iters=args.iters,
                         scan=True)
    sps = stats["samples_per_sec"]
    tps = sps * seq_len
    return {
        "bench": "lm_train", "impl": impl, "seq_len": seq_len,
        "batch": batch, "dim": args.dim, "layers": args.layers,
        "tokens_per_sec": round(tps, 1),
        "samples_per_sec": round(sps, 2),
        "mfu": round(_mfu(tr, batches[0], tps, batch * seq_len,
                          args.dtype), 4),
    }


def time_decode(tr, ids, max_new: int, use_cache: bool, reps: int):
    """Compile + warm up one lm_generate call, then time `reps` identical
    calls; returns the per-call seconds as an np.ndarray.  The ONE decode
    timing loop — bench.py's compact record and the per-context sweep
    below both call it, so methodology (warmup, sync-on-host-read) can
    never drift between the two recorded numbers."""
    import time as _time

    import numpy as np

    from paddle_tpu.graph.lm_decode import lm_generate

    kw = dict(max_new=max_new, use_cache=use_cache)
    toks, _ = lm_generate(tr.executor, tr.params, ids, **kw)
    np.asarray(toks)                                   # compile + warmup
    times = []
    for _ in range(reps):
        t0 = _time.perf_counter()
        toks, _ = lm_generate(tr.executor, tr.params, ids, **kw)
        np.asarray(toks)
        times.append(_time.perf_counter() - t0)
    return np.asarray(times)


def bench_decode(args, context: int, use_cache: bool) -> dict:
    """Greedy decode throughput: median +- IQR over fixed-size reps (the
    whole decode is one jitted scan; per-call dispatch jitter demands a
    robust statistic, not one stopwatch pass).  use_cache measures the
    O(T)-per-token KV-cache path vs the whole-prefix re-forward."""
    import numpy as np

    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.trainer.trainer import Trainer

    batch = max(1, args.decode_batch)
    prompt = max(1, context - args.max_new)
    cfg = parse_config(
        "demo/model_zoo/transformer_lm.py",
        f"vocab={args.vocab},dim={args.dim},layers={args.layers},"
        f"heads={args.heads},batch_size={batch},"
        f"compute_dtype={args.dtype}")
    tr = Trainer(cfg, seed=1)

    rng = np.random.default_rng(0)
    ids = rng.integers(2, args.vocab, (batch, prompt)).astype(np.int32)
    times = time_decode(tr, ids, args.max_new, use_cache, args.decode_reps)
    q1, med, q3 = np.percentile(times, [25, 50, 75])
    n_tok = batch * args.max_new
    return {
        "bench": "lm_decode", "context": context, "batch": batch,
        "max_new": args.max_new, "kv_cache": use_cache,
        "tokens_per_sec_median": round(n_tok / med, 1),
        "tokens_per_sec_iqr": [round(n_tok / q3, 1), round(n_tok / q1, 1)],
        "reps": args.decode_reps,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lens", default="512,1024,4096")
    ap.add_argument("--impls", default="auto,dense")
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--tokens-per-batch", type=int, default=32768)
    ap.add_argument("--decode", action="store_true", default=True)
    ap.add_argument("--no-decode", dest="decode", action="store_false")
    ap.add_argument("--decode-batch", type=int, default=32)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--decode-reps", type=int, default=20)
    args = ap.parse_args()

    lens = [int(x) for x in args.lens.split(",") if x]
    impls = [x.strip() for x in args.impls.split(",") if x.strip()]
    ok = True
    for seq_len in lens:
        for impl in impls:
            try:
                print(json.dumps(bench_train(args, seq_len, impl)),
                      flush=True)
            except Exception as e:                      # noqa: BLE001
                ok = False
                print(json.dumps({
                    "bench": "lm_train", "impl": impl, "seq_len": seq_len,
                    "error": f"{type(e).__name__}: {str(e)[:300]}"}),
                    flush=True)
    if args.decode:
        for context in lens:
            for use_cache in (True, False):
                if context > 2048 and not use_cache:
                    print(json.dumps({
                        "bench": "lm_decode", "context": context,
                        "kv_cache": False,
                        "skipped": "O(T^2) whole-prefix re-forward at this "
                                   "length; measured via the KV-cache path"}),
                        flush=True)
                    continue
                try:
                    print(json.dumps(bench_decode(args, context, use_cache)),
                          flush=True)
                except Exception as e:                  # noqa: BLE001
                    ok = False
                    print(json.dumps({
                        "bench": "lm_decode", "context": context,
                        "kv_cache": use_cache,
                        "error": f"{type(e).__name__}: {str(e)[:300]}"}),
                        flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
