"""Micro-benchmark: pallas LSTM/GRU time-grid kernels vs the lax.scan
fallback — the routing evidence the additive kernel already has
(MEASURE/additive_bench.out) but the RNN kernels never got on hardware.

Measures fwd+bwd training-step time at the shapes that matter:
the sentiment bench (B64 T30-ish D512-class hidden) plus a small and a
long-sequence point.  Prints one JSON line per (cell, impl, shape).

Usage: python tools/bench_rnn.py [--iters 3] [--shapes B,T,D;B,T,D;...]
(--iters = timed reps of the single-dispatch ~250ms scanned region, not
per-call loop iterations)
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _time(loss, argnums, args, reps, est_flops):
    """Dispatch-proof timing (tools/_scan_bench.py): the grads of `loss`
    w.r.t. `argnums` chain into the next iteration's inputs inside one
    jitted scan — the old per-call loop + block_until_ready reported
    dispatch latency, not compute, through the axon tunnel."""
    from _scan_bench import fold, scan_length, timed_chain

    def step(carry):
        l, g = jax.value_and_grad(loss, argnums=argnums)(*carry)
        return fold(carry, g), l

    return timed_chain(step, tuple(args), scan_length(est_flops), reps)


def bench_cell(cell: str, impl: str, B: int, T: int, D: int,
               iters: int) -> dict:
    assert cell in ("lstm", "gru"), f"unknown cell {cell!r}"
    # the scan entry points SELF-ROUTE to the pallas kernels on TPU when
    # D % 128 == 0 (ops/rnn.py _use_fused) — the 'scan' arm must force
    # the real lax.scan fallback or it benchmarks pallas against itself
    prev = os.environ.get("PADDLE_TPU_PALLAS")
    os.environ["PADDLE_TPU_PALLAS"] = "0" if impl == "scan" else "1"
    try:
        return _bench_cell(cell, impl, B, T, D, iters)
    finally:
        if prev is None:
            os.environ.pop("PADDLE_TPU_PALLAS", None)
        else:
            os.environ["PADDLE_TPU_PALLAS"] = prev


def _bench_cell(cell: str, impl: str, B: int, T: int, D: int,
                iters: int) -> dict:
    from paddle_tpu.ops import pallas_rnn, rnn

    rng = np.random.default_rng(0)
    lens = jnp.asarray(rng.integers(max(1, T // 2), T + 1, B), jnp.int32)
    z = jnp.zeros((B, D), jnp.float32)

    if cell == "lstm":
        x = jnp.asarray(rng.standard_normal((B, T, 4 * D)) * 0.5,
                        jnp.float32)
        w = jnp.asarray(rng.standard_normal((D, 4 * D)) * 0.2, jnp.float32)
        peeps = jnp.zeros((3, D), jnp.float32)

        if impl == "pallas":
            def loss(x, w):
                hs, hl, cl = pallas_rnn.lstm_fused(
                    x, lens, w, peeps, z, z, active_type="tanh",
                    gate_active_type="sigmoid", state_active_type="tanh",
                    reverse=False)
                return jnp.sum(hs * hs) + jnp.sum(hl * cl)
        else:
            def loss(x, w):
                hs, hl, cl = rnn.lstm_scan(x, lens, w, None)
                return jnp.sum(hs * hs) + jnp.sum(hl * cl)
        # fwd: T recurrent [B,D]x[D,4D] matmuls; bwd ~2.5x
        est = 3.5 * T * 2 * B * D * 4 * D
        dt = _time(loss, (0, 1), (x, w), iters, est)
    else:
        x = jnp.asarray(rng.standard_normal((B, T, 3 * D)) * 0.5,
                        jnp.float32)
        wg = jnp.asarray(rng.standard_normal((D, 2 * D)) * 0.2, jnp.float32)
        wc = jnp.asarray(rng.standard_normal((D, D)) * 0.2, jnp.float32)

        if impl == "pallas":
            def loss(x, wg, wc):
                hs, hl = pallas_rnn.gru_fused(
                    x, lens, wg, wc, z, active_type="tanh",
                    gate_active_type="sigmoid", reverse=False)
                return jnp.sum(hs * hs) + jnp.sum(hl)
        else:
            def loss(x, wg, wc):
                hs, hl = rnn.gru_scan(x, lens, wg, wc, None)
                return jnp.sum(hs * hs) + jnp.sum(hl)
        est = 3.5 * T * 2 * B * D * 3 * D
        dt = _time(loss, (0, 1, 2), (x, wg, wc), iters, est)

    return {"bench": "rnn", "cell": cell, "impl": impl,
            "B": B, "T": T, "D": D,
            "ms_per_step": round(dt * 1e3, 3),
            "tokens_per_sec": round(B * T / dt, 1)}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=3,
                    help="timed reps of the scanned region")
    ap.add_argument("--shapes", default="64,30,512;16,8,64;8,512,256")
    ap.add_argument("--cells", default="lstm,gru")
    args = ap.parse_args()

    shapes = [tuple(int(v) for v in s.split(","))
              for s in args.shapes.split(";") if s]
    ok = True
    for B, T, D in shapes:
        for cell in args.cells.split(","):
            for impl in ("pallas", "scan"):
                try:
                    print(json.dumps(bench_cell(cell, impl, B, T, D,
                                                args.iters)), flush=True)
                except Exception as e:                  # noqa: BLE001
                    ok = False
                    print(json.dumps({
                        "bench": "rnn", "cell": cell, "impl": impl,
                        "B": B, "T": T, "D": D,
                        "error": f"{type(e).__name__}: {str(e)[:300]}"}),
                        flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
