"""Tune the pallas flash-attention block sizes on device (VERDICT r4
item 7: "tune flash block sizes").

Sweeps (block_q, block_k) over the flash kernel at transformer-LM-ish
shapes with the shared dispatch-proof harness (tools/_scan_bench.py) and
prints one JSON row per point plus a `best` row per sequence length.
Apply a winner globally via the env defaults the attention layer reads
(PADDLE_TPU_FLASH_BLOCK_Q / PADDLE_TPU_FLASH_BLOCK_K,
graph/layers_attn.py) or per layer via the block_q/block_k attrs.

Usage: python tools/tune_flash.py [--lens 1024,4096] [--blocks 128,256,512]
       [--batch 8] [--heads 8] [--dim 64] [--dtype bfloat16]
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--lens", default="1024,4096")
    ap.add_argument("--blocks", default="128,256,512")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--target-ms", type=float, default=250.0)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    from _scan_bench import attn_step_flops, fold, scan_length, timed_chain
    from paddle_tpu.ops import pallas_attention

    if not pallas_attention.supported():
        print(json.dumps({"error": "pallas flash unsupported on this "
                          "backend (set PADDLE_TPU_PALLAS_INTERPRET=1 to "
                          "rehearse)"}))
        return 1

    dt = jnp.bfloat16 if args.dtype == "bfloat16" else jnp.float32
    blocks = [int(b) for b in args.blocks.split(",")]
    rng = np.random.default_rng(0)
    ok = True
    for T in [int(x) for x in args.lens.split(",")]:
        shape = (args.batch, T, args.heads, args.dim)
        q = jnp.asarray(rng.normal(size=shape), dt)
        k = jnp.asarray(rng.normal(size=shape), dt)
        v = jnp.asarray(rng.normal(size=shape), dt)
        est = attn_step_flops(args.batch, T, args.heads, args.dim)
        n_steps = scan_length(est, target_ms=args.target_ms)
        best = None
        for bq, bk in itertools.product(blocks, blocks):
            if bq > T or bk > T:
                continue

            def step(carry, bq=bq, bk=bk):
                q, k, v = carry

                def loss(q, k, v):
                    return jnp.sum(pallas_attention.flash_attention(
                        q, k, v, causal=True, block_q=bq, block_k=bk)
                        .astype(jnp.float32))
                l, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
                return fold(carry, g), l

            try:
                sec = timed_chain(step, (q, k, v), n_steps, args.reps)
                row = {"seq_len": T, "block_q": bq, "block_k": bk,
                       "n_steps": n_steps,
                       "ms_per_step": round(sec * 1e3, 3)}
                print(json.dumps(row), flush=True)
                if best is None or sec < best[0]:
                    best = (sec, bq, bk)
            except Exception as e:
                ok = False
                print(json.dumps({"seq_len": T, "block_q": bq,
                                  "block_k": bk,
                                  "error": f"{type(e).__name__}: "
                                           f"{str(e)[:200]}"}), flush=True)
        if best is not None:
            print(json.dumps({"best": True, "seq_len": T,
                              "block_q": best[1], "block_k": best[2],
                              "ms_per_step": round(best[0] * 1e3, 3)}),
                  flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
