"""Background tunnel-recovery poller.

The axon TPU tunnel has been down for entire rounds at a time (see
MEASURE/ history); when it recovers mid-session nobody may be watching.
This poller probes backend health every --interval seconds and, on the
first healthy probe, runs tools/tpu_measure.py end-to-end (which
persists every measurement under MEASURE/ + PERF_LOG.jsonl as it goes).

Never imports jax in-process (a wedged tunnel blocks backend init
forever); every probe is a subprocess under a hard timeout.

Exit codes: 0 = measurement session ran (see MEASURE/), 2 = gave up
after --max-hours without a healthy probe.

Usage: python tools/tpu_poller.py [--interval=300] [--max-hours=10.5]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from tpu_measure import health as probe  # noqa: E402


def main() -> int:
    interval = 300.0
    max_hours = 10.5
    for a in sys.argv[1:]:
        if a.startswith("--interval="):
            interval = float(a.split("=", 1)[1])
        elif a.startswith("--max-hours="):
            max_hours = float(a.split("=", 1)[1])
    deadline = time.time() + max_hours * 3600
    n = 0
    while time.time() < deadline:
        n += 1
        ok = probe()
        print(json.dumps({"probe": n, "healthy": ok,
                          "t": round(time.time())}), flush=True)
        if ok:
            rc = subprocess.call(
                [sys.executable, "tools/tpu_measure.py"], cwd=REPO)
            print(json.dumps({"measure_rc": rc}), flush=True)
            # rc!=0 means the tunnel died mid-session; whatever completed
            # is already persisted. Keep polling so a later recovery
            # finishes the remaining steps (tpu_measure reruns everything,
            # but each step's .out is overwritten with fresh data: fine).
            if rc == 0:
                return 0
        time.sleep(interval)
    return 2


if __name__ == "__main__":
    sys.exit(main())
