"""Trainer-level N-device vs 1-device equivalence oracle.

The reference asserts that multi-trainer / remote-updater training produces
IDENTICAL final parameters to local training (ref: paddle/trainer/tests/
test_CompareSparse.cpp:133-152, test_TrainerOnePass.cpp:123-291).  Here the
same oracle runs at the full-Trainer level: the same config, seed and batch
stream trained on a 1-device setup vs an 8-virtual-device dp mesh must give
matching loss trajectories and final parameters — proving the mesh path
(shard_batch, sharded embedding tables, XLA gradient all-reduce) computes
the same optimization as the serial path, not merely a finite one.  The
oracle itself lives in paddle_tpu/trainer/parity.py (shared with the
driver's dryrun_multichip phase 3b).
"""

import numpy as np

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.parity import assert_dp_parity
import pytest

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")



def test_mnist_mlp_dp8_matches_dp1():
    """MNIST MLP (a BASELINE config family), 20 steps, dp=8 vs dp=1."""
    rng = np.random.default_rng(0)
    B = 16
    batches = [
        {"pixel": Argument(value=(rng.random((B, 784), np.float32)
                                  .astype(np.float32) - 0.5)),
         "label": Argument(ids=rng.integers(0, 10, B).astype(np.int32))}
        for _ in range(20)
    ]
    cfg = parse_config("demo/mnist/mlp_mnist.py", f"batch_size={B}")
    assert cfg.opt_config.batch_size == B
    assert_dp_parity(cfg, batches, make_mesh(data=8))


def test_lstm_sequence_model_dp8_matches_dp1():
    """A recurrent (LSTM-scan) sequence model under dp: the scan carry,
    masking, and per-step psum'd gradients must reproduce dp=1 exactly —
    the parity matrix's sequence-model cell."""
    from paddle_tpu.config.parser import parse_config_callable

    def conf():
        from paddle_tpu.dsl import (AdamOptimizer, ParamAttr,
                                    SoftmaxActivation, classification_cost,
                                    data_layer, embedding_layer, fc_layer,
                                    last_seq, settings, simple_lstm)
        settings(batch_size=16, learning_rate=0.005,
                 learning_method=AdamOptimizer())
        w = data_layer(name="word", size=50)
        emb = embedding_layer(input=w, size=12,
                              param_attr=ParamAttr(initial_std=0.1))
        lstm = simple_lstm(input=emb, size=16)
        rep = last_seq(input=lstm)
        out = fc_layer(input=rep, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))

    rng = np.random.default_rng(5)
    B, T = 16, 7
    batches = []
    for _ in range(8):
        batches.append({
            "word": Argument(ids=rng.integers(0, 50, (B, T)).astype(np.int32),
                             lengths=rng.integers(2, T + 1, B)
                             .astype(np.int32)),
            "y": Argument(ids=rng.integers(0, 3, B).astype(np.int32)),
        })
    cfg = parse_config_callable(conf)
    assert_dp_parity(cfg, batches, make_mesh(data=8),
                     config2=parse_config_callable(conf))


def test_zero1_sharded_optimizer_matches_dp1():
    """ZeRO-1 (settings(shard_optimizer_state=True)): optimizer slot
    buffers shard their leading dim over `data` — the pserver
    each-server-updates-1/N design — and training must STILL match dp=1
    exactly (XLA partitions the update along the slot sharding)."""
    import jax as _jax
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.trainer.parity import assert_dp_parity
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        from paddle_tpu.dsl import (AdamOptimizer, SoftmaxActivation,
                                    TanhActivation, classification_cost,
                                    data_layer, fc_layer, settings)
        settings(batch_size=16, learning_rate=0.01,
                 learning_method=AdamOptimizer(),
                 shard_optimizer_state=True)
        x = data_layer(name="pixel", size=64)
        h = fc_layer(input=x, size=32, act=TanhActivation())
        out = fc_layer(input=h, size=8, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="label", size=8))

    rng = np.random.default_rng(2)
    B = 16
    batches = [
        {"pixel": Argument(value=rng.normal(size=(B, 64)).astype(np.float32)),
         "label": Argument(ids=rng.integers(0, 8, B).astype(np.int32))}
        for _ in range(15)
    ]
    mesh = make_mesh(data=8)
    cfg = parse_config_callable(conf)
    assert cfg.opt_config.shard_optimizer_state

    # the slots are REALLY sharded (1/8th of the rows per device)
    tr = Trainer(cfg, seed=1, mesh=mesh)
    w_slots = tr.opt_state["slots"]["___fc_layer_0__.w0"]
    leaf = _jax.tree.leaves(w_slots)[0]          # adam m for the [64,32] w
    shard_shape = leaf.sharding.shard_shape(leaf.shape)
    assert shard_shape[0] == leaf.shape[0] // 8, (shard_shape, leaf.shape)

    assert_dp_parity(cfg, batches, mesh, config2=parse_config_callable(conf))

    # checkpoint round-trip keeps the ZeRO sharding: save from the sharded
    # trainer, load into a fresh mesh trainer -> slots re-sharded, params
    # identical
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        tr.train_one_batch(batches[0])
        path = tr.save(d)
        tr2 = Trainer(parse_config_callable(conf), seed=9, mesh=mesh)
        tr2.load(path)
        leaf2 = _jax.tree.leaves(tr2.opt_state["slots"]["___fc_layer_0__.w0"])[0]
        assert leaf2.sharding.shard_shape(leaf2.shape)[0] == \
            leaf2.shape[0] // 8
        for name in tr.params:
            np.testing.assert_allclose(
                np.asarray(_jax.device_get(tr.params[name])),
                np.asarray(_jax.device_get(tr2.params[name])), rtol=1e-6)


def test_recommendation_dp8_matches_dp1():
    """The recommendation config with its sparse slots (sharded embedding
    tables + a sparse-row genres input), dp=8 vs dp=1 — the closest analog
    of test_CompareSparse's local-vs-remote-sparse assertion."""
    rng = np.random.default_rng(1)
    B, title_len = 16, 6
    movie, user, title_vocab = 48, 40, 64     # vocab % 8 == 0 -> sharded
    ids = lambda n: rng.integers(0, n, B).astype(np.int32)
    batches = []
    for _ in range(10):
        gen = rng.integers(0, 18, (B, 3)).astype(np.int32)
        batches.append({
            "movie_id": Argument(ids=ids(movie)),
            "title": Argument(
                ids=rng.integers(0, title_vocab, (B, title_len)).astype(np.int32),
                lengths=np.full((B,), title_len, np.int32)),
            "genres": Argument(ids=gen,
                               sparse_vals=np.ones((B, 3), np.float32),
                               sparse_dim=18),
            "user_id": Argument(ids=ids(user)),
            "gender": Argument(ids=ids(2)),
            "age": Argument(ids=ids(7)),
            "occupation": Argument(ids=ids(21)),
            "rating": Argument(value=(rng.random((B, 1), np.float32)
                                      .astype(np.float32) * 2 - 1)),
        })
    args = (f"batch_size={B},emb_size=16,movie_dim={movie},user_dim={user},"
            f"title_vocab={title_vocab},learning_rate=0.01")
    cfg = parse_config("demo/recommendation/trainer_config.py", args)
    assert_dp_parity(cfg, batches, make_mesh(data=8))
