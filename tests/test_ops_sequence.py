"""Sequence op tests against numpy oracles — the analog of the reference's
CPU-vs-GPU comparison tests (ref: paddle/math/tests/test_matrixCompare.cpp,
paddle/cuda/src/hl_cuda_sequence.cu ops)."""

import numpy as np
import jax.numpy as jnp

from paddle_tpu.ops import sequence as seqops


def _ragged(rng, B=5, T=7, D=3):
    lengths = rng.integers(1, T + 1, size=B).astype(np.int32)
    x = rng.standard_normal((B, T, D)).astype(np.float32)
    for i in range(B):
        x[i, lengths[i]:] = 0.0
    return x, lengths


def test_seq_pool_max_avg_last_first():
    rng = np.random.default_rng(0)
    x, lengths = _ragged(rng)
    got_max = np.asarray(seqops.seq_pool_max(jnp.asarray(x), jnp.asarray(lengths)))
    got_avg = np.asarray(seqops.seq_pool_avg(jnp.asarray(x), jnp.asarray(lengths)))
    got_sum = np.asarray(seqops.seq_pool_avg(jnp.asarray(x), jnp.asarray(lengths), "sum"))
    got_last = np.asarray(seqops.seq_pool_last(jnp.asarray(x), jnp.asarray(lengths)))
    got_first = np.asarray(seqops.seq_pool_first(jnp.asarray(x), jnp.asarray(lengths)))
    for i, L in enumerate(lengths):
        v = x[i, :L]
        np.testing.assert_allclose(got_max[i], v.max(0), rtol=1e-6)
        np.testing.assert_allclose(got_avg[i], v.mean(0), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got_sum[i], v.sum(0), rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(got_last[i], v[-1], rtol=1e-6)
        np.testing.assert_allclose(got_first[i], v[0], rtol=1e-6)


def test_expand_to_sequence():
    rng = np.random.default_rng(1)
    B, T, D = 4, 6, 2
    lengths = np.array([2, 6, 1, 4], np.int32)
    v = rng.standard_normal((B, D)).astype(np.float32)
    got = np.asarray(seqops.expand_to_sequence(jnp.asarray(v), jnp.asarray(lengths), T))
    for i, L in enumerate(lengths):
        for t in range(T):
            expect = v[i] if t < L else np.zeros(D)
            np.testing.assert_allclose(got[i, t], expect, rtol=1e-6)


def test_context_projection_matches_naive():
    rng = np.random.default_rng(2)
    B, T, D = 3, 5, 2
    lengths = np.array([5, 3, 4], np.int32)
    x = rng.standard_normal((B, T, D)).astype(np.float32)
    for i in range(B):
        x[i, lengths[i]:] = 0.0
    start, clen = -1, 3
    got = np.asarray(seqops.context_projection(
        jnp.asarray(x), jnp.asarray(lengths), start, clen))
    # naive oracle
    for i, L in enumerate(lengths):
        for t in range(T):
            if t >= L:
                assert np.allclose(got[i, t], 0.0)
                continue
            cols = []
            for j in range(clen):
                src = t + start + j
                cols.append(x[i, src] if 0 <= src < L else np.zeros(D))
            np.testing.assert_allclose(got[i, t], np.concatenate(cols),
                                       rtol=1e-6, atol=1e-7)


def test_seq_reverse():
    rng = np.random.default_rng(3)
    x, lengths = _ragged(rng)
    got = np.asarray(seqops.seq_reverse(jnp.asarray(x), jnp.asarray(lengths)))
    for i, L in enumerate(lengths):
        np.testing.assert_allclose(got[i, :L], x[i, :L][::-1], rtol=1e-6)


def test_seq_concat():
    rng = np.random.default_rng(4)
    B, Ta, Tb, D = 3, 4, 3, 2
    la = np.array([2, 4, 1], np.int32)
    lb = np.array([3, 1, 2], np.int32)
    a = rng.standard_normal((B, Ta, D)).astype(np.float32)
    b = rng.standard_normal((B, Tb, D)).astype(np.float32)
    for i in range(B):
        a[i, la[i]:] = 0
        b[i, lb[i]:] = 0
    got, lens = seqops.seq_concat(jnp.asarray(a), jnp.asarray(la),
                                  jnp.asarray(b), jnp.asarray(lb))
    got = np.asarray(got)
    for i in range(B):
        expect = np.concatenate([a[i, :la[i]], b[i, :lb[i]]], axis=0)
        np.testing.assert_allclose(got[i, :la[i] + lb[i]], expect, rtol=1e-6)
        assert int(lens[i]) == la[i] + lb[i]
