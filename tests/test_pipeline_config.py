"""Config-driven pipeline parallelism (ref: paddle/gserver/
gradientmachines/ParallelNeuralNetwork.h:35-70 — model parallelism on any
config via the per-layer `device=N` attribute).

The oracle is exactness: GPipe microbatching is pure dataflow, so training
a config under a (data, pipe) mesh must produce the same losses and final
parameters as un-pipelined single-device training — not merely finite
ones.  Also covers skip connections (activations carried through
intermediate stages) and stage-crossing sequence metadata.
"""

import jax
import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer

B, DIN, NCLS = 16, 48, 4


def _mlp_conf(n_stages):
    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, ReluActivation,
            SoftmaxActivation, TanhActivation, classification_cost,
            data_layer, fc_layer, settings,
        )
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9),
                 pipeline_micro_batches=2)
        x = data_layer(name="pixel", size=DIN)
        sizes = [64, 48, 32, NCLS]
        acts = [TanhActivation(), ReluActivation(), TanhActivation(),
                SoftmaxActivation()]
        h = x
        for s in range(n_stages):
            h = fc_layer(input=h, size=sizes[s], act=acts[s],
                         layer_attr=ExtraLayerAttribute(device=s))
        classification_cost(input=h, label=data_layer(name="label", size=NCLS))
    return conf


def _batches(n, rng):
    out = []
    for _ in range(n):
        out.append({
            "pixel": Argument(value=rng.normal(size=(B, DIN))
                              .astype(np.float32)),
            "label": Argument(ids=rng.integers(0, NCLS, B).astype(np.int32)),
        })
    return out


def _train(conf, mesh, batches):
    tr = Trainer(parse_config_callable(conf), seed=1, mesh=mesh)
    losses = [float(tr.train_one_batch(b)) for b in batches]
    params = {k: np.asarray(jax.device_get(v)) for k, v in tr.params.items()}
    return np.asarray(losses), params, tr


def test_pipeline_matches_unpipelined():
    """4-stage fc chain on a (data=2, pipe=4) mesh == 1-device training."""
    batches = _batches(12, np.random.default_rng(0))
    conf = _mlp_conf(4)
    l1, p1, _ = _train(conf, None, batches)
    mesh = make_mesh(data=2, pipe=4)
    lp, pp, tr = _train(conf, mesh, batches)
    from paddle_tpu.parallel.pipeline_config import PipelineExecutor
    assert isinstance(tr.executor, PipelineExecutor)
    np.testing.assert_allclose(lp, l1, rtol=2e-4, atol=1e-6,
                               err_msg="pipeline loss trajectory diverged")
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-4, atol=2e-5,
                                   err_msg=f"param {name!r} diverged under pp")


def test_pipeline_skip_connection():
    """A stage-0 activation consumed at stage 2 rides through stage 1's
    carrier (the reference's copyOutputToOtherDevice across non-adjacent
    devices)."""
    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, ReluActivation,
            SoftmaxActivation, TanhActivation, classification_cost,
            data_layer, fc_layer, settings,
        )
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9),
                 pipeline_micro_batches=2)
        x = data_layer(name="pixel", size=DIN)
        h0 = fc_layer(input=x, size=32, act=TanhActivation(),
                      layer_attr=ExtraLayerAttribute(device=0))
        h1 = fc_layer(input=h0, size=32, act=ReluActivation(),
                      layer_attr=ExtraLayerAttribute(device=1))
        # consumes BOTH h1 and the stage-0 output h0
        h2 = fc_layer(input=[h1, h0], size=NCLS, act=SoftmaxActivation(),
                      layer_attr=ExtraLayerAttribute(device=2))
        classification_cost(input=h2,
                            label=data_layer(name="label", size=NCLS))

    batches = _batches(8, np.random.default_rng(1))
    l1, p1, _ = _train(conf, None, batches)
    # 3 stages -> pipe axis exactly 3 (on a 3-device subset of the 8)
    mesh3 = make_mesh(data=1, pipe=3, devices=jax.devices()[:3])
    lp, pp, _ = _train(conf, mesh3, batches)
    np.testing.assert_allclose(lp, l1, rtol=2e-4, atol=1e-6)
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-4, atol=2e-5)


def _bn_conf(use_global):
    """fc -> batch_norm -> softmax head over 2 stages (the VGG-with-BN
    shape question from VERDICT r4 item 8, minimized)."""
    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, SoftmaxActivation,
            TanhActivation, batch_norm_layer, classification_cost,
            data_layer, fc_layer, settings,
        )
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9),
                 pipeline_micro_batches=2)
        x = data_layer(name="pixel", size=DIN)
        h0 = fc_layer(input=x, size=32, act=TanhActivation(),
                      layer_attr=ExtraLayerAttribute(device=0))
        hbn = batch_norm_layer(input=h0, use_global_stats=use_global,
                               layer_attr=ExtraLayerAttribute(device=0))
        h1 = fc_layer(input=hbn, size=NCLS, act=SoftmaxActivation(),
                      layer_attr=ExtraLayerAttribute(device=1))
        classification_cost(input=h1,
                            label=data_layer(name="label", size=NCLS))
    return conf


def test_pipeline_training_mode_bn_raises_actionable():
    """Default (training-mode) BN keeps moving stats — unsupported under
    pp, and the error must name the supported pattern (VERDICT r4 item 8:
    'fails with an actionable message covered by a test')."""
    batches = _batches(1, np.random.default_rng(5))
    mesh = make_mesh(data=1, pipe=2, devices=jax.devices()[:2])
    with pytest.raises(AssertionError, match="use_global_stats"):
        _train(_bn_conf(None), mesh, batches)


def test_pipeline_frozen_bn_matches_unpipelined():
    """use_global_stats=True freezes BN into a stateless affine — the
    documented pattern for BN under device=N pp (the reference's
    ParallelNeuralNetwork places any layer on any device,
    ref ParallelNeuralNetwork.h:35-70; our pp trades training-mode BN for
    exact microbatch dataflow).  Must train and match un-pipelined."""
    batches = _batches(8, np.random.default_rng(6))
    conf = _bn_conf(True)
    l1, p1, _ = _train(conf, None, batches)
    mesh = make_mesh(data=2, pipe=2, devices=jax.devices()[:4])
    lp, pp, tr = _train(conf, mesh, batches)
    from paddle_tpu.parallel.pipeline_config import PipelineExecutor
    assert isinstance(tr.executor, PipelineExecutor)
    np.testing.assert_allclose(lp, l1, rtol=2e-4, atol=1e-6)
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-4, atol=2e-5)


def test_pipeline_frozen_bn_with_loaded_stats_matches_unpipelined():
    """ADVICE r5 regression: the frozen-fine-tune pattern the
    use_global_stats=True message advertises must actually WORK — a
    checkpoint's BN moving stats (non-trivial mean/var, registered in
    net_state) are embedded into the stage bodies as constants, and the
    pipelined run matches the un-pipelined oracle using the same stats."""
    import jax.numpy as jnp

    conf = _bn_conf(True)

    def with_stats(tr):
        bn = [l.name for l in tr.model.layers if l.type == "batch_norm"][0]
        tr.net_state = {bn: {
            "mean": jnp.asarray(rng.normal(size=(32,)).astype(np.float32)),
            "var": jnp.asarray((rng.random(32) + 0.5).astype(np.float32)),
            "count": jnp.asarray(3.0, jnp.float32)}}
        return tr

    rng = np.random.default_rng(7)      # regenerate identical stats
    batches = _batches(6, rng)
    tr1 = with_stats(Trainer(parse_config_callable(conf), seed=1))
    l1 = np.asarray([float(tr1.train_one_batch(b)) for b in batches])
    p1 = {k: np.asarray(jax.device_get(v)) for k, v in tr1.params.items()}

    rng = np.random.default_rng(7)
    batches = _batches(6, rng)
    mesh = make_mesh(data=2, pipe=2, devices=jax.devices()[:4])
    trp = with_stats(Trainer(parse_config_callable(conf), seed=1, mesh=mesh))
    from paddle_tpu.parallel.pipeline_config import PipelineExecutor
    assert isinstance(trp.executor, PipelineExecutor)
    lp = np.asarray([float(trp.train_one_batch(b)) for b in batches])
    pp = {k: np.asarray(jax.device_get(v)) for k, v in trp.params.items()}

    np.testing.assert_allclose(lp, l1, rtol=2e-4, atol=1e-6)
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-4, atol=2e-5)
    # and the error for GENUINELY mutable state stays scoped + actionable
    with pytest.raises(AssertionError, match="mutable state"):
        _train(_bn_conf(None), make_mesh(data=1, pipe=2,
                                         devices=jax.devices()[:2]),
               _batches(1, np.random.default_rng(5)))


def test_pipeline_sequence_boundary():
    """A sequence activation (value + lengths) crossing a stage boundary:
    embedding + masked pooling on stage 0, classifier on stage 1 — the
    carrier must round-trip the lengths exactly."""
    V, T = 32, 6

    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, ParamAttr,
            SoftmaxActivation, TanhActivation, classification_cost,
            data_layer, embedding_layer, fc_layer, pooling_layer, settings,
        )
        from paddle_tpu.dsl.poolings import SumPooling
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9))
        w = data_layer(name="word", size=V)
        emb = embedding_layer(input=w, size=16,
                              param_attr=ParamAttr(initial_std=0.1))
        seq_fc = fc_layer(input=emb, size=16, act=TanhActivation(),
                          layer_attr=ExtraLayerAttribute(device=0))
        pooled = pooling_layer(input=seq_fc, pooling_type=SumPooling(),
                               layer_attr=ExtraLayerAttribute(device=1))
        out = fc_layer(input=pooled, size=NCLS, act=SoftmaxActivation(),
                       layer_attr=ExtraLayerAttribute(device=1))
        classification_cost(input=out,
                            label=data_layer(name="label", size=NCLS))

    rng = np.random.default_rng(2)
    batches = []
    for _ in range(8):
        batches.append({
            "word": Argument(ids=rng.integers(0, V, (B, T)).astype(np.int32),
                             lengths=rng.integers(1, T + 1, B)
                             .astype(np.int32)),
            "label": Argument(ids=rng.integers(0, NCLS, B).astype(np.int32)),
        })
    l1, p1, _ = _train(conf, None, batches)
    lp, pp, _ = _train(conf, make_mesh(data=4, pipe=2), batches)
    np.testing.assert_allclose(lp, l1, rtol=2e-4, atol=1e-6)
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-4, atol=2e-5)


def test_pipeline_with_recurrent_group_stage():
    """A recurrent group (LSTM-style scan) whole inside stage 0, classifier
    on stage 1 — the scan runs inside its stage's lax.switch branch and the
    pooled sequence output crosses the boundary."""
    V, T = 20, 6

    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, ParamAttr,
            SoftmaxActivation, TanhActivation, classification_cost,
            data_layer, embedding_layer, fc_layer, last_seq, memory,
            recurrent_group, settings,
        )
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9),
                 pipeline_micro_batches=2)
        w = data_layer(name="word", size=V)
        emb = embedding_layer(input=w, size=12,
                              param_attr=ParamAttr(initial_std=0.1,
                                                   name="emb"))

        def step(y):
            mem = memory(name="state", size=12)
            return fc_layer(input=[y, mem], size=12, act=TanhActivation(),
                            name="state",
                            layer_attr=ExtraLayerAttribute(device=0))

        rnn = recurrent_group(name="rg", step=step, input=emb)
        rep = last_seq(input=rnn, layer_attr=ExtraLayerAttribute(device=0))
        out = fc_layer(input=rep, size=NCLS, act=SoftmaxActivation(),
                       layer_attr=ExtraLayerAttribute(device=1))
        classification_cost(input=out,
                            label=data_layer(name="label", size=NCLS))

    rng = np.random.default_rng(3)
    batches = []
    for _ in range(6):
        batches.append({
            "word": Argument(ids=rng.integers(0, V, (B, T)).astype(np.int32),
                             lengths=rng.integers(1, T + 1, B)
                             .astype(np.int32)),
            "label": Argument(ids=rng.integers(0, NCLS, B).astype(np.int32)),
        })
    l1, p1, _ = _train(conf, None, batches)
    lp, pp, _ = _train(conf, make_mesh(data=4, pipe=2), batches)
    np.testing.assert_allclose(lp, l1, rtol=2e-4, atol=1e-6)
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-4, atol=2e-5)


def test_pipeline_bf16_compute_close_to_unpipelined():
    """Mixed precision under pp: bf16 activations cross stage boundaries
    through the fp32 carrier (cast bf16 -> f32 -> bf16 is exact), so bf16
    pipelined training must track bf16 un-pipelined training to bf16
    tolerance."""
    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, ReluActivation,
            SoftmaxActivation, TanhActivation, classification_cost,
            data_layer, fc_layer, settings,
        )
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9),
                 compute_dtype="bfloat16", pipeline_micro_batches=2)
        x = data_layer(name="pixel", size=DIN)
        h0 = fc_layer(input=x, size=32, act=TanhActivation(),
                      layer_attr=ExtraLayerAttribute(device=0))
        h1 = fc_layer(input=h0, size=32, act=ReluActivation(),
                      layer_attr=ExtraLayerAttribute(device=1))
        out = fc_layer(input=h1, size=NCLS, act=SoftmaxActivation(),
                       layer_attr=ExtraLayerAttribute(device=1))
        classification_cost(input=out,
                            label=data_layer(name="label", size=NCLS))

    batches = _batches(6, np.random.default_rng(4))
    l1, p1, _ = _train(conf, None, batches)
    lp, pp, _ = _train(conf, make_mesh(data=4, pipe=2), batches)
    assert np.isfinite(l1).all() and np.isfinite(lp).all()
    # bf16 tolerance: the carrier round-trip is exact, but reduction
    # orders differ between the pipelined and monolithic programs
    np.testing.assert_allclose(lp, l1, rtol=2e-2, atol=2e-2)
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-2, atol=3e-2)


def test_pipeline_with_gradient_accumulation():
    """Pipeline parallelism composes with gradient accumulation: pp training
    with num_batches_per_send_parameter=2 must equal un-pipelined
    accumulated training on the same batches."""
    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, SoftmaxActivation,
            TanhActivation, classification_cost, data_layer, fc_layer,
            settings,
        )
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9),
                 pipeline_micro_batches=2,
                 num_batches_per_send_parameter=2)
        x = data_layer(name="pixel", size=DIN)
        h = fc_layer(input=x, size=32, act=TanhActivation(),
                     layer_attr=ExtraLayerAttribute(device=0))
        out = fc_layer(input=h, size=NCLS, act=SoftmaxActivation(),
                       layer_attr=ExtraLayerAttribute(device=1))
        classification_cost(input=out,
                            label=data_layer(name="label", size=NCLS))

    batches = _batches(8, np.random.default_rng(6))
    l1, p1, _ = _train(conf, None, batches)
    lp, pp, tr = _train(conf, make_mesh(data=4, pipe=2), batches)
    assert int(tr.opt_state["num_updates"]) == 4       # 8 batches / N=2
    np.testing.assert_allclose(lp, l1, rtol=2e-4, atol=1e-6)
    for name in p1:
        np.testing.assert_allclose(pp[name], p1[name], rtol=3e-4, atol=2e-5)


def test_pipeline_rejects_bad_annotations():
    """Non-contiguous device order fails with a clear message."""
    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, SoftmaxActivation, TanhActivation,
            classification_cost, data_layer, fc_layer, settings,
        )
        settings(batch_size=8, learning_rate=0.1)
        x = data_layer(name="x", size=8)
        h = fc_layer(input=x, size=8, act=TanhActivation(),
                     layer_attr=ExtraLayerAttribute(device=1))
        out = fc_layer(input=h, size=2, act=SoftmaxActivation(),
                       layer_attr=ExtraLayerAttribute(device=0))
        classification_cost(input=out, label=data_layer(name="y", size=2))

    with pytest.raises(AssertionError, match="contiguous in config order"):
        Trainer(parse_config_callable(conf), seed=0,
                mesh=make_mesh(data=4, pipe=2))


# -- 1F1B schedule ----------------------------------------------------------

def _mlp_conf_1f1b(n_stages, n_micro=4):
    """Like _mlp_conf but selecting the 1F1B schedule with M > S, the
    regime 1F1B exists for (in-flight carriers capped at S, not M)."""
    base = _mlp_conf(n_stages)

    def conf():
        base()
        from paddle_tpu.dsl.base import current_context
        opt = current_context().opt
        opt.pipeline_schedule = "1f1b"
        opt.pipeline_micro_batches = n_micro
    return conf


def test_1f1b_matches_unpipelined():
    """4-stage chain, 8 microbatches (M > S: the stash's mod-S slot reuse
    is live), 1F1B == 1-device training — the same phase-2a exactness
    discipline as GPipe: schedules are dataflow-equivalent, so losses AND
    final params must match."""
    batches = _batches(12, np.random.default_rng(0))
    conf = _mlp_conf_1f1b(4, n_micro=8)
    l1, p1, _ = _train(conf, None, batches)
    mesh = make_mesh(data=2, pipe=4)
    lf, pf, tr = _train(conf, mesh, batches)
    assert tr.executor.schedule == "1f1b"
    info = tr.executor.schedule_info()
    assert info["micro_batches"] == 8
    assert info["in_flight_carriers"] == 4      # S stays the cap, not M=8
    np.testing.assert_allclose(lf, l1, rtol=2e-4, atol=1e-6,
                               err_msg="1f1b loss trajectory diverged")
    for name in p1:
        np.testing.assert_allclose(pf[name], p1[name], rtol=3e-4, atol=2e-5,
                                   err_msg=f"param {name!r} diverged (1f1b)")


def test_1f1b_matches_gpipe():
    """Same config trained under both schedules: identical trajectories."""
    batches = _batches(8, np.random.default_rng(3))
    mesh = make_mesh(data=2, pipe=4)
    lg, pg, _ = _train(_mlp_conf(4), mesh, batches)

    def conf_f():
        _mlp_conf(4)()
        from paddle_tpu.dsl.base import current_context
        current_context().opt.pipeline_schedule = "1f1b"
    lf, pf, _ = _train(conf_f, mesh, batches)
    np.testing.assert_allclose(lf, lg, rtol=2e-4, atol=1e-6)
    for name in pg:
        np.testing.assert_allclose(pf[name], pg[name], rtol=3e-4, atol=2e-5)


def test_1f1b_skip_connection():
    """Skip connections ride the carrier under the hand-scheduled backward
    too (the vjp recompute path must unpack/pack identically)."""
    def conf():
        from paddle_tpu.dsl import (
            ExtraLayerAttribute, MomentumOptimizer, ReluActivation,
            SoftmaxActivation, TanhActivation, classification_cost,
            data_layer, fc_layer, settings,
        )
        settings(batch_size=B, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9),
                 pipeline_micro_batches=4, pipeline_schedule="1f1b")
        x = data_layer(name="pixel", size=DIN)
        h0 = fc_layer(input=x, size=32, act=TanhActivation(),
                      layer_attr=ExtraLayerAttribute(device=0))
        h1 = fc_layer(input=h0, size=32, act=ReluActivation(),
                      layer_attr=ExtraLayerAttribute(device=1))
        h2 = fc_layer(input=[h1, h0], size=NCLS, act=SoftmaxActivation(),
                      layer_attr=ExtraLayerAttribute(device=2))
        classification_cost(input=h2,
                            label=data_layer(name="label", size=NCLS))

    batches = _batches(6, np.random.default_rng(1))
    l1, p1, _ = _train(conf, None, batches)
    mesh3 = make_mesh(data=1, pipe=3, devices=jax.devices()[:3])
    lf, pf, _ = _train(conf, mesh3, batches)
    np.testing.assert_allclose(lf, l1, rtol=2e-4, atol=1e-6)
    for name in p1:
        np.testing.assert_allclose(pf[name], p1[name], rtol=3e-4, atol=2e-5)


def test_schedule_info_accounting():
    from paddle_tpu.parallel.pipeline_config import PipelineExecutor
    mesh = make_mesh(data=2, pipe=4)
    cfg = parse_config_callable(_mlp_conf(4))
    ex = PipelineExecutor(cfg.model_config, mesh, n_micro=8,
                          schedule="gpipe")
    gi = ex.schedule_info()
    assert gi["bubble_fraction"] == pytest.approx(3 / 11)
    assert gi["in_flight_carriers"] == 8        # GPipe: grows with M
    ex2 = PipelineExecutor(cfg.model_config, mesh, n_micro=8,
                           schedule="1f1b")
    assert ex2.schedule_info()["in_flight_carriers"] == 4


def test_1f1b_checkgrad_audits_the_hand_scheduled_backward():
    """--job=checkgrad must validate loss_and_grad (what 1f1b training
    uses), not the autodiff of loss(): finite differences vs the
    hand-scheduled backward."""
    conf = _mlp_conf_1f1b(4, n_micro=4)
    mesh = make_mesh(data=2, pipe=4)
    tr = Trainer(parse_config_callable(conf), seed=1, mesh=mesh)
    rng = np.random.default_rng(0)
    batch = {"pixel": Argument(value=rng.normal(size=(B, DIN))
                               .astype(np.float32)),
             "label": Argument(ids=rng.integers(0, NCLS, B).astype(np.int32))}
    errors = tr.check_gradient(batch, max_entries=2)
    assert errors
    for name, err in errors.items():
        # fp32 central differences at eps=1e-3 carry a ~1e-2 noise floor
        # (small-magnitude entries divide an ~1e-4 absolute FD error); the
        # tight autodiff oracle below is the real correctness bar
        assert err < 5e-2, f"1f1b analytic grad for {name} off: {err}"

    # tight oracle: loss_and_grad (hand-scheduled backward) vs jax.grad of
    # loss() (GPipe autodiff) — dataflow-equivalent, so near-identical
    import jax
    from paddle_tpu.graph.context import TEST
    key = jax.random.PRNGKey(7)
    tr.executor.compute_dtype = ""
    _, g1 = jax.jit(lambda p: tr.executor.loss_and_grad(
        p, batch, TEST, key))(tr.params)
    g2 = jax.jit(jax.grad(lambda p: tr.executor.loss(
        p, batch, None, TEST, key)[0]))(tr.params)
    for n in g1:
        a, b = np.asarray(g1[n]), np.asarray(g2[n])
        rel = np.max(np.abs(a - b)) / max(np.max(np.abs(b)), 1e-9)
        assert rel < 1e-5, f"1f1b vs autodiff grads differ for {n}: {rel}"


@pytest.mark.slow
def test_pipeline_transformer_blocks():
    """Pipeline a transformer stack (the realistic pp workload, VERDICT r3:
    pp 'only ever exercised on small fc stacks'): 2 pre-norm blocks —
    layer_norm + causal multi-head attention + GELU MLP with residual
    addto — split device=0/1, sequence ids+lengths crossing the stage
    boundary.  Exactness vs un-pipelined, both schedules."""
    VOCAB, DIM, T = 32, 16, 8

    def conf(schedule="gpipe"):
        def f():
            from paddle_tpu.dsl import (
                AdamOptimizer, ExtraLayerAttribute, GeluActivation,
                LinearActivation, ParamAttr, SoftmaxActivation, addto_layer,
                classification_cost, data_layer, embedding_layer, fc_layer,
                layer_norm_layer, multi_head_attention_layer, settings,
            )
            settings(batch_size=8, learning_rate=1e-3,
                     learning_method=AdamOptimizer(),
                     pipeline_micro_batches=2,
                     pipeline_schedule=schedule)
            toks = data_layer(name="tokens", size=VOCAB)
            h = embedding_layer(
                input=toks, size=DIM,
                param_attr=ParamAttr(name="_emb", initial_std=0.02),
                layer_attr=ExtraLayerAttribute(device=0))
            for i, dev in enumerate([0, 1]):
                attr = ExtraLayerAttribute(device=dev)
                ln1 = layer_norm_layer(input=h, name=f"b{i}_ln1",
                                       layer_attr=attr)
                att = multi_head_attention_layer(
                    ln1, size=DIM, num_heads=2, causal=True, use_rope=True,
                    name=f"b{i}_att", layer_attr=attr)
                h = addto_layer(input=[h, att], act=LinearActivation(),
                                name=f"b{i}_r1", bias_attr=False,
                                layer_attr=attr)
                ln2 = layer_norm_layer(input=h, name=f"b{i}_ln2",
                                       layer_attr=attr)
                ff = fc_layer(input=ln2, size=DIM * 2, act=GeluActivation(),
                              name=f"b{i}_ff1", bias_attr=True,
                              layer_attr=attr)
                ff = fc_layer(input=ff, size=DIM, act=LinearActivation(),
                              name=f"b{i}_ff2", bias_attr=True,
                              layer_attr=attr)
                h = addto_layer(input=[h, ff], act=LinearActivation(),
                                name=f"b{i}_r2", bias_attr=False,
                                layer_attr=attr)
            logits = fc_layer(input=h, size=VOCAB, act=SoftmaxActivation(),
                              name="head", bias_attr=False,
                              layer_attr=ExtraLayerAttribute(device=1))
            classification_cost(input=logits,
                                label=data_layer(name="next", size=VOCAB))
        return f

    rng = np.random.default_rng(11)
    batches = []
    for _ in range(6):
        lens = np.full((8,), T, np.int32)
        batches.append({
            "tokens": Argument(ids=rng.integers(0, VOCAB, (8, T))
                               .astype(np.int32), lengths=lens),
            "next": Argument(ids=rng.integers(0, VOCAB, (8, T))
                             .astype(np.int32), lengths=lens),
        })

    l1, p1, _ = _train(conf(), None, batches)
    mesh = make_mesh(data=4, pipe=2)
    for schedule in ("gpipe", "1f1b"):
        lp, pp_, tr = _train(conf(schedule), mesh, batches)
        assert tr.executor.schedule == schedule
        np.testing.assert_allclose(
            lp, l1, rtol=2e-4, atol=1e-6,
            err_msg=f"transformer pp loss diverged ({schedule})")
        for name in p1:
            np.testing.assert_allclose(
                pp_[name], p1[name], rtol=3e-4, atol=2e-5,
                err_msg=f"param {name!r} diverged ({schedule})")


def _mlp_conf_interleaved(n_chunks, v, n_micro=4):
    def conf():
        _mlp_conf(n_chunks)()
        from paddle_tpu.dsl.base import current_context
        opt = current_context().opt
        opt.pipeline_schedule = "interleaved"
        opt.pipeline_virtual_stages = v
        opt.pipeline_micro_batches = n_micro
    return conf


def test_interleaved_matches_unpipelined():
    """Interleaved 1F1B (v=2 virtual stages on a 2-device pipe axis: 4
    chunks round-robin, device 0 hosts chunks 0+2, device 1 hosts 1+3) —
    same exactness bar as every other schedule: losses AND final params
    equal un-pipelined training.  Also drives the forward-only table
    (executor.loss) and the schedule accounting."""
    batches = _batches(8, np.random.default_rng(21))
    conf = _mlp_conf_interleaved(4, v=2, n_micro=4)
    l1, p1, tr1 = _train(conf, None, batches)
    mesh = make_mesh(data=4, pipe=2)
    li, pi, tr = _train(conf, mesh, batches)
    assert tr.executor.schedule == "interleaved"
    info = tr.executor.schedule_info()
    assert info["virtual_stages"] == 2
    C, M = 4, 4
    # the simulated schedule must beat the depth-C 1F1B lockstep formula
    assert info["ticks"] <= 2 * (M + C - 1), info
    np.testing.assert_allclose(li, l1, rtol=2e-4, atol=1e-6,
                               err_msg="interleaved loss trajectory diverged")
    for name in p1:
        np.testing.assert_allclose(
            pi[name], p1[name], rtol=3e-4, atol=2e-5,
            err_msg=f"param {name!r} diverged (interleaved)")
    # forward-only table (test/eval path) matches the unpipelined loss
    import jax
    from paddle_tpu.graph.context import TEST
    b = batches[0]
    lu = float(tr1.executor.loss(tr1.params, b, None, TEST, None)[0])
    lp = float(jax.jit(lambda p: tr.executor.loss(
        p, b, None, TEST, None)[0])(tr.params))
    assert abs(lp - lu) < 1e-4, (lp, lu)


def test_interleaved_v1_matches_1f1b():
    """v=1 interleaved is plain 1F1B expressed as a schedule table — the
    two implementations must produce identical trajectories."""
    batches = _batches(6, np.random.default_rng(22))
    mesh = make_mesh(data=2, pipe=4)

    def conf_1f1b():
        _mlp_conf(4)()
        from paddle_tpu.dsl.base import current_context
        current_context().opt.pipeline_schedule = "1f1b"
        current_context().opt.pipeline_micro_batches = 4
    lf, pf, _ = _train(conf_1f1b, mesh, batches)
    li, pi, tr = _train(_mlp_conf_interleaved(4, v=1, n_micro=4), mesh,
                        batches)
    assert tr.executor.n_chunks == 4
    np.testing.assert_allclose(li, lf, rtol=1e-5, atol=1e-7)
    for name in pf:
        np.testing.assert_allclose(pi[name], pf[name], rtol=1e-5, atol=1e-6)


def test_schedule_table_invariants():
    """Brute-force verification of the compiled interleaved tables across
    a sweep of (S, v, M): every op scheduled exactly once, dependencies
    respected with ring-hop latency, at most one op per device per leg
    per tick, deposits routed to the consumer's slot before use, and no
    two live carriers ever share a stash slot.  Pure-numpy simulation of
    exactly what the scan body executes."""
    from paddle_tpu.parallel.pipeline_config import _compile_schedule

    for S, v, M in [(2, 1, 2), (2, 2, 4), (3, 2, 2), (4, 1, 8),
                    (2, 3, 3), (4, 2, 4), (5, 2, 3)]:
        for fwd_only in (False, True):
            tbl = _compile_schedule(S, v, M, fwd_only=fwd_only)
            C = S * v
            tF, tB = {}, {}
            for t in range(tbl.T):
                for s in range(S):
                    if tbl.f_run[s, t]:
                        c, m = int(tbl.f_chunk[s, t]), int(tbl.f_m[s, t])
                        assert c % S == s, (c, s)
                        assert (c, m) not in tF, "double-scheduled F"
                        tF[(c, m)] = t
                    if tbl.b_run[s, t]:
                        c, m = int(tbl.b_chunk[s, t]), int(tbl.b_m[s, t])
                        assert c % S == s
                        assert (c, m) not in tB, "double-scheduled B"
                        tB[(c, m)] = t
            # completeness
            assert len(tF) == C * M
            assert len(tB) == (0 if fwd_only else C * M)
            # dependency order with one-tick ring latency; F-before-B
            for (c, m), t in tF.items():
                if c > 0:
                    assert t >= tF[(c - 1, m)] + 1, (c, m)
            for (c, m), t in tB.items():
                assert t >= tF[(c, m)], (c, m)
                if c < C - 1:
                    assert t >= tB[(c + 1, m)] + 1, (c, m)
            # deposit routing: the arrival of F(c-1,m)'s output lands on
            # device c%S at tick tF(c-1,m)+1 in the slot F(c,m) reads;
            # slot 0 (zeros) only for chunk 0 / last-chunk cotangent
            for (c, m), t in tF.items():
                slot = int(tbl.f_slot[c % S, t])
                if c == 0:
                    assert slot == 0
                else:
                    arr = tF[(c - 1, m)] + 1
                    assert int(tbl.f_dep[c % S, arr]) == slot > 0
                    # the slot is not overwritten between arrival and the
                    # BACKWARD consumption (B recomputes from it)
                    last_use = t if fwd_only else tB[(c, m)]
                    for t2 in range(arr + 1, last_use + 1):
                        assert int(tbl.f_dep[c % S, t2]) != slot, \
                            (c, m, "slot overwritten while live")
            for (c, m), t in tB.items():
                slot = int(tbl.b_slot[c % S, t])
                if c == C - 1:
                    assert slot == 0
                else:
                    arr = tB[(c + 1, m)] + 1
                    assert int(tbl.b_dep[c % S, arr]) == slot > 0
                    for t2 in range(arr + 1, t + 1):
                        assert int(tbl.b_dep[c % S, t2]) != slot
                assert int(tbl.b_fslot[c % S, t]) == \
                    int(tbl.f_slot[c % S, tF[(c, m)]])
