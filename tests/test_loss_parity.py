"""Loss parity against the reference training math (BASELINE.md:
"samples/sec/chip + loss parity").

No public dataset is reachable from this machine (zero egress), so parity
is asserted in its strongest falsifiable form: the SAME VGG-style network,
initialized with the SAME weights (transferred via tools/torch2paddle),
trained on the SAME batches with the SAME optimizer (SGD momentum + L2)
must produce the SAME per-step loss curve as torch-CPU — the
implementation used to measure the reference baseline numbers in
BASELINE.json.  This checks conv/BN/pool/fc forward, their backward
passes, and the updater math end to end; a single wrong gradient or a
mismatched BN/momentum/L2 convention diverges the curve within steps.
(ref: trainer/tests/test_CompareTwoNets.cpp — step-wise parameter/cost
comparison between two implementations of one network.)
"""

import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

torch = pytest.importorskip("torch")

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.tools.torch2paddle import convert_state_dict
from paddle_tpu.trainer.trainer import Trainer

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")

LR = 0.002
MOM = 0.9
L2 = 5e-4
BATCH = 16
STEPS = 8

CFG = """
from paddle_tpu.dsl import *

settings(batch_size=16, learning_rate=0.002,
         learning_method=MomentumOptimizer(momentum=0.9),
         regularization=L2Regularization(5e-4))
img = data_layer(name="image", size=3*32*32, height=32, width=32)
c1 = img_conv_layer(input=img, filter_size=3, num_filters=32, padding=1,
                    stride=1, act=LinearActivation(), bias_attr=False,
                    num_channels=3)
b1 = batch_norm_layer(input=c1, act=ReluActivation())
p1 = img_pool_layer(input=b1, pool_size=2, stride=2, pool_type=MaxPooling())
c2 = img_conv_layer(input=p1, filter_size=3, num_filters=64, padding=1,
                    stride=1, act=LinearActivation(), bias_attr=False)
b2 = batch_norm_layer(input=c2, act=ReluActivation())
p2 = img_pool_layer(input=b2, pool_size=2, stride=2, pool_type=MaxPooling())
h = fc_layer(input=p2, size=128, act=ReluActivation(), bias_attr=True)
out = fc_layer(input=h, size=10, act=SoftmaxActivation(), bias_attr=True)
classification_cost(input=out, label=data_layer(name="label", size=10))
"""


@pytest.mark.skipif(not os.environ.get("PADDLE_TPU_SLOW_TESTS"),
                    reason="slow quality run; set PADDLE_TPU_SLOW_TESTS=1")
def test_vgg_cifar_quality():
    """Train the demo small_vgg to a reported accuracy (ref:
    demo/image_classification/train.sh quality expectation).  On real
    CIFAR-10 (drop the pickle batches under
    demo/image_classification/data/cifar-10-batches-py) this trains the
    real task; hermetically it trains the provider's deterministic
    template-class dataset (2x40 batches of 64, test error bar < 0.15,
    ~5 min on one CPU core)."""
    import itertools

    cfg = parse_config(
        os.path.join(REPO, "demo/image_classification/vgg_16_cifar.py"),
        "batch_size=64")
    tr = Trainer(cfg, seed=0)
    for _ in range(2):
        tr.train_one_pass(batches=itertools.islice(tr.train_batches(), 40),
                          log_period=0)
    stats = tr.test()
    assert stats["classification_error"] < 0.15, stats


class TorchTwin(torch.nn.Module):
    def __init__(self):
        super().__init__()
        self.c1 = torch.nn.Conv2d(3, 32, 3, padding=1, bias=False)
        self.b1 = torch.nn.BatchNorm2d(32)
        self.c2 = torch.nn.Conv2d(32, 64, 3, padding=1, bias=False)
        self.b2 = torch.nn.BatchNorm2d(64)
        self.fc1 = torch.nn.Linear(64 * 8 * 8, 128)
        self.fc2 = torch.nn.Linear(128, 10)

    def forward(self, x):
        x = torch.relu(self.b1(self.c1(x)))
        x = torch.max_pool2d(x, 2, 2)
        x = torch.relu(self.b2(self.c2(x)))
        x = torch.max_pool2d(x, 2, 2)
        x = torch.relu(self.fc1(x.flatten(1)))
        return self.fc2(x)


def test_vgg_loss_curve_matches_torch(tmp_path):
    path = str(tmp_path / "parity_cfg.py")
    with open(path, "w") as f:
        f.write(CFG)
    try:
        torch.manual_seed(0)
        tm = TorchTwin()
        tm.train()

        cfg = parse_config(path, "")
        tr = Trainer(cfg, seed=0)
        sd = {k: v for k, v in tm.state_dict().items()
              if "running_" not in k and "num_batches" not in k}
        converted = convert_state_dict(sd, cfg.model_config)
        assert set(converted) == set(tr.params), (
            sorted(converted), sorted(tr.params))
        import jax.numpy as jnp
        tr.params = {k: jnp.asarray(v) for k, v in converted.items()}
        tr.opt_state = tr.updater.init_state(tr.params)

        rng = np.random.default_rng(0)
        # cycle 2 fixed batches so memorization drives the curve DOWN —
        # parity on a rising noise-fit curve would still pass allclose, but
        # a descending curve also catches sign errors in the update
        xs_pool = rng.normal(size=(2, BATCH, 3, 32, 32)).astype(np.float32)
        W = rng.normal(size=(3 * 32 * 32, 10)).astype(np.float32)
        ys_pool = np.argmax(xs_pool.reshape(2, BATCH, -1) @ W, -1).astype(np.int64)
        xs = xs_pool[np.arange(STEPS) % 2]
        ys = ys_pool[np.arange(STEPS) % 2]

        # torch side: plain SGD momentum + coupled L2 (same math as the
        # updater: g += l2*p, v = m*v - lr*g, p += v under constant lr)
        opt = torch.optim.SGD(tm.parameters(), lr=LR, momentum=MOM,
                              weight_decay=L2)
        t_losses = []
        for s in range(STEPS):
            opt.zero_grad()
            logits = tm(torch.from_numpy(xs[s]))
            loss = torch.nn.functional.cross_entropy(
                logits, torch.from_numpy(ys[s]))
            loss.backward()
            opt.step()
            t_losses.append(float(loss))

        p_losses = []
        for s in range(STEPS):
            flat = xs[s].reshape(BATCH, -1)   # C-major rows == torch layout
            loss = tr.train_one_batch(
                {"image": Argument(value=flat),
                 "label": Argument(ids=ys[s].astype(np.int32))})
            p_losses.append(float(loss))
        tr._drain_losses()

        t_losses = np.asarray(t_losses)
        p_losses = np.asarray(p_losses)
        # identical math in fp32: per-step agreement to ~1e-3 relative
        np.testing.assert_allclose(p_losses, t_losses, rtol=5e-3, atol=5e-4,
                                   err_msg=f"torch={t_losses} ours={p_losses}")
        # and the curve actually moved (parity of a flat line proves nothing)
        assert t_losses[-1] < t_losses[0]
    finally:
        os.remove(path)
