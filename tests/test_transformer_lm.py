"""Transformer LM demo — the beyond-reference model family assembled from
the long-context stack (rotary multi-head attention, pre-norm layer_norm +
GELU blocks) through the classic DSL, including context-parallel training
over a mesh `seq` axis."""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.trainer.trainer import Trainer

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


CFG = "demo/model_zoo/transformer_lm.py"


def _train(args, mesh=None, steps=12):
    cfg = parse_config(CFG, args)
    tr = Trainer(cfg, seed=0, mesh=mesh)
    it = tr.train_batches()
    return [float(tr.train_one_batch(next(it))) for _ in range(steps)]


def test_lm_learns_the_motif_language():
    cfg = parse_config(CFG, "dim=32,layers=2,heads=4,vocab=64,batch_size=8")
    tr = Trainer(cfg, seed=0)
    first = tr.train_one_pass(batches=tr.train_batches())["cost"]
    last = first
    for _ in range(3):
        last = tr.train_one_pass(batches=tr.train_batches())["cost"]
    assert np.isfinite(last)
    assert last < first * 0.8, (first, last)


def test_lm_trains_context_parallel_over_seq_axis():
    """Same config over a (data=2, seq=4) mesh: ring attention carries the
    sequence shards; losses must track the single-device run closely (ring
    reduction order differs, so allclose with a loose-but-real tolerance)."""
    args = "dim=32,layers=1,heads=4,vocab=64,batch_size=8"
    l1 = _train(args, steps=6)
    lm = _train(args, mesh=make_mesh(data=2, seq=4), steps=6)
    assert np.isfinite(lm).all()
    np.testing.assert_allclose(lm, l1, rtol=5e-3, atol=5e-3)


def test_lm_gqa_and_window_variants():
    for args in ("dim=32,layers=1,heads=4,kv_heads=2,vocab=64,batch_size=8",
                 "dim=32,layers=1,heads=4,window=8,vocab=64,batch_size=8"):
        losses = _train(args, steps=4)
        assert np.isfinite(losses).all(), (args, losses)


def test_lm_layer_norm_and_gelu_grads():
    """f64 finite-difference gradient check on the new layer types
    (layer_norm scale/bias, GELU fc) — a tiny pre-norm block, same harness
    discipline as tests/test_layer_grad.py."""
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from test_layer_grad import fd_check

    def conf():
        from paddle_tpu.dsl import (GeluActivation, SoftmaxActivation,
                                    classification_cost, data_layer,
                                    fc_layer, layer_norm_layer, settings)
        settings(batch_size=3, learning_rate=0.1)
        x = data_layer(name="x", size=8)
        n = layer_norm_layer(input=x)
        h = fc_layer(input=n, size=8, act=GeluActivation(),
                     param_attr=None, bias_attr=True)
        out = fc_layer(input=h, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))

    rng = np.random.default_rng(0)
    feed = {"x": Argument(value=rng.standard_normal((3, 8))
                          .astype(np.float32)),
            "y": Argument(ids=rng.integers(0, 3, 3).astype(np.int32))}
    fd_check(parse_config_callable(conf), feed)


def test_lm_generate_greedy_and_sampled():
    """Compiled autoregressive decode over the trained motif LM: greedy
    continuation of a motif prefix must beat random tokens on model
    likelihood, eos stops rows early, and sampling respects top_k."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.graph.lm_decode import lm_generate
    from paddle_tpu.parameter.argument import Argument

    cfg = parse_config(CFG, "dim=32,layers=2,heads=4,vocab=64,batch_size=8")
    tr = Trainer(cfg, seed=0)
    for _ in range(3):
        tr.train_one_pass(batches=tr.train_batches())

    # prompts: real motif-language prefixes from the provider
    it = tr.train_batches()
    batch = next(it)
    prompt = np.asarray(batch["tokens"].ids)[:4, :8]
    out, lengths = lm_generate(tr.executor, tr.params, prompt, max_new=8)
    out, lengths = np.asarray(out), np.asarray(lengths)
    assert out.shape == (4, 16) and (lengths == 16).all()
    np.testing.assert_array_equal(out[:, :8], prompt)

    # the model must prefer its own greedy continuation to random tokens
    def seq_logprob(tokens):
        feed = {"tokens": Argument(ids=jnp.asarray(tokens, jnp.int32),
                                   lengths=jnp.full((4,), 15, jnp.int32))}
        outputs, _, _ = tr.executor.forward(tr.params, feed)
        probs = np.asarray(outputs["lm_head"].value, np.float32)
        lp = 0.0
        for b in range(4):
            for t in range(8 - 1, 14):       # score the generated region
                lp += np.log(max(probs[b, t, tokens[b, t + 1]], 1e-30))
        return lp

    rng = np.random.default_rng(0)
    rand = out[:, :15].copy()
    rand[:, 8:] = rng.integers(2, 64, (4, 7))
    assert seq_logprob(out[:, :15]) > seq_logprob(rand) + 1.0

    # eos freezes rows at the stop token
    eos = int(out[0, 8])                     # force an early stop for row 0
    out2, len2 = lm_generate(tr.executor, tr.params, prompt, max_new=8,
                             eos_id=eos)
    out2, len2 = np.asarray(out2), np.asarray(len2)
    assert (len2 <= 16).all() and len2.min() < 16

    # top-k sampling stays within the model's k best at each step
    out3, _ = lm_generate(tr.executor, tr.params, prompt, max_new=4,
                          temperature=0.8, top_k=1,
                          rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(out3)[:, :12],
                                  np.asarray(out[:, :12]))  # top_k=1 == greedy

    # nucleus sampling: a vanishing top_p keeps only the argmax token
    # (== greedy), and top_p=1.0 disables the cut (== full sampling,
    # exact by the gate — no float-rounding knife edge)
    out4, _ = lm_generate(tr.executor, tr.params, prompt, max_new=4,
                          temperature=0.8, top_p=1e-9,
                          rng=jax.random.PRNGKey(1))
    np.testing.assert_array_equal(np.asarray(out4)[:, :12],
                                  np.asarray(out[:, :12]))
    full, _ = lm_generate(tr.executor, tr.params, prompt, max_new=4,
                          temperature=0.8, rng=jax.random.PRNGKey(2))
    nuc, _ = lm_generate(tr.executor, tr.params, prompt, max_new=4,
                         temperature=0.8, top_p=1.0,
                         rng=jax.random.PRNGKey(2))
    np.testing.assert_array_equal(np.asarray(nuc), np.asarray(full))


def test_nucleus_filter_exact_support():
    """nucleus_filter keeps exactly the smallest cum-prob prefix — the
    first token AT the threshold stays, logit ties at the cutoff cannot
    widen the set, and an argmax-only cut survives."""
    import jax.numpy as jnp
    from paddle_tpu.graph.lm_decode import nucleus_filter

    # probs [0.4, 0.3, 0.2, 0.1] -> top_p=0.5 keeps exactly two tokens
    logits = jnp.log(jnp.asarray([[0.4, 0.3, 0.2, 0.1]], jnp.float32))
    out = np.asarray(nucleus_filter(logits, 0.5))
    assert np.isfinite(out[0, :2]).all() and np.isneginf(out[0, 2:]).all()

    # exact tie at the cutoff: [2.0, 2.0, 0.0] with a tiny top_p must keep
    # ONE of the tied tokens, not both
    tied = jnp.asarray([[2.0, 2.0, 0.0]], jnp.float32)
    out = np.asarray(nucleus_filter(tied, 0.3))
    assert np.sum(np.isfinite(out)) == 1, out

    # vanishing top_p -> argmax only; gate disables at 0 and 1
    out = np.asarray(nucleus_filter(logits, 1e-9))
    assert np.sum(np.isfinite(out)) == 1 and np.isfinite(out[0, 0])
    for p in (0.0, 1.0):
        np.testing.assert_array_equal(
            np.asarray(nucleus_filter(logits, p)), np.asarray(logits))


def test_sampling_knobs_need_temperature():
    """top_k/top_p with the default temperature=0 (greedy) would be
    silently ignored — lm_generate must reject the combination."""
    import pytest
    from paddle_tpu.graph.lm_decode import lm_generate

    cfg = parse_config(CFG, "dim=32,layers=1,heads=2,vocab=32,batch_size=4")
    tr = Trainer(cfg, seed=0)
    prompt = np.zeros((2, 4), np.int32)
    with pytest.raises(ValueError, match="temperature"):
        lm_generate(tr.executor, tr.params, prompt, max_new=2, top_p=0.9)
    with pytest.raises(ValueError, match="temperature"):
        lm_generate(tr.executor, tr.params, prompt, max_new=2, top_k=5)


def test_byte_level_provider_on_real_text(tmp_path, monkeypatch):
    """lm_provider's byte mode: pointing the train list at an existing
    text file trains byte-level LM on its contents (the synthetic motif
    stream stays the fallback for the stock placeholder list)."""
    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over the lazy dog. " * 40)
    lst = tmp_path / "train.list"
    lst.write_text(str(corpus) + "\n")

    import demo.model_zoo.lm_provider as lp

    class S:
        pass

    s = S()
    lp.process.init_hook(s, str(lst), vocab=258)
    samples = list(lp.process.fn(s, str(corpus)))
    assert len(samples) > 10
    for smp in samples[:5]:
        toks, nxt = smp["tokens"], smp["next_tokens"]
        assert toks[0] == 1                     # BOS
        assert toks[1:] == nxt[:-1]             # shifted by one
        assert all(2 <= t < 258 for t in nxt)   # byte ids
    # round-trips back to the source text
    txt = bytes(t - 2 for t in samples[0]["next_tokens"]).decode()
    assert txt.startswith("the quick brown fox")
    # the stock placeholder (missing file) still yields the synthetic
    # stream
    synth = list(lp.process.fn(s, "dummy"))
    assert len(synth) == 256
