"""Config-pair equivalence oracles (ref: paddle/gserver/tests/
test_NetworkCompare.cpp, paddle/trainer/tests/test_CompareTwoNets.cpp):
two differently-expressed configs of the same function must produce
identical outputs, gradients, and — after identical update sequences —
identical final parameters.  These catch "compiles but computes the wrong
graph" bugs that per-layer finite-difference checks cannot."""

import os
import sys

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer

FC_CFG = """
from paddle_tpu.dsl import *
settings(batch_size=8, learning_rate=0.1,
         learning_method=MomentumOptimizer(momentum=0.9))
x = data_layer(name="x", size=16)
h = fc_layer(input=x, size=24, act=TanhActivation(), bias_attr=True)
out = fc_layer(input=h, size=4, act=SoftmaxActivation(), bias_attr=True)
classification_cost(input=out, label=data_layer(name="label", size=4))
"""

# the same network via mixed_layer + full_matrix projections
# (ref: test_NetworkCompare.cpp compareNetwork config pairs)
MIXED_CFG = """
from paddle_tpu.dsl import *
settings(batch_size=8, learning_rate=0.1,
         learning_method=MomentumOptimizer(momentum=0.9))
x = data_layer(name="x", size=16)
with mixed_layer(size=24, act=TanhActivation(), bias_attr=True) as h:
    h += full_matrix_projection(input=x, size=24)
with mixed_layer(size=4, act=SoftmaxActivation(), bias_attr=True) as out:
    out += full_matrix_projection(input=h, size=4)
classification_cost(input=out, label=data_layer(name="label", size=4))
"""


def _write(tmp_name, src):
    path = os.path.join(REPO, "tests", tmp_name)
    with open(path, "w") as f:
        f.write(src)
    return path


def _batches(n=6, B=8, seed=0):
    rng = np.random.default_rng(seed)
    W = rng.normal(size=(16, 4)).astype(np.float32)
    out = []
    for _ in range(n):
        x = rng.normal(size=(B, 16)).astype(np.float32)
        y = np.argmax(x @ W, -1).astype(np.int32)
        out.append({"x": Argument(value=x), "label": Argument(ids=y)})
    return out


def test_mixed_layer_matches_fc_layer():
    pa = _write("_eq_fc.py", FC_CFG)
    pb = _write("_eq_mixed.py", MIXED_CFG)
    try:
        batches = _batches()
        results = []
        for path in (pa, pb):
            cfg = parse_config(path, "")
            ex = GraphExecutor(cfg.model_config)
            params = ex.init_params(jax.random.PRNGKey(11))

            loss, grads = jax.value_and_grad(
                lambda p: ex.loss(p, batches[0])[0])(params)

            # full update sequence through the Trainer
            tr = Trainer(cfg, seed=11)
            for b in batches:
                tr.train_one_batch(b)
            results.append((float(loss), grads, jax.device_get(tr.params)))

        (la, ga, fa), (lb, gb, fb) = results
        assert abs(la - lb) < 1e-6, (la, lb)
        # params pair positionally (names legitimately differ between the
        # two expressions); the counts must match or the oracle is void
        assert len(ga) == len(gb), (sorted(ga), sorted(gb))
        assert len(fa) == len(fb), (sorted(fa), sorted(fb))
        for ka, kb in zip(ga, gb):
            np.testing.assert_allclose(np.asarray(ga[ka]), np.asarray(gb[kb]),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"grad {ka} vs {kb}")
        for ka, kb in zip(fa, fb):
            np.testing.assert_allclose(np.asarray(fa[ka]), np.asarray(fb[kb]),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"final param {ka} vs {kb}")
    finally:
        os.remove(pa)
        os.remove(pb)
