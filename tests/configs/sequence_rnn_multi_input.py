"""Flat twin of sequence_nest_rnn_multi_input.py
(ref: gserver/tests/sequence_rnn_multi_input.conf)."""

from paddle_tpu.dsl import *

settings(batch_size=2, learning_rate=0.01)

dict_dim = 10
word_dim = 8
hidden_dim = 8
label_dim = 3

data = data_layer(name="word", size=dict_dim)
emb = embedding_layer(input=data, size=word_dim)


def step(y, wid):
    z = embedding_layer(input=wid, size=word_dim)
    mem = memory(name="rnn_state", size=hidden_dim)
    return fc_layer(input=[y, z, mem], size=hidden_dim,
                    act=TanhActivation(), bias_attr=True, name="rnn_state")


out = recurrent_group(name="rnn", step=step, input=[emb, data])

rep = last_seq(input=out)
prob = fc_layer(size=label_dim, input=rep, act=SoftmaxActivation(),
                bias_attr=True)
classification_cost(input=prob, label=data_layer(name="label", size=label_dim))
