"""Hierarchical RNN with TWO nested in-links (raw ids + embeddings), the
inner step embedding its id slice — equivalent to the flat twin
(ref: gserver/tests/sequence_nest_rnn_multi_input.conf)."""

from paddle_tpu.dsl import *

settings(batch_size=2, learning_rate=0.01)

dict_dim = 10
word_dim = 8
hidden_dim = 8
label_dim = 3

data = data_layer(name="word", size=dict_dim)
emb = embedding_layer(input=data, size=word_dim)


def outer_step(wid, x):
    outer_mem = memory(name="outer_rnn_state", size=hidden_dim)

    def inner_step(y, wid):
        z = embedding_layer(input=wid, size=word_dim)
        inner_mem = memory(name="inner_rnn_state", size=hidden_dim,
                           boot_layer=outer_mem)
        return fc_layer(input=[y, z, inner_mem], size=hidden_dim,
                        act=TanhActivation(), bias_attr=True,
                        name="inner_rnn_state")

    inner_rnn_output = recurrent_group(
        step=inner_step, name="inner", input=[x, wid])
    last_seq(input=inner_rnn_output, name="outer_rnn_state")
    return inner_rnn_output


out = recurrent_group(name="outer", step=outer_step,
                      input=[SubsequenceInput(data), SubsequenceInput(emb)])

rep = last_seq(input=out)
prob = fc_layer(size=label_dim, input=rep, act=SoftmaxActivation(),
                bias_attr=True)
classification_cost(input=prob, label=data_layer(name="label", size=label_dim))
