"""auc-validation / pnpair-validation layer types — evaluation inside the
graph during training (ref: paddle/gserver/layers/ValidationLayer.cpp,
created at Layer.cpp:116-119; config classes config_parser.py:1961-1962).

Oracle strategy: run the model forward once to get its actual scores, compute
AUC / pnpair with straight numpy, and require the in-graph validation layers
to report the same numbers through Trainer.test()."""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.data.provider import dense_vector, integer_value, provider
from paddle_tpu.dsl import (
    SoftmaxActivation, TanhActivation, auc_validation, classification_cost,
    data_layer, fc_layer, pnpair_validation, settings,
)
from paddle_tpu.trainer.trainer import Trainer

DIM = 8
N = 64


def _config():
    settings(batch_size=16, learning_rate=0.1)
    x = data_layer(name="x", size=DIM)
    h = fc_layer(input=x, size=16, act=TanhActivation())
    out = fc_layer(input=h, size=2, act=SoftmaxActivation())
    lbl = data_layer(name="label", size=2)
    qid = data_layer(name="qid", size=N)
    classification_cost(input=out, label=lbl)
    auc_validation(input=out, label=lbl, name="val_auc")
    pnpair_validation(input=out, label=lbl, info=qid, name="val_pnpair")


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((N, DIM)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int32)
    qid = (np.arange(N) // 8).astype(np.int32)     # 8 queries of 8 rows
    return x, y, qid


@provider(input_types={"x": dense_vector(DIM), "label": integer_value(2),
                       "qid": integer_value(N)})
def _prov(settings, fname):
    x, y, qid = _data()
    for i in range(N):
        yield [x[i], int(y[i]), int(qid[i])]


def _numpy_auc(scores, labels, bins=1024):
    """The evaluator's own histogram method, independently re-derived."""
    idx = np.clip((scores * bins).astype(np.int64), 0, bins - 1)
    pos = np.bincount(idx, weights=labels, minlength=bins)
    neg = np.bincount(idx, weights=1.0 - labels, minlength=bins)
    tp, fp = np.cumsum(pos[::-1]), np.cumsum(neg[::-1])
    tpr = np.concatenate([[0.0], tp / tp[-1]])
    fpr = np.concatenate([[0.0], fp / fp[-1]])
    return float(np.trapezoid(tpr, fpr))


def _numpy_pnpair(scores, labels, qid):
    pos = neg = 0.0
    for q in np.unique(qid):
        sel = qid == q
        s, l = scores[sel], labels[sel]
        for a in range(len(s)):
            for b in range(a + 1, len(s)):
                if l[a] == l[b]:
                    continue
                if (s[a] - s[b]) * (l[a] - l[b]) > 0:
                    pos += 1.0
                elif (s[a] - s[b]) * (l[a] - l[b]) < 0:
                    neg += 1.0
    return pos, neg


@pytest.fixture(scope="module")
def trained():
    cfg = parse_config_callable(_config)
    tr = Trainer(cfg, seed=11)
    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(_prov, ["d"], ["x", "label", "qid"],
                        batch_size=16, seed=3, shuffle=False, drop_last=False)
    metrics = tr.test(batches=feeder.batches())
    # model scores for the oracle: forward via the executor
    x, y, qid = _data()
    from paddle_tpu.parameter.argument import Argument
    outputs, _, _ = tr.executor.forward(
        tr.params,
        {"x": Argument(value=x), "label": Argument(ids=y),
         "qid": Argument(ids=qid)},
        None, "test", None)
    score_layer = [l.name for l in tr.model.layers if l.type == "fc"][-1]
    scores = np.asarray(outputs[score_layer].value)[:, 1]
    return metrics, scores, y, qid


def test_auc_validation_matches_numpy(trained):
    metrics, scores, y, qid = trained
    key = [k for k in metrics if "val_auc" in k and "auc" in k]
    assert key, f"auc-validation metric missing from {sorted(metrics)}"
    want = _numpy_auc(scores, y.astype(np.float64))
    assert metrics[key[0]] == pytest.approx(want, abs=1e-6)


def test_pnpair_validation_matches_numpy(trained):
    metrics, scores, y, qid = trained
    key = [k for k in metrics if "val_pnpair" in k and k.endswith("pnpair")]
    assert key, f"pnpair-validation metric missing from {sorted(metrics)}"
    pos, neg = _numpy_pnpair(scores, y, qid)
    assert metrics[key[0]] == pytest.approx(pos / max(neg, 1e-8), rel=1e-6)


def test_validation_layers_train_ok():
    """Training with validation layers present must run and not affect
    gradients (reference backward is a no-op)."""
    cfg = parse_config_callable(_config)
    tr = Trainer(cfg, seed=11)
    from paddle_tpu.data.feeder import DataFeeder
    feeder = DataFeeder(_prov, ["d"], ["x", "label", "qid"],
                        batch_size=16, seed=3)
    stats = tr.train_one_pass(batches=feeder.batches())
    assert np.isfinite(stats["cost"])
    assert any("val_auc" in k for k in stats), sorted(stats)
