"""Distributed-training exactness oracles (ISSUE 14 acceptance).

The headline contract: K=2 trainer PROCESSES (real `tools/train_dist.py`
subprocesses over real TCP) against a pserver produce parameters
BIT-IDENTICAL to a single-process run with `grad_accum=K` — including
the poly LR schedule, L2 weight decay, and model averaging, all of which
live server-side.  The slow churn soak kills a trainer with SIGKILL
mid-training and proves the surviving fleet's final parameters replay
EXACTLY from the server's commit log (zero lost updates, exact
rank-ordered reduction under churn)."""

import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG = "demo/distributed/mlp_dist.py"
# small but non-trivial: 8 batches/pass, full update-rule surface ON
CONFIG_ARGS = ("samples=128,batch_size=16,dim=16,hidden=32,"
               "l2=0.0001,avg_window=0.5")


def _spawn_trainer(port, rank, trainers, passes, extra=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "train_dist.py"),
         "--config", CONFIG, "--config-args", CONFIG_ARGS,
         "--pserver", f"127.0.0.1:{port}", "--rank", str(rank),
         "--trainers", str(trainers), "--passes", str(passes), *extra],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


def _oracle_trainer(accum, updater=None):
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config(CONFIG, CONFIG_ARGS)
    cfg.opt_config.num_batches_per_send_parameter = accum
    return Trainer(cfg, seed=1, updater=updater)


def _host(tree):
    import jax

    return {k: np.asarray(jax.device_get(v)) for k, v in tree.items()}


def test_sync_k2_processes_bit_exact_vs_grad_accum2(tmp_path):
    """THE acceptance oracle: two trainer processes, disjoint stride
    shards, 2 passes == one process with grad_accum=2, bit for bit —
    run with the FULL tracing stack ON both sides (server ring enabled,
    trainers --trace-out), so the observability tier provably never
    perturbs the update math (ISSUE 15 acceptance)."""
    from paddle_tpu.obs import Tracer
    from paddle_tpu.pserver.server import ParameterServer

    tracer = Tracer()
    tracer.enabled = True
    srv = ParameterServer(port=0, beat_timeout_s=60.0, tracer=tracer)
    host, port = srv.start_background()
    try:
        procs = [_spawn_trainer(
            port, r, 2, 2,
            extra=("--trace-out", str(tmp_path / f"r{r}.jsonl")))
            for r in range(2)]
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"trainer failed:\n{err[-2000:]}"
            assert "TRAIN_JSON" in out
        # tracing really ran: the server ring recorded shard-side spans
        # and both trainers flushed stitchable files
        assert tracer.recorded > 0
        for r in range(2):
            assert (tmp_path / f"r{r}.jsonl").stat().st_size > 0
        assert srv.engine is not None
        params, opt = srv.engine.assemble_full()
        assert int(opt["pass_id"]) == 2

        oracle = _oracle_trainer(accum=2)
        for _ in range(2):
            oracle.train_one_pass(batches=None)
        o_params = _host(oracle.params)
        o_avg = _host(oracle.updater.averaged_params(oracle.params,
                                                     oracle.opt_state))
        for n in o_params:
            np.testing.assert_array_equal(
                params[n], o_params[n],
                err_msg=f"{n}: K=2 fleet != grad_accum=2 oracle")
        # model averaging (eval-time params) must agree too
        for n in o_avg:
            np.testing.assert_array_equal(
                opt["average"][n], o_avg[n],
                err_msg=f"{n}: averaged params diverge")
        # scheduler state agrees (LR schedule inputs)
        assert int(opt["num_samples"]) == \
            int(oracle.opt_state["num_samples"])
        assert int(opt["num_updates"]) == \
            int(oracle.opt_state["num_updates"])
    finally:
        srv.stop_background(drain=False)


def test_async_mode_trains_with_bounded_staleness():
    """Async mode: no barrier, bounded staleness, pass accounting still
    synchronized — the trainer makes progress and the divergence metric
    is populated honestly."""
    from paddle_tpu.optim.remote_updater import RemoteParameterUpdater
    from paddle_tpu.pserver.server import ParameterServer

    srv = ParameterServer(port=0, mode="async", max_staleness=8,
                          beat_timeout_s=60.0)
    host, port = srv.start_background()
    try:
        from paddle_tpu.config.parser import parse_config
        from paddle_tpu.trainer.trainer import Trainer

        cfg = parse_config(CONFIG, CONFIG_ARGS)
        upd = RemoteParameterUpdater(cfg.model_config, cfg.opt_config,
                                     [(host, port)])
        tr = Trainer(cfg, seed=1, updater=upd)
        init = _host(tr.params)
        stats = tr.train_one_pass(batches=None)
        assert stats["batches"] == 8
        assert srv.engine.version == 8
        assert srv.engine.pass_id == 1
        final = _host(tr.params)
        assert any(not np.array_equal(init[n], final[n]) for n in init)
        m = upd.client.metrics()
        assert "pserver_async_staleness_count 8" in m
        upd.drain_and_leave()
    finally:
        srv.stop_background(drain=False)


def test_pre_accum_n2_bit_exact_and_cuts_grad_wire_bytes():
    """ISSUE 17 satellite: num_batches_per_send_parameter=2 buffers two
    batches' gradients host-side (the same sample-weighted fp32 ladder
    as the server) and pushes ONE pre_accum send_grad per window — the
    final parameters, averaging slots, and scheduler counters are
    bit-identical to the local grad_accum=2 oracle, and the send_grad
    wire bytes drop to ~half of the N=1 run's."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.optim.remote_updater import RemoteParameterUpdater
    from paddle_tpu.pserver.server import ParameterServer
    from paddle_tpu.trainer.trainer import Trainer

    def run(n):
        srv = ParameterServer(port=0, beat_timeout_s=60.0)
        host, port = srv.start_background()
        try:
            cfg = parse_config(CONFIG, CONFIG_ARGS)
            cfg.opt_config.num_batches_per_send_parameter = n
            upd = RemoteParameterUpdater(cfg.model_config, cfg.opt_config,
                                         [(host, port)])
            assert upd.accum_n == n
            tr = Trainer(cfg, seed=1, updater=upd)
            for _ in range(2):
                tr.train_one_pass(batches=None)
            params, opt = srv.engine.assemble_full()
            wire_bytes = upd.client.grad_bytes_sent
            versions = srv.engine.version
            upd.drain_and_leave()
            return params, opt, wire_bytes, versions
        finally:
            srv.stop_background(drain=False)

    p1, _o1, bytes1, v1 = run(1)
    p2, o2, bytes2, v2 = run(2)
    # 8 batches/pass: N=1 commits 8 windows/pass, N=2 commits 4
    assert v1 == 16 and v2 == 8
    assert int(o2["pass_id"]) == 2

    oracle = _oracle_trainer(accum=2)
    for _ in range(2):
        oracle.train_one_pass(batches=None)
    o_params = _host(oracle.params)
    o_avg = _host(oracle.updater.averaged_params(oracle.params,
                                                 oracle.opt_state))
    for n in o_params:
        np.testing.assert_array_equal(
            p2[n], o_params[n],
            err_msg=f"{n}: pre_accum N=2 != grad_accum=2 oracle")
    for n in o_avg:
        np.testing.assert_array_equal(
            o2["average"][n], o_avg[n],
            err_msg=f"{n}: averaged params diverge under pre_accum")
    assert int(o2["num_samples"]) == int(oracle.opt_state["num_samples"])
    assert int(o2["num_updates"]) == int(oracle.opt_state["num_updates"])
    # the satellite's headline: half the send_grad frames -> ~half the
    # gradient wire bytes (fp32 promotion + per-frame headers keep it
    # from being exactly 2x, hence the band)
    assert bytes2 > 0
    assert bytes2 < 0.65 * bytes1, (bytes1, bytes2)


# ---------------------------------------------------------------------------
# churn soak: SIGKILL a trainer mid-training, replay the commit log
# ---------------------------------------------------------------------------


class _GradTap:
    """is_remote updater stub: runs the IDENTICAL grad-only jitted train
    step the live trainers ran, but hands the gradients to the replay
    loop instead of a socket."""

    is_remote = True
    accum_n = 1

    def __init__(self, opt):
        self.use_average = opt.average_window > 0
        self.captured = None

    def apply_init_hooks(self, params):
        return params

    def init_state(self, params):
        return {"remote": True}

    def connect_and_sync(self, params_host, config_json=None):
        return params_host

    def remote_step(self, grads_host, batch_size, tag=None, compute=None):
        self.captured = (grads_host, batch_size)
        return None

    def start_pass(self, state):
        return state

    def finish_pass(self, state):
        return state

    def averaged_params(self, params, state):
        return params


@pytest.mark.slow
def test_churn_soak_killed_trainer_replays_exact(tmp_path):
    """3 trainer processes; one is SIGKILLed mid-training.  Training
    completes on the survivors, the fleet ends healthy, and replaying
    the server's commit log (exactly the contributions that committed,
    in rank order, pass boundaries included) through a fresh
    UpdateEngine reproduces the live parameters BIT-EXACTLY — zero lost
    updates, nothing double-counted, the dead trainer's in-flight
    contribution provably discarded."""
    import jax.numpy as jnp

    from paddle_tpu.pserver.blocks import BlockMap
    from paddle_tpu.pserver.server import ParameterServer, UpdateEngine

    srv = ParameterServer(port=0, beat_timeout_s=60.0,
                          snapshot_dir=str(tmp_path / "ck"),
                          snapshot_every=5)
    host, port = srv.start_background()
    try:
        procs = [_spawn_trainer(port, r, 3, 3) for r in range(3)]
        # let the fleet make progress, then kill rank 2 ABRUPTLY
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if srv.engine is not None and srv.engine.version >= 2:
                break
            time.sleep(0.05)
        assert srv.engine is not None and srv.engine.version >= 2
        procs[2].send_signal(signal.SIGKILL)
        outs = []
        for p in procs[:2]:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"survivor failed:\n{err[-2000:]}"
            outs.append(out)
        procs[2].communicate(timeout=30)
        # fleet healthy: survivors drained cleanly, no stuck members
        st = srv._stats_msg()
        assert st["trainers_active"] == 0
        assert st["pending_barriers"] == 0
        live_params, live_opt = srv.engine.assemble_full()
        log = list(srv.commit_log)
        assert any("pass" in rec for rec in log)
        # rank 2 appears in SOME committed window (it did real work
        # before dying) but not all
        r2_windows = [rec for rec in log if "members" in rec
                      and any(m[1] == 2 for m in rec["members"])]
        assert r2_windows, "kill landed before rank 2 ever contributed " \
                           "— lower the kill threshold"

        # ---- replay oracle ------------------------------------------------
        tap_cfgless = _oracle_trainer(accum=1)   # only for the batch stream
        stream = list(tap_cfgless.train_batches())
        shards = {r: stream[r::3] for r in range(3)}

        from paddle_tpu.config.parser import parse_config
        from paddle_tpu.trainer.trainer import Trainer
        cfg = parse_config(CONFIG, CONFIG_ARGS)
        tap = _GradTap(cfg.opt_config)
        tr = Trainer(cfg, seed=1, updater=tap)
        init = _host_params = {k: np.asarray(v)
                               for k, v in _host(tr.params).items()}
        bm = BlockMap.from_arrays(init, n_shards=1,
                                  block_size=srv.block_size)
        pcfgs = {p.name: p for p in cfg.model_config.parameters}
        engine = UpdateEngine(bm, 0, cfg.opt_config, pcfgs,
                              bm.split_all(init))
        for rec in log:
            if "pass" in rec:
                engine.finish_pass()
                continue
            current = engine.assemble_full()[0]
            entries = []
            for tid, rank, samples, tag in rec["members"]:
                # tag "r{rank}b{i}": i-th batch this rank contributed
                i = int(tag.split("b", 1)[1])
                shard = shards[rank]
                batch = shard[i % len(shard)]
                tr.params = {n: jnp.asarray(v)
                             for n, v in current.items()}
                tr._dispatch_step(batch)
                grads, bsz = tap.captured
                assert bsz == samples
                entries.append((rank, tid, samples,
                                bm.split_all(grads)))
            engine.commit(entries)
        re_params, re_opt = engine.assemble_full()
        for n in live_params:
            np.testing.assert_array_equal(
                re_params[n], live_params[n],
                err_msg=f"{n}: replayed commit log != live fleet state")
        assert int(re_opt["num_updates"]) == int(live_opt["num_updates"])
        assert int(re_opt["num_samples"]) == int(live_opt["num_samples"])
        # the streaming checkpoints kept up through the churn
        assert srv.snapshots_written >= 1
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        srv.stop_background(drain=False)


@pytest.mark.slow
def test_pserver_cli_sigterm_drain_writes_final_checkpoint(tmp_path):
    """tools/pserver.py contract: SIGTERM → drain → final checkpoint →
    exit 0; tools/train_dist.py drains on SIGTERM → exit 0."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    ps = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "pserver.py"),
         "--port", "0", "--snapshot-dir", str(tmp_path / "ck")],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        import json

        line = ""
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            line = ps.stdout.readline()
            if line.startswith("PSERVER_JSON:"):
                break
        info = json.loads(line.split("PSERVER_JSON:", 1)[1])
        port = info["port"]
        t = _spawn_trainer(port, 0, 1, 30)    # many passes: will be cut
        time.sleep(8)
        t.send_signal(signal.SIGTERM)
        out, err = t.communicate(timeout=120)
        assert t.returncode == 0, f"trainer SIGTERM drain failed:\n{err}"
        assert '"drained": true' in out or '"passes": 30' in out
        ps.send_signal(signal.SIGTERM)
        _out, perr = ps.communicate(timeout=120)
        assert ps.returncode == 0, f"pserver SIGTERM drain failed:\n{perr}"
        from paddle_tpu.trainer.checkpoint import (latest_checkpoint,
                                                   load_checkpoint)
        final = latest_checkpoint(str(tmp_path / "ck"))
        assert final is not None
        data = load_checkpoint(final)
        assert "momentum" in next(iter(data["opt"]["slots"].values()))
    finally:
        for p in (ps,):
            if p.poll() is None:
                p.kill()
