"""Legacy recurrent_units building blocks (ref: python/paddle/trainer/
recurrent_units.py): LSTM/GRU units + layer groups with para_prefix
parameter sharing."""

import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.config.parser import parse_config
from paddle_tpu.data.feeder import make_batch
from paddle_tpu.data.provider import integer_value, integer_value_sequence
from paddle_tpu.trainer.trainer import Trainer

CFG = """
from paddle_tpu.dsl import *
from paddle_tpu.dsl.recurrent_units import (
    GatedRecurrentLayerGroup, LstmRecurrentLayerGroup)

settings(batch_size=4, learning_rate=0.5)
data = data_layer(name="word", size=10)
emb = embedding_layer(input=data, size=8)
lstm = LstmRecurrentLayerGroup(name="lstm_u", size=8,
                               inputs=[full_matrix_projection(input=emb)])
gru = GatedRecurrentLayerGroup(name="gru_u", size=8,
                               inputs=[full_matrix_projection(input=emb)])
# a second GRU group SHARING the first's parameters via para_prefix
gru2 = GatedRecurrentLayerGroup(name="gru_u2", size=8, para_prefix="gru_u",
                                inputs=[full_matrix_projection(input=emb)])
rep = concat_layer(input=[last_seq(input=lstm), last_seq(input=gru),
                          last_seq(input=gru2)])
out = fc_layer(input=rep, size=3, act=SoftmaxActivation())
classification_cost(input=out, label=data_layer(name="label", size=3))
"""


def test_units_train_and_share_parameters():
    path = os.path.join(REPO, "tests", "_runits_cfg.py")
    with open(path, "w") as f:
        f.write(CFG)
    try:
        cfg = parse_config(path, "")
        pnames = [p.name for p in cfg.model_config.parameters]
        # para_prefix sharing: the recurrent weight/bias exist ONCE
        assert pnames.count("gru_u_gate_recurrent.w") == 1
        assert pnames.count("gru_u_input_proj.b") == 1
        assert not any("gru_u2_gate" in n for n in pnames), pnames
        assert "lstm_u_input_recurrent.w" in pnames
        assert "lstm_u_check.b" in pnames

        tr = Trainer(cfg, seed=0)
        rng = np.random.default_rng(0)
        dataset = []
        for _ in range(12):
            L = int(rng.integers(2, 6))
            seq = rng.integers(0, 10, L).tolist()
            dataset.append((seq, seq[0] % 3))

        def batches():
            for i in range(0, 12, 4):
                yield make_batch(
                    dataset[i:i + 4],
                    [integer_value_sequence(10), integer_value(3)],
                    ["word", "label"])

        c0 = tr.train_one_pass(batches=batches(), log_period=0)["cost"]
        last = c0
        for _ in range(30):
            last = tr.train_one_pass(batches=batches(), log_period=0)["cost"]
        assert last < c0 * 0.8, (c0, last)
    finally:
        os.remove(path)
