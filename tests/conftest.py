"""Test env: CPU backend with 8 virtual devices so mesh/sharding tests run
without TPU hardware (mirrors the reference's strategy of testing distributed
paths in one process — SURVEY.md §4(d)).

Note: the machine image starts every interpreter with the axon TPU plugin
already imported (sitecustomize) and JAX_PLATFORMS=axon latched into
jax.config, so setting os.environ here is too late — we must update
jax.config directly.  XLA_FLAGS is still read at first CPU-client creation,
which happens after conftest, so the env route works for the device count.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests spawn

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
