"""Deep-introspection suite (ISSUE 6): compile watch + storm detector,
flight-recorder ring + atomic postmortem bundles, device-memory
accounting, and the tools/postmortem.py round-trip.

The serving-server trigger paths (pump death, watchdog wedge, `dump`
RPC) are exercised over TCP in tests/test_server.py; this file owns the
unit semantics plus the REAL bucket-churn storm: an engine fed prompts
across distinct prefill buckets must fire the recompile-storm detector
EXACTLY ONCE.
"""

import json
import os
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.obs.compile_watch import (CompileWatch, compile_collector,
                                          get_compile_watch, signature_of)
from paddle_tpu.obs.flight import (BUNDLE_FILES, FlightRecorder,
                                   flight_collector, load_bundle)
from paddle_tpu.obs.hbm import hbm_collector, hbm_snapshot, tree_bytes


# ---------------------------------------------------------------------------
# compile watch
# ---------------------------------------------------------------------------

def test_signature_of_describes_shapes_and_scalars():
    a = np.zeros((4, 8), np.float32)
    sig = signature_of((a, 3, "greedy"), {"flag": True})
    assert "float32[4,8]" in sig and "3" in sig and "True" in sig
    # nested pytrees walk deterministically (dict order by key)
    s1 = signature_of(({"b": a, "a": np.zeros(2, np.int32)},), {})
    s2 = signature_of(({"a": np.zeros(2, np.int32), "b": a},), {})
    assert s1 == s2
    # a big pytree digests down to a bounded signature
    big = tuple(np.zeros(i + 1) for i in range(64))
    assert len(signature_of((big,), {})) < 160


class _FakeJit:
    """Jit stand-in: cache grows on each new input shape."""

    def __init__(self):
        self.sigs = set()
        self.calls = 0

    def _cache_size(self):
        return len(self.sigs)

    def __call__(self, x):
        self.calls += 1
        self.sigs.add(np.asarray(x).shape)
        return x

    def lower(self):
        return "lowered"


def test_wrap_jit_detects_compiles_by_cache_growth_and_proxies_attrs():
    cw = CompileWatch(storm_n=99)
    fn = cw.wrap_jit("t.site", _FakeJit())
    fn(np.zeros((2, 2)))                      # compile 1
    fn(np.zeros((2, 2)))                      # cache hit
    fn(np.zeros((4, 4)))                      # compile 2
    snap = cw.snapshot()["t.site"]
    assert snap["compiles"] == 2 and snap["signatures"] == 2
    assert snap["storms"] == 0
    # introspection flows through the proxy (bench.py / oracle tests use
    # ._cache_size() and .lower() on the wrapped object)
    assert fn._cache_size() == 2
    assert fn.lower() == "lowered"
    assert fn.calls == 3


def test_watch_context_records_first_key_only():
    cw = CompileWatch()
    with cw.watch("lm.gen", (2, 8, 4)):
        pass
    with cw.watch("lm.gen", (2, 8, 4)):       # repeat key: no event
        pass
    with cw.watch("lm.gen", (2, 16, 4)):      # new key: event
        pass
    snap = cw.snapshot()["lm.gen"]
    assert snap["compiles"] == 2 and snap["signatures"] == 2
    # an exception inside the watched block records nothing (the call
    # never finished; the NEXT successful call owns the compile event)
    with pytest.raises(RuntimeError):
        with cw.watch("lm.gen", (9, 9, 9)):
            raise RuntimeError("boom")
    assert cw.snapshot()["lm.gen"]["compiles"] == 2


def test_storm_detector_fires_once_then_rearms_after_window_drains():
    cw = CompileWatch(storm_n=3, storm_window_s=0.25)
    for i in range(5):                        # 5 distinct sigs in-window
        cw.record("site", f"sig{i}", 0.01)
    assert cw.storms["site"] == 1, \
        "a sustained storm must be ONE alert, not an alert storm"
    time.sleep(0.3)                           # window drains -> re-arm
    for i in range(3):
        cw.record("site", f"late{i}", 0.01)
    assert cw.storms["site"] == 2


def test_compile_collector_emits_catalog_names_per_site():
    cw = CompileWatch()
    cw.record("a.site", "s0", 0.5)
    cw.record("a.site", "s1", 0.25)
    out = compile_collector(cw)()
    by_name = {}
    for name, kind, labels, val in out:
        assert labels == {"site": "a.site"}
        by_name[name] = (kind, val)
    assert by_name["jit_compiles_total"] == ("counter", 2.0)
    assert by_name["jit_signatures"] == ("gauge", 2.0)
    assert by_name["jit_compile_seconds"][1] == pytest.approx(0.75)
    assert by_name["jit_recompile_storms_total"] == ("counter", 0.0)


def test_bucket_churn_fires_storm_exactly_once(monkeypatch):
    """The acceptance storm: REAL per-bucket prefill compiles.  Prompts
    spanning 3 feeder buckets (8/16/32) against storm_n=3 fire the
    detector exactly once at serving.prefill — and the decode step stays
    one signature throughout (no storm there)."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.serving import Request, ServingEngine
    from paddle_tpu.trainer.trainer import Trainer

    fresh = CompileWatch(storm_n=3, storm_window_s=300.0)
    monkeypatch.setattr("paddle_tpu.serving.engine.get_compile_watch",
                        lambda: fresh)
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    tr = Trainer(cfg, seed=7)
    rng = np.random.default_rng(0)
    # lengths 3 -> bucket 8, 12 -> 16, 20 -> 32 (feeder _bucket_len)
    prompts = [rng.integers(2, 31, n).astype(np.int32)
               for n in (3, 12, 20)]
    reqs = [Request(i, p, max_new=2) for i, p in enumerate(prompts)]
    # prefill_chunk=None: the LEGACY bucketed path is the one that churns
    # per-bucket compiles (chunked admission has no prefill programs)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64, prefill_chunk=None)
    eng.run(reqs)

    snap = fresh.snapshot()
    assert snap["serving.prefill"]["signatures"] == 3
    assert snap["serving.prefill"]["storms"] == 1, \
        "3 distinct prefill signatures at storm_n=3 must fire EXACTLY once"
    assert snap["serving.decode_step"]["signatures"] == 1
    assert snap["serving.decode_step"].get("storms", 0) == 0


# ---------------------------------------------------------------------------
# flight recorder ring
# ---------------------------------------------------------------------------

def test_flight_ring_bounded_keeps_newest_in_order():
    fr = FlightRecorder(capacity=4)
    fr.record("dropped_while_disabled")
    assert fr.recorded == 0
    fr.enabled = True
    for i in range(10):
        fr.record("ev", i=i)
    assert fr.recorded == 10 and fr.dropped == 6
    evs = fr.snapshot()
    assert [e["data"]["i"] for e in evs] == [6, 7, 8, 9]
    assert [e["seq"] for e in evs] == [6, 7, 8, 9]
    assert all(e["kind"] == "ev" for e in evs)


def test_flight_collector_reports_ring_accounting():
    fr = FlightRecorder(capacity=2)
    fr.enabled = True
    for _ in range(5):
        fr.record("x")
    fr.bundles_written = 1
    vals = {name: v for name, _k, _l, v in flight_collector(fr)()}
    assert vals["flight_events_recorded_total"] == 5.0
    assert vals["flight_events_dropped_total"] == 3.0
    assert vals["postmortem_bundles_total"] == 1.0


# ---------------------------------------------------------------------------
# postmortem bundles
# ---------------------------------------------------------------------------

def _dump(fr, out_dir, **kw):
    kw.setdefault("spans", [{"seq": 0, "name": "queued", "track": "req:a",
                             "ts": 0.0, "dur": 0.5}])
    kw.setdefault("engine", {"n_decode_steps": 3, "slots": [None],
                             "queued": [], "pages_in_use": 0,
                             "free_pages": 7, "num_pages": 8,
                             "page_size": 8})
    kw.setdefault("metrics", {"pump_alive": 1.0})
    kw.setdefault("config", {"num_slots": 1})
    return fr.dump(str(out_dir), "test_reason", **kw)


def test_bundle_dump_load_roundtrip_schema(tmp_path):
    fr = FlightRecorder()
    fr.enabled = True
    fr.record("queued", req="r0")
    fr.record("pump_death", error="boom")
    path = _dump(fr, tmp_path, error="RuntimeError: boom\n  traceback")

    assert os.path.basename(path).startswith("postmortem-")
    assert not path.endswith(".tmp")
    for name in BUNDLE_FILES:
        assert os.path.exists(os.path.join(path, name)), name
    b = load_bundle(path)
    assert b["meta"]["reason"] == "test_reason"
    assert b["meta"]["pid"] == os.getpid()
    assert "python" in b["meta"]["versions"]
    assert b["meta"]["error"].startswith("RuntimeError: boom")
    assert [e["kind"] for e in b["events"]] == ["queued", "pump_death"]
    assert b["spans"][0]["name"] == "queued"
    assert b["engine"]["free_pages"] == 7
    assert b["metrics"]["pump_alive"] == 1.0
    assert b["config"]["num_slots"] == 1
    # bundle spans are tools/trace_dump.py food directly
    from tools.trace_dump import load_spans, summarize

    spans = load_spans(os.path.join(path, "spans.jsonl"))
    assert "queued" in summarize(spans)


def test_bundle_same_second_redump_and_unserializable_part(tmp_path):
    fr = FlightRecorder()
    fr.enabled = True
    fr.record("ev")
    p1 = _dump(fr, tmp_path)
    circular = {}
    circular["self"] = circular                # json refuses: ValueError
    p2 = _dump(fr, tmp_path, engine=circular)
    assert p1 != p2                            # same-second dump: suffixed
    assert fr.bundles_written == 2
    b2 = load_bundle(p2)
    # the broken part degraded to a stub; the bundle itself committed
    assert "snapshot_error" in b2["engine"]
    assert b2["meta"]["reason"] == "test_reason"


def test_load_bundle_refuses_tmp_straggler_and_nondir(tmp_path):
    frag = tmp_path / "postmortem-x.tmp"
    frag.mkdir()
    (frag / "meta.json").write_text("{}")      # crashed mid-dump
    with pytest.raises(ValueError, match="incomplete bundle"):
        load_bundle(str(frag))
    with pytest.raises(ValueError, match="not a bundle"):
        load_bundle(str(tmp_path / "absent"))


def test_postmortem_tool_renders_and_exits_nonzero_on_bad(tmp_path, capsys):
    from tools.postmortem import main

    fr = FlightRecorder()
    fr.enabled = True
    fr.record("queued", req="r0")
    fr.record("wedge", age_s=31.2)
    path = _dump(fr, tmp_path, engine={
        "n_decode_steps": 5, "tokens_generated": 12, "n_preemptions": 1,
        "n_cancelled": 0, "n_expired": 0,
        "slots": [{"slot": 0, "req_id": "r0", "pos": 9, "generated": 2,
                   "max_new": 8}, None],
        "queued": ["r1", "r2"], "pages_in_use": 3, "free_pages": 5,
        "num_pages": 8, "page_size": 8,
        "compile_watch": {"serving.prefill": {
            "compiles": 4, "seconds": 1.25, "signatures": 4, "storms": 1}},
        "hbm": {"kv_pool_bytes": 4096, "param_bytes": 1 << 20},
    }, metrics={"pump_alive": 0.0, "pump_last_step_age_s": 31.5})

    assert main([path]) == 0
    out = capsys.readouterr().out
    assert "test_reason" in out
    assert "[0] r0 pos=9 gen=2/8" in out
    assert "queued (2)" in out
    assert "3 in use" in out
    assert "serving.prefill" in out and "STORMS=1" in out
    assert "kv_pool=4.0KiB" in out and "param=1.0MiB" in out
    assert "wedge" in out

    assert main([path, "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["meta"]["reason"] == "test_reason"

    # a .tmp straggler (or junk path) is a loud exit 2
    frag = tmp_path / "postmortem-y.tmp"
    frag.mkdir()
    assert main([str(frag)]) == 2


# ---------------------------------------------------------------------------
# device-memory accounting
# ---------------------------------------------------------------------------

def test_tree_bytes_walks_mixed_pytrees_exactly():
    tree = {"w": np.zeros((4, 4), np.float32),       # 64
            "nested": [np.zeros(8, np.int32),        # 32
                       (np.zeros(2, np.float64),)],  # 16
            "scalar": 3, "none": None}
    assert tree_bytes(tree) == 64 + 32 + 16
    assert tree_bytes({}) == 0


def test_hbm_collector_cpu_safe_and_param_kv_gauges():
    """On the CPU test backend every probe may be absent — the collector
    must still answer, and the duck-typed param/KV gauges are always
    present when their accessors are given."""
    params = {"layer": {"w": np.zeros((16, 16), np.float32)}}

    class KV:
        pools = [np.zeros((8, 8), np.float32), np.zeros((8, 8), np.float32)]

    out = hbm_collector(params_fn=lambda: params, kv_fn=lambda: KV())()
    vals = {name: v for name, _k, _l, v in out}
    assert vals["hbm_param_bytes"] == 16 * 16 * 4
    assert vals["hbm_kv_pool_bytes"] == 2 * 8 * 8 * 4
    for name, kind, labels, _v in out:
        assert kind == "gauge" and labels is None
    # accessors optional: a bare registry still renders
    assert isinstance(hbm_collector()(), list)

    snap = hbm_snapshot(params=params)
    assert snap["param_bytes"] == 16 * 16 * 4
    json.dumps(snap)                           # bundle-ready


def test_hbm_gauges_ride_a_strict_registry_render():
    """The hbm_*/jit_*/flight_* names are CATALOG rows — a strict
    registry (what the server and trainer build) accepts the collectors
    and renders them."""
    from paddle_tpu.obs import MetricsRegistry

    reg = MetricsRegistry(strict=True)
    reg.register_collector(hbm_collector(
        params_fn=lambda: {"w": np.zeros(4, np.float32)}))
    cw = CompileWatch()
    cw.record("s", "sig", 0.1)
    reg.register_collector(compile_collector(cw))
    fr = FlightRecorder()
    reg.register_collector(flight_collector(fr))
    text = reg.render()
    assert "hbm_param_bytes 16" in text
    assert 'jit_compiles_total{site="s"} 1' in text
    assert "postmortem_bundles_total 0" in text
