"""Serving front-end loopback tests (serving/server.py + client.py).

The acceptance contract: mixed-length STREAMING requests over real TCP —
with one client-initiated cancellation and one deadline expiry mid-flight
— produce per-request token streams exactly matching
`lm_generate(use_cache=True)` run per surviving request, while the engine
pump keeps ONE compiled decode signature; overload yields an explicit
backpressure response instead of unbounded queueing; drain finishes
in-flight work and refuses new; SIGTERM on tools/serve.py drains and
exits 0 (slow)."""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.serving import Request, ServingEngine
from paddle_tpu.serving.client import OverloadError, ServingClient
from paddle_tpu.serving.server import ServingServer
from paddle_tpu.trainer.trainer import Trainer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def tiny_tr():
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _engine(tr, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_context", 64)
    eng = ServingEngine(tr.executor, tr.params, **kw)
    # deterministic deadline clock: "seconds" = decode steps taken
    eng.clock = lambda: float(eng.n_decode_steps)
    return eng


def _oracle(tr, prompt, max_new, **kw):
    import jax

    rng = jax.random.PRNGKey(kw.pop("seed")) if "seed" in kw else None
    toks, lens = lm_generate(tr.executor, tr.params,
                             np.asarray(prompt, np.int32)[None, :],
                             max_new=max_new, use_cache=True, rng=rng, **kw)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])].tolist()


def _paired_client():
    """A ServingClient wired to one end of a socketpair — lets the frame
    routing be tested without a server (or jax) in the loop."""
    import socket

    a, b = socket.socketpair()
    a.settimeout(5.0)
    c = ServingClient.__new__(ServingClient)
    c.sock = a
    c._next_id = 0
    c._pending = []
    return c, b


def test_client_routing_drains_socket_past_buffered_foreign_frames():
    """Regression: collect()/stats() with _pending holding ONLY other
    requests' frames must fall through to the socket instead of recycling
    the buffer forever (the pre-fix behavior busy-looped here)."""
    from paddle_tpu.serving import wire

    c, peer = _paired_client()
    try:
        # buffer frames that belong to a different in-flight request
        c._pending = [{"type": "token", "id": "r1", "token": 5, "index": 0},
                      {"type": "token", "id": "r1", "token": 6, "index": 1}]
        peer.sendall(wire.encode({"type": "done", "id": "r0",
                                  "tokens": [1, 2], "reason": "length"}))
        res = c.collect(["r0"])
        assert res["r0"]["tokens"] == [1, 2]
        # r1's frames survived, untouched and in order
        assert [m["token"] for m in c._pending] == [5, 6]

        # stats() mid-stream: socket frames for r1 get stashed, stats returns
        peer.sendall(wire.encode({"type": "token", "id": "r1",
                                  "token": 7, "index": 2}))
        peer.sendall(wire.encode({"type": "stats", "queue_depth": 0}))
        assert c.stats()["queue_depth"] == 0
        assert [m["token"] for m in c._pending] == [5, 6, 7]

        # the buffered stream then collects exactly, buffer first
        peer.sendall(wire.encode({"type": "done", "id": "r1",
                                  "tokens": [5, 6, 7], "reason": "length"}))
        res = c.collect(["r1"])
        assert res["r1"]["stream"] == [5, 6, 7]
        assert c._pending == []
    finally:
        c.close()
        peer.close()


def test_streaming_cancel_deadline_oracle_exact_over_tcp(tiny_tr):
    """The end-to-end acceptance test (ISSUE 4)."""
    rng = np.random.default_rng(0)
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=32)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            assert c.ping()
            # mixed lengths spanning prefill buckets; r3 sampled (seeded)
            p0 = rng.integers(2, 31, 3).tolist()
            p1 = rng.integers(2, 31, 9).tolist()
            p2 = rng.integers(2, 31, 5).tolist()
            p3 = rng.integers(2, 31, 12).tolist()
            p_dead = rng.integers(2, 31, 4).tolist()
            p_cancel = rng.integers(2, 31, 6).tolist()
            # the deadline request goes FIRST: the idle pump admits it to a
            # slot at step ~0, and a 3-step budget (engine.clock counts
            # decode steps) against 30 tokens guarantees an IN-SLOT expiry
            r_dead = c.submit(p_dead, max_new=30, timeout_s=3.0)
            r0 = c.submit(p0, max_new=6)
            r1 = c.submit(p1, max_new=8)
            r2 = c.submit(p2, max_new=4)
            r3 = c.submit(p3, max_new=5, temperature=0.8, top_k=5, seed=11)
            r_cancel = c.submit(p_cancel, max_new=30)

            cancelled = []

            def on_token(rid, tok, idx):
                # cancel mid-flight: after its first streamed token the
                # request provably occupies a slot
                if rid == r_cancel and idx >= 1 and not cancelled:
                    cancelled.append(True)
                    c.cancel(r_cancel)

            out = c.collect([r0, r1, r2, r3, r_dead, r_cancel],
                            on_token=on_token)
        # surviving requests: token-for-token against the per-request oracle
        assert out[r0]["tokens"] == _oracle(tiny_tr, p0, 6)
        assert out[r1]["tokens"] == _oracle(tiny_tr, p1, 8)
        assert out[r2]["tokens"] == _oracle(tiny_tr, p2, 4)
        assert out[r3]["tokens"] == _oracle(tiny_tr, p3, 5, temperature=0.8,
                                            top_k=5, seed=11)
        # every stream (survivors AND aborted) is exactly its final result:
        # token frames arrive in order and the done frame agrees
        for rid, prompt in ((r0, p0), (r1, p1), (r2, p2), (r3, p3),
                            (r_dead, p_dead), (r_cancel, p_cancel)):
            assert out[rid]["tokens"][:len(prompt)] == prompt
            assert out[rid]["stream"] == out[rid]["tokens"][len(prompt):]
        for rid in (r0, r1, r2, r3):
            assert out[rid]["reason"] == "length"
        # the aborted pair: right reasons, genuinely stopped mid-flight
        assert out[r_dead]["reason"] == "deadline"
        assert len(p_dead) < len(out[r_dead]["tokens"]) < len(p_dead) + 30, \
            "deadline request should die in a slot with partial output"
        assert out[r_cancel]["reason"] == "cancelled"
        assert cancelled, "cancel hook never fired"
        assert len(out[r_cancel]["tokens"]) < len(p_cancel) + 30
        assert eng.n_expired == 1 and eng.n_cancelled >= 1
        # ONE compiled decode signature for the whole mixed workload
        assert eng._decode_step._cache_size() == 1
        # every page reclaimable once all requests resolved: free outright
        # or retained only by the prefix index (evictable on demand)
        eng.kv.check_reclaimed()
    finally:
        srv.stop_background(drain=True)


def test_stats_rpc_reports_occupancy_and_latency(tiny_tr):
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            c.generate([3, 4, 5], max_new=4)
            s = c.stats()
        assert s["num_slots"] == 2
        assert s["max_inflight"] == 6
        assert s["queue_depth"] == 0 and s["inflight"] == 0
        assert s["tokens_generated"] >= 4
        assert s["free_pages"] == s["num_pages"] - 1
        assert s["draining"] is False
        lat = s["latency_ms"]
        assert lat["request_latency"]["p50"] > 0.0
        assert lat["first_token_latency"]["p99"] >= \
            lat["first_token_latency"]["p50"]
    finally:
        srv.stop_background(drain=True)


def test_metrics_frame_and_consistent_stats_over_tcp(tiny_tr):
    """ISSUE 5: the Prometheus-style `metrics` frame over TCP loopback,
    plus the reworked stats snapshot — the default path builds the engine
    half on the PUMP thread (consistent), `stale_ok` answers from the
    loop thread immediately, and both carry the watchdog fields."""
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            c.generate([3, 4, 5], max_new=4)
            text = c.metrics()
            # exposition-format spot checks against documented names
            assert "# TYPE serving_queue_depth gauge" in text
            assert "# TYPE serving_tokens_generated_total counter" in text
            assert "pump_alive 1" in text
            vals = {}
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    key, v = line.rsplit(" ", 1)
                    vals[key] = float(v)
            assert vals["serving_tokens_generated_total"] >= 4.0
            assert vals["serving_requests_accepted_total"] == 1.0
            assert vals["serving_num_slots"] == 2.0
            assert 0.0 <= vals["pump_last_step_age_s"] < 60.0
            assert vals['serving_latency_seconds'
                        '{quantile="p50",stat="request_latency"}'] > 0.0
            assert vals['serving_latency_count'
                        '{stat="first_token_latency"}'] == 1.0
            # consistent (pump round-trip) vs stale_ok (loop fast path)
            s = c.stats()
            assert s["consistent"] is True and s["pump_alive"] is True
            assert s["queue_depth"] == 0 and s["slots_in_use"] == 0
            s2 = c.stats(stale_ok=True)
            assert s2["consistent"] is False
            assert s2["tokens_generated"] == s["tokens_generated"]
            assert s2["pump_last_step_age_s"] >= 0.0
        # docs lint lockstep: every name the frame rendered is catalogued
        # (histogram samples render as <family>_bucket/_sum/_count — the
        # family name is the catalogued one, same mapping the strict
        # registry applies)
        from paddle_tpu.obs import CATALOG
        from paddle_tpu.obs.metrics import MetricsRegistry
        for key in vals:
            base = key.split("{", 1)[0]
            fam = MetricsRegistry._family_of(base, "histogram")
            assert base in CATALOG or fam in CATALOG, \
                f"{base} rendered but not in CATALOG"
    finally:
        srv.stop_background(drain=True)


def test_multi_step_streams_burst_frames_and_honest_itl(tiny_tr):
    """ISSUE 16: a decode_steps=4 engine behind the server streams token
    frames in deterministic ≤k bursts — each frame stamped with `burst` =
    fresh tokens remaining in its burst including itself — the outputs
    stay oracle-exact, token_latency charges every post-first token an
    equal SHARE of its burst gap (count == fresh tokens, no k-times
    undercount), and the scan dispatch counters surface in metrics."""
    from paddle_tpu.serving import wire

    eng = _engine(tiny_tr, decode_steps=4)
    srv = ServingServer(eng, max_queue=8)
    host, port = srv.start_background()
    try:
        import socket

        prompt = [3, 9, 4, 7, 2]
        sock = socket.create_connection((host, port), timeout=30)
        try:
            wire.write_frame_sync(sock, wire.hello_msg("client"))
            assert wire.read_frame_sync(sock)["role"] == "replica"
            # max_new=9: token 0 from the prefill boundary, then exactly
            # two full k=4 scanned flushes
            wire.write_frame_sync(sock, {"type": "generate", "id": "r0",
                                         "prompt": prompt, "max_new": 9,
                                         "stream": True})
            frames = []
            while True:
                msg = wire.read_frame_sync(sock)
                frames.append(msg)
                if msg["type"] == "done":
                    break
        finally:
            sock.close()
        toks = [f for f in frames if f["type"] == "token"]
        done = frames[-1]
        assert done["reason"] == "length"
        assert done["tokens"] == _oracle(tiny_tr, prompt, 9)
        assert [f["token"] for f in toks] == done["tokens"][len(prompt):]
        # the burst countdown: first token rides its own 1-burst (the
        # prefill boundary), then two scanned flushes of 4
        assert [f["burst"] for f in toks] == [1, 4, 3, 2, 1, 4, 3, 2, 1]
        assert eng.n_scan_flushes == 2 and eng.n_scan_steps == 8

        with ServingClient(host, port) as c:
            s = c.stats()
            assert s["decode_steps_k"] == 4
            assert s["scan_flushes"] == 2 and s["scan_steps"] == 8
            text = c.metrics()
            vals = {}
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    key, v = line.rsplit(" ", 1)
                    vals[key] = float(v)
        assert vals["serving_scan_steps_total"] == 8.0
        assert vals["serving_scan_flushes_total"] == 2.0
        # burst-honest accounting: EVERY fresh post-first token charged
        # token_latency exactly once (8 = 9 generated - the first)
        assert vals['serving_latency_count{stat="token_latency"}'] == 8.0
        assert vals['serving_latency_count'
                    '{stat="first_token_latency"}'] == 1.0
        eng.kv.check_reclaimed()
    finally:
        srv.stop_background(drain=True)


def test_stats_stale_ok_works_with_pump_off(tiny_tr):
    """The watchdog path must answer when the pump never started — and
    the DEFAULT path must fall back rather than hang forever."""
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background(start_pump=False)
    try:
        with ServingClient(host, port) as c:
            s = c.stats(stale_ok=True)
            assert s["consistent"] is False and s["pump_alive"] is False
            assert s["pump_last_step_age_s"] == -1.0
            s = c.stats()                      # no pump -> stale fallback
            assert s["consistent"] is False
    finally:
        srv.stop_background(drain=True)


def test_stats_queued_behind_stop_is_still_answered(tiny_tr):
    """A consistent-stats command already sitting in the command queue
    when the pump pops "stop" must be answered, not orphaned — the
    pump's stop-drain replies (consistently: it runs between steps on
    the pump thread) instead of leaving the client blocked until its
    socket times out."""
    import socket

    from paddle_tpu.serving import wire

    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    sock = socket.create_connection((host, port))
    sock.settimeout(30)
    try:
        deadline = time.time() + 10
        while not srv._conns and time.time() < deadline:
            time.sleep(0.01)
        conn = next(iter(srv._conns))
        # deterministic ordering: the stats round trip lands BEHIND stop
        srv._cmds.put(("stop",))
        srv._cmds.put(("stats", conn))
        srv._wake.set()
        msg = wire.read_frame_sync(sock)
        assert msg["type"] == "stats" and msg["consistent"] is True
    finally:
        sock.close()
        srv.stop_background(drain=True)


def test_overload_returns_backpressure_not_unbounded_queue(tiny_tr):
    """Admission cap = num_slots + max_queue accepted-but-unfinished
    requests; one more gets an explicit overload frame.  The pump is held
    off so the staging is deterministic."""
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=1)          # cap = 2 slots + 1 = 3
    host, port = srv.start_background(start_pump=False)
    try:
        with ServingClient(host, port) as c:
            prompt = [3, 4, 5]
            ids = [c.submit(prompt, max_new=3) for _ in range(3)]
            over = c.submit(prompt, max_new=3)
            with pytest.raises(OverloadError) as ei:
                c.collect([over])
            assert ei.value.info["reason"] == "queue_full"
            assert ei.value.info["max_inflight"] == 3
            # the three accepted ones complete once the pump starts —
            # backpressure never cost admitted work
            srv.start_pump()
            out = c.collect(ids)
            want = _oracle(tiny_tr, prompt, 3)
            for rid in ids:
                assert out[rid]["tokens"] == want
    finally:
        srv.stop_background(drain=True)


def test_drain_finishes_inflight_and_refuses_new(tiny_tr):
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=8)
    host, port = srv.start_background(start_pump=False)
    stopper = threading.Thread(target=lambda: srv.stop_background(drain=True))
    try:
        with ServingClient(host, port) as c:
            prompt = [4, 5, 6, 7]
            rid = c.submit(prompt, max_new=5)      # accepted, pump off
            # same-connection barrier: the stats reply proves the generate
            # frame was ADMITTED before drain flips the refusal flag —
            # otherwise drain could see inflight=0 and shut down first
            assert c.stats()["inflight"] == 1
            stopper.start()
            for _ in range(200):                   # wait for draining state
                if srv._draining:
                    break
                time.sleep(0.01)
            assert srv._draining
            late = c.submit(prompt, max_new=5)
            with pytest.raises(OverloadError) as ei:
                c.collect([late])
            assert ei.value.info["reason"] == "draining"
            # draining still FINISHES accepted work — drain itself starts
            # the pump that was never running (no explicit start_pump)
            out = c.collect([rid])
            assert out[rid]["tokens"] == _oracle(tiny_tr, prompt, 5)
            assert out[rid]["reason"] == "length"
    finally:
        stopper.join(timeout=120)
    assert not stopper.is_alive(), "drain never completed"
    # listener is down: fresh connections are refused
    with pytest.raises(OSError):
        ServingClient(host, port, timeout=5)


def test_disconnect_cancels_inflight_requests(tiny_tr):
    """A client that vanishes mid-stream must not pin its slot and pages
    forever — the server cancels its requests on connection loss."""
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=8)
    host, port = srv.start_background()
    try:
        c = ServingClient(host, port)
        rid = c.submit([3, 4, 5, 6], max_new=50)
        # wait for the first token frame, then vanish
        msg = c.recv()
        while msg.get("type") != "token":
            msg = c.recv()
        c.close()
        # cancelled pages are reclaimable — free, or donated to the prefix
        # index as cached refcount-zero (evictable on the next allocation)
        def _reclaimable():
            return (eng.kv.free_page_count + eng.kv.cached_page_count
                    == eng.kv.num_pages - 1)

        deadline = time.time() + 60
        while time.time() < deadline:
            if _reclaimable() and srv._inflight == 0:
                break
            time.sleep(0.02)
        assert srv._inflight == 0, "dead client's request never cancelled"
        eng.kv.check_reclaimed()
    finally:
        srv.stop_background(drain=True)


def test_malformed_frames_get_error_frames_not_disconnect(tiny_tr):
    """Protocol garbage — unhashable ids, negative max_new, empty prompts,
    unknown types — must each answer an `error` frame and leave the
    connection (and every other request multiplexed on it) alive."""
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            c.send({"type": "generate", "id": [1], "prompt": [3, 4]})
            assert c.recv()["type"] == "error"          # unhashable id
            c.send({"type": "generate", "id": "neg", "prompt": [3, 4],
                    "max_new": -1})
            msg = c.recv()
            assert msg["type"] == "error" and msg["id"] == "neg"
            assert "negative" in msg["error"]
            c.send({"type": "generate", "id": "empty", "prompt": []})
            msg = c.recv()
            assert msg["type"] == "error" and "prompt" in msg["error"]
            c.send({"type": "generate", "id": "bad", "prompt": "zzz"})
            assert c.recv()["type"] == "error"          # non-id prompt
            c.send({"type": "cancel", "id": {}})        # silently ignored
            c.send({"type": "wat"})
            assert "unknown" in c.recv()["error"]
            # the connection survived all of it — real work still flows
            toks, reason = c.generate([3, 4, 5], max_new=3)
            assert reason == "length" and len(toks) == 6
    finally:
        srv.stop_background(drain=True)


def test_int_and_str_client_ids_do_not_collide(tiny_tr):
    """JSON id 1 and id \"1\" are distinct requests: the engine req_id
    namespace must keep them apart or one route is overwritten and
    _inflight leaks (wedging drain forever)."""
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            c.send({"type": "generate", "id": 1, "prompt": [3, 4],
                    "max_new": 2})
            c.send({"type": "generate", "id": "1", "prompt": [3, 4, 5],
                    "max_new": 2})
            out = c.collect([1, "1"])
        assert len(out[1]["tokens"]) == 4
        assert len(out["1"]["tokens"]) == 5
        assert srv._inflight == 0, "a route was overwritten and leaked"
    finally:
        srv.stop_background(drain=True)


def test_pump_death_fails_pending_and_refuses_new(tiny_tr):
    """If the engine pump dies (device fault mid-step), every accepted
    request gets an error frame and later generates are refused
    immediately — no client may hang on frames that will never come."""
    from paddle_tpu.serving.client import ServerError

    eng = _engine(tiny_tr)
    orig_step = eng.step

    def bad_step():
        if eng.queue or any(s is not None for s in eng.slots):
            raise RuntimeError("boom")
        return orig_step()

    eng.step = bad_step
    srv = ServingServer(eng, max_queue=8)
    host, port = srv.start_background()
    with ServingClient(host, port) as c:
        rid = c.submit([3, 4, 5], max_new=4)
        with pytest.raises(ServerError, match="pump died.*boom"):
            c.collect([rid])           # pending work failed, not stranded
        rid2 = c.submit([3, 4], max_new=4)
        with pytest.raises(ServerError, match="pump died"):
            c.collect([rid2])          # new work refused up front
        s = c.stats()                  # dead pump: stale fallback, no hang
        assert s["consistent"] is False and s["pump_alive"] is False
    with pytest.raises(RuntimeError, match="engine pump died"):
        srv.stop_background(drain=True)


@pytest.mark.slow
def test_serve_cli_sigterm_drains_and_exits_zero():
    """tools/serve.py end to end in a subprocess: bind ephemeral port,
    stream one completion, SIGTERM mid-flight on a second, the drain
    finishes it, process exits 0."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve.py"),
         "--config-args", "vocab=31,dim=16,layers=1,heads=2,batch_size=2",
         "--slots", "2", "--page-size", "8", "--max-context", "32",
         "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        cwd=REPO, env=env)
    try:
        line = ""
        t0 = time.time()
        while time.time() - t0 < 300:
            line = proc.stdout.readline()
            if line.startswith("SERVE_JSON:"):
                break
        assert line.startswith("SERVE_JSON:"), "server never bound"
        import json as _json

        addr = _json.loads(line[len("SERVE_JSON:"):])
        with ServingClient(addr["host"], addr["port"]) as c:
            toks, reason = c.generate([3, 4, 5], max_new=4)
            assert reason == "length" and len(toks) == 7
            rid = c.submit([4, 5, 6], max_new=12)
            # first token seen -> mid-flight; now ask for shutdown
            msg = c.recv()
            while msg.get("type") != "token":
                msg = c.recv()
            proc.send_signal(15)                   # SIGTERM
            c._pending.append(msg)
            out = c.collect([rid])
            assert out[rid]["reason"] == "length"
            assert len(out[rid]["tokens"]) == 3 + 12, \
                "drain did not finish the in-flight request"
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)


@pytest.mark.slow
def test_soak_overcommitted_pool_over_tcp_stays_exact(tiny_tr):
    """Longer mixed workload through TCP against an OVERCOMMITTED pool:
    preemptions fire under the server pump and every completed request
    still matches its oracle exactly."""
    rng = np.random.default_rng(3)
    eng = _engine(tiny_tr, num_slots=2, page_size=4, max_context=16,
                  num_pages=6)
    srv = ServingServer(eng, max_queue=64)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            jobs = []
            for i in range(10):
                # every request's full footprint is 16 tokens = 4 pages,
                # so any two concurrently-decoding slots want 8 of the 5
                # real pages — the pool MUST wedge and preempt
                plen = int(rng.integers(7, 11))
                p = rng.integers(2, 31, plen).tolist()
                mn = 16 - plen
                jobs.append((c.submit(p, max_new=mn), p, mn))
            out = c.collect([rid for rid, _, _ in jobs])
        for rid, p, mn in jobs:
            assert out[rid]["tokens"] == _oracle(tiny_tr, p, mn), \
                f"request {rid} diverged (preemption changed its tokens?)"
        assert eng.n_preemptions > 0, "pool was never overcommitted"
        assert eng._decode_step._cache_size() == 1
    finally:
        srv.stop_background(drain=True)


# ---------------------------------------------------------------------------
# ISSUE 10 satellites: hello negotiation, protocol-naming errors, and the
# client's reconnect-with-backoff
# ---------------------------------------------------------------------------

def test_hello_frame_reports_proto_and_capabilities(tiny_tr):
    """The version/capabilities frame answered on connect — the fleet
    router classifies peers with it, so role/proto/page_size must hold."""
    from paddle_tpu.serving import wire

    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            h = c.hello()
            assert h["proto"] == wire.PROTO
            assert h["role"] == "replica"
            assert "generate" in h["capabilities"]
            assert "dump" in h["capabilities"]
            assert h["page_size"] == 8 and h["num_slots"] == 2
            assert h["max_inflight"] == 6 and h["draining"] is False
            # the KV transfer plane (ISSUE 19): the capability the router
            # keys disaggregated placement on, plus the replica's role tier
            assert "kv_xfer" in h["capabilities"]
            assert h["role_mode"] == "both"
            # negotiation is just another frame: real work still flows
            toks, reason = c.generate([3, 4, 5], max_new=3)
            assert reason == "length" and len(toks) == 6
    finally:
        srv.stop_background(drain=True)


def test_trace_rpc_live_flip_and_context_adoption(tiny_tr):
    """ISSUE 13: the `trace` RPC snapshots the span ring with process
    identity + a clock sample, flips tracing LIVE via `enable` (no
    restart — the operator move and the bench probe's A/B switch), and
    a generate frame's trace context is adopted into the engine's
    lifecycle spans."""
    from paddle_tpu.obs import Tracer

    tracer = Tracer()
    eng = _engine(tiny_tr, tracer=tracer)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            assert "trace" in c.hello()["capabilities"]
            t0 = c.trace()
            assert t0["enabled"] is False and t0["spans"] == []
            assert t0["process"]["role"] == "replica"
            assert t0["process"]["addr"].endswith(f":{port}")
            assert abs(t0["offset_s"]) < 1.0     # same-process clocks
            # flip on live, run one traced request with a CLIENT context
            assert c.trace(enable=True)["enabled"] is True
            toks, reason = c.generate(
                [2, 7, 9], max_new=4,
                trace={"trace_id": "cafe01", "parent": "p9"})
            assert reason == "length"
            # flip off + collect what it froze
            t1 = c.trace(enable=False)
            assert t1["enabled"] is False and tracer.enabled is False
            req = [s for s in t1["spans"]
                   if (s.get("attrs") or {}).get("trace_id") == "cafe01"]
            assert [s["name"] for s in req] == \
                ["queued", "prefill", "decode", "done"]
            assert all(s["attrs"]["parent"] == "p9" for s in req)
            # the done frame carried the timing breakdown too
            rid = c.submit([2, 3, 4], max_new=3)
            timing = c.collect([rid])[rid]["timing"]
            assert timing["total_ms"] <= timing["request_ms"] + 1.0
    finally:
        srv.stop_background(drain=True)


def test_malformed_first_frame_names_expected_protocol(tiny_tr):
    """A peer speaking the wrong protocol (here: HTTP) gets an `error`
    frame NAMING the expected protocol, not a silent close — the router
    depends on this to classify peers."""
    import socket

    from paddle_tpu.serving import wire

    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        s = socket.create_connection((host, port), timeout=10)
        s.settimeout(10)
        try:
            s.sendall(b"GET / HTTP/1.1\r\nHost: x\r\n\r\n")
            msg = wire.read_frame_sync(s)
            assert msg["type"] == "error"
            assert "4-byte big-endian length" in msg["error"]
            assert "hello" in msg["error"]
            assert f"wire protocol v{wire.PROTO}" in msg["error"]
            # after the error frame the server closes the connection
            assert wire.read_frame_sync(s) is None
        finally:
            s.close()
    finally:
        srv.stop_background(drain=True)


def test_client_connect_backoff_survives_restart_window():
    """ECONNREFUSED during a rolling restart's rebind window is a WAIT,
    not an instant failure: the client retries with bounded jittered
    backoff until the listener binds."""
    import socket

    from paddle_tpu.serving import wire

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()                      # port free -> connects are refused

    accepted = []

    def late_bind():
        time.sleep(0.6)                # the restart window
        srv = socket.socket()
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("127.0.0.1", port))
        srv.listen(1)
        conn, _ = srv.accept()
        accepted.append(True)
        # answer a pong so the client can prove the connection works
        f = wire.read_frame_sync(conn)
        assert f == {"type": "ping"}
        conn.sendall(wire.encode({"type": "pong"}))
        time.sleep(0.2)
        conn.close()
        srv.close()

    t = threading.Thread(target=late_bind)
    t.start()
    try:
        c = ServingClient("127.0.0.1", port, timeout=10,
                          connect_attempts=10)
        try:
            assert c.ping()
        finally:
            c.close()
        assert accepted, "client never reached the late-bound listener"
    finally:
        t.join(timeout=30)


def test_client_connect_backoff_exhaustion_is_actionable():
    """Capped attempts against a dead address fail with an error that
    says what was tried and what to do — still an OSError subclass, so
    existing callers' except clauses keep working."""
    import socket

    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    port = probe.getsockname()[1]
    probe.close()
    t0 = time.monotonic()
    with pytest.raises(ConnectionRefusedError,
                       match="after 3 attempts") as ei:
        ServingClient("127.0.0.1", port, timeout=5, connect_attempts=3,
                      connect_backoff_s=0.02)
    assert "restart" in str(ei.value)
    assert time.monotonic() - t0 < 5.0, "backoff must stay bounded"


# ---------------------------------------------------------------------------
# ISSUE 6: flight recorder + postmortem bundle trigger paths
# ---------------------------------------------------------------------------

def _bundles(d):
    import glob

    return sorted(p for p in glob.glob(os.path.join(str(d), "postmortem-*"))
                  if not p.endswith(".tmp"))


def test_pump_crash_writes_loadable_postmortem_bundle(tiny_tr, tmp_path):
    """An induced pump crash freezes one atomic bundle — written on the
    DYING pump thread with engine state exactly as the failure left it —
    and tools/postmortem.py round-trips it."""
    from paddle_tpu.obs.flight import load_bundle
    from paddle_tpu.serving.client import ServerError
    from tools.postmortem import main as postmortem_main

    eng = _engine(tiny_tr)
    orig_step = eng.step

    def bad_step():
        if eng.queue or any(s is not None for s in eng.slots):
            raise RuntimeError("induced device fault")
        return orig_step()

    eng.step = bad_step
    srv = ServingServer(eng, max_queue=8, postmortem_dir=str(tmp_path))
    host, port = srv.start_background()
    with ServingClient(host, port) as c:
        rid = c.submit([3, 4, 5], max_new=4)
        with pytest.raises(ServerError, match="pump died"):
            c.collect([rid])

    found = _bundles(tmp_path)
    assert len(found) == 1, "pump death must freeze exactly one bundle"
    b = load_bundle(found[0])
    assert b["meta"]["reason"] == "pump_death"
    assert "induced device fault" in b["meta"]["error"]
    assert "Traceback" in b["meta"]["error"]
    kinds = [e["kind"] for e in b["events"]]
    assert "pump_death" in kinds and "accept" in kinds
    # the engine snapshot froze the crash state: the victim request is
    # still visible (queued or in its slot), pools are accounted
    occupied = [s for s in b["engine"]["slots"] if s]
    assert b["engine"]["queued"] or occupied
    assert b["engine"]["num_pages"] == eng.kv.num_pages
    assert "compile_watch" in b["engine"] and "hbm" in b["engine"]
    assert b["config"]["num_slots"] == 2
    assert postmortem_main([found[0]]) == 0     # pretty-printer round-trip
    with pytest.raises(RuntimeError, match="engine pump died"):
        srv.stop_background(drain=True)


def test_wedge_watchdog_dumps_once_and_metrics_stay_readable(tiny_tr,
                                                             tmp_path):
    """ISSUE 6 acceptance: deliberately wedge the pump — the watchdog
    sees `pump_last_step_age_s` grow, the metrics frame stays readable
    the whole time (loop-thread path), and the flight recorder emits
    EXACTLY ONE bundle at the threshold (one per wedge episode)."""
    from paddle_tpu.obs.flight import load_bundle

    eng = _engine(tiny_tr)
    orig_step = eng.step
    wedged, release = threading.Event(), threading.Event()

    def wedge_step():
        if not release.is_set() and \
                (eng.queue or any(s is not None for s in eng.slots)):
            wedged.set()
            release.wait(60)                  # the deliberate wedge
        return orig_step()

    eng.step = wedge_step
    # threshold must clear the 0.5 s idle-wait bound or an idle pump
    # reads as wedged (docs/observability.md watchdog semantics)
    srv = ServingServer(eng, max_queue=4, postmortem_dir=str(tmp_path),
                        wedge_threshold_s=1.0)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            rid = c.submit([3, 4, 5], max_new=3)
            assert wedged.wait(30), "pump never picked up the request"
            # the age gauge grows while wedged — stale-ok reads answer
            # from the loop thread against the stuck pump
            a1 = c.stats(stale_ok=True)["pump_last_step_age_s"]
            time.sleep(0.3)
            a2 = c.stats(stale_ok=True)["pump_last_step_age_s"]
            # a1 can round to 0.0 when the read lands within 0.5ms of
            # the frozen beat — the growth is the signal, not the start
            assert a2 > a1 >= 0.0 and a2 >= 0.25
            # the metrics frame stays readable against the wedged engine
            text = c.metrics()
            assert "pump_alive 1" in text
            assert "pump_last_step_age_s" in text
            # the watchdog crosses the 1.0s threshold and dumps ONCE
            deadline = time.time() + 20
            while not _bundles(tmp_path) and time.time() < deadline:
                time.sleep(0.05)
            found = _bundles(tmp_path)
            assert len(found) == 1, "no bundle at the wedge threshold"
            time.sleep(0.6)                   # > watchdog poll period
            assert len(_bundles(tmp_path)) == 1, \
                "a sustained wedge must be one bundle, not one per poll"
            b = load_bundle(found[0])
            assert b["meta"]["reason"] == "wedge"
            assert "pump wedged" in b["meta"]["error"]
            assert "wedge" in [e["kind"] for e in b["events"]]
            # the wedged request is frozen in the snapshot
            assert b["engine"]["queued"] or \
                [s for s in b["engine"]["slots"] if s]
            # release: the pump recovers and the request completes exactly
            release.set()
            out = c.collect([rid])
            assert out[rid]["tokens"] == _oracle(tiny_tr, [3, 4, 5], 3)
    finally:
        release.set()
        srv.stop_background(drain=True)


def test_dump_rpc_freezes_bundle_on_demand(tiny_tr, tmp_path):
    """The operator path: `dump` over the wire freezes a bundle NOW and
    answers its path; without a configured directory it is a clean error
    frame, not a dead connection."""
    from paddle_tpu.obs.flight import load_bundle
    from paddle_tpu.serving.client import ServerError

    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4, postmortem_dir=str(tmp_path))
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            toks, reason = c.generate([3, 4, 5], max_new=4)
            assert reason == "length"
            d = c.dump()
            assert os.path.isdir(d["path"])
            assert d["events"] > 0
            b = load_bundle(d["path"])
            assert b["meta"]["reason"] == "rpc"
            kinds = [e["kind"] for e in b["events"]]
            assert "dump_rpc" in kinds and "finish" in kinds
            assert b["metrics"]["serving_requests_accepted_total"] >= 1.0
            # the engine is healthy and idle in the snapshot
            assert b["engine"]["queued"] == []
            assert all(s is None for s in b["engine"]["slots"])
            # connection survives; the server keeps serving after a dump
            toks2, _ = c.generate([4, 5], max_new=3)
            assert len(toks2) == 5
    finally:
        srv.stop_background(drain=True)

    eng2 = _engine(tiny_tr)
    srv2 = ServingServer(eng2, max_queue=4)    # no postmortem dir
    host, port = srv2.start_background()
    try:
        with ServingClient(host, port) as c:
            with pytest.raises(ServerError, match="no postmortem dir"):
                c.dump()
    finally:
        srv2.stop_background(drain=True)


# ---------------------------------------------------------------------------
# ISSUE 19: binary-frame robustness + the kv_push page-transfer plane
# ---------------------------------------------------------------------------

def test_bin_frame_over_cap_answers_error_then_severs(tiny_tr):
    """A peer declaring a binary frame bigger than the endpoint's 8 MiB
    cap gets an error frame NAMING the cap, then a clean close — the
    declared length is refused from the 4-byte prefix alone, before a
    single payload byte is buffered."""
    import socket
    import struct

    from paddle_tpu.serving import wire

    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        s = socket.create_connection((host, port), timeout=10)
        s.settimeout(10)
        try:
            wire.write_frame_sync(s, {"type": "ping"})
            assert wire.read_frame_sync(s)["type"] == "pong"
            s.sendall(struct.pack(
                ">I", wire.BIN_BIT | (wire.MAX_BIN_PAYLOAD + 1)))
            msg = wire.read_frame_sync(s)
            assert msg["type"] == "error"
            assert "binary-frame cap" in msg["error"]
            assert str(wire.MAX_BIN_PAYLOAD) in msg["error"]
            assert wire.read_frame_sync(s) is None     # severed cleanly
        finally:
            s.close()
        # the listener survived the hostile peer: real work still flows
        with ServingClient(host, port) as c:
            toks, reason = c.generate([3, 4, 5], max_new=3)
            assert reason == "length" and len(toks) == 6
    finally:
        srv.stop_background(drain=True)


def test_bin_frame_truncated_mid_payload_severs_cleanly(tiny_tr):
    """A binary frame whose sender dies mid-payload must not wedge the
    reader or leak half-buffered kv_push state — the connection dies,
    the buffered parts die with it, the server keeps serving."""
    import socket
    import struct

    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        from paddle_tpu.serving import wire

        s = socket.create_connection((host, port), timeout=10)
        try:
            # declare a 4096-byte binary body, deliver 10 bytes, vanish
            s.sendall(struct.pack(">I", wire.BIN_BIT | 4096) + b"x" * 10)
        finally:
            s.close()
        deadline = time.time() + 20
        while srv._conns and time.time() < deadline:
            time.sleep(0.01)
        assert not srv._conns, "truncated peer's connection never reaped"
        assert srv._kv_parts == {}
        with ServingClient(host, port) as c:
            toks, reason = c.generate([3, 4, 5], max_new=3)
            assert reason == "length" and len(toks) == 6
    finally:
        srv.stop_background(drain=True)


def test_kv_push_malformed_frames_refused_not_fatal(tiny_tr):
    """Hostile/buggy kv_push senders — no part 0, page counts outside
    the pool, payload overrunning the declared blob, garbage meta — each
    answer a `kv_push ok:false` (or error) frame and leave the
    connection serving; nothing is buffered past the refusal."""
    import socket

    from paddle_tpu.serving import wire

    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        s = socket.create_connection((host, port), timeout=30)
        s.settimeout(30)
        try:
            # unusable id: error frame, not a dead socket
            s.sendall(wire.encode_bin({"type": "kv_push", "id": [1],
                                       "seq": 0, "last": True}, b""))
            msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
            assert msg["type"] == "error" and "id" in msg["error"]
            # part 1 with no part 0 before it
            s.sendall(wire.encode_bin({"type": "kv_push", "id": "a",
                                       "seq": 1, "last": True}, b"zz"))
            msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
            assert msg["type"] == "kv_push" and msg["ok"] is False
            assert "no part 0" in msg["error"]
            # page counts the pool cannot hold (zero / the whole pool)
            for n in (0, eng.kv.num_pages):
                s.sendall(wire.encode_bin(
                    {"type": "kv_push", "id": "b", "seq": 0, "last": True,
                     "tokens": [3] * 8, "meta": {"n_pages": n}}, b""))
                msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
                assert msg["ok"] is False and "pool" in msg["error"]
            # payload overruns the declared 1-page blob
            s.sendall(wire.encode_bin(
                {"type": "kv_push", "id": "c", "seq": 0, "last": True,
                 "tokens": [3] * 8, "meta": {"n_pages": 1}},
                b"\0" * (eng.kv.page_nbytes + 1)))
            msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
            assert msg["ok"] is False and "declared blob" in msg["error"]
            # structurally valid framing, garbage meta: the import itself
            # refuses on the pump thread and answers ok:false
            s.sendall(wire.encode_bin(
                {"type": "kv_push", "id": "d", "seq": 0, "last": True,
                 "tokens": [3] * 8,
                 "meta": {"n_pages": 1, "page_size": 8, "layers": []}},
                b"\0" * eng.kv.page_nbytes))
            msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
            assert msg["type"] == "kv_push" and msg["ok"] is False
            assert srv._kv_parts == {}, "a refusal left buffered parts"
            # a repeated part 0 while the id's blob is still accumulating
            # is refused (the half-built blob dropped), never a silent
            # restart of the accumulation
            for _ in range(2):
                s.sendall(wire.encode_bin(
                    {"type": "kv_push", "id": "e", "seq": 0, "last": False,
                     "tokens": [3] * 8, "meta": {"n_pages": 1}}, b""))
            msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
            assert msg["ok"] is False and "repeated" in msg["error"]
            # server-wide buffer budget: two blobs that together declare
            # more than one pool's worth of bytes — the second is refused
            # up front instead of buffering multiples of the pool
            s.sendall(wire.encode_bin(
                {"type": "kv_push", "id": "f", "seq": 0, "last": False,
                 "tokens": [3] * 8,
                 "meta": {"n_pages": eng.kv.num_pages - 1}}, b""))
            s.sendall(wire.encode_bin(
                {"type": "kv_push", "id": "g", "seq": 0, "last": True,
                 "tokens": [3] * 8,
                 "meta": {"n_pages": eng.kv.num_pages - 1}}, b""))
            msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
            assert msg["ok"] is False and "budget" in msg["error"]
            # finish the live blob: the pump's import refuses the
            # token/page mismatch cleanly and nothing stays buffered
            s.sendall(wire.encode_bin(
                {"type": "kv_push", "id": "f", "seq": 1, "last": True},
                b""))
            msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
            assert msg["type"] == "kv_push" and msg["ok"] is False
            assert srv._kv_parts == {}, "a refusal left buffered parts"
            # the connection survived every refusal — real work flows
            wire.write_frame_sync(s, {"type": "generate", "id": "ok",
                                      "prompt": [3, 4, 5], "max_new": 2,
                                      "stream": False})
            while True:
                msg = wire.read_frame_sync(s, bin_cap=wire.MAX_BIN_PAYLOAD)
                if msg["type"] == "done":
                    break
            assert msg["reason"] == "length" and len(msg["tokens"]) == 5
        finally:
            s.close()
        eng.kv.check_reclaimed()
    finally:
        srv.stop_background(drain=True)


def test_kv_push_ships_pages_and_decode_side_admission_hits(tiny_tr):
    """The transfer plane end to end between two servers: a prefill_only
    request on replica A pushes its committed prompt pages to replica B;
    B mounts them through its prefix tree, so the SAME prompt admitted
    at B is a prefix hit and decodes token-for-token with the oracle.
    A push aimed at a dead port degrades to push_ok:false on the done
    frame (counted), never an error."""
    rng = np.random.default_rng(9)
    eng_a = _engine(tiny_tr)
    srv_a = ServingServer(eng_a, max_queue=8, role="prefill")
    ha, pa = srv_a.start_background()
    eng_b = _engine(tiny_tr)
    srv_b = ServingServer(eng_b, max_queue=8, role="decode")
    hb, pb = srv_b.start_background()
    try:
        prompt = rng.integers(2, 31, 19).tolist()   # 2 committed pages
        with ServingClient(ha, pa) as ca:
            rid = ca.submit(prompt, max_new=8, prefill_only=True,
                            push_to={"host": hb, "port": pb})
            out = ca.collect([rid])
            assert out[rid]["push_ok"] is True
            assert out[rid]["pushed_pages"] == 2
            # prefill_only clamps generation to the 1-token boundary
            assert len(out[rid]["tokens"]) == len(prompt) + 1
            sa = ca.stats()
            assert sa["role"] == "prefill"
            assert sa["kv_pushes"] == 1 and sa["kv_push_failures"] == 0
            assert sa["kv_pages_shipped"] == 2
        with ServingClient(hb, pb) as cb:
            sb = cb.stats()
            assert sb["role"] == "decode"
            assert sb["kv_pages_received"] == 2 and sb["kv_mounts"] == 1
            toks, reason = cb.generate(prompt, max_new=6)
            assert reason == "length"
            assert toks == _oracle(tiny_tr, prompt, 6)
            assert cb.stats()["prefix_hits"] == 1, \
                "shipped pages must make the decode-side admission a hit"
        # same request single-replica: identical tokens (the exactness bar)
        eng_c = _engine(tiny_tr)
        srv_c = ServingServer(eng_c, max_queue=8)
        hc, pc = srv_c.start_background()
        try:
            with ServingClient(hc, pc) as cc:
                ctoks, _ = cc.generate(prompt, max_new=6)
            assert toks == ctoks
        finally:
            srv_c.stop_background(drain=True)
        # a push to a dead port: honest push_ok:false, request still done
        import socket

        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
        probe.close()
        with ServingClient(ha, pa) as ca:
            rid = ca.submit(rng.integers(2, 31, 10).tolist(), max_new=4,
                            prefill_only=True,
                            push_to={"host": "127.0.0.1",
                                     "port": dead_port})
            out = ca.collect([rid])
            assert out[rid]["push_ok"] is False
            assert "kv_push" in out[rid]["push_error"]
            assert ca.stats()["kv_push_failures"] == 1
        eng_a.kv.check_reclaimed()
        eng_b.kv.check_reclaimed()
    finally:
        srv_a.stop_background(drain=True)
        srv_b.stop_background(drain=True)


def test_kv_push_part0_chunk_sized_from_encoded_header():
    """Long-prompt regression: part 0's JSON header carries the FULL
    token list, so a fixed 64 KiB headroom busts the 8 MiB bin cap past
    ~9k tokens — exactly the prompts --disagg-min-prompt selects for.
    Every frame must stay under the receiver's bin_cap with the part-0
    chunk sized from the encoded header, and the parts must reassemble
    the exact payload.  Pure framing — no engine in the loop."""
    from paddle_tpu.serving import wire
    from paddle_tpu.serving.server import _kv_push_frames

    toks = list(range(20_000))               # header alone ~ 130 KiB
    meta = {"n_pages": 4, "page_size": 8, "layers": [
        {"name": "l0.attn", "h_kv": 2, "dh": 8, "dtype": "float32"}]}
    payload = bytes(range(256)) * 66_000     # ~16 MiB -> several parts
    frames = _kv_push_frames("rid", toks, meta, payload)
    assert len(frames) >= 3
    got = b""
    for i, fr in enumerate(frames):
        # the receiver's first act: bound the DECLARED body by bin_cap —
        # an over-cap part 0 would be refused and the connection severed
        n, binary = wire.split_length(fr[:4], bin_cap=wire.MAX_BIN_PAYLOAD)
        assert binary and n == len(fr) - 4
        msg = wire._decode_bin_body(fr[4:])
        assert msg["seq"] == i and msg["last"] == (i == len(frames) - 1)
        if i == 0:
            assert msg["tokens"] == toks and msg["meta"] == meta
        got += msg[wire.PAYLOAD_KEY]
    assert got == payload
    # a token list that cannot fit even an empty-chunk part 0 raises
    # FrameError — the sender degrades to push_ok:false, never ships a
    # frame the peer is guaranteed to refuse
    with pytest.raises(wire.FrameError, match="binary-frame cap"):
        _kv_push_frames("rid", list(range(1_500_000)), meta, b"")


def test_kv_push_malformed_reply_degrades_to_push_ok_false(tiny_tr):
    """A decode peer that answers the push with a MALFORMED frame raises
    wire.FrameError (a ValueError, not an OSError) in the sender's
    reply read — the fire-and-forget push task must still resolve the
    prefill leg: done arrives with push_ok:false, the route does not
    leak, and the inflight slot is released.  (An uncaught exception
    here hangs the router's prefill leg forever — the replica stays
    healthy so no retry fires — and pins an inflight slot per hit.)"""
    import socket
    import struct

    lst = socket.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)
    port = lst.getsockname()[1]
    peers = []

    def peer():
        # accept the push, then answer a valid-length non-JSON body —
        # FrameError on the sender, with the socket held OPEN so no
        # OSError path can mask the bug
        c, _ = lst.accept()
        peers.append(c)
        c.recv(1 << 20)
        c.sendall(struct.pack(">I", 5) + b"notjs")

    threading.Thread(target=peer, daemon=True).start()
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4, role="prefill")
    host, sport = srv.start_background()
    try:
        with ServingClient(host, sport) as c:
            rid = c.submit([3, 4, 5, 6, 7, 8, 9, 10], max_new=4,
                           prefill_only=True,
                           push_to={"host": "127.0.0.1", "port": port})
            out = c.collect([rid])
            assert out[rid]["push_ok"] is False
            assert "kv_push failed" in out[rid]["push_error"]
            assert c.stats()["kv_push_failures"] == 1
        assert srv._routes == {} and srv._inflight == 0, \
            "a failed push leaked its route/inflight slot"
        eng.kv.check_reclaimed()
    finally:
        srv.stop_background(drain=True)
        lst.close()
        for p in peers:
            p.close()
