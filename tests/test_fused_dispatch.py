"""Fused k-step scan dispatch (--steps_per_dispatch) oracles.

Three contracts, all CPU-verifiable:
  1. PARITY — train_one_pass(steps_per_dispatch=k) is bit-exact (fp32)
     with the k=1 loop: same losses, same parameters, same evaluator
     results, on an RNN config whose batches span two length buckets
     (so groups must flush on signature change to preserve update order),
     including under gradient accumulation.
  2. DISPATCH COUNT — n same-signature batches execute in exactly
     ceil(n/k) compiled scan dispatches, each carrying k batches (the
     last possibly fewer), with ZERO per-batch step dispatches.
  3. PREFETCH OVERLAP — the DeviceDoubleBuffer stages item i+1 while the
     consumer still holds item i, and propagates producer errors.
"""

import threading

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.data.feeder import DeviceDoubleBuffer, make_batch
from paddle_tpu.data.provider import integer_value, integer_value_sequence
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer

B, VOCAB, NCLS = 4, 10, 3


def _rnn_conf():
    from paddle_tpu.dsl import (
        MomentumOptimizer, SoftmaxActivation, classification_cost,
        data_layer, embedding_layer, fc_layer, last_seq, settings,
    )
    from paddle_tpu.dsl.recurrent_units import GatedRecurrentLayerGroup

    settings(batch_size=B, learning_rate=0.1,
             learning_method=MomentumOptimizer(momentum=0.9))
    data = data_layer(name="word", size=VOCAB)
    emb = embedding_layer(input=data, size=8)
    from paddle_tpu.dsl import full_matrix_projection
    gru = GatedRecurrentLayerGroup(name="gru_u", size=8,
                                   inputs=[full_matrix_projection(input=emb)])
    out = fc_layer(input=last_seq(input=gru), size=NCLS,
                   act=SoftmaxActivation())
    classification_cost(input=out, label=data_layer(name="label", size=NCLS))


def _accum_conf():
    """Same net with gradient accumulation (window of 2): the
    accumulate-or-apply lax.cond must scan unchanged inside a k-group."""
    from paddle_tpu.dsl import (
        MomentumOptimizer, SoftmaxActivation, classification_cost,
        data_layer, embedding_layer, fc_layer, full_matrix_projection,
        last_seq, settings,
    )
    from paddle_tpu.dsl.recurrent_units import GatedRecurrentLayerGroup

    settings(batch_size=B, learning_rate=0.1,
             learning_method=MomentumOptimizer(momentum=0.9),
             num_batches_per_send_parameter=2)
    data = data_layer(name="word", size=VOCAB)
    emb = embedding_layer(input=data, size=8)
    gru = GatedRecurrentLayerGroup(name="gru_u", size=8,
                                   inputs=[full_matrix_projection(input=emb)])
    out = fc_layer(input=last_seq(input=gru), size=NCLS,
                   act=SoftmaxActivation())
    classification_cost(input=out, label=data_layer(name="label", size=NCLS))


def _bucketed_batches(n_batches=8, seed=0):
    """Batches alternating between two length buckets (pad 8 vs pad 16):
    the fused grouper must flush on every signature change to keep the
    update order identical to the per-batch loop."""
    rng = np.random.default_rng(seed)
    types = [integer_value_sequence(VOCAB), integer_value(NCLS)]
    out = []
    for i in range(n_batches):
        # bucket A: lengths 3..8 (pads to 8); bucket B: 9..16 (pads to 16)
        lo, hi = (3, 8) if (i // 2) % 2 == 0 else (9, 16)
        samples = []
        for _ in range(B):
            L = int(rng.integers(lo, hi + 1))
            samples.append((rng.integers(0, VOCAB, L).tolist(),
                            int(rng.integers(0, NCLS))))
        out.append(make_batch(samples, types, ["word", "label"]))
    return out


def _params(tr):
    return {k: np.asarray(v) for k, v in tr.params.items()}


def _strip(stats):
    return {k: v for k, v in stats.items()
            if k not in ("seconds", "samples_per_sec")}


@pytest.mark.parametrize("conf", [_rnn_conf, _accum_conf],
                         ids=["plain", "grad_accum"])
@pytest.mark.parametrize("k", [1, 2, 4])
def test_scan_vs_loop_parity(conf, k):
    """train_one_pass(steps_per_dispatch=k) reproduces the k=1 loop
    bit-exactly: losses (total cost), parameters, and evaluator results,
    on length-bucketed RNN batches."""
    batches = _bucketed_batches()
    ref = Trainer(parse_config_callable(conf), seed=11)
    ref_stats = ref.train_one_pass(batches=iter(batches),
                                   steps_per_dispatch=1)
    tr = Trainer(parse_config_callable(conf), seed=11)
    stats = tr.train_one_pass(batches=iter(batches), steps_per_dispatch=k)

    assert _strip(stats) == _strip(ref_stats)
    pr, pf = _params(ref), _params(tr)
    for name in pr:
        np.testing.assert_array_equal(
            pr[name], pf[name],
            err_msg=f"param {name!r} diverged at steps_per_dispatch={k}")
    # the rng stream advanced identically (pre-split per-step keys)
    np.testing.assert_array_equal(np.asarray(ref.rng), np.asarray(tr.rng))


def test_two_passes_stay_exact():
    """The fused path must leave every carried state (rng, optimizer
    slots, grad-accum window reset at finish_pass) exactly as the k=1
    loop does — a second pass stays bit-identical too."""
    batches = _bucketed_batches()
    ref = Trainer(parse_config_callable(_accum_conf), seed=5)
    tr = Trainer(parse_config_callable(_accum_conf), seed=5)
    for _ in range(2):
        ref.train_one_pass(batches=iter(batches), steps_per_dispatch=1)
        tr.train_one_pass(batches=iter(batches), steps_per_dispatch=3)
    pr, pf = _params(ref), _params(tr)
    for name in pr:
        np.testing.assert_array_equal(pr[name], pf[name])


def test_dispatch_count_is_ceil_n_over_k():
    """7 same-signature batches at k=3 -> exactly ceil(7/3)=3 compiled
    scan executions carrying [3, 3, 1] batches, and ZERO per-batch step
    dispatches (the per-step Python dispatch overhead is what the fusion
    removes)."""
    rng = np.random.default_rng(2)
    types = [integer_value_sequence(VOCAB), integer_value(NCLS)]
    batches = []
    for _ in range(7):
        samples = [(rng.integers(0, VOCAB, 6).tolist(),
                    int(rng.integers(0, NCLS))) for _ in range(B)]
        batches.append(make_batch(samples, types, ["word", "label"]))

    tr = Trainer(parse_config_callable(_rnn_conf), seed=1)
    fused_sizes = []
    per_batch = []
    orig_fused, orig_step = tr._fused_step, tr._train_step

    def counting_fused(p, o, n, stacked, keys):
        fused_sizes.append(int(keys.shape[0]))
        return orig_fused(p, o, n, stacked, keys)

    def counting_step(*a):
        per_batch.append(1)
        return orig_step(*a)

    tr._fused_step = counting_fused
    tr._train_step = counting_step
    stats = tr.train_one_pass(batches=iter(batches), steps_per_dispatch=3)

    assert fused_sizes == [3, 3, 1], fused_sizes
    assert per_batch == [], "per-batch step dispatched in fused mode"
    assert tr._n_fused_dispatches == 3
    assert stats["batches"] == 7
    # the h2d window filled from the prefetch thread: staging is observable
    assert len(tr.barrier_stat.h2d_s) == 3


def test_stateful_model_settles_then_fuses():
    """A stateful model (training-mode batch norm) grows net_state on its
    first dispatch; the fused path routes exactly that one batch through
    the per-batch step (as k=1's batch 0 does), then scans — and stays
    bit-exact."""
    def conf():
        from paddle_tpu.dsl import (
            MomentumOptimizer, SoftmaxActivation, TanhActivation,
            batch_norm_layer, classification_cost, data_layer, fc_layer,
            settings,
        )
        settings(batch_size=8, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.9))
        x = data_layer(name="x", size=6)
        h = fc_layer(input=x, size=10, act=TanhActivation())
        h = batch_norm_layer(input=h)
        out = fc_layer(input=h, size=NCLS, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=NCLS))

    rng = np.random.default_rng(0)
    batches = [{"x": Argument(value=rng.normal(size=(8, 6)).astype(np.float32)),
                "y": Argument(ids=rng.integers(0, NCLS, 8).astype(np.int32))}
               for _ in range(5)]
    ref = Trainer(parse_config_callable(conf), seed=7)
    ref.train_one_pass(batches=iter(batches), steps_per_dispatch=1)
    tr = Trainer(parse_config_callable(conf), seed=7)
    per_batch = []
    orig_step = tr._train_step

    def counting_step(*a):
        per_batch.append(1)
        return orig_step(*a)

    tr._train_step = counting_step
    tr.train_one_pass(batches=iter(batches), steps_per_dispatch=2)
    assert len(per_batch) == 1, "exactly one settling dispatch expected"
    pr, pf = _params(ref), _params(tr)
    for name in pr:
        np.testing.assert_array_equal(pr[name], pf[name])
    import jax
    ns_ref = jax.tree.map(np.asarray, ref.net_state)
    ns_tr = jax.tree.map(np.asarray, tr.net_state)
    for lname in ns_ref:
        for stat in ns_ref[lname]:
            np.testing.assert_array_equal(ns_ref[lname][stat],
                                          ns_tr[lname][stat])


# -- DeviceDoubleBuffer ------------------------------------------------------

def test_device_double_buffer_overlaps_staging():
    """While the consumer holds item i, the background thread must already
    be staging item i+1 — that overlap is the whole point of the device
    double buffer."""
    staged = [threading.Event() for _ in range(3)]

    def place(i):
        staged[i].set()
        return i

    buf = DeviceDoubleBuffer(iter(range(3)), place)
    it = iter(buf)
    assert next(it) == 0
    # consumer still "computing" on item 0: item 1 must stage meanwhile
    assert staged[1].wait(timeout=10.0), \
        "item 1 was not prefetched while item 0 was being consumed"
    assert list(it) == [1, 2]


def test_device_double_buffer_propagates_errors():
    def items():
        yield 1
        raise ValueError("provider died")

    buf = DeviceDoubleBuffer(items(), lambda x: x)
    with pytest.raises(ValueError, match="provider died"):
        list(buf)


def test_device_double_buffer_close_releases_producer():
    """An abandoning consumer (mid-pass exception) must not leave the
    producer thread blocked on the bounded queue holding staged items:
    close() releases it."""
    produced = []

    def items():
        for i in range(100):
            produced.append(i)
            yield i

    buf = DeviceDoubleBuffer(items(), lambda x: x)
    it = iter(buf)
    assert next(it) == 0
    buf.close()
    assert not buf._thread.is_alive(), "producer thread still blocked"
    assert len(produced) < 100, "producer ran the whole source after close"


def test_device_double_buffer_times_staging():
    ticks = []

    class _Ctx:
        def __enter__(self):
            return self

        def __exit__(self, *exc):
            ticks.append(1)
            return False

    buf = DeviceDoubleBuffer(iter(range(4)), lambda x: x, timer=_Ctx)
    assert list(buf) == [0, 1, 2, 3]
    assert len(ticks) == 4


def test_feeder_device_batches_stages_to_device():
    """DataFeeder.device_batches: batches from a real @provider flow
    through the background double buffer with place_fn applied — the
    feeder-level H2D staging surface (ShardFeeder shares the contract)."""
    import jax

    from paddle_tpu.data.feeder import DataFeeder
    from paddle_tpu.data.provider import (
        dense_vector, integer_value as iv, provider,
    )

    @provider(input_types={"x": dense_vector(4), "y": iv(NCLS)})
    def proc(settings, filename):
        rng = np.random.default_rng(0)
        for _ in range(12):
            yield {"x": rng.normal(size=(4,)).astype(np.float32),
                   "y": int(rng.integers(0, NCLS))}

    proc.initialize(["f0"])
    feeder = DataFeeder(proc, ["f0"], input_names=["x", "y"], batch_size=4,
                        shuffle=False, drop_last=False)
    placed = []

    def place(batch):
        placed.append(1)
        return jax.device_put(batch)

    got = list(feeder.device_batches(place))
    assert len(got) == 3 and len(placed) == 3
    assert all(isinstance(b["x"].value, jax.Array) for b in got)
    # values survive the staging round-trip
    ref = list(feeder.batches())
    np.testing.assert_array_equal(np.asarray(got[0]["x"].value),
                                  ref[0]["x"].value)
