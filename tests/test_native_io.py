"""Native C++ shard loader vs pure-Python reader — content oracle
(mirrors the reference's ProtoDataProvider tests: write shards, read them
back through the provider machinery, check batching/shuffle/sequence
layout — ref: paddle/gserver/tests/test_ProtoDataProvider.cpp)."""

import os

import numpy as np
import pytest

from paddle_tpu.data.provider import (
    dense_vector, dense_vector_sequence, integer_value, integer_value_sequence,
)
from paddle_tpu.io import (
    NativeShardLoader, available, read_shard, write_shards,
)

TYPES = [dense_vector(4), integer_value(10), integer_value_sequence(50),
         dense_vector_sequence(3)]
NAMES = ["feat", "label", "words", "frames"]


def _make_samples(n, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        T1 = int(rng.integers(1, 9))
        T2 = int(rng.integers(1, 6))
        out.append((
            rng.standard_normal(4).astype(np.float32),
            int(rng.integers(0, 10)),
            rng.integers(0, 50, T1).astype(np.int32),
            rng.standard_normal((T2, 3)).astype(np.float32),
        ))
    return out


def test_shard_roundtrip_python(tmp_path):
    samples = _make_samples(37)
    paths = write_shards(samples, TYPES, str(tmp_path), shard_size=20)
    assert len(paths) == 2
    back = [s for p in paths for s in read_shard(p)]
    assert len(back) == 37
    for orig, got in zip(samples, back):
        np.testing.assert_allclose(got[0], orig[0])
        assert got[1] == orig[1]
        np.testing.assert_array_equal(got[2], orig[2])
        np.testing.assert_allclose(got[3], orig[3])


@pytest.mark.skipif(not available(), reason="no C++ toolchain")
def test_native_loader_contents(tmp_path):
    samples = _make_samples(53, seed=1)
    paths = write_shards(samples, TYPES, str(tmp_path), shard_size=25)
    loader = NativeShardLoader(paths, NAMES, TYPES, batch_size=8,
                               shuffle=False, seed=0)
    got = []
    nb = 0
    for batch in loader.one_pass():
        nb += 1
        B = batch["label"].ids.shape[0]
        assert B <= 8
        # padded shapes: multiple of pad_multiple
        assert batch["words"].ids.shape[1] % 8 == 0
        for b in range(B):
            L1 = int(batch["words"].lengths[b])
            L2 = int(batch["frames"].lengths[b])
            got.append((batch["feat"].value[b],
                        int(batch["label"].ids[b]),
                        batch["words"].ids[b, :L1],
                        batch["frames"].value[b, :L2]))
            # padding is zero
            assert np.all(batch["words"].ids[b, L1:] == 0)
            assert np.all(batch["frames"].value[b, L2:] == 0)
    loader.close()
    assert nb == 7  # ceil(53/8)
    assert len(got) == 53
    # no-shuffle preserves order
    for orig, g in zip(samples, got):
        np.testing.assert_allclose(g[0], orig[0], rtol=1e-6)
        assert g[1] == orig[1]
        np.testing.assert_array_equal(g[2], orig[2])
        np.testing.assert_allclose(g[3], orig[3], rtol=1e-6)


@pytest.mark.skipif(not available(), reason="no C++ toolchain")
def test_native_loader_shuffle_covers_all(tmp_path):
    samples = _make_samples(40, seed=2)
    paths = write_shards(samples, TYPES, str(tmp_path), shard_size=40)
    loader = NativeShardLoader(paths, NAMES, TYPES, batch_size=8,
                               shuffle=True, pool_size=16, seed=7)
    labels1 = []
    for batch in loader.one_pass():
        labels1.extend(batch["feat"].value[:, 0].tolist())
    labels2 = []
    for batch in loader.one_pass():
        labels2.extend(batch["feat"].value[:, 0].tolist())
    loader.close()
    # each pass covers the whole dataset exactly once
    expect = sorted(s[0][0] for s in samples)
    assert np.allclose(sorted(labels1), expect, rtol=1e-6)
    assert np.allclose(sorted(labels2), expect, rtol=1e-6)
    # and in a different order (shuffled)
    assert labels1 != labels2


@pytest.mark.skipif(not available(), reason="no C++ toolchain")
def test_native_loader_schema_mismatch(tmp_path):
    samples = _make_samples(5)
    paths = write_shards(samples, TYPES, str(tmp_path))
    with pytest.raises(AssertionError, match="schema"):
        NativeShardLoader(paths, ["a"], [dense_vector(2)], batch_size=4)


@pytest.mark.skipif(not available(), reason="no C++ toolchain")
def test_native_loader_corrupt_shard(tmp_path):
    samples = _make_samples(5)
    paths = write_shards(samples, TYPES, str(tmp_path))
    with open(paths[0], "r+b") as f:
        f.truncate(os.path.getsize(paths[0]) - 3)
    loader = NativeShardLoader(paths, NAMES, TYPES, batch_size=64,
                               shuffle=False)
    with pytest.raises(RuntimeError, match="corrupt|native loader"):
        for _ in loader.one_pass():
            pass
    loader.close()


def test_train_from_shards_e2e(tmp_path):
    """Full path: samples -> shards -> define_ptsh_data_sources -> Trainer."""
    from paddle_tpu import dsl
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.trainer.trainer import Trainer

    rng = np.random.default_rng(3)
    samples = []
    for _ in range(64):
        x = rng.standard_normal(6).astype(np.float32)
        y = int(x.sum() > 0)
        samples.append((x, y))
    write_shards(samples, [dense_vector(6), integer_value(2)],
                 str(tmp_path), shard_size=32)

    def conf():
        dsl.settings(batch_size=16, learning_rate=0.5,
                     learning_method=dsl.MomentumOptimizer(momentum=0.9))
        dsl.define_ptsh_data_sources(str(tmp_path), names=["x", "y"])
        x = dsl.data_layer(name="x", size=6)
        out = dsl.fc_layer(input=x, size=2, act=dsl.SoftmaxActivation())
        dsl.classification_cost(input=out, label=dsl.data_layer(name="y", size=2))

    cfg = parse_config_callable(conf)
    tr = Trainer(cfg, seed=0)
    costs = []
    for _ in range(5):
        st = tr.train_one_pass()
        costs.append(st["cost"])
    assert costs[-1] < costs[0] * 0.7, costs
