"""Cross-replica KV page transfer (ISSUE 19): the allocator seam under
the disaggregated prefill/decode plane — `export_pages` serializes live
committed pages to host bytes (the spill tier's per-layer layout),
`import_pages` scatters them into freshly-taken pages with one bucketed
dispatch, and `ServingEngine.import_prefix` mounts the run through the
prefix tree so the next admission is a prefix hit.

The contracts pinned here: marker K/V survives the wire round-trip
bit-exactly, refcounts balance (`check()`/`check_reclaimed()` green after
every path), a malformed blob or a dry pool rolls the allocator back
EXACTLY (free-list order included), and a re-import of an already-mounted
run frees the duplicate pages instead of leaking them.  The end-to-end
cross-REPLICA oracles (router + kv_push wire plane) live in
tests/test_fleet.py; this file is the in-process allocator/engine half.
"""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.serving import PagedKVCache, Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer

BIG = 1 << 20


@pytest.fixture(scope="module")
def tr():
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=23,dim=16,layers=2,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _oracle(tr, req: Request):
    toks, lens = lm_generate(
        tr.executor, tr.params, req.prompt_ids[None, :],
        max_new=req.max_new, temperature=req.temperature, top_k=req.top_k,
        top_p=req.top_p, eos_id=req.eos_id, rng=req.rng, use_cache=True)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])]


def _kv(tr, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("pages_per_slot", 3)
    kw.setdefault("num_pages", 8)
    return PagedKVCache(tr.executor, **kw)


def _committed_pages(kv, n_tokens=12):
    """Grow slot 0, mark the pages prefix-cached, release the slot —
    refcount-zero cached pages, the exportable state donation leaves."""
    assert kv.try_grow(0, n_tokens)
    pages = [int(kv.table[0, j]) for j in range(kv.pages_for(n_tokens))]
    for p in pages:
        kv.cache_page(p)
    kv.release(0)
    return pages


# ---------------------------------------------------------------------------
# allocator unit: export/import round trip + exact rollback
# ---------------------------------------------------------------------------

def test_export_import_roundtrip_unit(tr):
    """Marker K/V planted in a source pool survives export -> bytes ->
    import into a SEPARATE pool bit-exactly, refcounts balance on both
    sides, and both allocators end check()/check_reclaimed() green."""
    src, dst = _kv(tr), _kv(tr)
    pages = _committed_pages(src)
    name = next(iter(src.pools))
    src.pools[name]["k"] = \
        src.pools[name]["k"].at[pages[0], 1, 0, 2].set(7.5)
    src.pools[name]["v"] = \
        src.pools[name]["v"].at[pages[2], 3, 1, 1].set(-2.25)

    meta, payload = src.export_pages(pages)
    assert meta["n_pages"] == 3 and meta["page_size"] == src.page_size
    assert [l["name"] for l in meta["layers"]] == sorted(src.pools)
    assert len(payload) == 3 * src.page_nbytes
    assert src.n_exported == 3
    src.check()                                     # export mutates nothing

    taken = dst.take_pages(3)
    dst.import_pages(meta, payload, taken)
    dst.adopt_restored(taken)
    assert float(dst.pools[name]["k"][taken[0], 1, 0, 2]) == 7.5, \
        "imported page lost its K contents"
    assert float(dst.pools[name]["v"][taken[2], 3, 1, 1]) == -2.25, \
        "imported page lost its V contents"
    assert dst.n_imported == 3
    dst.check()
    assert dst.cached_page_count == 3

    # full reclaim on both sides: the transfer leaked nothing
    for p in pages:
        src.uncache_page(p)
    for p in taken:
        dst.uncache_page(p)
    src.check_reclaimed()
    dst.check_reclaimed()


def test_import_validates_before_touching_device(tr):
    """Every malformed-blob class raises ValueError BEFORE any device
    mutation, so untake_pages restores the allocator exactly — free-list
    ORDER included."""
    src, dst = _kv(tr), _kv(tr)
    pages = _committed_pages(src)
    meta, payload = src.export_pages(pages)

    free0 = list(dst._free)
    cases = [
        (dict(meta, n_pages=2), payload, "page-count mismatch"),
        (dict(meta, page_size=8), payload, "page-size mismatch"),
        (dict(meta, layers=meta["layers"][:1]), payload, "layer set"),
        (dict(meta, layers=[dict(meta["layers"][0], h_kv=99)]
              + [dict(l) for l in meta["layers"][1:]]),
         payload, "layer shape"),
        (meta, payload[:-1], "truncated payload"),
        (meta, payload + b"\x00", "oversized payload"),
    ]
    for bad_meta, bad_payload, why in cases:
        taken = dst.take_pages(3)
        with pytest.raises(ValueError):
            dst.import_pages(bad_meta, bad_payload, taken)
        dst.untake_pages(taken)
        assert dst._free == free0, \
            f"{why}: rollback did not restore the exact free list"
        assert dst.n_imported == 0
        dst.check()
    dst.check_reclaimed()


def test_export_rejects_free_pages(tr):
    """Exporting a page nobody holds would ship garbage — asserted."""
    kv = _kv(tr)
    with pytest.raises(AssertionError):
        kv.export_pages([int(kv._free[-1])])


# ---------------------------------------------------------------------------
# engine seam: import_prefix mounts, dedups, rolls back
# ---------------------------------------------------------------------------

def _engine(tr, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_context", 16)
    return ServingEngine(tr.executor, tr.params, **kw)


def test_import_prefix_mounts_and_next_admission_hits(tr):
    """The disagg tentpole in-process: engine A retires a request (pages
    donated), export_prefix serializes the committed prompt prefix,
    engine B import_prefix-mounts it, and B's admission of the SAME
    prompt is a prefix HIT whose tokens bit-match both the cold oracle
    and A's run."""
    rng = np.random.default_rng(3)
    prompt = rng.integers(2, 23, 9).astype(np.int32)
    a, b = _engine(tr), _engine(tr)

    ra = Request("a", prompt.copy(), max_new=4)
    out_a = a.run([ra])["a"]
    exp = a.export_prefix(prompt)
    assert exp is not None, "retire donated nothing exportable"
    toks, meta, payload = exp
    full = (prompt.size // a.kv.page_size) * a.kv.page_size
    assert toks.size == full and meta["n_pages"] == full // a.kv.page_size
    np.testing.assert_array_equal(toks, prompt[:full])

    hits0, saved0 = b.n_prefix_hits, b.prefill_tokens_saved
    added = b.import_prefix(toks, meta, payload)
    assert added == meta["n_pages"]
    assert b.n_kv_mounts == 1 and b.kv_pages_mounted == meta["n_pages"]
    b.kv.check()
    rb = Request("b", prompt.copy(), max_new=4)
    out_b = b.run([rb])["b"]
    assert b.n_prefix_hits - hits0 == 1, \
        "mounted run did not turn the admission into a prefix hit"
    assert b.prefill_tokens_saved - saved0 >= full - b.kv.page_size
    np.testing.assert_array_equal(out_a, out_b)
    np.testing.assert_array_equal(_oracle(tr, rb), out_b)


def test_import_prefix_dedups_already_mounted_runs(tr):
    """Importing a blob whose runs are already DEVICE-resident frees the
    duplicate pages immediately (no donor slot ever releases them) —
    node count and retention stay flat, nothing leaks."""
    rng = np.random.default_rng(4)
    prompt = rng.integers(2, 23, 9).astype(np.int32)
    a, b = _engine(tr), _engine(tr)
    a.run([Request("a", prompt.copy(), max_new=4)])
    toks, meta, payload = a.export_prefix(prompt)

    assert b.import_prefix(toks, meta, payload) == meta["n_pages"]
    nodes0, cached0 = b.prefix.n_nodes, b.kv.cached_page_count
    free0 = b.kv.free_page_count
    assert b.import_prefix(toks, meta, payload) == 0, \
        "re-import must add no nodes"
    assert b.prefix.n_nodes == nodes0
    assert b.kv.cached_page_count == cached0
    assert b.kv.free_page_count == free0, \
        "duplicate imported pages leaked"
    b.kv.check()


def test_import_prefix_rolls_back_on_dry_pool(tr):
    """Page starvation mid-import raises with the allocator exactly as
    before — and a partial-failure check() stays green."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(2, 23, 13).astype(np.int32)
    a = _engine(tr, max_context=16)
    a.run([Request("a", prompt[:9].copy(), max_new=4)])
    toks, meta, payload = a.export_prefix(prompt[:9])

    # 3 usable pages total: a 2-page import cannot fit after 2 are pinned
    b = _engine(tr, num_slots=1, num_pages=4, max_context=12,
                prefix_cache=True)
    assert b.kv.try_grow(0, 12)                     # pin every page
    with pytest.raises(ValueError, match="cannot cover"):
        b.import_prefix(toks, meta, payload)
    b.kv.check()
    b.kv.release(0)
    b.kv.check_reclaimed()

    # malformed blob after a successful take: exact rollback through
    # import_prefix's untake path
    c = _engine(tr)
    free0 = list(c.kv._free)
    with pytest.raises(ValueError):
        c.import_prefix(toks, meta, payload[:-1])
    assert c.kv._free == free0
    c.kv.check_reclaimed()


def test_import_prefix_requires_prefix_cache(tr):
    rng = np.random.default_rng(6)
    prompt = rng.integers(2, 23, 9).astype(np.int32)
    a = _engine(tr)
    a.run([Request("a", prompt.copy(), max_new=4)])
    toks, meta, payload = a.export_prefix(prompt)
    b = _engine(tr, prefix_cache=False)
    with pytest.raises(ValueError, match="prefix cache"):
        b.import_prefix(toks, meta, payload)
    assert b.export_prefix(prompt) is None
