"""Finite-difference gradient checks per layer family — the analog of the
reference's layer-grad harness (ref: paddle/gserver/tests/test_LayerGrad.cpp,
LayerGradUtil.h testLayerGrad): build a tiny net around one layer type,
compare autodiff grads against central differences.
"""

import jax

from paddle_tpu.utils import jax_compat
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.dsl import *  # noqa: F403
from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.context import TEST
from paddle_tpu.parameter.argument import Argument

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")



def fd_check(cfg, feed, seed=0, eps=1e-5, rtol=1e-3, atol=1e-6, n_coords=6):
    """Central-difference check in float64 (float32 FD noise would swamp the
    comparison — the reference uses double throughout its checkers)."""
    with jax_compat.enable_x64():
        ex = GraphExecutor(cfg.model_config)
        params = ex.init_params(jax.random.PRNGKey(seed))
        params = {k: jnp.asarray(v, jnp.float64) for k, v in params.items()}
        feed = jax.tree.map(
            lambda x: jnp.asarray(x, jnp.float64)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else jnp.asarray(x),
            feed)
        rng = jax.random.PRNGKey(seed + 1)

        def loss(p):
            return ex.loss(p, feed, mode=TEST, rng=rng)[0]

        analytic = jax.grad(loss)(params)
        rnd = np.random.default_rng(seed)
        for name, g in analytic.items():
            g = np.asarray(g)
            flat_p = np.asarray(params[name]).reshape(-1)
            idxs = rnd.choice(flat_p.size, size=min(n_coords, flat_p.size), replace=False)
            for i in idxs:
                pp = dict(params)
                v = flat_p.copy()
                v[i] += eps
                pp[name] = jnp.asarray(v.reshape(params[name].shape))
                up = float(loss(pp))
                v[i] -= 2 * eps
                pp[name] = jnp.asarray(v.reshape(params[name].shape))
                down = float(loss(pp))
                numeric = (up - down) / (2 * eps)
                a = g.reshape(-1)[i]
                assert abs(a - numeric) <= atol + rtol * max(abs(a), abs(numeric)), \
                    f"{name}[{i}]: analytic={a} numeric={numeric}"


def _seq_feed(rng, B=3, T=5, D=8, classes=3):
    lengths = np.array([5, 3, 4], np.int32)
    x = rng.standard_normal((B, T, D)).astype(np.float32)
    for i in range(B):
        x[i, lengths[i]:] = 0
    y = rng.integers(0, classes, B).astype(np.int32)
    return {
        "x": Argument(value=jnp.asarray(x), lengths=jnp.asarray(lengths)),
        "y": Argument(ids=jnp.asarray(y)),
    }


def test_fc_softmax_grad():
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=6)
        h = fc_layer(input=x, size=5, act=TanhActivation())
        out = fc_layer(input=h, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(0)
    feed = {"x": Argument(value=jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 3, 4), jnp.int32))}
    fd_check(cfg, feed)


def test_lstm_grad():
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=8)
        proj = fc_layer(input=x, size=16, act=LinearActivation(), bias_attr=False)
        h = lstmemory(input=proj)
        pooled = pooling_layer(input=h, pooling_type=MaxPooling())
        out = fc_layer(input=pooled, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))
    cfg = parse_config_callable(conf)
    feed = _seq_feed(np.random.default_rng(1))
    fd_check(cfg, feed)


def test_gru_grad():
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=8)
        proj = fc_layer(input=x, size=12, act=LinearActivation(), bias_attr=False)
        h = grumemory(input=proj, reverse=True)
        pooled = last_seq(input=h)
        out = fc_layer(input=pooled, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))
    cfg = parse_config_callable(conf)
    feed = _seq_feed(np.random.default_rng(2))
    fd_check(cfg, feed)


def test_conv_pool_grad():
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=1 * 8 * 8)
        c = img_conv_layer(input=x, filter_size=3, num_filters=4, num_channels=1,
                           padding=1, act=ReluActivation())
        p = img_pool_layer(input=c, pool_size=2, stride=2)
        out = fc_layer(input=p, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(3)
    feed = {"x": Argument(value=jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 3, 4), jnp.int32))}
    fd_check(cfg, feed)


def test_embedding_context_grad():
    def conf():
        settings(batch_size=3)
        words = data_layer(name="w", size=20)
        emb = embedding_layer(input=words, size=6)
        with mixed_layer(size=18) as ctxp:
            ctxp += context_projection(input=emb, context_len=3)
        h = fc_layer(input=ctxp, size=5, act=TanhActivation())
        pooled = pooling_layer(input=h, pooling_type=AvgPooling())
        out = fc_layer(input=pooled, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(4)
    lengths = np.array([5, 2, 4], np.int32)
    ids = rng.integers(0, 20, (3, 5)).astype(np.int32)
    feed = {"w": Argument(ids=jnp.asarray(ids), lengths=jnp.asarray(lengths)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 3, 3), jnp.int32))}
    fd_check(cfg, feed)


def test_crf_grad():
    def conf():
        settings(batch_size=3)
        x = data_layer(name="x", size=8)
        feats = fc_layer(input=x, size=4, act=LinearActivation())
        crf_layer(input=feats, label=data_layer(name="t", size=4), size=4)
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(5)
    B, T = 3, 5
    lengths = np.array([5, 3, 4], np.int32)
    x = rng.standard_normal((B, T, 8)).astype(np.float32)
    tags = rng.integers(0, 4, (B, T)).astype(np.int32)
    feed = {"x": Argument(value=jnp.asarray(x), lengths=jnp.asarray(lengths)),
            "t": Argument(ids=jnp.asarray(tags), lengths=jnp.asarray(lengths))}
    fd_check(cfg, feed)


def test_mdlstm_grad():
    H, W, D = 2, 3, 2

    def conf():
        settings(batch_size=2)
        x = data_layer(name="x", size=5 * D)
        h = mdlstm_layer(input=x, height=H, width=W, directions=(True, False))
        pooled = pooling_layer(input=h, pooling_type=MaxPooling())
        out = fc_layer(input=pooled, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(6)
    B, T = 2, H * W
    x = rng.standard_normal((B, T, 5 * D)).astype(np.float32)
    lengths = np.full((B,), T, np.int32)
    feed = {"x": Argument(value=jnp.asarray(x), lengths=jnp.asarray(lengths)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 3, B), jnp.int32))}
    fd_check(cfg, feed)


def test_subseq_forward_and_grad():
    def conf():
        settings(batch_size=3)
        x = data_layer(name="x", size=4)
        off = data_layer(name="off", size=1)
        sz = data_layer(name="sz", size=1)
        sub = sub_seq_layer(input=x, offsets=off, sizes=sz, name="subseq")
        pooled = pooling_layer(input=sub, pooling_type=AvgPooling())
        out = fc_layer(input=pooled, size=3, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=3))
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(7)
    B, T, D = 3, 6, 4
    lengths = np.array([6, 4, 5], np.int32)
    offsets = np.array([1, 0, 2], np.int32)
    sizes = np.array([3, 2, 3], np.int32)
    x = rng.standard_normal((B, T, D)).astype(np.float32)
    feed = {"x": Argument(value=jnp.asarray(x), lengths=jnp.asarray(lengths)),
            "off": Argument(ids=jnp.asarray(offsets)),
            "sz": Argument(ids=jnp.asarray(sizes)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 3, B), jnp.int32))}

    # forward semantics: row b, step t == x[b, offset+t] for t < size
    ex = GraphExecutor(cfg.model_config)
    params = ex.init_params(jax.random.PRNGKey(0))
    outs, _, _ = ex.forward(params, feed, mode=TEST, rng=jax.random.PRNGKey(1))
    sub = np.asarray(outs[[n for n in outs if n.startswith("subseq")][0]].value)
    for b in range(B):
        for t in range(sizes[b]):
            np.testing.assert_allclose(sub[b, t], x[b, offsets[b] + t], rtol=1e-6)
        assert np.all(sub[b, sizes[b]:] == 0)

    fd_check(cfg, feed)


def test_nce_grad():
    """NCE cost gradients (ref: test_LayerGrad.cpp testNceLayer analog):
    with a fixed rng the sampled negatives are deterministic, so central
    differences see the same loss surface as autodiff."""
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=6)
        h = fc_layer(input=x, size=8, act=TanhActivation())
        nce_layer(input=h, label=data_layer(name="y", size=12),
                  num_classes=12, num_neg_samples=5, bias_attr=True)
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(11)
    feed = {"x": Argument(value=jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 12, 4), jnp.int32))}
    fd_check(cfg, feed)


def test_hsigmoid_grad():
    """Hierarchical sigmoid cost gradients (ref: test_LayerGrad.cpp
    testHsigmoidLayer analog)."""
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=6)
        h = fc_layer(input=x, size=8, act=TanhActivation())
        hsigmoid(input=h, label=data_layer(name="y", size=10),
                 num_classes=10, bias_attr=True)
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(12)
    feed = {"x": Argument(value=jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 10, 4), jnp.int32))}
    fd_check(cfg, feed)


def test_selective_fc_grad():
    """Selective FC gradients, with and without a selection input
    (ref: test_LayerGrad.cpp testSelectiveFcLayer analog).  With selection,
    unselected classes must carry ~zero probability (the reference's
    selected-columns-only softmax)."""
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=6)
        sel = data_layer(name="sel", size=5)
        h = selective_fc_layer(input=x, select=sel, size=5,
                               act=SoftmaxActivation(), bias_attr=True)
        classification_cost(input=h, label=data_layer(name="y", size=5))
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(13)
    sel = np.zeros((4, 5), np.float32)
    for b in range(4):
        sel[b, rng.choice(5, 3, replace=False)] = 1.0
    # labels must be among the selected columns (unselected prob ~ 0)
    y = np.asarray([int(np.flatnonzero(sel[b])[0]) for b in range(4)], np.int32)
    feed = {"x": Argument(value=jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)),
            "sel": Argument(value=jnp.asarray(sel)),
            "y": Argument(ids=jnp.asarray(y))}

    ex = GraphExecutor(cfg.model_config)
    params = ex.init_params(jax.random.PRNGKey(0))
    outs, _, _ = ex.forward(params, feed, mode=TEST, rng=jax.random.PRNGKey(1))
    probs = np.asarray(
        outs[[n for n in outs if "selective" in n][0]].value, np.float64)
    assert np.abs(probs[sel == 0]).max() < 1e-6, "unselected prob must be ~0"
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-5)

    fd_check(cfg, feed)


def test_selective_fc_no_selection_grad():
    """Without a selection input selective_fc is a plain FC."""
    def conf():
        settings(batch_size=4)
        x = data_layer(name="x", size=6)
        h = selective_fc_layer(input=x, select=None, size=5,
                               act=SoftmaxActivation(), bias_attr=True)
        classification_cost(input=h, label=data_layer(name="y", size=5))
    cfg = parse_config_callable(conf)
    rng = np.random.default_rng(14)
    feed = {"x": Argument(value=jnp.asarray(rng.standard_normal((4, 6)), jnp.float32)),
            "y": Argument(ids=jnp.asarray(rng.integers(0, 5, 4), jnp.int32))}
    fd_check(cfg, feed)
