"""Fleet-router loopback tests (paddle_tpu/fleet/ over serving/server.py).

The acceptance contract (ISSUE 10): token streams through the router are
BIT-IDENTICAL to a direct single-replica connection (itself oracle-checked
against lm_generate) — including requests transparently retried after a
replica death; prefix-affinity placement steers shared-prefix traffic to
one replica; a rolling restart of a 2-replica fleet under load completes
with zero failed requests; and a saturated fleet answers an explicit
overload frame instead of queueing.  Replicas here are in-process
ServingServer instances — the same wire protocol `tools/serve.py` serves
from its own process (the slow churn soak exercises 3 of them).
"""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.fleet import FleetCtl, FleetRouter
from paddle_tpu.fleet.policy import AffinityIndex, PlacementPolicy
from paddle_tpu.fleet.replica import Replica
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.obs.flight import get_flight_recorder, load_bundle
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.client import (OverloadError, ServerError,
                                       ServingClient)
from paddle_tpu.serving.server import ServingServer
from paddle_tpu.trainer.trainer import Trainer

PAGE = 8


@pytest.fixture(scope="module")
def tiny_tr():
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _replica(tr, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_context", 64)
    max_queue = kw.pop("max_queue", 16)
    role = kw.pop("role", "both")
    eng = ServingEngine(tr.executor, tr.params, **kw)
    srv = ServingServer(eng, max_queue=max_queue, role=role)
    host, port = srv.start_background()
    return srv, host, port


def _fleet(tr, n, router_kw=None, **replica_kw):
    """n in-process replicas + a router joined to all of them."""
    reps = [_replica(tr, **replica_kw) for _ in range(n)]
    rkw = dict(poll_interval_s=0.1, heartbeat_misses=100)  # no accidental
    rkw.update(router_kw or {})                            # expiry on a
    rt = FleetRouter(port=0,                               # loaded CI box
                     replicas=[(h, p) for _, h, p in reps], **rkw)
    host, port = rt.start_background()
    return rt, host, port, [srv for srv, _, _ in reps]


def _stop_all(rt, srvs, drain=True):
    rt.stop_background(drain=drain)
    for srv in srvs:
        try:
            srv.stop_background(drain=drain)
        except RuntimeError:
            pass                       # a deliberately-killed replica


def _oracle(tr, prompt, max_new, **kw):
    import jax

    rng = jax.random.PRNGKey(kw.pop("seed")) if "seed" in kw else None
    toks, lens = lm_generate(tr.executor, tr.params,
                             np.asarray(prompt, np.int32)[None, :],
                             max_new=max_new, use_cache=True, rng=rng, **kw)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])].tolist()


def _loop_call(rt, fn):
    """Run fn on the router's loop thread (transport ops are not
    thread-safe from the test thread)."""
    done = threading.Event()
    rt._loop.call_soon_threadsafe(lambda: (fn(), done.set()))
    assert done.wait(10)


# ---------------------------------------------------------------------------
# policy unit coverage (no sockets)
# ---------------------------------------------------------------------------

def test_affinity_index_bounds_and_replica_drop():
    idx = AffinityIndex(window=4, capacity=3)
    assert idx.key_of([1, 2, 3]) is None          # shorter than one page
    k1, k2 = idx.key_of([1, 2, 3, 4, 9]), idx.key_of([5, 6, 7, 8])
    idx.put(k1, "r0")
    idx.put(k2, "r1")
    assert idx.get(k1) == "r0" and idx.get(k2) == "r1"
    idx.put(idx.key_of([9] * 4), "r0")
    idx.put(idx.key_of([8] * 4), "r0")            # capacity 3: k1 evicted
    assert len(idx) == 3 and idx.get(k1) is None
    assert idx.drop_replica("r0") == 2            # both r0 keys forgotten
    assert idx.get(k2) == "r1"


def test_policy_places_by_affinity_then_least_loaded():
    pol = PlacementPolicy("affinity", window=2)
    a, b = Replica("r0", "h", 1), Replica("r1", "h", 2)
    a.hello = {"max_inflight": 10}
    b.hello = {"max_inflight": 10}
    a.pending.add("g0")                           # a is busier
    first, why = pol.place([7, 7, 1], [a, b])
    assert first is b and why == "least_loaded"
    again, why = pol.place([7, 7, 2], [a, b])     # same first-page run
    assert again is b and why == "affinity"
    # the remembered replica gone -> fall back AND re-point the key
    moved, why = pol.place([7, 7, 3], [a])
    assert moved is a and why == "least_loaded"
    back, why = pol.place([7, 7, 4], [a, b])
    assert back is a and why == "affinity"


# ---------------------------------------------------------------------------
# the router over real TCP loopback
# ---------------------------------------------------------------------------

def test_fleet_token_exactness_through_router_vs_direct(tiny_tr):
    """ISSUE 10 acceptance: streamed tokens through the router are
    bit-identical to a direct single-replica connection, which itself
    matches lm_generate — greedy AND seeded-sampled requests."""
    rng = np.random.default_rng(0)
    rt, host, port, srvs = _fleet(tiny_tr, 2)
    try:
        prompts = [rng.integers(2, 31, int(rng.integers(3, 14))).tolist()
                   for _ in range(6)]
        jobs = [(p, 4 + i % 3) for i, p in enumerate(prompts)]
        with ServingClient(host, port) as c:
            ids = [c.submit(p, max_new=mn) for p, mn in jobs]
            sampled = c.submit(prompts[0], max_new=5, temperature=0.9,
                               top_k=4, seed=13)
            out = c.collect(ids + [sampled])
        # direct connection to ONE replica, same requests
        dsrv, dh, dp = _replica(tiny_tr)
        try:
            with ServingClient(dh, dp) as d:
                for rid, (p, mn) in zip(ids, jobs):
                    toks, reason = d.generate(p, max_new=mn)
                    assert out[rid]["tokens"] == toks == _oracle(
                        tiny_tr, p, mn)
                    assert out[rid]["reason"] == reason == "length"
                    # the per-token stream agrees with the final frame
                    assert out[rid]["stream"] == \
                        out[rid]["tokens"][len(p):]
                stoks, _ = d.generate(prompts[0], max_new=5,
                                      temperature=0.9, top_k=4, seed=13)
                assert out[sampled]["tokens"] == stoks == _oracle(
                    tiny_tr, prompts[0], 5, temperature=0.9, top_k=4,
                    seed=13)
        finally:
            dsrv.stop_background(drain=True)
        # every request went through the router exactly once
        with ServingClient(host, port) as c:
            rows = c.stats()["replicas"]
        assert sum(r["routed_total"] for r in rows) == 7
    finally:
        _stop_all(rt, srvs)


def test_prefix_affinity_steers_shared_prefixes_to_one_replica(tiny_tr):
    """Requests sharing a first-page token run land on the SAME replica
    (so PR 7's per-replica prefix cache can hit under fan-out), and the
    router's flight `route` events record the affinity decisions."""
    flight = get_flight_recorder()
    rng = np.random.default_rng(1)
    rt, host, port, srvs = _fleet(tiny_tr, 2)
    mark = flight.recorded
    try:
        prefixes = [rng.integers(2, 31, PAGE).tolist() for _ in range(2)]
        assert prefixes[0][:PAGE] != prefixes[1][:PAGE]
        with ServingClient(host, port) as c:
            ids = []
            for i in range(8):                    # interleave the groups
                p = prefixes[i % 2] + rng.integers(2, 31, 3).tolist()
                ids.append((c.submit(p, max_new=3), i % 2, p))
            out = c.collect([rid for rid, _, _ in ids])
        for rid, g, p in ids:
            assert out[rid]["tokens"] == _oracle(tiny_tr, p, 3)
        routes = [e for e in flight.snapshot()
                  if e["seq"] >= mark and e["kind"] == "route"]
        assert len(routes) == 8
        by_key: dict = {}
        for e in routes:
            by_key.setdefault(e["data"]["akey"], []).append(e["data"])
        assert len(by_key) == 2, "two prefix groups, two affinity keys"
        for key, evs in by_key.items():
            homes = {e["replica"] for e in evs}
            assert len(homes) == 1, \
                f"prefix group {key} split across {homes}"
            # first placement picks a home; every follower is an
            # affinity decision
            assert [e["policy"] for e in evs[1:]] == ["affinity"] * 3
        # the two groups went to DIFFERENT replicas (least-loaded spread)
        assert {evs[0]["replica"] for evs in by_key.values()} == \
            {"r0", "r1"}
        # and the steering paid: the replicas' prefix caches hit (each
        # replica has 2 slots, so per 4-request group at least the two
        # admissions after the first retirement map donated pages)
        hits = sum(srv.engine.n_prefix_hits for srv in srvs)
        assert hits >= 4, f"affinity routing should produce prefix hits " \
                          f"(got {hits})"
    finally:
        _stop_all(rt, srvs)


def test_replica_kill_midstream_retries_unstreamed_on_survivor(tiny_tr):
    """A replica dying mid-stream: requests whose client saw ZERO tokens
    retry transparently on the survivor (bit-exact); a partially-streamed
    request gets an honest error, never a spliced stream."""
    flight = get_flight_recorder()
    rng = np.random.default_rng(2)
    rt, host, port, srvs = _fleet(tiny_tr, 2)
    mark = flight.recorded
    try:
        prefix = rng.integers(2, 31, PAGE).tolist()
        p_a = prefix + [3, 4]
        p_b = prefix + [5, 6]
        p_c = prefix + [7, 8]
        with ServingClient(host, port) as c:
            ra = c.submit(p_a, max_new=30)        # will stream first
            msg = c.recv()
            while msg.get("type") != "token":     # ra provably streamed
                msg = c.recv()
            c._pending.append(msg)
            # two more requests whose client sees NOTHING before the kill:
            # rb decodes in the second slot, rc queues behind (2 slots)
            rb = c.submit(p_b, max_new=25, stream=False)
            rc = c.submit(p_c, max_new=4, stream=False)
            # all three co-located by affinity (shared first-page run)
            deadline = time.time() + 30
            victim = None
            while victim is None and time.time() < deadline:
                victim = next((r for r in rt.table
                               if len(r.pending) >= 3), None)
                time.sleep(0.005)
            assert victim is not None, \
                "affinity should have co-located all three requests"
            survivor = next(r for r in rt.table if r is not victim)
            _loop_call(rt, victim.backend.abort)  # the replica "dies"
            out = c.collect([rb, rc])
            assert out[rb]["tokens"] == _oracle(tiny_tr, p_b, 25), \
                "retried request must stay bit-exact"
            assert out[rc]["tokens"] == _oracle(tiny_tr, p_c, 4)
            with pytest.raises(ServerError, match="already streamed"):
                c.collect([ra])
            s = c.stats()
            assert s["replicas_registered"] == 1
            assert s["replicas"][0]["replica"] == survivor.rid
            assert s["retries"] >= 2.0
        kinds = [e["kind"] for e in flight.snapshot() if e["seq"] >= mark]
        assert "replica_leave" in kinds and "retry" in kinds
    finally:
        _stop_all(rt, srvs)


def test_nonstreaming_request_retries_even_after_replica_made_tokens(
        tiny_tr):
    """A stream=False client has seen ZERO tokens no matter how far its
    replica got — the retry predicate is tokens DELIVERED, not tokens
    produced, so a replica death mid-decode must still retry the request
    transparently (bit-exact: the verbatim resend replays the same
    deterministic decode)."""
    rng = np.random.default_rng(7)
    rt, host, port, srvs = _fleet(tiny_tr, 2)
    try:
        p = rng.integers(2, 31, PAGE + 2).tolist()
        with ServingClient(host, port) as c:
            rid = c.submit(p, max_new=25, stream=False)
            # wait until the VICTIM's engine has provably decoded tokens
            deadline = time.time() + 30
            victim = None
            while victim is None and time.time() < deadline:
                victim = next(
                    (r for r in rt.table if r.pending
                     and next(s for s in srvs if s.port == r.port)
                     .engine.tokens_generated >= 3), None)
                time.sleep(0.005)
            assert victim is not None, "request never started decoding"
            _loop_call(rt, victim.backend.abort)
            out = c.collect([rid])
            assert out[rid]["tokens"] == _oracle(tiny_tr, p, 25), \
                "non-streaming request must retry bit-exact"
            assert c.stats()["retries"] >= 1.0
    finally:
        _stop_all(rt, srvs)


def test_malformed_prompt_answers_error_without_leaking_a_route(tiny_tr):
    """Garbage prompts (non-list, or non-numeric tokens) must answer an
    error frame BEFORE touching routing state — in least_loaded/random
    modes placement never reads the prompt, so a late failure used to
    strand a phantom in-flight request that inflated load and wedged
    drain forever."""
    rt, host, port, srvs = _fleet(tiny_tr, 2,
                                  router_kw=dict(policy="least_loaded"))
    try:
        with ServingClient(host, port) as c:
            for bad in ("zzz", 5, [3, "x", 4], [True, 3]):
                c.send({"type": "generate", "id": f"b{bad!r}"[:12],
                        "prompt": bad, "max_new": 3})
                msg = c.recv()
                assert msg["type"] == "error" and "prompt" in msg["error"]
            s = c.stats()
            assert s["inflight"] == 0, "a malformed prompt leaked a route"
            assert all(r["pending"] == 0 for r in s["replicas"])
            # the connection and the fleet still serve real work
            toks, reason = c.generate([3, 4, 5], max_new=3)
            assert reason == "length" and len(toks) == 6
    finally:
        _stop_all(rt, srvs)           # drain: wedges if a route leaked


def test_rolling_restart_under_load_zero_failed_requests(tiny_tr):
    """ISSUE 10 acceptance: drain-aware rolling restart of a 2-replica
    fleet while clients keep submitting — every request completes with
    reason=length and oracle-exact tokens; nothing fails, nothing drops."""
    rng = np.random.default_rng(3)
    rt, host, port, srvs = _fleet(tiny_tr, 2)
    live = {s: True for s in srvs}
    results: list = []
    errors: list = []
    stop_load = threading.Event()

    def load_worker(wid):
        try:
            with ServingClient(host, port) as c:
                w_rng = np.random.default_rng(100 + wid)
                for i in range(10):
                    p = w_rng.integers(2, 31, int(w_rng.integers(3, 10))
                                       ).tolist()
                    toks, reason = c.generate(p, max_new=4)
                    results.append((p, toks, reason))
                    if stop_load.is_set():
                        break
        except Exception as e:                     # noqa: BLE001
            errors.append(e)

    workers = [threading.Thread(target=load_worker, args=(w,))
               for w in range(2)]
    try:
        for t in workers:
            t.start()
        time.sleep(0.2)                           # load provably flowing

        def restart(row):
            host_r, port_r = row["addr"].rsplit(":", 1)
            old = next(s for s in srvs
                       if live[s] and s.port == int(port_r))
            old.stop_background(drain=True)       # the SIGTERM-drain path
            live[old] = False
            new_srv, nh, np_ = _replica(tiny_tr)
            srvs.append(new_srv)
            live[new_srv] = True
            return nh, np_

        with FleetCtl(host, port) as ctl:
            new_ids = ctl.rolling_restart(restart, drain_timeout_s=120,
                                          log=lambda s: None)
        assert len(new_ids) == 2
        for t in workers:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in workers), "load wedged"
        assert errors == [], f"rolling restart failed requests: {errors}"
        assert len(results) == 20
        for p, toks, reason in results:
            assert reason == "length"
            assert toks == _oracle(tiny_tr, p, 4)
        with ServingClient(host, port) as c:
            s = c.stats()
        assert s["replicas_healthy"] == 2
        assert {r["replica"] for r in s["replicas"]} == set(new_ids)
    finally:
        stop_load.set()
        _stop_all(rt, [s for s in srvs if live.get(s)])


def test_fleet_overload_sheds_when_every_replica_saturated(tiny_tr):
    """The fleet-level backpressure contract: every healthy replica at
    its admission cap -> an explicit overload frame (reason
    fleet_saturated), never unbounded queueing."""
    flight = get_flight_recorder()
    rt, host, port, srvs = _fleet(tiny_tr, 2, num_slots=1, max_queue=0)
    mark = flight.recorded
    try:
        with ServingClient(host, port) as c:
            # each replica's cap is 1 (one slot, no queue): two long
            # requests saturate the fleet; frames on one connection are
            # processed in order, so placement is deterministic
            r0 = c.submit([3, 4, 5], max_new=25)
            r1 = c.submit([4, 5, 6], max_new=25)
            over = c.submit([5, 6, 7], max_new=4)
            with pytest.raises(OverloadError) as ei:
                c.collect([over])
            assert ei.value.info["reason"] == "fleet_saturated"
            assert ei.value.info["max_inflight"] == 2
            # shedding cost nothing admitted: the two placed requests
            # finish exactly
            out = c.collect([r0, r1])
            assert out[r0]["tokens"] == _oracle(tiny_tr, [3, 4, 5], 25)
            assert out[r1]["tokens"] == _oracle(tiny_tr, [4, 5, 6], 25)
            text = c.metrics()
            vals = {ln.rsplit(" ", 1)[0]: float(ln.rsplit(" ", 1)[1])
                    for ln in text.splitlines() if not ln.startswith("#")}
            assert vals["fleet_sheds_total"] >= 1.0
            assert vals["fleet_requests_accepted_total"] == 2.0
        kinds = [e["kind"] for e in flight.snapshot() if e["seq"] >= mark]
        assert "shed" in kinds
    finally:
        _stop_all(rt, srvs)


def test_replica_overload_race_answers_overload_not_error(tiny_tr):
    """A replica refusing admission (filled by a DIRECT client between
    the router's poll and the frame's arrival) with no alternative
    capacity must surface as the retryable `overload` contract — a
    terminal error frame would turn transient saturation into a hard
    failure (and skip the shed accounting)."""
    rt, host, port, srvs = _fleet(
        tiny_tr, 1, router_kw=dict(poll_interval_s=60.0),  # stale view
        num_slots=1, max_queue=0)                          # replica cap 1
    try:
        rep_srv = srvs[0]
        with ServingClient(rep_srv.host, rep_srv.port) as direct:
            rid = direct.submit([3, 4, 5], max_new=25)     # fills the cap
            # same-connection barrier: admission provably happened
            assert direct.stats(stale_ok=True)["inflight"] == 1
            with ServingClient(host, port) as c:
                over = c.submit([4, 5, 6], max_new=3)
                with pytest.raises(OverloadError) as ei:
                    c.collect([over])
                assert ei.value.info["reason"] == "fleet_saturated"
                assert c.stats()["sheds"] >= 1.0
            direct.cancel(rid)
            direct.collect([rid])
    finally:
        _stop_all(rt, srvs)


def test_fleet_stats_metrics_dump_frames_and_unhealthy_bundle(
        tiny_tr, tmp_path):
    """The ops surface: fleet-shaped stats, CATALOG-lockstep metrics, an
    on-demand postmortem bundle — and the automatic bundle frozen the
    moment the LAST healthy replica is gone."""
    rt, host, port, srvs = _fleet(
        tiny_tr, 2, router_kw=dict(postmortem_dir=str(tmp_path)))
    try:
        with ServingClient(host, port) as c:
            h = c.hello()
            assert h["role"] == "router" and h["proto"] == 1
            assert "fleet" in h["capabilities"]
            toks, reason = c.generate([3, 4, 5, 6], max_new=3)
            assert reason == "length" and len(toks) == 7
            s = c.stats()
            assert s["fleet"] is True and s["replicas_healthy"] == 2
            assert s["affinity_window"] == PAGE
            assert len(s["replicas"]) == 2
            text = c.metrics()
            vals = {}
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    key, v = line.rsplit(" ", 1)
                    vals[key] = float(v)
            assert vals["fleet_replicas_healthy"] == 2.0
            assert vals["fleet_requests_accepted_total"] == 1.0
            from paddle_tpu.obs import CATALOG
            from paddle_tpu.obs.metrics import MetricsRegistry
            for key in vals:
                base = key.split("{", 1)[0]
                fam = MetricsRegistry._family_of(base, "histogram")
                assert base in CATALOG or fam in CATALOG, \
                    f"{base} rendered but not in CATALOG"
            d = c.dump()
            b = load_bundle(d["path"])
            assert b["meta"]["reason"] == "rpc"
            assert b["engine"]["router"] is True
            assert len(b["engine"]["replicas"]) == 2
            assert b["config"]["policy"] == "affinity"
            # now the whole fleet dies: ONE fleet_unhealthy bundle
            for r in list(rt.table):
                _loop_call(rt, r.backend.abort)
            deadline = time.time() + 20
            while time.time() < deadline:
                if any(load_bundle(str(p)).get("meta", {}).get("reason")
                       == "fleet_unhealthy"
                       for p in tmp_path.iterdir()
                       if p.is_dir() and not str(p).endswith(".tmp")):
                    break
                time.sleep(0.05)
            bundles = [load_bundle(str(p)) for p in tmp_path.iterdir()
                       if p.is_dir() and not str(p).endswith(".tmp")]
            unhealthy = [b for b in bundles
                         if b["meta"]["reason"] == "fleet_unhealthy"]
            assert len(unhealthy) == 1, \
                "total-fleet-unhealthy must freeze exactly one bundle"
            assert "no healthy replicas" in unhealthy[0]["meta"]["error"]
            # with nothing registered, generate sheds with no_replicas
            with pytest.raises(OverloadError) as ei:
                c.generate([3, 4], max_new=2)
            assert ei.value.info["reason"] == "no_replicas"
    finally:
        _stop_all(rt, srvs)


def test_router_relay_itl_burst_honest_through_multi_step_replicas(
        tiny_tr):
    """ISSUE 16 satellite: replicas running decode_steps=3 relay token
    frames in bursts; the router divides the inter-burst arrival gap by
    the frame's `burst` stamp so relay ITL counts every token (no
    k-times undercount, no 0-gap flood), streams stay bit-exact, and the
    percentiles surface in the stats frame + CATALOG metrics."""
    rng = np.random.default_rng(3)
    rt, host, port, srvs = _fleet(tiny_tr, 2, decode_steps=3)
    try:
        prompts = [rng.integers(2, 31, int(rng.integers(3, 10))).tolist()
                   for _ in range(4)]
        with ServingClient(host, port) as c:
            ids = [c.submit(p, max_new=7) for p in prompts]
            out = c.collect(ids)
            for rid, p in zip(ids, prompts):
                assert out[rid]["tokens"] == _oracle(tiny_tr, p, 7)
                assert out[rid]["stream"] == out[rid]["tokens"][len(p):]
            # the replicas really did scan (multi-step actually engaged)
            assert sum(srv.engine.n_scan_flushes for srv in srvs) > 0
            s = c.stats()
            itl = s["relay_itl_ms"]
            assert set(itl) == {"p50", "p90", "p99"}
            assert 0.0 <= itl["p50"] <= itl["p99"]
            text = c.metrics()
            vals = {}
            for line in text.splitlines():
                if line and not line.startswith("#"):
                    key, v = line.rsplit(" ", 1)
                    vals[key] = float(v)
        # every relayed token past each request's first charged exactly
        # one relay_token_latency sample: 4 requests x (7 - 1) tokens
        assert vals['fleet_relay_latency_count'
                    '{stat="relay_token_latency"}'] == 24.0
        assert vals['fleet_relay_latency_seconds'
                    '{quantile="p99",stat="relay_token_latency"}'] >= 0.0
    finally:
        _stop_all(rt, srvs)


def test_router_rejects_non_replica_peer_on_join(tiny_tr):
    """Joining an address that is not a serving replica (here: the
    router ITSELF — role 'router') must fail the hello classification,
    not route traffic into a loop."""
    rt, host, port, srvs = _fleet(tiny_tr, 1)
    try:
        with FleetCtl(host, port) as ctl:
            with pytest.raises(ServerError,
                               match="not a serving replica"):
                ctl.join(host, port)              # the router's own addr
            assert len(ctl.list()) == 1           # table unchanged
    finally:
        _stop_all(rt, srvs)


# ---------------------------------------------------------------------------
# ISSUE 19: disaggregated prefill/decode through the router
# ---------------------------------------------------------------------------

def _disagg_fleet(tr, router_kw=None, prefill_kw=None, decode_kw=None):
    """1 prefill-role + 1 decode-role replica behind a router — the
    minimal disaggregated fleet.  Long prompts place on the prefill
    replica, kv_push their committed pages to the decode replica, and
    the generate frame follows the pages."""
    sp, hp, pp = _replica(tr, role="prefill", **(prefill_kw or {}))
    sd, hd, pd = _replica(tr, role="decode", **(decode_kw or {}))
    rkw = dict(poll_interval_s=0.1, heartbeat_misses=100)
    rkw.update(router_kw or {})
    rt = FleetRouter(port=0, replicas=[(hp, pp), (hd, pd)], **rkw)
    host, port = rt.start_background()
    return rt, host, port, [sp, sd]


def test_disagg_cross_replica_exactness_and_role_surfaces(tiny_tr):
    """ISSUE 19 acceptance: a request prefilled on replica A and decoded
    on replica B streams token-for-token what a single replica (itself
    oracle-checked) produces — greedy AND seeded-sampled — while the
    router's kv_xfer counters, the placement ledger, and ctl's role
    column all tell the disaggregation story.  Short prompts bypass the
    split and stay exact."""
    rng = np.random.default_rng(5)
    rt, host, port, srvs = _disagg_fleet(tiny_tr)
    sp, sd = srvs
    try:
        prompts = [rng.integers(2, 31, int(rng.integers(2 * PAGE + 1,
                                                        3 * PAGE))).tolist()
                   for _ in range(3)]
        with ServingClient(host, port) as c:
            ids = [c.submit(p, max_new=5) for p in prompts]
            sampled = c.submit(prompts[0], max_new=5, temperature=0.9,
                               top_k=4, seed=13)
            out = c.collect(ids + [sampled])
        dsrv, dh, dp = _replica(tiny_tr)          # single-replica control
        try:
            with ServingClient(dh, dp) as d:
                for rid, p in zip(ids, prompts):
                    toks, reason = d.generate(p, max_new=5)
                    assert out[rid]["tokens"] == toks == _oracle(
                        tiny_tr, p, 5), "disagg decode diverged"
                    assert out[rid]["reason"] == reason == "length"
                    assert out[rid]["stream"] == \
                        out[rid]["tokens"][len(p):]
                stoks, _ = d.generate(prompts[0], max_new=5,
                                      temperature=0.9, top_k=4, seed=13)
                assert out[sampled]["tokens"] == stoks == _oracle(
                    tiny_tr, prompts[0], 5, temperature=0.9, top_k=4,
                    seed=13), "seeded sampling must survive the split"
        finally:
            dsrv.stop_background(drain=True)
        # every long prompt actually split: prefill leg + decode leg
        with ServingClient(host, port) as c:
            s = c.stats()
            assert s["kv_pushes"] == 4 and s["kv_push_failures"] == 0
            assert s["kv_fallbacks"] == 0
            assert s["kv_pages_shipped"] == 8     # 4 x two committed pages
            assert s["placements"]["disagg"] == 8.0
            roles = {r["replica"]: r["role"] for r in s["replicas"]}
            assert sorted(roles.values()) == ["decode", "prefill"]
            # the pages really moved: shipped == received, and the decode
            # side's admissions were prefix hits on mounted runs
            by_role = {r["role"]: r for r in s["replicas"]}
            assert by_role["prefill"]["kv_pushes"] == 4
            assert by_role["prefill"]["kv_pages_shipped"] == 8
            assert by_role["decode"]["kv_pages_received"] == 8
            # the ctl's fleet view carries the same columns
            with FleetCtl(host, port) as ctl:
                rows = ctl.list()
            assert sorted(r["role"] for r in rows) == ["decode", "prefill"]
        assert sd.engine.n_kv_mounts >= 3 and sd.engine.n_prefix_hits >= 4
        # a prompt under the floor (one KV page) never splits
        short = [3, 4, 5, 6, 7]
        with ServingClient(host, port) as c:
            toks, reason = c.generate(short, max_new=4)
            assert reason == "length"
            assert toks == _oracle(tiny_tr, short, 4)
            assert c.stats()["kv_pushes"] == 4    # unchanged
    finally:
        _stop_all(rt, srvs)


def test_disagg_cow_divergence_on_shipped_pages_stays_exact(tiny_tr):
    """Two requests sharing the shipped two-page run then DIVERGING
    afterward: both reference the same mounted pages on the decode
    replica concurrently, each appends into its own pages past the
    shared run, and both stay bit-exact (a write-through into a shared
    mounted page would corrupt the sibling)."""
    rng = np.random.default_rng(6)
    rt, host, port, srvs = _disagg_fleet(tiny_tr)
    sp, sd = srvs
    try:
        shared = rng.integers(2, 31, 2 * PAGE).tolist()
        p_a = shared + [9, 3, 11]
        p_b = shared + [4, 17]
        with ServingClient(host, port) as c:
            ra = c.submit(p_a, max_new=6)
            rb = c.submit(p_b, max_new=6)
            out = c.collect([ra, rb])
        assert out[ra]["tokens"] == _oracle(tiny_tr, p_a, 6)
        assert out[rb]["tokens"] == _oracle(tiny_tr, p_b, 6), \
            "divergent sibling corrupted by a shared shipped page?"
        assert sd.engine.n_kv_mounts >= 1
        assert sd.engine.n_prefix_hits >= 2      # both legs hit the run
        for srv in srvs:
            srv.engine.kv.check_reclaimed()
    finally:
        _stop_all(rt, srvs)


def test_disagg_decode_preemption_replay_stays_exact(tiny_tr):
    """An OVERCOMMITTED decode-side pool under disaggregated load:
    mounted pages are shared by concurrent slots, growth wedges the
    pool, victims are preempted and replayed — and every completed
    request still matches its oracle exactly."""
    rng = np.random.default_rng(8)
    rt, host, port, srvs = _disagg_fleet(tiny_tr,
                                         decode_kw=dict(num_pages=5))
    sp, sd = srvs
    try:
        shared = rng.integers(2, 31, 2 * PAGE).tolist()
        jobs = []
        with ServingClient(host, port) as c:
            for i in range(4):
                # 2 shared pages + 1 distinct token, then 14 new tokens:
                # two concurrent slots want 6 of the 5 real pages
                p = shared + [2 + i]
                jobs.append((c.submit(p, max_new=14, stream=False), p))
            out = c.collect([rid for rid, _ in jobs])
        for rid, p in jobs:
            assert out[rid]["tokens"] == _oracle(tiny_tr, p, 14), \
                "preemption/replay changed a disagg request's tokens"
            assert out[rid]["reason"] == "length"
        assert sd.engine.n_preemptions > 0, \
            "decode pool was never overcommitted"
        sd.engine.kv.check_reclaimed()
    finally:
        _stop_all(rt, srvs)


def test_disagg_prefill_tier_death_degrades_to_both_mode(tiny_tr):
    """Killing the prefill tier mid-workload: requests in their prefill
    phase (never streamed, by construction) retry transparently, the
    router stops planning splits the moment the tier is gone, and the
    workload completes with ZERO failed requests — all oracle-exact on
    the surviving decode replica."""
    rt, host, port, srvs = _disagg_fleet(tiny_tr)
    sp, sd = srvs
    results: list = []
    errors: list = []

    def load_worker(wid):
        try:
            with ServingClient(host, port) as c:
                w_rng = np.random.default_rng(300 + wid)
                for _ in range(8):
                    p = w_rng.integers(
                        2, 31, 2 * PAGE + int(w_rng.integers(1, 6))
                    ).tolist()
                    rid = c.submit(p, max_new=4, stream=False)
                    res = c.collect([rid])[rid]
                    results.append((p, res["tokens"], res["reason"]))
        except Exception as e:                     # noqa: BLE001
            errors.append(e)

    workers = [threading.Thread(target=load_worker, args=(w,))
               for w in range(2)]
    try:
        for t in workers:
            t.start()
        time.sleep(0.3)                           # splits provably flowing
        victim = next(r for r in rt.table if r.role == "prefill")
        _loop_call(rt, victim.backend.abort)      # the tier "dies"
        sp.stop_background(drain=False)
        for t in workers:
            t.join(timeout=300)
        assert not any(t.is_alive() for t in workers), "load wedged"
        assert errors == [], \
            f"prefill-tier death failed requests: {errors}"
        assert len(results) == 16
        for p, toks, reason in results:
            assert reason == "length"
            assert toks == _oracle(tiny_tr, p, 4)
        with ServingClient(host, port) as c:
            s = c.stats()
        assert s["replicas_registered"] == 1
        assert s["replicas"][0]["role"] == "decode"
        assert s["kv_pushes"] >= 1, "no split ever ran before the kill"
    finally:
        _stop_all(rt, [sd])


@pytest.mark.slow
def test_soak_3replica_churn_stays_exact(tiny_tr):
    """3-replica churn soak: continuous mixed-prefix load while one
    replica is abruptly killed and another is drain-restarted through
    ctl; every completed request stays oracle-exact, the only tolerated
    failures are mid-stream deaths, and the fleet ends healthy at 3."""
    rng = np.random.default_rng(4)
    rt, host, port, srvs = _fleet(tiny_tr, 3)
    live = {s: True for s in srvs}
    prefixes = [rng.integers(2, 31, PAGE).tolist() for _ in range(3)]
    results: list = []
    failures: list = []
    done_load = threading.Event()

    def load_worker(wid):
        w_rng = np.random.default_rng(200 + wid)
        with ServingClient(host, port) as c:
            for i in range(12):
                p = prefixes[int(w_rng.integers(0, 3))] + \
                    w_rng.integers(2, 31, int(w_rng.integers(2, 6))
                                   ).tolist()
                try:
                    toks, reason = c.generate(p, max_new=4)
                    results.append((p, toks, reason))
                except (ServerError, OverloadError) as e:
                    failures.append(str(e))
                except ConnectionError as e:
                    failures.append(f"conn: {e}")
                    return

    workers = [threading.Thread(target=load_worker, args=(w,))
               for w in range(3)]
    try:
        for t in workers:
            t.start()
        time.sleep(0.3)
        # churn 1: abrupt kill of whichever replica is busiest
        victim = max(rt.table, key=lambda r: len(r.pending))
        _loop_call(rt, victim.backend.abort)
        vic_srv = next(s for s in srvs if s.port == victim.port)
        vic_srv.stop_background(drain=False)
        live[vic_srv] = False
        with FleetCtl(host, port) as ctl:
            # churn 2: drain-restart one survivor through the runbook
            rid = ctl.list()[0]["replica"]
            ctl.drain(rid)
            ctl.wait_drained(rid, timeout_s=120)
            row = ctl.status(rid)
            ctl.leave(rid)
            old_port = int(row["addr"].rsplit(":", 1)[1])
            old = next(s for s in srvs if live[s] and s.port == old_port)
            old.stop_background(drain=True)
            live[old] = False
            for _ in range(2):                     # restore to 3 replicas
                new_srv, nh, np_ = _replica(tiny_tr)
                srvs.append(new_srv)
                live[new_srv] = True
                ctl.join(nh, np_)
            for t in workers:
                t.join(timeout=300)
            assert not any(t.is_alive() for t in workers), "load wedged"
            rows = ctl.list()
        for p, toks, reason in results:
            assert reason == "length" and toks == _oracle(tiny_tr, p, 4), \
                "a churn survivor diverged from its oracle"
        # only mid-stream deaths may fail; everything else completed
        assert len(results) + len(failures) == 36
        for f in failures:
            assert "already streamed" in f or "no healthy replica" in f \
                or "retry limit" in f or "overloaded" in f, \
                f"unexpected failure: {f}"
        assert len(results) >= 30, f"too much lost to churn: {failures}"
        assert sum(1 for r in rows if r["state"] == "healthy") == 3
    finally:
        done_load.set()
        _stop_all(rt, [s for s in srvs if live.get(s)])
