"""Health-plane tests (ISSUE 20): the metric time-series ring, SLO
burn-rate alerting, the stale-ok `history` RPC, and the router's
fleet-aggregate view.

The acceptance contract: an induced p99 blowup on a real TCP serving
front end trips `slo_fire`, flips the labelled `obs_slo_firing` gauge,
freezes EXACTLY one proactive postmortem bundle per episode (with the
offending series in history.json), recovery emits `slo_clear` and
re-arms, and a second episode dumps again; the `history` RPC answers
against a deliberately wedged pump; a router's aggregate history labels
each replica's series `replica="rN"`.

Determinism: unit tests drive `MetricHistory.sample(now=..., samples=...)`
with a synthetic clock; the e2e test stops the background sampler and
ticks `sample()`/`evaluate()` by hand at synthetic times far past any
real-time sample, so wall-clock jitter can neither fire nor mask an SLO.
"""

import threading
import time

import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.fleet import FleetRouter
from paddle_tpu.obs.flight import load_bundle
from paddle_tpu.obs.metrics import MetricsRegistry
from paddle_tpu.obs.slo import SloEvaluator, SloSpec, default_serving_slos
from paddle_tpu.obs.timeseries import (MetricHistory, history_collector,
                                       merge_history, relabel_series_key)
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.client import ServingClient
from paddle_tpu.serving.server import ServingServer
from paddle_tpu.trainer.trainer import Trainer

#: synthetic clock origin: far past any real wall-clock sample a server
#: background thread could have slipped in before tests stopped it, so
#: trailing-window reads never mix real and synthetic points
T = 2_000_000_000.0


@pytest.fixture(scope="module")
def tiny_tr():
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _engine(tr, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_context", 64)
    return ServingEngine(tr.executor, tr.params, **kw)


def _bundles(d):
    import glob
    import os

    return sorted(p for p in glob.glob(os.path.join(str(d), "postmortem-*"))
                  if not p.endswith(".tmp"))


# ---------------------------------------------------------------------------
# MetricHistory: the downsampled ring
# ---------------------------------------------------------------------------

def test_gauge_ring_downsamples_last_wins_and_bounds_retention():
    h = MetricHistory(resolution_s=5.0, retention_s=20.0)   # capacity 4
    assert h.capacity == 4
    # two samples in ONE 5s window collapse to one point, last value wins
    h.sample(now=T, samples=[("g", "gauge", None, 1.0)])
    h.sample(now=T + 1, samples=[("g", "gauge", None, 2.0)])
    assert h.points("g") == [(T, 2.0)]
    # six more windows: the ring keeps only the newest 4
    for k in range(1, 7):
        h.sample(now=T + 5 * k, samples=[("g", "gauge", None, float(k))])
    pts = h.points("g")
    assert [v for _, v in pts] == [3.0, 4.0, 5.0, 6.0]
    # window starts align to the resolution grid
    assert all(t % 5.0 == 0.0 for t, _ in pts)
    # last_s trims to the trailing window (lo boundary inclusive)
    assert [v for _, v in h.points("g", last_s=10.0, now=T + 30)] \
        == [4.0, 5.0, 6.0]


def test_counter_ring_stores_clamped_deltas():
    h = MetricHistory(resolution_s=1.0, retention_s=10.0)
    for k, raw in enumerate([5.0, 12.0, 3.0, 10.0]):
        h.sample(now=T + k, samples=[("c_total", "counter", None, raw)])
    # first reading IS the delta since process start; the 12->3 restart
    # clamps to 0 instead of going negative
    assert [v for _, v in h.points("c_total")] == [5.0, 7.0, 0.0, 7.0]
    assert h.kind("c_total") == "counter"
    # two samples landing in one window accumulate their deltas
    h.sample(now=T + 4.1, samples=[("c_total", "counter", None, 11.0)])
    h.sample(now=T + 4.9, samples=[("c_total", "counter", None, 14.0)])
    assert h.points("c_total")[-1] == (T + 4.0, 4.0)


def test_histogram_sum_count_ride_as_counters_buckets_skipped():
    h = MetricHistory(resolution_s=1.0, retention_s=10.0)
    h.sample(now=T, samples=[
        ("lat_sum", "histogram", None, 4.0),
        ("lat_count", "histogram", None, 2.0),
        ("lat_bucket", "histogram", {"le": "1"}, 2.0),   # cardinality guard
    ])
    assert h.points("lat_sum") == [(T, 4.0)]
    assert h.kind("lat_count") == "counter"
    assert h.points('lat_bucket{le="1"}') == []
    assert h.series_count() == 2


def test_series_cap_degrades_to_accounting_not_memory():
    h = MetricHistory(resolution_s=1.0, retention_s=5.0, max_series=2)
    h.sample(now=T, samples=[("a", "gauge", None, 1.0),
                             ("b", "gauge", None, 1.0),
                             ("c", "gauge", None, 1.0)])
    assert h.series_count() == 2 and h.dropped_series == 1
    # the ring's own collector surfaces the drop
    got = {name: v for name, _k, _l, v in history_collector(h)()}
    assert got["obs_history_dropped_series_total"] == 1.0
    assert got["obs_history_series"] == 2.0
    assert got["obs_history_samples_total"] == 1.0


def test_snapshot_filters_by_prefix_and_window():
    h = MetricHistory(resolution_s=1.0, retention_s=30.0)
    for k in range(5):
        h.sample(now=T + k, samples=[
            ("serving_num_slots", "gauge", None, 2.0),
            ("fleet_inflight", "gauge", None, float(k))])
    snap = h.snapshot(names=["serving_"], now=T + 4)
    assert set(snap["series"]) == {"serving_num_slots"}
    assert snap["samples_taken"] == 5
    assert snap["first_sample_unix"] == T
    assert snap["last_sample_unix"] == T + 4
    snap = h.snapshot(last_s=2.0, now=T + 4)
    assert [v for _, v in snap["series"]["fleet_inflight"]["points"]] \
        == [2.0, 3.0, 4.0]


def test_relabel_and_merge_tag_replica_series():
    assert relabel_series_key('a{x="1"}', {"replica": "r0"}) \
        == 'a{replica="r0",x="1"}'
    assert relabel_series_key("plain", {"replica": "r1"}) \
        == 'plain{replica="r1"}'
    local = {"resolution_s": 5.0, "samples_taken": 3,
             "series": {"fleet_inflight": {"kind": "gauge",
                                           "points": [[T, 1.0]]}}}
    rep = {"series": {"serving_num_slots": {"kind": "gauge",
                                            "points": [[T, 2.0]]}}}
    out = merge_history([(None, local), ("r0", rep)])
    # the None part (the router's own) passes through unlabeled and
    # supplies the ring accounting; replica series get the label
    assert out["resolution_s"] == 5.0 and out["replicas"] == ["r0"]
    assert "fleet_inflight" in out["series"]
    assert out["series"]['serving_num_slots{replica="r0"}']["points"] \
        == [[T, 2.0]]


# ---------------------------------------------------------------------------
# SloEvaluator: multi-window burn rate, warm-up gate, episode re-arm
# ---------------------------------------------------------------------------

def test_slo_warmup_gate_fire_clear_and_one_dump_per_episode():
    h = MetricHistory(resolution_s=1.0, retention_s=60.0)
    reg = MetricsRegistry()
    dumps = []
    spec = SloSpec(name="lat", series="g", objective=1.0, op=">",
                   short_window_s=2.0, long_window_s=4.0)
    ev = SloEvaluator(h, [spec], registry=reg, dump_fn=dumps.append)
    # violating from the very first sample — but the warm-up gate holds
    # until the ring has covered one long window (4s of evidence)
    for k in range(4):
        h.sample(now=T + k, samples=[("g", "gauge", None, 5.0)])
        assert ev.evaluate(now=T + k) == []
    h.sample(now=T + 4, samples=[("g", "gauge", None, 5.0)])
    tr = ev.evaluate(now=T + 4)
    assert [t["event"] for t in tr] == ["slo_fire"]
    assert ev.firing() == ["lat"]
    assert reg.snapshot()['obs_slo_firing{slo="lat"}'] == 1.0
    assert reg.snapshot()['obs_slo_fired_total{slo="lat"}'] == 1.0
    assert len(dumps) == 1 and dumps[0][0]["slo"] == "lat"
    # a sustained violation is one episode: no new transition, no 2nd dump
    h.sample(now=T + 5, samples=[("g", "gauge", None, 5.0)])
    assert ev.evaluate(now=T + 5) == [] and len(dumps) == 1
    # recovery: the short window fills with healthy points -> clear
    for k in range(6, 10):
        h.sample(now=T + k, samples=[("g", "gauge", None, 0.5)])
    tr = ev.evaluate(now=T + 9)
    assert [t["event"] for t in tr] == ["slo_clear"]
    assert ev.firing() == []
    assert reg.snapshot()['obs_slo_firing{slo="lat"}'] == 0.0
    # a second episode re-fires AND dumps again (the dump re-armed when
    # everything cleared)
    for k in range(10, 15):
        h.sample(now=T + k, samples=[("g", "gauge", None, 9.0)])
        ev.evaluate(now=T + k)
    assert ev.firing() == ["lat"] and len(dumps) == 2
    assert reg.snapshot()['obs_slo_fired_total{slo="lat"}'] == 2.0


def test_ratio_slo_skips_zero_denominator_windows():
    h = MetricHistory(resolution_s=1.0, retention_s=60.0)
    spec = SloSpec(name="shed", kind="ratio", series=("sheds",),
                   den=("ok", "sheds"), objective=0.05, op=">",
                   short_window_s=2.0, long_window_s=4.0)
    ev = SloEvaluator(h, [spec])
    # zero traffic: every window has denominator 0 -> skipped, never burns
    for k in range(10):
        h.sample(now=T + k, samples=[("sheds", "counter", None, 0.0),
                                     ("ok", "counter", None, 0.0)])
        assert ev.evaluate(now=T + k) == []
    # traffic that sheds everything burns both windows and fires
    tot = 0.0
    for k in range(10, 16):
        tot += 5.0
        h.sample(now=T + k, samples=[("sheds", "counter", None, tot),
                                     ("ok", "counter", None, 0.0)])
        ev.evaluate(now=T + k)
    assert ev.firing() == ["shed"]


def test_default_serving_slos_match_the_catalog():
    # the shipped defaults reference catalogued series only (guards the
    # specs against a metrics rename)
    names = {s.name for s in default_serving_slos()}
    assert {"serving_ttft_p99", "serving_itl_p99",
            "serving_shed_ratio"} <= names
    for s in default_serving_slos():
        assert s.long_window_s >= s.short_window_s


# ---------------------------------------------------------------------------
# e2e over TCP: history RPC stale-ok, SLO fire -> bundle -> clear -> re-arm
# ---------------------------------------------------------------------------

def test_history_rpc_answers_against_wedged_pump(tiny_tr):
    """The stale-ok contract: the `history` frame is served on the loop
    thread from the lock-guarded ring — it answers while the engine pump
    is deliberately wedged, exactly when the trailing window matters."""
    eng = _engine(tiny_tr)
    orig_step = eng.step
    wedged, release = threading.Event(), threading.Event()

    def wedge_step():
        if not release.is_set() and \
                (eng.queue or any(s is not None for s in eng.slots)):
            wedged.set()
            release.wait(60)
        return orig_step()

    eng.step = wedge_step
    srv = ServingServer(eng, max_queue=4)
    host, port = srv.start_background()
    try:
        # manual sampling below: the background cadence is irrelevant here
        srv.history_sampler.stop()
        with ServingClient(host, port) as c:
            assert "history" in (c.hello().get("capabilities") or [])
            rid = c.submit([3, 4, 5], max_new=3)
            assert wedged.wait(30), "pump never picked up the request"
            srv.history.sample()          # the sampler-thread write path
            reply = c.history()           # ...answered against the wedge
            assert reply["type"] == "history"
            assert reply["process"]["role"] == "replica"
            assert reply["samples_taken"] >= 1
            assert "serving_num_slots" in reply["series"]
            kinds = {s["kind"] for s in reply["series"].values()}
            assert kinds <= {"counter", "gauge"}
            # prefix filter travels over the wire too
            only = c.history(names=["obs_history_"])["series"]
            assert only and all(k.startswith("obs_history_") for k in only)
            release.set()
            c.collect([rid])              # the pump recovers cleanly
    finally:
        release.set()
        srv.stop_background(drain=True)


def test_slo_episode_e2e_fire_bundle_clear_rearm(tiny_tr, tmp_path):
    """ISSUE 20 acceptance: induced p99 blowup -> slo_fire flight event,
    labelled gauge flips over the wire, EXACTLY one proactive bundle per
    episode (with the offending series in history.json), recovery emits
    slo_clear, and a second episode freezes a second bundle."""
    q = 'serving_latency_seconds{quantile="p99",stat="first_token_latency"}'
    spec = SloSpec(name="ttft_p99", series=q, objective=0.1, op=">",
                   short_window_s=2.0, long_window_s=4.0)
    eng = _engine(tiny_tr)
    srv = ServingServer(eng, max_queue=4, postmortem_dir=str(tmp_path),
                        history_resolution_s=1.0, history_retention_s=60.0,
                        slo_specs=[spec])
    host, port = srv.start_background()
    try:
        # deterministic clock: stop the background sampler and tick the
        # ring by hand at synthetic times past any real-time sample it
        # may have slipped in before the stop
        srv.history_sampler.stop()
        t0 = time.time() + 3600.0
        st = srv.stats.get("first_token_latency")
        for _ in range(8):
            st.add(5.0)                   # the p99 blowup: 5s TTFT
        for k in range(5):
            srv.history.sample(now=t0 + k)
            srv.slo.evaluate(now=t0 + k)
        assert srv.slo.firing() == ["ttft_p99"]

        found = _bundles(tmp_path)
        assert len(found) == 1, "first fire must freeze exactly one bundle"
        b = load_bundle(found[0])
        assert b["meta"]["reason"] == "slo:ttft_p99"
        assert "slo firing: ttft_p99" in b["meta"]["error"]
        fire_evs = [e for e in b["events"] if e["kind"] == "slo_fire"]
        assert fire_evs and fire_evs[-1]["data"]["slo"] == "ttft_p99"
        assert fire_evs[-1]["data"]["series"] == q
        # the bundle carries the offending series' trailing window —
        # frozen BEFORE anything died
        assert q in b["history"]["series"]
        assert b["history"]["series"][q]["points"][-1][1] == 5.0

        with ServingClient(host, port) as c:
            assert 'obs_slo_firing{slo="ttft_p99"} 1' in c.metrics()
            assert q in c.history()["series"]
        # a sustained violation stays one episode, one bundle
        srv.history.sample(now=t0 + 5)
        srv.slo.evaluate(now=t0 + 5)
        assert len(_bundles(tmp_path)) == 1

        # recovery: the latency window drains and healthy samples land
        st.reset()
        for _ in range(8):
            st.add(0.01)
        cleared = []
        for k in range(6, 10):
            srv.history.sample(now=t0 + k)
            cleared += srv.slo.evaluate(now=t0 + k)
        assert [t["event"] for t in cleared] == ["slo_clear"]
        assert srv.slo.firing() == []
        with ServingClient(host, port) as c:
            assert 'obs_slo_firing{slo="ttft_p99"} 0' in c.metrics()

        # second episode: re-fires and freezes a SECOND bundle
        for _ in range(8):
            st.add(5.0)
        for k in range(10, 16):
            srv.history.sample(now=t0 + k)
            srv.slo.evaluate(now=t0 + k)
        assert srv.slo.firing() == ["ttft_p99"]
        assert len(_bundles(tmp_path)) == 2, \
            "a new episode after recovery must dump again"
        # the renderer round-trips the health-plane section
        from tools.postmortem import main as postmortem_main
        assert postmortem_main([found[0]]) == 0
    finally:
        srv.stop_background(drain=True)


# ---------------------------------------------------------------------------
# fleet: the router's aggregate history view + obs_top over it
# ---------------------------------------------------------------------------

def test_router_aggregate_history_labels_replicas(tiny_tr):
    srvs = []
    for _ in range(2):
        eng = _engine(tiny_tr)
        srv = ServingServer(eng, max_queue=16)
        srv.start_background()
        srvs.append(srv)
    rt = FleetRouter(port=0,
                     replicas=[(s.host, s.port) for s in srvs],
                     poll_interval_s=0.1, heartbeat_misses=100)
    host, port = rt.start_background()
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(rt.table) == 2 and \
                    all(r.backend is not None and not r.backend.dead
                        for r in rt.table):
                break
            time.sleep(0.05)
        else:
            pytest.fail("replica backends never connected")
        # deterministic rings: one manual sample each, background off
        for srv in srvs:
            srv.history_sampler.stop()
            srv.history.sample()
        rt.history_sampler.stop()
        rt.history.sample()

        rids = sorted(r.rid for r in rt.table)
        with ServingClient(host, port) as c:
            reply = c.history(aggregate=True)
            c.metrics(aggregate=True)      # populate the metrics rpc lane
        assert reply["aggregate"] is True
        assert reply["replicas"] == rids
        keys = reply["series"]
        for rid in rids:
            assert f'serving_num_slots{{replica="{rid}"}}' in keys
        # the router's own series pass through unlabeled
        assert "fleet_replicas_registered" in keys
        assert "fleet_replicas_healthy" in keys

        # the loop-thread RPC audit: each reply type fans out on its own
        # lock-serialized lane — a slow history pull must never block the
        # stats heartbeat
        be = next(iter(rt.table)).backend
        assert be._rpc_locks["history"] is not be._rpc_locks["metrics"]

        # obs_top renders the same aggregate (no TTY: one-shot poll)
        from tools.obs_top import poll_router, render
        frame = poll_router(f"{host}:{port}", 300.0)
        assert frame["mode"] == "router"
        assert frame["replicas"] == rids
        assert "router" in frame["rows"]
        for rid in rids:
            assert rid in frame["rows"]
            assert frame["rows"][rid]["occupancy"] == 0.0
        text = render(frame)
        assert "tok/s" in text and "router" in text
    finally:
        rt.stop_background(drain=True)
        for srv in srvs:
            srv.stop_background(drain=True)


def test_obs_top_key_parsing_and_bucketing():
    from tools.obs_top import bucket_series, parse_key, sparkline

    assert parse_key('a{replica="r0",x="1"}') \
        == ("a", {"replica": "r0", "x": "1"})
    assert parse_key("plain") == ("plain", {})
    series = {
        'serving_num_slots{replica="r0"}':
            {"kind": "gauge", "points": [[T, 2.0]]},
        "fleet_inflight": {"kind": "gauge", "points": [[T, 1.0]]},
    }
    buckets = bucket_series(series)
    assert set(buckets) == {"", "r0"}
    assert buckets["r0"].points("serving_num_slots") == [[T, 2.0]]
    s = sparkline([0.0, 1.0, 2.0, 3.0], width=4)
    assert len(s) == 4 and s[0] != s[-1]
