"""Continuous-batching serving engine oracles.

The exactness contract: for a mixed-length request set, per-request tokens
from the engine (paged KV + slot scheduler + per-slot sampler) EXACTLY
match `lm_generate(use_cache=True)` run on each request alone — same rng
stream, same sampler semantics, same eos early-stop — while the compiled
decode step stays at ONE jit signature for the whole workload and prompt
prefill compiles once per feeder bucket, not per length."""

import numpy as np
import pytest

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.serving import PagedKVCache, Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer


def _make(args: str):
    cfg = parse_config("demo/model_zoo/transformer_lm.py", args)
    return Trainer(cfg, seed=7)


def _prompts(lens, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(2, vocab, n).astype(np.int32) for n in lens]


def _oracle(tr, req: Request):
    toks, lens = lm_generate(
        tr.executor, tr.params, req.prompt_ids[None, :],
        max_new=req.max_new, temperature=req.temperature, top_k=req.top_k,
        top_p=req.top_p, eos_id=req.eos_id, rng=req.rng, use_cache=True)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])]


def _assert_pool_reclaimed(eng):
    """End-of-workload pool accounting under prefix caching — the
    allocator's own check_reclaimed oracle (free or prefix-cached-only =
    whole pool; no slot-mapped pages left)."""
    eng.kv.check_reclaimed()


def _assert_all_match(tr, reqs, results):
    for r in reqs:
        np.testing.assert_array_equal(
            _oracle(tr, r), results[r.req_id],
            err_msg=f"request {r.req_id!r} diverged from the "
                    f"lm_generate(use_cache=True) oracle")


def test_engine_matches_per_request_oracle_greedy():
    """Mixed prompt lengths and max_new across more requests than slots:
    freed slots refill mid-flight, tokens stay per-request exact, and the
    whole workload runs through ONE compiled decode signature."""
    tr = _make("vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    prompts = _prompts((3, 9, 5, 12, 7, 4), 61)
    reqs = [Request(i, p, max_new=m)
            for i, (p, m) in enumerate(zip(prompts, (5, 7, 3, 6, 8, 2)))]
    eng = ServingEngine(tr.executor, tr.params, num_slots=3, page_size=8,
                        max_context=64)
    results = eng.run(reqs)
    _assert_all_match(tr, reqs, results)
    # jit cache inspection (the test_fused_dispatch discipline): the decode
    # step compiled exactly once for the whole mixed workload
    assert eng._decode_step._cache_size() == 1
    assert eng.n_decode_steps > 0


@pytest.mark.parametrize("extra", ["kv_heads=2", "window=5"])
def test_engine_oracle_gqa_and_window(extra):
    """Grouped-query heads and sliding-window attention flow through the
    paged read path (kv-head groups in the gather, window in the mask)
    without breaking per-request exactness."""
    tr = _make(f"vocab=97,dim=32,layers=2,heads=4,batch_size=4,{extra}")
    prompts = _prompts((3, 9, 6), 97)
    reqs = [Request(i, p, max_new=6) for i, p in enumerate(prompts)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    _assert_all_match(tr, reqs, eng.run(reqs))
    assert eng._decode_step._cache_size() == 1


def test_engine_matches_per_request_oracle_sampled():
    """Per-request sampling knobs (greedy / top-k / nucleus / full) and
    per-request rng keys, all inside the one compiled step."""
    tr = _make("vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    prompts = _prompts((4, 9, 6, 11), 61, seed=1)
    knobs = [dict(),                                     # greedy
             dict(temperature=0.8, top_k=5),
             dict(temperature=0.7, top_p=0.9),
             dict(temperature=1.1)]                      # full sampling
    reqs = [Request(i, p, max_new=6, rng=jax.random.PRNGKey(100 + i), **kw)
            for i, (p, kw) in enumerate(zip(prompts, knobs))]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    results = eng.run(reqs)
    _assert_all_match(tr, reqs, results)
    assert eng._decode_step._cache_size() == 1


def test_engine_eos_early_stop_refills_slots():
    """eos-stopped requests retire their slot early; the freed slot admits
    the next request mid-flight and every output stays oracle-exact."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    prompts = _prompts((6, 4, 5, 3, 6, 4), 11, seed=3)
    # eos = the first token request 0 greedily emits, so at least one
    # request is guaranteed to stop early
    t0, _ = lm_generate(tr.executor, tr.params, prompts[0][None, :],
                        max_new=1, use_cache=True)
    eos = int(np.asarray(t0)[0, prompts[0].size])
    reqs = [Request(i, p, max_new=8, eos_id=eos)
            for i, p in enumerate(prompts)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=32)
    results = eng.run(reqs)
    _assert_all_match(tr, reqs, results)
    assert eng._decode_step._cache_size() == 1
    # at least one row must actually have hit eos for this test to bite
    assert any(results[r.req_id].size < r.prompt_ids.size + r.max_new
               for r in reqs)


def test_prefill_compiles_per_bucket_not_per_length():
    """LEGACY (prefill_chunk=None) path: prompts of lengths 3/5/7 share
    the 8-bucket; 12 lands in the 16-bucket — exactly two prefill
    signatures (the feeder's _bucket_len grid, page-aligned), not four.
    The chunked default compiles NO per-bucket prefill programs at all —
    tests/test_chunked_prefill.py pins that signature discipline."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    prompts = _prompts((3, 5, 7, 12), 31, seed=2)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=32, prefill_chunk=None)
    results = eng.run([Request(i, p, max_new=3)
                       for i, p in enumerate(prompts)])
    assert len(results) == 4
    assert sorted(eng._prefill_cache) == [8, 16]
    assert eng._decode_step._cache_size() == 1
    assert eng._mixed_step._cache_size() == 0, \
        "legacy mode must never touch the mixed step"


def test_overcommitted_pool_preempts_and_stays_exact():
    """A pool smaller than the worst case forces pauses/preemptions; the
    deterministic per-request key schedule makes them invisible in the
    output — tokens still match the oracle exactly, and every page returns
    to the free list."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    prompts = _prompts((6, 4, 5, 3, 6), 11, seed=3)
    reqs = [Request(i, p, max_new=8) for i, p in enumerate(prompts)]
    # 2 slots x 4 pages would want 8; give 5 real pages
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16, num_pages=6)
    results = eng.run(reqs)
    _assert_all_match(tr, reqs, results)
    assert eng.n_preemptions > 0, "pool was never actually overcommitted"
    _assert_pool_reclaimed(eng)
    assert eng._decode_step._cache_size() == 1


def test_request_validation():
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16)
    with pytest.raises(ValueError, match="temperature"):
        Request(0, [3, 4], max_new=4, top_k=5)
    with pytest.raises(ValueError, match="slot capacity"):
        eng.add_request(Request(0, list(range(2, 10)), max_new=12))
    # network-reachable garbage must raise, not crash the pump later
    with pytest.raises(ValueError, match="negative"):
        eng.add_request(Request(0, [3, 4], max_new=-1))
    with pytest.raises(ValueError, match="empty prompt"):
        Request(0, [], max_new=4)
    # max_new=0 resolves immediately to the prompt (lm_generate semantics)
    # — even when the prompt alone would flunk capacity/page validation,
    # since it never touches a slot or a page
    eng.add_request(Request("p", [3, 4, 5], max_new=0))
    eng.add_request(Request("big0", list(range(2, 40)), max_new=0))
    assert not eng.step()
    np.testing.assert_array_equal(eng.results["p"], [3, 4, 5])
    assert eng.results["big0"].size == 38


def test_pool_too_small_to_complete_is_rejected():
    """A request whose worst-case footprint (prompt + max_new - 1 tokens)
    exceeds the whole pool can never finish — preemption would just replay
    it forever once it is alone.  add_request must reject it up front."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=1, page_size=4,
                        max_context=32, num_pages=4)   # 3 real pages
    with pytest.raises(ValueError, match="pages to complete"):
        # 4 + 16 - 1 = 19 tokens -> 5 pages > 3
        eng.add_request(Request(0, [3, 4, 5, 6], max_new=16))
    # the same footprint fits exactly -> admitted and completes
    ok = Request(1, [3, 4, 5, 6], max_new=9)           # 12 tokens -> 3 pages
    res = eng.run([ok])
    np.testing.assert_array_equal(_oracle(tr, ok), res[1])


def test_failed_admission_releases_partial_page_grab():
    """An admission attempt that grabs some pages and then starves must
    return them: a later retry can land on a DIFFERENT free slot, and
    pages stranded on the first one would leak the pool and strand the
    queued request forever."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    # 5 real pages, ps=4: A (prompt 14 -> 4 pages, max_new=3) fills slot 0;
    # B (prompt 17 -> 5 pages, max_new=2) must wait for A, then take the
    # whole pool — regardless of which slot the retry lands on
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=20, num_pages=6)
    rng = np.random.default_rng(0)
    a = Request("a", rng.integers(2, 11, 14).astype(np.int32), max_new=3)
    b = Request("b", rng.integers(2, 11, 17).astype(np.int32), max_new=2)
    results = eng.run([a, b])
    assert set(results) == {"a", "b"}, "queued request was dropped"
    _assert_all_match(tr, [a, b], results)
    _assert_pool_reclaimed(eng)


def test_run_returns_only_its_own_completions_and_pools_stay_live():
    """A long-lived engine: each run() pops exactly the requests that
    completed on its watch (no bleed from earlier workloads, no unbounded
    result archive), and kv.pools always points at live buffers (the
    donating jits must rebind it, not leave deleted aliases)."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    prompts = _prompts((4, 7), 31, seed=6)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=32)
    first = eng.run([Request("a", prompts[0], max_new=3)])
    assert set(first) == {"a"}
    # the donated-and-rebound pool must still be readable
    for pool in eng.kv.pools.values():
        np.asarray(pool["k"][0, 0, 0, 0])
    second = eng.run([Request("b", prompts[1], max_new=3)])
    assert set(second) == {"b"}
    assert not eng.results, "completed results were retained after run()"


def test_cancel_inflight_frees_slot_and_pages_and_survivors_stay_exact():
    """Client-initiated cancellation mid-flight: the victim's slot and
    pages return to the pool immediately (accounting back to baseline at
    the end), its partial tokens are an exact PREFIX of its oracle run,
    and every surviving request still matches the oracle token-for-token
    through ONE compiled decode signature."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    prompts = _prompts((5, 9, 4, 7), 31, seed=4)
    reqs = [Request(i, p, max_new=8) for i, p in enumerate(prompts)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=32)
    for r in reqs:
        eng.add_request(r)
    for _ in range(3):                     # get the first wave mid-flight
        eng.step()
    victim = next(sl.req.req_id for sl in eng.slots if sl is not None)
    # cancel must return the victim's pages to the pool THIS call — free
    # outright, or donated to the prefix index (cached refcount-zero =
    # reclaimable by eviction on the very next allocation)
    reclaimable_before = eng.kv.free_page_count + eng.kv.cached_page_count
    mapped_before = eng.kv.private_pages_in_use + eng.kv.shared_pages_in_use
    assert eng.cancel(victim)
    assert eng.kv.free_page_count + eng.kv.cached_page_count \
        > reclaimable_before, "cancel freed no pages"
    assert eng.kv.private_pages_in_use + eng.kv.shared_pages_in_use \
        < mapped_before, "cancel left the victim's pages slot-mapped"
    assert not eng.cancel(victim), "double-cancel must report unknown"
    assert eng.finish_reasons[victim] == "cancelled"
    partial = eng.results[victim]
    full = _oracle(tr, reqs[victim])
    np.testing.assert_array_equal(partial, full[:partial.size],
                                  err_msg="cancelled tokens are not a "
                                          "prefix of the oracle run")
    assert partial.size > reqs[victim].prompt_ids.size, \
        "victim was not actually mid-flight"
    results = eng.run()
    survivors = [r for r in reqs if r.req_id != victim]
    _assert_all_match(tr, survivors, results)
    _assert_pool_reclaimed(eng)
    assert eng._decode_step._cache_size() == 1
    assert eng.n_cancelled == 1


def test_deadline_expiry_frees_pages_for_waiting_requests():
    """Deadline sweep on a deterministic step-count clock over an
    overcommitted pool: the expired request's pages are what let the
    WAITING request admit at all — after expiry it runs to completion
    oracle-exact, and the sweep reports reason 'deadline'."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    rng = np.random.default_rng(5)
    # ps=4, 4 pages/slot, pool = 8 real pages: a and b (4 pages each once
    # decoding) fill it; c can only ever admit from freed pages
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16, num_pages=9)
    eng.clock = lambda: float(eng.n_decode_steps)   # deterministic clock
    a = Request("a", rng.integers(2, 31, 9).astype(np.int32), max_new=7,
                deadline=3.0)                       # expires at step 3
    b = Request("b", rng.integers(2, 31, 10).astype(np.int32), max_new=6)
    c = Request("c", rng.integers(2, 31, 11).astype(np.int32), max_new=5)
    results = eng.run([a, b, c])
    assert eng.n_expired == 1
    assert set(results) == {"a", "b", "c"}
    partial = results["a"]
    np.testing.assert_array_equal(partial, _oracle(tr, a)[:partial.size])
    assert partial.size < _oracle(tr, a).size, \
        "deadline request ran to completion — never actually expired"
    _assert_all_match(tr, [b, c], results)
    _assert_pool_reclaimed(eng)
    assert eng._decode_step._cache_size() == 1


def test_cancel_and_deadline_on_queued_requests():
    """A queued (never-admitted) request cancels/expires cleanly: result
    is the bare prompt, no slot or page was ever touched."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=1, page_size=4,
                        max_context=16)
    eng.clock = lambda: float(eng.n_decode_steps)
    run = Request("run", [3, 4, 5], max_new=4)
    q_cancel = Request("qc", [4, 5], max_new=4)
    q_expire = Request("qe", [5, 6], max_new=4, deadline=0.0)  # born dead
    eng.add_request(run)
    eng.add_request(q_cancel)
    eng.add_request(q_expire)
    assert eng.cancel("qc")
    np.testing.assert_array_equal(eng.results["qc"], [4, 5])
    assert eng.finish_reasons["qc"] == "cancelled"
    results = eng.run()
    np.testing.assert_array_equal(results["qe"], [5, 6])
    assert eng.n_expired == 1 and eng.n_cancelled == 1
    np.testing.assert_array_equal(_oracle(tr, run), results["run"])
    assert not eng.cancel("nonexistent")


def test_cancel_of_preempted_queued_request_keeps_streamed_tokens():
    """A preempted request waits in the queue with its generated-so-far
    rolled back; cancelling it THERE must still report the tokens that
    were already emitted (a front end streamed them to the client — the
    done frame has to agree with the stream) and restore the
    tokens_generated accounting the preempt rollback subtracted."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    prompts = _prompts((6, 4, 5), 11, seed=3)
    reqs = [Request(i, p, max_new=8) for i, p in enumerate(prompts)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16, num_pages=6)
    streamed: dict = {}
    eng.on_token = lambda rid, tok, idx: streamed.setdefault(
        rid, {}).update({idx: tok})
    for r in reqs:
        eng.add_request(r)
    while eng.n_preemptions == 0 and eng.step():
        pass
    assert eng.n_preemptions > 0, "pool was never overcommitted"
    victim = eng.queue[0]              # preemption requeues at the front
    stash = list(victim._preempted_gen)
    assert stash, "preempted request carried no generated-token stash"
    tg_before = eng.tokens_generated
    assert eng.cancel(victim.req_id)
    toks = eng.results[victim.req_id]
    # prompt + exactly what was emitted (== what a server streamed), and
    # still a prefix of the uninterrupted oracle run
    np.testing.assert_array_equal(toks[victim.prompt_ids.size:], stash)
    seen = streamed[victim.req_id]
    np.testing.assert_array_equal(
        stash, [seen[i] for i in range(len(stash))])
    np.testing.assert_array_equal(toks, _oracle(tr, victim)[:toks.size])
    assert eng.tokens_generated == tg_before + len(stash)
    # survivors finished either during the pressure loop (still sitting in
    # eng.results) or under run() — merge both phases
    results = dict(eng.results)
    results.update(eng.run())
    survivors = [r for r in reqs if r.req_id != victim.req_id]
    _assert_all_match(tr, survivors, results)
    _assert_pool_reclaimed(eng)


def test_cancel_mid_replay_reports_all_previously_streamed_tokens():
    """Preempt a request that already emitted k tokens, re-admit it, and
    cancel while the deterministic replay is still short of k: the result
    must carry all k originally-delivered tokens (replay and original are
    identical prefixes of one stream) and re-bank the not-yet-replayed
    remainder in tokens_generated."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=1, page_size=4,
                        max_context=16)
    r = Request("r", [3, 4, 5], max_new=8)
    eng.add_request(r)
    for _ in range(3):       # mixed(chunk+token 0) + 2 decode: gen = 3
        assert eng.step()
    s = next(i for i, sl in enumerate(eng.slots) if sl is not None)
    stash = list(eng.slots[s].generated)
    assert len(stash) == 3
    eng._preempt(s)
    assert r._preempted_gen == stash
    assert eng.step()                      # re-admit; replay at gen = 2
    sl = next(sl for sl in eng.slots if sl is not None)
    assert sl.req is r and sl.gen < len(stash), "replay already caught up"
    tg = eng.tokens_generated
    behind = len(stash) - sl.gen
    assert eng.cancel("r")
    toks = eng.results["r"]
    np.testing.assert_array_equal(
        toks, np.concatenate([r.prompt_ids, np.asarray(stash, np.int32)]),
        err_msg="mid-replay cancel dropped already-delivered tokens")
    np.testing.assert_array_equal(toks, _oracle(tr, r)[:toks.size])
    assert eng.tokens_generated == tg + behind
    _assert_pool_reclaimed(eng)


def test_finish_hooks_fire_once_per_token_and_request():
    """on_token sees every emitted token exactly once (index = position in
    the generated stream), on_finish exactly once per request with the
    final array — the contract serving/server.py streams through."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16)
    seen_toks: dict = {}
    finishes: dict = {}
    eng.on_token = lambda rid, tok, idx: seen_toks.setdefault(
        rid, []).append((idx, tok))
    eng.on_finish = lambda rid, toks, reason: finishes.setdefault(
        rid, (toks, reason))
    reqs = [Request(i, p, max_new=m) for i, (p, m) in
            enumerate(zip(_prompts((3, 5, 4), 11, seed=7), (4, 6, 1)))]
    results = eng.run(reqs)
    for r in reqs:
        toks, reason = finishes[r.req_id]
        np.testing.assert_array_equal(toks, results[r.req_id])
        assert reason in ("stop", "length")
        gen = [t for _, t in sorted(seen_toks[r.req_id])]
        idxs = [i for i, _ in sorted(seen_toks[r.req_id])]
        assert idxs == list(range(len(gen))), "token indices not dense"
        np.testing.assert_array_equal(
            gen, results[r.req_id][r.prompt_ids.size:],
            err_msg="streamed tokens disagree with the final result")


def test_paged_kv_allocator():
    """Page accounting: grow on demand, pause on exhaustion, release on
    retire; page 0 stays reserved as the trash page."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    kv = PagedKVCache(tr.executor, num_slots=2, page_size=4,
                      pages_per_slot=3, num_pages=5)   # 4 real pages
    assert kv.free_page_count == 4
    assert kv.try_grow(0, 9)                  # 3 pages
    assert kv.pages_in_use == 3
    assert (kv.table[0, :3] > 0).all()        # never the trash page
    assert kv.try_grow(1, 4)                  # 1 page
    assert not kv.try_grow(1, 5)              # exhausted -> pause
    kv.release(0)
    assert kv.free_page_count == 3
    assert kv.try_grow(1, 8)                  # resumes after the release
    assert (kv.table[0] == 0).all()


def test_paged_attention_step_matches_cached_dense():
    """Ops-level oracle: the paged read/write path reproduces
    cached_attention_step's math on a slot whose pages are mapped
    arbitrarily (non-contiguous, interleaved across slots)."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import (cached_attention_step,
                                          paged_attention_step)

    rng = np.random.default_rng(1)
    S, H, Hkv, D, ps, maxp, P = 3, 4, 2, 8, 4, 4, 12
    pos = np.asarray([5, 9, 2], np.int32)
    table = np.asarray([[4, 7, 0, 0], [2, 9, 5, 0], [11, 0, 0, 0]], np.int32)

    def mk(*shape):
        return jnp.asarray(rng.normal(size=shape), jnp.float32)

    q, kn, vn = mk(S, 1, H, D), mk(S, 1, Hkv, D), mk(S, 1, Hkv, D)
    kp, vp = jnp.zeros((P, ps, Hkv, D)), jnp.zeros((P, ps, Hkv, D))
    # seed each slot's mapped pages with its own history
    hist_k = [mk(int(p), Hkv, D) for p in pos]
    hist_v = [mk(int(p), Hkv, D) for p in pos]
    for s in range(S):
        for t in range(int(pos[s])):
            kp = kp.at[table[s, t // ps], t % ps].set(hist_k[s][t])
            vp = vp.at[table[s, t // ps], t % ps].set(hist_v[s][t])

    out, _, _ = paged_attention_step(q, kn, vn, kp, vp,
                                     jnp.asarray(table), jnp.asarray(pos),
                                     use_kernel=False)
    for s in range(S):
        n = int(pos[s])
        Tmax = n + 1
        ck = jnp.zeros((1, Tmax, Hkv, D)).at[0, :n].set(hist_k[s])
        cv = jnp.zeros((1, Tmax, Hkv, D)).at[0, :n].set(hist_v[s])
        want, _, _, _ = cached_attention_step(
            q[s:s + 1], kn[s:s + 1], vn[s:s + 1], ck, cv,
            jnp.asarray([n], jnp.int32), jnp.ones((1,), jnp.int32))
        np.testing.assert_allclose(np.asarray(out[s]), np.asarray(want[0]),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pallas_paged_kernel_matches_fallback():
    """Interpret-mode parity of the ragged-paged Pallas kernel against the
    jnp gather fallback, incl. grouped-query heads and ragged lengths."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import paged_attention_step
    from paddle_tpu.ops.pallas_paged import paged_attention

    rng = np.random.default_rng(0)
    for (S, H, Hkv, D, ps, maxp) in [(3, 4, 2, 8, 4, 4),
                                     (2, 8, 8, 16, 8, 3),
                                     (4, 6, 3, 32, 16, 2)]:
        P = 1 + S * maxp
        kp = jnp.asarray(rng.normal(size=(P, ps, Hkv, D)), jnp.float32)
        vp = jnp.asarray(rng.normal(size=(P, ps, Hkv, D)), jnp.float32)
        pos = rng.integers(0, maxp * ps - 1, S).astype(np.int32)
        table = np.zeros((S, maxp), np.int32)
        free = list(range(1, P))
        for s in range(S):
            for j in range(-(-int(pos[s] + 1) // ps)):
                table[s, j] = free.pop()
        q = jnp.asarray(rng.normal(size=(S, 1, H, D)), jnp.float32)
        kn = jnp.asarray(rng.normal(size=(S, 1, Hkv, D)), jnp.float32)
        vn = jnp.asarray(rng.normal(size=(S, 1, Hkv, D)), jnp.float32)
        want, ck, cv = paged_attention_step(
            q, kn, vn, kp, vp, jnp.asarray(table), jnp.asarray(pos),
            use_kernel=False)
        got = paged_attention(q[:, 0], ck, cv, jnp.asarray(table),
                              jnp.asarray(pos) + 1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want[:, 0]),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=str((S, H, Hkv, D, ps, maxp)))
