"""Checkpoint durability: atomic commit, corrupt-file handling, resume
entry point.

The failure being engineered away: a crash mid-`np.savez` used to leave a
`pass-%05d/model.npz` that LOOKS loadable (the dir exists, the file
exists) but dies inside zipfile at load time — the worst possible resume
experience.  Saves now stage the whole pass dir under `.tmp` and rename
into place last, so every committed dir is complete by construction and
every reader skips stragglers."""

import os

import numpy as np
import pytest

from paddle_tpu.trainer import checkpoint as ckpt


def _params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": rng.normal(size=(4, 3)).astype(np.float32),
            "b": rng.normal(size=(3,)).astype(np.float32)}


def test_save_commits_atomically_and_roundtrips(tmp_path):
    save_dir = str(tmp_path / "ck")
    params = _params()
    d = ckpt.save_checkpoint(save_dir, 0, params, config_json='{"a": 1}')
    assert os.path.basename(d) == "pass-00000"
    # no staging residue once committed
    assert not any(x.endswith(".tmp") or x.endswith(".part")
                   for x in os.listdir(save_dir))
    assert not any(x.endswith(".part") for x in os.listdir(d))
    out = ckpt.load_checkpoint(d)
    np.testing.assert_array_equal(out["params"]["w"], params["w"])
    assert out["config_json"] == '{"a": 1}'
    # re-saving the same pass replaces it cleanly
    params2 = _params(seed=1)
    ckpt.save_checkpoint(save_dir, 0, params2)
    out2 = ckpt.load_checkpoint(d)
    np.testing.assert_array_equal(out2["params"]["w"], params2["w"])


def test_stale_tmp_straggler_is_invisible_and_overwritten(tmp_path):
    """A crash between staging and rename leaves `pass-%05d.tmp` — every
    reader must skip it, and the next save of that pass must clobber it."""
    save_dir = str(tmp_path / "ck")
    ckpt.save_checkpoint(save_dir, 0, _params())
    straggler = os.path.join(save_dir, "pass-00001.tmp")
    os.makedirs(straggler)
    with open(os.path.join(straggler, "model.npz"), "wb") as f:
        f.write(b"half a zip")
    assert ckpt.latest_pass(save_dir) == 0
    assert ckpt.latest_checkpoint(save_dir).endswith("pass-00000")
    # resume-from-root keeps working (load_checkpoint ignores the .tmp)
    out = ckpt.load_checkpoint(save_dir)
    assert out["pass_id"] == 0
    # saving pass 1 for real sweeps the straggler and commits
    d = ckpt.save_checkpoint(save_dir, 1, _params(seed=2))
    assert not os.path.isdir(straggler)
    assert ckpt.latest_checkpoint(save_dir) == d


def test_corrupt_npz_raises_actionable_error(tmp_path):
    """A truncated model.npz must name the offending path, not surface a
    raw zipfile.BadZipFile from the guts of numpy."""
    save_dir = str(tmp_path / "ck")
    d = ckpt.save_checkpoint(save_dir, 0, _params())
    npz = os.path.join(d, "model.npz")
    blob = open(npz, "rb").read()
    with open(npz, "wb") as f:
        f.write(blob[: len(blob) // 2])            # torn write
    with pytest.raises(ValueError, match="corrupt or truncated") as ei:
        ckpt.load_checkpoint(d)
    assert npz in str(ei.value)


def test_latest_checkpoint_resume_entry_point(tmp_path):
    save_dir = str(tmp_path / "ck")
    assert ckpt.latest_checkpoint(save_dir) is None
    ckpt.save_checkpoint(save_dir, -1, _params())     # pre-training snap
    assert ckpt.latest_checkpoint(save_dir).endswith("pass-init")
    ckpt.save_checkpoint(save_dir, 0, _params())
    ckpt.save_checkpoint(save_dir, 3, _params())
    assert ckpt.latest_checkpoint(save_dir).endswith("pass-00003")


def test_keep_last_prunes_only_after_commit(tmp_path):
    save_dir = str(tmp_path / "ck")
    for p in range(4):
        ckpt.save_checkpoint(save_dir, p, _params(seed=p), keep_last=2)
    kept = sorted(x for x in os.listdir(save_dir))
    assert kept == ["pass-00002", "pass-00003"]
    # the survivor of the pruning is the newly COMMITTED dir — loadable
    out = ckpt.load_checkpoint(save_dir)
    assert out["pass_id"] == 3
    # an orphaned straggler from a crashed save of ANOTHER pass (never
    # re-saved, so same-pass cleanup never sees it) is swept by pruning
    os.makedirs(os.path.join(save_dir, "pass-00009.tmp"))
    ckpt.save_checkpoint(save_dir, 4, _params(), keep_last=2)
    assert not os.path.isdir(os.path.join(save_dir, "pass-00009.tmp"))
    assert sorted(os.listdir(save_dir)) == ["pass-00003", "pass-00004"]


def test_load_canonicalizes_key_order(tmp_path):
    """load_checkpoint must return identically-ORDERED trees no matter
    what order the writer inserted npz entries in — the trainer's save()
    flattens jax-pytree-sorted, but the pserver's streaming snapshotter
    assembles blocks in its own iteration order, and optimizer-slot
    iteration order must round-trip deterministically either way."""
    d = tmp_path / "pass-00000"
    d.mkdir(parents=True)
    sep = ckpt.SEP
    arrs = {
        f"params{sep}w": np.arange(6, dtype=np.float32),
        f"params{sep}b": np.ones(3, np.float32),
        f"opt{sep}slots{sep}w{sep}momentum": np.zeros(6, np.float32),
        f"opt{sep}slots{sep}b{sep}momentum": np.zeros(3, np.float32),
        f"opt{sep}slots{sep}a{sep}momentum": np.zeros(2, np.float32),
        f"opt{sep}num_updates": np.int32(4),
    }
    # adversarial writer: reverse-sorted insertion (npz preserves order)
    with open(d / "model.npz", "wb") as f:
        np.savez(f, **{k: arrs[k] for k in sorted(arrs, reverse=True)})
    out = ckpt.load_checkpoint(str(d))
    assert list(out["params"]) == ["b", "w"]
    assert list(out["opt"]["slots"]) == ["a", "b", "w"]
    # and a canonical writer produces the very same ordering
    d2 = ckpt.save_checkpoint(
        str(tmp_path / "ck2"), 0,
        {"w": arrs[f"params{sep}w"], "b": arrs[f"params{sep}b"]},
        opt_state={"slots": {"w": {"momentum": np.zeros(6, np.float32)},
                             "b": {"momentum": np.zeros(3, np.float32)},
                             "a": {"momentum": np.zeros(2, np.float32)}},
                   "num_updates": np.int32(4)})
    out2 = ckpt.load_checkpoint(d2)
    assert list(out2["params"]) == list(out["params"])
    assert list(out2["opt"]["slots"]) == list(out["opt"]["slots"])
    for name in out["opt"]["slots"]:
        assert list(out2["opt"]["slots"][name]) == \
            list(out["opt"]["slots"][name])
