"""Parameter-server tier units + loopback integration (paddle_tpu/pserver/).

Covers the deterministic block map, the wire codec's bit-exactness, the
elastic membership state machine (join/drain/leave/expiry — ISSUE 14
satellite), the live server's elastic behavior over real sockets
(mid-window join, drain, abrupt death discarding the in-flight
contribution), the streaming snapshotter's no-stall contract, the
sharded-checkpoint reassembly, and the misconnected-peer refusals both
directions.  The full training exactness oracle lives in
tests/test_train_dist.py."""

import threading
import time

import numpy as np
import pytest

from paddle_tpu.config.schema import OptimizationConfig, ParameterConfig
from paddle_tpu.pserver import membership as mem
from paddle_tpu.pserver.blocks import BlockMap, decode_array, encode_array
from paddle_tpu.pserver.client import ParameterClient
from paddle_tpu.pserver.membership import Membership
from paddle_tpu.pserver.server import (ParameterServer, UpdateEngine,
                                       assemble_sharded_checkpoint)

# ---------------------------------------------------------------------------
# block map + codec units (no sockets, no jax)
# ---------------------------------------------------------------------------


def test_codec_bit_exact_roundtrip():
    rng = np.random.default_rng(0)
    arrs = [rng.standard_normal((5, 7)).astype(np.float32),
            np.array([np.nan, np.inf, -np.inf, 1e-45, -0.0], np.float32),
            rng.integers(0, 100, (3,)).astype(np.int32),
            np.float64(3.141592653589793) * np.ones((2, 2))]
    for a in arrs:
        b = decode_array(encode_array(a))
        assert b.dtype == a.dtype and b.shape == a.shape
        np.testing.assert_array_equal(
            a.view(np.uint8) if a.dtype.kind == "f" else a,
            b.view(np.uint8) if b.dtype.kind == "f" else b)


def test_block_map_deterministic_and_partitions():
    specs = {"b": ((7,), "float32"), "a": ((10, 3), "float32"),
             "c": ((4,), "float32")}
    bm1 = BlockMap(specs, n_shards=3, block_size=8)
    bm2 = BlockMap.from_config(bm1.config())
    assert bm1 == bm2
    # every element covered exactly once, shards disjoint
    seen = set()
    for s in range(3):
        for r in bm1.shard_blocks(s):
            key = (r.name, r.start, r.stop)
            assert key not in seen
            seen.add(key)
    for name, (shape, _dt) in specs.items():
        size = int(np.prod(shape))
        covered = sorted((r.start, r.stop) for r in bm1.blocks[name])
        assert covered[0][0] == 0 and covered[-1][1] == size
        for (s0, e0), (s1, e1) in zip(covered, covered[1:]):
            assert e0 == s1
    # a 10x3 param at block 8 must split into 4 blocks
    assert len(bm1.blocks["a"]) == 4


def test_block_split_assemble_roundtrip():
    rng = np.random.default_rng(1)
    params = {"w": rng.standard_normal((9, 5)).astype(np.float32),
              "b": rng.standard_normal((3,)).astype(np.float32)}
    bm = BlockMap.from_arrays(params, n_shards=2, block_size=7)
    blocks = {}
    for s in range(2):
        blocks.update(bm.split_all(params, shard=s))
    out = bm.assemble_all(blocks)
    for n in params:
        np.testing.assert_array_equal(out[n], params[n])
    with pytest.raises(KeyError, match="missing block"):
        one_shard = bm.split_all(params, shard=0)
        bm.assemble("w", one_shard)


def test_bin_blocks_codec_bit_exact_and_bounds_checked():
    from paddle_tpu.pserver.blocks import (decode_blocks_bin,
                                           encode_blocks_bin)
    rng = np.random.default_rng(7)
    blocks = {"w#1": rng.standard_normal((5, 3)).astype(np.float32),
              "w#0": np.array([np.nan, np.inf, -0.0, 1e-45], np.float32),
              "b#0": rng.integers(0, 9, (4,)).astype(np.int32)}
    meta, payload = encode_blocks_bin(blocks)
    # layout is sorted-bid and gap-free
    assert list(meta) == sorted(blocks)
    assert sum(d["n"] for d in meta.values()) == len(payload)
    out = decode_blocks_bin(meta, payload)
    assert set(out) == set(blocks)
    for bid, a in blocks.items():
        b = out[bid]
        assert b.dtype == a.dtype and b.shape == a.shape
        np.testing.assert_array_equal(a.view(np.uint8), b.view(np.uint8))
        assert b.flags.writeable          # same contract as decode_array
    # exactly what decode_array yields from the JSON codec — the two wire
    # formats are interchangeable representations of the same arrays
    for bid, a in blocks.items():
        np.testing.assert_array_equal(
            out[bid].view(np.uint8),
            decode_array(encode_array(a)).view(np.uint8))
    # a corrupt span must fail loudly, not read out of bounds
    bad = {k: dict(v) for k, v in meta.items()}
    bad["w#1"]["off"] = len(payload)
    with pytest.raises(ValueError, match="overruns"):
        decode_blocks_bin(bad, payload)


def test_bin_wire_frame_roundtrip_and_json_interleave():
    import socket as socket_mod

    from paddle_tpu.serving import wire

    a, b = socket_mod.socketpair()
    try:
        payload = bytes(range(256)) * 17
        wire.write_frame_bin_sync(a, {"type": "send_grad", "window": 3},
                                  payload)
        wire.write_frame_sync(a, {"type": "barrier", "window": 3})
        msg = wire.read_frame_sync(b)
        assert msg["type"] == "send_grad" and msg["window"] == 3
        assert msg[wire.PAYLOAD_KEY] == payload
        # a plain JSON frame on the same stream is untouched by the
        # binary variant (no payload key, same framing)
        nxt = wire.read_frame_sync(b)
        assert nxt == {"type": "barrier", "window": 3}
        assert wire.PAYLOAD_KEY not in nxt
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# membership state machine units (ISSUE 14 satellite: deterministic
# join/drain/leave — no sockets, injected clocks)
# ---------------------------------------------------------------------------


def test_membership_join_drain_leave():
    ms = Membership()
    a = ms.join(now=0.0)
    b = ms.join(now=0.0)
    assert (a.tid, a.rank) == ("t0", 0) and (b.tid, b.rank) == ("t1", 1)
    # both active: both required at a barrier nobody reached yet
    assert ms.required(set()) == {"t0", "t1"}
    assert ms.required({"t0"}) == {"t1"}
    # drain: b stops stalling the fleet but may still contribute
    assert ms.drain("t1")
    assert ms.required(set()) == {"t0"}
    assert ms.in_rank_order(["t1", "t0"]) == ["t0", "t1"]
    assert ms.counts() == {mem.ACTIVE: 1, mem.DRAINING: 1}
    assert ms.undrain("t1") and ms.required(set()) == {"t0", "t1"}
    ms.drain("t1")
    # clean leave removes entirely
    left = ms.leave("t1")
    assert left.state == mem.LEFT and len(ms) == 1
    # rank 1 is free again: a restarted trainer slides back in
    c = ms.join(now=1.0)
    assert c.rank == 1
    # duplicate explicit rank refused (double-counted data shard)
    with pytest.raises(ValueError, match="already held"):
        ms.join(rank=0)


def test_membership_expiry_and_rank_reuse():
    ms = Membership()
    a = ms.join(now=0.0)
    b = ms.join(now=0.0)
    ms.beat("t0", now=5.0)
    dead = ms.expire(timeout_s=3.0, now=6.0)
    assert [m.tid for m in dead] == ["t1"] and b.state == mem.DEAD
    assert ms.required(set()) == {"t0"}
    assert a.state == mem.ACTIVE
    # beat on a dropped member is a no-op, not a resurrection
    assert not ms.beat("t1", now=7.0)


# ---------------------------------------------------------------------------
# live-server helpers
# ---------------------------------------------------------------------------

OPT = OptimizationConfig(batch_size=4, learning_method="momentum",
                         momentum=0.9, learning_rate=0.1)
PCFGS = {"w": ParameterConfig(name="w", size=12, dims=[3, 4]),
         "b": ParameterConfig(name="b", size=4, dims=[4])}


def _init_params(seed=0):
    rng = np.random.default_rng(seed)
    return {"w": rng.standard_normal((3, 4)).astype(np.float32),
            "b": rng.standard_normal((4,)).astype(np.float32)}


def _grads(seed):
    rng = np.random.default_rng(100 + seed)
    return {"w": rng.standard_normal((3, 4)).astype(np.float32),
            "b": rng.standard_normal((4,)).astype(np.float32)}


def _client(addrs, params=None, join=True, rank=None, **kw):
    c = ParameterClient(addrs, timeout=30.0, **kw)
    if join:
        c.join(rank=rank)
    c.init_or_fetch(params if params is not None else _init_params(),
                    OPT.to_dict(), {n: p.to_dict()
                                    for n, p in PCFGS.items()})
    return c


def _start(n_shards=1, block_size=5, **kw):
    srvs = [ParameterServer(port=0, shard_index=i, n_shards=n_shards,
                            block_size=block_size, **kw)
            for i in range(n_shards)]
    addrs = [s.start_background() for s in srvs]
    return srvs, addrs


# ---------------------------------------------------------------------------
# elastic behavior over real sockets (tier-1, deterministic)
# ---------------------------------------------------------------------------


def test_elastic_join_drain_leave_and_abrupt_death():
    srvs, addrs = _start(beat_timeout_s=60.0)
    try:
        a = _client(addrs, rank=0)
        # single member: a window commits immediately
        out = a.push_grads(_grads(0), samples=4)
        assert a.version == 1 and set(out) == {"w", "b"}

        # B joins: the next window requires BOTH
        b = _client(addrs, rank=1)
        got = {}

        def push_a():
            got["a"] = a.push_grads(_grads(1), samples=4)

        th = threading.Thread(target=push_a)
        th.start()
        time.sleep(0.2)                  # A is parked in the barrier
        assert not got
        got["b"] = b.push_grads(_grads(2), samples=4)
        th.join(timeout=30)
        assert "a" in got
        for n in ("w", "b"):
            np.testing.assert_array_equal(got["a"][n], got["b"][n])
        log = a.commit_log()
        assert [m[1] for m in log[-1]["members"]] == [0, 1]  # rank order

        # B drains: A alone commits the next window (B never stalls it)
        b.drain()
        a.push_grads(_grads(3), samples=4)
        assert a.version == 3
        b.leave()
        b.close()

        # C joins then dies ABRUPTLY with a contribution in flight: the
        # buffered grads are discarded and A's barrier re-sizes
        c = _client(addrs, rank=1)
        # send C's gradient WITHOUT barriering, then kill the sockets
        blocks = c.block_map.split_all(_grads(4), shard=0)
        from paddle_tpu.serving import wire as w_
        w_.write_frame_sync(c.socks[0], {
            "type": "send_grad", "tid": c.tid, "window": c.window,
            "samples": 4,
            "blocks": {bid: encode_array(arr)
                       for bid, arr in blocks.items()}})
        assert w_.read_frame_sync(c.socks[0])["type"] == "grad_ack"
        c.close()                        # abrupt: no drain, no leave
        out = a.push_grads(_grads(5), samples=4)   # must not deadlock
        assert a.version == 4
        log = a.commit_log()
        assert [m[1] for m in log[-1]["members"]] == [0]
        st = a.stats()
        assert st["trainers_active"] == 1
        mtext = a.metrics()
        assert "pserver_grads_discarded_total 1" in mtext
        a.leave()
        a.close()
    finally:
        for s in srvs:
            s.stop_background(drain=False)


def test_bin_blocks_negotiated_and_bit_identical_to_json():
    """ISSUE 16 satellite: the binary block framing changes BYTES ON THE
    WIRE only — a fleet driven through binary frames commits bit-identical
    parameters to one driven by a legacy JSON-only client, and a client
    that advertises nothing (old peer) keeps working against a new
    server because sending binary is hello-negotiated."""
    def run_windows(force_json):
        srvs, addrs = _start(n_shards=2)
        try:
            c = ParameterClient(addrs, timeout=30.0)
            # every new shard advertises the capability
            assert c._bin is True
            if force_json:
                c._bin = False       # what a pre-capability client sends
            c.join(rank=0)
            c.init_or_fetch(_init_params(), OPT.to_dict(),
                            {n: p.to_dict() for n, p in PCFGS.items()})
            out = None
            for w in range(3):
                out = c.push_grads(_grads(w), samples=4)
            c.leave()
            c.close()
            return out
        finally:
            for s in srvs:
                s.stop_background(drain=False)

    p_bin = run_windows(force_json=False)
    p_json = run_windows(force_json=True)
    assert set(p_bin) == set(p_json) == {"w", "b"}
    for n in p_bin:
        np.testing.assert_array_equal(p_bin[n].view(np.uint8),
                                      p_json[n].view(np.uint8))


def test_wrong_window_after_eviction_is_actionable():
    srvs, addrs = _start(beat_timeout_s=0.4)
    try:
        a = _client(addrs, rank=0, beat_interval_s=10.0)  # beats too slow
        a._beat_stop.set()               # stop beating entirely
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if a.stats()["trainers_active"] == 0:
                break
            time.sleep(0.1)
        assert a.stats()["trainers_active"] == 0, "expiry never fired"
        from paddle_tpu.pserver.client import StaleTrainerError
        with pytest.raises(StaleTrainerError, match="rejoin"):
            a.push_grads(_grads(0), samples=4)
        a.close()
    finally:
        for s in srvs:
            s.stop_background(drain=False)


def test_async_mode_staleness_guard():
    srvs, addrs = _start(mode="async", max_staleness=1)
    try:
        a = _client(addrs, rank=0)
        b = _client(addrs, rank=1)
        assert a.push_grads(_grads(0), samples=4) is None
        # b races ahead: after 3 more applies, a's base version (0) is
        # 4 behind — its next contribution must be REJECTED, not applied
        for i in range(3):
            b.push_grads(_grads(1 + i), samples=4)
            b.pull()
        v_before = b.version
        assert a.push_grads(_grads(9), samples=4) is None
        st = a.stats(0)
        assert st["version"] == v_before, "stale gradient was applied"
        m = a.metrics()
        assert "pserver_async_rejected_total 1" in m
        # after a re-pull the same trainer contributes fine
        a.pull()
        a.push_grads(_grads(10), samples=4)
        assert a.stats(0)["version"] == v_before + 1
        for cl in (a, b):
            cl.leave()
            cl.close()
    finally:
        for s in srvs:
            s.stop_background(drain=False)


# ---------------------------------------------------------------------------
# streaming checkpoints
# ---------------------------------------------------------------------------


def test_streaming_snapshot_does_not_stall_updates(tmp_path):
    """The ISSUE 14 regression pin: a snapshot in progress must not pause
    send_grad traffic.  The write is artificially slowed via the test
    seam; the client keeps committing windows THROUGH it, and the
    snapshot's own capture stays consistent (copy-on-write)."""
    srvs, addrs = _start(snapshot_dir=str(tmp_path / "ck"),
                         snapshot_every=3)
    srv = srvs[0]
    progressed = {"during": 0, "version_at_capture": None}
    release = threading.Event()

    def slow_hook(snap):
        if progressed["version_at_capture"] is None:
            progressed["version_at_capture"] = snap["version"]
        release.wait(timeout=30)

    srv._snap_hook = slow_hook
    try:
        a = _client(addrs, rank=0)
        for i in range(3):               # 3rd commit triggers the snapshot
            a.push_grads(_grads(i), samples=4)
        deadline = time.monotonic() + 10
        while not srv.snapshot_in_progress and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.snapshot_in_progress, "snapshot never started"
        # updates must keep committing while the writer is stuck
        for i in range(4):
            a.push_grads(_grads(10 + i), samples=4)
        progressed["during"] = srv.engine.version
        assert progressed["during"] >= 7, \
            "send_grad stalled during the snapshot"
        release.set()
        deadline = time.monotonic() + 30
        while srv.snapshots_written == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        # commits 3 and 6 both trigger; the event coalesces to >= 1 write
        assert srv.snapshots_written >= 1
        # the first capture froze the state AT CAPTURE TIME, version 3 —
        # immutable-array copy-on-write means later commits never leak in
        assert progressed["version_at_capture"] == 3
        from paddle_tpu.trainer.checkpoint import load_checkpoint
        out = load_checkpoint(srv.last_snapshot_path)
        assert set(out["params"]) == {"w", "b"}
        assert "momentum" in out["opt"]["slots"]["w"]
        a.leave()
        a.close()
    finally:
        for s in srvs:
            s.stop_background(drain=False)


def test_sharded_snapshot_reassembles_bit_exact(tmp_path):
    """2-shard fleet checkpoints reassemble to exactly the state a
    1-shard server reaches on the same contribution sequence — INCLUDING
    a pass boundary, which must relay to the non-coordinator shard (its
    pass_id and snapshot pass labels must not lag shard 0's)."""
    seq = [(_grads(i), 4) for i in range(5)]

    def run(n_shards, snap_dir):
        srvs, addrs = [], []
        for i in range(n_shards):
            s = ParameterServer(port=0, shard_index=i, n_shards=n_shards,
                                block_size=5, snapshot_dir=snap_dir)
            addrs.append(s.start_background())
            srvs.append(s)
        a = _client(addrs, rank=0)
        for g, n in seq[:3]:
            a.push_grads(g, n)
        assert a.pass_barrier() == 1     # relays to every shard
        for s in srvs:
            assert s.engine.pass_id == 1, \
                f"shard {s.shard_index} missed the pass boundary"
        for g, n in seq[3:]:
            a.push_grads(g, n)
        a.leave()
        a.close()
        for s in srvs:
            s.stop_background(drain=True)   # final snapshot
        return srvs

    run(1, str(tmp_path / "one"))
    run(2, str(tmp_path / "two"))
    from paddle_tpu.trainer.checkpoint import (latest_checkpoint,
                                               load_checkpoint)
    ref = load_checkpoint(latest_checkpoint(str(tmp_path / "one")))
    import os
    shard0 = os.path.join(str(tmp_path / "two"), "shard-00")
    label = os.path.basename(latest_checkpoint(shard0))
    params, opt = assemble_sharded_checkpoint(str(tmp_path / "two"), label)
    for n in ref["params"]:
        np.testing.assert_array_equal(params[n], ref["params"][n])
    for n in ref["opt"]["slots"]:
        for k in ref["opt"]["slots"][n]:
            np.testing.assert_array_equal(opt["slots"][n][k],
                                          ref["opt"]["slots"][n][k])
    assert int(opt["num_updates"]) == int(ref["opt"]["num_updates"])


# ---------------------------------------------------------------------------
# misconnected peers get actionable refusals (both directions)
# ---------------------------------------------------------------------------


def test_wrong_role_connect_names_both_roles():
    srvs, addrs = _start()
    try:
        # a SERVING client pointed at a pserver: the op is refused with
        # the role named, the connection survives
        from paddle_tpu.serving.client import ServerError, ServingClient
        sc = ServingClient(addrs[0][0], addrs[0][1])
        assert sc.hello()["role"] == "pserver"
        with pytest.raises(ServerError, match="parameter server"):
            sc.generate([1, 2, 3], max_new=4)
        sc.close()
        # a PSERVER client pointed at... itself is fine; the negative
        # (pserver client at a serving replica) rides connect_with_backoff
        # expect_role and is covered without booting a full engine by the
        # role-mismatch error below
        from paddle_tpu.serving.client import connect_with_backoff
        sock, hello = connect_with_backoff(addrs[0][0], addrs[0][1], 10.0,
                                           expect_role="pserver")
        assert hello["role"] == "pserver"
        sock.close()
        with pytest.raises(ConnectionError, match="pserver.*not the "
                                                  "expected.*replica|is a"):
            connect_with_backoff(addrs[0][0], addrs[0][1], 10.0,
                                 expect_role="replica")
    finally:
        for s in srvs:
            s.stop_background(drain=False)


def test_pserver_client_refuses_serving_replica():
    """The satellite's headline case: a trainer pointed at a serving
    replica port must fail NAMING both roles, not with a frame error."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving.server import ServingServer
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    tr = Trainer(cfg, seed=7)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    srv = ServingServer(eng)
    host, port = srv.start_background()
    try:
        with pytest.raises(ConnectionError) as ei:
            ParameterClient([(host, port)], timeout=10.0)
        msg = str(ei.value)
        assert "serving replica" in msg and "parameter server" in msg
    finally:
        srv.stop_background(drain=False)


def test_async_multi_shard_refused():
    """Per-shard async staleness decisions could silently half-apply a
    gradient — multi-shard async is refused loudly at construction."""
    with pytest.raises(ValueError, match="half-applied"):
        ParameterServer(mode="async", n_shards=2, shard_index=0)


def test_restarted_shard_mixed_init_is_loud(tmp_path):
    """A shard that lost its state mid-job must NOT let a joiner train
    on a silent mix of trained and fresh-init blocks."""
    s0 = ParameterServer(port=0, shard_index=0, n_shards=2, block_size=5)
    s1 = ParameterServer(port=0, shard_index=1, n_shards=2, block_size=5)
    a0 = s0.start_background()
    a1 = s1.start_background()
    try:
        a = _client([a0, a1], rank=0)
        a.push_grads(_grads(0), samples=4)
        a.leave()
        a.close()
        # shard 1 "restarts" empty
        s1.stop_background(drain=False)
        s1b = ParameterServer(port=0, shard_index=1, n_shards=2,
                              block_size=5)
        a1b = s1b.start_background()
        from paddle_tpu.pserver.client import PServerError
        with pytest.raises(PServerError, match="restarted mid-job"):
            _client([a0, a1b], rank=1)
        s1b.stop_background(drain=False)
    finally:
        s0.stop_background(drain=False)


def test_joiner_pull_waits_for_commit_relay():
    """A joiner pulling between a coordinator commit and the commit-set
    relay must not assemble a mixed-version parameter state: the
    non-coordinator shard parks the version-gated read until the relay
    lands."""
    from paddle_tpu.serving import wire as w_

    s0 = ParameterServer(port=0, shard_index=0, n_shards=2, block_size=5)
    s1 = ParameterServer(port=0, shard_index=1, n_shards=2, block_size=5)
    a0 = s0.start_background()
    a1 = s1.start_background()
    try:
        a = _client([a0, a1], rank=0)
        # push window 0 by hand: grads to BOTH shards, barrier at shard
        # 0 (commits there) — but do NOT relay to shard 1 yet
        for s, sock in enumerate(a.socks):
            blocks = {}
            for name in a.block_map.names():
                blocks.update(a.block_map.split(name, _grads(0)[name],
                                                shard=s))
            w_.write_frame_sync(sock, {
                "type": "send_grad", "tid": a.tid, "window": 0,
                "samples": 4,
                "blocks": {bid: encode_array(arr)
                           for bid, arr in blocks.items()}})
            assert w_.read_frame_sync(sock)["type"] == "grad_ack"
        w_.write_frame_sync(a.socks[0], {"type": "barrier", "tid": a.tid,
                                         "window": 0})
        reply = w_.read_frame_sync(a.socks[0])
        assert reply["type"] == "barrier" and reply["version"] == 1
        assert s1.engine.version == 0       # relay withheld

        # joiner pulls NOW: must block until the relay, not mix v1+v0
        b = ParameterClient([a0, a1], timeout=30.0)
        b.join(rank=1)
        got = {}

        def join_pull():
            got["params"] = b.init_or_fetch(
                _init_params(), OPT.to_dict(),
                {n: p.to_dict() for n, p in PCFGS.items()})

        th = threading.Thread(target=join_pull)
        th.start()
        time.sleep(0.3)
        assert "params" not in got, "joiner read a mixed-version state"
        # now relay the commit set; the parked pull completes
        w_.write_frame_sync(a.socks[1], {
            "type": "get_params", "want": "params",
            "apply": {"window": 0, "members": reply["members"]}})
        assert w_.read_frame_sync(a.socks[1])["type"] == "params"
        th.join(timeout=30)
        assert "params" in got
        # both shards at version 1: the joiner's state is consistent
        ref = {}
        for s, sock in enumerate(a.socks):
            w_.write_frame_sync(sock, {"type": "get_params",
                                       "want": "params"})
            r = w_.read_frame_sync(sock)
            assert r["version"] == 1
            for bid, d in r["blocks"].items():
                ref[bid] = decode_array(d)
        ref = a.block_map.assemble_all(ref)
        for n in ref:
            np.testing.assert_array_equal(got["params"][n], ref[n])
        for cl in (a, b):
            cl.close()
    finally:
        s0.stop_background(drain=False)
        s1.stop_background(drain=False)


def test_engine_refuses_updater_hooks():
    bm = BlockMap.from_arrays(_init_params(), 1, block_size=5)
    bad = {"w": ParameterConfig(name="w", size=12, dims=[3, 4],
                                update_hooks=[{"type": "pruning",
                                               "sparsity_ratio": 0.5}]),
           "b": PCFGS["b"]}
    with pytest.raises(NotImplementedError, match="hooks"):
        UpdateEngine(bm, 0, OPT, bad,
                     bm.split_all(_init_params()))


# ---------------------------------------------------------------------------
# straggler detection + the wedged-update-thread path (ISSUE 15)
# ---------------------------------------------------------------------------


def test_window_skew_histogram_and_straggler_event():
    """The shard-0 coordinator measures per-window barrier-arrival skew;
    past straggler_ms, a `straggler` flight event NAMES the late rank."""
    from paddle_tpu.obs.flight import get_flight_recorder

    fr = get_flight_recorder()
    was_enabled = fr.enabled
    fr.enabled = True
    n0 = fr.recorded
    srvs, addrs = _start(beat_timeout_s=60.0, straggler_ms=50.0)
    try:
        a = _client(addrs, rank=0)
        b = _client(addrs, rank=1)
        got = {}

        def push_a():
            got["a"] = a.push_grads(_grads(0), samples=4)

        th = threading.Thread(target=push_a)
        th.start()
        time.sleep(0.3)                  # rank 1 is the straggler
        got["b"] = b.push_grads(_grads(1), samples=4)
        th.join(timeout=30)
        assert "a" in got
        events = [e for e in fr.snapshot()
                  if e["kind"] == "straggler" and e["seq"] >= n0]
        assert len(events) == 1
        assert events[0]["data"]["rank"] == 1        # the LATE rank
        assert events[0]["data"]["skew_ms"] >= 100.0
        m = a.metrics()
        assert "pserver_window_skew_ms_count 1" in m
        st = a.stats()
        assert st["last_skew_ms"] >= 100.0
        assert st["straggler_ms"] == 50.0
        # the barrier reply fed the skew into the client's attribution
        assert a.last_timing["skew_ms"] >= 100.0
        for cl in (a, b):
            cl.leave()
            cl.close()
    finally:
        fr.enabled = was_enabled
        for s in srvs:
            s.stop_background(drain=False)


def test_wedged_update_thread_stale_ok_one_bundle_per_episode(tmp_path):
    """ISSUE 15 satellite — the serving wedge e2e, ported to the
    pserver: a deliberately wedged optimizer apply leaves stats/metrics/
    trace RPCs answerable stale-ok on the loop thread, the watchdog's
    lag gauge grows, EXACTLY one postmortem bundle freezes per episode
    (role-aware in tools/postmortem.py), and releasing the wedge lets
    the barrier commit and re-arms the watchdog for the next episode."""
    import os

    from paddle_tpu.obs import Tracer
    from paddle_tpu.obs.flight import get_flight_recorder, load_bundle
    from paddle_tpu.serving.client import ServingClient
    from tools.postmortem import render

    fr = get_flight_recorder()
    was_enabled = fr.enabled
    fr.enabled = True
    tracer = Tracer()
    tracer.enabled = True
    srvs, addrs = _start(beat_timeout_s=60.0, wedge_threshold_s=0.5,
                         snapshot_dir=str(tmp_path), tracer=tracer)
    srv = srvs[0]

    def bundles():
        return sorted(p for p in os.listdir(tmp_path)
                      if p.startswith("postmortem-"))

    try:
        a = _client(addrs, rank=0)
        orig = srv.engine.commit
        gate = {"wedged": threading.Event(), "release": threading.Event()}

        def commit_wedged(entries, **kw):
            gate["wedged"].set()
            assert gate["release"].wait(60), "wedge never released"
            return orig(entries, **kw)

        srv.engine.commit = commit_wedged
        got = {}
        th = threading.Thread(
            target=lambda: got.update(out=a.push_grads(_grads(0),
                                                       samples=4)))
        th.start()
        assert gate["wedged"].wait(10), "update thread never picked up"
        # stale-ok frames answer on the LOOP thread while the update
        # thread is stuck, and the lag gauge grows between reads
        with ServingClient(addrs[0][0], addrs[0][1], timeout=10) as c:
            st1 = c.stats()
            assert st1["update_alive"] is True
            assert st1["update_lag_s"] >= 0.0
            time.sleep(0.3)
            st2 = c.stats()
            assert st2["update_lag_s"] > st1["update_lag_s"]
            mtext = c.metrics()
            assert "pserver_update_lag_s" in mtext
            assert "pserver_update_alive 1" in mtext
            pull = c.trace()             # answers against the wedge
            assert pull["process"]["role"] == "pserver"
        # exactly ONE bundle at the threshold, not one per poll
        deadline = time.monotonic() + 10
        while not bundles() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(bundles()) == 1, "no bundle at the wedge threshold"
        time.sleep(0.5)                  # > watchdog poll period
        assert len(bundles()) == 1, \
            "a sustained wedge must be one bundle, not one per poll"
        b = load_bundle(str(tmp_path / bundles()[0]))
        assert b["meta"]["reason"] == "update_wedge"
        assert "update thread wedged" in b["meta"]["error"]
        assert "ps_wedge" in [e["kind"] for e in b["events"]]
        # the bundle renders ROLE-AWARE: membership table + update-
        # thread state + window counters, not the serving slots layout
        txt = render(b)
        assert "pserver: shard 0/1" in txt
        assert "update thread: WEDGED" in txt
        assert "rank 0" in txt
        assert "slots" not in txt.split("events:")[0]
        # release: the parked barrier commits and the client advances
        gate["release"].set()
        th.join(timeout=30)
        assert got.get("out") is not None
        assert a.version == 1
        # recovery re-arms the episode latch: a SECOND wedge freezes a
        # second bundle
        deadline = time.monotonic() + 5
        while srv._wedge_dumped and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not srv._wedge_dumped, "watchdog never re-armed"
        gate["wedged"] = threading.Event()
        gate["release"] = threading.Event()
        th2 = threading.Thread(
            target=lambda: got.update(out2=a.push_grads(_grads(1),
                                                        samples=4)))
        th2.start()
        assert gate["wedged"].wait(10)
        deadline = time.monotonic() + 10
        while len(bundles()) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert len(bundles()) == 2, "second episode must dump again"
        gate["release"].set()
        th2.join(timeout=30)
        assert got.get("out2") is not None
        a.leave()
        a.close()
    finally:
        fr.enabled = was_enabled
        for s in srvs:
            s.stop_background(drain=False)
