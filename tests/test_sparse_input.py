"""Sparse-row input path (ref: paddle/math/SparseRowMatrix.h:31-301;
python/paddle/trainer/PyDataProvider2.py:57-107 sparse_binary_vector /
sparse_vector): sparse slots are packed as (ids, vals) with memory ∝ nnz,
and fc/mixed gather parameter rows instead of densifying — the reference's
whole point for these types is 100k+-dim vocabularies."""

import os
import sys

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.data.feeder import make_batch
from paddle_tpu.data.provider import (integer_value, sparse_binary_vector,
                                      sparse_vector)
from paddle_tpu.parameter.argument import Argument


def test_packing_memory_prop_nnz():
    """A 200k-dim slot with <=6 nonzeros packs to K=8 columns, not 200k."""
    dim = 200_000
    samples = [([5, 17, 199_999], 0), ([2, 3, 4, 5, 6, 7], 1)]
    b = make_batch(samples, [sparse_binary_vector(dim), integer_value(2)],
                   ["word", "label"])
    arg = b["word"]
    assert arg.sparse_dim == dim
    assert arg.ids.shape == (2, 8)          # bucketed nnz, NOT dim
    assert arg.sparse_vals.shape == (2, 8)
    assert arg.value is None                # never densified
    np.testing.assert_array_equal(arg.sparse_vals[0, :3], 1.0)
    np.testing.assert_array_equal(arg.sparse_vals[0, 3:], 0.0)


def test_sparse_fc_matches_dense():
    """fc over the sparse representation == fc over the dense multi-hot."""
    from paddle_tpu.graph.layers_core import _input_matmul

    rng = np.random.default_rng(0)
    dim, dout, B = 64, 5, 3
    w = rng.normal(size=(dim, dout)).astype(np.float32)
    rows = [[1, 7, 63], [0], [10, 11]]
    samples = [(r, 0) for r in rows]
    arg = make_batch(samples, [sparse_binary_vector(dim), integer_value(2)],
                     ["word", "label"])["word"]

    dense = np.zeros((B, dim), np.float32)
    for i, r in enumerate(rows):
        dense[i, r] = 1.0

    got = np.asarray(_input_matmul(arg, w))
    np.testing.assert_allclose(got, dense @ w, rtol=1e-5, atol=1e-6)

    # weighted (sparse_vector) variant
    pairs = [[(1, 0.5), (7, -2.0)], [(0, 3.0)], [(10, 1.0), (11, 1.0)]]
    argv = make_batch([(p, 0) for p in pairs],
                      [sparse_vector(dim), integer_value(2)],
                      ["word", "label"])["word"]
    densev = np.zeros((B, dim), np.float32)
    for i, ps in enumerate(pairs):
        for j, v in ps:
            densev[i, j] = v
    np.testing.assert_allclose(np.asarray(_input_matmul(argv, w)),
                               densev @ w, rtol=1e-5, atol=1e-6)

    # to_dense escape hatch round-trips
    np.testing.assert_allclose(np.asarray(argv.to_dense().value), densev,
                               rtol=1e-6)


def test_sparse_grad_touches_only_gathered_rows():
    """Backward through the gather is a scatter-add into the nnz rows only."""
    from paddle_tpu.graph.layers_core import _input_matmul

    dim, dout = 1000, 4
    w = np.ones((dim, dout), np.float32)
    arg = make_batch([([3, 900], 0)],
                     [sparse_binary_vector(dim), integer_value(2)],
                     ["word", "label"])["word"]

    g = jax.grad(lambda p: _input_matmul(arg, p).sum())(w)
    g = np.asarray(g)
    touched = set(np.flatnonzero(np.abs(g).sum(-1)).tolist())
    assert touched == {3, 900}   # padding slots are zero-weighted: no grad
    np.testing.assert_array_equal(g[0], 0.0)


def test_sparse_sequence_through_recurrent_group():
    """A sparse_binary_vector_sequence in_link keeps its sparse-row
    structure through recurrent_group per-step slicing (fc in the step
    gathers rows; padding slots contribute nothing)."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.graph.builder import GraphExecutor
    from paddle_tpu.data.provider import sparse_binary_vector_sequence

    dim = 512
    cfg_src = f"""
from paddle_tpu.dsl import *
settings(batch_size=2, learning_rate=0.1)
feats = data_layer(name="feats", size={dim})
def step(y):
    mem = memory(name="state", size=8)
    return fc_layer(input=[y, mem], size=8, act=TanhActivation(),
                    bias_attr=True, name="state")
out = recurrent_group(name="rg", step=step, input=feats)
rep = last_seq(input=out)
prob = fc_layer(size=2, input=rep, act=SoftmaxActivation(), bias_attr=True)
classification_cost(input=prob, label=data_layer(name="label", size=2))
"""
    path = os.path.join(REPO, "tests", "_sparse_seq_rg.py")
    with open(path, "w") as f:
        f.write(cfg_src)
    try:
        cfg = parse_config(path, "")
        ex = GraphExecutor(cfg.model_config)
        params = ex.init_params(jax.random.PRNGKey(0))
        seqs = [[[1, 5], [7], [2, 3, 8]], [[0], [dim - 1, 4]]]
        batch = make_batch([(s, 0) for s in seqs],
                           [sparse_binary_vector_sequence(dim),
                            integer_value(2)],
                           ["feats", "label"])
        loss, _ = ex.loss(params, batch)
        assert np.isfinite(float(loss))

        # oracle: dense multi-hot feed produces the identical loss
        T = batch["feats"].ids.shape[1]
        dense = np.zeros((2, T, dim), np.float32)
        for i, s in enumerate(seqs):
            for j, row in enumerate(s):
                dense[i, j, row] = 1.0
        dense_batch = dict(batch)
        dense_batch["feats"] = Argument(value=dense,
                                        lengths=batch["feats"].lengths)
        dloss, _ = ex.loss(params, dense_batch)
        np.testing.assert_allclose(float(loss), float(dloss), rtol=1e-5)
    finally:
        os.remove(path)


def test_sparse_value_sequence_matches_dense():
    """sparse_vector_sequence packs per-timestep (id, value) rows as
    [B, T, K] with memory ∝ nnz; fc over it == fc over the dense sequence,
    and the gradient scatter-adds into only the touched rows."""
    from paddle_tpu.data.provider import sparse_vector_sequence
    from paddle_tpu.graph.layers_core import _input_matmul

    rng = np.random.default_rng(2)
    dim, dout = 128, 4
    w = rng.normal(size=(dim, dout)).astype(np.float32)
    seqs = [
        [[(1, 0.5), (7, -2.0)], [(0, 3.0)]],             # len 2
        [[(10, 1.5)], [(11, -1.0), (12, 2.0)], [(127, 4.0)]],  # len 3
    ]
    b = make_batch([(s, 0) for s in seqs],
                   [sparse_vector_sequence(dim), integer_value(2)],
                   ["feats", "label"])
    arg = b["feats"]
    B, T, K = arg.ids.shape
    assert (B, K) == (2, 8) and arg.sparse_dim == dim and arg.value is None
    np.testing.assert_array_equal(np.asarray(arg.lengths), [2, 3])

    dense = np.zeros((B, T, dim), np.float32)
    for i, s in enumerate(seqs):
        for j, row in enumerate(s):
            for c, v in row:
                dense[i, j, c] = v
    np.testing.assert_allclose(np.asarray(_input_matmul(arg, w)),
                               dense @ w, rtol=1e-5, atol=1e-6)

    g = np.asarray(jax.grad(lambda p: _input_matmul(arg, p).sum())(w))
    gd = np.asarray(jax.grad(lambda p: jnp_matmul_sum(dense, p))(w))
    np.testing.assert_allclose(g, gd, rtol=1e-5, atol=1e-6)
    touched = set(np.flatnonzero(np.abs(g).sum(-1)).tolist())
    assert touched == {1, 7, 0, 10, 11, 12, 127}


def jnp_matmul_sum(x, p):
    import jax.numpy as jnp
    return jnp.matmul(x, p).sum()


def test_sparse_subsequence_slots_match_dense():
    """sparse_{binary,}_vector_sub_sequence pack as [B, S, T, K] ids+vals
    with lengths (#subseqs) and sub_lengths (tokens per subseq); fc over
    them == fc over the dense [B, S, T, dim] oracle (ref:
    PyDataProvider2.py:57-107 — the full input-type × sequence-level
    matrix)."""
    from paddle_tpu.data.provider import (
        sparse_binary_vector_sub_sequence, sparse_vector_sub_sequence)
    from paddle_tpu.graph.layers_core import _input_matmul

    rng = np.random.default_rng(3)
    dim, dout = 96, 3
    w = rng.normal(size=(dim, dout)).astype(np.float32)

    # binary: doc = list of sentences, sentence = list of sparse rows
    docs = [
        [[[1, 5], [7]], [[2, 3, 95]]],          # 2 subseqs, lens 2/1
        [[[0]]],                                # 1 subseq, len 1
    ]
    b = make_batch([(d, 0) for d in docs],
                   [sparse_binary_vector_sub_sequence(dim), integer_value(2)],
                   ["feats", "label"])
    arg = b["feats"]
    B, S, T, K = arg.ids.shape
    assert arg.sparse_dim == dim and arg.value is None
    np.testing.assert_array_equal(np.asarray(arg.lengths), [2, 1])
    assert np.asarray(arg.sub_lengths)[0, 0] == 2
    dense = np.zeros((B, S, T, dim), np.float32)
    for i, d in enumerate(docs):
        for j, ss in enumerate(d):
            for k, row in enumerate(ss):
                dense[i, j, k, row] = 1.0
    np.testing.assert_allclose(np.asarray(_input_matmul(arg, w)),
                               dense @ w, rtol=1e-5, atol=1e-6)

    # weighted variant + gradient parity with the dense oracle
    docsv = [
        [[[(1, 0.5)], [(7, -2.0), (8, 1.0)]]],
        [[[(0, 3.0)]], [[(90, 1.0)], [(91, -1.0)]]],
    ]
    argv = make_batch([(d, 0) for d in docsv],
                      [sparse_vector_sub_sequence(dim), integer_value(2)],
                      ["feats", "label"])["feats"]
    B, S, T, K = argv.ids.shape
    densev = np.zeros((B, S, T, dim), np.float32)
    for i, d in enumerate(docsv):
        for j, ss in enumerate(d):
            for k, row in enumerate(ss):
                for c, v in row:
                    densev[i, j, k, c] = v
    np.testing.assert_allclose(np.asarray(_input_matmul(argv, w)),
                               densev @ w, rtol=1e-5, atol=1e-6)
    g = np.asarray(jax.grad(lambda p: _input_matmul(argv, p).sum())(w))
    gd = np.asarray(jax.grad(lambda p: jnp_matmul_sum(densev, p))(w))
    np.testing.assert_allclose(g, gd, rtol=1e-5, atol=1e-6)
    # to_dense escape hatch round-trips the nested layout
    np.testing.assert_allclose(np.asarray(argv.to_dense().value), densev,
                               rtol=1e-6)


def test_dict_samples_match_tuple_samples():
    """Providers may yield dict samples keyed by slot name instead of
    aligned tuples (ref: PyDataProvider2.cpp dict-yield support); both
    forms must assemble identical batches."""
    from paddle_tpu.data.provider import sparse_vector_sequence

    dim = 32
    seqs = [[[(1, 0.5)], [(2, -1.0), (3, 2.0)]], [[(0, 1.0)]]]
    labels = [0, 1]
    types = [sparse_vector_sequence(dim), integer_value(2)]
    names = ["feats", "label"]
    bt = make_batch(list(zip(seqs, labels)), types, names)
    bd = make_batch([{"feats": s, "label": l} for s, l in zip(seqs, labels)],
                    types, names)
    for k in bt:
        for f in ("ids", "sparse_vals", "lengths"):
            a, b = getattr(bt[k], f), getattr(bd[k], f)
            if a is not None:
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_quick_start_lr_at_100k_vocab():
    """The quick_start LR shape trains at dict_dim=200k: memory ∝ nnz."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.trainer.trainer import Trainer

    dim = 200_000
    cfg_src = f"""
from paddle_tpu.dsl import *
settings(batch_size=8, learning_rate=2e-3, learning_method=AdamOptimizer())
data = data_layer(name="word", size={dim})
output = fc_layer(input=data, size=2, act=SoftmaxActivation())
classification_cost(input=output, label=data_layer(name="label", size=2))
"""
    path = os.path.join(REPO, "tests", "_qs_lr_100k.py")
    with open(path, "w") as f:
        f.write(cfg_src)
    try:
        cfg = parse_config(path, "")
        tr = Trainer(cfg, seed=0)
        rng = np.random.default_rng(0)

        def batches():
            for _ in range(8):
                samples = []
                for _ in range(8):
                    label = int(rng.integers(0, 2))
                    lo, hi = (0, dim // 2) if label == 0 else (dim // 2, dim)
                    words = sorted(set(rng.integers(lo, hi, 20).tolist()))
                    samples.append((words, label))
                yield make_batch(
                    samples, [sparse_binary_vector(dim), integer_value(2)],
                    ["word", "label"])

        c0 = tr.train_one_pass(batches=batches(), log_period=0)["cost"]
        st = c0
        for _ in range(4):
            st = tr.train_one_pass(batches=batches(), log_period=0)["cost"]
        assert st < c0, (c0, st)
    finally:
        os.remove(path)
