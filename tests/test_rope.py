"""Rotary position embedding oracles — NEW capability beyond the reference.

1. relative-position property: RoPE'd q·k must depend only on the offset
   (q_pos - k_pos), not absolute positions.
2. norm preservation: rotation never changes vector norms.
3. cross-implementation parity: dense vs flash vs ring with global shard
   positions all agree on roped inputs.
4. end-to-end: a DSL model with use_rope trains, and can recover a task
   that NEEDS position information (unlike bare attention, which is
   permutation-equivariant over keys).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import dot_product_attention, rope
from paddle_tpu.ops.pallas_attention import flash_attention


def test_relative_position_property():
    rng = np.random.default_rng(0)
    D = 16
    q = jnp.asarray(rng.normal(size=(1, 1, 1, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 1, D)), jnp.float32)

    def score(qpos, kpos):
        qr = rope(q, jnp.asarray([qpos]))
        kr = rope(k, jnp.asarray([kpos]))
        return float(jnp.sum(qr * kr))

    # same offset, different absolute positions -> same score
    np.testing.assert_allclose(score(7, 3), score(104, 100), rtol=1e-5)
    np.testing.assert_allclose(score(5, 5), score(400, 400), rtol=1e-5)
    # different offsets -> different scores
    assert abs(score(7, 3) - score(7, 5)) > 1e-4


def test_norm_preserved():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 9, 3, 8)), jnp.float32)
    r = rope(x, jnp.arange(9))
    np.testing.assert_allclose(np.linalg.norm(np.asarray(r), axis=-1),
                               np.linalg.norm(np.asarray(x), axis=-1),
                               rtol=1e-5)


def test_impl_parity_on_roped_inputs():
    rng = np.random.default_rng(2)
    B, T, H, D = 2, 32, 2, 16
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    qr, kr = rope(q, jnp.arange(T)), rope(k, jnp.arange(T))

    want = dot_product_attention(qr, kr, v, causal=True)
    got = flash_attention(qr, kr, v, causal=True, block_q=8, block_k=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_shard_positions_match_global(monkeypatch):
    """rope applied to the FULL sequence before sharding == per-shard rope
    with global positions (what a context-parallel caller must use)."""
    rng = np.random.default_rng(3)
    T, n = 16, 4
    x = jnp.asarray(rng.normal(size=(1, T, 2, 8)), jnp.float32)
    whole = rope(x, jnp.arange(T))
    Tl = T // n
    shards = [rope(x[:, i * Tl:(i + 1) * Tl], jnp.arange(i * Tl, (i + 1) * Tl))
              for i in range(n)]
    np.testing.assert_allclose(np.asarray(jnp.concatenate(shards, axis=1)),
                               np.asarray(whole), rtol=1e-6)


def test_rope_model_learns_positional_task():
    """Label = sign of the FIRST token's feature.  Bare mean-pooled
    attention cannot distinguish token order; RoPE makes it learnable."""
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.dsl import (
        AdamOptimizer, SoftmaxActivation, classification_cost, data_layer,
        fc_layer, multi_head_attention_layer, pooling_layer, settings,
    )
    from paddle_tpu.dsl.poolings import MaxPooling
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        settings(batch_size=8, learning_rate=0.05,
                 learning_method=AdamOptimizer())
        x = data_layer(name="x", size=16)
        a = multi_head_attention_layer(x, size=16, num_heads=4,
                                       use_rope=True, causal=True)
        p = pooling_layer(input=a, pooling_type=MaxPooling())
        out = fc_layer(input=p, size=2, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=2))

    cfg = parse_config_callable(conf)
    tr = Trainer(cfg, seed=0)
    rng = np.random.default_rng(0)
    T = 12
    data = []
    for _ in range(5):
        x = rng.normal(size=(8, T, 16)).astype(np.float32)
        y = (x[:, 0, 0] > 0).astype(np.int32)
        data.append({"x": Argument(value=x,
                                   lengths=np.full((8,), T, np.int32)),
                     "y": Argument(ids=y)})
    hist = [float(np.mean([tr.train_one_batch(b) for b in data]))
            for _ in range(15)]
    assert np.isfinite(hist).all()
    assert hist[-1] < hist[0] * 0.5, (hist[0], hist[-1])
