"""Rotary position embedding oracles — NEW capability beyond the reference.

1. relative-position property: RoPE'd q·k must depend only on the offset
   (q_pos - k_pos), not absolute positions.
2. norm preservation: rotation never changes vector norms.
3. cross-implementation parity: dense vs flash vs ring with global shard
   positions all agree on roped inputs.
4. end-to-end: a DSL model with use_rope trains, and can recover a task
   that NEEDS position information (unlike bare attention, which is
   permutation-equivariant over keys).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops.attention import dot_product_attention, rope
from paddle_tpu.ops.pallas_attention import flash_attention


def test_relative_position_property():
    rng = np.random.default_rng(0)
    D = 16
    q = jnp.asarray(rng.normal(size=(1, 1, 1, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(1, 1, 1, D)), jnp.float32)

    def score(qpos, kpos):
        qr = rope(q, jnp.asarray([qpos]))
        kr = rope(k, jnp.asarray([kpos]))
        return float(jnp.sum(qr * kr))

    # same offset, different absolute positions -> same score
    np.testing.assert_allclose(score(7, 3), score(104, 100), rtol=1e-5)
    np.testing.assert_allclose(score(5, 5), score(400, 400), rtol=1e-5)
    # different offsets -> different scores
    assert abs(score(7, 3) - score(7, 5)) > 1e-4


def test_norm_preserved():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 9, 3, 8)), jnp.float32)
    r = rope(x, jnp.arange(9))
    np.testing.assert_allclose(np.linalg.norm(np.asarray(r), axis=-1),
                               np.linalg.norm(np.asarray(x), axis=-1),
                               rtol=1e-5)


def test_impl_parity_on_roped_inputs():
    rng = np.random.default_rng(2)
    B, T, H, D = 2, 32, 2, 16
    q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
    qr, kr = rope(q, jnp.arange(T)), rope(k, jnp.arange(T))

    want = dot_product_attention(qr, kr, v, causal=True)
    got = flash_attention(qr, kr, v, causal=True, block_q=8, block_k=8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ring_shard_positions_match_global(monkeypatch):
    """rope applied to the FULL sequence before sharding == per-shard rope
    with global positions (what a context-parallel caller must use)."""
    rng = np.random.default_rng(3)
    T, n = 16, 4
    x = jnp.asarray(rng.normal(size=(1, T, 2, 8)), jnp.float32)
    whole = rope(x, jnp.arange(T))
    Tl = T // n
    shards = [rope(x[:, i * Tl:(i + 1) * Tl], jnp.arange(i * Tl, (i + 1) * Tl))
              for i in range(n)]
    np.testing.assert_allclose(np.asarray(jnp.concatenate(shards, axis=1)),
                               np.asarray(whole), rtol=1e-6)


def test_layer_use_rope_not_a_noop(monkeypatch):
    """use_rope must actually rotate q/k in the layer path: (a) a spy on
    ops.attention.rope records the calls, (b) with identical params the
    layer output differs between use_rope on/off, and (c) the model trains
    with finite decreasing loss."""
    import paddle_tpu.ops.attention as attn_mod
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.dsl import (
        AdamOptimizer, SoftmaxActivation, classification_cost, data_layer,
        fc_layer, multi_head_attention_layer, pooling_layer, settings,
    )
    from paddle_tpu.dsl.poolings import MaxPooling
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    calls = []
    real = attn_mod.rope
    monkeypatch.setattr(attn_mod, "rope",
                        lambda *a, **kw: (calls.append(1), real(*a, **kw))[1])

    def conf(with_rope):
        def f():
            settings(batch_size=8, learning_rate=0.05,
                     learning_method=AdamOptimizer())
            x = data_layer(name="x", size=16)
            a = multi_head_attention_layer(x, size=16, num_heads=4,
                                           use_rope=with_rope, causal=True)
            p = pooling_layer(input=a, pooling_type=MaxPooling())
            out = fc_layer(input=p, size=2, act=SoftmaxActivation())
            classification_cost(input=out,
                                label=data_layer(name="y", size=2))
        return f

    rng = np.random.default_rng(0)
    T = 12
    x = rng.normal(size=(8, T, 16)).astype(np.float32)
    batch = {"x": Argument(value=x, lengths=np.full((8,), T, np.int32)),
             "y": Argument(ids=(x[:, 0, 0] > 0).astype(np.int32))}

    tr_on = Trainer(parse_config_callable(conf(True)), seed=0)
    tr_off = Trainer(parse_config_callable(conf(False)), seed=0)
    # identical initial params (same seed/graph shapes) -> any output
    # difference is RoPE's doing
    for k in tr_on.params:
        np.testing.assert_array_equal(np.asarray(tr_on.params[k]),
                                      np.asarray(tr_off.params[k]))
    loss_on = float(tr_on.train_one_batch(batch))
    n_calls = len(calls)
    loss_off = float(tr_off.train_one_batch(batch))
    assert n_calls >= 2, "rope was not invoked for q and k"
    assert len(calls) == n_calls, "rope invoked with use_rope=False"
    assert abs(loss_on - loss_off) > 1e-6, "use_rope did not change the model"

    losses = [loss_on] + [float(tr_on.train_one_batch(batch))
                          for _ in range(9)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
