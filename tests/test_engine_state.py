"""EngineState pytree regressions: the zero-restaging hot path and the
mid-flight checkpoint/restore (fleet-migration) primitive.

Two contracts from the EngineState refactor:

  * a STEADY pure-decode run re-stages NOTHING from the host — pos/gen/
    last-token advance on device, keys are indexed by the device gen
    counter, and the page table re-uploads only when a host-side table
    write (admission/COW/preempt/retire/page-boundary growth) bumps
    `PagedKVCache.version`.  The engine's `_stage` chokepoint counts every
    host->device transfer, and a module-level jnp proxy double-checks no
    staging path bypasses it;
  * `checkpoint_state()` / `restore_state()` freeze an engine MID-FLIGHT
    (queued + decoding + mid-chunk-prefill slots) and a fresh engine of
    the same configuration resumes and finishes BIT-EXACTLY what the
    uninterrupted engine produces — key schedules, admit_seq preemption
    order, allocator free-list order and the prefix index all survive.
"""

import numpy as np
import pytest

import jax

import paddle_tpu.serving.engine as engine_mod
from paddle_tpu.config.parser import parse_config
from paddle_tpu.serving import Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer


def _make(args: str):
    cfg = parse_config("demo/model_zoo/transformer_lm.py", args)
    return Trainer(cfg, seed=7)


def _prompts(lens, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(2, vocab, n).astype(np.int32) for n in lens]


class _CountingJnp:
    """Proxy for the engine module's `jnp` binding: counts asarray calls
    (the host->device staging primitive) while delegating everything
    else — compiled steps never re-trace in the steady state, so any
    count during the window is a genuine per-step transfer."""

    def __init__(self, real):
        self._real = real
        self.asarray_calls = 0

    def asarray(self, *a, **kw):
        self.asarray_calls += 1
        return self._real.asarray(*a, **kw)

    def __getattr__(self, name):
        return getattr(self._real, name)


def test_pure_decode_steps_restage_nothing(monkeypatch):
    """The satellite regression: across a window of pure-decode steps
    with no admission/retire/pause and no page-boundary crossing, the
    engine performs ZERO host->device transfers — both by its own
    `n_host_stages` counter and by the jnp.asarray proxy."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=3")
    # page_size 32: after the 4-token prompts commit, decode positions
    # 4..31 stay inside the first page — no try_grow allocation (and so
    # no table-version bump) for the whole window
    eng = ServingEngine(tr.executor, tr.params, num_slots=3, page_size=32,
                        max_context=64)
    for i, p in enumerate(_prompts((4, 4, 4), 31, seed=1)):
        eng.add_request(Request(i, p, max_new=20))
    # admit + commit every prompt (mixed steps), then one settling PURE
    # decode step so the run mask and slot arrays are staged and cached
    while not all(sl is not None and sl.gen >= 1 for sl in eng.slots):
        assert eng.step()
    assert eng.step()

    proxy = _CountingJnp(engine_mod.jnp)
    monkeypatch.setattr(engine_mod, "jnp", proxy)
    stages0 = eng.n_host_stages
    steps0 = eng.n_decode_steps
    for _ in range(8):
        assert eng.step()
    assert eng.n_decode_steps == steps0 + 8
    assert eng.n_host_stages == stages0, \
        "pure-decode steps re-staged host arrays (pos/keys/knobs/table " \
        "must live on device between scheduling boundaries)"
    assert proxy.asarray_calls == 0, \
        "a staging path bypassed the engine's _stage chokepoint"
    monkeypatch.undo()
    # the window changed nothing semantically: drain and check exactness
    results = eng.run()
    assert len(results) == 3
    eng.kv.check_reclaimed()


def test_boundary_events_do_restage_and_stay_exact():
    """The inverse guard: an admission mid-flight (a genuine scheduling
    boundary) DOES re-stage the slot arrays — the dirty-flag system must
    not under-sync — and the workload stays exact end to end."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=32,
                        max_context=64)
    prompts = _prompts((4, 4, 4), 31, seed=2)
    for i in (0, 1):
        eng.add_request(Request(i, prompts[i], max_new=12))
    while not all(sl is not None and sl.gen >= 1 for sl in eng.slots):
        assert eng.step()
    assert eng.step()
    stages0 = eng.n_host_stages
    eng.add_request(Request(2, prompts[2], max_new=4))   # no free slot:
    eng.step()                                           # stays queued
    queued_stages = eng.n_host_stages
    while eng.step():
        pass
    assert eng.n_host_stages > stages0, \
        "the mid-flight admission/retire boundary never re-synced"
    assert queued_stages >= stages0, "queued-only admission is host-side"
    assert len(eng.results) == 3


def _drive_until(eng, pred, cap=200):
    for _ in range(cap):
        if pred():
            return
        assert eng.step(), "engine went idle before reaching the staged " \
                           "scenario"
    raise AssertionError("scenario never reached")


def _mk_engine(tr):
    return ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                         max_context=64, prefill_chunk=8)


def _mk_requests():
    rng = np.random.default_rng(9)
    mk = lambda n: rng.integers(2, 61, n).astype(np.int32)  # noqa: E731
    return [
        Request("dec", mk(5), max_new=10,
                temperature=0.8, top_k=5, rng=jax.random.PRNGKey(3)),
        Request("chunky", mk(30), max_new=8,
                temperature=0.7, top_p=0.9, rng=jax.random.PRNGKey(4)),
        Request("q1", mk(9), max_new=6),
        Request("q2", mk(12), max_new=5, temperature=1.1,
                rng=jax.random.PRNGKey(5)),
    ]


def test_checkpoint_restore_midflight_is_bit_exact(tmp_path):
    """The fleet-migration smoke: freeze an engine holding a DECODING
    slot, a MID-CHUNK-PREFILL slot and two QUEUED requests; a fresh
    engine restored from the (file-roundtripped) snapshot finishes every
    request with exactly the tokens the uninterrupted engine produces."""
    tr = _make("vocab=61,dim=32,layers=2,heads=4,batch_size=4")

    # --- uninterrupted reference run, snapshotting mid-flight ----------
    eng_a = _mk_engine(tr)
    for r in _mk_requests():
        eng_a.add_request(r)

    def staged():
        # slot holding a decoder + a slot still chunking + queue nonempty
        modes = [sl.gen if sl is not None else None for sl in eng_a.slots]
        return (any(g is not None and g >= 1 for g in modes)
                and any(g == 0 for g in modes) and len(eng_a.queue) > 0)

    _drive_until(eng_a, staged)
    chunking = [sl.req.req_id for sl in eng_a.slots
                if sl is not None and sl.gen == 0]
    assert chunking, "no mid-chunk prefill at snapshot time"
    assert any(0 < sl.pos < sl.req.prompt_ids.size for sl in eng_a.slots
               if sl is not None and sl.gen == 0), \
        "the chunking slot had not committed a partial prompt yet"
    path = str(tmp_path / "engine_state.pkl")
    eng_a.save_state(path)
    while eng_a.step():
        pass
    results_a = {k: np.asarray(v) for k, v in eng_a.results.items()}
    assert set(results_a) == {"dec", "chunky", "q1", "q2"}

    # --- fresh engine, restored, resumed --------------------------------
    eng_b = _mk_engine(tr)
    eng_b.load_state(path)
    while eng_b.step():
        pass
    results_b = {k: np.asarray(v) for k, v in eng_b.results.items()}
    assert set(results_b) == set(results_a)
    for k in results_a:
        np.testing.assert_array_equal(
            results_a[k], results_b[k],
            err_msg=f"request {k!r} diverged after mid-flight restore")
    assert eng_b.finish_reasons == eng_a.finish_reasons
    eng_b.kv.check_reclaimed()


def test_restore_guards_config_and_idleness():
    """A snapshot must only land on an idle engine of the SAME shape —
    page accounting silently corrupts otherwise, so both misuses raise
    actionable ValueErrors."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=3")
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=32)
    snap = eng.checkpoint_state()
    other = ServingEngine(tr.executor, tr.params, num_slots=3, page_size=8,
                          max_context=32)
    with pytest.raises(ValueError, match="configuration mismatch"):
        other.restore_state(snap)
    busy = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                         max_context=32)
    busy.add_request(Request("x", np.asarray([3, 4, 5], np.int32),
                             max_new=4))
    with pytest.raises(ValueError, match="idle"):
        busy.restore_state(busy.checkpoint_state())
