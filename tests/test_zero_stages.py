"""ZeRO-2/3 (FSDP) over the data axis — exactness + sharding assertions.

The discipline from VERDICT r3: any new sharding mode must (a) keep the
dp-parity oracle green (identical losses/params to 1-device training — the
reference's test_CompareSparse contract) and (b) observably shard what it
claims to shard.  Ref for the design being generalized:
paddle/pserver/ParameterServer2.h:120-145 (per-server parameter blocks),
:501 addGradient (each server receives only its own gradient blocks)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.parity import assert_dp_parity
from paddle_tpu.trainer.trainer import Trainer


def _mnist_batches(n=12, B=16, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {"pixel": Argument(value=(rng.random((B, 784), np.float32) - 0.5)),
         "label": Argument(ids=rng.integers(0, 10, B).astype(np.int32))}
        for _ in range(n)
    ]


def _cfg(zero_stage, B=16):
    cfg = parse_config("demo/mnist/mlp_mnist.py", f"batch_size={B}")
    cfg.opt_config.zero_stage = zero_stage
    return cfg


@pytest.mark.parametrize("stage", [2, 3])
def test_zero_stage_parity(stage):
    """dp=8 with ZeRO stage 2/3 must reproduce dp=1 exactly."""
    batches = _mnist_batches()
    assert_dp_parity(_cfg(stage), batches, make_mesh(data=8),
                     config2=_cfg(stage))


def _data_sharded(arr, mesh) -> bool:
    sh = arr.sharding
    return isinstance(sh, jax.sharding.NamedSharding) and \
        sh.spec and sh.spec[0] == "data"


def test_zero3_param_and_slot_sharding():
    """Stage 3: every eligible parameter (leading dim % 8 == 0) and its
    optimizer slots live sharded over `data`; ineligible ones replicated."""
    mesh = make_mesh(data=8)
    tr = Trainer(_cfg(3), seed=2, mesh=mesh)
    sharded = {n for n, v in tr.params.items() if _data_sharded(v, mesh)}
    for name, v in tr.params.items():
        if v.shape[0] % 8 == 0:
            assert name in sharded, f"{name} {v.shape} should be data-sharded"
        else:
            assert name not in sharded, f"{name} {v.shape} must stay replicated"
    assert sharded, "no parameter got sharded at stage 3"
    for name, slots in tr.opt_state["slots"].items():
        for leaf in jax.tree.leaves(slots):
            if leaf.ndim >= 1 and leaf.shape[0] % 8 == 0:
                assert _data_sharded(leaf, mesh), \
                    f"slot of {name} not sharded: {leaf.shape}"


def test_zero3_memory_footprint():
    """The point of FSDP: per-device parameter bytes shrink ~N-fold for
    eligible params.  Check addressable shard sizes."""
    mesh = make_mesh(data=8)
    tr = Trainer(_cfg(3), seed=2, mesh=mesh)
    for name, v in tr.params.items():
        if v.shape[0] % 8 == 0:
            shard = v.addressable_shards[0].data
            assert shard.size == v.size // 8, (
                f"{name}: shard holds {shard.size} of {v.size} elements")


def test_zero3_checkpoint_roundtrip(tmp_path):
    """Save gathers shards to host; load re-shards; params identical and
    still sharded after the round-trip."""
    mesh = make_mesh(data=8)
    batches = _mnist_batches(n=3)
    tr = Trainer(_cfg(3), seed=2, mesh=mesh)
    for b in batches:
        tr.train_one_batch(b)
    before = {n: np.asarray(jax.device_get(v)) for n, v in tr.params.items()}
    d = tr.save(str(tmp_path))
    tr2 = Trainer(_cfg(3), seed=77, mesh=mesh)
    tr2.load(d)
    for n in before:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(tr2.params[n])), before[n])
    assert any(_data_sharded(v, mesh) for v in tr2.params.values())


def test_zero_stage_flag_normalization():
    """shard_optimizer_state=True floors the stage at 1."""
    from paddle_tpu.parallel.dp import effective_zero_stage
    cfg = _cfg(0)
    cfg.opt_config.shard_optimizer_state = True
    assert effective_zero_stage(cfg.opt_config) == 1
    cfg.opt_config.zero_stage = 3
    assert effective_zero_stage(cfg.opt_config) == 3


def test_zero2_leaves_vocab_sharded_embeddings_alone():
    """A sparse_update embedding defaults to vocab-dim (model-axis) sharding;
    ZeRO >= 2 must NOT pin its gradient to the data axis — params, slots and
    grads must agree on the parameter's home axis."""
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parallel.dp import zero_grad_shardings

    def conf():
        from paddle_tpu.dsl import (ParamAttr, SoftmaxActivation,
                                    classification_cost, data_layer,
                                    embedding_layer, fc_layer, last_seq,
                                    settings)
        settings(batch_size=16, learning_rate=0.1, zero_stage=2)
        w = data_layer(name="word", size=64)
        emb = embedding_layer(input=w, size=8,
                              param_attr=ParamAttr(sparse_update=True))
        out = fc_layer(input=last_seq(input=emb), size=4,
                       act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=4))

    cfg = parse_config_callable(conf)
    mesh = make_mesh(data=2, model=4)
    tr = Trainer(cfg, seed=1, mesh=mesh)
    gs = zero_grad_shardings(mesh, cfg.model_config, tr.params)
    emb_names = [p.name for p in cfg.model_config.parameters
                 if p.sparse_update]
    assert emb_names
    for n in emb_names:
        assert gs[n] is None, (
            f"embedding {n} gradient pinned to data axis despite "
            f"vocab sharding")
    # the table itself must be model-axis sharded, and SOME dense param's
    # grad must be data-pinned (the stage-2 mechanism is active)
    for n in emb_names:
        spec = tr.params[n].sharding.spec
        assert spec and spec[0] == "model", f"{n} table not vocab-sharded: {spec}"
    assert any(s is not None for s in gs.values())
