"""BarrierStat analog (ref: paddle/utils/BarrierStat.h:198-389): per-step
dispatch/sync timing windows and the straggler report on mesh runs."""

import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.parallel.barrier_stat import BarrierTimer


def test_percentiles_and_render():
    bt = BarrierTimer(window=100)
    for ms in (1, 2, 3, 100):
        bt.dispatch_s.append(ms / 1e3)
    bt.sync_s.append(0.005)
    s = bt.local_summary()
    assert 1.0 <= s["dispatch"]["p50"] <= 3.0
    assert abs(s["dispatch"]["max"] - 100.0) < 1e-6
    assert abs(s["sync"]["p50"] - 5.0) < 1e-6
    line = bt.render()
    assert "dispatch" in line and "sync" in line
    # single process: no straggler table
    assert bt.straggler_summary() is None


def test_fused_windows_render():
    """The steps_per_dispatch windows (h2d staging on the prefetch thread,
    k-step scan enqueue) surface in the summary + render line."""
    bt = BarrierTimer(window=100)
    with bt.time_h2d():
        pass
    with bt.time_scan():
        pass
    s = bt.local_summary()
    assert "h2d" in s and "scan" in s
    line = bt.render()
    assert "h2d" in line and "scan" in line


def test_timed_context_managers():
    bt = BarrierTimer()
    with bt.time_dispatch():
        time.sleep(0.01)
    with bt.time_sync():
        time.sleep(0.005)
    assert bt.dispatch_s[0] >= 0.009
    assert bt.sync_s[0] >= 0.004


def test_trainer_logs_barrier_on_mesh():
    """Mesh training populates the windows and renders a summary line."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    cfg_src = """
from paddle_tpu.dsl import *
settings(batch_size=16, learning_rate=0.1)
x = data_layer(name="x", size=8)
h = fc_layer(input=x, size=8, act=TanhActivation())
out = fc_layer(input=h, size=2, act=SoftmaxActivation())
classification_cost(input=out, label=data_layer(name="label", size=2))
"""
    path = os.path.join(REPO, "tests", "_barrier_cfg.py")
    with open(path, "w") as f:
        f.write(cfg_src)
    try:
        cfg = parse_config(path, "")
        tr = Trainer(cfg, seed=0, mesh=make_mesh())
        rng = np.random.default_rng(0)

        def batches():
            for _ in range(6):
                x = rng.normal(size=(16, 8)).astype(np.float32)
                y = (x.sum(-1) > 0).astype(np.int32)
                yield {"x": Argument(value=x), "label": Argument(ids=y)}

        tr.train_one_pass(batches=batches(), log_period=2)
        # first dispatch (compile) is excluded from the window
        assert len(tr.barrier_stat.dispatch_s) == 5
        assert len(tr.barrier_stat.sync_s) >= 1
        assert "dispatch" in tr.barrier_stat.render()
    finally:
        os.remove(path)
