"""Worker for the real multi-process jax.distributed test (launched by
tests/test_multiprocess.py, one subprocess per simulated host).

Each process boots via init_distributed (the pserver-fleet bootstrap
analog), builds the SAME model from the same seed, feeds its OWN local
batch shard (per-host data-parallel input, like each trainer reading its
own file list), trains a few steps over a data-parallel mesh, and prints
the per-step losses — which must agree bit-for-bit across processes since
the loss is computed from the global batch.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force the CPU backend BEFORE jax import (the axon plugin must not latch)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> int:
    coord = sys.argv[1]
    num_procs = int(sys.argv[2])
    pid = int(sys.argv[3])

    from paddle_tpu.parallel.mesh import init_distributed, make_mesh
    init_distributed(coord, num_procs, pid)
    assert jax.process_count() == num_procs, jax.process_count()

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        from paddle_tpu.dsl import (MomentumOptimizer, SoftmaxActivation,
                                    TanhActivation, classification_cost,
                                    data_layer, fc_layer, settings)
        settings(batch_size=8 * num_procs, learning_rate=0.1,
                 learning_method=MomentumOptimizer(momentum=0.9))
        x = data_layer(name="x", size=16)
        h = fc_layer(input=x, size=32, act=TanhActivation())
        out = fc_layer(input=h, size=4, act=SoftmaxActivation())
        classification_cost(input=out, label=data_layer(name="y", size=4))

    cfg = parse_config_callable(conf)
    mesh = make_mesh()          # data axis spans both processes' devices
    tr = Trainer(cfg, seed=7, mesh=mesh)

    # per-process data: DIFFERENT shards (seeded by process id), global
    # batch = concatenation over processes
    rng = np.random.default_rng(100 + pid)
    W = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    losses = []
    for _ in range(4):
        x = rng.normal(size=(8, 16)).astype(np.float32)
        y = np.argmax(x @ W, -1).astype(np.int32)
        loss = tr.train_one_batch({"x": Argument(value=x),
                                   "y": Argument(ids=y)})
        # the step loss is computed from the GLOBAL batch and fully
        # replicated, so float() is legal multi-process and every process
        # must see the same value
        losses.append(float(loss))
    tr._drain_losses()
    print("RESULT pid={} losses={}".format(
        pid, ",".join(f"{l:.10f}" for l in losses)), flush=True)
    # final parameters, for the single-process equivalence oracle in the
    # test (ref: test_CompareSparse.cpp — multi-trainer == local training)
    for name in sorted(tr.params):
        flat = np.asarray(jax.device_get(tr.params[name])).ravel()
        print(f"RESULT pid={pid} param {name} "
              f"sum={flat.sum():.8f} asum={np.abs(flat).sum():.8f}",
              flush=True)

    # barrier stats straggler table exercises process_allgather
    bt = tr.barrier_stat
    strag = bt.straggler_summary()
    assert strag is not None and strag["skew"] >= 1.0, strag
    print(f"RESULT pid={pid} straggler_ok skew={strag['skew']:.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
