"""Worker for the real multi-process jax.distributed test (launched by
tests/test_multiprocess.py, one subprocess per simulated host).

Each process boots via init_distributed (the pserver-fleet bootstrap
analog), builds the SAME model from the same seed, feeds its OWN local
batch shard (per-host data-parallel input, like each trainer reading its
own file list), trains a few steps over a data-parallel mesh, and prints
the per-step losses — which must agree bit-for-bit across processes since
the loss is computed from the global batch.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# force the CPU backend BEFORE jax import (the axon plugin must not latch)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np


def main() -> int:
    coord = sys.argv[1]
    num_procs = int(sys.argv[2])
    pid = int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "dp"   # 'dp' | 'tpdp'

    from paddle_tpu.parallel.mesh import init_distributed, make_mesh
    init_distributed(coord, num_procs, pid)
    assert jax.process_count() == num_procs, jax.process_count()

    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    model_par = 2 if mode == "tpdp" else 1
    data_par = num_procs // model_par

    def conf():
        from paddle_tpu.dsl import (MomentumOptimizer, ParameterAttribute,
                                    SoftmaxActivation, TanhActivation,
                                    classification_cost, data_layer,
                                    fc_layer, settings)
        settings(batch_size=8 * data_par, learning_rate=0.1,
                 learning_method=MomentumOptimizer(momentum=0.9))
        x = data_layer(name="x", size=16)
        tp = (ParameterAttribute(partition_spec=[None, "model"])
              if model_par > 1 else None)
        tp2 = (ParameterAttribute(partition_spec=["model", None])
               if model_par > 1 else None)
        h = fc_layer(input=x, size=32, act=TanhActivation(), param_attr=tp)
        out = fc_layer(input=h, size=4, act=SoftmaxActivation(),
                       param_attr=tp2)
        classification_cost(input=out, label=data_layer(name="y", size=4))

    cfg = parse_config_callable(conf)
    if model_par > 1:
        # devices laid out [data, model]: device i -> data row i // model_par
        mesh = make_mesh(data=data_par, model=model_par)
    else:
        mesh = make_mesh()      # data axis spans every process's devices
    tr = Trainer(cfg, seed=7, mesh=mesh)

    if model_par > 1:
        # tp params must REALLY shard across processes: each process holds
        # 1/model_par of the annotated weights
        w0 = tr.params["___fc_layer_0__.w0"]
        assert not w0.is_fully_addressable
        local = w0.addressable_shards[0].data
        assert local.shape[1] * model_par == w0.shape[1], (
            local.shape, w0.shape)
        print(f"RESULT pid={pid} tp_shard_ok local={local.shape} "
              f"global={w0.shape}", flush=True)

    # per-process data: one stream per DATA ROW (processes replicating the
    # same data shard across `model` must feed identical rows), global
    # batch = concatenation over data rows
    data_row = pid // model_par
    rng = np.random.default_rng(100 + data_row)
    W = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    losses = []
    for _ in range(4):
        x = rng.normal(size=(8, 16)).astype(np.float32)
        y = np.argmax(x @ W, -1).astype(np.int32)
        loss = tr.train_one_batch({"x": Argument(value=x),
                                   "y": Argument(ids=y)})
        # the step loss is computed from the GLOBAL batch and fully
        # replicated, so float() is legal multi-process and every process
        # must see the same value
        losses.append(float(loss))
    tr._drain_losses()
    print("RESULT pid={} losses={}".format(
        pid, ",".join(f"{l:.10f}" for l in losses)), flush=True)
    # final parameters, for the single-process equivalence oracle in the
    # test (ref: test_CompareSparse.cpp — multi-trainer == local training)
    from paddle_tpu.trainer.trainer import _host_tree
    host_params = _host_tree(tr.params)
    for name in sorted(host_params):
        flat = np.asarray(host_params[name]).ravel()
        print(f"RESULT pid={pid} param {name} "
              f"sum={flat.sum():.8f} asum={np.abs(flat).sum():.8f}",
              flush=True)

    # barrier stats straggler table exercises process_allgather
    bt = tr.barrier_stat
    strag = bt.straggler_summary()
    assert strag is not None and strag["skew"] >= 1.0, strag
    print(f"RESULT pid={pid} straggler_ok skew={strag['skew']:.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
