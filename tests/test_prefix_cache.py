"""Prefix caching: refcounted copy-on-write page sharing in the paged KV
cache (serving/prefix_tree.py + the PR-7 allocator/engine changes).

The exactness contract is unchanged and non-negotiable: a prefix-HIT
request's tokens are bit-identical to a cold `lm_generate(use_cache=True)`
run — including under LRU eviction, COW divergence mid-page, and
preemption-with-replay — while `_decode_step._cache_size() == 1` stays
asserted (all sharing is host-side table/allocator state; the decode jit
signature never changes)."""

import numpy as np
import pytest

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.serving import (PagedKVCache, PrefixTree, Request,
                                ServingEngine)
from paddle_tpu.trainer.trainer import Trainer


@pytest.fixture(scope="module")
def tr():
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=23,dim=16,layers=2,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _oracle(tr, req: Request):
    toks, lens = lm_generate(
        tr.executor, tr.params, req.prompt_ids[None, :],
        max_new=req.max_new, temperature=req.temperature, top_k=req.top_k,
        top_p=req.top_p, eos_id=req.eos_id, rng=req.rng, use_cache=True)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])]


def _assert_exact(tr, reqs, results):
    for r in reqs:
        np.testing.assert_array_equal(
            _oracle(tr, r), results[r.req_id],
            err_msg=f"request {r.req_id!r} diverged from the cold "
                    f"lm_generate oracle")


def _pool_reclaimed(eng):
    eng.kv.check_reclaimed()


# ---------------------------------------------------------------------------
# the token-exactness oracle, extended to the sharing paths
# ---------------------------------------------------------------------------

def test_shared_prefix_hits_stay_oracle_exact(tr):
    """A pool of requests sharing one system-prompt prefix with distinct
    suffixes and mixed sampling knobs: the first pays full prefill, the
    rest prefix-hit (mapping the committed pages read-only + suffix-only
    prefill) — every output bit-matches its own cold run, tokens-saved
    accumulates, and the decode step stays ONE signature."""
    rng = np.random.default_rng(0)
    system = rng.integers(2, 23, 19).astype(np.int32)   # spans 2+ pages
    knobs = [dict(), dict(temperature=0.8, top_k=5),
             dict(temperature=0.7, top_p=0.9), dict(temperature=1.1)]
    reqs = [Request(f"r{i}",
                    np.concatenate([system,
                                    rng.integers(2, 23, 3 + i)
                                    .astype(np.int32)]),
                    max_new=5, rng=jax.random.PRNGKey(40 + i), **kw)
            for i, kw in enumerate(knobs)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    results = {}
    for r in reqs:                        # sequential: each later request
        results.update(eng.run([r]))      # sees the earlier donations
    _assert_exact(tr, reqs, results)
    assert eng.n_prefix_hits >= len(reqs) - 1
    assert eng.prefill_tokens_saved >= (len(reqs) - 1) * 16, \
        "hits did not skip the shared full pages"
    assert eng._decode_step._cache_size() == 1
    _pool_reclaimed(eng)


def test_concurrent_same_prefix_requests_share_pages(tr):
    """Two live slots mapping the same cached prefix simultaneously:
    shared pages show refcount > 1 (shared_pages_in_use), neither slot
    writes them (COW gave each a private boundary), and both outputs stay
    exact."""
    rng = np.random.default_rng(1)
    system = rng.integers(2, 23, 17).astype(np.int32)
    warm = Request("warm", system.copy(), max_new=9)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    res = eng.run([warm])
    a = Request("a", np.concatenate([system, [3, 4, 5]]).astype(np.int32),
                max_new=6)
    b = Request("b", np.concatenate([system, [6, 7]]).astype(np.int32),
                max_new=6)
    eng.add_request(a)
    eng.add_request(b)
    eng.step()                            # both admitted, both hit
    assert eng.n_prefix_hits == 2
    assert eng.kv.shared_pages_in_use >= 2, \
        "concurrent hits did not actually share physical pages"
    eng.kv.check()
    res.update(eng.run())
    _assert_exact(tr, [warm, a, b], res)
    assert eng._decode_step._cache_size() == 1
    _pool_reclaimed(eng)


def test_cow_divergence_mid_page_and_donor_page_intact(tr):
    """B's prompt follows A's sequence INTO a page and diverges mid-run:
    admission maps the boundary page, COWs it, and B's suffix overwrites
    only its own copy — B is oracle-exact, and a third request repeating
    A's exact prompt still hits the ORIGINAL page and stays exact (the
    shared original was never written)."""
    rng = np.random.default_rng(2)
    base = rng.integers(2, 23, 13).astype(np.int32)     # 13 = 1.625 pages
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    a = Request("a", base.copy(), max_new=6)
    results = eng.run([a])
    cow0 = eng.kv.n_cow
    # B: matches page 0 fully, then tokens 8..10 of page 1, then diverges
    b_prompt = np.concatenate([base[:11],
                               (base[11:13] + 1) % 23 + 2,
                               rng.integers(2, 23, 4)]).astype(np.int32)
    b = Request("b", b_prompt, max_new=6)
    results.update(eng.run([b]))
    assert eng.kv.n_cow > cow0, "mid-page divergence never copied-on-write"
    assert eng.n_prefix_hits >= 1
    # C repeats A's prompt exactly: the original boundary page must still
    # hold A's committed K/V bit-for-bit
    c = Request("c", base.copy(), max_new=6)
    results.update(eng.run([c]))
    _assert_exact(tr, [a, b, c], results)
    assert eng._decode_step._cache_size() == 1
    _pool_reclaimed(eng)


def test_eviction_runs_before_pausing_and_stays_exact(tr):
    """A tree fat with retired prefixes + a pool with no free pages left:
    admission and decode growth reclaim via LRU eviction (free list was
    dry) WITHOUT any preemption, and outputs stay exact."""
    rng = np.random.default_rng(3)
    eng = ServingEngine(tr.executor, tr.params, num_slots=1, page_size=4,
                        max_context=16, num_pages=5)    # 4 real pages
    filler = [Request(f"f{i}", rng.integers(2, 23, 7).astype(np.int32),
                      max_new=5) for i in range(2)]
    results = {}
    for r in filler:
        results.update(eng.run([r]))
    assert eng.kv.cached_page_count > 0
    assert eng.kv.free_page_count < eng.kv.pages_for(9 + 7 - 1), \
        "pool not tight enough to force eviction"
    big = Request("big", rng.integers(2, 23, 9).astype(np.int32), max_new=7)
    results.update(eng.run([big]))
    assert eng.prefix.n_evictions > 0, "free list never pressured the tree"
    assert eng.n_preemptions == 0, \
        "eviction should have satisfied pressure before any preemption"
    _assert_exact(tr, filler + [big], results)
    _pool_reclaimed(eng)


def test_eviction_racing_admission_of_the_same_prefix(tr):
    """The admission that HITS a prefix also triggers eviction for its
    suffix pages: the matched pages are mapped (refcount > 0) before the
    pressure hook runs, so LRU eviction must reclaim OTHER nodes and can
    never steal the prefix out from under the admission using it."""
    rng = np.random.default_rng(4)
    eng = ServingEngine(tr.executor, tr.params, num_slots=1, page_size=4,
                        max_context=16, num_pages=7)    # 6 real pages
    keep = Request("keep", rng.integers(2, 23, 8).astype(np.int32),
                   max_new=5)                            # donates 2+ pages
    other = Request("other", rng.integers(2, 23, 7).astype(np.int32),
                    max_new=4)
    results = eng.run([keep])
    results.update(eng.run([other]))
    nodes_before = eng.prefix.n_nodes
    assert eng.kv.cached_page_count >= 4
    # rerun keep's prompt with a long suffix: hits keep's pages, and the
    # suffix allocation must evict from `other`'s nodes
    hit = Request("hit", np.concatenate(
        [keep.prompt_ids, rng.integers(2, 23, 5)]).astype(np.int32),
        max_new=3)
    ev0 = eng.prefix.n_evictions
    results.update(eng.run([hit]))
    assert eng.n_prefix_hits >= 1
    assert eng.prefix.n_evictions > ev0, "no eviction pressure occurred"
    assert eng.prefix.n_nodes <= nodes_before + 3
    _assert_exact(tr, [keep, other, hit], results)
    _pool_reclaimed(eng)


def test_preempt_replay_prefix_hits_and_refcounts_balance(tr):
    """Preemption donates the victim's committed pages; the deterministic
    replay re-admission prefix-hits its own prompt (skipping the prefill
    it already paid for), outputs stay exact, and slot-mapping refcounts
    drop back to zero everywhere at the end."""
    rng = np.random.default_rng(5)
    prompts = [rng.integers(2, 23, n).astype(np.int32) for n in (6, 4, 5)]
    reqs = [Request(i, p, max_new=8) for i, p in enumerate(prompts)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16, num_pages=6)
    results = eng.run(reqs)
    assert eng.n_preemptions > 0, "pool was never overcommitted"
    assert eng.n_prefix_hits > 0, \
        "preempt replay never hit the victim's own donated prefix"
    _assert_exact(tr, reqs, results)
    assert (eng.kv._ref == 0).all()
    assert eng._decode_step._cache_size() == 1
    _pool_reclaimed(eng)


def test_overcommit_pool_with_hits_stays_exact_under_churn(tr):
    """Sustained churn over a small pool with repeated prompts: hits,
    evictions, COWs, and preemptions all interleave — every request of
    every wave still matches its cold oracle."""
    rng = np.random.default_rng(6)
    bases = [rng.integers(2, 23, 9).astype(np.int32) for _ in range(2)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16, num_pages=8)
    reqs = []
    for w in range(3):
        for i, base in enumerate(bases):
            suffix = rng.integers(2, 23, 1 + w).astype(np.int32)
            reqs.append(Request(f"w{w}b{i}",
                                np.concatenate([base, suffix]),
                                max_new=4))
    results = {}
    for r in reqs:
        eng.add_request(r)
        eng.step()
    results.update(eng.run())
    results.update({k: eng.results.pop(k) for k in list(eng.results)})
    _assert_exact(tr, reqs, results)
    assert eng.n_prefix_hits > 0
    assert eng._decode_step._cache_size() == 1
    _pool_reclaimed(eng)


# ---------------------------------------------------------------------------
# allocator satellites: double-release guard, deterministic reset, COW unit
# ---------------------------------------------------------------------------

def test_release_is_idempotent_and_guards_double_free(tr):
    """Releasing a slot twice (or after reset()) must NOT append its pages
    to the free list twice — the double-free would hand one physical page
    to two slots and silently corrupt the allocator."""
    kv = PagedKVCache(tr.executor, num_slots=2, page_size=4,
                      pages_per_slot=3, num_pages=8)
    assert kv.try_grow(0, 9)                 # 3 pages
    assert kv.try_grow(1, 4)                 # 1 page
    free_before = kv.free_page_count
    kv.release(0)
    assert kv.free_page_count == free_before + 3
    kv.release(0)                            # double release: no-op
    assert kv.free_page_count == free_before + 3
    kv.check()
    kv.reset()
    kv.release(0)                            # release after reset: no-op
    kv.release(1)
    assert kv.free_page_count == kv.num_pages - 1
    assert len(set(kv._free)) == len(kv._free), "free list holds duplicates"
    kv.check()


def test_reset_rebuilds_canonical_free_list(tr):
    """After arbitrary grow/release churn, reset() restores the free list
    to construction order, so page placement is reproducible across
    restarts (exactness tests and engine.json snapshots stay stable)."""
    kv = PagedKVCache(tr.executor, num_slots=2, page_size=4,
                      pages_per_slot=3, num_pages=8)
    pristine = list(kv._free)
    assert kv.try_grow(0, 12) and kv.try_grow(1, 7)
    kv.release(1)
    kv.cache_page(int(kv.table[0, 0]))       # prefix retention survives...
    kv.reset()                               # ...until reset forgets it
    assert kv._free == pristine, \
        f"reset() free list {kv._free} != canonical {pristine}"
    assert kv.cached_page_count == 0 and (kv._ref == 0).all()
    # allocation after reset is bit-reproducible: same pages, same order
    assert kv.try_grow(0, 12)
    first = kv.table[0, :3].tolist()
    kv.reset()
    assert kv.try_grow(0, 12)
    assert kv.table[0, :3].tolist() == first
    kv.check()


def test_engine_reset_prefix_cache_restores_cold_start(tr):
    """ServingEngine.reset_prefix_cache is the engine-level cold start:
    the index empties, the free list returns to canonical order, and
    re-running the same workload reproduces the same page placement AND
    the same tokens (a restart is bit-indistinguishable from a fresh
    engine)."""
    rng = np.random.default_rng(5)
    system = rng.integers(2, 23, 18).astype(np.int32)
    mk = lambda: [Request(f"r{i}", np.concatenate(
        [system, rng2.integers(2, 23, 2 + i).astype(np.int32)]), max_new=4)
        for i, rng2 in ((j, np.random.default_rng(50 + j))
                        for j in range(3))]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    first = eng.run(mk())
    cached1 = np.flatnonzero(eng.kv._cached).tolist()
    eng.reset_prefix_cache()
    assert eng.prefix.n_nodes == 0 and eng.kv.cached_page_count == 0
    assert eng.kv.free_page_count == eng.kv.num_pages - 1
    assert eng.kv._free == eng.kv._canonical_free()
    assert eng.n_prefix_hits > 0                  # first pass did share
    again = eng.run(mk())
    for rid in first:
        np.testing.assert_array_equal(first[rid], again[rid])
    # same physical pages ended up prefix-cached: placement reproduced
    assert np.flatnonzero(eng.kv._cached).tolist() == cached1
    _pool_reclaimed(eng)


def test_map_shared_refcounts_and_cow_unit(tr):
    """Allocator-level sharing: map_shared bumps refcounts, writes to a
    shared page COW through ensure_writable (contents preserved), and the
    last release frees everything exactly once."""
    import jax.numpy as jnp

    kv = PagedKVCache(tr.executor, num_slots=3, page_size=4,
                      pages_per_slot=2, num_pages=8)
    assert kv.try_grow(0, 8)                 # slot 0 owns 2 private pages
    donor = [int(kv.table[0, 0]), int(kv.table[0, 1])]
    name = next(iter(kv.pools))
    kv.pools[name]["k"] = kv.pools[name]["k"].at[donor[0], 0, 0, 0].set(7.5)
    kv.cache_page(donor[0])
    kv.cache_page(donor[1])
    kv.map_shared(1, donor)
    kv.map_shared(2, donor[:1])
    assert kv._ref[donor[0]] == 3 and kv._ref[donor[1]] == 2
    assert kv.shared_pages_in_use == 2 and kv.private_pages_in_use == 0
    assert not kv.page_writable(donor[0])
    assert kv.ensure_writable(1, 0) is True            # COW copies
    fresh = int(kv.table[1, 0])
    assert fresh != donor[0] and kv.page_writable(fresh)
    assert float(kv.pools[name]["k"][fresh, 0, 0, 0]) == 7.5, \
        "COW did not copy the page contents"
    assert kv._ref[donor[0]] == 2
    assert kv.ensure_writable(1, 0) is False           # already private
    kv.check()
    kv.release(0)
    kv.release(1)
    kv.release(2)
    # cached pages stay out of the free list until uncached
    assert kv.cached_page_count == 2
    kv.uncache_page(donor[0])
    kv.uncache_page(donor[1])
    assert kv.free_page_count == kv.num_pages - 1
    kv.check()


def test_cow_returns_none_when_pool_dry(tr):
    """ensure_writable on a shared page with an empty free list and no
    reclaimer reports None (caller rolls back) instead of corrupting."""
    kv = PagedKVCache(tr.executor, num_slots=2, page_size=4,
                      pages_per_slot=2, num_pages=3)    # 2 real pages
    assert kv.try_grow(0, 8)
    kv.cache_page(int(kv.table[0, 0]))
    kv.map_shared(1, [int(kv.table[0, 0])])
    assert kv.ensure_writable(1, 0) is None
    kv.check()


# ---------------------------------------------------------------------------
# radix tree unit behavior
# ---------------------------------------------------------------------------

def test_prefix_tree_match_insert_evict(tr):
    kv = PagedKVCache(tr.executor, num_slots=1, page_size=4,
                      pages_per_slot=4, num_pages=12)
    tree = PrefixTree(kv)
    kv.on_page_pressure = tree.evict_for
    toks = np.arange(2, 18, dtype=np.int32)              # 4 full runs
    assert kv.try_grow(0, 16)
    pages = [int(kv.table[0, j]) for j in range(4)]
    assert tree.insert(toks, pages) == 4
    assert tree.insert(toks, pages) == 0                 # dedupe: no new nodes
    kv.release(0)
    assert kv.cached_page_count == 4

    full, partial = tree.match(toks[:11])                # 2 runs + 3 partial
    assert full == pages[:2]
    assert partial == (pages[2], 3)
    full, partial = tree.match(np.asarray([99, 98], np.int32))
    assert full == [] and partial is None

    # eviction is LRU leaf-first: deepest node goes first, the prefix
    # property (parents outlive children) holds throughout
    assert tree.evict_for(1) == 1
    assert kv.cached_page_count == 3
    full, partial = tree.match(toks)
    assert full == pages[:3], "eviction removed a non-leaf node"
    # a page mapped by a live slot is never evicted
    kv.map_shared(0, pages[:3])
    assert tree.evict_for(99) == 0
    kv.release(0)
    assert tree.evict_for(99) == 3
    assert kv.free_page_count == kv.num_pages - 1
    kv.check()
