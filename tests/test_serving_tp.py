"""Tensor-parallel sharded decode exactness oracles.

The contract (docs/serving.md "Sharded decode"): a `ServingEngine` over a
mesh whose `model` axis shards attention heads and the KV page pools is
TOKEN-FOR-TOKEN identical to the single-device engine — and therefore to
the per-request `lm_generate(use_cache=True)` oracle — across every
sampling knob, prefix-cache hits, chunked mixed steps, and preempt/replay,
while holding the sacred signature set (ONE compiled decode step + ONE
mixed step per token budget).  Runs on the conftest 8-virtual-CPU-device
mesh (`--xla_force_host_platform_device_count`), the same harness as the
dp-parity tests: SPMD partitioning decisions are backend-agnostic, so the
collective structure (and the exactness) is the evidence a single real
chip cannot provide."""

import numpy as np
import pytest

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parallel.mesh import model_mesh
from paddle_tpu.serving import Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4,
    reason="needs >= 4 devices (conftest provides 8 host devices)")


def _make(args: str):
    cfg = parse_config("demo/model_zoo/transformer_lm.py", args)
    return Trainer(cfg, seed=7)


def _prompts(lens, vocab, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(2, vocab, n).astype(np.int32) for n in lens]


def _tp_engine(tr, n: int, **kw) -> ServingEngine:
    # each engine owns the executor's mesh for its lifetime — reset it so
    # a later single-device engine (or another shard count) starts clean
    tr.executor.mesh = None
    return ServingEngine(tr.executor, tr.params,
                         mesh=model_mesh(n) if n > 1 else None, **kw)


def _assert_same_results(base: dict, tp: dict, label: str) -> None:
    assert set(base) == set(tp)
    for k in base:
        np.testing.assert_array_equal(
            base[k], tp[k],
            err_msg=f"request {k!r} diverged between single-device and "
                    f"{label} decode")


def test_tp2_and_tp4_match_single_device_across_sampling_knobs():
    """All four sampling modes (greedy / top-k / nucleus / full), mixed
    prompt lengths, chunked prefill on (the default): model=2 and model=4
    shards produce the exact token streams of the single-device engine,
    through ONE decode + ONE mixed signature each."""
    tr = _make("vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    prompts = _prompts((3, 9, 5, 12), 61, seed=1)
    knobs = [dict(),                                     # greedy
             dict(temperature=0.8, top_k=5),
             dict(temperature=0.7, top_p=0.9),
             dict(temperature=1.1)]                      # full sampling

    def reqs():
        return [Request(i, p, max_new=6, rng=jax.random.PRNGKey(100 + i),
                        **kw)
                for i, (p, kw) in enumerate(zip(prompts, knobs))]

    kw = dict(num_slots=3, page_size=8, max_context=64)
    base = _tp_engine(tr, 1, **kw).run(reqs())
    for n in (2, 4):
        eng = _tp_engine(tr, n, **kw)
        _assert_same_results(base, eng.run(reqs()), f"model={n}")
        assert eng._decode_step._cache_size() == 1
        assert eng._mixed_step._cache_size() == 1
        assert eng.tp == n
        assert eng.kv.pool_bytes_per_shard == eng.kv.pool_bytes // n


def test_tp_gqa_grouped_heads_stay_exact():
    """Grouped-query attention under tensor parallelism: h_kv=2 over
    model=2 gives each device one kv head serving its two query heads —
    the pool's kv-head shard and the in-shard GQA expansion must
    reproduce the single-device tokens exactly."""
    tr = _make("vocab=97,dim=32,layers=2,heads=4,batch_size=4,kv_heads=2")
    prompts = _prompts((3, 9, 6), 97)
    kw = dict(num_slots=2, page_size=8, max_context=64)
    base = _tp_engine(tr, 1, **kw).run(
        [Request(i, p, max_new=6) for i, p in enumerate(prompts)])
    tp = _tp_engine(tr, 2, **kw).run(
        [Request(i, p, max_new=6) for i, p in enumerate(prompts)])
    _assert_same_results(base, tp, "model=2 (gqa)")


def test_tp_prefix_cache_hits_and_cow_stay_exact():
    """Prefix-cache hits under sharding: the second wave maps pages the
    first wave committed (including a mid-page COW boundary), and the
    suffix-only prefill + sharded pools still produce single-device
    tokens.  Both engines must actually HIT (same host-side tree walk —
    sharding is invisible to the allocator)."""
    tr = _make("vocab=61,dim=32,layers=2,heads=4,batch_size=4")
    rng = np.random.default_rng(5)
    shared = rng.integers(2, 61, 19).astype(np.int32)
    suffixes = [rng.integers(2, 61, n).astype(np.int32) for n in (4, 7, 3)]

    def waves():
        first = [Request("w0", shared.copy(), max_new=5)]
        second = [Request(f"s{i}", np.concatenate([shared, suf]), max_new=5)
                  for i, suf in enumerate(suffixes)]
        return first, second

    kw = dict(num_slots=2, page_size=8, max_context=64)
    engines = {1: _tp_engine(tr, 1, **kw), 2: _tp_engine(tr, 2, **kw)}
    results = {}
    for n, eng in engines.items():
        first, second = waves()
        results[n] = {**eng.run(first), **eng.run(second)}
        assert eng.n_prefix_hits > 0, f"model={n}: prefix cache never hit"
        eng.kv.check_reclaimed()
    _assert_same_results(results[1], results[2], "model=2 (prefix)")
    assert engines[1].n_prefix_hits == engines[2].n_prefix_hits
    assert engines[1].kv.n_cow == engines[2].kv.n_cow


def test_tp_overcommitted_pool_preempt_replay_stays_exact():
    """Preempt/replay under sharding: the overcommitted pool forces
    pauses and preemptions, whose deterministic replay must stay
    invisible in the sharded output exactly as in the single-device
    engine (same preemption count — scheduling is host-side and
    shard-independent)."""
    tr = _make("vocab=11,dim=16,layers=1,heads=2,batch_size=3")
    prompts = _prompts((6, 4, 5, 3, 6), 11, seed=3)
    kw = dict(num_slots=2, page_size=4, max_context=16, num_pages=6)
    base_eng = _tp_engine(tr, 1, **kw)
    base = base_eng.run([Request(i, p, max_new=8)
                         for i, p in enumerate(prompts)])
    assert base_eng.n_preemptions > 0, "pool was never overcommitted"
    tp_eng = _tp_engine(tr, 2, **kw)
    tp = tp_eng.run([Request(i, p, max_new=8)
                     for i, p in enumerate(prompts)])
    _assert_same_results(base, tp, "model=2 (preempt/replay)")
    assert tp_eng.n_preemptions == base_eng.n_preemptions
    tp_eng.kv.check_reclaimed()


def test_tp_legacy_unchunked_prefill_path_stays_exact():
    """prefill_chunk=None (legacy whole-prompt bucketed prefill) under
    sharding: the dense prefill + pack path partitions too — same
    tokens, zero mixed-step signatures."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    prompts = _prompts((3, 5, 12), 31, seed=2)
    kw = dict(num_slots=2, page_size=8, max_context=32, prefill_chunk=None)
    base = _tp_engine(tr, 1, **kw).run(
        [Request(i, p, max_new=4) for i, p in enumerate(prompts)])
    eng = _tp_engine(tr, 2, **kw)
    tp = eng.run([Request(i, p, max_new=4) for i, p in enumerate(prompts)])
    _assert_same_results(base, tp, "model=2 (legacy prefill)")
    assert eng._mixed_step._cache_size() == 0


def test_tp_head_divisibility_validated():
    """heads (and kv heads) must divide the model axis — a mesh the model
    cannot shard over is an actionable construction-time error, not a
    silent wrong answer."""
    tr = _make("vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    with pytest.raises(ValueError, match="num_heads"):
        _tp_engine(tr, 4, num_slots=2, page_size=8, max_context=32)
