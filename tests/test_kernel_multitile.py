"""Multi-lane-tile and unaligned-shape sweeps for the pallas kernels.

The round-5 rehearsal found the parity matrix thin exactly where the
round-4 hardware failures lived: shapes whose blocks span MULTIPLE
(8/16, 128) TPU tiles.  These interpret-mode sweeps pin the kernels'
math at those shapes (Mosaic lowering is separately validated on device
by tools/tpu_parity.py's ledger queue):

- flash attention at head dim > 128 (two+ lane tiles), incl. GQA,
  sliding window, and unaligned D;
- LSTM/GRU time-grid kernels at multi-tile / unaligned D and reverse
  (weights 1/sqrt(D)-scaled — a fixed large std puts the backward
  recurrence in an exploding-gradient regime where NO two fp32
  implementations agree; adjudicated r5 with an f64 oracle);
- the additive-attention kernel at mixed wide dims, with the bf16
  gradient compared against the jnp-bf16 formulation (like-for-like:
  vs an fp32 oracle BOTH paths carry the same ~2.7%-of-scale input-
  rounding error, measured identical to the last bit).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow


@pytest.fixture(autouse=True)
def _interpret(monkeypatch):
    # per-test only (monkeypatch restores): a module-level env set would
    # leak interpret mode into every other test via collection-time import.
    # PADDLE_TPU_PALLAS=1 pins the kernel path even if the ambient env
    # carries the =0 debugging switch — these tests exist to exercise the
    # kernels, and must not silently green on the jnp fallback.
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("PADDLE_TPU_PALLAS", "1")
    from paddle_tpu.ops import pallas_attention
    assert pallas_attention.supported()


class TestFlashMultiTile:
    @pytest.mark.parametrize("B,T,H,D,dt,causal,window,Hkv", [
        (2, 256, 2, 256, jnp.float32, True, None, None),
        (1, 384, 2, 192, jnp.float32, False, None, None),   # unaligned D
        (2, 256, 4, 256, jnp.float32, True, 64, None),      # window
        (2, 256, 4, 256, jnp.float32, True, None, 2),       # GQA
    ])
    def test_matches_dense(self, B, T, H, D, dt, causal, window, Hkv):
        from paddle_tpu.ops import pallas_attention
        from paddle_tpu.ops.attention import dot_product_attention

        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.normal(size=(B, T, H, D)), dt)
        kv = (B, T, Hkv or H, D)
        k = jnp.asarray(rng.normal(size=kv), dt)
        v = jnp.asarray(rng.normal(size=kv), dt)
        got = pallas_attention.flash_attention(q, k, v, causal=causal,
                                               window=window)
        with jax.default_matmul_precision("highest"):
            want = dot_product_attention(q, k, v, causal=causal,
                                         window=window)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=2e-5, atol=2e-5)
        g1 = jax.grad(lambda q: jnp.sum(pallas_attention.flash_attention(
            q, k, v, causal=causal, window=window).astype(jnp.float32)))(q)
        with jax.default_matmul_precision("highest"):
            g2 = jax.grad(lambda q: jnp.sum(dot_product_attention(
                q, k, v, causal=causal, window=window)
                .astype(jnp.float32)))(q)
        np.testing.assert_allclose(np.asarray(g1, np.float32),
                                   np.asarray(g2, np.float32),
                                   rtol=1e-4, atol=1e-4)


class TestRnnMultiTile:
    @pytest.mark.parametrize("cell,B,T,D,reverse", [
        ("lstm", 4, 12, 640, False),
        ("gru", 4, 12, 640, False),
        ("lstm", 4, 24, 384, True),
        ("gru", 4, 24, 384, True),
    ])
    def test_matches_scan(self, cell, B, T, D, reverse, monkeypatch):
        from paddle_tpu.ops import pallas_rnn, rnn

        rng = np.random.default_rng(11)
        lens = jnp.asarray(rng.integers(1, T + 1, B), jnp.int32)
        z = jnp.zeros((B, D), jnp.float32)

        def forced_scan(fn, *args):
            monkeypatch.setenv("PADDLE_TPU_PALLAS", "0")
            try:
                return fn(*args)
            finally:
                monkeypatch.setenv("PADDLE_TPU_PALLAS", "1")

        if cell == "lstm":
            x = jnp.asarray(rng.standard_normal((B, T, 4 * D)) * 0.5,
                            jnp.float32)
            w = jnp.asarray(rng.standard_normal((D, 4 * D)) * D ** -0.5,
                            jnp.float32)
            peeps = jnp.zeros((3, D), jnp.float32)

            def fused(x, w):
                hs, hl, cl = pallas_rnn.lstm_fused(
                    x, lens, w, peeps, z, z, active_type="tanh",
                    gate_active_type="sigmoid", state_active_type="tanh",
                    reverse=reverse)
                return jnp.sum(hs * hs) + jnp.sum(hl) + jnp.sum(cl * cl)

            def ref(x, w):
                hs, hl, cl = rnn.lstm_scan(x, lens, w, None,
                                           reverse=reverse)
                return jnp.sum(hs * hs) + jnp.sum(hl) + jnp.sum(cl * cl)

            lf, gf = jax.value_and_grad(fused, argnums=(0, 1))(x, w)
            lr, gr = forced_scan(
                jax.value_and_grad(ref, argnums=(0, 1)), x, w)
        else:
            x = jnp.asarray(rng.standard_normal((B, T, 3 * D)) * 0.5,
                            jnp.float32)
            wg = jnp.asarray(rng.standard_normal((D, 2 * D)) * D ** -0.5,
                             jnp.float32)
            wc = jnp.asarray(rng.standard_normal((D, D)) * D ** -0.5,
                             jnp.float32)

            def fused(x, wg, wc):
                hs, hl = pallas_rnn.gru_fused(
                    x, lens, wg, wc, z, active_type="tanh",
                    gate_active_type="sigmoid", reverse=reverse)
                return jnp.sum(hs * hs) + jnp.sum(hl)

            def ref(x, wg, wc):
                hs, hl = rnn.gru_scan(x, lens, wg, wc, None,
                                      reverse=reverse)
                return jnp.sum(hs * hs) + jnp.sum(hl)

            lf, gf = jax.value_and_grad(fused, argnums=(0, 1, 2))(x, wg, wc)
            lr, gr = forced_scan(
                jax.value_and_grad(ref, argnums=(0, 1, 2)), x, wg, wc)
        np.testing.assert_allclose(float(lf), float(lr), rtol=1e-4)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)


class TestContextParallelMultiTile:
    @pytest.mark.parametrize("impl,B,T,H,D", [
        ("ring", 2, 64, 4, 256),
        ("ring", 2, 128, 2, 192),
        ("ulysses", 2, 64, 4, 256),
        ("ulysses", 2, 128, 8, 192),
    ])
    def test_matches_dense(self, impl, B, T, H, D):
        """Ring / all-to-all context parallelism over the seq mesh axis at
        head dims spanning multiple lane tiles."""
        from paddle_tpu.parallel.context import (ring_attention_sharded,
                                                 ulysses_attention_sharded)
        from paddle_tpu.parallel.mesh import make_mesh
        from paddle_tpu.ops.attention import dot_product_attention

        mesh = make_mesh(data=2, seq=4)
        fn = (ring_attention_sharded if impl == "ring"
              else ulysses_attention_sharded)
        rng = np.random.default_rng(9)
        q = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
        got = fn(mesh, q, k, v, causal=True)
        with jax.default_matmul_precision("highest"):
            want = dot_product_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)


class TestAdditiveWide:
    def test_bf16_grad_error_matches_jnp_formulation(self):
        """Like-for-like bar: against an fp32 oracle the kernel's bf16
        gradient error must be no worse than the jnp-bf16 formulation's —
        the error is the bf16 INPUT rounding, not the kernel (measured
        bitwise-identical in round 5)."""
        from paddle_tpu.ops import pallas_additive
        from paddle_tpu.ops.attention import additive_attention_step as ref

        B, T, Ds, D, Dv = 16, 40, 512, 512, 512
        dt = jnp.bfloat16
        rng = np.random.default_rng(3)
        dec = jnp.asarray(rng.normal(size=(B, Ds)), dt)
        w = jnp.asarray(rng.normal(size=(Ds, D)) * 0.1, dt)
        v = jnp.asarray(rng.normal(size=(D,)), dt)
        proj = jnp.asarray(rng.normal(size=(B, T, D)), dt)
        seq = jnp.asarray(rng.normal(size=(B, T, Dv)), dt)
        lens = rng.integers(1, T + 1, B).astype(np.int32)
        mask = jnp.arange(T)[None, :] < jnp.asarray(lens)[:, None]

        def gk(p):
            return jnp.sum(pallas_additive.additive_attention_step(
                dec, w, v, p, seq, mask).astype(jnp.float32))

        def gj(p):
            return jnp.sum(ref(dec, w, v, p, seq, mask)
                           .astype(jnp.float32))

        with jax.default_matmul_precision("highest"):
            g32 = np.asarray(jax.grad(lambda p: jnp.sum(ref(
                *(a.astype(jnp.float32) for a in (dec, w, v)), p,
                seq.astype(jnp.float32), mask)))(
                proj.astype(jnp.float32)))
        ek = np.abs(np.asarray(jax.grad(gk)(proj), np.float32) - g32).max()
        ej = np.abs(np.asarray(jax.grad(gj)(proj), np.float32) - g32).max()
        assert ek <= ej * 1.5 + 1e-6, (ek, ej)

    def test_unaligned_wide_fp32(self):
        from paddle_tpu.ops import pallas_additive
        from paddle_tpu.ops.attention import additive_attention_step as ref

        B, T, Ds, D, Dv = 3, 130, 257, 129, 255
        rng = np.random.default_rng(5)
        dec = jnp.asarray(rng.normal(size=(B, Ds)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(Ds, D)) * 0.1, jnp.float32)
        v = jnp.asarray(rng.normal(size=(D,)), jnp.float32)
        proj = jnp.asarray(rng.normal(size=(B, T, D)), jnp.float32)
        seq = jnp.asarray(rng.normal(size=(B, T, Dv)), jnp.float32)
        lens = rng.integers(1, T + 1, B).astype(np.int32)
        mask = jnp.arange(T)[None, :] < jnp.asarray(lens)[:, None]
        got = pallas_additive.additive_attention_step(dec, w, v, proj, seq,
                                                      mask)
        with jax.default_matmul_precision("highest"):
            want = ref(dec, w, v, proj, seq, mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)
