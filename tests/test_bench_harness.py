"""bench.py orchestrator logic — the record must always be parseable.

Unit-tests the pieces that made BENCH_r02 unrecoverable when they were
missing: last-known-good selection (newest complete record, errored/skipped
extras stripped), the degraded-record merge, and the PERF_LOG append gate.
The live subprocess paths (child bench, wedged-backend degradation) are
exercised against the real backend by the driver and tools/tpu_measure.py.
"""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_assemble_lkg_stitches_per_config_records(tmp_path):
    """The round-5 short-window queue banks ONE config per PERF_LOG record
    (bench.py + BENCH_ONLY); the assembler must stitch the newest
    occurrence of every part — whether nested under a full run or its own
    top-level record — each stamped measured_at, with errored/skipped
    parts never advertised as known-good."""
    bench = _load_bench()
    M = bench._METRIC_OF
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-07-29T10:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0,
                    "platform": "tpu",
                    "seq2seq": {"metric": M["seq2seq"], "value": 5.0},
                    "mnist": {"skipped": "budget"},
                    "lm": {"error": "timeout"}}},
        # newer per-config records (the BENCH_ONLY queue shape)
        {"ts": "2026-07-30T10:00:00+00:00",
         "record": {"metric": M["sentiment"], "value": 9.0,
                    "vs_baseline": 1.0,
                    "measured_at": "2026-07-30T10:00:00+00:00"}},
        {"ts": "2026-07-30T11:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 200.0, "vs_baseline": 4.0,
                    "platform": "tpu", "device_kind": "TPU v5 lite",
                    "measured_at": "2026-07-30T11:00:00+00:00"}},
        # decode-phase record merges into the seq2seq part
        {"ts": "2026-07-30T12:00:00+00:00",
         "record": {"metric": "wmt14_seq2seq_beam_decode_tokens_per_sec",
                    "value": 60000.0, "beam_decode_tokens_per_sec": 60000.0,
                    "measured_at": "2026-07-30T12:00:00+00:00"}},
        {"ts": "2026-07-30T13:00:00+00:00",
         "record": {"metric": M["vgg"], "error": "boom", "value": 0.0}},
        "not json at all",
    ]
    log.write_text("\n".join(r if isinstance(r, str) else json.dumps(r)
                             for r in rows) + "\n")
    bench._PERF_LOG = str(log)

    out = bench._assemble_lkg()
    assert out["value"] == 200.0                      # newest valid headline
    assert out["measured_at"] == "2026-07-30T11:00:00+00:00"
    assert out["platform"] == "tpu"                   # provenance preserved
    assert out["sentiment"]["value"] == 9.0
    # errored/skipped parts must NOT be advertised as known-good
    assert "mnist" not in out and "lm" not in out
    # seq2seq train came from the old full run; decode merged from the
    # newer phase-isolated record
    assert out["seq2seq"]["value"] == 5.0
    assert out["seq2seq"]["beam_decode_tokens_per_sec"] == 60000.0
    assert out["seq2seq"]["beam_decode_measured_at"] == \
        "2026-07-30T12:00:00+00:00"


def test_assemble_lkg_stitches_serving_record(tmp_path):
    """The continuous-batching serving metric (lm_serving_tok_per_sec)
    rides the same per-config queue shape: a top-level BENCH_ONLY=serving
    record must stitch into the assembled fallback under the `serving`
    key, newest occurrence winning."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving"] == "lm_serving_tok_per_sec"
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-07-30T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0,
                    "serving": {"metric": M["serving"], "value": 1000.0}}},
        {"ts": "2026-07-31T10:00:00+00:00",
         "record": {"metric": M["serving"], "value": 2000.0,
                    "occupancy": 0.9,
                    "measured_at": "2026-07-31T10:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving"]["value"] == 2000.0
    assert out["serving"]["occupancy"] == 0.9


def test_assemble_lkg_stitches_serving_prefix_record(tmp_path):
    """PR 7 wiring: the prefix-cache record (lm_serving_prefix_hit_rate +
    the prefill-tokens-saved companion) rides the same per-config queue
    shape — a top-level BENCH_ONLY=serving_prefix record must stitch into
    the assembled fallback under the `serving_prefix` key with its
    companion fields intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving_prefix"] == "lm_serving_prefix_hit_rate"
    assert "serving_prefix" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-01T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-08-02T10:00:00+00:00",
         "record": {"metric": M["serving_prefix"], "value": 0.94,
                    "lm_serving_prefill_tokens_saved_total": 5760,
                    "first_tok_ms_p50": 449.2,
                    "baseline_first_tok_ms_p50": 835.5,
                    "measured_at": "2026-08-02T10:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving_prefix"]["value"] == 0.94
    assert out["serving_prefix"][
        "lm_serving_prefill_tokens_saved_total"] == 5760
    assert out["serving_prefix"]["baseline_first_tok_ms_p50"] == 835.5


def test_assemble_lkg_stitches_serving_chunked_record(tmp_path):
    """PR 8 wiring: the chunked-prefill record (lm_serving_p99_itl_chunked_ms
    + the baseline/first-token tail companions) rides the same per-config
    queue shape — a top-level BENCH_ONLY=serving_chunked record must
    stitch into the assembled fallback under the `serving_chunked` key
    with the A/B companion fields intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving_chunked"] == "lm_serving_p99_itl_chunked_ms"
    assert "serving_chunked" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-02T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-08-03T10:00:00+00:00",
         "record": {"metric": M["serving_chunked"], "value": 12.4,
                    "baseline_itl_ms_p99": 310.7,
                    "itl_ms_p50": 9.8,
                    "baseline_first_tok_ms_p99": 1200.0,
                    "first_tok_ms_p99": 640.2,
                    "p99_itl_improved": True,
                    "measured_at": "2026-08-03T10:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving_chunked"]["value"] == 12.4
    assert out["serving_chunked"]["baseline_itl_ms_p99"] == 310.7
    assert out["serving_chunked"]["p99_itl_improved"] is True


def test_assemble_lkg_stitches_serving_fleet_record(tmp_path):
    """ISSUE 10 wiring (+ ISSUE 13's fleet trace-overhead probe): the
    fleet-router record (affinity-arm tok/s + the affinity-vs-random
    hit-rate comparison companions + the router-path tracing-overhead
    pct) rides the same per-config queue shape — a top-level
    BENCH_ONLY=serving_fleet record must stitch into the assembled
    fallback under the `serving_fleet` key with the companions intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving_fleet"] == "lm_serving_fleet_tok_per_sec"
    assert "serving_fleet" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-03T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-08-04T10:00:00+00:00",
         "record": {"metric": M["serving_fleet"], "value": 5120.4,
                    "single_tok_per_sec": 2700.1,
                    "speedup_vs_single": 1.896,
                    "hit_rate_affinity": 0.91,
                    "hit_rate_random": 0.55,
                    "affinity_hit_gt_random": True,
                    "lm_serving_fleet_trace_overhead_pct": 0.7,
                    "trace_on_tok_per_sec": 5084.6,
                    "measured_at": "2026-08-04T10:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving_fleet"]["value"] == 5120.4
    assert out["serving_fleet"]["hit_rate_affinity"] == 0.91
    assert out["serving_fleet"]["hit_rate_random"] == 0.55
    assert out["serving_fleet"]["affinity_hit_gt_random"] is True
    # the fleet trace-overhead probe (router + replica tracing ON through
    # the router path, <= 2% budget) survives the per-part stitch
    assert out["serving_fleet"][
        "lm_serving_fleet_trace_overhead_pct"] == 0.7
    assert out["serving_fleet"]["trace_on_tok_per_sec"] == 5084.6


def test_assemble_lkg_stitches_serving_disagg_record(tmp_path):
    """ISSUE 19 wiring: the disaggregated prefill/decode record
    (role-split tok/s vs the colocated arm + the kv_push transfer
    ledger) rides the same per-config queue shape — a top-level
    BENCH_ONLY=serving_disagg record must stitch into the assembled
    fallback under the `serving_disagg` key with the companions
    intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving_disagg"] == "lm_serving_disagg_tok_per_sec"
    assert "serving_disagg" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-03T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-08-05T10:00:00+00:00",
         "record": {"metric": M["serving_disagg"], "value": 4980.2,
                    "coloc_tok_per_sec": 4410.7,
                    "speedup_vs_coloc": 1.129,
                    "first_tok_ms_p50": 21.4,
                    "first_tok_ms_p99": 48.9,
                    "coloc_first_tok_ms_p50": 35.6,
                    "coloc_first_tok_ms_p99": 92.3,
                    "kv_pushes": 64.0,
                    "kv_push_failures": 0.0,
                    "kv_fallbacks": 0.0,
                    "pages_shipped": 512.0,
                    "ok": True,
                    "measured_at": "2026-08-05T10:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving_disagg"]["value"] == 4980.2
    assert out["serving_disagg"]["coloc_tok_per_sec"] == 4410.7
    assert out["serving_disagg"]["speedup_vs_coloc"] == 1.129
    # the transfer-plane reconcile ledger (pages genuinely shipped,
    # zero push failures or fallbacks) survives the per-part stitch
    assert out["serving_disagg"]["kv_pushes"] == 64.0
    assert out["serving_disagg"]["kv_push_failures"] == 0.0
    assert out["serving_disagg"]["kv_fallbacks"] == 0.0
    assert out["serving_disagg"]["pages_shipped"] == 512.0
    assert out["serving_disagg"]["ok"] is True


def test_assemble_lkg_stitches_serving_tp_record(tmp_path):
    """ISSUE 11 wiring: the tensor-parallel sharded-decode record
    (lm_serving_tp_tok_per_sec + the 1-vs-N-shard A/B companions incl.
    the per-shard pool bytes) rides the same per-config queue shape —
    a top-level BENCH_ONLY=serving_tp record must stitch into the
    assembled fallback under the `serving_tp` key with the companions
    intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving_tp"] == "lm_serving_tp_tok_per_sec"
    assert "serving_tp" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-03T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-08-04T11:00:00+00:00",
         "record": {"metric": M["serving_tp"], "value": 8412.9,
                    "mesh_model": 2,
                    "single_tok_per_sec": 5100.3,
                    "speedup_vs_single": 1.65,
                    "pool_bytes_per_shard": 402653184,
                    "single_pool_bytes": 805306368,
                    "pool_shrink_vs_single": 2.0,
                    "sig_stable": True,
                    "measured_at": "2026-08-04T11:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving_tp"]["value"] == 8412.9
    assert out["serving_tp"]["pool_shrink_vs_single"] == 2.0
    assert out["serving_tp"]["speedup_vs_single"] == 1.65
    assert out["serving_tp"]["sig_stable"] is True


def test_assemble_lkg_stitches_serving_spec_record(tmp_path):
    """ISSUE 12 wiring: the speculative-decoding record
    (lm_serving_spec_tok_per_sec + the accept rate and the drafted/
    accepted/emitted reconciliation companions) rides the same
    per-config queue shape — a top-level BENCH_ONLY=serving_spec record
    must stitch into the assembled fallback under the `serving_spec`
    key with the companions intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving_spec"] == "lm_serving_spec_tok_per_sec"
    assert "serving_spec" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-03T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-08-04T12:00:00+00:00",
         "record": {"metric": M["serving_spec"], "value": 9120.7,
                    "lm_serving_spec_accept_rate": 0.62,
                    "baseline_tok_per_sec": 4100.2,
                    "speedup_vs_baseline": 2.22,
                    "drafted": 12000, "accepted": 7440,
                    "chains": 4210, "spec_tokens": 11650,
                    "reconcile_ok": True, "sig_stable": True,
                    "measured_at": "2026-08-04T12:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving_spec"]["value"] == 9120.7
    assert out["serving_spec"]["lm_serving_spec_accept_rate"] == 0.62
    assert out["serving_spec"]["speedup_vs_baseline"] == 2.22
    assert out["serving_spec"]["reconcile_ok"] is True
    assert out["serving_spec"]["sig_stable"] is True


def test_assemble_lkg_stitches_serving_spill_record(tmp_path):
    """ISSUE 17 wiring: the host-spill record (lm_serving_spill_hit_rate
    + the off-arm comparison and spill/restore page counters) rides the
    same per-config queue shape — a top-level BENCH_ONLY=serving_spill
    record must stitch into the assembled fallback under the
    `serving_spill` key with the companions intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["serving_spill"] == "lm_serving_spill_hit_rate"
    assert "serving_spill" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-03T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-08-04T12:00:00+00:00",
         "record": {"metric": M["serving_spill"], "value": 0.91,
                    "lm_serving_spill_tok_per_sec": 5120.5,
                    "off_hit_rate": 0.42, "hit_rate_improved": True,
                    "spilled_pages": 480, "restored_pages": 455,
                    "restore_hits": 120, "restore_tokens_saved": 6900,
                    "reconcile_ok": True, "sig_stable": True,
                    "measured_at": "2026-08-04T12:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving_spill"]["value"] == 0.91
    assert out["serving_spill"]["lm_serving_spill_tok_per_sec"] == 5120.5
    assert out["serving_spill"]["off_hit_rate"] == 0.42
    assert out["serving_spill"]["hit_rate_improved"] is True
    assert out["serving_spill"]["restored_pages"] == 455
    assert out["serving_spill"]["reconcile_ok"] is True
    assert out["serving_spill"]["sig_stable"] is True


def test_serving_latency_fields_ride_the_lkg_and_freshness_paths(tmp_path):
    """PR 4 wiring: the serving record's p99 per-token latency companion
    (lm_serving_p99_tok_latency_ms) must survive _assemble_lkg, and the
    tpu_measure queue's freshness gate must treat a record WITHOUT the
    field as stale (pre-latency-era records force one re-measure)."""
    bench = _load_bench()
    M = bench._METRIC_OF
    log = tmp_path / "PERF_LOG.jsonl"
    old = {"ts": "2026-08-01T10:00:00+00:00",
           "record": {"metric": M["serving"], "value": 1500.0,
                      "measured_at": "2026-08-01T10:00:00+00:00"}}
    new = {"ts": "2026-08-02T10:00:00+00:00",
           "record": {"metric": M["serving"], "value": 2100.0,
                      "tok_latency_ms_p50": 4.2,
                      "lm_serving_p99_tok_latency_ms": 9.7,
                      "measured_at": "2026-08-02T10:00:00+00:00"}}
    log.write_text(json.dumps(old) + "\n" + json.dumps(new) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["serving"]["lm_serving_p99_tok_latency_ms"] == 9.7

    # freshness: need_field distinguishes the eras (tools/tpu_measure.py
    # passes it for the bench_serving_record step)
    sys.path.insert(0, os.path.join(REPO, ""))
    os.environ["BENCH_PERF_LOG"] = str(log)
    try:
        import importlib

        import tools.tpu_measure as tm
        importlib.reload(tm)
        assert tm._metric_fresh(M["serving"], 1e6,
                                need_field="lm_serving_p99_tok_latency_ms")
        # only the latency-era record satisfies it: rewrite with old alone
        log.write_text(json.dumps(old) + "\n")
        assert not tm._metric_fresh(
            M["serving"], 1e6, need_field="lm_serving_p99_tok_latency_ms")
        assert tm._metric_fresh(M["serving"], 1e6)
    finally:
        del os.environ["BENCH_PERF_LOG"]


def test_assemble_lkg_decode_only_survives_missing_train(tmp_path):
    """s2s_decode can bank while s2s_train wedges — the measured decode
    number must still surface in the assembled fallback."""
    bench = _load_bench()
    M = bench._METRIC_OF
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-07-30T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0}},
        {"ts": "2026-07-30T12:00:00+00:00",
         "record": {"metric": "wmt14_seq2seq_beam_decode_tokens_per_sec",
                    "value": 61000.0,
                    "beam_decode_tokens_per_sec": 61000.0,
                    "measured_at": "2026-07-30T12:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["seq2seq"]["beam_decode_tokens_per_sec"] == 61000.0


def test_ts_newer_parses_before_comparing():
    """ADVICE r5 regression: measured_at ordering must ISO-parse, not
    string-compare — a non-UTC offset (or naive-vs-aware mix) can rank a
    STALE timestamp above a newer one lexicographically."""
    bench = _load_bench()
    # 15:00+05:00 == 10:00Z, OLDER than 11:00Z — but string-wise "15" > "11"
    assert not bench._ts_newer("2026-07-30T15:00:00+05:00",
                               "2026-07-30T11:00:00+00:00")
    assert bench._ts_newer("2026-07-30T11:00:00+00:00",
                           "2026-07-30T15:00:00+05:00")
    # 'Z' suffix and naive (assumed UTC) both parse
    assert bench._ts_newer("2026-07-30T11:00:00Z", "2026-07-30T10:59:59")
    # unparseable falls back to the string compare (empty = oldest)
    assert bench._ts_newer("2026-07-30T11:00:00+00:00", "")
    assert not bench._ts_newer("", "2026-07-30T11:00:00+00:00")


def test_assemble_lkg_orders_mixed_timestamp_formats(tmp_path):
    """A per-config top-level record measured at 11:00Z must supersede a
    nested part stamped 15:00+05:00 (= 10:00Z): the lexicographic compare
    picked the stale nested part here (ADVICE r5)."""
    bench = _load_bench()
    M = bench._METRIC_OF
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-07-30T12:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0,
                    "measured_at": "2026-07-30T12:00:00+00:00",
                    "mnist": {"metric": M["mnist"], "value": 111.0,
                              "measured_at": "2026-07-30T15:00:00+05:00"}}},
        {"ts": "2026-07-30T11:00:00+00:00",
         "record": {"metric": M["mnist"], "value": 222.0,
                    "vs_baseline": 1.0,
                    "measured_at": "2026-07-30T11:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["mnist"]["value"] == 222.0, (
        "stale +05:00-stamped part selected over the newer UTC record")


def test_degraded_record_merges_lkg(tmp_path):
    bench = _load_bench()
    log = tmp_path / "PERF_LOG.jsonl"
    log.write_text(json.dumps(
        {"ts": "2026-07-30T10:00:00+00:00",
         "record": {"metric": "vgg16_cifar10_train_samples_per_sec_per_chip",
                    "value": 123.0, "vs_baseline": 2.5, "mfu": 0.41,
                    "platform": "tpu"}}) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._degraded_record("tunnel died")
    assert out["error"] == "tunnel died" and out["degraded"] is True
    assert out["value"] == 123.0 and out["mfu"] == 0.41
    assert out["platform"] == "tpu"           # provenance preserved
    assert "last-known-good" in out["degraded_source"]
    json.dumps(out)                           # always serializable


def test_degraded_record_without_lkg(tmp_path):
    bench = _load_bench()
    bench._PERF_LOG = str(tmp_path / "absent.jsonl")
    out = bench._degraded_record("nothing ever measured")
    assert out["value"] == 0.0 and out["vs_baseline"] == 0.0
    assert out["degraded"] is True and "degraded_source" not in out


def test_append_perf_log_roundtrip(tmp_path):
    bench = _load_bench()
    bench._PERF_LOG = str(tmp_path / "PERF_LOG.jsonl")
    bench._append_perf_log({"metric": bench._METRIC_OF["vgg"], "value": 7.0,
                            "vs_baseline": 1.1})
    out = bench._assemble_lkg()
    assert out["value"] == 7.0
    assert "T" in out["measured_at"]          # ISO timestamp (from log ts)


def test_spawn_reports_timeout_as_error():
    bench = _load_bench()
    rc, out, err = bench._run_group(
        [sys.executable, "-c", "import time; time.sleep(30)"], 1.5)
    assert rc is None                         # timed out, group killed


def test_spawn_recovers_interim_record_on_timeout(monkeypatch):
    """A child killed mid-phase (the seq2seq decode wedge) must yield its
    last banked BENCH_JSON line, marked partial — not a bare timeout."""
    bench = _load_bench()
    interim = {"metric": "wmt14_seq2seq_train_samples_per_sec_per_chip",
               "value": 123.0, "beam_decode": "pending"}
    stdout = ("noise\nBENCH_JSON:" + json.dumps(interim) +
              "\nmore noise after the bank\n")
    monkeypatch.setattr(bench, "_run_group",
                        lambda argv, t: (None, stdout, ""))
    out = bench._spawn("seq2seq", 900)
    assert out["value"] == 123.0
    assert "partial" in out and "error" not in out
    # ISSUE 6: the interim record carries the degraded provenance flag —
    # it was measured inside a wedging window (the r04/r05 init-hang
    # pattern), so LKG assembly must be able to skip it explicitly
    assert out["degraded"] is True

    # no banked line -> the plain timeout error as before
    monkeypatch.setattr(bench, "_run_group",
                        lambda argv, t: (None, "no json here", ""))
    out = bench._spawn("seq2seq", 900)
    assert "error" in out and "timeout" in out["error"]


def test_assemble_lkg_skips_degraded_records_explicitly(tmp_path):
    """ISSUE 6: records (and nested parts) flagged `degraded` — a wedged
    child's interim numbers, or parts echoed into a degraded fallback —
    must be skipped by provenance, NOT by hoping a healthy record has a
    newer timestamp.  Here the degraded records are strictly NEWER than
    the healthy ones, which timestamp ordering alone would get wrong."""
    bench = _load_bench()
    M = bench._METRIC_OF
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        # the healthy measurements — OLDER than everything degraded
        {"ts": "2026-07-28T10:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0, "vs_baseline": 2.0,
                    "platform": "tpu",
                    "measured_at": "2026-07-28T10:00:00+00:00",
                    "lm": {"metric": M["lm"], "value": 5000.0,
                           "measured_at": "2026-07-28T10:00:00+00:00"}}},
        # a newer top-level record measured in a degraded window (a killed
        # child's interim bank — _spawn stamps partial + degraded)
        {"ts": "2026-07-29T10:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 1.0, "vs_baseline": 0.1,
                    "partial": "child killed after 900s; interim record",
                    "degraded": True,
                    "measured_at": "2026-07-29T10:00:00+00:00"}},
        # a newer full record whose nested lm part is a degraded interim
        {"ts": "2026-07-30T10:00:00+00:00",
         "record": {"metric": M["sentiment"], "value": 9.0,
                    "measured_at": "2026-07-30T10:00:00+00:00",
                    "lm": {"metric": M["lm"], "value": 2.0,
                           "degraded": True,
                           "measured_at": "2026-07-30T10:00:00+00:00"}}},
        # a degraded fallback record echoing LKG parts (parent flag) —
        # its nested serving echo must not read as a fresh measurement
        {"ts": "2026-07-31T10:00:00+00:00",
         "record": {"error": "tunnel died", "degraded": True,
                    "metric": M["vgg"], "value": 100.0,
                    "serving": {"metric": M["serving"], "value": 777.0,
                                "measured_at":
                                    "2026-07-31T10:00:00+00:00"}}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)

    out = bench._assemble_lkg()
    assert out["value"] == 100.0              # healthy headline, not 1.0
    assert out["measured_at"] == "2026-07-28T10:00:00+00:00"
    assert out["lm"]["value"] == 5000.0       # healthy part, not 2.0
    # the degraded fallback's echoed serving part never became "measured"
    assert "serving" not in out
    assert out["sentiment"]["value"] == 9.0   # healthy parts still stitch


def test_assemble_lkg_stitches_train_dist_record(tmp_path):
    """ISSUE 14 wiring: the parameter-server training record
    (train_dist_samples_per_sec + the 1-trainer arm and scaling
    efficiency) rides the per-config queue shape — a top-level
    BENCH_ONLY=train_dist record must stitch into the assembled fallback
    under the `train_dist` key with the companions intact."""
    bench = _load_bench()
    M = bench._METRIC_OF
    assert M["train_dist"] == "train_dist_samples_per_sec"
    assert "train_dist" in bench.BENCHES
    log = tmp_path / "PERF_LOG.jsonl"
    rows = [
        {"ts": "2026-08-03T09:00:00+00:00",
         "record": {"metric": M["vgg"], "value": 100.0,
                    "vs_baseline": 2.0}},
        {"ts": "2026-08-04T12:00:00+00:00",
         "record": {"metric": M["train_dist"], "value": 5321.7,
                    "trainers": 2,
                    "single_samples_per_sec": 2900.4,
                    "scaling_efficiency": 0.9174,
                    "fleet_wall_s": 3.2,
                    "train_dist_trace_overhead_pct": 0.8,
                    "trace_overhead_spread_pct": 2.1,
                    "trace_off_samples_per_sec": 5400.0,
                    "trace_on_samples_per_sec": 5356.8,
                    "measured_at": "2026-08-04T12:00:00+00:00"}},
    ]
    log.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    bench._PERF_LOG = str(log)
    out = bench._assemble_lkg()
    assert out["train_dist"]["value"] == 5321.7
    assert out["train_dist"]["scaling_efficiency"] == 0.9174
    assert out["train_dist"]["single_samples_per_sec"] == 2900.4
    # ISSUE 15 wiring: the live-flip trace-overhead probe's fields ride
    # the same record through the fallback assembly
    assert out["train_dist"]["train_dist_trace_overhead_pct"] == 0.8
    assert out["train_dist"]["trace_overhead_spread_pct"] == 2.1
    assert out["train_dist"]["trace_off_samples_per_sec"] == 5400.0
