"""End-to-end seq2seq: attention training + beam-search generation on the
sequence-reversal task (ref test analog:
paddle/trainer/tests/test_recurrent_machine_generation.cpp — train a gen
model, decode, compare against expected output)."""

import os
import sys

import numpy as np
import jax.numpy as jnp

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.builder import GraphExecutor
from paddle_tpu.graph.generator import generate
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer
import pytest

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


CONFIG = os.path.join(REPO, "demo/seqToseq/seqToseq_net.py")


def test_train_then_beam_generate():
    os.chdir(REPO)  # provider file lists are repo-relative
    cfg = parse_config(CONFIG, "dict_size=32")
    tr = Trainer(cfg, seed=3)
    first = tr.train_one_pass(log_period=0)
    stats = first
    for _ in range(9):
        stats = tr.train_one_pass(log_period=0)
    assert stats["cost"] < first["cost"]
    assert stats["classification_error"] < 0.02, stats

    gcfg = parse_config(CONFIG, "dict_size=32,is_generating=1,beam_size=3")
    gex = GraphExecutor(gcfg.model_config)
    # generation graph must reference exactly the trained parameter set
    gparams = {}
    for p in gcfg.model_config.parameters:
        assert p.name in tr.params, f"gen param {p.name} missing from training"
        gparams[p.name] = tr.params[p.name]

    src = [[5, 9, 12, 7], [20, 4, 30, 11, 6], [3, 3, 8]]
    B, T = len(src), max(len(s) for s in src)
    ids = np.zeros((B, T), np.int32)
    for i, s in enumerate(src):
        ids[i, :len(s)] = s
    lengths = np.asarray([len(s) for s in src], np.int32)
    feed = {"source_language_word": Argument(ids=jnp.asarray(ids),
                                             lengths=jnp.asarray(lengths))}
    seqs, scores = generate(gex, gparams, feed)
    seqs = np.asarray(seqs)
    correct = 0
    for i, s in enumerate(src):
        got = seqs[i, 0].tolist()
        got = got[:got.index(1)] if 1 in got else got
        if got == s[::-1]:
            correct += 1
    assert correct >= 2, f"beam decode failed: {seqs[:, 0]}"
    # beams are sorted best-first
    assert np.all(np.diff(np.asarray(scores), axis=1) <= 1e-5)
