"""Sampler edge cases for `lm_decode.pick_next` / `nucleus_filter` and the
serving per-slot twin (`serving/sampler.py:pick_next_per_slot`):

  * top_p = 1.0 must be a true no-op (the (0,1) gate, not a float knife
    edge at cumulative mass 1.0),
  * logit ties AT the k-th value must not widen the top-k support,
  * the probs-layer path (`_is_probs` -> sample through log) must floor
    zero probabilities instead of producing -inf/nan,
  * and every per-slot row must reproduce the scalar sampler exactly —
    the serving engine's sampled-decode exactness rests on it."""

import numpy as np

import jax
import jax.numpy as jnp

from paddle_tpu.graph.lm_decode import nucleus_filter, pick_next
from paddle_tpu.serving.sampler import pick_next_per_slot


def _logits(rows):
    return jnp.asarray(rows, jnp.float32)


def test_top_p_one_is_exact_noop():
    """top_p=1.0 (and 0.0) disables the nucleus cut exactly: identical
    draws to the unfiltered sampler under the same key, and
    nucleus_filter returns its input unchanged."""
    logits = _logits([[0.3, -1.0, 2.0, 0.0, 1.4]])
    for p in (0.0, 1.0):
        np.testing.assert_array_equal(np.asarray(nucleus_filter(logits, p)),
                                      np.asarray(logits))
    for seed in range(8):
        key = jax.random.PRNGKey(seed)
        np.testing.assert_array_equal(
            np.asarray(pick_next(logits, key, temperature=0.7, top_p=1.0)),
            np.asarray(pick_next(logits, key, temperature=0.7)))


def test_top_k_tie_at_kth_value_does_not_widen_support():
    """[3, 2, 2, 1] with top_k=2: the tie at the 2nd value breaks to the
    LOWER index (lax.top_k order) — index 2 must never be drawn, and both
    kept tokens must actually appear."""
    logits = _logits([[3.0, 2.0, 2.0, 1.0]])
    drawn = {int(np.asarray(pick_next(
        logits, jax.random.PRNGKey(s), temperature=1.5, top_k=2))[0])
        for s in range(64)}
    assert drawn == {0, 1}, drawn


def test_probs_layer_log_path():
    """is_probs=True samples through log(max(p, 1e-30)): greedy equals
    argmax of the probabilities, zero-probability tokens are never drawn,
    and the draw equals sampling the floored log directly."""
    probs = _logits([[0.0, 0.3, 0.7, 0.0]])
    assert int(np.asarray(pick_next(probs, None, is_probs=True))[0]) == 2
    floored = jnp.log(jnp.maximum(probs, 1e-30))
    for seed in range(16):
        key = jax.random.PRNGKey(seed)
        got = pick_next(probs, key, temperature=1.0, is_probs=True)
        assert int(np.asarray(got)[0]) in (1, 2)
        np.testing.assert_array_equal(
            np.asarray(got),
            np.asarray(pick_next(floored, key, temperature=1.0)))


def test_greedy_ignores_knobless_key():
    logits = _logits([[0.1, 5.0, -2.0]])
    out = pick_next(logits, None)          # temperature=0: key never touched
    assert int(np.asarray(out)[0]) == 1


def test_per_slot_rows_match_scalar_sampler():
    """The serving sampler's row s must reproduce pick_next on row s alone
    — heterogeneous knobs (greedy / top-k / nucleus / full / tied logits)
    under per-slot keys, in one call."""
    rng = np.random.default_rng(0)
    S, V = 6, 17
    last = rng.normal(size=(S, V)).astype(np.float32)
    last[4, :4] = 2.0                       # ties for the top-k row
    last = jnp.asarray(last)
    temp = np.asarray([0.0, 0.8, 0.7, 1.2, 1.0, 0.0], np.float32)
    topk = np.asarray([0, 5, 0, 0, 3, 0], np.int32)
    topp = np.asarray([0.0, 0.0, 0.9, 1.0, 0.0, 0.0], np.float32)
    keys = np.asarray([np.asarray(jax.random.PRNGKey(100 + s))
                       for s in range(S)])
    for is_probs in (False, True):
        rows = jnp.abs(last) if is_probs else last
        got = np.asarray(pick_next_per_slot(
            rows, jnp.asarray(keys), jnp.asarray(temp), jnp.asarray(topk),
            jnp.asarray(topp), is_probs=is_probs))
        for s in range(S):
            want = pick_next(rows[s:s + 1], jnp.asarray(keys[s]),
                             temperature=float(temp[s]), top_k=int(topk[s]),
                             top_p=float(topp[s]), is_probs=is_probs)
            assert got[s] == int(np.asarray(want)[0]), \
                f"slot {s} diverged (is_probs={is_probs})"
