"""CTC loss oracles (ref: paddle/gserver/layers/LinearChainCTC.cpp; test
pattern of test_LayerGrad's CTC cases):

1. brute force — enumerate every alignment path of a tiny case and sum
   probabilities; the alpha recursion must match exactly.
2. torch.nn.functional.ctc_loss — an independent full-scale implementation.
3. finite differences — gradient of the loss w.r.t. the probabilities.
"""

import itertools

import numpy as np
import pytest

import jax

from paddle_tpu.utils import jax_compat
import jax.numpy as jnp

from paddle_tpu.ops.ctc import ctc_loss

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")



def _collapse(path, blank):
    out = []
    prev = None
    for p in path:
        if p != prev and p != blank:
            out.append(p)
        prev = p
    return out


def test_matches_brute_force_enumeration():
    rng = np.random.default_rng(0)
    T, C, blank = 4, 3, 0
    logits = rng.normal(size=(1, T, C))
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    label = [1, 2]

    total = 0.0
    for path in itertools.product(range(C), repeat=T):
        if _collapse(path, blank) == label:
            total += np.prod([probs[0, t, c] for t, c in enumerate(path)])

    got = ctc_loss(jnp.asarray(probs, jnp.float32),
                   jnp.asarray([T], jnp.int32),
                   jnp.asarray([label], jnp.int32),
                   jnp.asarray([len(label)], jnp.int32), blank=blank)
    np.testing.assert_allclose(float(got[0]), -np.log(total), rtol=1e-5)


def test_matches_torch_ctc():
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(1)
    B, T, C, L, blank = 3, 9, 5, 3, 0
    logits = rng.normal(size=(B, T, C)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    in_lens = np.array([9, 7, 5], np.int64)
    lbl_lens = np.array([3, 2, 1], np.int64)
    labels = rng.integers(1, C, (B, L)).astype(np.int64)

    want = torch.nn.functional.ctc_loss(
        torch.log(torch.tensor(probs)).transpose(0, 1),  # [T, B, C]
        torch.tensor(labels), torch.tensor(in_lens), torch.tensor(lbl_lens),
        blank=blank, reduction="none", zero_infinity=False).numpy()

    got = ctc_loss(jnp.asarray(probs), jnp.asarray(in_lens, jnp.int32),
                   jnp.asarray(labels, jnp.int32),
                   jnp.asarray(lbl_lens, jnp.int32), blank=blank)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_grad_finite_differences():
    rng = np.random.default_rng(2)
    B, T, C, L = 2, 5, 4, 2
    with jax_compat.enable_x64():
        logits = jnp.asarray(rng.normal(size=(B, T, C)), jnp.float64)
        in_lens = jnp.asarray([5, 4], jnp.int32)
        labels = jnp.asarray([[1, 2], [3, 0]], jnp.int32)
        lbl_lens = jnp.asarray([2, 1], jnp.int32)

        def loss(logits):
            probs = jax.nn.softmax(logits, axis=-1)
            return jnp.sum(ctc_loss(probs, in_lens, labels, lbl_lens))

        g = jax.grad(loss)(logits)
        eps = 1e-6
        for _ in range(12):
            b, t, c = (int(rng.integers(B)), int(rng.integers(T)),
                       int(rng.integers(C)))
            d = jnp.zeros_like(logits).at[b, t, c].set(eps)
            fd = (loss(logits + d) - loss(logits - d)) / (2 * eps)
            np.testing.assert_allclose(float(g[b, t, c]), float(fd),
                                       rtol=1e-4, atol=1e-7)
