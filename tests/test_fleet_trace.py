"""Fleet-scope distributed tracing (ISSUE 13 acceptance).

One traced request through a 2-replica fleet must yield ONE merged,
Perfetto-loadable trace in which the router's placement/relay spans and
the replica's engine lifecycle spans share a trace_id on DISTINCT
process tracks, with the replica spans parented on the router's ingress
span — and the `done` frame's timing breakdown must reconcile: phases
sum to the engine total, the totals nest engine <= server <= router <=
client-observed wall time.  Replicas here are in-process ServingServer
instances, each with its OWN Tracer ring (the per-process shape the
`trace` RPC snapshots in a real deployment), so the cross-process stitch
is exercised without subprocess cost.
"""

import time

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.fleet import FleetRouter
from paddle_tpu.obs import Tracer, merge_chrome
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving.client import ServingClient
from paddle_tpu.serving.server import ServingServer
from paddle_tpu.trainer.trainer import Trainer

PAGE = 8


@pytest.fixture(scope="module")
def tiny_tr():
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _traced_fleet(tr, n):
    """n in-process replicas (each with a private enabled Tracer) + a
    router (its own enabled Tracer) joined to all of them."""
    reps = []
    for _ in range(n):
        tracer = Tracer()
        tracer.enabled = True
        eng = ServingEngine(tr.executor, tr.params, num_slots=2,
                            page_size=PAGE, max_context=64, tracer=tracer)
        srv = ServingServer(eng, max_queue=16)
        host, port = srv.start_background()
        reps.append((srv, host, port))
    rt_tracer = Tracer()
    rt_tracer.enabled = True
    rt = FleetRouter(port=0, replicas=[(h, p) for _, h, p in reps],
                     poll_interval_s=0.1, heartbeat_misses=100,
                     tracer=rt_tracer)
    host, port = rt.start_background()
    return rt, host, port, reps


def _stop_all(rt, reps):
    rt.stop_background(drain=True)
    for srv, _, _ in reps:
        srv.stop_background(drain=True)


def _spans_for_trace(pull, tid):
    return [s for s in pull["spans"]
            if (s.get("attrs") or {}).get("trace_id") == tid]


def test_fleet_e2e_one_trace_id_and_timing_reconciles(tiny_tr):
    """The ISSUE 13 acceptance path, end to end over real TCP."""
    rt, host, port, reps = _traced_fleet(tiny_tr, 2)
    try:
        with ServingClient(host, port) as c:
            t0 = time.perf_counter()
            rid = c.submit([2, 7, 9, 4, 5], max_new=8, seed=3)
            res = c.collect([rid])[rid]
            wall_ms = (time.perf_counter() - t0) * 1e3
            router_pull = c.trace()
            agg = c.metrics(aggregate=True)
        replica_pulls = []
        for _, h, p in reps:
            with ServingClient(h, p) as rc:
                replica_pulls.append(rc.trace())

        # -- (c) the timing breakdown, no trace viewer needed -------------
        timing = res["timing"]
        assert timing is not None
        phase_sum = (timing["queue_ms"] + timing["prefill_ms"]
                     + timing["decode_ms"] + timing["replay_ms"])
        assert abs(phase_sum - timing["total_ms"]) < 1.0
        # totals nest: engine <= server <= router <= client wall (each
        # gap is a real hop; generous slack only for scheduler jitter)
        assert timing["total_ms"] <= timing["request_ms"] + 1.0
        assert timing["request_ms"] <= timing["router"]["total_ms"] + 50.0
        assert timing["router"]["total_ms"] <= wall_ms + 50.0
        # ...and the breakdown accounts for the client-observed latency:
        # the unattributed remainder (wire + pump pickup) is bounded
        assert wall_ms - timing["total_ms"] < 1500.0
        assert timing["router"]["hops"] == 1
        assert timing["router"]["retries"] == 0
        assert timing["router"]["replica"] in ("r0", "r1")

        # -- (a) one trace_id threads router + replica spans --------------
        ingress = [s for s in router_pull["spans"]
                   if s["name"] == "ingress"]
        assert len(ingress) == 1
        tid = ingress[0]["attrs"]["trace_id"]
        sid = ingress[0]["attrs"]["span_id"]
        place = [s for s in _spans_for_trace(router_pull, tid)
                 if s["name"] == "place"]
        assert len(place) == 1 and place[0]["attrs"]["parent"] == sid
        assert place[0]["attrs"]["policy"] in ("affinity", "least_loaded")
        served_rid = timing["router"]["replica"]
        assert place[0]["attrs"]["replica"] == served_rid
        # relay marks the FIRST streamed token only (the router-side
        # TTFT stitch point; per-token markers would put tracer work on
        # the loop thread's per-token critical path) — the relayed count
        # rides on the ingress span instead
        relays = [s for s in _spans_for_trace(router_pull, tid)
                  if s["name"] == "relay"]
        assert len(relays) == 1 and relays[0]["attrs"]["index"] == 0
        assert ingress[0]["attrs"]["streamed"] == len(res["stream"])

        # exactly ONE replica carries the trace; its lifecycle spans are
        # parented on the router's ingress span
        carrying = [p for p in replica_pulls if _spans_for_trace(p, tid)]
        assert len(carrying) == 1
        rep_spans = _spans_for_trace(carrying[0], tid)
        names = [s["name"] for s in rep_spans]
        assert names == ["queued", "prefill", "decode", "done"]
        assert all(s["attrs"]["parent"] == sid for s in rep_spans)

        # -- (b) the merged trace is Perfetto-loadable, per-process ------
        pulls = [router_pull] + replica_pulls
        merged = merge_chrome([{"spans": p["spans"],
                                "process": p["process"],
                                "offset_s": p["offset_s"]}
                               for p in pulls])
        assert set(merged) == {"traceEvents", "displayTimeUnit"}
        procs = [e for e in merged["traceEvents"]
                 if e.get("name") == "process_name"]
        assert len(procs) == 3
        assert len({p["pid"] for p in procs}) == 3     # distinct tracks
        roles = [p["args"]["name"].split()[0] for p in procs]
        assert sorted(roles) == ["replica", "replica", "router"]
        for ev in merged["traceEvents"]:
            assert ev["ph"] in ("M", "X", "i")
            if ev["ph"] != "M":
                assert ev["ts"] >= 0.0                 # global rebase
        # the same request's router and replica spans sit on different
        # pids but aligned clocks: the replica's queued span starts
        # within the router's ingress span (offsets applied)
        by_pid = {}
        for ev in merged["traceEvents"]:
            if ev["ph"] == "X" and (ev.get("args") or {}).get(
                    "trace_id") == tid:
                by_pid.setdefault(ev["pid"], []).append(ev)
        assert len(by_pid) == 2
        ing_ev = next(e for pid in by_pid for e in by_pid[pid]
                      if e["name"] == "ingress")
        q_ev = next(e for pid in by_pid for e in by_pid[pid]
                    if e["name"] == "queued")
        assert ing_ev["pid"] != q_ev["pid"]
        assert ing_ev["ts"] - 1e5 <= q_ev["ts"] <= \
            ing_ev["ts"] + ing_ev["dur"] + 1e5         # 100ms clock slack

        # -- (d) one scrape for the whole fleet ---------------------------
        assert 'replica="r0"' in agg and 'replica="r1"' in agg
        assert "fleet_inflight" in agg
        assert "serving_tokens_generated_total" in agg
        # families both tiers emit render ONE TYPE header
        assert agg.count("# TYPE trace_spans_recorded_total counter") == 1
    finally:
        _stop_all(rt, reps)


def test_retry_and_shed_spans_carry_the_trace(tiny_tr):
    """Router-side retry/shed instrumentation, unit-level: a fake
    backend lets _handle_generate -> _send_to -> _requeue run without
    sockets, asserting the retry span is parented on the ingress span
    and the re-placement keeps the SAME trace_id (a retried request is
    one trace, not two)."""
    import paddle_tpu.fleet.replica as rep
    from paddle_tpu.fleet.router import FleetRouter as FR

    class _FakeBackend:
        dead = False

        def send(self, msg):
            self.last = msg
            return True

    class _FakeConn:
        def __init__(self):
            self.sent = []
            self.rids = {}

        def send(self, msg):
            self.sent.append(msg)

    tracer = Tracer()
    tracer.enabled = True
    rt = FR(port=0, tracer=tracer)
    for _ in range(2):
        r = rt.table.add("h", 0)
        r.state = rep.HEALTHY
        r.hello = {"max_inflight": 8}
        r.backend = _FakeBackend()
    conn = _FakeConn()
    rt._handle_generate(conn, {"type": "generate", "id": "q0",
                               "prompt": [1, 2, 3], "max_new": 4,
                               "trace": {"trace_id": "feedc0de",
                                         "parent": "cli01"}})
    st = next(iter(rt._routes.values()))
    assert st.trace_id == "feedc0de"       # client context adopted
    assert st.client_parent == "cli01"
    first_rid = st.rid
    fwd = rt.table.get(first_rid).backend.last
    assert fwd["trace"] == {"trace_id": "feedc0de",
                            "parent": st.span_id}
    rt._requeue(st, why="replica died under test")
    assert st.rid != first_rid             # re-placed on the survivor
    fwd2 = rt.table.get(st.rid).backend.last
    assert fwd2["trace"]["trace_id"] == "feedc0de"
    spans = tracer.snapshot()
    retry = [s for s in spans if s["name"] == "retry"]
    assert len(retry) == 1
    assert retry[0]["attrs"]["trace_id"] == "feedc0de"
    assert retry[0]["attrs"]["parent"] == st.span_id
    places = [s for s in spans if s["name"] == "place"]
    assert len(places) == 2 and all(
        s["attrs"]["trace_id"] == "feedc0de" for s in places)
    # terminal frame closes the ingress span, which parents on the
    # CLIENT's span id — the client's own span stitches above the
    # router's in a merged trace
    rt._on_backend_frame(rt.table.get(st.rid),
                         rt.table.get(st.rid).backend,
                         {"type": "done", "id": st.grid,
                          "tokens": [1, 2, 3, 9], "reason": "length"})
    ingress = [s for s in tracer.snapshot() if s["name"] == "ingress"]
    assert len(ingress) == 1
    assert ingress[0]["attrs"]["parent"] == "cli01"
    assert ingress[0]["attrs"]["span_id"] == st.span_id
    assert conn.sent[-1]["type"] == "done"
    assert conn.sent[-1]["timing"]["router"]["retries"] == 1

    # shed: drop both replicas, a new generate records a shed instant
    for r in list(rt.table):
        rt.table.replicas.pop(r.rid)
    rt._handle_generate(conn, {"type": "generate", "id": "q1",
                               "prompt": [1], "max_new": 1})
    assert conn.sent[-1]["type"] == "overload"
    sheds = [s for s in tracer.snapshot() if s["name"] == "shed"]
    assert sheds and sheds[-1]["attrs"]["reason"] == "no_replicas"


def test_replica_timing_rides_preempt_and_seed_paths(tiny_tr):
    """Direct (no-router) server: the done frame's timing breakdown is
    present, phase-complete, and counts preemptions when the pool forces
    them."""
    eng = ServingEngine(tiny_tr.executor, tiny_tr.params, num_slots=2,
                        page_size=PAGE, max_context=64,
                        num_pages=11)        # tight pool: preempt likely
    srv = ServingServer(eng, max_queue=16)
    host, port = srv.start_background()
    try:
        with ServingClient(host, port) as c:
            rids = [c.submit(list(range(2, 10)), max_new=24, seed=i)
                    for i in range(3)]
            res = c.collect(rids)
        total_preempts = 0
        for rid in rids:
            t = res[rid]["timing"]
            assert t is not None
            s = t["queue_ms"] + t["prefill_ms"] + t["decode_ms"] + \
                t["replay_ms"]
            assert abs(s - t["total_ms"]) < 1.0
            assert t["total_ms"] <= t["request_ms"] + 1.0
            total_preempts += t.get("preempts", 0)
            if t.get("preempts"):
                assert t["replay_ms"] >= 0.0
        assert total_preempts == eng.n_preemptions
    finally:
        srv.stop_background(drain=True)
