
from paddle_tpu.dsl import *
settings(batch_size=2, learning_rate=0.1)
x = data_layer(name="x", size=4)
proj = fc_layer(input=x, size=8, act=LinearActivation(), bias_attr=False)
rnn = recurrent_layer(input=proj, name="rnn_out")
rep = last_seq(input=rnn)
out = fc_layer(input=rep, size=2, act=SoftmaxActivation())
classification_cost(input=out, label=data_layer(name="label", size=2))
