"""doOperation vector-math analog + sparse shard-traffic diagnostics.

Mirrors ref: pserver/ParameterServer2.cpp op_* semantics (transliterated
numpy oracles below) and pserver/SparseParameterDistribution.cpp's
balance-check behavior (unbalanced batches counted, crash past ratio)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.parallel import vecmath
from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.sparse import SparseShardStats, sharded_table_feeds

N = 64


def _sharded_pair(mesh, seed):
    rng = np.random.default_rng(seed)
    sh = NamedSharding(mesh, P("data"))
    u = jax.device_put(rng.normal(size=N).astype(np.float32), sh)
    v = jax.device_put(rng.normal(size=N).astype(np.float32), sh)
    return u, v


def test_utv_au_bv_sharded_match_numpy():
    mesh = make_mesh(data=8)
    u, v = _sharded_pair(mesh, 0)
    un, vn = np.asarray(u), np.asarray(v)
    np.testing.assert_allclose(float(jax.jit(vecmath.utv)(u, v)),
                               un.astype(np.float64) @ vn, rtol=1e-5)
    out = jax.jit(lambda u, v: vecmath.au_bv(u, v, 0.3, -1.7))(u, v)
    np.testing.assert_allclose(np.asarray(out), 0.3 * un - 1.7 * vn,
                               rtol=1e-5)
    out3 = vecmath.au_bv_cw(u, v, u + v, 0.5, 2.0, -1.0)
    np.testing.assert_allclose(np.asarray(out3),
                               0.5 * un + 2.0 * vn - (un + vn), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(vecmath.au(u, 2.5)), 2.5 * un,
                               rtol=1e-6)


def _steepest_oracle(grad, x, w):
    # transliteration of ref: ParameterServer2.cpp:1301-1315
    d = np.zeros_like(grad)
    for i in range(len(grad)):
        if x[i] < 0:
            d[i] = -grad[i] + w
        elif x[i] > 0:
            d[i] = -grad[i] - w
        elif grad[i] < -w:
            d[i] = -grad[i] - w
        elif grad[i] > w:
            d[i] = -grad[i] + w
    return d


def _dir_deriv_oracle(d, grad, x, w):
    # transliteration of ref: ParameterServer2.cpp:1352-1363
    s = 0.0
    for i in range(len(d)):
        if d[i] == 0:
            continue
        if x[i] < 0 or (x[i] == 0 and d[i] < 0):
            s += d[i] * (grad[i] - w)
        else:
            s += d[i] * (grad[i] + w)
    return s


def test_owlqn_ops_match_reference_semantics():
    rng = np.random.default_rng(1)
    grad = rng.normal(size=N).astype(np.float32)
    # force exact zeros so every branch of the orthant logic is exercised
    x = rng.normal(size=N).astype(np.float32)
    x[::5] = 0.0
    w = 0.4
    d = np.asarray(vecmath.make_steepest_desc_dir(jnp.asarray(grad),
                                                  jnp.asarray(x), w))
    np.testing.assert_allclose(d, _steepest_oracle(grad, x, w), rtol=1e-6)

    fixed = np.asarray(vecmath.fix_dir_signs(jnp.asarray(grad),
                                             jnp.asarray(d)))
    assert (fixed[grad * d <= 0] == 0).all()
    assert np.array_equal(fixed[grad * d > 0], grad[grad * d > 0])

    dd = float(vecmath.dir_deriv(jnp.asarray(d), jnp.asarray(grad),
                                 jnp.asarray(x), w))
    np.testing.assert_allclose(dd, _dir_deriv_oracle(d, grad, x, w),
                               rtol=1e-4)

    newx = x + 0.5 * d
    proj = np.asarray(vecmath.fix_omega_signs(jnp.asarray(x),
                                              jnp.asarray(newx)))
    assert (proj[x * newx < 0] == 0).all()
    np.testing.assert_allclose(
        float(vecmath.l1_cost(jnp.asarray(x), w)), w * np.abs(x).sum(),
        rtol=1e-5)


# ---------------------------------------------------------------------------
# SparseParameterDistribution analog
# ---------------------------------------------------------------------------

class _Arg:
    def __init__(self, ids, lengths=None):
        self.ids = ids
        self.lengths = lengths


def _stats(n_shards=4, vocab=64, **kw):
    return SparseShardStats({"emb_w": (["w"], vocab, n_shards)}, **kw)


def test_balanced_ids_pass():
    st = _stats(batches=5, strict=True)
    rng = np.random.default_rng(0)
    for _ in range(5):
        st.probe_batch({"w": _Arg(rng.integers(0, 64, 128))})
    assert st.done and st.unbalance_cnt == 0


def test_skewed_ids_crash_past_ratio():
    st = _stats(batches=4, ratio=0.5, strict=True)
    with pytest.raises(RuntimeError, match="unbalanced sparse id"):
        for _ in range(4):
            # every id lands in shard 0 (ids < 16 of vocab 64 over 4 shards)
            st.probe_batch({"w": _Arg(np.zeros(128, np.int32))})
    assert st.batch_passed == 4 and st.unbalance_cnt == 4


def test_skewed_ids_warn_when_not_strict():
    st = _stats(batches=3, ratio=0.5, strict=False)
    for _ in range(3):
        st.probe_batch({"w": _Arg(np.full(64, 63, np.int32))})
    assert st.done and st.unbalance_cnt == 3


def test_padding_not_counted_as_traffic():
    """Pad cells (feeder pads id slots with 0) must not inflate shard 0:
    balanced real ids in heavily padded batches stay balanced."""
    st = _stats(batches=4, ratio=0.25, strict=True)
    rng = np.random.default_rng(2)
    for _ in range(4):
        ids = np.zeros((16, 32), np.int64)  # mostly padding -> id 0
        lengths = np.full(16, 8, np.int64)
        for r in range(16):
            ids[r, :8] = rng.integers(0, 64, 8)
        st.probe_batch({"w": _Arg(ids, lengths)})
    assert st.done
    # with pads counted, every batch would be shard-0 skewed and raise
    assert st.unbalance_cnt <= 1


def test_uneven_vocab_uses_ceil_shards():
    # vocab 10 over 4 shards: GSPMD owns rows ceil-wise, 3/3/3/1
    st = SparseShardStats({"emb_w": (["w"], 10, 4)}, batches=1, strict=False)
    st.probe_batch({"w": _Arg(np.tile(np.arange(10), 8))})
    assert st.batch_passed == 1  # no div-by-zero, ids 9 -> shard 3


def test_tiny_batches_carry_no_balance_evidence():
    # 6 ids over 8 shards: some shard is always 0-touch; must not be
    # judged, and the probe must STOP once the budget is spent (no
    # per-batch host fetch forever)
    st = SparseShardStats({"emb_w": (["w"], 64, 8)}, batches=2, ratio=0.0)
    rng = np.random.default_rng(3)
    for _ in range(100):
        st.probe_batch({"w": _Arg(rng.integers(0, 64, 6))})
    assert st.batch_passed == 0 and st.unbalance_cnt == 0
    assert st.done  # budget (10*batches) spent -> probing switched off


def test_probe_stops_after_budget():
    st = _stats(batches=2)
    rng = np.random.default_rng(1)
    for _ in range(5):
        st.probe_batch({"w": _Arg(rng.integers(0, 64, 64))})
    assert st.batch_passed == 2  # later batches are free (ref: batchPassed_ gate)


def _emb_conf(batch_size=16):
    """Shared tiny embedding->fc model with a vocab-shardable table."""
    def conf():
        from paddle_tpu.dsl import (
            ParamAttr, MomentumOptimizer, TanhActivation, data_layer,
            embedding_layer, fc_layer, pooling_layer, regression_cost,
            settings, SumPooling,
        )
        settings(batch_size=batch_size, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.0))
        w = data_layer(name="w", size=64)
        emb = embedding_layer(input=w, size=8,
                              param_attr=ParamAttr(name="emb_w",
                                                   sparse_update=True,
                                                   initial_std=0.1))
        pooled = pooling_layer(input=emb, pooling_type=SumPooling())
        out = fc_layer(input=pooled, size=1, act=TanhActivation(),
                       param_attr=ParamAttr(initial_std=0.1))
        regression_cost(input=out, label=data_layer(name="y", size=1))
    return conf


def test_sharded_table_feeds_mapping():
    from paddle_tpu.config.parser import parse_config_callable

    cfg = parse_config_callable(_emb_conf())
    mesh = make_mesh(data=2, model=4)
    feeds = sharded_table_feeds(mesh, cfg.model_config)
    assert feeds == {"emb_w": (["w"], 64, 4)}
    # an unsharded mesh probes nothing
    solo = make_mesh(data=1, devices=jax.devices()[:1])
    assert sharded_table_feeds(solo, cfg.model_config) == {}


def test_trainer_probes_when_flag_set():
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer
    from paddle_tpu.utils.flags import FLAGS

    cfg = parse_config_callable(_emb_conf(batch_size=8))
    old = FLAGS.check_sparse_distribution
    FLAGS.check_sparse_distribution = True
    try:
        tr = Trainer(cfg, seed=0, mesh=make_mesh(data=2, model=4))
        assert tr.sparse_stats is not None
        rng = np.random.default_rng(0)
        ids = rng.integers(0, 64, (8, 8)).astype(np.int32)
        batch = {"w": Argument(ids=ids,
                               lengths=np.full(8, 8, np.int32)),
                 "y": Argument(value=np.zeros((8, 1), np.float32))}
        tr.train_one_batch(batch)
        assert tr.sparse_stats.batch_passed == 1
        assert int(sum(c.sum() for c in tr.sparse_stats.counts.values())) == 0
    finally:
        FLAGS.check_sparse_distribution = old
