"""Sharded embedding tests (mirrors ref: trainer/tests/test_CompareSparse.cpp
— local vs remote-sparse training must produce identical parameters; here:
sharded-table vs replicated training must match, and the explicit shard_map
lookup must match plain indexing)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.sparse import (
    embedding_partition_spec, sharded_embedding_lookup,
)

VOCAB, D = 64, 16


def test_sharded_lookup_matches_dense():
    mesh = make_mesh(data=2, model=4)
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(VOCAB, D)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, VOCAB, (8, 5)).astype(np.int32))
    out = sharded_embedding_lookup(mesh, table, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(table[ids]),
                               rtol=1e-6)


def test_sharded_lookup_grad_matches_dense():
    mesh = make_mesh(data=2, model=4)
    rng = np.random.default_rng(1)
    table = jnp.asarray(rng.normal(size=(VOCAB, D)).astype(np.float32))
    ids = jnp.asarray(rng.integers(0, VOCAB, (16,)).astype(np.int32))
    tgt = jnp.asarray(rng.normal(size=(16, D)).astype(np.float32))

    def loss_sharded(t):
        return jnp.sum((sharded_embedding_lookup(mesh, t, ids) - tgt) ** 2)

    def loss_dense(t):
        return jnp.sum((t[ids] - tgt) ** 2)

    g1 = jax.grad(loss_sharded)(table)
    g2 = jax.grad(loss_dense)(table)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5,
                               atol=1e-6)


def test_embedding_partition_spec():
    mesh = make_mesh(data=2, model=4)
    assert embedding_partition_spec(mesh) == ["model", None]
    mesh_dp = make_mesh(data=8, model=1)
    assert embedding_partition_spec(mesh_dp) == ["data", None]


def _train_embedding_model(mesh, steps=5):
    """Tiny embedding->fc regression trained via the Trainer; returns the
    embedding table after `steps` batches."""
    from paddle_tpu.config.parser import parse_config_callable
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    def conf():
        from paddle_tpu.dsl import (
            ParamAttr, MomentumOptimizer, TanhActivation, data_layer,
            embedding_layer, fc_layer, pooling_layer, regression_cost,
            settings, SumPooling,
        )
        settings(batch_size=16, learning_rate=0.05,
                 learning_method=MomentumOptimizer(momentum=0.0))
        w = data_layer(name="w", size=VOCAB)
        emb = embedding_layer(input=w, size=D,
                              param_attr=ParamAttr(name="emb_w",
                                                   sparse_update=True,
                                                   initial_std=0.1))
        pooled = pooling_layer(input=emb, pooling_type=SumPooling())
        out = fc_layer(input=pooled, size=1, act=TanhActivation(),
                       param_attr=ParamAttr(initial_std=0.1))
        regression_cost(input=out, label=data_layer(name="y", size=1))

    cfg = parse_config_callable(conf)
    tr = Trainer(cfg, seed=3, mesh=mesh)
    rng = np.random.default_rng(0)
    for _ in range(steps):
        ids = rng.integers(0, VOCAB, (16, 6)).astype(np.int32)
        lengths = rng.integers(2, 7, 16).astype(np.int32)
        y = np.tanh(0.01 * ids.sum(axis=1, keepdims=True)).astype(np.float32)
        batch = {"w": Argument(ids=ids, lengths=lengths),
                 "y": Argument(value=y)}
        tr.train_one_batch(batch)
    return np.asarray(jax.device_get(tr.params["emb_w"]))


def test_sharded_table_training_matches_replicated():
    """Training with a vocab-sharded table over an 8-dev mesh must produce
    the same table as single-device training (the test_CompareSparse analog)."""
    t_sharded = _train_embedding_model(make_mesh(data=2, model=4))
    t_local = _train_embedding_model(None)
    np.testing.assert_allclose(t_sharded, t_local, rtol=2e-4, atol=1e-5)


def test_recommendation_demo_trains():
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config("demo/recommendation/trainer_config.py",
                       "batch_size=64,emb_size=32,learning_rate=0.01")
    tr = Trainer(cfg, seed=0)
    it = tr.train_batches()
    losses = [tr.train_one_batch(next(it)) for _ in range(50)]
    assert np.isfinite(losses).all()
    assert np.mean(losses[-5:]) < np.mean(losses[:5])


@pytest.mark.xfail(
    strict=False,
    reason="adjudicated (CHANGES.md PR 1): this image's XLA partitioner "
           "genuinely all-gathers the vocab-sharded tables in their grouped "
           "[rows/n, n, D] lowering — the shape-anchored detector "
           "(tools/hlo_sparse_check.py) reports it honestly; red at seed "
           "too.  xfail (not skip) so a partitioner that stops "
           "materializing the table surfaces as XPASS and the guard can "
           "be re-armed.")
def test_gspmd_no_table_allgather_in_recsys_step():
    """GSPMD must service vocab-sharded table lookups with local
    gather + reduce, NOT by all-gathering the table to every device (the
    failure mode parallel/sparse.py's explicit path exists for; the
    reference's economics move touched rows only —
    ref: math/SparseRowMatrix.h:211).  Compiles the recommendation demo's
    full train step on the 8-device mesh and asserts the HLO is
    all-gather-free; if XLA's partitioner ever regresses, this trips and
    the config should switch to the explicit shard_map path."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.trainer.trainer import Trainer

    from tools.hlo_sparse_check import gather_spans_table

    mesh = make_mesh(data=8)
    cfg = parse_config("demo/recommendation/trainer_config.py",
                       "batch_size=64")
    tr = Trainer(cfg, seed=1, mesh=mesh)
    sharded = {k: v for k, v in tr.params.items()
               if any(s is not None
                      for s in getattr(v.sharding, "spec", []) or [])}
    assert sharded, "expected vocab-sharded embedding tables under the mesh"
    it = tr.train_batches()
    batch = next(it)
    hlo = tr._train_step.lower(tr.params, tr.opt_state, tr.net_state, batch,
                               jax.random.PRNGKey(0)).compile().as_text()
    # shape-anchored: only an all-gather that MATERIALIZES a table (full
    # table shape, gathered along its sharded axis) is the failure mode
    # this test guards (XLA legitimately all-gathers small activations; a
    # blanket no-all-gather assertion false-positives on those — the same
    # over-match tools/hlo_sparse_check.py:113 had, ADVICE r5)
    tables = [(tuple(v.shape),
               next((i for i, s in enumerate(v.sharding.spec)
                     if s is not None), None))
              for v in sharded.values()]
    offenders = [ln.strip()[:120] for ln in hlo.splitlines()
                 if "all-gather" in ln and "-done" not in ln
                 and gather_spans_table(ln, tables)]
    assert not offenders, f"GSPMD all-gathers a table: {offenders[:3]}"
