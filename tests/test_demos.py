"""Demo config train-smoke tests (mirrors ref: trainer/tests
test_TrainerOnePass — full train-one-pass on bundled mini-data; here a few
batches per config with loss-finite + loss-decrease checks)."""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config
from paddle_tpu.trainer.trainer import Trainer

pytestmark = pytest.mark.slow  # heavy: excluded from the fast gate (pytest -m "not slow")


ALL_CONFIGS = [
    "demo/sentiment/trainer_config.py",
    "demo/sequence_tagging/rnn_crf.py",
    "demo/sequence_tagging/linear_crf.py",
    "demo/semantic_role_labeling/db_lstm.py",
    "demo/quick_start/trainer_config.lr.py",
    "demo/quick_start/trainer_config.cnn.py",
    "demo/quick_start/trainer_config.lstm.py",
    "demo/quick_start/trainer_config.emb.py",
    "demo/quick_start/trainer_config.bidi-lstm.py",
    "demo/quick_start/trainer_config.db-lstm.py",
    "demo/quick_start/trainer_config.resnet-lstm.py",
]


@pytest.mark.parametrize("path", ALL_CONFIGS)
def test_demo_config_parses(path):
    cfg = parse_config(path)
    assert cfg.model_config.layers
    assert cfg.model_config.parameters


def _train_few(path, n_batches=6, config_args=""):
    cfg = parse_config(path, config_args)
    tr = Trainer(cfg, seed=0)
    losses = []
    it = tr.train_batches()
    for _ in range(n_batches):
        losses.append(tr.train_one_batch(next(it)))
    assert all(np.isfinite(l) for l in losses), losses
    return losses


def test_quick_start_lr_trains():
    losses = _train_few("demo/quick_start/trainer_config.lr.py",
                        n_batches=10, config_args="batch_size=32")
    assert losses[-1] < losses[0]


def test_quick_start_emb_trains():
    losses = _train_few("demo/quick_start/trainer_config.emb.py",
                        n_batches=10, config_args="batch_size=32")
    assert losses[-1] < losses[0]


def test_quick_start_deep_stacks_train():
    # shallow variants of the db-lstm / resnet-lstm stacks for speed
    _train_few("demo/quick_start/trainer_config.db-lstm.py",
               n_batches=3, config_args="batch_size=16,depth=2")
    _train_few("demo/quick_start/trainer_config.resnet-lstm.py",
               n_batches=3, config_args="batch_size=16,depth=1")


def test_sentiment_small_trains():
    # shrink hid_dim for test speed; stacked 3-LSTM path still exercised
    losses = _train_few("demo/sentiment/trainer_config.py", n_batches=4,
                        config_args="batch_size=8,hid_dim=32")
    assert np.isfinite(losses).all()


def test_linear_crf_trains():
    losses = _train_few("demo/sequence_tagging/linear_crf.py", n_batches=6,
                        config_args="batch_size=8")
    assert losses[-1] < losses[0]


def test_srl_db_lstm_trains():
    losses = _train_few("demo/semantic_role_labeling/db_lstm.py", n_batches=3,
                        config_args="batch_size=8,depth=4,hidden_dim=32")
    assert np.isfinite(losses).all()


def test_introduction_recovers_line():
    """The linear-regression demo must recover y = 2x + 0.3
    (ref: demo/introduction/README quality target)."""
    cfg = parse_config("demo/introduction/trainer_config.py")
    tr = Trainer(cfg, seed=0)
    for _ in range(30):
        tr.train_one_pass(log_period=0)
    w = float(np.asarray(tr.params["w"]).reshape(-1)[0])
    b = float(np.asarray(tr.params["b"]).reshape(-1)[0])
    assert abs(w - 2.0) < 0.1 and abs(b - 0.3) < 0.1, (w, b)


@pytest.mark.parametrize("layer_num,n_layers", [(50, 128), (101, 247), (152, 366)])
def test_model_zoo_resnet_parses(layer_num, n_layers):
    cfg = parse_config(
        "demo/model_zoo/resnet.py",
        f"layer_num={layer_num},image_size=32,num_classes=4,use_data=0")
    assert len(cfg.model_config.layers) == n_layers


def test_model_zoo_resnet50_trains():
    losses = _train_few(
        "demo/model_zoo/resnet.py", n_batches=2,
        config_args="layer_num=50,image_size=32,num_classes=4,batch_size=8")
    assert np.isfinite(losses).all()


def test_model_zoo_classify_runs(capsys):
    from demo.model_zoo.classify import main as classify_main
    classify_main([])
    out = capsys.readouterr().out
    assert "sample 0: label=" in out


def test_mlp_mnist_pp_demo_trains_on_pipe_mesh():
    """The pipeline demo config (device=N annotations) trains on a
    (data, pipe) mesh through the real provider, and its losses match the
    un-annotated mlp_mnist.py trained on the same batches."""
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.parallel.pipeline_config import PipelineExecutor

    cfg_pp = parse_config("demo/mnist/mlp_mnist_pp.py",
                          "batch_size=16,micro_batches=2")
    tr = Trainer(cfg_pp, seed=0, mesh=make_mesh(data=4, pipe=2))
    assert isinstance(tr.executor, PipelineExecutor)
    it = tr.train_batches()
    batches = [next(it) for _ in range(4)]
    losses = [float(tr.train_one_batch(b)) for b in batches]
    assert all(np.isfinite(l) for l in losses), losses

    cfg_ref = parse_config("demo/mnist/mlp_mnist.py", "batch_size=16")
    tr_ref = Trainer(cfg_ref, seed=0)
    ref_losses = [float(tr_ref.train_one_batch(b)) for b in batches]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=1e-6)
