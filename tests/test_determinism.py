"""Determinism and resume-exactness oracles.

A framework's reproducibility claims need pinning: the same config, seed
and data must give bit-identical trajectories across independent Trainer
instances, and a checkpoint/restart mid-training must continue EXACTLY
as the uninterrupted run would (the checkpoint bundle carries optimizer
slots, so momentum/Adam state survives — ref: the reference's
ParamUtil + force_load_parameter resume semantics)."""

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer

B, DIN, NCLS = 16, 12, 3


def _conf():
    from paddle_tpu.dsl import (
        MomentumOptimizer, SoftmaxActivation, TanhActivation,
        classification_cost, data_layer, fc_layer, settings,
    )
    settings(batch_size=B, learning_rate=0.05,
             learning_method=MomentumOptimizer(momentum=0.9))
    x = data_layer(name="x", size=DIN)
    h = fc_layer(input=x, size=16, act=TanhActivation())
    out = fc_layer(input=h, size=NCLS, act=SoftmaxActivation())
    classification_cost(input=out, label=data_layer(name="y", size=NCLS))


def _batches(n, seed=0):
    rng = np.random.default_rng(seed)
    return [{
        "x": Argument(value=rng.normal(size=(B, DIN)).astype(np.float32)),
        "y": Argument(ids=rng.integers(0, NCLS, B).astype(np.int32)),
    } for _ in range(n)]


def _params(tr):
    return {k: np.asarray(v) for k, v in tr.params.items()}


def test_training_is_deterministic():
    b = _batches(6)
    runs = []
    for _ in range(2):
        tr = Trainer(parse_config_callable(_conf), seed=7)
        losses = [float(tr.train_one_batch(x)) for x in b]
        runs.append((losses, _params(tr)))
    (l1, p1), (l2, p2) = runs
    assert l1 == l2, "loss trajectories differ across identical runs"
    for k in p1:
        np.testing.assert_array_equal(p1[k], p2[k])


def _conf_dropout():
    from paddle_tpu.dsl import (
        ExtraLayerAttribute, MomentumOptimizer, SoftmaxActivation,
        TanhActivation, classification_cost, data_layer, fc_layer, settings,
    )
    settings(batch_size=B, learning_rate=0.05,
             learning_method=MomentumOptimizer(momentum=0.9))
    x = data_layer(name="x", size=DIN)
    h = fc_layer(input=x, size=16, act=TanhActivation(),
                 layer_attr=ExtraLayerAttribute(drop_rate=0.3))
    out = fc_layer(input=h, size=NCLS, act=SoftmaxActivation())
    classification_cost(input=out, label=data_layer(name="y", size=NCLS))


@pytest.mark.parametrize("conf", [_conf, _conf_dropout],
                         ids=["deterministic", "dropout"])
def test_resume_equals_uninterrupted(tmp_path, conf):
    """Resume is exact even for STOCHASTIC models: the checkpoint bundle
    carries the optimizer slots AND the trainer's PRNG key, so the
    resumed run's dropout stream continues where the uninterrupted run's
    would."""
    batches = _batches(4, seed=1)

    # uninterrupted: 2 passes over the 4 batches
    tr_full = Trainer(parse_config_callable(conf), seed=3)
    tr_full.train_one_pass(batches=batches)
    tr_full.train_one_pass(batches=batches)

    # interrupted: 1 pass, checkpoint, fresh Trainer (different seed to
    # prove the restored key wins), resume, 1 more pass
    tr_a = Trainer(parse_config_callable(conf), seed=3)
    tr_a.train_one_pass(batches=batches)
    d = str(tmp_path / "ckpt")
    tr_a.save(d)
    tr_b = Trainer(parse_config_callable(conf), seed=99)
    tr_b.load(d)
    tr_b.train_one_pass(batches=batches)

    pf, pr = _params(tr_full), _params(tr_b)
    for k in pf:
        np.testing.assert_array_equal(
            pf[k], pr[k],
            err_msg=f"param {k!r}: resume diverged from uninterrupted "
                    f"(optimizer slots + rng must ride the checkpoint)")
