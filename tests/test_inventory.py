"""Inventory components: MultiNetwork (sub_network), MultiDataProvider,
pruning updater hook, truncated-BPTT continuation, beam-search controls.

Refs: gserver/gradientmachines/MultiNetwork.h:25-62;
gserver/dataproviders/MultiDataProvider.{h,cpp};
parameter/ParameterUpdaterHook.cpp:32,167 (StaticPruningHook);
gserver/layers/RecurrentLayer.cpp prevOutput_ (--prev_batch_state);
gserver/gradientmachines/RecurrentGradientMachine.h:86-170 (beam callbacks).
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.config.parser import parse_config
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer
from paddle_tpu.utils.flags import FLAGS


def _cfg(tmp_name, src):
    path = os.path.join(REPO, "tests", tmp_name)
    with open(path, "w") as f:
        f.write(src)
    return path


# ---------------------------------------------------------------------------
# MultiNetwork / sub_network
# ---------------------------------------------------------------------------

MULTI_NN = """
from paddle_tpu.dsl import *
settings(batch_size=16, learning_rate=0.1,
         learning_method=MomentumOptimizer(momentum=0.9))
with sub_network("task_a"):
    xa = data_layer(name="xa", size=8)
    oa = fc_layer(input=xa, size=2, act=SoftmaxActivation())
    classification_cost(input=oa, label=data_layer(name="ya", size=2),
                        name="cost_a")
with sub_network("task_b"):
    xb = data_layer(name="xb", size=4)
    ob = fc_layer(input=xb, size=3, act=SoftmaxActivation())
    classification_cost(input=ob, label=data_layer(name="yb", size=3),
                        name="cost_b")
"""


def test_multi_network_trains_both_tasks():
    path = _cfg("_multi_nn.py", MULTI_NN)
    try:
        cfg = parse_config(path, "")
        assert cfg.model_config.type == "multi_nn"
        subs = {sm.name for sm in cfg.model_config.sub_models}
        assert {"task_a", "task_b"} <= subs
        tr = Trainer(cfg, seed=0)
        rng = np.random.default_rng(0)

        def batches():
            for _ in range(15):
                xa = rng.normal(size=(16, 8)).astype(np.float32)
                xb = rng.normal(size=(16, 4)).astype(np.float32)
                yield {"xa": Argument(value=xa),
                       "ya": Argument(ids=(xa.sum(-1) > 0).astype(np.int32)),
                       "xb": Argument(value=xb),
                       "yb": Argument(ids=(np.abs(xb.sum(-1)) % 3).astype(np.int32))}

        first = tr.train_one_pass(batches=batches(), log_period=0)
        last = first
        for _ in range(5):
            last = tr.train_one_pass(batches=batches(), log_period=0)
        assert last["cost"] < first["cost"]
    finally:
        os.remove(path)


# ---------------------------------------------------------------------------
# MultiDataProvider
# ---------------------------------------------------------------------------

def test_multi_provider_mixes_by_ratio():
    from paddle_tpu.data.provider import (MultiProviderWrapper, integer_value,
                                          dense_vector, provider)

    def mk(tag, n):
        @provider(input_types={"x": dense_vector(2), "label": integer_value(2)},
                  should_shuffle=False)
        def p(settings, filename):
            for i in range(n):
                yield [float(tag), float(i)], tag
        return p

    multi = MultiProviderWrapper([mk(0, 8), mk(1, 4)], [["f"], ["f"]],
                                 ratios=[2, 1])
    samples = list(multi.samples([]))
    assert len(samples) == 12
    # first mixing rounds follow the 2:1 ratio
    tags = [int(s[0][0]) for s in samples[:6]]
    assert tags == [0, 0, 1, 0, 0, 1], tags

    # test mode concatenates everything
    multi_t = MultiProviderWrapper([mk(0, 3), mk(1, 2)], [["f"], ["f"]],
                                   is_test=True)
    tags_t = [int(s[0][0]) for s in multi_t.samples([])]
    assert tags_t == [0, 0, 0, 1, 1]


def test_multi_data_sources_config():
    src = """
from paddle_tpu.dsl import *
settings(batch_size=8, learning_rate=0.1)
define_multi_py_data_sources2(
    train_sources=[
        {"files": "demo/quick_start/train.list",
         "module": "demo.quick_start.qs_provider", "obj": "process_bow"},
        {"files": "demo/quick_start/train.list",
         "module": "demo.quick_start.qs_provider", "obj": "process_bow"},
    ], ratios=[3, 1])
data = data_layer(name="word", size=1024)
output = fc_layer(input=data, size=2, act=SoftmaxActivation())
classification_cost(input=output, label=data_layer(name="label", size=2))
"""
    path = _cfg("_multi_src.py", src)
    try:
        cfg = parse_config(path, "")
        assert cfg.data_config.type == "multi"
        assert len(cfg.data_config.sub_configs) == 2
        tr = Trainer(cfg, seed=0)
        it = tr.train_batches()
        losses = [float(tr.train_one_batch(next(it))) for _ in range(3)]
        tr._drain_losses()
        assert all(np.isfinite(l) for l in losses)
    finally:
        os.remove(path)


# ---------------------------------------------------------------------------
# pruning updater hook
# ---------------------------------------------------------------------------

def test_pruning_hook_masks_and_stays_masked():
    src = """
from paddle_tpu.dsl import *
settings(batch_size=8, learning_rate=0.5,
         learning_method=MomentumOptimizer(momentum=0.9))
x = data_layer(name="x", size=16)
h = fc_layer(input=x, size=8, act=TanhActivation(),
             param_attr=ParamAttr(name="pruned_w",
                                  update_hooks=[{"type": "pruning",
                                                 "sparsity_ratio": 0.75}]))
out = fc_layer(input=h, size=2, act=SoftmaxActivation())
classification_cost(input=out, label=data_layer(name="label", size=2))
"""
    path = _cfg("_prune.py", src)
    try:
        cfg = parse_config(path, "")
        tr = Trainer(cfg, seed=0)
        w0 = np.asarray(tr.params["pruned_w"])
        sparsity = float((w0 == 0).mean())
        assert abs(sparsity - 0.75) < 0.05, sparsity
        mask = w0 != 0

        rng = np.random.default_rng(0)
        for _ in range(10):
            x = rng.normal(size=(8, 16)).astype(np.float32)
            y = (x.sum(-1) > 0).astype(np.int32)
            tr.train_one_batch({"x": Argument(value=x), "label": Argument(ids=y)})
        tr._drain_losses()
        w1 = np.asarray(tr.params["pruned_w"])
        np.testing.assert_array_equal(w1[~mask], 0.0)   # pruned stay zero
        assert np.abs(w1[mask] - w0[mask]).max() > 0    # survivors trained
    finally:
        os.remove(path)


# ---------------------------------------------------------------------------
# truncated BPTT (--prev_batch_state)
# ---------------------------------------------------------------------------

def test_prev_batch_state_continuation():
    src = """
from paddle_tpu.dsl import *
settings(batch_size=2, learning_rate=0.1)
x = data_layer(name="x", size=4)
proj = fc_layer(input=x, size=8, act=LinearActivation(), bias_attr=False)
rnn = recurrent_layer(input=proj, name="rnn_out")
rep = last_seq(input=rnn)
out = fc_layer(input=rep, size=2, act=SoftmaxActivation())
classification_cost(input=out, label=data_layer(name="label", size=2))
"""
    path = _cfg("_bptt.py", src)
    try:
        cfg = parse_config(path, "")
        ex_args = dict(seed=0)
        rng = np.random.default_rng(0)
        xs = rng.normal(size=(2, 6, 4)).astype(np.float32)   # [B, T=6, D]
        lens3 = np.full((2,), 3, np.int32)
        lens6 = np.full((2,), 6, np.int32)
        y = np.zeros((2,), np.int32)

        old = FLAGS.prev_batch_state
        FLAGS.prev_batch_state = True
        try:
            tr = Trainer(cfg, **ex_args)
            # two 3-step chunks with state carry ...
            out1, _, st1 = tr.executor.forward(
                tr.params, {"x": Argument(value=xs[:, :3], lengths=lens3),
                            "label": Argument(ids=y)}, state={}, mode="test")
            out2, _, _ = tr.executor.forward(
                tr.params, {"x": Argument(value=xs[:, 3:], lengths=lens3),
                            "label": Argument(ids=y)}, state=st1, mode="test")
            chunked = np.asarray(out2["rnn_out"].value[:, -1])
        finally:
            FLAGS.prev_batch_state = old

        # ... equals one unchunked 6-step forward
        tr2 = Trainer(cfg, **ex_args)
        full, _, _ = tr2.executor.forward(
            tr2.params, {"x": Argument(value=xs, lengths=lens6),
                         "label": Argument(ids=y)}, state={}, mode="test")
        np.testing.assert_allclose(chunked,
                                   np.asarray(full["rnn_out"].value[:, -1]),
                                   rtol=1e-5, atol=1e-6)
    finally:
        os.remove(path)


# ---------------------------------------------------------------------------
# beam-search control callbacks
# ---------------------------------------------------------------------------

def test_beam_controls_ban_token_and_count_steps():
    from paddle_tpu.graph.builder import GraphExecutor
    from paddle_tpu.graph.generator import BeamSearchControls, generate

    gcfg = parse_config(
        os.path.join(REPO, "demo/seqToseq/seqToseq_net.py"),
        "dict_size=32,is_generating=1,beam_size=3,max_length=8")
    gex = GraphExecutor(gcfg.model_config)
    params = gex.init_params(jax.random.PRNGKey(3))
    ids = np.asarray([[5, 9, 12, 7]], np.int32)
    feed = {"source_language_word": Argument(
        ids=ids, lengths=np.asarray([4], np.int32))}

    # pick a token the UNCONSTRAINED search actually emits, then ban it —
    # proves the constraint does real work
    ref_seqs = np.asarray(generate(gex, params, feed)[0])
    emitted = [t for t in np.unique(ref_seqs) if t > 2]
    banned = int(emitted[0])

    steps_seen = []

    def adjust(step, tokens, logp):
        return logp.at[..., banned].set(-1e9)

    controls = BeamSearchControls(adjust_logp=adjust,
                                  on_step=lambda t: steps_seen.append(int(t)))
    seqs, scores = generate(gex, params, feed, controls=controls)
    seqs = np.asarray(seqs)
    assert not (seqs == banned).any(), (banned, seqs)
    jax.effects_barrier()
    assert sorted(steps_seen) == list(range(8)), steps_seen

    # norm_path replaces the default normalization
    controls2 = BeamSearchControls(norm_path=lambda s, l: s * 0.0)
    _, z = generate(gex, params, feed, controls=controls2)
    np.testing.assert_array_equal(np.asarray(z), 0.0)


# ---------------------------------------------------------------------------
# calc_batch_size (cost-weighted batching)
# ---------------------------------------------------------------------------

def test_calc_batch_size_token_weighted_batches():
    """calc_batch_size weights each sample's contribution to the batch
    budget (ref: PyDataProvider2.py:265 — e.g. token counts, so long
    sequences form smaller batches); batches may exceed the budget like
    the reference's can_over_batch_size mode."""
    import numpy as np
    from paddle_tpu.data.feeder import DataFeeder
    from paddle_tpu.data.provider import integer_value_sequence, integer_value, provider

    lens = [5, 5, 5, 9, 9, 2, 2, 2, 2, 2]

    @provider(input_types={"w": integer_value_sequence(50),
                           "label": integer_value(2)},
              should_shuffle=False,
              calc_batch_size=lambda s: len(s["w"]))
    def p(settings, filename):
        for L in lens:
            yield {"w": list(range(L)), "label": 0}

    feeder = DataFeeder(p, ["f"], ["w", "label"], batch_size=10,
                        drop_last=False, bucket_by_length=False,
                        shuffle=False)
    batches = list(feeder.batches())
    sizes = [int(b["w"].batch_size) for b in batches]
    # 5+5=10 | 5+9=14 (over-budget close) | 9+2=11 | 2+2+2+2=8 (tail kept)
    assert sizes == [2, 2, 2, 4], sizes
    # every sample arrives exactly once
    assert sum(int(np.asarray(b["w"].lengths).sum()) for b in batches) == sum(lens)

    # drop_last=True discards the under-budget tail
    feeder2 = DataFeeder(p, ["f"], ["w", "label"], batch_size=10,
                         drop_last=True, bucket_by_length=False,
                         shuffle=False)
    assert [int(b["w"].batch_size) for b in feeder2.batches()] == [2, 2, 2]


def test_constant_slots_fill_extra_inputs():
    """DataConfig.constant_slots appends fixed-value [B, 1] slots after the
    provider's slots (ref: config_parser.py:888, DataProvider.cpp:177-195)."""
    import numpy as np
    from paddle_tpu.data.feeder import DataFeeder
    from paddle_tpu.data.provider import dense_vector, integer_value, provider

    @provider(input_types={"x": dense_vector(2), "label": integer_value(2)},
              should_shuffle=False)
    def p(settings, filename):
        for i in range(8):
            yield {"x": [float(i), 0.0], "label": i % 2}

    feeder = DataFeeder(p, ["f"], ["x", "label", "c1", "c2"], batch_size=4,
                        drop_last=False, constant_slots=[0.5, -2.0])
    batches = list(feeder.batches())
    assert len(batches) == 2
    for b in batches:
        np.testing.assert_array_equal(np.asarray(b["c1"].value),
                                      np.full((4, 1), 0.5, np.float32))
        np.testing.assert_array_equal(np.asarray(b["c2"].value),
                                      np.full((4, 1), -2.0, np.float32))
