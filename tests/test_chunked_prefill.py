"""Chunked prefill with mixed prefill/decode steps (serving/engine.py
`_run_mixed_step` + ops/attention.py `ragged_paged_attention_step`).

The exactness contract is unchanged and non-negotiable: whatever the
chunk size, token budget, prefix-cache state, or preemption schedule, a
request's tokens are identical to a cold `lm_generate(use_cache=True)`
run — while the compiled-step signature set stays small and FIXED (the
one `[S, 1]` decode signature plus ONE mixed-step signature per
max_step_tokens value, and zero per-bucket prefill programs)."""

import numpy as np
import pytest

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.serving import Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer


@pytest.fixture(scope="module")
def tr():
    # layers=1 keeps every compile in this file cheap (the 2-CPU tier-1
    # budget is tight); multi-layer state threading through the chunked
    # path is covered by test_serving/test_prefix_cache, which run the
    # chunked default on layers=2 models
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=23,dim=16,layers=1,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _oracle(tr, req: Request):
    toks, lens = lm_generate(
        tr.executor, tr.params, req.prompt_ids[None, :],
        max_new=req.max_new, temperature=req.temperature, top_k=req.top_k,
        top_p=req.top_p, eos_id=req.eos_id, rng=req.rng, use_cache=True)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])]


def _assert_exact(tr, reqs, results):
    for r in reqs:
        np.testing.assert_array_equal(
            _oracle(tr, r), results[r.req_id],
            err_msg=f"request {r.req_id!r} diverged from the cold "
                    f"lm_generate oracle")


def _assert_sigs(eng):
    """The tentpole's signature discipline: one decode signature, at most
    one mixed signature, NO per-bucket prefill programs."""
    assert eng._decode_step._cache_size() == 1
    assert eng._mixed_step._cache_size() <= 1
    assert not eng._prefill_cache and not eng._pack_cache, \
        "chunked mode compiled a legacy per-bucket prefill program"


# ---------------------------------------------------------------------------
# the token-exactness oracle under multi-chunk prefill
# ---------------------------------------------------------------------------

def test_multi_chunk_prompts_stay_oracle_exact_across_knobs(tr):
    """Prompts spanning 1..5 chunks with mixed sampling knobs, tiny chunk
    (= page size) and a tight token budget: every request bit-matches its
    cold run, at least one request decoded WHILE another was still
    chunking (the mixed step actually mixed), and the signature set is
    the fixed pair."""
    rng = np.random.default_rng(0)
    knobs = [dict(), dict(temperature=0.8, top_k=5),
             dict(temperature=0.7, top_p=0.9), dict(temperature=1.1)]
    lens = (3, 19, 9, 17)
    reqs = [Request(f"r{i}", rng.integers(2, 23, n).astype(np.int32),
                    max_new=5, rng=jax.random.PRNGKey(40 + i), **kw)
            for i, (n, kw) in enumerate(zip(lens, knobs))]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, prefill_chunk=4, max_step_tokens=7)
    results = eng.run(reqs)
    _assert_exact(tr, reqs, results)
    assert eng.n_mixed_steps > 0 and eng.n_prefill_chunks >= 4
    _assert_sigs(eng)
    eng.kv.check_reclaimed()


def test_decode_advances_while_long_prompt_chunks(tr):
    """The HOL-blocking kill shot: a short request is mid-decode when a
    long prompt admits — the short request's tokens keep advancing on
    the very steps that carry the long prompt's chunks (no stall), and
    both stay exact."""
    rng = np.random.default_rng(1)
    short = Request("short", rng.integers(2, 23, 3).astype(np.int32),
                    max_new=12)
    long_ = Request("long", rng.integers(2, 23, 25).astype(np.int32),
                    max_new=4)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, prefill_chunk=4, max_step_tokens=6)
    eng.add_request(short)
    eng.step()                       # short: chunk+token0 (mixed step)
    eng.step()                       # short decoding alone
    gen_before = next(sl for sl in eng.slots if sl is not None).gen
    eng.add_request(long_)
    # 25 prompt tokens / (budget 6 - 1 decode row) = 5 chunk steps
    stalled = 0
    while any(sl is not None and sl.req is long_ and sl.gen == 0
              for sl in eng.slots) or long_ in eng.queue:
        before = eng.tokens_generated
        eng.step()
        if eng.tokens_generated == before:
            stalled += 1
    short_sl = next((sl for sl in eng.slots
                     if sl is not None and sl.req is short), None)
    assert short_sl is not None and short_sl.gen > gen_before, \
        "the decoding request stalled behind the long prompt's prefill"
    assert stalled == 0, \
        f"{stalled} steps advanced no decode token while chunking"
    results = eng.run()
    _assert_exact(tr, [short, long_], results)
    _assert_sigs(eng)


def test_step_token_budget_is_never_exceeded(tr):
    """max_step_tokens is a hard per-step bound: across a workload
    saturating every slot with multi-chunk prompts, no recorded step
    scheduled more rows than the budget (the serving_step_tokens
    histogram's +Inf bucket equals its <=budget bucket)."""
    rng = np.random.default_rng(2)
    reqs = [Request(f"r{i}", rng.integers(2, 23, 14 + i).astype(np.int32),
                    max_new=4) for i in range(6)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=3, page_size=4,
                        max_context=24, prefill_chunk=8,
                        max_step_tokens=16)   # == a histogram bucket edge
    results = eng.run(reqs)
    _assert_exact(tr, reqs, results)
    h = eng.step_tokens_hist
    counts, _total, n = h._vals[()]
    over_budget = counts[-1] - counts[h.buckets.index(16.0)]
    assert n == eng.n_decode_steps and n > 0
    assert over_budget == 0, \
        "a step scheduled more rows than max_step_tokens"
    # and the budget actually bit: some step packed more than one row
    # per live slot (chunk rows rode along with decodes)
    assert eng.n_mixed_steps > 0


# ---------------------------------------------------------------------------
# chunked prefill x prefix cache (the PR-7 machinery at chunk granularity)
# ---------------------------------------------------------------------------

def test_prefix_hit_ending_mid_chunk_stays_exact(tr):
    """A cached prefix that ends MID-chunk (and mid-page): the follower's
    chunk cursor starts at the matched token count inside the COW'd
    boundary page, only the uncached remainder takes chunk rows, and the
    output bit-matches the cold run."""
    rng = np.random.default_rng(3)
    base = rng.integers(2, 23, 13).astype(np.int32)      # 3.25 pages of 4
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, prefill_chunk=8,
                        max_step_tokens=10)
    a = Request("a", base.copy(), max_new=6)
    results = eng.run([a])
    chunks_a = eng.n_prefill_chunks
    # b shares 11 of a's 13 tokens (2 full pages + 3 into the boundary
    # page — the match ends inside b's FIRST chunk), then diverges
    b_prompt = np.concatenate([base[:11], (base[11:13] + 1) % 23 + 2,
                               rng.integers(2, 23, 4)]).astype(np.int32)
    b = Request("b", b_prompt, max_new=6)
    results.update(eng.run([b]))
    assert eng.n_prefix_hits >= 1 and eng.kv.n_cow >= 1
    assert eng.prefill_tokens_saved >= 11
    # the suffix (17 - 11 = 6 tokens) fits one budget window after the
    # hit, so b paid fewer chunks than a cold 17-token prompt would
    assert eng.n_prefill_chunks - chunks_a <= 2
    # c repeats a exactly: the shared original page was never written
    c = Request("c", base.copy(), max_new=6)
    results.update(eng.run([c]))
    _assert_exact(tr, [a, b, c], results)
    _assert_sigs(eng)
    eng.kv.check_reclaimed()


def test_cow_divergence_inside_chunk_boundary_stays_exact(tr):
    """COW divergence landing inside a chunk's page span: two concurrent
    followers of the same prefix, one diverging mid-page — each writes
    only its private boundary copy, both bit-match cold runs, and the
    donor page survives for a later exact repeat."""
    rng = np.random.default_rng(4)
    base = rng.integers(2, 23, 10).astype(np.int32)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, prefill_chunk=4, max_step_tokens=6)
    warm = Request("warm", base.copy(), max_new=5)
    results = eng.run([warm])
    x = Request("x", np.concatenate([base[:9], [3, 4, 5]])
                .astype(np.int32), max_new=5)
    y = Request("y", np.concatenate([base[:9], [7, 8]])
                .astype(np.int32), max_new=5)
    eng.add_request(x)
    eng.add_request(y)
    eng.step()                       # both admitted: both hit, both COW
    assert eng.n_prefix_hits >= 2
    assert eng.kv.n_cow >= 2, "mid-page divergence never copied-on-write"
    assert eng.kv.shared_pages_in_use >= 2
    results.update(eng.run())
    again = Request("again", base.copy(), max_new=5)
    results.update(eng.run([again]))
    _assert_exact(tr, [warm, x, y, again], results)
    _assert_sigs(eng)
    eng.kv.check_reclaimed()


def test_preempt_of_half_chunked_prefill_replays_exact(tr):
    """Preempt -> replay of a request whose prefill was HALF-CHUNKED: a
    decoding slot starves for its next page while `big` is still
    chunking, so the scheduler preempts `big` MID-PREFILL (gen == 0,
    chunk cursor inside the prompt — never letting the decoder stall
    behind the remaining chunks), donates its committed whole pages, and
    its re-admission prefix-hits its own chunks — both requests finish
    bit-exact."""
    rng = np.random.default_rng(5)
    # 8 real pages, ps=4: a takes 2 (prompt 8) then grows to 4 while
    # decoding; big reserves 5 (prompt 20) at admission — a's growth at
    # pos 12 finds the pool dry while big, chunking 4 tokens per
    # 5-token-budget step, is still mid-prefill.  The preempt donates
    # big's 4 committed pages; its re-admission retries fail WITHOUT
    # evicting them (the try_grow feasibility gate) until a finishes,
    # then prefix-hit.
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=24, num_pages=9,
                        prefill_chunk=4, max_step_tokens=5)
    a = Request("a", rng.integers(2, 23, 8).astype(np.int32), max_new=8)
    big = Request("big", rng.integers(2, 23, 20).astype(np.int32),
                  max_new=3)
    eng.add_request(a)
    eng.step()                        # a: first chunk
    eng.add_request(big)
    preempted_mid_prefill = False
    for _ in range(80):
        n_pre = eng.n_preemptions
        busy = eng.step()
        if eng.n_preemptions > n_pre and big in eng.queue \
                and (big._preempted_gen or []) == []:
            preempted_mid_prefill = True
        if not busy:
            break
    results = dict(eng.results)
    results.update(eng.run())
    assert eng.n_preemptions > 0, "pool was never overcommitted"
    assert preempted_mid_prefill, \
        "big was never preempted mid-prefill — the decoder must not " \
        "stall behind a filler's remaining chunks"
    _assert_exact(tr, [a, big], results)
    # big's replay prefix-hit its own donated chunk pages
    assert eng.n_prefix_hits > 0
    assert (eng.kv._ref == 0).all()
    _assert_sigs(eng)


def test_preempt_of_decoding_slot_replays_exact_with_chunks_inflight(tr):
    """The classic decode-preempt replay, but with the mixed step in the
    loop: pressure comes from a chunking admission, the decode victim's
    stash replays through mixed steps, everything stays exact."""
    rng = np.random.default_rng(6)
    prompts = [rng.integers(2, 23, n).astype(np.int32) for n in (6, 4, 7)]
    reqs = [Request(i, p, max_new=8) for i, p in enumerate(prompts)]
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=16, num_pages=6,
                        prefill_chunk=4, max_step_tokens=6)
    results = eng.run(reqs)
    assert eng.n_preemptions > 0, "pool was never overcommitted"
    _assert_exact(tr, reqs, results)
    assert (eng.kv._ref == 0).all()
    _assert_sigs(eng)


# ---------------------------------------------------------------------------
# admission beyond the feeder-bucket grid (the bucket-ceiling fix)
# ---------------------------------------------------------------------------

def test_prompts_beyond_the_largest_feeder_bucket_admit_and_serve(tr):
    """Chunk count derives from prompt length, not a bucket ceiling: a
    prompt longer than the largest feeder bucket (512) admits, serves
    oracle-exact through ~bucketless chunk steps, and the signature set
    does NOT grow with prompt length.  Only pool capacity rejects, with
    an actionable error."""
    rng = np.random.default_rng(7)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=16,
                        max_context=576, prefill_chunk=64,
                        max_step_tokens=66)
    long_req = Request("long", rng.integers(2, 23, 520).astype(np.int32),
                       max_new=3)
    short = Request("short", rng.integers(2, 23, 5).astype(np.int32),
                    max_new=3)
    results = eng.run([long_req, short])
    _assert_exact(tr, [long_req, short], results)
    _assert_sigs(eng)
    # capacity (not bucket) is the only rejection, and it says what to do
    with pytest.raises(ValueError, match="raise max_context"):
        eng.add_request(Request("huge",
                                rng.integers(2, 23, 640).astype(np.int32),
                                max_new=3))


def test_set_chunking_validates_and_toggles(tr):
    """set_chunking is the A/B knob: budget must exceed num_slots,
    toggling to None restores the legacy bucketed path, and both modes
    produce identical tokens for the same request."""
    rng = np.random.default_rng(8)
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                        max_context=32, prefix_cache=False)
    assert eng.prefill_chunk == 16 and eng.max_step_tokens == 18
    with pytest.raises(ValueError, match="must exceed num_slots"):
        eng.set_chunking(4, max_step_tokens=2)
    with pytest.raises(ValueError, match="must be positive"):
        eng.set_chunking(0)
    prompt = rng.integers(2, 23, 9).astype(np.int32)
    chunked = eng.run([Request("r", prompt.copy(), max_new=5)])["r"]
    eng.set_chunking(None)
    assert eng.prefill_chunk is None
    legacy = eng.run([Request("r", prompt.copy(), max_new=5)])["r"]
    np.testing.assert_array_equal(chunked, legacy)
    assert len(eng._prefill_cache) > 0, "legacy mode never bucketed"


# ---------------------------------------------------------------------------
# ops-level oracle: the ragged row path vs the per-slot decode path
# ---------------------------------------------------------------------------

def test_ragged_paged_attention_matches_per_slot_step(tr):
    """A packed row list holding one decode row per slot reproduces
    paged_attention_step exactly (same math, row-indirected), and chunk
    rows of one slot see each other's K/V under the causal mask."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import (paged_attention_step,
                                          ragged_paged_attention_step)

    rng = np.random.default_rng(1)
    S, H, Hkv, D, ps, maxp, P = 3, 4, 2, 8, 4, 4, 12
    pos = np.asarray([5, 9, 2], np.int32)
    table = np.asarray([[4, 7, 0, 0], [2, 9, 5, 0], [11, 0, 0, 0]],
                       np.int32)

    def mk(*shape):
        return jnp.asarray(rng.normal(size=shape), jnp.float32)

    q, kn, vn = mk(S, 1, H, D), mk(S, 1, Hkv, D), mk(S, 1, Hkv, D)
    kp, vp = jnp.zeros((P, ps, Hkv, D)), jnp.zeros((P, ps, Hkv, D))
    for s in range(S):
        for t in range(int(pos[s])):
            kp = kp.at[table[s, t // ps], t % ps].set(mk(Hkv, D))
            vp = vp.at[table[s, t // ps], t % ps].set(mk(Hkv, D))

    want, wck, wcv = paged_attention_step(
        q, kn, vn, kp, vp, jnp.asarray(table), jnp.asarray(pos),
        use_kernel=False)
    got, gck, gcv = ragged_paged_attention_step(
        q[:, 0], kn[:, 0], vn[:, 0], kp, vp, jnp.asarray(table),
        jnp.arange(S, dtype=jnp.int32), jnp.asarray(pos),
        use_kernel=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want[:, 0]),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(gck), np.asarray(wck))
    np.testing.assert_array_equal(np.asarray(gcv), np.asarray(wcv))

    # intra-chunk causality: two consecutive rows of slot 2 — row 1 must
    # attend row 0's K/V written THIS call.  Oracle: run the rows one at
    # a time through the per-slot step.
    q2 = mk(2, H, D)
    kn2, vn2 = mk(2, Hkv, D), mk(2, Hkv, D)
    chunk_out, _, _ = ragged_paged_attention_step(
        q2, kn2, vn2, kp, vp, jnp.asarray(table),
        jnp.asarray([2, 2], jnp.int32),
        jnp.asarray([pos[2], pos[2] + 1], jnp.int32), use_kernel=False)
    o1, ck1, cv1 = paged_attention_step(
        q2[0][None, None], kn2[0][None, None], vn2[0][None, None],
        kp, vp, jnp.asarray(table[2:3]), jnp.asarray(pos[2:3]),
        use_kernel=False)
    o2, _, _ = paged_attention_step(
        q2[1][None, None], kn2[1][None, None], vn2[1][None, None],
        ck1, cv1, jnp.asarray(table[2:3]), jnp.asarray(pos[2:3] + 1),
        use_kernel=False)
    np.testing.assert_allclose(np.asarray(chunk_out[0]),
                               np.asarray(o1[0, 0]), rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(chunk_out[1]),
                               np.asarray(o2[0, 0]), rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pallas_ragged_kernel_matches_fallback(tr):
    """Interpret-mode parity of the row-indirected Pallas kernel against
    the jnp ragged gather fallback over a mixed decode/chunk row list."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import ragged_paged_attention_step
    from paddle_tpu.ops.pallas_paged import paged_attention

    rng = np.random.default_rng(0)
    S, H, Hkv, D, ps, maxp = 3, 4, 2, 8, 4, 4
    P = 1 + S * maxp
    kp = jnp.asarray(rng.normal(size=(P, ps, Hkv, D)), jnp.float32)
    vp = jnp.asarray(rng.normal(size=(P, ps, Hkv, D)), jnp.float32)
    table = np.zeros((S + 1, maxp), np.int32)   # + virtual trash row
    free = list(range(1, P))
    pos = np.asarray([6, 3, 10], np.int32)
    for s in range(S):
        for j in range(-(-int(pos[s] + 4) // ps)):
            table[s, j] = free.pop()
    # rows: slot 0 decode, slot 1 a 3-token chunk, slot 2 decode, one pad
    row_slot = np.asarray([0, 1, 1, 1, 2, S], np.int32)
    row_pos = np.asarray([pos[0], pos[1], pos[1] + 1, pos[1] + 2,
                          pos[2], 0], np.int32)
    T = row_slot.size
    q = jnp.asarray(rng.normal(size=(T, H, D)), jnp.float32)
    kn = jnp.asarray(rng.normal(size=(T, Hkv, D)), jnp.float32)
    vn = jnp.asarray(rng.normal(size=(T, Hkv, D)), jnp.float32)
    want, ck, cv = ragged_paged_attention_step(
        q, kn, vn, kp, vp, jnp.asarray(table), jnp.asarray(row_slot),
        jnp.asarray(row_pos), use_kernel=False)
    got = paged_attention(q, ck, cv, jnp.asarray(table),
                          jnp.asarray(row_pos) + 1,
                          row_slot=jnp.asarray(row_slot))
    real = row_slot < S
    np.testing.assert_allclose(np.asarray(got)[real],
                               np.asarray(want)[real],
                               rtol=2e-5, atol=2e-5)
