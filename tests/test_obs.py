"""Observability suite (paddle_tpu/obs): tracer ring semantics, Chrome
trace export validity, metrics registry + Prometheus render, Stat
thread-safety, full request-lifecycle traces out of the serving engine
(incl. preempt + replay), and the trainer's metrics.jsonl sink."""

import json
import os
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.obs import (CATALOG, MetricsRegistry, Tracer,
                            barrier_collector, get_tracer,
                            spans_to_chrome, statset_collector)
from paddle_tpu.utils.stat import SAMPLE_WINDOW, Stat, StatSet


# ---------------------------------------------------------------------------
# tracer ring
# ---------------------------------------------------------------------------

def test_ring_overflow_keeps_newest_in_order():
    t = Tracer(capacity=8)
    t.enabled = True
    for i in range(20):
        t.add(f"s{i}", ts=float(i), dur=0.5)
    assert t.recorded == 20 and t.dropped == 12
    snap = t.snapshot()
    assert [s["name"] for s in snap] == [f"s{i}" for i in range(12, 20)]
    assert [s["seq"] for s in snap] == list(range(12, 20))
    # under capacity: everything retained, oldest first
    t.clear()
    t.add("a", 0.0, 1.0)
    t.add("b", 2.0, 1.0)
    assert [s["name"] for s in t.snapshot()] == ["a", "b"]
    assert t.dropped == 0


def test_tracer_overflow_surfaces_through_collector():
    """ISSUE 13 satellite: the ring drops spans SILENTLY when full — the
    only visibility is tracer_collector's accounting, so a strict
    registry must render recorded/dropped totals plus the capacity they
    are read against after an overflow."""
    from paddle_tpu.obs import tracer_collector

    t = Tracer(capacity=4)
    t.enabled = True
    for i in range(10):
        t.add(f"s{i}", float(i), 0.1)
    assert len(t.snapshot()) == 4          # the drop is silent...
    reg = MetricsRegistry(strict=True)
    reg.register_collector(tracer_collector(t))
    snap = reg.snapshot()                  # ...but not invisible
    assert snap["trace_spans_recorded_total"] == 10.0
    assert snap["trace_spans_dropped_total"] == 6.0
    assert snap["trace_ring_capacity"] == 4.0
    text = reg.render()
    assert "trace_spans_dropped_total 6" in text
    assert "trace_ring_capacity 4" in text


def test_merge_chrome_aligns_clocks_across_process_tracks():
    """ISSUE 13: merge_chrome applies each source's offset before the
    global rebase, gives every source its own pid + process_name, and
    two spans simultaneous in wall time land at the same merged ts even
    when the source perf_counter epochs differ wildly."""
    from paddle_tpu.obs import merge_chrome

    # process A's epoch: event at local t=100.0; process B's epoch is
    # 90s behind (same wall moment reads 10.0 there) -> offset_s=+90
    src_a = {"spans": [{"seq": 0, "name": "ingress", "track": "req:x",
                        "ts": 100.0, "dur": 2.0}],
             "process": {"role": "router", "pid": 11,
                         "addr": "h:1"}, "offset_s": 0.0}
    src_b = {"spans": [{"seq": 0, "name": "queued", "track": "req:x",
                        "ts": 10.0, "dur": 1.0},
                       {"seq": 1, "name": "done", "track": "req:x",
                        "ts": 11.5, "dur": 0.0, "instant": True}],
             "process": {"role": "replica", "pid": 11,
                         "addr": "h:2"}, "offset_s": 90.0}
    merged = merge_chrome([src_a, src_b])
    evs = merged["traceEvents"]
    procs = {e["pid"]: e["args"]["name"] for e in evs
             if e.get("name") == "process_name"}
    assert len(procs) == 2                 # same OS pid, distinct tracks
    assert "router" in procs[1] and "replica" in procs[2]
    ing = next(e for e in evs if e["name"] == "ingress")
    qd = next(e for e in evs if e["name"] == "queued")
    done = next(e for e in evs if e["name"] == "done")
    assert ing["ts"] == 0.0                # global rebase to earliest
    assert qd["ts"] == 0.0                 # same wall moment, aligned
    assert done["ts"] == pytest.approx(1.5e6)
    assert done["ph"] == "i" and qd["ph"] == "X"


def test_disabled_tracer_records_nothing():
    t = Tracer(capacity=8)
    t.add("x", 0.0, 1.0)
    t.instant("y")
    with t.span("z"):
        pass
    assert t.end(t.begin("w")) is None
    assert t.recorded == 0 and t.snapshot() == []


def test_begin_end_and_span_record_attrs_and_durations():
    t = Tracer()
    t.enabled = True
    h = t.begin("queued", track="req:a", max_new=4)
    t.end(h, reason="length")
    with t.span("prefill", track="req:a", bucket=16):
        pass
    t.instant("done", track="req:a")
    snap = t.snapshot()
    assert [s["name"] for s in snap] == ["queued", "prefill", "done"]
    assert snap[0]["attrs"] == {"max_new": 4, "reason": "length"}
    assert snap[1]["attrs"] == {"bucket": 16}
    assert snap[2].get("instant") is True
    assert all(s["dur"] >= 0.0 for s in snap)


def test_chrome_export_schema_and_track_nesting():
    """Chrome trace_event validity: metadata thread names per track, "X"
    complete events with non-negative ts/dur, instants as "i" — and spans
    on one track are monotonically ordered and non-overlapping (the
    sequential-phase contract a lifecycle trace relies on)."""
    t = Tracer()
    t.enabled = True
    t.add("queued", 10.0, 0.5, track="req:a")
    t.add("prefill", 10.5, 0.25, track="req:a", attrs={"bucket": 16})
    t.add("decode", 10.75, 1.0, track="req:a")
    t.instant("done", track="req:a")
    t.add("dispatch", 10.2, 0.1, track="trainer")
    doc = t.chrome_trace()
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert {m["args"]["name"] for m in meta} == {"req:a", "trainer"}
    xs = [e for e in evs if e["ph"] == "X"]
    ins = [e for e in evs if e["ph"] == "i"]
    assert len(xs) == 4 and len(ins) == 1
    for e in xs + ins:
        assert e["ts"] >= 0 and e["name"]
        assert {"pid", "tid"} <= set(e)
    assert all(e["dur"] >= 0 for e in xs)
    # per-track phases nest monotonically: next span starts at/after the
    # previous one's end (1us grid tolerance)
    tid_a = next(m["tid"] for m in meta if m["args"]["name"] == "req:a")
    lane = sorted((e for e in xs if e["tid"] == tid_a),
                  key=lambda e: e["ts"])
    assert [e["name"] for e in lane] == ["queued", "prefill", "decode"]
    for prev, nxt in zip(lane, lane[1:]):
        assert nxt["ts"] >= prev["ts"] + prev["dur"] - 1.0
    # attrs survive as args
    assert next(e for e in xs if e["name"] == "prefill")["args"] == \
        {"bucket": 16}
    # json-serializable end to end
    json.dumps(doc)


def test_trace_dump_tool_roundtrip(tmp_path):
    from tools.trace_dump import load_spans, main, summarize

    t = Tracer()
    t.enabled = True
    t.add("queued", 0.0, 0.5, track="req:a")
    t.instant("done", track="req:a")
    src = tmp_path / "spans.jsonl"
    assert t.export_jsonl(str(src)) == 2
    spans = load_spans(str(src))
    assert [s["name"] for s in spans] == ["queued", "done"]
    assert "queued" in summarize(spans)
    out = tmp_path / "trace.json"
    assert main([str(src), "-o", str(out)]) == 0
    with open(out) as f:
        doc = json.load(f)
    assert any(e.get("name") == "queued" for e in doc["traceEvents"])
    # empty input is a loud exit 2, not a silent empty trace
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main([str(empty), "-o", str(out)]) == 2
    # a complete span missing dur (hand-edited / foreign JSONL) is the
    # clean error path too, not a KeyError traceback from spans_to_chrome
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"name": "x", "ts": 1.0}\n')
    assert main([str(bad), "-o", str(out)]) == 2


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_render():
    reg = MetricsRegistry()
    c = reg.counter("demo_requests_total", "requests")
    c.inc()
    c.inc(2)
    g = reg.gauge("demo_depth", "queue depth", labels=("lane",))
    g.set(3, lane="a")
    g.set_fn(lambda: 7.0, lane="b")
    h = reg.histogram("demo_latency_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert "# HELP demo_requests_total requests" in text
    assert "# TYPE demo_requests_total counter" in text
    assert "demo_requests_total 3" in text
    assert 'demo_depth{lane="a"} 3' in text
    assert 'demo_depth{lane="b"} 7' in text
    assert "# TYPE demo_latency_seconds histogram" in text
    assert 'demo_latency_seconds_bucket{le="0.1"} 1' in text
    assert 'demo_latency_seconds_bucket{le="1"} 2' in text
    assert 'demo_latency_seconds_bucket{le="+Inf"} 3' in text
    assert "demo_latency_seconds_count 3" in text
    snap = reg.snapshot()
    assert snap["demo_requests_total"] == 3.0
    assert snap['demo_depth{lane="b"}'] == 7.0
    # re-declaration is idempotent; kind mismatch is loud
    assert reg.counter("demo_requests_total") is c
    with pytest.raises(ValueError, match="re-declared"):
        reg.gauge("demo_requests_total")
    with pytest.raises(ValueError, match="cannot decrease"):
        c.inc(-1)
    with pytest.raises(ValueError, match="declared labels"):
        g.set(1, wrong="x")


def test_strict_registry_pins_names_to_catalog():
    reg = MetricsRegistry(strict=True)
    reg.gauge("serving_queue_depth")             # catalogued: fine
    with pytest.raises(ValueError, match="CATALOG"):
        reg.gauge("not_a_documented_metric")
    reg.register_collector(lambda: [("rogue_metric", "gauge", None, 1.0)])
    with pytest.raises(ValueError, match="uncataloged"):
        reg.render()
    # every catalog name is docs-lintable (the tools/check_metrics_names
    # grammar): lowercase identifier
    for name in CATALOG:
        assert name[0].isalpha() and name == name.lower()


def test_statset_and_barrier_collectors():
    from paddle_tpu.parallel.barrier_stat import BarrierTimer

    ss = StatSet("t")
    for v in (0.01, 0.02, 0.03):
        ss.get("phase_a").add(v)
    reg = MetricsRegistry()
    reg.register_collector(statset_collector(
        ss, "trainer_host_phase_seconds", "trainer_host_phase_count",
        label="phase", total_metric="trainer_host_phase_seconds_total"))
    bt = BarrierTimer()
    bt.dispatch_s.extend([0.001, 0.002])
    reg.register_collector(barrier_collector(bt))
    snap = reg.snapshot()
    assert snap['trainer_host_phase_count{phase="phase_a"}'] == 3.0
    assert abs(snap['trainer_host_phase_seconds_total{phase="phase_a"}']
               - 0.06) < 1e-9
    p50 = snap['trainer_host_phase_seconds{phase="phase_a",quantile="p50"}']
    assert abs(p50 - 0.02) < 1e-9
    disp = snap['trainer_barrier_seconds{quantile="p50",window="dispatch"}']
    assert abs(disp - 0.0015) < 1e-9


# ---------------------------------------------------------------------------
# Stat thread-safety (pump add() vs stats-RPC percentiles())
# ---------------------------------------------------------------------------

def test_stat_concurrent_add_and_percentiles_exact():
    ss = StatSet("conc")
    n_threads, per = 4, 5000
    stop = threading.Event()
    errors = []

    def reader():
        try:
            while not stop.is_set():
                ss.percentiles("hot", (50.0, 99.0))
        except Exception as e:                     # noqa: BLE001
            errors.append(e)

    def writer(k):
        try:
            for i in range(per):
                ss.get("hot").add((k * per + i) * 1e-6)
        except Exception as e:                     # noqa: BLE001
            errors.append(e)

    readers = [threading.Thread(target=reader) for _ in range(2)]
    writers = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)]
    for th in readers + writers:
        th.start()
    for th in writers:
        th.join()
    stop.set()
    for th in readers:
        th.join()
    assert not errors, errors
    s = ss.get("hot")
    # the lock makes accounting EXACT under contention, not approximate
    assert s.count == n_threads * per
    assert len(s.samples) == min(SAMPLE_WINDOW, s.count)
    total = sum((k * per + i) * 1e-6
                for k in range(n_threads) for i in range(per))
    assert abs(s.total_s - total) < 1e-9
    p = ss.percentiles("hot", (50.0,))
    assert p["p50"] > 0.0


def test_statset_get_creation_race_single_object():
    ss = StatSet("race")
    got = []

    def grab():
        got.append(ss.get("only"))

    ths = [threading.Thread(target=grab) for _ in range(8)]
    for t in ths:
        t.start()
    for t in ths:
        t.join()
    assert all(s is got[0] for s in got)


# ---------------------------------------------------------------------------
# engine request-lifecycle traces (the oracle-implied phase regression)
# ---------------------------------------------------------------------------

@pytest.fixture()
def lifecycle_tracer():
    t = get_tracer()
    saved = (t.enabled, t._ring, t._n)
    t.clear()
    t.enabled = True
    yield t
    t.enabled, t._ring, t._n = saved


def _phases(tracer, rid):
    return [s["name"] for s in tracer.snapshot()
            if s["track"] == f"req:{rid}"]


def test_request_lifecycle_phases_incl_preempt_replay(lifecycle_tracer):
    """A full serving run traces exactly the lifecycle the oracle run
    implies: queued -> prefill -> decode -> done for untroubled requests;
    a page-pool preemption inserts preempt -> queued -> prefill -> replay
    before the terminal phase.  Durations are sane: phases on one request
    track are sequential and the decode span covers the decode steps."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.serving import Request, ServingEngine
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    tr = Trainer(cfg, seed=7)

    # -- no preemption: exact phase list ---------------------------------
    eng = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=8,
                        max_context=64)
    rng = np.random.default_rng(0)
    eng.add_request(Request("plain", rng.integers(2, 31, 5), max_new=4))
    res = eng.run()
    assert len(res["plain"]) == 9
    assert _phases(lifecycle_tracer, "plain") == \
        ["queued", "prefill", "decode", "done"]
    spans = {s["name"]: s for s in lifecycle_tracer.snapshot()
             if s["track"] == "req:plain"}
    # chunked prefill (the default): the span carries the chunk size and
    # prompt length instead of a legacy bucket
    assert spans["prefill"]["attrs"]["prompt_len"] == 5
    assert spans["prefill"]["attrs"]["chunk"] == eng.prefill_chunk
    assert spans["done"]["attrs"]["reason"] == "length"
    # sequential, non-overlapping phases
    order = [spans[n] for n in ("queued", "prefill", "decode")]
    for a, b in zip(order, order[1:]):
        assert b["ts"] >= a["ts"] + a["dur"] - 1e-6
    # the engine lane recorded one span per compiled step (the mixed
    # chunk step that sampled token 0 carries a `mixed` attr)
    steps = [s for s in lifecycle_tracer.snapshot()
             if s["track"] == "engine" and s["name"] == "decode_step"]
    assert len(steps) == eng.n_decode_steps
    # span-vs-stats reconciliation: the decode span covers every PURE
    # decode step this (only) request was live for — the mixed prefill
    # step ran inside the `prefill` phase, before decode opened
    assert spans["decode"]["dur"] >= sum(
        s["dur"] for s in steps if not s["attrs"].get("mixed")) - 1e-6

    # -- overcommitted pool: preempt + replay phases ---------------------
    lifecycle_tracer.clear()
    eng2 = ServingEngine(tr.executor, tr.params, num_slots=2, page_size=4,
                         max_context=16, num_pages=6)
    rng = np.random.default_rng(1)
    reqs = [Request(f"r{i}", rng.integers(2, 31, 8), max_new=8)
            for i in range(2)]
    out = eng2.run(reqs)
    assert eng2.n_preemptions > 0, "pool was never overcommitted"
    assert set(out) == {"r0", "r1"}
    preempted = [s["track"][4:] for s in lifecycle_tracer.snapshot()
                 if s["name"] == "preempt"]
    assert preempted, "no preempt instant recorded"
    survivors = {"r0", "r1"} - set(preempted)
    for rid in survivors:
        assert _phases(lifecycle_tracer, rid) == \
            ["queued", "prefill", "decode", "done"]
    for rid in set(preempted):
        # a preempted victim's re-admission may prefix-hit its own donated
        # pages (the PR-7 donation, preserved across doomed retries by the
        # allocator's feasibility gate) — the `prefix_hit` instant rides
        # the same track; drop it when checking the phase SHAPE
        ph = [n for n in _phases(lifecycle_tracer, rid)
              if n != "prefix_hit"]
        # one preempt cycle: the oracle-implied shape is
        #   queued prefill decode (preempt queued prefill replay)+ ... done
        assert ph[:4] == ["queued", "prefill", "decode", "preempt"]
        assert "replay" in ph, f"preempted {rid} never traced a replay: {ph}"
        assert ph[-1] == "done"
        i = ph.index("replay")
        assert ph[i - 2:i] == ["queued", "prefill"], ph
        # replay happened strictly after the preempt marker
        assert i > ph.index("preempt")


def test_cancel_and_deadline_terminal_phases(lifecycle_tracer):
    """Aborted requests close their open phase and mark the right
    terminal event: cancelled (client abort while decoding) and deadline
    (expired while queued — no slot ever held)."""
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.serving import Request, ServingEngine
    from paddle_tpu.trainer.trainer import Trainer

    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=31,dim=16,layers=1,heads=2,batch_size=4")
    tr = Trainer(cfg, seed=3)
    eng = ServingEngine(tr.executor, tr.params, num_slots=1, page_size=8,
                        max_context=64)
    eng.clock = lambda: float(eng.n_decode_steps)
    eng.add_request(Request("work", [3, 4, 5], max_new=30))
    # expires while QUEUED: the single slot is busy with "work"
    eng.add_request(Request("late", [4, 5], max_new=30, deadline=2.0))
    for _ in range(4):
        eng.step()
    eng.cancel("work")
    ph_w = _phases(lifecycle_tracer, "work")
    assert ph_w == ["queued", "prefill", "decode", "cancelled"]
    ph_l = _phases(lifecycle_tracer, "late")
    assert ph_l == ["queued", "deadline"]


# ---------------------------------------------------------------------------
# trainer metrics.jsonl sink
# ---------------------------------------------------------------------------

def test_trainer_metrics_jsonl_sink(tmp_path):
    from paddle_tpu.config.parser import parse_config
    from paddle_tpu.parameter.argument import Argument
    from paddle_tpu.trainer.trainer import Trainer

    cfg_src = (
        "from paddle_tpu.dsl import *\n"
        "settings(batch_size=8, learning_rate=0.1)\n"
        "x = data_layer(name='x', size=4)\n"
        "out = fc_layer(input=x, size=2, act=SoftmaxActivation(), "
        "name='out')\n"
        "classification_cost(input=out, label=data_layer(name='y', "
        "size=2))\n")
    cfg_file = tmp_path / "cfg.py"
    cfg_file.write_text(cfg_src)
    tr = Trainer(parse_config(str(cfg_file), ""), seed=0)
    rng = np.random.default_rng(0)

    def batches():
        for _ in range(3):
            x = rng.normal(size=(8, 4)).astype(np.float32)
            yield {"x": Argument(value=x),
                   "y": Argument(ids=(x.sum(-1) > 0).astype(np.int32))}

    stats = tr.train_one_pass(batches=batches())
    path = tr.append_metrics(str(tmp_path / "run"), extra=stats)
    assert path.endswith("metrics.jsonl")
    with open(path) as f:
        recs = [json.loads(ln) for ln in f]
    assert len(recs) == 1
    rec = recs[0]
    assert rec["pass_id"] == 1 and "ts" in rec
    assert rec["cost"] == pytest.approx(stats["cost"])
    m = rec["metrics"]
    assert m["trainer_pass_id"] == 1.0
    assert m["trainer_batches_total"] == 3.0
    assert m["trainer_samples_total"] == 24.0
    # the global StatSet host phases flowed through the collector
    assert any(k.startswith('trainer_host_phase_count{phase="trainOneBatch"')
               for k in m), sorted(m)[:8]
    # appends accumulate (one line per pass)
    tr.append_metrics(str(tmp_path / "run"))
    with open(path) as f:
        assert len(f.readlines()) == 2
