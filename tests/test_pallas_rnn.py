"""Pallas fused RNN kernels vs the lax.scan reference — numeric oracle
(analog of the reference's CPU-vs-GPU comparison tests for its fused LSTM
kernels, ref: paddle/gserver/tests/test_RecurrentLayer.cpp,
math/tests/test_matrixCompare.cpp pattern).  Runs the Pallas kernels in
interpret mode on CPU; on real TPU the same code path compiles.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.ops import pallas_rnn, rnn


def _lstm_case(rng, B, T, D, peep):
    x4 = jnp.asarray(rng.standard_normal((B, T, 4 * D)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((D, 4 * D)) * 0.3, jnp.float32)
    lengths = jnp.asarray(rng.integers(1, T + 1, B), jnp.int32)
    peeps = (jnp.asarray(rng.standard_normal((3, D)) * 0.2, jnp.float32)
             if peep else jnp.zeros((3, D), jnp.float32))
    h0 = jnp.zeros((B, D), jnp.float32)
    c0 = jnp.zeros((B, D), jnp.float32)
    return x4, w, lengths, peeps, h0, c0


@pytest.mark.parametrize("reverse", [False, True])
@pytest.mark.parametrize("peep", [False, True])
def test_lstm_fused_matches_scan(reverse, peep):
    rng = np.random.default_rng(0 if peep else 1)
    B, T, D = 4, 6, 8
    x4, w, lengths, peeps, h0, c0 = _lstm_case(rng, B, T, D, peep)

    def ref_loss(x4, w, peeps):
        bias = (jnp.concatenate([jnp.zeros(4 * D), peeps.reshape(-1)])
                if peep else None)
        hs, hl, cl = rnn.lstm_scan(x4, lengths, w, bias, reverse=reverse)
        return jnp.sum(hs * hs) + jnp.sum(hl) + jnp.sum(cl * cl), (hs, hl, cl)

    def fused_loss(x4, w, peeps):
        hs, hl, cl = pallas_rnn.lstm_fused(
            x4, lengths, w, peeps, h0, c0,
            active_type="tanh", gate_active_type="sigmoid",
            state_active_type="tanh", reverse=reverse)
        return jnp.sum(hs * hs) + jnp.sum(hl) + jnp.sum(cl * cl), (hs, hl, cl)

    (ref_l, (ref_hs, ref_hl, ref_cl)) = ref_loss(x4, w, peeps)
    (fus_l, (fus_hs, fus_hl, fus_cl)) = fused_loss(x4, w, peeps)
    np.testing.assert_allclose(np.asarray(fus_hs), np.asarray(ref_hs),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(fus_hl), np.asarray(ref_hl),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(fus_cl), np.asarray(ref_cl),
                               rtol=1e-5, atol=1e-5)

    g_ref = jax.grad(lambda *a: ref_loss(*a)[0], argnums=(0, 1, 2))(x4, w, peeps)
    g_fus = jax.grad(lambda *a: fused_loss(*a)[0], argnums=(0, 1, 2))(x4, w, peeps)
    for gr, gf, name in zip(g_ref, g_fus, ["dx", "dw", "dpeep"]):
        if not peep and name == "dpeep":
            continue  # scan path has no peephole param when bias is absent
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4, err_msg=name)


@pytest.mark.parametrize("reverse", [False, True])
def test_gru_fused_matches_scan(reverse):
    rng = np.random.default_rng(2)
    B, T, D = 3, 5, 8
    x3 = jnp.asarray(rng.standard_normal((B, T, 3 * D)), jnp.float32)
    wg = jnp.asarray(rng.standard_normal((D, 2 * D)) * 0.3, jnp.float32)
    wc = jnp.asarray(rng.standard_normal((D, D)) * 0.3, jnp.float32)
    lengths = jnp.asarray(rng.integers(1, T + 1, B), jnp.int32)
    h0 = jnp.zeros((B, D), jnp.float32)

    def ref_loss(x3, wg, wc):
        hs, hl = rnn.gru_scan(x3, lengths, wg, wc, None, reverse=reverse)
        return jnp.sum(hs * hs) + jnp.sum(hl), (hs, hl)

    def fused_loss(x3, wg, wc):
        hs, hl = pallas_rnn.gru_fused(
            x3, lengths, wg, wc, h0,
            active_type="tanh", gate_active_type="sigmoid", reverse=reverse)
        return jnp.sum(hs * hs) + jnp.sum(hl), (hs, hl)

    (_, (ref_hs, ref_hl)) = ref_loss(x3, wg, wc)
    (_, (fus_hs, fus_hl)) = fused_loss(x3, wg, wc)
    np.testing.assert_allclose(np.asarray(fus_hs), np.asarray(ref_hs),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(fus_hl), np.asarray(ref_hl),
                               rtol=1e-5, atol=1e-5)

    g_ref = jax.grad(lambda *a: ref_loss(*a)[0], argnums=(0, 1, 2))(x3, wg, wc)
    g_fus = jax.grad(lambda *a: fused_loss(*a)[0], argnums=(0, 1, 2))(x3, wg, wc)
    for gr, gf, name in zip(g_ref, g_fus, ["dx", "dwg", "dwc"]):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                                   rtol=1e-4, atol=1e-4, err_msg=name)
