"""Host-RAM KV spill tier (ISSUE 17): two-level eviction for the prefix
cache — cold refcount-zero cached pages spill to host RAM under page
pressure instead of being destroyed, and an admission that prefix-hits a
spilled run faults the pages back with one batched scatter.

The exactness contract is the prefix cache's, extended across the tier
boundary: a request whose prefix restores from host RAM produces tokens
BIT-IDENTICAL to a cold `lm_generate(use_cache=True)` run — greedy and
seeded sampling, through COW divergence mid-restored-page, preemption
replay, budget-pressure host evictions, and checkpoint migration — while
`_decode_step._cache_size() == 1` stays asserted (restores ride their own
bucketed admission-boundary jit; the decode/mixed signatures never see
the tier).

Most tests here recycle ONE module-scoped engine via
`reset_prefix_cache()` + `set_spill_budget()` — both idle-engine
allocator-exact knobs, and reset reproducibility is itself pinned by
test_reset_prefix_cache_drains_host_tier_and_reproduces — so the jit
compiles are paid once, not per test.  Counters are lifetime (a reset's
drains land in `_host_drained`, keeping the conservation ledger closed),
so recycled tests assert count DELTAS, never absolutes.

The fast gate (`-m "not slow"`) keeps the tentpole restore oracle, the
zero-budget back-compat guard, the budget-flip seam, reset
reproducibility, and the allocator unit; the heavier interaction
oracles (sampling, COW, preemption, LRU pressure, drain knobs,
checkpoint migration) carry `slow` like the repo's other heavy e2e
oracles and run in the full suite."""

import numpy as np
import pytest

import jax

from paddle_tpu.config.parser import parse_config
from paddle_tpu.graph.lm_decode import lm_generate
from paddle_tpu.serving import PagedKVCache, Request, ServingEngine
from paddle_tpu.trainer.trainer import Trainer

BIG = 1 << 20                       # "never the binding constraint" budget


@pytest.fixture(scope="module")
def tr():
    cfg = parse_config("demo/model_zoo/transformer_lm.py",
                       "vocab=23,dim=16,layers=2,heads=2,batch_size=4")
    return Trainer(cfg, seed=7)


def _oracle(tr, req: Request):
    toks, lens = lm_generate(
        tr.executor, tr.params, req.prompt_ids[None, :],
        max_new=req.max_new, temperature=req.temperature, top_k=req.top_k,
        top_p=req.top_p, eos_id=req.eos_id, rng=req.rng, use_cache=True)
    return np.asarray(toks)[0, :int(np.asarray(lens)[0])]


def _assert_exact(tr, reqs, results):
    for r in reqs:
        np.testing.assert_array_equal(
            _oracle(tr, r), results[r.req_id],
            err_msg=f"request {r.req_id!r} diverged from the cold "
                    f"lm_generate oracle")


def _tight_engine(tr, budget, **kw):
    """1 slot over a 5-usable-page pool: one retired 12-token sequence
    donates 3 pages, so the SECOND distinct sequence already forces
    eviction pressure — the spill trigger every test here builds on."""
    kw.setdefault("num_slots", 1)
    return ServingEngine(tr.executor, tr.params, page_size=4,
                         max_context=16, num_pages=6,
                         spill_bytes_budget=budget, **kw)


@pytest.fixture(scope="module")
def tight(tr):
    return _tight_engine(tr, BIG)


def _recycle(eng, budget=BIG):
    """Cold-cache the shared engine: both tiers drained, free list
    canonical, budget reset — only the jit caches survive."""
    eng.set_prefix_cache(True)
    eng.reset_prefix_cache()
    eng.set_spill_budget(budget)
    return eng


def _pressure_abb(tr, eng, rng, max_new=5):
    """a, then b, then b2 — three distinct 12-token sequences through the
    tight pool.  Each retired run donates its 2 fully-committed pages, so
    b2's admission overflows the 5-page pool and (with a big budget)
    spills a's chain to host instead of destroying it.  Returns the
    requests and the results dict (results hold prompt + generated
    tokens, so callers can build follow-on prompts that reach a's
    SPILLED pages)."""
    reqs = [Request(n, rng.integers(2, 23, 7).astype(np.int32),
                    max_new=max_new) for n in ("a", "b", "b2")]
    results = {}
    for r in reqs:
        results.update(eng.run([r]))
    return reqs, results


# ---------------------------------------------------------------------------
# the token-exactness oracle, extended across the spill/restore boundary
# ---------------------------------------------------------------------------

def test_spill_then_restore_hit_stays_oracle_exact(tr, tight):
    """The tentpole path end to end: pressure spills a retired run to
    host RAM (device pages freed, tokens retained), a later admission
    prefix-hits the spilled run, restores the pages with the batched
    scatter, and its tokens bit-match the cold oracle.  The tokens-saved
    counter reconciles against restored pages and the decode step stays
    ONE signature."""
    rng = np.random.default_rng(0)
    eng = _recycle(tight)
    spilled0, hits0 = eng.kv.n_spilled, eng.n_restore_hits
    restored0, saved0 = eng.kv.n_restored, eng.restore_tokens_saved
    reqs, results = _pressure_abb(tr, eng, rng)
    assert eng.kv.n_spilled - spilled0 >= 2, \
        "pressure never reached the host tier"
    assert eng.kv.host_page_count >= 2
    assert eng.kv.free_page_count + eng.kv.cached_page_count == \
        eng.kv.num_pages - 1, \
        "spilled pages must FREE their device page (that is the point)"
    seq_a = np.asarray(results["a"]).astype(np.int32)
    # c extends a's sequence past its first two (now host-resident)
    # pages: the hit must fault them back, not re-prefill
    c = Request("c", seq_a[:9].copy(), max_new=4)
    results.update(eng.run([c]))
    assert eng.n_restore_hits - hits0 >= 1, \
        "hit on a spilled run never restored"
    restored = eng.kv.n_restored - restored0
    assert restored >= 2
    assert 0 < eng.restore_tokens_saved - saved0 <= \
        restored * eng.kv.page_size, \
        "restored-token accounting out of band"
    _assert_exact(tr, reqs + [c], results)
    assert eng._decode_step._cache_size() == 1
    # restores bucket by power-of-two page count: a handful of jits,
    # never one per batch size
    assert 1 <= len(eng.kv._restore_fns) <= 3
    eng.kv.check_reclaimed()


@pytest.mark.slow
def test_sampled_restore_hit_stays_oracle_exact(tr, tight):
    """Seeded sampling through a restored prefix: the spilled pages'
    K/V round-trips host RAM bit-exactly, so the sampled continuation
    (its own key schedule, temperature/top-p knobs) matches the cold
    oracle the same way greedy does."""
    rng = np.random.default_rng(1)
    eng = _recycle(tight)
    spilled0, hits0 = eng.kv.n_spilled, eng.n_restore_hits
    a = Request("a", rng.integers(2, 23, 7).astype(np.int32), max_new=5,
                temperature=0.8, top_k=5, rng=jax.random.PRNGKey(11))
    results = eng.run([a])
    fillers = [Request(n, rng.integers(2, 23, 7).astype(np.int32),
                       max_new=5) for n in ("b", "b2")]
    for f in fillers:                       # pressure: spill a's chain
        results.update(eng.run([f]))
    assert eng.kv.n_spilled - spilled0 >= 1
    seq_a = np.asarray(results["a"]).astype(np.int32)
    c = Request("c", seq_a[:9].copy(), max_new=4,
                temperature=0.7, top_p=0.9, rng=jax.random.PRNGKey(12))
    results.update(eng.run([c]))
    assert eng.n_restore_hits - hits0 >= 1
    _assert_exact(tr, [a, c] + fillers, results)
    eng.kv.check_reclaimed()


@pytest.mark.slow
def test_cow_divergence_mid_restored_page(tr, tight):
    """d's prompt follows a's sequence INTO a restored page and then
    diverges: admission restores the spilled run, COWs the boundary
    page, and d's suffix overwrites only its own copy — d is exact, and
    a later request replaying a's exact sequence is exact too (the
    restored original was never written)."""
    rng = np.random.default_rng(2)
    eng = _recycle(tight)
    hits0 = eng.n_restore_hits
    reqs, results = _pressure_abb(tr, eng, rng)
    seq_a = np.asarray(results["a"]).astype(np.int32)
    cow0 = eng.kv.n_cow
    # matches 6 of a's tokens (1 full spilled page + 2 into the second),
    # then diverges mid-page: the boundary page restores AND COWs
    d_prompt = np.concatenate([seq_a[:6],
                               (seq_a[6:8] + 1) % 21 + 2,
                               rng.integers(2, 23, 2)]).astype(np.int32)
    d = Request("d", d_prompt, max_new=3)
    results.update(eng.run([d]))
    assert eng.n_restore_hits - hits0 >= 1
    assert eng.kv.n_cow > cow0, \
        "mid-restored-page divergence never copied-on-write"
    e = Request("e", seq_a[:9].copy(), max_new=3)
    results.update(eng.run([e]))
    _assert_exact(tr, reqs + [d, e], results)
    assert eng._decode_step._cache_size() == 1
    eng.kv.check_reclaimed()


@pytest.mark.slow
def test_preempt_replay_with_spill_tier_on_stays_exact(tr):
    """Overcommitted slots over the spilling pool: preemptions, device
    evictions, spills and restores all interleave, and every request of
    both waves still matches its cold oracle with refcounts back to
    zero — the tier adds no scheduling state the replay can trip on."""
    rng = np.random.default_rng(3)
    eng = _tight_engine(tr, BIG, num_slots=2)
    reqs, results = _pressure_abb(tr, eng, rng)
    seq_a = np.asarray(results["a"]).astype(np.int32)
    seq_b = np.asarray(results["b"]).astype(np.int32)
    wave = [Request("r1", seq_a[:9].copy(), max_new=6),
            Request("r2", seq_b[:9].copy(), max_new=6),
            Request("r3", rng.integers(2, 23, 6).astype(np.int32),
                    max_new=6)]
    results.update(eng.run(wave))
    assert eng.n_preemptions > 0, "pool was never overcommitted"
    assert eng.kv.n_spilled > 0
    _assert_exact(tr, reqs + wave, results)
    assert (eng.kv._ref == 0).all()
    assert eng._decode_step._cache_size() == 1
    eng.kv.check_reclaimed()


# ---------------------------------------------------------------------------
# budget discipline: LRU inside the host tier, zero-budget == old behavior
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_host_tier_budget_evicts_lru_and_never_overflows(tr, tight):
    """A ONE-page budget under two pages of spill pressure: the tier
    drops its least-recently-used host leaf to admit the second spill
    (kv.check() asserts the byte bound), and a hit on the run whose page
    was dropped simply admits the missing part cold — still exact."""
    rng = np.random.default_rng(4)
    budget = tight.kv.page_nbytes
    eng = _recycle(tight, budget)
    spilled0, evicted0 = eng.kv.n_spilled, eng.kv.n_host_evicted
    reqs, results = _pressure_abb(tr, eng, rng)
    assert eng.kv.n_spilled - spilled0 >= 2
    assert eng.kv.n_host_evicted - evicted0 > 0, \
        "over-budget spills never displaced the host LRU"
    assert eng.kv.host_bytes <= budget
    seq_a = np.asarray(results["a"]).astype(np.int32)
    c = Request("c", seq_a[:9].copy(), max_new=4)
    results.update(eng.run([c]))
    _assert_exact(tr, reqs + [c], results)
    eng.kv.check_reclaimed()


def test_zero_budget_is_the_pre_spill_engine(tr, tight):
    """spill_bytes_budget=0 (the default): the same pressure workload
    destroys victims exactly as before the tier existed — nothing
    spills, no NEW restore jit compiles, eviction still relieves
    pressure, outputs stay exact."""
    rng = np.random.default_rng(0)
    eng = _recycle(tight, 0)
    spilled0, hits0 = eng.kv.n_spilled, eng.n_restore_hits
    ev0, fns0 = eng.prefix.n_evictions, len(eng.kv._restore_fns)
    reqs, results = _pressure_abb(tr, eng, rng)
    assert eng.prefix.n_evictions > ev0, "no pressure — workload too loose"
    assert eng.kv.n_spilled == spilled0 and eng.kv.host_page_count == 0
    seq_a = np.asarray(results["a"]).astype(np.int32)
    c = Request("c", seq_a[:9].copy(), max_new=4)
    results.update(eng.run([c]))
    assert eng.n_restore_hits == hits0
    assert len(eng.kv._restore_fns) == fns0
    _assert_exact(tr, reqs + [c], results)
    eng.kv.check_reclaimed()


# ---------------------------------------------------------------------------
# cache-management seams: reset / disable / budget flips / stale generations
# ---------------------------------------------------------------------------

def test_reset_prefix_cache_drains_host_tier_and_reproduces(tr, tight):
    """reset_prefix_cache drains BOTH tiers (a host entry left behind
    would hold budget bytes no node can ever name again) and bumps the
    spill generation; re-running the workload afterwards reproduces the
    same tokens — a restart is bit-indistinguishable from a fresh
    engine, host tier included."""
    eng = _recycle(tight)

    def mk():
        r2 = np.random.default_rng(50)
        return [Request(n, r2.integers(2, 23, 7).astype(np.int32),
                        max_new=5) for n in ("a", "b", "b2")]

    first = {}
    for r in mk():
        first.update(eng.run([r]))
    assert eng.kv.host_page_count > 0
    gen0 = eng.kv._host_gen
    eng.reset_prefix_cache()
    assert eng.kv.host_page_count == 0 and eng.kv.host_bytes == 0
    assert eng.kv._host_gen > gen0
    assert eng.kv.free_page_count == eng.kv.num_pages - 1
    again = {}
    for r in mk():
        again.update(eng.run([r]))
    for rid in first:
        np.testing.assert_array_equal(first[rid], again[rid])
    eng.kv.check_reclaimed()


@pytest.mark.slow
def test_set_prefix_cache_off_drains_host_tier(tr, tight):
    """Disabling the prefix cache (the A/B knob) walks the index down —
    spilled nodes drain the HOST tier, device nodes drop their cached
    retention — and re-enabling serves cold-but-exact."""
    rng = np.random.default_rng(6)
    eng = _recycle(tight)
    reqs, results = _pressure_abb(tr, eng, rng)
    assert eng.kv.host_page_count > 0
    eng.set_prefix_cache(False)
    assert eng.kv.host_page_count == 0 and eng.kv.host_bytes == 0
    assert eng.kv.cached_page_count == 0
    eng.set_prefix_cache(True)
    hits0 = eng.n_restore_hits
    c = Request("c", np.asarray(results["a"])[:9].astype(np.int32),
                max_new=4)
    results.update(eng.run([c]))
    assert eng.n_restore_hits == hits0   # nothing survived the drain
    _assert_exact(tr, reqs + [c], results)
    eng.kv.check_reclaimed()


def test_set_spill_budget_shrink_drops_lru_grow_reenables(tr, tight):
    """The idle-engine budget knob: shrinking below residency drops LRU
    host leaves until the tier fits, zero drains it entirely, and
    growing it back re-enables spilling — without ever touching device
    state (the free list is unchanged across the flips)."""
    rng = np.random.default_rng(7)
    eng = _recycle(tight)
    _pressure_abb(tr, eng, rng)
    assert eng.kv.host_page_count >= 2
    free0 = list(eng.kv._free)
    one_page = eng.kv.page_nbytes
    eng.set_spill_budget(one_page)
    assert eng.kv.host_bytes <= one_page
    assert eng.kv.host_page_count == 1
    eng.set_spill_budget(0)
    assert eng.kv.host_page_count == 0 and eng.kv.host_bytes == 0
    assert eng.kv._free == free0, "budget flips must not touch the pool"
    eng.set_spill_budget(BIG)
    spilled0 = eng.kv.n_spilled
    r = Request("again", rng.integers(2, 23, 7).astype(np.int32),
                max_new=5)
    res = eng.run([r])
    assert eng.kv.n_spilled > spilled0, "re-enabled budget never spilled"
    _assert_exact(tr, [r], res)
    eng.kv.check_reclaimed()


@pytest.mark.slow
def test_stale_generation_never_restores(tr, tight):
    """The zombie guard: host entries stamped by a dead generation (the
    kv.reset-without-tree-clear seam) must never restore — the hit drops
    the stale subtree and admits COLD, tokens still exact, and the
    conservation ledger accounts the drops as drains."""
    rng = np.random.default_rng(8)
    eng = _recycle(tight)
    reqs, results = _pressure_abb(tr, eng, rng)
    assert eng.kv.host_page_count == 2      # exactly a's spilled chain
    eng.kv._host_gen += 1                   # simulate the dead generation
    drained0 = eng.kv._host_drained
    hits0, restored0 = eng.n_restore_hits, eng.kv.n_restored
    seq_a = np.asarray(results["a"]).astype(np.int32)
    c = Request("c", seq_a[:9].copy(), max_new=3)
    results.update(eng.run([c]))
    assert eng.n_restore_hits == hits0 and eng.kv.n_restored == restored0, \
        "a dead-generation entry was resurrected"
    # both zombies drained on the failed hit; anything resident now is a
    # CURRENT-generation entry (c's cold admission re-pressured the pool)
    assert eng.kv._host_drained == drained0 + 2
    assert all(eng.kv.host_entry_live(h) for h in eng.kv._host)
    _assert_exact(tr, reqs + [c], results)
    eng.kv.check_reclaimed()


# ---------------------------------------------------------------------------
# checkpoint migration: the host tier serializes INTO the bundle
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_save_load_state_migrates_host_resident_pages(tr, tight):
    """A snapshot taken while pages sit in host RAM carries them in the
    bundle (the documented choice: a migrated replica keeps its whole
    effective cache); the restored engine holds the same host residency,
    and a hit on the migrated run restores from the migrated bytes —
    tokens identical to the donor engine's."""
    rng = np.random.default_rng(9)
    eng_a = _recycle(tight)
    hits0 = eng_a.n_restore_hits
    reqs, results_a = _pressure_abb(tr, eng_a, rng)
    h0 = eng_a.kv.host_page_count
    assert h0 > 0
    import os
    import tempfile
    fd, path = tempfile.mkstemp(suffix=".pkl")
    os.close(fd)
    try:
        eng_a.save_state(path)
        seq_a = np.asarray(results_a["a"]).astype(np.int32)
        c = Request("c", seq_a[:9].copy(), max_new=4)
        results_a.update(eng_a.run([c]))
        assert eng_a.n_restore_hits - hits0 >= 1

        eng_b = _tight_engine(tr, BIG)
        eng_b.load_state(path)
        assert eng_b.kv.host_page_count == h0
        eng_b.kv.check()
        restored0 = eng_b.kv.n_restored
        c2 = Request("c", seq_a[:9].copy(), max_new=4)
        results_b = eng_b.run([c2])
        assert eng_b.kv.n_restored > restored0, \
            "the migrated host pages never served a restore"
        np.testing.assert_array_equal(
            results_a["c"], results_b["c"],
            err_msg="restore-from-migrated-host-tier diverged from donor")
        eng_b.kv.check_reclaimed()
    finally:
        os.unlink(path)


# ---------------------------------------------------------------------------
# allocator unit: spill/restore round-trip, budget bound, rollback, ledger
# ---------------------------------------------------------------------------

def test_allocator_spill_restore_roundtrip_unit(tr):
    """PagedKVCache-level contract: spill_page frees the device page and
    banks exact bytes, restore_pages moves the K/V back bit-for-bit
    (marker round-trip), take/untake is an exact rollback, the budget
    bound rejects over-spill, and reset() kills the generation."""
    kv = PagedKVCache(tr.executor, num_slots=2, page_size=4,
                      pages_per_slot=3, num_pages=8,
                      spill_bytes_budget=BIG)
    assert kv.try_grow(0, 12)                       # 3 private pages
    pages = [int(kv.table[0, j]) for j in range(3)]
    name = next(iter(kv.pools))
    kv.pools[name]["k"] = kv.pools[name]["k"].at[pages[0], 1, 0, 2].set(7.5)
    for p in pages:
        kv.cache_page(p)
    kv.release(0)                                   # refcounts to zero
    free0 = kv.free_page_count
    hid = kv.spill_page(pages[0])
    assert hid is not None
    assert kv.host_page_count == 1
    assert kv.host_bytes == kv.page_nbytes
    assert kv.free_page_count == free0 + 1, "spill must free the device page"
    assert not kv._cached[pages[0]]
    # the budget bound: no room -> None, caller makes room first
    kv.spill_bytes_budget = kv.page_nbytes
    assert kv.spill_page(pages[1]) is None
    kv.spill_bytes_budget = BIG
    # take/untake is an exact rollback
    free_list0 = list(kv._free)
    taken = kv.take_pages(2)
    kv.untake_pages(taken)
    assert kv._free == free_list0
    # restore: marker survives the host round-trip
    (dst,) = kv.take_pages(1)
    kv.restore_pages([hid], [dst])
    kv.adopt_restored([dst])
    assert float(kv.pools[name]["k"][dst, 1, 0, 2]) == 7.5, \
        "restored page lost its K/V contents"
    assert kv.host_page_count == 0 and kv.n_restored == 1
    assert not kv.host_entry_live(hid)
    kv.drop_host_page(hid)                          # idempotent on gone
    kv.check()
    # conservation ledger across a reset: wholesale drain, gen bump
    hid2 = kv.spill_page(pages[1])
    assert hid2 is not None and kv.host_entry_live(hid2)
    gen0 = kv._host_gen
    kv.reset()
    assert kv._host_gen == gen0 + 1
    assert kv.host_page_count == 0 and kv.host_bytes == 0
    assert not kv.host_entry_live(hid2)
    assert kv.host_page_count == kv.n_spilled - kv.n_restored - \
        kv.n_host_evicted - kv._host_drained
    kv.check()
