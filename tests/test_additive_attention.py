"""Fused additive-attention step (the seq2seq decoder hot path).

Oracles: the single fused layer must reproduce the reference's 5-layer
simple_attention composite (ref: networks.py:1257) bit-for-bit in math —
same parameters (identical names/shapes/creation order), same losses and
gradients through a real decoder recurrent group — and the pallas kernel
(ops/pallas_additive.py, interpret mode here) must match the jnp
formulation including masking, padding-to-tile, and backward.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.parameter.argument import Argument
from paddle_tpu.trainer.trainer import Trainer

V, DIM, B, T = 24, 16, 4, 6


def _s2s_conf(fused):
    def conf():
        from paddle_tpu.dsl import (
            AdamOptimizer, ParameterAttribute, SoftmaxActivation,
            StaticInput, TanhActivation, classification_cost, concat_layer,
            data_layer, embedding_layer, first_seq, full_matrix_projection,
            gru_step_layer, memory, mixed_layer, recurrent_group, settings,
            simple_attention, simple_gru,
        )
        settings(batch_size=B, learning_rate=1e-3,
                 learning_method=AdamOptimizer())
        src = data_layer(name="src", size=V)
        emb = embedding_layer(input=src, size=DIM,
                              param_attr=ParameterAttribute(name="_emb"))
        enc = simple_gru(input=emb, size=DIM)
        with mixed_layer(size=DIM) as enc_proj:
            enc_proj += full_matrix_projection(input=enc, size=DIM)
        boot_raw = first_seq(input=enc)
        with mixed_layer(size=DIM, act=TanhActivation()) as boot:
            boot += full_matrix_projection(input=boot_raw, size=DIM)

        def step(enc_vec_s, enc_proj_s, cur):
            mem = memory(name="dec", size=DIM, boot_layer=boot)
            ctxv = simple_attention(name="att", encoded_sequence=enc_vec_s,
                                    encoded_proj=enc_proj_s,
                                    decoder_state=mem, fused=fused)
            with mixed_layer(size=DIM * 3, name="dec_in") as dec_in:
                dec_in += full_matrix_projection(input=ctxv, size=DIM * 3)
                dec_in += full_matrix_projection(input=cur, size=DIM * 3)
            return gru_step_layer(name="dec", input=dec_in, output_mem=mem,
                                  size=DIM)

        trg = data_layer(name="trg", size=V)
        trg_emb = embedding_layer(input=trg, size=DIM,
                                  param_attr=ParameterAttribute(name="_temb"))
        dec = recurrent_group(name="decoder", step=step,
                              input=[StaticInput(input=enc, is_seq=True),
                                     StaticInput(input=enc_proj, is_seq=True),
                                     trg_emb])
        out = mixed_layer(size=V, act=SoftmaxActivation(), name="prob",
                          input=[full_matrix_projection(input=dec, size=V)])
        classification_cost(input=out, label=data_layer(name="nxt", size=V))
    return conf


def _batch(rng):
    lens = rng.integers(2, T + 1, B).astype(np.int32)
    return {
        "src": Argument(ids=rng.integers(0, V, (B, T)).astype(np.int32),
                        lengths=lens),
        "trg": Argument(ids=rng.integers(0, V, (B, T)).astype(np.int32),
                        lengths=lens),
        "nxt": Argument(ids=rng.integers(0, V, (B, T)).astype(np.int32),
                        lengths=lens),
    }


def test_fused_layer_matches_composite():
    """Same seed -> identical params; losses and post-step params must
    match between the fused layer and the 5-layer composite."""
    cfg_f = parse_config_callable(_s2s_conf(True))
    cfg_c = parse_config_callable(_s2s_conf(False))
    # identical parameter lists (names, shapes, order) = identical init
    pf = [(p.name, tuple(p.dims)) for p in cfg_f.model_config.parameters]
    pc = [(p.name, tuple(p.dims)) for p in cfg_c.model_config.parameters]
    assert pf == pc

    tr_f = Trainer(cfg_f, seed=3)
    tr_c = Trainer(cfg_c, seed=3)
    rng = np.random.default_rng(0)
    batches = [_batch(rng) for _ in range(3)]
    lf = [float(tr_f.train_one_batch(b)) for b in batches]
    lc = [float(tr_c.train_one_batch(b)) for b in batches]
    np.testing.assert_allclose(lf, lc, rtol=1e-5, atol=1e-7)
    for name in tr_f.params:
        np.testing.assert_allclose(np.asarray(tr_f.params[name]),
                                   np.asarray(tr_c.params[name]),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"param {name!r} diverged")


def test_pallas_kernel_matches_reference():
    """Interpret-mode pallas kernel vs the jnp formulation: values and all
    gradients, with ragged lengths and non-tile-aligned B/T/D."""
    os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    try:
        from paddle_tpu.ops import pallas_additive
        from paddle_tpu.ops.attention import additive_attention_step as ref

        rng = np.random.default_rng(1)
        Bq, Tq, Ds, D, Dv = 5, 7, 11, 19, 13      # all unaligned
        dec = jnp.asarray(rng.normal(size=(Bq, Ds)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(Ds, D)) * 0.3, jnp.float32)
        v = jnp.asarray(rng.normal(size=(D,)), jnp.float32)
        proj = jnp.asarray(rng.normal(size=(Bq, Tq, D)), jnp.float32)
        seq = jnp.asarray(rng.normal(size=(Bq, Tq, Dv)), jnp.float32)
        lens = rng.integers(1, Tq + 1, Bq).astype(np.int32)
        mask = jnp.arange(Tq)[None, :] < jnp.asarray(lens)[:, None]

        got = pallas_additive.additive_attention_step(dec, w, v, proj, seq,
                                                      mask)
        want = ref(dec, w, v, proj, seq, mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-6)

        def loss_p(dec, w, v, proj, seq):
            return jnp.sum(pallas_additive.additive_attention_step(
                dec, w, v, proj, seq, mask) ** 2)

        def loss_r(dec, w, v, proj, seq):
            return jnp.sum(ref(dec, w, v, proj, seq, mask) ** 2)

        gp = jax.grad(loss_p, argnums=(0, 1, 2, 3, 4))(dec, w, v, proj, seq)
        gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(dec, w, v, proj, seq)
        for a, b in zip(gp, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-6)
    finally:
        os.environ.pop("PADDLE_TPU_PALLAS_INTERPRET", None)


def test_pallas_kernel_bf16_short_seq():
    """bf16 inputs with T < 16 (the sublane minimum ADVICE flagged): tiles
    round up to 16 and results stay close to the fp32 reference."""
    os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    try:
        from paddle_tpu.ops import pallas_additive
        from paddle_tpu.ops.attention import additive_attention_step as ref

        rng = np.random.default_rng(2)
        Bq, Tq, Ds, D, Dv = 3, 5, 8, 16, 16
        dec = jnp.asarray(rng.normal(size=(Bq, Ds)), jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(Ds, D)) * 0.3, jnp.bfloat16)
        v = jnp.asarray(rng.normal(size=(D,)), jnp.bfloat16)
        proj = jnp.asarray(rng.normal(size=(Bq, Tq, D)), jnp.bfloat16)
        seq = jnp.asarray(rng.normal(size=(Bq, Tq, Dv)), jnp.bfloat16)
        mask = jnp.asarray([[1, 1, 1, 0, 0], [1] * 5, [1, 0, 0, 0, 0]],
                           bool)
        got = np.asarray(pallas_additive.additive_attention_step(
            dec, w, v, proj, seq, mask), np.float32)
        want = np.asarray(ref(dec, w, v, proj, seq, mask), np.float32)
        np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)
    finally:
        os.environ.pop("PADDLE_TPU_PALLAS_INTERPRET", None)
