"""Importing reference v0.9.0 binary checkpoints (ref:
parameter/Parameter.cpp:309-381 — header {version=0, valueSize, size} + raw
little-endian reals; trainer/ParamUtil.cpp pass-%05d dirs with one file per
parameter)."""

import os

import numpy as np
import pytest

from paddle_tpu.config.parser import parse_config_callable
from paddle_tpu.dsl import (
    SoftmaxActivation, TanhActivation, classification_cost, data_layer,
    fc_layer, settings,
)
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.trainer.trainer import Trainer


def _config():
    settings(batch_size=8, learning_rate=0.1)
    x = data_layer(name="x", size=6)
    h = fc_layer(input=x, size=5, act=TanhActivation())
    out = fc_layer(input=h, size=3, act=SoftmaxActivation())
    classification_cost(input=out, label=data_layer(name="label", size=3))


def test_parameter_file_roundtrip(tmp_path):
    arr = np.arange(12, dtype=np.float32).reshape(3, 4) * 0.25
    p = str(tmp_path / "___fc_layer_0__.w0")
    ckpt.write_reference_parameter(p, arr)
    # exact on-disk layout: 16-byte header {0, 4, 12} + 48 bytes of floats
    raw = open(p, "rb").read()
    assert len(raw) == 16 + 48
    assert raw[:16] == (0).to_bytes(4, "little") + (4).to_bytes(4, "little") \
        + (12).to_bytes(8, "little")
    back = ckpt.read_reference_parameter(p)
    np.testing.assert_array_equal(back, arr.reshape(-1))


def test_reject_malformed(tmp_path):
    p = str(tmp_path / "bad")
    with open(p, "wb") as f:
        f.write(b"\x07\x00\x00\x00" + b"\x04\x00\x00\x00" + (8).to_bytes(8, "little"))
        f.write(np.zeros(8, np.float32).tobytes())
    with pytest.raises(ValueError, match="version"):
        ckpt.read_reference_parameter(p)
    assert not ckpt._is_reference_parameter_file(p)


def _synthesize_pass_dir(d, trainer, seed=0):
    """Write every model parameter as a v0.9 binary file, as the reference
    trainer would have saved it."""
    rng = np.random.default_rng(seed)
    os.makedirs(d, exist_ok=True)
    want = {}
    for name, cur in trainer.params.items():
        vals = rng.standard_normal(np.asarray(cur).size).astype(np.float32)
        ckpt.write_reference_parameter(os.path.join(d, name), vals)
        want[name] = vals.reshape(np.asarray(cur).shape)
    return want


def test_import_reference_pass_dir(tmp_path):
    cfg = parse_config_callable(_config)
    tr = Trainer(cfg, seed=3)
    d = str(tmp_path / "pass-00007")
    want = _synthesize_pass_dir(d, tr)
    tr.load(d)
    for name, w in want.items():
        got = np.asarray(tr.params[name])
        np.testing.assert_allclose(got, w.astype(got.dtype), rtol=1e-6)
    assert tr.pass_id == 8      # resumes after the imported pass


def test_import_reference_save_root(tmp_path):
    """Given the reference's save_dir root, resume from its newest pass."""
    cfg = parse_config_callable(_config)
    tr = Trainer(cfg, seed=3)
    _synthesize_pass_dir(str(tmp_path / "pass-00001"), tr, seed=1)
    want = _synthesize_pass_dir(str(tmp_path / "pass-00002"), tr, seed=2)
    tr.load(str(tmp_path))
    for name, w in want.items():
        got = np.asarray(tr.params[name])
        np.testing.assert_allclose(got, w.astype(got.dtype), rtol=1e-6)


def test_size_mismatch_fails_loudly(tmp_path):
    cfg = parse_config_callable(_config)
    tr = Trainer(cfg, seed=3)
    d = tmp_path / "pass-00000"
    d.mkdir()
    for name in tr.params:
        ckpt.write_reference_parameter(str(d / name),
                                       np.zeros(2, np.float32))
    with pytest.raises(AssertionError, match="reference file"):
        tr.load(str(d))
