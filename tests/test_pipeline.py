"""Pipeline parallelism tests: the pipelined execution must match running
the stages sequentially on one device (equivalence-oracle pattern,
SURVEY.md §4) — forward and gradients, on the 8-virtual-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.parallel.mesh import make_mesh
from paddle_tpu.parallel.pipeline import (
    pipeline_apply,
    place_stage_params,
    stack_stage_params,
)

S, D = 4, 8


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _make_params(rng, n_stages=S, d=D):
    return [{"w": jnp.asarray(rng.normal(size=(d, d)) * 0.5, jnp.float32),
             "b": jnp.asarray(rng.normal(size=(d,)), jnp.float32)}
            for _ in range(n_stages)]


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


class TestPipeline:
    @pytest.mark.parametrize("n_micro", [1, 2, 4])
    def test_matches_sequential(self, n_micro):
        rng = np.random.default_rng(0)
        mesh = make_mesh(data=1, pipe=4, devices=jax.devices()[:4])
        per_stage = _make_params(rng)
        stacked = place_stage_params(mesh, stack_stage_params(per_stage))
        x = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)
        ref = _sequential(per_stage, x)
        out = pipeline_apply(mesh, _stage_fn, stacked, x, n_micro=n_micro)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-6)

    def test_grads_match_sequential(self):
        rng = np.random.default_rng(1)
        mesh = make_mesh(data=1, pipe=4, devices=jax.devices()[:4])
        per_stage = _make_params(rng)
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)

        def loss_pipe(stacked, x):
            return jnp.sum(jnp.square(
                pipeline_apply(mesh, _stage_fn, stacked, x, n_micro=2)))

        def loss_seq(stacked, x):
            per = [jax.tree.map(lambda p: p[i], stacked) for i in range(S)]
            return jnp.sum(jnp.square(_sequential(per, x)))

        g_pipe = jax.jit(jax.grad(loss_pipe))(
            place_stage_params(mesh, stacked), x)
        g_seq = jax.grad(loss_seq)(stacked, x)
        for k in ("w", "b"):
            np.testing.assert_allclose(np.asarray(g_pipe[k]),
                                       np.asarray(g_seq[k]),
                                       rtol=1e-4, atol=1e-5)

    def test_out_dim_trim(self):
        rng = np.random.default_rng(2)
        mesh = make_mesh(data=1, pipe=4, devices=jax.devices()[:4])
        per_stage = _make_params(rng)
        stacked = place_stage_params(mesh, stack_stage_params(per_stage))
        x = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)
        out = pipeline_apply(mesh, _stage_fn, stacked, x, n_micro=2, out_dim=3)
        ref = _sequential(per_stage, x)[:, :3]
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-6)

    def test_stage_count_mismatch_is_loud(self):
        rng = np.random.default_rng(4)
        mesh = make_mesh(data=1, pipe=4, devices=jax.devices()[:4])
        per_stage = _make_params(rng, n_stages=8)      # 8 stages, pipe=4
        stacked = stack_stage_params(per_stage)
        x = jnp.zeros((8, D), jnp.float32)
        with pytest.raises(AssertionError, match="stage dim"):
            pipeline_apply(mesh, _stage_fn, stacked, x, n_micro=2)

    def test_size1_axes_keep_partition_specs_valid(self):
        """Any canonical axis may appear in a partition spec on any mesh
        (regression: dryrun_multichip(3) crashed when model=1 dropped the
        'model' axis)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = make_mesh(data=8)          # model/seq/pipe all size 1
        for ax in ("model", "seq", "pipe", "data"):
            NamedSharding(mesh, P(None, ax))  # must not raise

    def test_composes_with_data_axis(self):
        """data x pipe mesh: pipeline under the same mesh as data sharding."""
        rng = np.random.default_rng(3)
        mesh = make_mesh(data=2, pipe=4)
        per_stage = _make_params(rng)
        stacked = place_stage_params(mesh, stack_stage_params(per_stage))
        x = jnp.asarray(rng.normal(size=(8, D)), jnp.float32)
        ref = _sequential(per_stage, x)
        out = pipeline_apply(mesh, _stage_fn, stacked, x, n_micro=2)
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=1e-6)
